"""Operator tests (reference: tests/python/unittest/test_operator.py).

Numeric checks against numpy + finite-difference gradient checks via the
shipped test toolkit (mxnet_tpu/test_utils.py).
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, autograd
from mxnet_tpu.test_utils import assert_almost_equal, check_numeric_gradient


def test_fully_connected():
    x = np.random.rand(4, 10).astype(np.float32)
    w = np.random.rand(3, 10).astype(np.float32)
    b = np.random.rand(3).astype(np.float32)
    out = nd.FullyConnected(nd.array(x), nd.array(w), nd.array(b), num_hidden=3)
    assert_almost_equal(out, x @ w.T + b, rtol=1e-4, atol=1e-4)
    out = nd.FullyConnected(nd.array(x), nd.array(w), no_bias=True, num_hidden=3)
    assert_almost_equal(out, x @ w.T, rtol=1e-4, atol=1e-4)


def test_fc_grad():
    x = np.random.rand(3, 5).astype(np.float32)
    w = np.random.rand(2, 5).astype(np.float32)
    b = np.random.rand(2).astype(np.float32)
    check_numeric_gradient(
        lambda a, c, d: nd.FullyConnected(a, c, d, num_hidden=2), [x, w, b])


def test_convolution_shapes():
    x = nd.array(np.random.rand(2, 3, 8, 8).astype(np.float32))
    w = nd.array(np.random.rand(4, 3, 3, 3).astype(np.float32))
    b = nd.array(np.zeros(4, np.float32))
    out = nd.Convolution(x, w, b, kernel=(3, 3), num_filter=4)
    assert out.shape == (2, 4, 6, 6)
    out = nd.Convolution(x, w, b, kernel=(3, 3), num_filter=4, pad=(1, 1))
    assert out.shape == (2, 4, 8, 8)
    out = nd.Convolution(x, w, b, kernel=(3, 3), num_filter=4, stride=(2, 2),
                         pad=(1, 1))
    assert out.shape == (2, 4, 4, 4)


def test_convolution_vs_numpy():
    # 1x1 conv == per-pixel matmul
    x = np.random.rand(2, 3, 5, 5).astype(np.float32)
    w = np.random.rand(4, 3, 1, 1).astype(np.float32)
    out = nd.Convolution(nd.array(x), nd.array(w), no_bias=True,
                         kernel=(1, 1), num_filter=4)
    expect = np.einsum("nchw,oc->nohw", x, w[:, :, 0, 0])
    assert_almost_equal(out, expect, rtol=1e-4, atol=1e-4)


def test_conv_grad():
    x = np.random.rand(1, 2, 5, 5).astype(np.float32)
    w = np.random.rand(2, 2, 3, 3).astype(np.float32)
    check_numeric_gradient(
        lambda a, c: nd.Convolution(a, c, no_bias=True, kernel=(3, 3),
                                    num_filter=2), [x, w],
        rtol=2e-2, atol=5e-3)


def test_grouped_and_depthwise_conv():
    x = nd.array(np.random.rand(1, 4, 6, 6).astype(np.float32))
    w = nd.array(np.random.rand(4, 1, 3, 3).astype(np.float32))
    out = nd.Convolution(x, w, no_bias=True, kernel=(3, 3), num_filter=4,
                         num_group=4)
    assert out.shape == (1, 4, 4, 4)


def test_pooling():
    x = np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4)
    out = nd.Pooling(nd.array(x), kernel=(2, 2), stride=(2, 2), pool_type="max")
    assert_almost_equal(out, [[[[5, 7], [13, 15]]]])
    out = nd.Pooling(nd.array(x), kernel=(2, 2), stride=(2, 2), pool_type="avg")
    assert_almost_equal(out, [[[[2.5, 4.5], [10.5, 12.5]]]])
    out = nd.Pooling(nd.array(x), global_pool=True, pool_type="max")
    assert out.shape == (1, 1, 1, 1)
    assert out.asscalar() == 15


def test_batchnorm_train_eval():
    np.random.seed(0)
    x = np.random.rand(8, 3, 4, 4).astype(np.float32) * 5
    gamma = np.ones(3, np.float32)
    beta = np.zeros(3, np.float32)
    mm = np.zeros(3, np.float32)
    mv = np.ones(3, np.float32)
    out, new_mm, new_mv = nd.BatchNorm(
        nd.array(x), nd.array(gamma), nd.array(beta), nd.array(mm),
        nd.array(mv), fix_gamma=False, training=True, momentum=0.9)
    o = out.asnumpy()
    assert abs(o.mean()) < 1e-4
    assert abs(o.std() - 1) < 1e-2
    # moving stats moved toward batch stats
    assert np.all(new_mm.asnumpy() != 0)
    # eval mode uses moving stats
    out_eval, _, _ = nd.BatchNorm(
        nd.array(x), nd.array(gamma), nd.array(beta), nd.array(mm),
        nd.array(mv), fix_gamma=False, training=False)
    assert_almost_equal(out_eval, x, rtol=1e-3, atol=1e-3)  # mm=0, mv=1 → identity-ish


def test_layernorm():
    x = np.random.rand(4, 10).astype(np.float32)
    g = np.random.rand(10).astype(np.float32)
    b = np.random.rand(10).astype(np.float32)
    out = nd.LayerNorm(nd.array(x), nd.array(g), nd.array(b))
    mean = x.mean(-1, keepdims=True)
    var = x.var(-1, keepdims=True)
    expect = (x - mean) / np.sqrt(var + 1e-5) * g + b
    assert_almost_equal(out, expect, rtol=1e-4, atol=1e-4)
    check_numeric_gradient(lambda a: nd.LayerNorm(a, nd.array(g), nd.array(b)),
                           [x], rtol=2e-2, atol=5e-3)


def test_activations():
    x = np.array([-2.0, -0.5, 0.0, 0.5, 2.0], np.float32)
    assert_almost_equal(nd.Activation(nd.array(x), act_type="relu"),
                        np.maximum(x, 0))
    assert_almost_equal(nd.Activation(nd.array(x), act_type="sigmoid"),
                        1 / (1 + np.exp(-x)), rtol=1e-4, atol=1e-5)
    assert_almost_equal(nd.Activation(nd.array(x), act_type="tanh"),
                        np.tanh(x), rtol=1e-4, atol=1e-5)
    assert_almost_equal(nd.Activation(nd.array(x), act_type="softrelu"),
                        np.log1p(np.exp(x)), rtol=1e-4, atol=1e-5)
    assert_almost_equal(nd.LeakyReLU(nd.array(x), act_type="leaky", slope=0.1),
                        np.where(x > 0, x, 0.1 * x), rtol=1e-4, atol=1e-5)


def test_softmax():
    x = np.random.rand(3, 5).astype(np.float32)
    out = nd.softmax(nd.array(x))
    e = np.exp(x - x.max(-1, keepdims=True))
    assert_almost_equal(out, e / e.sum(-1, keepdims=True), rtol=1e-4, atol=1e-5)
    ls = nd.log_softmax(nd.array(x))
    assert_almost_equal(nd.exp(ls), out, rtol=1e-4, atol=1e-5)
    wgt = nd.array(np.random.rand(3, 5).astype(np.float32))
    check_numeric_gradient(lambda a: nd.softmax(a) * wgt, [x],
                           rtol=2e-2, atol=5e-3)


def test_dropout():
    x = nd.ones((100, 100))
    out = nd.Dropout(x, p=0.5, training=True)
    o = out.asnumpy()
    frac = (o == 0).mean()
    assert 0.3 < frac < 0.7
    kept = o[o != 0]
    assert np.allclose(kept, 2.0, atol=1e-5)  # inverted dropout scaling
    out_eval = nd.Dropout(x, p=0.5, training=False)
    assert np.allclose(out_eval.asnumpy(), 1.0)


def test_embedding():
    w = np.random.rand(10, 4).astype(np.float32)
    idx = np.array([1, 3, 5], np.float32)
    out = nd.Embedding(nd.array(idx), nd.array(w), input_dim=10, output_dim=4)
    assert_almost_equal(out, w[[1, 3, 5]])
    # gradient scatters into rows
    wn = nd.array(w)
    wn.attach_grad()
    with autograd.record():
        y = nd.Embedding(nd.array(idx), wn, input_dim=10, output_dim=4).sum()
    y.backward()
    g = wn.grad.asnumpy()
    assert np.allclose(g[[1, 3, 5]], 1)
    assert np.allclose(g[[0, 2, 4, 6, 7, 8, 9]], 0)


def test_transpose_deconv():
    x = nd.array(np.random.rand(1, 2, 4, 4).astype(np.float32))
    w = nd.array(np.random.rand(2, 3, 3, 3).astype(np.float32))
    out = nd.Deconvolution(x, w, no_bias=True, kernel=(3, 3), num_filter=3,
                           stride=(2, 2))
    assert out.shape[1] == 3
    assert out.shape[2] == 9  # (4-1)*2 + 3


def test_sequence_ops():
    data = np.arange(24, dtype=np.float32).reshape(4, 2, 3)  # (T, N, C)
    lens = nd.array([2, 3], dtype="float32")
    masked = nd.SequenceMask(nd.array(data), lens, use_sequence_length=True,
                             value=-1.0)
    m = masked.asnumpy()
    assert np.allclose(m[2:, 0], -1)
    assert np.allclose(m[:2, 0], data[:2, 0])
    assert np.allclose(m[3, 1], -1)
    last = nd.SequenceLast(nd.array(data), lens, use_sequence_length=True)
    assert np.allclose(last.asnumpy()[0], data[1, 0])
    assert np.allclose(last.asnumpy()[1], data[2, 1])
    rev = nd.SequenceReverse(nd.array(data), lens, use_sequence_length=True)
    assert np.allclose(rev.asnumpy()[0, 0], data[1, 0])
    assert np.allclose(rev.asnumpy()[1, 0], data[0, 0])
    assert np.allclose(rev.asnumpy()[2:, 0], data[2:, 0])


def test_linalg():
    a = np.random.rand(3, 3).astype(np.float32)
    spd = a @ a.T + 3 * np.eye(3, dtype=np.float32)
    L = nd.linalg.potrf(nd.array(spd))
    assert_almost_equal(nd.dot(L, L.T), spd, rtol=1e-3, atol=1e-3)
    x = np.random.rand(2, 3, 4).astype(np.float32)
    y = np.random.rand(2, 4, 5).astype(np.float32)
    out = nd.linalg.gemm2(nd.array(x), nd.array(y))
    assert_almost_equal(out, x @ y, rtol=1e-4, atol=1e-4)
    sld = nd.linalg.sumlogdiag(nd.array(spd))
    assert_almost_equal(sld, np.log(np.diag(spd)).sum(), rtol=1e-4, atol=1e-4)


def test_optimizer_ops():
    w = np.random.rand(5).astype(np.float32)
    g = np.random.rand(5).astype(np.float32)
    out = nd.sgd_update(nd.array(w), nd.array(g), lr=0.1, wd=0.0)
    assert_almost_equal(out, w - 0.1 * g, rtol=1e-5, atol=1e-6)
    mom = np.zeros(5, np.float32)
    new_w, new_mom = nd.sgd_mom_update(nd.array(w), nd.array(g), nd.array(mom),
                                       lr=0.1, momentum=0.9)
    assert_almost_equal(new_w, w - 0.1 * g, rtol=1e-5, atol=1e-6)
    mean = np.zeros(5, np.float32)
    var = np.zeros(5, np.float32)
    new_w, new_mean, new_var = nd.adam_update(
        nd.array(w), nd.array(g), nd.array(mean), nd.array(var), lr=0.01)
    assert new_w.shape == (5,)


def test_elementwise_grad_sampling():
    for opname in ["exp", "log", "sigmoid", "tanh", "sqrt", "square", "relu"]:
        x = np.random.rand(3, 4).astype(np.float32) + 0.5
        check_numeric_gradient(lambda a, op=opname: getattr(nd, op)(a), [x],
                               rtol=2e-2, atol=5e-3)


def test_lrn():
    x = np.random.rand(2, 8, 4, 4).astype(np.float32)
    out = nd.LRN(nd.array(x), nsize=5)
    assert out.shape == x.shape


def test_instance_norm_l2norm():
    x = np.random.rand(2, 3, 4, 4).astype(np.float32)
    out = nd.InstanceNorm(nd.array(x), nd.ones((3,)), nd.zeros((3,)))
    o = out.asnumpy()
    assert abs(o[0, 0].mean()) < 1e-4
    out = nd.L2Normalization(nd.array(x))
    o = out.asnumpy().reshape(2, -1)
    assert np.allclose((o ** 2).sum(1), 1, atol=1e-4)


def test_upsampling():
    x = nd.array(np.random.rand(1, 2, 3, 3).astype(np.float32))
    out = nd.UpSampling(x, scale=2, sample_type="nearest")
    assert out.shape == (1, 2, 6, 6)
    assert np.allclose(out.asnumpy()[0, 0, 0, 0], x.asnumpy()[0, 0, 0, 0])


def test_smooth_l1_where():
    x = np.array([-2.0, -0.5, 0.5, 2.0], np.float32)
    out = nd.smooth_l1(nd.array(x), scalar=1.0)
    expect = np.where(np.abs(x) < 1, 0.5 * x * x, np.abs(x) - 0.5)
    assert_almost_equal(out, expect, rtol=1e-5, atol=1e-6)


def test_gather_scatter():
    data = np.random.rand(4, 5).astype(np.float32)
    idx = np.array([[0, 2], [1, 3]], np.float32)
    out = nd.gather_nd(nd.array(data), nd.array(idx))
    assert np.allclose(out.asnumpy(), data[[0, 2], [1, 3]])
    sc = nd.scatter_nd(nd.array(np.array([1.0, 2.0], np.float32)),
                       nd.array(idx), shape=(4, 5))
    s = sc.asnumpy()
    assert s[0, 1] == 1 and s[2, 3] == 2 and s.sum() == 3
