"""Examples and tools run end-to-end (reference: the drivers under
example/image-classification and tools/ — train_mnist, train_imagenet
--benchmark, im2rec, bandwidth/measure)."""
import os
import subprocess
import sys
import tempfile

import numpy as np
import pytest

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
# MXNET_DEVICE=cpu is honored IN-PROCESS by the drivers (jax.config
# pin before backend init) — the plain JAX_PLATFORMS env var is
# overridden by the TPU plugin and silently dials the chip.
_ENV = dict(os.environ, JAX_PLATFORMS="cpu", MXNET_DEVICE="cpu",
            XLA_FLAGS="--xla_force_host_platform_device_count=2")


def _run(cmd, timeout=240, env=None):
    res = subprocess.run(cmd, capture_output=True, text=True,
                         env=env or _ENV, timeout=timeout, cwd=_ROOT)
    assert res.returncode == 0, \
        "cmd %s failed:\n%s\n%s" % (cmd, res.stdout[-2000:],
                                    res.stderr[-2000:])
    return res.stdout


def test_train_mnist_synthetic():
    out = _run([sys.executable, "examples/train_mnist.py", "--synthetic",
                "--num-examples", "1500", "--num-epochs", "4",
                "--network", "mlp", "--lr", "0.5"])
    line = [l for l in out.splitlines() if l.startswith("final-accuracy")]
    assert line, out
    acc = float(line[0].split()[1])
    assert acc > 0.8, "mnist driver accuracy %.3f" % acc


def test_train_telemetry_example(tmp_path):
    """README Observability snippet: TelemetryCallback + StepMonitor in
    a TrainStep loop, streaming trace segments merged to a chrome
    trace, fleet-view (rank-labeled) prometheus exposition."""
    import json

    out = _run([sys.executable, "examples/train_telemetry.py",
                "--num-batches", "12", "--batch-size", "32",
                "--out-dir", str(tmp_path)])
    assert "telemetry demo ok" in out
    assert 'mx_train_steps_total{rank="0"} 12' in out
    assert "mx_slo_burn_rate" in out
    with open(os.path.join(str(tmp_path), "chrome_trace.json")) as f:
        events = json.load(f)["traceEvents"]
    names = {e["name"] for e in events}
    assert any(n.startswith("train_step::") for n in names), names
    assert any(n.startswith("checkpoint::") for n in names), names
    # streamed segments were committed and survive in the out dir
    segs = os.listdir(os.path.join(str(tmp_path), "trace_segments"))
    assert any(s.startswith("trace.rank0.") for s in segs), segs


def test_train_imagenet_benchmark_mode():
    out = _run([sys.executable, "examples/train_imagenet.py",
                "--benchmark", "1", "--network", "resnet18",
                "--batch-size", "2", "--image-shape", "3,64,64"],
               timeout=400)
    line = [l for l in out.splitlines() if l.startswith("benchmark:")]
    assert line, out
    assert float(line[0].split()[-2]) > 0


def test_im2rec_roundtrip():
    cv2 = pytest.importorskip("cv2")
    import mxnet_tpu as mx

    with tempfile.TemporaryDirectory() as d:
        rng = np.random.RandomState(0)
        for cls in ("cat", "dog"):
            os.makedirs(os.path.join(d, "imgs", cls))
            for i in range(3):
                img = (rng.rand(20, 24, 3) * 255).astype(np.uint8)
                cv2.imwrite(os.path.join(d, "imgs", cls,
                                         "%d.jpg" % i), img)
        prefix = os.path.join(d, "set")
        _run([sys.executable, "tools/im2rec.py", prefix,
              os.path.join(d, "imgs")])
        assert os.path.exists(prefix + ".rec")
        assert os.path.exists(prefix + ".idx")
        # readable through the training-side iterator
        it = mx.io.ImageRecordIter(path_imgrec=prefix + ".rec",
                                   batch_size=2, data_shape=(3, 20, 20))
        batch = next(iter(it))
        assert batch.data[0].shape == (2, 3, 20, 20)
        labels = set()
        it.reset()
        for b in it:
            labels.update(b.label[0].asnumpy().tolist())
        assert {0.0, 1.0} <= labels


def test_bandwidth_measure():
    sys.path.insert(0, os.path.join(_ROOT, "tools", "bandwidth"))
    from measure import measure

    rows = measure("device", num_devices=2, sizes=(4096,), repeat=2,
                   warmup=1)
    assert len(rows) == 1
    size, dt, gbs = rows[0]
    assert dt > 0 and gbs > 0


def test_train_rnn_lm_synthetic():
    """The LSTM PTB-style tracked config as a runnable driver
    (BASELINE.md; reference example/rnn/bucketing/lstm_bucketing.py)."""
    out = _run([sys.executable, "examples/train_rnn_lm.py", "--synthetic",
                "--num-sentences", "400", "--vocab-size", "50",
                "--num-hidden", "32", "--num-embed", "16",
                "--num-layers", "1", "--buckets", "6,10",
                "--batch-size", "16", "--num-epochs", "4"], timeout=500)
    line = [l for l in out.splitlines()
            if l.startswith("final-perplexity")]
    assert line, out
    # uniform guessing over the 50-word vocab would be ppl 50
    assert float(line[0].split()[1]) < 30


def test_train_ssd_synthetic():
    """The SSD tracked config as a runnable driver (BASELINE.md;
    reference example/ssd/train.py)."""
    out = _run([sys.executable, "examples/train_ssd.py",
                "--num-examples", "128", "--num-epochs", "8",
                "--batch-size", "16"], timeout=500)
    line = [l for l in out.splitlines() if l.startswith("final-loss")]
    assert line, out
    assert float(line[0].split()[3]) > 0.5, "recall too low: %s" % line


def test_gluon_image_classification_hybrid():
    """The Gluon imperative/hybrid driver (reference
    example/gluon/image_classification.py) trains to high accuracy in
    hybrid (compiled) mode."""
    out = _run([sys.executable, "examples/gluon_image_classification.py",
                "--model", "resnet18_v1", "--num-examples", "384",
                "--epochs", "8", "--batch-size", "32", "--lr", "0.1"],
               timeout=540)
    line = [l for l in out.splitlines() if l.startswith("final-accuracy")]
    assert line, out
    assert float(line[0].split()[1]) > 0.7


def test_rec2idx_roundtrip(tmp_path):
    """rec2idx rebuilds a usable index for an unindexed .rec
    (reference tools/rec2idx.py)."""
    import mxnet_tpu as mx

    rec_path = str(tmp_path / "data.rec")
    rec = mx.recordio.MXRecordIO(rec_path, "w")
    payloads = [("item%03d" % i).encode() * (i + 1) for i in range(7)]
    for p in payloads:
        rec.write(p)
    rec.close()

    _run([sys.executable, "tools/rec2idx.py", rec_path])
    idx_path = str(tmp_path / "data.idx")
    assert os.path.exists(idx_path)
    reader = mx.recordio.MXIndexedRecordIO(idx_path, rec_path, "r")
    assert sorted(reader.keys) == list(range(7))
    for i in (3, 0, 6):        # random access
        assert reader.read_idx(i) == payloads[i]
    reader.close()


def test_rec_shard_split_balanced_and_manifest(tmp_path):
    """tools/rec_shard.py splits a .rec into N balanced indexed shards
    with a manifest, and every record survives the split (ISSUE 6)."""
    import json

    import mxnet_tpu as mx

    rec_path = str(tmp_path / "full.rec")
    idx_path = str(tmp_path / "full.idx")
    w = mx.recordio.MXIndexedRecordIO(idx_path, rec_path, "w")
    payloads = [("rec%04d" % i).encode() * (1 + i % 5) for i in range(11)]
    for i, p in enumerate(payloads):
        w.write_idx(i, p)
    w.close()

    prefix = str(tmp_path / "shards" / "part")
    out = _run([sys.executable, "tools/rec_shard.py", "split", rec_path,
                "--num-shards", "3", "--out-prefix", prefix])
    manifest = json.loads(out)
    counts = [s["records"] for s in manifest["shards"]]
    assert manifest["total_records"] == 11
    assert sorted(counts) == [3, 4, 4]          # balanced to within 1
    # all records survive, ids stay recoverable (round-robin i%N)
    from mxnet_tpu.data import RecordDataset

    got = []
    for s in manifest["shards"]:
        shard = RecordDataset([str(tmp_path / "shards" / s["rec"])])
        assert len(shard) == s["records"]
        got.extend(shard.read(i) for i in range(len(shard)))
    assert sorted(got) == sorted(payloads)

    out = _run([sys.executable, "tools/rec_shard.py", "inspect",
                prefix + "-manifest.json"])
    assert json.loads(out)["balanced"] is True
    out = _run([sys.executable, "tools/rec_shard.py", "inspect", rec_path])
    assert json.loads(out)["records"] == 11


def test_parse_log(monkeypatch):
    monkeypatch.syspath_prepend(os.path.join(_ROOT, "tools"))
    from parse_log import parse, render

    lines = [
        "INFO Epoch[0] Train-accuracy=0.5\n",
        "INFO Epoch[0] Validation-accuracy=0.4\n",
        "INFO Epoch[0] Time cost=12.5\n",
        "INFO Epoch[1] Train-accuracy=0.8\n",
        "INFO Epoch[1] Validation-accuracy=0.7\n",
        "INFO Epoch[1] Time cost=11.0\n",
    ]
    data = parse(lines, ["accuracy"])
    out = render(data, ["accuracy"], "markdown")
    assert "| epoch |" in out and "0.800000" in out and "11.0" in out
    tsv = render(data, ["accuracy"], "none")
    assert tsv.splitlines()[0].startswith("epoch\t")


def test_flakiness_checker(tmp_path, monkeypatch):
    """Flakiness checker reports failing seeds reproducibly
    (reference tools/flakiness_checker.py)."""
    monkeypatch.syspath_prepend(os.path.join(_ROOT, "tools"))
    import flakiness_checker

    assert flakiness_checker.resolve_target("test_io.test_foo") == \
        "tests/test_io.py::test_foo"
    assert flakiness_checker.resolve_target(
        "tests/test_io.py::test_foo") == "tests/test_io.py::test_foo"
    out = _run([sys.executable, "tools/flakiness_checker.py",
                "tests/test_lr_callback.py::test_scheduler_warmup",
                "-n", "2"], timeout=300)
    assert "0/2 trials failed" in out


def test_train_gan_adversarial_loop():
    """Two-optimizer adversarial loop (reference example/gan)."""
    out = _run([sys.executable, "examples/train_gan.py",
                "--epochs", "1", "--batches", "4", "--batch-size", "16"],
               timeout=300)
    assert "d_loss" in out and "fake mean" in out


def test_train_matrix_factorization_sparse():
    """Sparse-embedding MF recommender (reference example/recommenders)."""
    out = _run([sys.executable, "examples/train_matrix_factorization.py",
                "--epochs", "2", "--samples", "1024",
                "--num-users", "80", "--num-items", "60"], timeout=300)
    assert "val_rmse" in out


def test_train_rcnn_rpn_proposal_head():
    """RPN training + Proposal + ROIPooling head (reference example/rcnn)."""
    out = _run([sys.executable, "examples/train_rcnn.py",
                "--steps", "6", "--batch-size", "2"], timeout=400)
    assert "rois" in out and "rpn_loss" in out


def test_benchmark_sparse_end2end():
    """Sparse end-to-end bench runs and reports all three modes
    (reference benchmark/python/sparse)."""
    out = _run([sys.executable, "benchmark/sparse_end2end.py",
                "--features", "2000", "--batches", "3",
                "--batch-size", "32"], timeout=300)
    assert out.count("sparse_end2end_samples_per_s") == 3
    assert "row_sparse" in out and "trainstep_fused" in out


def test_benchmark_control_flow():
    """foreach-vs-unrolled bench runs (reference benchmark/python/
    control_flow)."""
    out = _run([sys.executable, "benchmark/control_flow_bench.py",
                "--seq-len", "16", "--iters", "2"], timeout=300)
    assert "foreach_scan" in out and "unrolled" in out


def test_model_parallel_lstm_group2ctx():
    """Layer groups placed on distinct devices via group2ctx
    (reference example/model-parallel)."""
    env = dict(_ENV, XLA_FLAGS="--xla_force_host_platform_device_count=4")
    out = _run([sys.executable, "examples/model_parallel_lstm.py",
                "--steps", "30"], timeout=400, env=env)
    assert "placement" in out and "nll" in out


def test_adversarial_fgsm_input_grads():
    """Input-gradient API: FGSM collapses accuracy (reference
    example/adversary)."""
    out = _run([sys.executable, "examples/adversarial_fgsm.py",
                "--epochs", "3", "--train", "256", "--test", "128"],
               timeout=400)
    assert "adversarial accuracy" in out


def test_train_ctc_ocr():
    """CTC loss over unaligned sequence labels (reference example/ctc,
    example/captcha)."""
    out = _run([sys.executable, "examples/train_ctc_ocr.py",
                "--steps", "40", "--batch-size", "16"], timeout=400)
    assert "ctc_loss" in out and "exact-sequence" in out


def test_bi_lstm_sort():
    """BidirectionalCell seq2seq sorting via Module.fit (reference
    example/bi-lstm-sort)."""
    out = _run([sys.executable, "examples/bi_lstm_sort.py",
                "--steps", "100", "--batch-size", "16"], timeout=400)
    assert "sorted-position accuracy" in out


def test_train_multi_task():
    """Shared trunk + two heads + joint backward (reference
    example/multi-task)."""
    out = _run([sys.executable, "examples/train_multi_task.py",
                "--epochs", "4"], timeout=400)
    assert "quad-acc" in out and "xpos-mae" in out


def test_neural_style_input_optimization():
    """Gatys-style input optimization with Gram losses (reference
    example/neural-style)."""
    out = _run([sys.executable, "examples/neural_style.py",
                "--steps", "40"], timeout=400)
    assert "total loss" in out


def test_kill_mxnet_finds_dmlc_processes():
    """tools/kill_mxnet.py sweeps processes carrying the DMLC_ROLE
    launch contract (reference tools/kill-mxnet.py)."""
    import time

    marker = "kill_mxnet_test_%d" % os.getpid()
    proc = subprocess.Popen(
        [sys.executable, "-c",
         "import time; time.sleep(60)  # " + marker],
        env=dict(os.environ, DMLC_ROLE="worker"))
    try:
        time.sleep(0.3)
        out = _run([sys.executable, "tools/kill_mxnet.py", "--dry-run",
                    "--match", marker])
        assert ("pid %d" % proc.pid) in out and "worker" in out
        # kill ONLY our marked sleeper — a parallel dist test's
        # scheduler/server/workers must survive this test.
        out = _run([sys.executable, "tools/kill_mxnet.py",
                    "--grace", "1", "--match", marker])
        assert "terminated" in out
        time.sleep(0.5)
        assert proc.poll() is not None, "stray process survived"
    finally:
        if proc.poll() is None:
            proc.kill()


def test_train_autoencoder():
    """Conv2DTranspose decoder + reconstruction training (reference
    example/autoencoder)."""
    out = _run([sys.executable, "examples/train_autoencoder.py",
                "--epochs", "5"], timeout=400)
    assert "recon_loss" in out


def test_cnn_text_classification():
    """Multi-width Conv1D + max-over-time text classifier (reference
    example/cnn_text_classification)."""
    out = _run([sys.executable, "examples/cnn_text_classification.py",
                "--epochs", "3", "--train", "1024"], timeout=400)
    assert "val-acc" in out


def test_train_fcn_segmentation():
    """Per-pixel classification + Conv2DTranspose upsampling (reference
    example/fcn-xs)."""
    out = _run([sys.executable, "examples/train_fcn_segmentation.py",
                "--epochs", "6"], timeout=500)
    assert "mean-IoU" in out


def test_serve_mnist_inference_server():
    """Serving driver: save_checkpoint -> bucketed warmup -> concurrent
    batched inference -> per-bucket stats (mxnet_tpu.serving)."""
    out = _run([sys.executable, "examples/serve_mnist.py",
                "--train-epochs", "2", "--num-examples", "1000",
                "--requests", "96", "--concurrency", "8",
                "--max-batch", "16", "--max-delay-ms", "5"],
               timeout=300)
    acc = [l for l in out.splitlines() if l.startswith("served-accuracy")]
    thr = [l for l in out.splitlines()
           if l.startswith("serving-throughput")]
    assert acc and thr, out
    assert float(acc[0].split()[1]) > 0.7
    assert float(thr[0].split()[1]) > 0
    # the shedding demo actually fired (the printed shed dict is
    # non-empty), not just the unconditional "shed:" label
    assert "bucket" in out and "'deadline'" in out


def test_train_resume_preemption_bit_exact():
    """Checkpoint driver (mxnet_tpu.checkpoint): train → SIGTERM
    mid-run → restart resumes from the latest atomic commit and finishes
    bit-exact vs an uninterrupted run (train_resume.py demo mode drives
    the kill itself and compares final state digests)."""
    out = _run([sys.executable, "examples/train_resume.py",
                "--steps", "10", "--kill-after", "4",
                "--step-delay", "0.05"], timeout=400)
    assert "phase-1 exit code 143" in out, out       # clean preempt save
    resumed = [l for l in out.splitlines()
               if l.startswith("resumed-from-step")]
    assert resumed, out
    assert int(resumed[0].split()[1]) >= 1           # really mid-run
    assert "bitexact True" in out, out
    # loss curve continued from the saved step, not from scratch: the
    # resumed phase printed its first step at the resume point
    steps2 = [l for l in out.splitlines() if l.startswith("  | step ")]
    assert steps2, out


def test_train_resnet_trainstep_blessed_path():
    """The TPU-blessed pipeline end to end: RecordIO -> decode team ->
    fused bf16 SPMD TrainStep -> checkpoint."""
    pytest.importorskip("cv2")
    out = _run([sys.executable, "examples/train_resnet_trainstep.py",
                "--steps", "18", "--batch-size", "16",
                "--samples", "128"], timeout=500)
    assert "img/s (post-compile)" in out and "checkpoint" in out


def test_compile_cache_tool_smoke(tmp_path):
    """tools/compile_cache.py inspect/verify/gc over a real store
    layout (entries written through the store's commit protocol)."""
    import json

    from mxnet_tpu.compile.store import CompileCacheStore, make_key

    cache = str(tmp_path / "cc")
    store = CompileCacheStore(cache)
    for i in range(2):
        store.put(make_key(["tool_smoke", i]), b"payload" * 50,
                  {"site": "cached_op", "compile_seconds": 1.5,
                   "backend": {"platform": "cpu", "device_kind": "cpu",
                               "num_devices": 2, "jax": "x",
                               "jaxlib": "y"}})
    out = json.loads(_run([sys.executable, "tools/compile_cache.py",
                           "inspect", cache]))
    assert out["entries"] == 2
    assert out["by_site"]["cached_op"]["entries"] == 2
    assert out["warm_restart_saves_seconds"] == 3.0
    out = json.loads(_run([sys.executable, "tools/compile_cache.py",
                           "verify", cache]))
    assert out["valid"] == 2 and out["damaged"] == 0
    out = json.loads(_run([sys.executable, "tools/compile_cache.py",
                           "gc", cache, "--max-mb", "0"]))
    assert out["removed_entries"] == 2 and out["bytes_after"] == 0
