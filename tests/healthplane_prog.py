"""Worker program for the 2-process fleet-health-plane acceptance test
(tests/test_healthplane.py, launched via tools/launch.py roles).

Proves the two ISSUE 8 acceptance properties over a REAL dist kvstore:

* **Pod snapshot without a shared filesystem.** Each rank commits its
  flight-recorder bundles into its own private directory; rank 0's
  ``request_pod_bundle`` fan-out makes every rank capture on demand and
  ``diag_push`` the bundle over the kvstore; rank 0 collects one bundle
  per rank into ``collected/rank<R>/``.
* **Fleet-level SLO evaluation.** Rank 0 observes only fast probes,
  rank 1 only slow ones — neither rank's own series crosses the SLO
  alone in an alarming way; the rank-0 BurnRateMonitor evaluates the
  merged ``rank="all"`` histogram and fires exactly one ``slo_burn``
  alert for the pod's combined 50% error rate.
"""
import json
import os
import sys

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _ROOT)
sys.path.insert(0, os.path.join(_ROOT, "tools"))

import mxnet_tpu as mx                                  # noqa: E402
from mxnet_tpu import telemetry                         # noqa: E402
from mxnet_tpu.telemetry import healthplane as hp       # noqa: E402
from mxnet_tpu.telemetry import metrics as tm           # noqa: E402


def main():
    out_dir = sys.argv[1]
    kv = mx.kvstore.create("dist_sync")
    rank = kv.rank

    # Each rank's recorder writes to a PRIVATE directory — nothing
    # below may rely on peers reading it.
    local_dir = os.path.join(out_dir, "local_rank%d" % rank)
    recorder = telemetry.FlightRecorder(local_dir, rank=rank,
                                        rate_limit_s=0.0)
    collector = hp.DiagCollector(
        kv, recorder, interval_s=0.0,
        directory=os.path.join(out_dir, "collected") if rank == 0
        else None)

    lat = tm.REGISTRY.histogram("podhp_latency_seconds",
                                "synthetic probe latency",
                                buckets=(0.1, 1.0))
    aggregator = telemetry.Aggregator(kv, interval_s=0.0)
    monitor = telemetry.StepMonitor(warn_interval_s=1e9)
    burn = telemetry.BurnRateMonitor(
        monitor=monitor, eval_interval_s=0.0,
        registry=tm.Registry())     # gauges stay out of the pushed snapshot
    burn.add(aggregator.fleet_slo("pod_latency", 0.99, 0.1,
                                  "podhp_latency_seconds"))

    # Baseline SLO sample BEFORE any traffic (cumulative differencing).
    if rank == 0:
        burn.evaluate(now=1_000_000.0)

    # Traffic: rank 0 is 100% good (50 ms <= 100 ms threshold), rank 1
    # is 100% bad (500 ms) — the pod is 50% bad, burn 0.5/0.01 = 50x.
    for _ in range(50):
        lat.observe(0.05 if rank == 0 else 0.5)
    aggregator.step()               # push this rank's snapshot
    kv._barrier()                   # both snapshots have landed

    if rank == 0:
        aggregator.step()           # pull + merge the pod view
        # ONE evaluation pass over the merged view -> exactly one
        # pod-level alert (not one per rank); a continuing burn would
        # keep re-firing on later passes, Prometheus-style.
        burns = burn.evaluate(now=1_000_060.0)
        with open(os.path.join(out_dir, "slo.txt"), "w") as f:
            f.write(json.dumps({
                "alerts": monitor.anomaly_counts.get("slo_burn", 0),
                "burn_5m": burns["pod_latency"]["5m"],
                "merged_p99": aggregator.merged_quantile(
                    "podhp_latency_seconds", 0.99),
            }))

    # -- pod snapshot over the kvstore ----------------------------------------
    if rank == 0:
        collector.request_pod_bundle("pod_snapshot",
                                     "acceptance pod snapshot")
    kv._barrier()                   # request is posted before anyone polls
    collector.step()                # every rank: poll -> capture -> push
    assert recorder.bundles, "rank %d captured no bundle" % rank
    kv._barrier()                   # all pushes processed server-side
    if rank == 0:
        collector.collect()         # drain whatever landed by now
        with open(os.path.join(out_dir, "collected.txt"), "w") as f:
            f.write("\n".join(sorted(collector.collected)))
    kv._barrier()
    return 0


if __name__ == "__main__":
    sys.exit(main())
