"""Worker program for the 2-process pod-profile acceptance test
(tests/test_profiling.py, launched via tools/launch.py roles).

Proves the ISSUE 12 pod-profile property over a REAL dist kvstore:
each rank runs its own ContinuousProfiler (private retention ring, no
shared filesystem); rank 0's ``request_pod_profile`` fan-out makes
every rank push its collapsed capture over the kvstore diag channel;
rank 0 collects one ``profile.rank<R>.*.collapsed`` per rank and merges
them into one pod profile whose stacks keep per-rank roots.
"""
import json
import os
import sys
import threading
import time

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _ROOT)
sys.path.insert(0, os.path.join(_ROOT, "tools"))

import mxnet_tpu as mx                                  # noqa: E402
from mxnet_tpu import telemetry                         # noqa: E402
from mxnet_tpu.telemetry import healthplane as hp       # noqa: E402


def rank_marker_0():
    """Rank 0's distinctive busy frame (shows up in its stacks)."""
    time.sleep(0.002)


def rank_marker_1():
    """Rank 1's distinctive busy frame."""
    time.sleep(0.002)


def main():
    out_dir = sys.argv[1]
    kv = mx.kvstore.create("dist_sync")
    rank = kv.rank
    marker = rank_marker_0 if rank == 0 else rank_marker_1

    # A worker thread with a rank-distinct frame for the profiler to
    # catch; sampled manually for determinism (no Hz-timing races).
    stop = threading.Event()

    def busy():
        while not stop.is_set():
            marker()

    worker = threading.Thread(target=busy, name="pod_busy", daemon=True)
    worker.start()

    profiler = telemetry.ContinuousProfiler(hz=200.0, window_s=3600.0,
                                            retain=4)
    for _ in range(50):
        profiler.sample()
    profiler.rotate()

    recorder = telemetry.FlightRecorder(
        os.path.join(out_dir, "local_rank%d" % rank), rank=rank,
        rate_limit_s=0.0)
    collector = hp.DiagCollector(
        kv, recorder, interval_s=0.0, profiler=profiler,
        directory=os.path.join(out_dir, "collected") if rank == 0
        else None)

    if rank == 0:
        collector.request_pod_profile(seconds=3600.0)
    kv._barrier()                   # request posted before anyone polls
    pushed = collector.poll_request()
    assert pushed, "rank %d pushed no profile" % rank
    collector.push_new()            # (no bundles; keeps parity w/ step)
    kv._barrier()                   # all pushes processed server-side
    if rank == 0:
        collector.collect()
        merged = collector.merged_pod_profile()
        with open(os.path.join(out_dir, "result.json"), "w") as f:
            f.write(json.dumps({
                "collected": sorted(collector.collected),
                "merged": merged,
            }))
    kv._barrier()
    stop.set()
    profiler.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
