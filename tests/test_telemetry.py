"""mxnet_tpu.telemetry — unified metrics registry, chrome-trace span
export, and the step-health monitor (ISSUE 3)."""
import json
import math
import threading
import urllib.request

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import telemetry
from mxnet_tpu.telemetry import metrics as tmetrics
from mxnet_tpu.telemetry import trace


# -- metrics registry ---------------------------------------------------------

def test_counter_hammer_no_lost_increments():
    """Multi-threaded hammer: concurrent labeled increments are never
    lost, and exposition snapshots taken mid-hammer stay parseable."""
    reg = tmetrics.Registry()
    c = reg.counter("hammer_total", "hammered", labels=("worker",))
    n_threads, n_incs = 8, 5000
    renders = []

    def hit(i):
        child = c.labels(worker="w%d" % (i % 2))
        for _ in range(n_incs):
            child.inc()

    def scrape():
        for _ in range(50):
            renders.append(reg.render_prometheus())

    threads = [threading.Thread(target=hit, args=(i,))
               for i in range(n_threads)]
    threads.append(threading.Thread(target=scrape))
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert c.labels(worker="w0").value == 4 * n_incs
    assert c.labels(worker="w1").value == 4 * n_incs
    for text in renders:
        for line in text.splitlines():
            assert line.startswith("#") or " " in line


def test_histogram_exact_aggregates_and_quantiles():
    reg = tmetrics.Registry()
    h = reg.histogram("lat_seconds", "latencies")
    values = [0.0005, 0.001, 0.002, 0.004, 0.1]
    for v in values:
        h.observe(v)
    snap = h.snapshot()
    assert snap["count"] == 5
    assert snap["sum"] == pytest.approx(sum(values))
    assert snap["min"] == pytest.approx(min(values))
    assert snap["max"] == pytest.approx(max(values))
    # cumulative bucket counts are monotone and end at count
    cums = [c for _, c in snap["buckets"]]
    assert cums == sorted(cums) and cums[-1] == 5
    assert math.isinf(snap["buckets"][-1][0])
    # quantiles: monotone in q, clamped to observed [min, max]
    qs = [h.quantile(q) for q in (0.0, 0.25, 0.5, 0.75, 0.99, 1.0)]
    assert qs == sorted(qs)
    assert snap["min"] <= qs[0] and qs[-1] <= snap["max"]
    assert qs[0] > 0


def test_histogram_empty_and_custom_buckets():
    reg = tmetrics.Registry()
    h = reg.histogram("x_seconds", buckets=(1.0, 2.0, 4.0))
    assert h.quantile(0.5) == 0.0
    h.observe(100.0)            # overflow bucket
    assert h.quantile(0.5) == pytest.approx(100.0)
    with pytest.raises(ValueError):
        reg.histogram("x_seconds", buckets=(1.0, 8.0))


def test_gauge_and_nonblocking_inc():
    reg = tmetrics.Registry()
    g = reg.gauge("pending", "in flight")
    g.set(5)
    g.dec(2)
    assert g.value == 3
    assert g.inc_try(4) is True
    assert g.value == 7
    # inc_try drops the tick (returns False) when the lock is held
    child = g.labels()
    child._lock.acquire()
    try:
        assert g.inc_try(1) is False
    finally:
        child._lock.release()
    assert g.value == 7


def test_registry_type_and_name_validation():
    reg = tmetrics.Registry()
    reg.counter("a_total", labels=("x",))
    with pytest.raises(ValueError):
        reg.gauge("a_total")                    # type conflict
    with pytest.raises(ValueError):
        reg.counter("a_total", labels=("y",))   # label conflict
    with pytest.raises(ValueError):
        reg.counter("bad name")
    with pytest.raises(ValueError):
        reg.counter("ok_total", labels=("bad-label",))
    with pytest.raises(ValueError):
        reg.counter("neg_total").inc(-1)        # counters are monotonic


def test_render_prometheus_format():
    reg = tmetrics.Registry()
    reg.counter("req_total", "requests served",
                labels=("route",)).labels(route='a"b\\c').inc(2)
    reg.histogram("dur_seconds", "durations",
                  buckets=(0.1, 1.0)).observe(0.5)
    text = reg.render_prometheus()
    assert '# TYPE req_total counter' in text
    assert 'req_total{route="a\\"b\\\\c"} 2' in text
    assert '# TYPE dur_seconds histogram' in text
    assert 'dur_seconds_bucket{le="0.1"} 0' in text
    assert 'dur_seconds_bucket{le="1"} 1' in text
    assert 'dur_seconds_bucket{le="+Inf"} 1' in text
    assert 'dur_seconds_sum 0.5' in text
    assert 'dur_seconds_count 1' in text


def test_metrics_http_endpoint():
    reg = tmetrics.Registry()
    reg.counter("served_total").inc(9)
    try:
        server = tmetrics.start_http_server(0, registry=reg)
    except OSError as exc:         # sandboxed CI without localhost bind
        pytest.skip("cannot bind localhost: %s" % exc)
    try:
        host, port = server.server_address[:2]
        with urllib.request.urlopen(
                "http://%s:%d/metrics" % (host, port), timeout=10) as r:
            assert r.status == 200
            body = r.read().decode("utf-8")
        assert "served_total 9" in body
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(
                "http://%s:%d/nope" % (host, port), timeout=10)
    finally:
        server.shutdown()


def test_set_enabled_pauses_recording():
    reg = tmetrics.Registry()
    c = reg.counter("gated_total")
    prev = telemetry.set_enabled(False)
    try:
        c.inc(5)
        with trace.span("gated::span"):
            pass
        trace.instant("gated::instant")
    finally:
        telemetry.set_enabled(prev)
    assert c.value == 0
    names = [e["name"] for e in trace.chrome_trace()["traceEvents"]]
    assert "gated::span" not in names and "gated::instant" not in names
    c.inc(1)
    assert c.value == 1


# -- trace --------------------------------------------------------------------

def test_chrome_trace_schema():
    trace.clear()
    with trace.span("t::outer", step=3):
        with trace.span("t::inner"):
            pass
        trace.instant("t::mark", kind="x")
    trace.complete("t::retro", 1.0, 1.5, rows=2)
    data = trace.chrome_trace()
    text = json.dumps(data)
    data = json.loads(text)                 # round-trips as valid JSON
    events = data["traceEvents"]
    assert events, "no events captured"
    for event in events:
        for key in ("ph", "ts", "pid", "tid", "name"):
            assert key in event, event
        if event["ph"] == "X":
            assert "dur" in event and event["dur"] >= 0
    by_name = {e["name"]: e for e in events}
    assert by_name["t::outer"]["args"] == {"step": 3}
    assert by_name["t::retro"]["dur"] == pytest.approx(0.5e6)
    assert by_name["t::mark"]["ph"] == "i"
    # nesting: inner span lies within outer on the same track
    outer, inner = by_name["t::outer"], by_name["t::inner"]
    assert outer["tid"] == inner["tid"]
    assert outer["ts"] <= inner["ts"]
    assert inner["ts"] + inner["dur"] <= outer["ts"] + outer["dur"] + 1


def test_trace_ring_is_bounded():
    trace.clear()
    cap = trace.capacity()
    for i in range(cap + 500):
        trace.instant("bound::mark", i=i)
    assert trace.event_count() <= cap
    trace.clear()
    assert trace.event_count() == 0


def test_trace_dump_loads_in_perfetto_format(tmp_path):
    trace.clear()
    with trace.span("dumped::span"):
        pass
    path = trace.dump(str(tmp_path / "chrome_trace.json"))
    with open(path) as f:
        data = json.load(f)
    assert isinstance(data["traceEvents"], list)
    assert any(e["name"] == "dumped::span" and e["ph"] == "X"
               for e in data["traceEvents"])


def test_trace_dead_thread_rings_pruned():
    """Thread churn must not grow the ring registry without bound:
    dead threads' rings are pruned past a small retained tail."""
    trace.clear()

    def emit():
        trace.instant("churn::mark")

    for _ in range(64):                   # 64 short-lived threads
        t = threading.Thread(target=emit)
        t.start()
        t.join()
    # force a prune by registering one more ring from a fresh thread
    t = threading.Thread(target=emit)
    t.start()
    t.join()
    with trace._registry_lock:
        dead = sum(1 for th, _ in trace._rings if not th.is_alive())
    assert dead <= trace._MAX_DEAD_RINGS + 1
    # recent dead threads' events are still flushable
    assert any(e["name"] == "churn::mark"
               for e in trace.chrome_trace()["traceEvents"])
    trace.clear()


def test_serving_metrics_close_unregisters_series():
    from mxnet_tpu.serving.metrics import ServingMetrics

    m = ServingMetrics()
    m.record_batch(4, rows=3, n_requests=2, seconds=0.01)
    m.record_shed("queue_full")
    fam = telemetry.REGISTRY.get("mx_serving_requests_total")
    assert any(v[0] == m.server_id for v, _ in fam.collect())
    m.close()
    for name in ("mx_serving_requests_total", "mx_serving_batches_total",
                 "mx_serving_rows_total",
                 "mx_serving_request_latency_seconds",
                 "mx_serving_shed_total"):
        fam = telemetry.REGISTRY.get(name)
        assert not any(v[0] == m.server_id for v, _ in fam.collect()), name


# -- step-health monitor ------------------------------------------------------

class _FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def test_step_monitor_slow_step_detection():
    clock = _FakeClock()
    mon = telemetry.StepMonitor(slow_factor=2.0, alpha=0.5,
                                warmup_steps=3, warn_interval_s=100.0,
                                clock=clock)
    for _ in range(5):
        assert mon.observe_step(0.1) == []
    before = mon._anomalies.labels(kind="slow_step").value
    assert mon.observe_step(0.5) == ["slow_step"]
    assert mon.anomaly_counts["slow_step"] == 1
    assert mon._anomalies.labels(kind="slow_step").value == before + 1
    # the outlier re-baselines the EWMA: a second same-size step is fine
    assert mon.observe_step(0.5) == []
    # legacy mirror rides profiler.dumps
    payload = json.loads(mx.profiler.dumps(format="json"))
    assert payload["counters"]["telemetry::anomalies"] >= 1


def test_step_monitor_warmup_suppresses():
    mon = telemetry.StepMonitor(slow_factor=2.0, warmup_steps=10,
                                clock=_FakeClock())
    assert mon.observe_step(0.001) == []
    assert mon.observe_step(10.0) == []      # still warming up
    assert mon.anomaly_counts == {}


def test_step_monitor_warning_rate_limited(caplog):
    clock = _FakeClock()
    mon = telemetry.StepMonitor(slow_factor=2.0, alpha=0.0,
                                warmup_steps=0, warn_interval_s=60.0,
                                clock=clock)
    mon.observe_step(0.1)
    with caplog.at_level("WARNING", logger="mxnet_tpu.telemetry"):
        for _ in range(5):
            mon.observe_step(1.0)        # alpha=0: EWMA stays 0.1
        assert mon.anomaly_counts["slow_step"] == 5
        emitted = [r for r in caplog.records if "slow step" in r.message]
        assert len(emitted) == 1         # rate-limited to one per window
        clock.t += 61.0
        mon.observe_step(1.0)
        emitted = [r for r in caplog.records if "slow step" in r.message]
        assert len(emitted) == 2
        assert "suppressed" in emitted[-1].getMessage()


def test_warn_rate_limited_concurrent_exactly_once(caplog):
    """ISSUE 5 satellite: N threads racing the same key inside one
    window emit EXACTLY one warning; every suppressed call is still
    counted and reported on the next window's line."""
    import logging

    from mxnet_tpu import log as mxlog

    logger = logging.getLogger("rate_limit_hammer")
    key = "hammer:%d" % id(object())
    n_threads, n_calls = 8, 200
    results = []
    barrier = threading.Barrier(n_threads)

    def hammer():
        barrier.wait()
        mine = []
        for _ in range(n_calls):
            mine.append(mxlog.warn_rate_limited(
                logger, key, 60.0, "storm warning", now=10.0))
        results.append(mine)

    with caplog.at_level("WARNING", logger="rate_limit_hammer"):
        threads = [threading.Thread(target=hammer)
                   for _ in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        emitted = [r for r in caplog.records
                   if "storm warning" in r.getMessage()]
        assert len(emitted) == 1                 # exactly once
        flat = [r for rs in results for r in rs]
        assert flat.count(True) == 1             # one caller won
        # next window: the one emission reports every suppressed call
        assert mxlog.warn_rate_limited(
            logger, key, 60.0, "storm warning", now=80.0) is True
        tail = [r for r in caplog.records
                if "storm warning" in r.getMessage()][-1].getMessage()
        assert "+%d suppressed" % (n_threads * n_calls - 1) in tail


def test_step_monitor_recompile_detection():
    class FakeOp:
        on_trace = None
        _op = None

    op = FakeOp()
    hits = []
    op.on_trace = lambda o: hits.append(o)   # pre-existing hook chains
    mon = telemetry.StepMonitor(expected_traces=1, clock=_FakeClock())
    mon.attach(op)
    op.on_trace(op)                          # warmup compile: expected
    assert mon.anomaly_counts.get("recompile", 0) == 0
    op.on_trace(op)                          # retrace: anomaly
    op.on_trace(op)
    assert mon.anomaly_counts["recompile"] == 2
    assert len(hits) == 3                    # original hook kept firing


def test_step_monitor_recompile_on_real_cached_op():
    from mxnet_tpu.cached_op import CachedOp

    op = CachedOp(lambda x: x * 2.0)
    mon = telemetry.StepMonitor(expected_traces=1, clock=_FakeClock())
    mon.attach(op)
    a = op(mx.nd.ones((2, 2)))
    a.wait_to_read()
    assert mon.anomaly_counts.get("recompile", 0) == 0
    b = op(mx.nd.ones((3, 3)))               # new shape → retrace
    b.wait_to_read()
    assert mon.anomaly_counts["recompile"] == 1


def test_step_monitor_checkpoint_backlog():
    class FakeManager:
        pending = 0

    mgr = FakeManager()
    mon = telemetry.StepMonitor(checkpoint_backlog=2, warmup_steps=0,
                                clock=_FakeClock())
    mon.watch_checkpoint(mgr)
    assert mon.observe_step(0.1) == []
    mgr.pending = 3
    assert "checkpoint_backlog" in mon.observe_step(0.1)
    assert mon.anomaly_counts["checkpoint_backlog"] == 1
    snap = mon.snapshot()
    assert snap["steps"] == 2 and snap["ewma_ms"] > 0


def test_step_monitor_step_context_manager():
    clock = _FakeClock()
    mon = telemetry.StepMonitor(clock=clock)
    with mon.step(0):
        clock.t += 0.25
    assert mon.ewma_seconds == pytest.approx(0.25)
    assert mon.steps == 1


# -- cross-subsystem integration ---------------------------------------------

def test_serving_and_checkpoint_share_registry(tmp_path):
    """Acceptance: serving stats and checkpoint counters all read
    through the one telemetry registry."""
    from mxnet_tpu import serving
    from mxnet_tpu.checkpoint import CheckpointManager

    w = mx.nd.array(np.eye(4, dtype=np.float32))
    srv = serving.InferenceServer(lambda wp, x: mx.nd.dot(x, wp), [w],
                                  item_shape=(4,), buckets=(2,),
                                  max_delay_ms=0)
    try:
        srv.predict(np.ones((2, 4), np.float32))
    finally:
        srv.shutdown()
    mgr = CheckpointManager(str(tmp_path))
    mgr.save(1, {"a": np.ones((4, 4), np.float32)}, sync=True)
    mgr.close()

    text = telemetry.render_prometheus()
    assert "mx_serving_requests_total" in text
    assert "mx_serving_request_latency_seconds_bucket" in text
    assert 'mx_profiler_counter{name="checkpoint::bytes"}' in text
    assert "mx_cachedop_compiles_total" in text
    payload = json.loads(mx.profiler.dumps(format="json"))
    assert payload["counters"]["serving::requests"] >= 1
    assert payload["counters"]["checkpoint::bytes"] > 0
    # srv.stats() is a view over the same registry children
    sid = srv.metrics.server_id
    fam = telemetry.REGISTRY.get("mx_serving_requests_total")
    mine = {v: c for v, c in fam.collect() if v[0] == sid}
    assert sum(c.value for c in mine.values()) \
        == sum(b["requests"] for b in srv.stats()["buckets"].values())


def test_chrome_trace_spans_all_three_layers(tmp_path):
    """Acceptance: one captured chrome_trace.json holds spans from the
    train-step, serving, and checkpoint layers, and parses as
    trace-event JSON."""
    from mxnet_tpu import gluon, serving
    from mxnet_tpu.checkpoint import CheckpointManager
    from mxnet_tpu.parallel import TrainStep, make_mesh

    trace.clear()
    mx.random.seed(7)
    net = gluon.nn.HybridSequential(prefix="ttel_")
    net.add(gluon.nn.Dense(8, in_units=4, prefix="d1_"))
    net.add(gluon.nn.Dense(2, in_units=8, prefix="d2_"))
    net.initialize()
    step = TrainStep(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                     optimizer="sgd",
                     optimizer_params={"learning_rate": 0.1},
                     mesh=make_mesh())
    x = np.random.RandomState(0).rand(8, 4).astype(np.float32)
    y = np.array([0, 1, 1, 0, 1, 0, 0, 1])
    float(np.asarray(step(x, y)))

    w = mx.nd.array(np.eye(4, dtype=np.float32))
    srv = serving.InferenceServer(lambda wp, xb: mx.nd.dot(xb, wp), [w],
                                  item_shape=(4,), buckets=(1,),
                                  max_delay_ms=0)
    try:
        srv.predict(np.ones((1, 4), np.float32))
    finally:
        srv.shutdown()

    mgr = CheckpointManager(str(tmp_path / "ckpt"))
    mgr.save(1, step.state_dict(), sync=True)
    mgr.close()

    path = trace.dump(str(tmp_path / "chrome_trace.json"))
    with open(path) as f:
        events = json.load(f)["traceEvents"]
    names = {e["name"] for e in events}
    assert any(n.startswith("train_step::") for n in names), names
    assert any(n.startswith("serving::") for n in names), names
    assert any(n.startswith("checkpoint::") for n in names), names
    for event in events:
        for key in ("ph", "ts", "pid", "tid"):
            assert key in event
