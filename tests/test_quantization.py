"""INT8 quantization: ops + calibration driver (reference
tests/python/quantization/test_quantization.py; acceptance: quantized
LeNet within 1% of fp32 accuracy on synthetic MNIST)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import io as mio


def test_quantize_dequantize_roundtrip():
    x = mx.nd.array(np.linspace(-3, 5, 64, dtype=np.float32).reshape(8, 8))
    q, mn, mx_ = mx.nd._contrib_quantize(x, mx.nd.array([-3.0]),
                                         mx.nd.array([5.0]))
    assert str(q.dtype) == "int8"
    back = mx.nd._contrib_dequantize(q, mn, mx_)
    np.testing.assert_allclose(back.asnumpy(), x.asnumpy(), atol=5.0 / 127)


def test_quantize_v2_auto_range():
    x = mx.nd.array(np.array([[-1.0, 0.5, 2.0]], np.float32))
    q, mn, mx_ = mx.nd._contrib_quantize_v2(x)
    assert float(mn.asnumpy()) == -1.0 and float(mx_.asnumpy()) == 2.0
    back = mx.nd._contrib_dequantize(q, mn, mx_)
    np.testing.assert_allclose(back.asnumpy(), x.asnumpy(), atol=2.0 / 127)


def test_optimal_threshold_reasonable():
    from mxnet_tpu.contrib.quantization import _get_optimal_threshold

    rng = np.random.RandomState(0)
    arr = np.concatenate([rng.randn(100000), np.array([50.0])])  # outlier
    lo, hi = _get_optimal_threshold(arr)
    # KL calibration should clip far below the outlier
    assert hi < 25.0 and hi > 1.0


def _make_lenet_data():
    """Synthetic MNIST-like: class k puts a bright patch in quadrant k."""
    rng = np.random.RandomState(42)
    n = 400
    X = (rng.rand(n, 1, 12, 12) * 0.3).astype(np.float32)
    y = rng.randint(0, 4, n).astype(np.float32)
    quads = [(slice(0, 6), slice(0, 6)), (slice(0, 6), slice(6, 12)),
             (slice(6, 12), slice(0, 6)), (slice(6, 12), slice(6, 12))]
    for i in range(n):
        r, c = quads[int(y[i])]
        X[i, 0, r, c] += 1.0
    return X, y


def _lenet_sym():
    data = mx.sym.var("data")
    c1 = mx.sym.Convolution(data, kernel=(3, 3), num_filter=8, name="c1")
    a1 = mx.sym.Activation(c1, act_type="relu")
    p1 = mx.sym.Pooling(a1, kernel=(2, 2), stride=(2, 2), pool_type="max")
    f1 = mx.sym.FullyConnected(p1, num_hidden=32, name="f1")
    a2 = mx.sym.Activation(f1, act_type="relu")
    f2 = mx.sym.FullyConnected(a2, num_hidden=4, name="f2")
    return mx.sym.SoftmaxOutput(f2, name="softmax")


def test_quantized_lenet_accuracy():
    X, y = _make_lenet_data()
    train_iter = mio.NDArrayIter(X[:300], y[:300], batch_size=50,
                                 shuffle=True, label_name="softmax_label")
    test_iter = mio.NDArrayIter(X[300:], y[300:], batch_size=50,
                                label_name="softmax_label")
    mod = mx.mod.Module(_lenet_sym(), context=mx.cpu(),
                        label_names=["softmax_label"])
    mod.fit(train_iter, num_epoch=10, optimizer="adam",
            optimizer_params={"learning_rate": 0.01})
    fp32_acc = mod.score(test_iter, mx.metric.Accuracy())[0][1]
    assert fp32_acc > 0.7, "fp32 LeNet failed to train (acc %.3f)" % fp32_acc

    arg_params, aux_params = mod.get_params()
    calib_iter = mio.NDArrayIter(X[:100], y[:100], batch_size=50,
                                 label_name="softmax_label")
    from mxnet_tpu.contrib.quantization import quantize_model

    qsym, qargs, qaux = quantize_model(
        mod.symbol, arg_params, aux_params, calib_mode="naive",
        calib_data=calib_iter, num_calib_examples=100)
    # int8 weights really are int8
    assert any(str(v.dtype) == "int8" for v in qargs.values())

    qmod = mx.mod.Module(qsym, context=mx.cpu(),
                         label_names=["softmax_label"])
    test_iter.reset()
    qmod.bind(data_shapes=test_iter.provide_data,
              label_shapes=test_iter.provide_label, for_training=False)
    qmod.set_params(qargs, qaux, allow_missing=False)
    test_iter.reset()
    q_acc = qmod.score(test_iter, mx.metric.Accuracy())[0][1]
    assert abs(fp32_acc - q_acc) <= 0.01 + 1e-9, \
        "quantized accuracy %.3f vs fp32 %.3f" % (q_acc, fp32_acc)


def test_quantized_ops_lower_to_int8_mxu_path():
    """The contraction must reach XLA with s8 operands and an s32
    accumulator — not an f32 matmul of casted values (the int8 MXU
    path; VERDICT r3 weak #5)."""
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.ops.quantization_ops import (_quantized_conv,
                                                _quantized_fc)

    x = jnp.ones((2, 8), jnp.float32)
    w = jnp.ones((4, 8), jnp.int8)
    hlo = jax.jit(lambda a, b: _quantized_fc(
        a, b, num_hidden=4, no_bias=True, min_data=-1.0, max_data=1.0,
        w_scale=1.0)).lower(x, w).as_text()
    assert ("xi8" in hlo and "xi32" in hlo), hlo[:800]

    xc = jnp.ones((1, 3, 8, 8), jnp.float32)
    wc = jnp.ones((4, 3, 3, 3), jnp.int8)
    hlo = jax.jit(lambda a, b: _quantized_conv(
        a, b, kernel=(3, 3), num_filter=4, no_bias=True,
        min_data=-1.0, max_data=1.0, w_scale=1.0)).lower(xc, wc).as_text()
    assert ("xi8" in hlo and "xi32" in hlo), hlo[:800]
