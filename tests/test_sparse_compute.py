"""Sparse compute path: CSR dot via segment ops, lazy row_sparse
optimizer updates, non-densifying kvstore pulls, and a LibSVM linear
model converging with CSR data + row_sparse weights (reference:
tests/python/unittest/test_sparse_operator.py, test_sparse_ndarray.py,
tests/python/train/test_sparse_fm.py; VERDICT missing #5)."""
import os
import tempfile

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu.ndarray import sparse as sp


def _rand_csr(rng, m, n, density=0.3):
    dense = rng.rand(m, n) * (rng.rand(m, n) < density)
    return sp.csr_matrix(dense.astype(np.float32)), dense.astype(np.float32)


def test_csr_matrix_vectorized():
    rng = np.random.RandomState(0)
    csr, dense = _rand_csr(rng, 13, 7)
    np.testing.assert_allclose(csr.asnumpy(), dense)
    # rows with no nonzeros round-trip
    z = sp.csr_matrix(np.zeros((3, 4), np.float32))
    np.testing.assert_allclose(z.asnumpy(), 0)


def test_dot_csr_dense():
    rng = np.random.RandomState(1)
    csr, dense = _rand_csr(rng, 9, 6)
    rhs = mx.nd.array(rng.rand(6, 4).astype(np.float32))
    out = sp.dot(csr, rhs)
    np.testing.assert_allclose(out.asnumpy(), dense @ rhs.asnumpy(),
                               rtol=1e-5)
    # method form
    out2 = csr.dot(rhs)
    np.testing.assert_allclose(out2.asnumpy(), out.asnumpy())


def test_dot_csr_dense_transpose():
    rng = np.random.RandomState(2)
    csr, dense = _rand_csr(rng, 9, 6)
    rhs = mx.nd.array(rng.rand(9, 3).astype(np.float32))
    out = sp.dot(csr, rhs, transpose_a=True)
    np.testing.assert_allclose(out.asnumpy(), dense.T @ rhs.asnumpy(),
                               rtol=1e-5)


def test_sparse_sgd_lazy_update():
    opt = mx.optimizer.SGD(learning_rate=0.5, momentum=0.9, wd=0.0)
    w = mx.nd.ones((6, 3))
    state = opt.create_state(0, w)
    grad = sp.row_sparse_array(
        (np.full((2, 3), 1.0, np.float32), np.array([1, 4])), shape=(6, 3))
    opt.update(0, w, grad, state)
    wn = w.asnumpy()
    # untouched rows unchanged (lazy), touched rows stepped
    np.testing.assert_allclose(wn[0], 1.0)
    np.testing.assert_allclose(wn[1], 1.0 - 0.5)
    # momentum state only on touched rows
    st = state.asnumpy()
    assert np.all(st[0] == 0) and np.all(st[1] != 0)
    # second update compounds momentum on touched rows only
    opt.update(0, w, grad, state)
    np.testing.assert_allclose(w.asnumpy()[0], 1.0)
    np.testing.assert_allclose(w.asnumpy()[1], 1.0 - 0.5 - (0.9 * 0.5 + 0.5))


def test_sparse_adam_update_duplicates_aggregate():
    opt = mx.optimizer.Adam(learning_rate=0.1)
    w = mx.nd.ones((5, 2))
    state = opt.create_state(0, w)
    # duplicate indices must sum before the moment update
    grad = sp.row_sparse_array(
        (np.array([[1.0, 1.0], [2.0, 2.0]], np.float32),
         np.array([2, 2])), shape=(5, 2))
    opt.update(0, w, grad, state)
    wn = w.asnumpy()
    assert np.all(wn[0] == 1.0) and np.all(wn[2] < 1.0)
    mean = state[0].asnumpy()
    np.testing.assert_allclose(mean[2], 0.1 * 3.0)   # (1-beta1)*(1+2)


def test_kvstore_row_sparse_pull_no_densify():
    kv = mx.kv.create("local")
    init = sp.row_sparse_array(
        (np.arange(6, dtype=np.float32).reshape(3, 2),
         np.array([0, 2, 5])), shape=(8, 2))
    kv.init("emb", init)
    out = sp.zeros("row_sparse", (3, 2))
    kv.row_sparse_pull("emb", out=out,
                       row_ids=mx.nd.array([0, 3, 5], dtype="int64"))
    got = out.data.asnumpy()
    np.testing.assert_allclose(got[0], [0, 1])       # stored row 0
    np.testing.assert_allclose(got[1], [0, 0])       # absent row -> 0
    np.testing.assert_allclose(got[2], [4, 5])       # stored row 5


def test_sparse_embedding_trains_lazily():
    """SparseEmbedding + Trainer: gradient flows as row_sparse, lazy
    updates touch only seen rows."""
    from mxnet_tpu.gluon.contrib.nn import SparseEmbedding

    emb = SparseEmbedding(50, 4)
    emb.initialize()
    w0 = emb.weight.data().asnumpy().copy()
    trainer = gluon.Trainer(emb.collect_params(), "sgd",
                            {"learning_rate": 1.0, "momentum": 0.9})
    idx = mx.nd.array(np.array([3, 7, 3], np.float32))
    with autograd.record():
        out = emb(idx)
        loss = (out * out).sum()
    loss.backward()
    trainer.step(1)
    w1 = emb.weight.data().asnumpy()
    changed = np.where(np.abs(w1 - w0).sum(axis=1) > 0)[0]
    assert set(changed.tolist()) == {3, 7}


def test_libsvm_linear_model_converges():
    """Sparse logistic regression: CSR features, row_sparse weight,
    gradients via dot(csr.T, residual) — the reference's sparse linear
    benchmark pattern (benchmark/python/sparse, test_sparse_fm)."""
    rng = np.random.RandomState(0)
    n, d = 200, 60
    dense = (rng.rand(n, d) * (rng.rand(n, d) < 0.15)).astype(np.float32)
    w_true = rng.randn(d).astype(np.float32)
    y = (dense @ w_true > 0).astype(np.float32)

    # write libsvm file, read through LibSVMIter
    with tempfile.NamedTemporaryFile("w", suffix=".libsvm",
                                     delete=False) as f:
        for i in range(n):
            cols = np.nonzero(dense[i])[0]
            f.write("%d %s\n" % (y[i], " ".join(
                "%d:%.6f" % (c, dense[i, c]) for c in cols)))
        path = f.name
    try:
        it = mx.io.LibSVMIter(data_libsvm=path, data_shape=(d,),
                              batch_size=50)
        w = mx.nd.zeros((d, 1))
        bias = mx.nd.zeros((1,))
        lr = 2.0
        losses = []
        for epoch in range(50):
            it.reset()
            total, count = 0.0, 0
            for batch in it:
                Xb = batch.data[0]               # CSRNDArray
                yb = batch.label[0].reshape((-1, 1))
                logits = sp.dot(Xb, w) + bias
                p = logits.sigmoid()
                eps = 1e-7
                loss = -(yb * (p + eps).log()
                         + (1 - yb) * (1 - p + eps).log()).mean()
                resid = (p - yb) / Xb.shape[0]
                gw = sp.dot(Xb, resid, transpose_a=True)
                w -= lr * gw
                bias -= lr * resid.sum()
                total += float(loss.asnumpy())
                count += 1
            losses.append(total / count)
        assert losses[-1] < losses[0] * 0.5, losses[::7]
        # training accuracy
        pred = (dense @ w.asnumpy().ravel() + float(bias.asnumpy()) > 0)
        acc = (pred == (y > 0)).mean()
        assert acc > 0.9, "sparse linear model accuracy %.3f" % acc
    finally:
        os.unlink(path)


def test_gather_rows_unsorted_and_empty():
    """Regressions: unsorted stored indices and empty stores."""
    kv = mx.kv.create("local")
    vals = np.array([[10., 11.], [20., 21.]], np.float32)
    kv.init("u", sp.row_sparse_array((vals, np.array([5, 1])), shape=(8, 2)))
    out = mx.nd.zeros((2, 2))
    kv.row_sparse_pull("u", out=out,
                       row_ids=mx.nd.array([1, 5], dtype="int64"))
    np.testing.assert_allclose(out.asnumpy(), [[20, 21], [10, 11]])
    # empty store -> zeros, no crash
    kv.init("e", sp.zeros("row_sparse", (4, 2)))
    out2 = mx.nd.zeros((2, 2))
    kv.row_sparse_pull("e", out=out2,
                       row_ids=mx.nd.array([0, 3], dtype="int64"))
    np.testing.assert_allclose(out2.asnumpy(), 0)


def test_dot_csr_vector_rhs():
    csr = sp.csr_matrix(np.array([[1., 0., 2.], [0., 3., 0.]], np.float32))
    v = mx.nd.array(np.array([1., 1., 1.], np.float32))
    out = sp.dot(csr, v)
    assert out.shape == (2,)
    np.testing.assert_allclose(out.asnumpy(), [3., 3.])
    out_t = sp.dot(csr, mx.nd.array(np.array([1., 1.], np.float32)),
                   transpose_a=True)
    np.testing.assert_allclose(out_t.asnumpy(), [1., 3., 2.])


def test_rsp_cross_context_keeps_sparsity():
    """as_in_context preserves row_sparse storage (no silent densify in
    cross-context kvstore pushes)."""
    rsp = sp.row_sparse_array(
        (np.ones((2, 3), np.float32), np.array([1, 4])), shape=(6, 3))
    moved = rsp.as_in_context(mx.cpu(1))
    assert isinstance(moved, sp.RowSparseNDArray)
    assert moved.indices.shape == (2,)
    kv = mx.kv.create("local")
    kv.init("w", mx.nd.zeros((6, 3)))
    opt = mx.optimizer.SGD(learning_rate=1.0)
    kv.set_optimizer(opt)
    kv.push("w", rsp.as_in_context(mx.cpu(1)))
    out = mx.nd.zeros((6, 3))
    kv.pull("w", out=out)
    got = out.asnumpy()
    np.testing.assert_allclose(got[1], -1.0)
    np.testing.assert_allclose(got[0], 0.0)


def test_sparse_sgd_std_update_decays_all_rows():
    opt = mx.optimizer.SGD(learning_rate=0.1, momentum=0.9, wd=0.1,
                           lazy_update=False)
    w = mx.nd.ones((4, 2))
    state = opt.create_state(0, w)
    grad = sp.row_sparse_array(
        (np.ones((1, 2), np.float32), np.array([2])), shape=(4, 2))
    opt.update(0, w, grad, state)
    wn = w.asnumpy()
    # untouched rows still decay by lr*wd under std update
    np.testing.assert_allclose(wn[0], 1.0 - 0.1 * 0.1, rtol=1e-6)
    assert wn[2][0] < wn[0][0]
