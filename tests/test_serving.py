"""mxnet_tpu.serving — shape-bucketed batching inference server
(ISSUE 1 tentpole). Tiny models + max_delay_ms <= 20 keep every test
CI-sized; every server is closed in a finally/with so no worker thread
outlives its test."""
import json
import threading
import time
from concurrent.futures import ThreadPoolExecutor

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import profiler, serving
from mxnet_tpu.cached_op import CachedOp
from mxnet_tpu.serving import (BucketPolicy, DeadlineExceededError,
                               InferenceServer, QueueFullError)

_W = None


def _weight():
    global _W
    if _W is None:
        _W = mx.nd.array(np.arange(12, dtype=np.float32).reshape(4, 3))
    return _W


def _dot_fn(w, x):
    return mx.nd.dot(x, w)


def _server(**kw):
    kw.setdefault("item_shape", (4,))
    kw.setdefault("max_batch", 8)
    kw.setdefault("max_delay_ms", 10)
    return InferenceServer(_dot_fn, [_weight()], **kw)


# -- bucket policy ----------------------------------------------------------

def test_bucket_policy_powers_of_two():
    p = BucketPolicy(max_batch=32)
    assert p.buckets == (1, 2, 4, 8, 16, 32)
    assert p.bucket_for(1) == 1
    assert p.bucket_for(3) == 4
    assert p.bucket_for(32) == 32
    assert p.pad_rows(5) == 3
    with pytest.raises(ValueError):
        p.bucket_for(33)
    with pytest.raises(ValueError):
        p.bucket_for(0)


def test_bucket_policy_explicit_ladder_and_uneven_top():
    p = BucketPolicy(buckets=(8, 1, 32))
    assert p.buckets == (1, 8, 32) and p.max_batch == 32
    assert p.bucket_for(2) == 8
    # non-power-of-two max_batch still tops the default ladder exactly
    q = BucketPolicy(max_batch=12)
    assert q.buckets == (1, 2, 4, 8, 12)


# -- acceptance (a): coalescing ---------------------------------------------

def test_concurrent_submits_coalesce_into_min_device_calls():
    """N concurrent batch-1 submits execute in <= ceil(N/max_batch)
    device calls, with correct per-request results."""
    srv = _server(warmup=True)
    try:
        base = srv.metrics.total_batches
        srv.pause()
        xs = [np.random.rand(1, 4).astype(np.float32) for _ in range(17)]
        with ThreadPoolExecutor(8) as pool:
            futs = list(pool.map(srv.submit, xs))
        srv.resume()
        outs = [f.result(timeout=30) for f in futs]
        w = _weight().asnumpy()
        for x, y in zip(xs, outs):
            assert y.shape == (1, 3)
            np.testing.assert_allclose(y.asnumpy(), x @ w, rtol=1e-5)
        calls = srv.metrics.total_batches - base
        assert calls <= -(-17 // 8), "17 singles took %d device calls" % calls
    finally:
        srv.shutdown()


# -- acceptance (b): one compile per bucket ---------------------------------

def test_one_compile_per_bucket_and_warmup_idempotent():
    srv = _server(warmup=True, start=False)
    try:
        assert srv.compile_count == len(srv.policy.buckets)  # 1,2,4,8
        srv.warmup()  # second warmup: no new executables
        assert srv.compile_count == len(srv.policy.buckets)
        srv.start()
        # warmed-bucket traffic never compiles
        srv.pause()
        futs = [srv.submit(np.ones((1, 4), np.float32)) for _ in range(9)]
        srv.resume()
        for f in futs:
            f.result(timeout=30)
        assert srv.compile_count == len(srv.policy.buckets)
    finally:
        srv.shutdown()


def test_cached_op_executable_cache_many_signatures():
    """The underlying contract: CachedOp compiles once per shape
    signature, and repeats are pure cache hits."""
    cop = CachedOp(lambda x: x * 2.0 + 1.0)
    shapes = [(1, 4), (2, 4), (4, 4), (8, 4), (3, 5)]
    for s in shapes * 3:
        y = cop.inference(mx.nd.ones(s))
        assert y.shape == s
    assert cop.num_traces == len(shapes)


def test_inference_call_skips_tape_and_train_mode():
    """CachedOp.inference never records on the tape even inside
    record(), and runs the eval-mode trace (dropout disabled)."""
    cop = CachedOp(lambda x: mx.nd.Dropout(x, p=0.5) * 1.0)
    x = mx.nd.ones((4, 4))
    x.attach_grad()
    with mx.autograd.record():
        y = cop.inference(x)
    assert y._ag_node is None, "inference() recorded on the tape"
    # eval-mode dropout is identity
    np.testing.assert_allclose(y.asnumpy(), np.ones((4, 4)), rtol=1e-6)


# -- unpadding --------------------------------------------------------------

def test_unpadding_slices_multi_row_requests():
    srv = _server(warmup=True)
    try:
        srv.pause()
        xa = np.random.rand(3, 4).astype(np.float32)
        xb = np.random.rand(2, 4).astype(np.float32)
        fa, fb = srv.submit(xa), srv.submit(xb)
        srv.resume()
        ya, yb = fa.result(timeout=30), fb.result(timeout=30)
        w = _weight().asnumpy()
        assert ya.shape == (3, 3) and yb.shape == (2, 3)
        np.testing.assert_allclose(ya.asnumpy(), xa @ w, rtol=1e-5)
        np.testing.assert_allclose(yb.asnumpy(), xb @ w, rtol=1e-5)
        # 5 rows coalesced -> one bucket-8 call
        assert srv.stats()["buckets"][8]["batches"] == 1
    finally:
        srv.shutdown()


def test_request_shape_validation():
    srv = _server(warmup=False, start=False)
    try:
        with pytest.raises(ValueError):
            srv.submit(np.ones((1, 5), np.float32))   # wrong item shape
        with pytest.raises(ValueError):
            srv.submit(np.ones((9, 4), np.float32))   # rows > max_batch
    finally:
        srv.shutdown()


# -- acceptance (c): overload -----------------------------------------------

def test_queue_full_sheds_while_inflight_completes():
    srv = _server(warmup=True, max_queue=4)
    try:
        srv.pause()
        futs = [srv.submit(np.ones((1, 4), np.float32)) for _ in range(4)]
        with pytest.raises(QueueFullError):
            srv.submit(np.ones((1, 4), np.float32))
        srv.resume()
        for f in futs:  # admitted requests still complete
            assert f.result(timeout=30).shape == (1, 3)
        assert srv.metrics.total_shed == 1
        assert srv.stats()["shed"]["queue_full"] == 1
    finally:
        srv.shutdown()


def test_short_deadline_served_when_device_idle():
    """A timeout shorter than the batching window must cap the wait —
    the idle device dispatches just before expiry instead of shedding."""
    srv = _server(warmup=True, max_delay_ms=300)
    try:
        out = srv.predict(np.ones((1, 4), np.float32), timeout_ms=60)
        assert out.shape == (1, 3)
        assert srv.stats()["shed"] == {}
    finally:
        srv.shutdown()


def test_batcher_rejects_oversize_rows_directly():
    """DynamicBatcher.submit is public API: rows > max_batch must raise,
    not wedge the collect loop into a hot spin."""
    srv = _server(warmup=False, start=False)
    try:
        with pytest.raises(ValueError):
            srv._batcher.submit(np.zeros((9, 4), np.float32), 9)
    finally:
        srv.shutdown()


def test_warmup_after_start_no_duplicate_compiles():
    """warmup() on an already-serving server is safe (device calls are
    serialized with the worker) and never double-compiles a bucket."""
    srv = _server(warmup=False)  # worker running, nothing warmed
    try:
        futs = [srv.submit(np.ones((1, 4), np.float32)) for _ in range(4)]
        srv.warmup()
        for f in futs:
            assert f.result(timeout=30).shape == (1, 3)
        assert srv.compile_count == len(srv.policy.buckets)
    finally:
        srv.shutdown()


def test_second_server_does_not_reset_shared_counters():
    """Constructing another server must not zero the shared 'serving'
    profiler-domain counters the first one already recorded."""
    s1 = _server(warmup=True)
    try:
        s1.predict(np.ones((1, 4), np.float32))
        before = json.loads(profiler.dumps(
            format="json"))["counters"]["serving::requests"]
        s2 = _server(warmup=True)
        try:
            s2.predict(np.ones((1, 4), np.float32))
        finally:
            s2.shutdown()
        after = json.loads(profiler.dumps(
            format="json"))["counters"]["serving::requests"]
        assert after == before + 1
    finally:
        s1.shutdown()


def test_deadline_shedding():
    srv = _server(warmup=True)
    try:
        srv.pause()
        doomed = srv.submit(np.ones((1, 4), np.float32), timeout_ms=5)
        live = srv.submit(np.ones((1, 4), np.float32))
        time.sleep(0.05)
        srv.resume()
        with pytest.raises(DeadlineExceededError):
            doomed.result(timeout=30)
        assert live.result(timeout=30).shape == (1, 3)
        assert srv.stats()["shed"]["deadline"] == 1
    finally:
        srv.shutdown()


def test_worker_survives_shedding_entire_queue():
    """Regression: expiring EVERY queued request must not kill the
    worker (the empty-queue collect after shedding crashed the loop,
    found by examples/serve_mnist.py)."""
    srv = _server(warmup=True)
    try:
        srv.pause()
        doomed = srv.submit(np.ones((1, 4), np.float32), timeout_ms=1)
        time.sleep(0.03)
        srv.resume()
        with pytest.raises(DeadlineExceededError):
            doomed.result(timeout=30)
        # the worker survived and keeps serving
        assert srv.predict(np.ones((1, 4), np.float32)).shape == (1, 3)
        assert srv._batcher._thread.is_alive()
    finally:
        srv.shutdown()


def test_cancelled_requests_are_dropped_not_fatal():
    """A client-cancelled future must not kill the worker or fail its
    co-batched neighbors — whether it is dropped at shed time (expired)
    or at dispatch time (set_running_or_notify_cancel)."""
    srv = _server(warmup=True)
    try:
        srv.pause()
        expired = srv.submit(np.ones((1, 4), np.float32), timeout_ms=1)
        at_dispatch = srv.submit(np.ones((1, 4), np.float32))
        live = srv.submit(np.ones((2, 4), np.float32))
        assert expired.cancel() and at_dispatch.cancel()
        time.sleep(0.03)
        srv.resume()
        assert live.result(timeout=30).shape == (2, 3)
        assert srv._batcher._thread.is_alive()
        # cancelled requests ran no device work and were not mis-shed
        assert srv.predict(np.ones((1, 4), np.float32)).shape == (1, 3)
    finally:
        srv.shutdown()


def test_submit_snapshots_caller_buffer():
    """submit() must copy the request: callers may reuse their input
    buffer immediately, while the worker reads it a delay window later."""
    srv = _server(warmup=True)
    try:
        srv.pause()
        buf = np.ones((1, 4), np.float32)
        f1 = srv.submit(buf)
        buf[:] = 5.0  # reuse the buffer before the batch dispatches
        f2 = srv.submit(buf)
        srv.resume()
        w = _weight().asnumpy()
        np.testing.assert_allclose(f1.result(timeout=30).asnumpy(),
                                   np.ones((1, 4)) @ w, rtol=1e-5)
        np.testing.assert_allclose(f2.result(timeout=30).asnumpy(),
                                   np.full((1, 4), 5.0) @ w, rtol=1e-5)
    finally:
        srv.shutdown()


# -- metrics / profiler integration -----------------------------------------

def test_profiler_dumps_contains_per_bucket_serving_stats():
    profiler.dumps(reset=True)
    srv = _server(warmup=True)
    try:
        for _ in range(3):
            srv.predict(np.ones((2, 4), np.float32))
    finally:
        srv.shutdown()
    table = profiler.dumps()
    assert "serving::bucket_2" in table
    payload = json.loads(profiler.dumps(format="json"))
    assert payload["ops"]["serving::bucket_2"]["calls"] == 3
    assert payload["counters"]["serving::requests"] >= 3
    snap = srv.stats()["buckets"][2]
    assert snap["requests"] == 3 and snap["mean_occupancy"] == 1.0
    assert snap["p99_ms"] >= snap["p50_ms"] > 0


# -- checkpoint backend -----------------------------------------------------

def test_from_checkpoint_matches_direct_forward(tmp_path):
    data = mx.sym.var("data")
    net = mx.sym.FullyConnected(data, num_hidden=6, name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=3, name="fc2")
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    args = {"fc1_weight": mx.nd.array(np.random.randn(6, 4) * 0.5),
            "fc1_bias": mx.nd.zeros((6,)),
            "fc2_weight": mx.nd.array(np.random.randn(3, 6) * 0.5),
            "fc2_bias": mx.nd.zeros((3,))}
    prefix = str(tmp_path / "mlp")
    mx.model.save_checkpoint(prefix, 0, net, args, {})

    x = np.random.rand(5, 4).astype(np.float32)
    feed = dict(args, data=mx.nd.array(x),
                softmax_label=mx.nd.zeros((5,)))
    want = net.bind(mx.cpu(), feed).forward(is_train=False)[0].asnumpy()

    with InferenceServer.from_checkpoint(
            prefix, 0, item_shape=(4,), buckets=(1, 8),
            max_delay_ms=5) as srv:
        got = srv.predict(x)
        assert srv.compile_count == len(srv.policy.buckets)
        np.testing.assert_allclose(got.asnumpy(), want, rtol=1e-5)


# -- lifecycle hygiene ------------------------------------------------------

def test_shutdown_drains_and_joins_worker():
    srv = _server(warmup=True)
    srv.pause()
    futs = [srv.submit(np.ones((1, 4), np.float32)) for _ in range(3)]
    srv.shutdown(drain=True)  # resumes, drains the queue, joins
    for f in futs:
        assert f.result(timeout=1).shape == (1, 3)
    assert srv._batcher._thread is not None
    assert not srv._batcher._thread.is_alive()
    with pytest.raises(RuntimeError):
        srv.submit(np.ones((1, 4), np.float32))


def test_shutdown_before_start_fails_pending():
    """A never-started server has no worker to drain through: shutdown
    must fail queued futures, not leave them hanging forever."""
    srv = _server(warmup=False, start=False)
    fut = srv.submit(np.ones((1, 4), np.float32))
    srv.shutdown(drain=True)
    with pytest.raises(RuntimeError):
        fut.result(timeout=1)


def test_shutdown_without_drain_fails_pending():
    srv = _server(warmup=True)
    srv.pause()
    fut = srv.submit(np.ones((1, 4), np.float32))
    srv.shutdown(drain=False)
    with pytest.raises(RuntimeError):
        fut.result(timeout=1)


def test_worker_threads_are_daemonized():
    srv = _server(warmup=False)
    try:
        assert srv._batcher._thread.daemon
        assert any(t.name == "mx-serving-batcher"
                   for t in threading.enumerate())
    finally:
        srv.shutdown()


# -- readiness-aware admission (ISSUE 11 satellite) ---------------------------

def test_shed_unready_503_until_warm():
    """With shed_unready=True, submits are shed with
    ServiceUnavailableError (the 503 semantics) while /readyz is false
    — queueing them would only blow their deadlines behind the warmup
    compile — and admit normally once every component is ready."""
    from mxnet_tpu.serving import ServiceUnavailableError
    from mxnet_tpu.telemetry import healthplane as hp

    hp.reset()
    try:
        srv = _server(warmup=False, start=False, shed_unready=True)
        try:
            assert not hp.is_ready()        # the server's own slot
            with pytest.raises(ServiceUnavailableError):
                srv.submit(np.ones((1, 4), np.float32))
            srv.warmup()                    # ladder warm -> ready
            assert hp.is_ready()
            srv.start()
            out = srv.predict(np.ones((2, 4), np.float32))
            assert out.shape == (2, 3)
        finally:
            srv.shutdown()
    finally:
        hp.reset()


def test_shed_unready_sees_other_components_too():
    """The gate mirrors /readyz: ANY warming component (a TrainStep
    mid-compile, a DataPipeline before first batch) sheds serving
    traffic, not just the server's own warmup."""
    from mxnet_tpu.serving import ServiceUnavailableError
    from mxnet_tpu.telemetry import healthplane as hp

    hp.reset()
    try:
        srv = _server(warmup=True, start=True, shed_unready=True)
        try:
            ghost = hp.unique_component("train_step")   # still warming
            with pytest.raises(ServiceUnavailableError):
                srv.submit(np.ones((1, 4), np.float32))
            hp.set_ready(ghost)
            assert srv.predict(
                np.ones((1, 4), np.float32)).shape == (1, 3)
        finally:
            srv.shutdown()
    finally:
        hp.reset()


def test_default_admission_ignores_readiness():
    """shed_unready defaults OFF: existing deployments queue through
    warmup exactly as before."""
    from mxnet_tpu.telemetry import healthplane as hp

    hp.reset()
    try:
        srv = _server(warmup=False, start=True)
        try:
            out = srv.predict(np.ones((1, 4), np.float32))
            assert out.shape == (1, 3)
        finally:
            srv.shutdown()
    finally:
        hp.reset()
