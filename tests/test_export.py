"""Graph export round-trip (reference: gluon/block.py:export :1008 +
SymbolBlock.imports :1032, tests/python/unittest/test_gluon.py export
tests) and StableHLO deployment artifacts (TPU-native analogue of the
reference's C predict API deployment path)."""
import json
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon


def _mlp():
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(16, activation="relu"),
            gluon.nn.Dense(8, activation="relu"),
            gluon.nn.Dense(4))
    return net


def _convnet():
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Conv2D(8, 3, padding=1),
            gluon.nn.BatchNorm(),
            gluon.nn.Activation("relu"),
            gluon.nn.MaxPool2D(2),
            gluon.nn.Flatten(),
            gluon.nn.Dense(5))
    return net


def test_export_imports_roundtrip_mlp(tmp_path):
    net = _mlp()
    net.initialize()
    x = mx.nd.array(np.random.RandomState(0).rand(3, 12)
                    .astype(np.float32))
    want = net(x).asnumpy()

    prefix = str(tmp_path / "mlp")
    sym_file, params_file = net.export(prefix, epoch=3)
    assert sym_file.endswith("mlp-symbol.json")
    assert params_file.endswith("mlp-0003.params")
    assert os.path.exists(sym_file) and os.path.exists(params_file)

    # the json is a real symbol graph, not a blob
    graph = json.loads(open(sym_file).read())
    assert "nodes" in graph and any(
        n.get("op", "null") != "null" for n in graph["nodes"])

    reloaded = gluon.SymbolBlock.imports(sym_file, ["data"], params_file)
    got = reloaded(x).asnumpy()
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_export_imports_roundtrip_convnet_with_aux(tmp_path):
    """BatchNorm running stats ride the aux: section and must restore."""
    net = _convnet()
    net.initialize()
    rng = np.random.RandomState(1)
    # a few training steps so running stats are non-trivial
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1})
    for _ in range(3):
        xb = mx.nd.array(rng.rand(4, 2, 8, 8).astype(np.float32))
        with autograd.record():
            loss = (net(xb) ** 2).mean()
        loss.backward()
        trainer.step(4)

    x = mx.nd.array(rng.rand(2, 2, 8, 8).astype(np.float32))
    with autograd.pause(train_mode=False):
        want = net(x).asnumpy()

    prefix = str(tmp_path / "cnn")
    sym_file, params_file = net.export(prefix)
    saved = mx.nd.load(params_file)
    assert any(k.startswith("aux:") for k in saved), \
        "BatchNorm running stats missing from aux: section"
    assert any(k.startswith("arg:") for k in saved)

    reloaded = gluon.SymbolBlock.imports(sym_file, ["data"], params_file)
    with autograd.pause(train_mode=False):
        got = reloaded(x).asnumpy()
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


def test_symbolblock_reload_sees_param_updates(tmp_path):
    net = _mlp()
    net.initialize()
    x = mx.nd.array(np.random.RandomState(2).rand(2, 12)
                    .astype(np.float32))
    net(x)
    prefix = str(tmp_path / "m")
    sym_file, params_file = net.export(prefix)
    blk = gluon.SymbolBlock.imports(sym_file, ["data"], params_file)
    out1 = blk(x).asnumpy()
    # mutate a parameter; the cached executor must see the new value
    name, p = next(iter(blk.collect_params().items()))
    p.set_data(p.data() * 0.0)
    out2 = blk(x).asnumpy()
    assert not np.allclose(out1, out2)


def test_export_stablehlo_standalone(tmp_path):
    """The .stablehlo artifact runs through plain jax.export with no
    mxnet_tpu involvement — weights embedded."""
    net = _mlp()
    net.initialize()
    x = np.random.RandomState(3).rand(2, 12).astype(np.float32)
    want = net(mx.nd.array(x)).asnumpy()

    fname = net.export_stablehlo(str(tmp_path / "mlp"), x)
    assert fname.endswith(".stablehlo") and os.path.exists(fname)

    # deployment side: plain jax only
    import jax
    from jax import export as jexport

    blob = open(fname, "rb").read()
    loaded = jexport.deserialize(blob)
    got = np.asarray(loaded.call(x))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_export_multi_input(tmp_path):
    class TwoIn(gluon.HybridBlock):
        def __init__(self):
            super().__init__()
            self.d = gluon.nn.Dense(4)
            self.register_child(self.d)

        def hybrid_forward(self, F, a, b):
            return self.d(a) + self.d(b)

    net = TwoIn()
    net.initialize()
    a = mx.nd.array(np.random.RandomState(4).rand(2, 6).astype(np.float32))
    b = mx.nd.array(np.random.RandomState(5).rand(2, 6).astype(np.float32))
    want = net(a, b).asnumpy()
    prefix = str(tmp_path / "two")
    sym_file, params_file = net.export(prefix)
    blk = gluon.SymbolBlock.imports(sym_file, ["data0", "data1"],
                                    params_file)
    got = blk(a, b).asnumpy()
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_export_frozen_params_stay_args(tmp_path):
    """grad_req='null' freezing must NOT reclassify weights as aux —
    only true auxiliary states (BatchNorm moving stats) ride aux:."""
    net = _convnet()
    net.initialize()
    x = mx.nd.array(np.random.RandomState(6).rand(1, 2, 8, 8)
                    .astype(np.float32))
    net(x)
    for p in net.collect_params().values():
        p._grad_req = "null"                # freeze everything
    sym_file, params_file = net.export(str(tmp_path / "fz"))
    saved = mx.nd.load(params_file)
    aux = {k for k in saved if k.startswith("aux:")}
    arg = {k for k in saved if k.startswith("arg:")}
    assert all("running_" in k for k in aux), aux
    assert any("weight" in k for k in arg)
    assert not any("weight" in k for k in aux)


def test_symbolblock_is_trainable(tmp_path):
    """Imported models fine-tune: gradients flow and loss drops
    (reference SymbolBlock trains like any Block)."""
    net = _mlp()
    net.initialize()
    rng = np.random.RandomState(7)
    X = rng.rand(32, 12).astype(np.float32)
    y = (X.sum(axis=1) > 6).astype(np.float32)
    xnd = mx.nd.array(X)
    net(xnd)
    sym_file, params_file = net.export(str(tmp_path / "t"))

    blk = gluon.SymbolBlock.imports(sym_file, ["data"], params_file)
    # output dim 4 -> binary via first two logits
    trainer = gluon.Trainer(blk.collect_params(), "adam",
                            {"learning_rate": 0.05})
    ce = gluon.loss.SoftmaxCrossEntropyLoss()
    ynd = mx.nd.array(y)
    first = last = None
    for _ in range(25):
        with autograd.record():
            out = blk(xnd)
            loss = ce(out.slice_axis(axis=1, begin=0, end=2), ynd).mean()
        loss.backward()
        trainer.step(32)
        last = float(loss.asnumpy().ravel()[0])
        if first is None:
            first = last
    assert last < first * 0.7, "SymbolBlock loss %.4f -> %.4f" % (first, last)
    # gradients actually reached the imported parameters
    gsum = sum(float(mx.nd.abs(p.grad()).sum().asnumpy())
               for p in blk.collect_params().values()
               if p.grad_req != "null")
    assert gsum > 0


def test_symbolblock_trains_batchnorm_aux(tmp_path):
    """Fine-tuning through an imported BatchNorm updates moving stats."""
    net = _convnet()
    net.initialize()
    x = mx.nd.array(np.random.RandomState(8).rand(4, 2, 8, 8)
                    .astype(np.float32))
    net(x)
    sym_file, params_file = net.export(str(tmp_path / "bn"))
    blk = gluon.SymbolBlock.imports(sym_file, ["data"], params_file)
    aux_before = {n: p.data().asnumpy().copy()
                  for n, p in blk.collect_params().items()
                  if "running" in n}
    assert aux_before
    trainer = gluon.Trainer(blk.collect_params(), "sgd",
                            {"learning_rate": 0.1})
    with autograd.record():
        loss = (blk(x) ** 2).mean()
    loss.backward()
    trainer.step(4)
    changed = any(
        not np.allclose(aux_before[n], p.data().asnumpy())
        for n, p in blk.collect_params().items() if n in aux_before)
    assert changed, "BatchNorm moving stats never updated during training"


def test_symbolblock_does_not_corrupt_caller_inputs(tmp_path):
    """The cached executor must not bind the caller's array: feeding a
    second input must leave the first untouched."""
    net = _mlp()
    net.initialize()
    x0 = mx.nd.array(np.random.RandomState(9).rand(1, 12)
                     .astype(np.float32))
    net(x0)
    sym_file, params_file = net.export(str(tmp_path / "c"))
    blk = gluon.SymbolBlock.imports(sym_file, ["data"], params_file)
    x1 = mx.nd.ones((1, 12))
    x2 = mx.nd.ones((1, 12)) * 5
    keep = x1.asnumpy().copy()
    blk(x1)
    blk(x2)
    np.testing.assert_allclose(x1.asnumpy(), keep)


def test_imports_missing_params_fail_fast(tmp_path):
    net = _mlp()
    net.initialize()
    x = mx.nd.array(np.random.RandomState(10).rand(1, 12)
                    .astype(np.float32))
    net(x)
    sym_file, params_file = net.export(str(tmp_path / "mf"))
    trunc = {k: v for i, (k, v) in
             enumerate(mx.nd.load(params_file).items()) if i != 0}
    mx.nd.save(params_file, trunc)
    import pytest

    with pytest.raises(ValueError, match="missing graph parameters"):
        gluon.SymbolBlock.imports(sym_file, ["data"], params_file)


def test_shared_var_not_reclassified_by_aux_slot():
    """Passing a var into a BatchNorm aux slot must not flip it to aux
    in OTHER graphs sharing the same var."""
    rm = mx.sym.var("rm")
    g1 = rm * 2.0
    assert "rm" in g1.list_arguments()
    x = mx.sym.var("x")
    gamma = mx.sym.var("g")
    beta = mx.sym.var("b")
    rv = mx.sym.var("rv")
    bn = mx.sym.BatchNorm(x, gamma, beta, rm, rv)
    assert "rm" in bn.list_auxiliary_states()
    # original graph unchanged
    assert "rm" in g1.list_arguments()


def test_stablehlo_to_savedmodel_resnet_parity():
    """Framework-neutral interchange (the ONNX-decision recipe, VERDICT
    r4 #10): export_stablehlo on a resnet -> SavedModel via
    tools/stablehlo_to_savedmodel.py -> served by PLAIN TensorFlow (no
    jax/mxnet on the serving side of the API) with inference parity."""
    import tempfile

    tf = pytest.importorskip("tensorflow")
    import sys

    from mxnet_tpu.gluon.model_zoo import vision

    net = vision.resnet18_v1(classes=10, thumbnail=True)
    net.initialize()
    x = np.random.RandomState(0).rand(2, 3, 32, 32).astype(np.float32)
    with mx.autograd.pause():
        want = net(mx.nd.array(x)).asnumpy()

    tools_dir = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools")
    sys.path.insert(0, tools_dir)
    try:
        from stablehlo_to_savedmodel import convert
    finally:
        sys.path.remove(tools_dir)

    with tempfile.TemporaryDirectory() as td:
        art = net.export_stablehlo(os.path.join(td, "r18"), x)
        sm_dir = os.path.join(td, "sm")
        convert(art, sm_dir)
        served = tf.saved_model.load(sm_dir)
        got = np.asarray(served.f(tf.constant(x)))
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)
