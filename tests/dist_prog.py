"""Worker program for the distributed kvstore tests.

Launched by tools/launch.py (local mode) with scheduler/server siblings —
the reference's pattern from tests/nightly/dist_sync_kvstore.py run via
`tools/launch.py --launcher local`. Server/scheduler processes block
inside `import mxnet_tpu` (kvstore_server bootstrap) and never reach
main(). Workers run numerical push/pull equality checks and exit 0 on
success; the pytest wrapper asserts every worker's exit code.
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

# Worker processes of the test harness stay off the (single, shared) TPU
# chip: the JAX_PLATFORMS env var can be overridden by site hooks, so pin
# through the config API before any backend initializes.
import jax

jax.config.update("jax_platforms", "cpu")

import mxnet_tpu as mx  # noqa: E402  (server roles exit inside this import)

SHAPE = (3, 3)
BIG_SHAPE = (100, 120)          # 12000 elems > bound set by the test -> sharded
RSP_SHAPE = (40, 5)
RATE = 0.3


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def check(actual, expected, what):
    if not np.allclose(actual, expected, rtol=1e-5, atol=1e-6):
        raise AssertionError("%s mismatch:\n%r\nvs expected\n%r"
                             % (what, actual, expected))


def run_sync(kv):
    nw = kv.num_workers
    my = kv.rank + 1
    total = nw * (nw + 1) // 2

    log("rank", kv.rank, "init start")
    kv.init("3", mx.nd.zeros(SHAPE))
    log("rank", kv.rank, "init 3 done")
    kv.init("99", mx.nd.zeros(BIG_SHAPE))
    log("rank", kv.rank, "init 99 done")

    # Phase 1 — no optimizer: server assigns the aggregated sum.
    kv.push("3", mx.nd.ones(SHAPE) * my)
    kv.push("99", mx.nd.ones(BIG_SHAPE) * my)
    out, big = mx.nd.zeros(SHAPE), mx.nd.zeros(BIG_SHAPE)
    kv.pull("3", out=out)
    kv.pull("99", out=big)
    check(out.asnumpy(), np.full(SHAPE, total), "sync assign small")
    check(big.asnumpy(), np.full(BIG_SHAPE, total), "sync assign big/sharded")

    # Phase 2 — Test optimizer on server: stored += rate * aggregate.
    kv.set_optimizer(mx.optimizer.create("test", rescale_grad=RATE))
    nrepeat = 3
    for _ in range(nrepeat):
        kv.push("3", mx.nd.ones(SHAPE) * my)
        kv.push("99", mx.nd.ones(BIG_SHAPE) * my)
    kv.pull("3", out=out)
    kv.pull("99", out=big)
    expected = total + nrepeat * RATE * total
    check(out.asnumpy(), np.full(SHAPE, expected), "sync optimizer small")
    check(big.asnumpy(), np.full(BIG_SHAPE, expected), "sync optimizer big")

    # Phase 3 — multi-device push: values on several local ctxs merge
    # before crossing to the server (XLA-side reduce).
    ndev = 2
    devvals = [mx.nd.ones(SHAPE, ctx=mx.cpu(d)) * my for d in range(ndev)]
    kv.push("3", devvals)
    kv.pull("3", out=out)
    expected += RATE * total * ndev
    check(out.asnumpy(), np.full(SHAPE, expected), "sync multi-device push")

    # Phase 4 — row_sparse push/pull of selected rows only.
    # A key's storage type is fixed by its init value (reference: server
    # stores what rank 0 pushes); row_sparse weights init row_sparse.
    kv.init("rsp", mx.nd.zeros(RSP_SHAPE).tostype("row_sparse"))
    rows = np.array([1, 5, 7], dtype=np.int64)
    grad = mx.nd.sparse.row_sparse_array(
        (np.full((len(rows), RSP_SHAPE[1]), float(my), dtype=np.float32),
         rows), shape=RSP_SHAPE)
    kv.push("rsp", grad)
    pull_rows = mx.nd.array(np.array([0, 1, 5], dtype=np.int64), dtype="int64")
    out_r = mx.nd.zeros((3, RSP_SHAPE[1]))
    kv.row_sparse_pull("rsp", out=out_r, row_ids=pull_rows)
    dense_expected = np.zeros(RSP_SHAPE, dtype=np.float32)
    dense_expected[rows] = RATE * total
    check(out_r.asnumpy(), dense_expected[np.array([0, 1, 5])],
          "row_sparse_pull rows")

    # Phase 4b — row_sparse key BIGGER than the bigarray bound: must stay
    # whole on one server (never flat-sharded), and still push/pull rows.
    big_rsp = (900, 5)          # 4500 elems > bound 4000
    kv.init("rsp_big", mx.nd.zeros(big_rsp).tostype("row_sparse"))
    rows_b = np.array([3, 870], dtype=np.int64)
    kv.push("rsp_big", mx.nd.sparse.row_sparse_array(
        (np.full((2, 5), float(my), dtype=np.float32), rows_b),
        shape=big_rsp))
    out_b = mx.nd.zeros((2, 5))
    kv.row_sparse_pull("rsp_big", out=out_b,
                       row_ids=mx.nd.array(rows_b, dtype="int64"))
    check(out_b.asnumpy(), np.full((2, 5), RATE * total), "big rsp rows")

    # Phase 5 — 2-bit gradient compression, lossless case (|v| == threshold
    # quantizes exactly, so expected value is closed-form).
    kv.init("comp", mx.nd.zeros(SHAPE))
    kv.set_gradient_compression({"type": "2bit", "threshold": 1.0})
    kv.push("comp", mx.nd.ones(SHAPE))
    cout = mx.nd.zeros(SHAPE)
    kv.pull("comp", out=cout)
    check(cout.asnumpy(), np.full(SHAPE, RATE * nw), "2bit compressed push")

    # Optimizer state checkpoint round-trip (state lives on servers).
    if kv.rank == 0:
        kv.save_optimizer_states("/tmp/dist_opt_states_%d.bin" % os.getpid())
    kv._barrier()


def run_async(kv):
    my = kv.rank + 1
    nw = kv.num_workers
    total = nw * (nw + 1) // 2
    kv.init("a", mx.nd.zeros(SHAPE))
    kv.set_optimizer(mx.optimizer.create("test", rescale_grad=1.0))
    nrepeat = 4
    for _ in range(nrepeat):
        kv.push("a", mx.nd.ones(SHAPE) * my)
    # Pushes are acked after the server applied them (async mode), so after
    # the barrier every worker's updates have landed.
    kv._barrier()
    out = mx.nd.zeros(SHAPE)
    kv.pull("a", out=out)
    check(out.asnumpy(), np.full(SHAPE, nrepeat * total), "async updates")


def run_train(kv):
    """End-to-end data-parallel training across worker processes with the
    optimizer on the servers (reference tests/nightly/dist_lenet.py /
    dist_sync_kvstore training pattern): every worker trains on its own
    shard, weights stay identical because each step pulls the same
    server-updated values."""
    from mxnet_tpu import gluon, autograd

    mx.random.seed(7)
    net = gluon.nn.Sequential()
    net.add(gluon.nn.Dense(16, activation="relu"), gluon.nn.Dense(2))
    net.initialize()
    # Materialize params with one forward so the Trainer can init the kv.
    with autograd.pause():
        net(mx.nd.zeros((2, 8)))
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.05}, kvstore=kv)
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    rng = np.random.RandomState(42 + kv.rank)          # per-worker shard
    w_true = np.arange(8).astype(np.float32) - 3.5
    X = rng.randn(64, 8).astype(np.float32)
    y = (X @ w_true > 0).astype(np.float32)
    first = last = None
    for _ in range(8):
        with autograd.record():
            out = net(mx.nd.array(X))
            loss = loss_fn(out, mx.nd.array(y))
        loss.backward()
        trainer.step(X.shape[0])
        last = float(loss.mean().asnumpy())
        if first is None:
            first = last
    assert trainer._update_on_kvstore, "dist trainer must update on kvstore"
    assert last < first, "loss did not decrease: %.4f -> %.4f" % (first, last)
    # Cross-worker weight equality: every worker writes a checksum file;
    # after a barrier rank 0 compares them.
    tag = os.environ["DMLC_PS_ROOT_PORT"]
    sums = np.concatenate([p.data().asnumpy().reshape(-1)
                           for p in net.collect_params().values()])
    np.save("/tmp/dist_train_%s_r%d.npy" % (tag, kv.rank), sums)
    kv._barrier()
    if kv.rank == 0:
        ref = np.load("/tmp/dist_train_%s_r0.npy" % tag)
        for r in range(1, kv.num_workers):
            other = np.load("/tmp/dist_train_%s_r%d.npy" % (tag, r))
            check(other, ref, "cross-worker weights rank %d" % r)
    kv._barrier()


def run_failure(kv):
    """Failure detection (reference tests: ps-lite heartbeat ->
    GetDeadNodes): rank 1 dies without finalizing; rank 0 observes it via
    get_dead_nodes and gets a loud error (not a hang) from the next
    barrier."""
    import time

    kv.init("f", mx.nd.zeros((2,)))
    if kv.rank == 1:
        os._exit(0)          # simulated crash: no finalize, no atexit
    deadline = time.time() + 60
    dead = []
    while time.time() < deadline:
        dead = kv.get_dead_nodes(timeout=30)
        if 1 in dead:
            break
        time.sleep(0.5)
    assert 1 in dead, "dead worker not detected: %r" % (dead,)
    try:
        kv._barrier()
    except RuntimeError:
        pass                  # loud failure, not a silent hang
    else:
        raise AssertionError("barrier succeeded despite a dead worker")


def run_server_restart(kv):
    """Phase 1: train a few steps. Then signal, wait for the harness to
    kill+restart the server, and verify the restored state continues
    training (reference: server-side is_recovery, kvstore_dist.h:52-55).
    Coordinated via marker files in MXNET_TEST_MARKER_DIR."""
    import time

    marker_dir = os.environ["MXNET_TEST_MARKER_DIR"]
    kv.set_optimizer(mx.optimizer.SGD(learning_rate=0.5))
    w = mx.nd.ones((4,))
    kv.init("w", w)
    out = mx.nd.zeros((4,))
    for _ in range(3):
        kv.push("w", mx.nd.ones((4,)))       # grad = 1
        kv.pull("w", out=out)
    before = out.asnumpy().copy()
    check(before, np.full(4, 1.0 - 0.5 * 3), "pre-restart value")

    open(os.path.join(marker_dir, "phase1_done"), "w").close()
    deadline = time.time() + 120
    while not os.path.exists(os.path.join(marker_dir, "server_restarted")):
        assert time.time() < deadline, "harness never restarted the server"
        time.sleep(0.2)

    # Restored state must be exactly the pre-kill value...
    kv.pull("w", out=out)
    check(out.asnumpy(), before, "restored value after server restart")
    # ...and training continues through the recovered server.
    for _ in range(2):
        kv.push("w", mx.nd.ones((4,)))
        kv.pull("w", out=out)
    check(out.asnumpy(), before - 0.5 * 2, "post-restart training")
    log("server restart recovery ok")


def run_server_profiling(kv):
    """Remote server profiling (reference
    tests/nightly/test_server_profiling.py): rank 0 switches the
    SERVERS' profiler on through the kvstore command channel, pushes
    work so the server-side optimizer records op spans, then retrieves
    each server's aggregate table over the wire."""
    from mxnet_tpu import profiler

    import shutil
    import tempfile

    kv.init("p", mx.nd.zeros(SHAPE))
    kv.set_optimizer(mx.optimizer.create("sgd", learning_rate=0.1))
    profiler.set_kvstore_handle(kv)
    trace_dir = None
    if kv.rank == 0:
        trace_dir = tempfile.mkdtemp(prefix="server_profile_")
        profiler.set_config(profile_process="server",
                            filename=trace_dir)
        profiler.set_state("run", profile_process="server")
    kv._barrier()
    for _ in range(3):
        kv.push("p", mx.nd.ones(SHAPE))
        out = mx.nd.zeros(SHAPE)
        kv.pull("p", out=out)
    kv._barrier()
    if kv.rank == 0:
        profiler.set_state("stop", profile_process="server")
        tables = profiler.server_dumps()
        assert tables and all(isinstance(t, str) for t in tables), tables
        # the server's optimizer math dispatched through the profiled
        # path: at least one server recorded sgd update spans
        assert any("sgd" in t for t in tables), tables[0][-500:]
        log("server profiling spans ok (%d servers)" % len(tables))
        shutil.rmtree(trace_dir, ignore_errors=True)
    kv._barrier()


def run_overlap(kv, compressed=False):
    """Overlapped fused step over a REAL 2-process dist store: each
    rank's bucketed gradients reduce through the parameter servers
    while earlier buckets' fused applies are already dispatching
    (trainer comm thread + async pull handles). Asserts:

    - every rank ends with IDENTICAL weights (the data-parallel
      contract), and without compression they match a single-process
      serial reference fed the summed gradients (the overlap changed
      nothing numerically);
    - with compression, the 2bit/1bit codec rides the bucketed flat
      path: worker-side error-feedback residuals key by the (stable)
      bucket shard subkeys and SURVIVE across steps.
    """
    from mxnet_tpu import gluon

    gc_type = os.environ.get("MXNET_TEST_GC_TYPE", "2bit")
    n, shape = 96, (4096,)          # ~1.5MB of grads -> 2 buckets at 1MB
    os.environ["MXNET_FUSED_OVERLAP_DEPTH"] = "2"
    os.environ["MXNET_FUSED_BUCKET_MB"] = "1"

    def make_params(tag):
        rng = np.random.RandomState(5)
        out = []
        for k in range(n):
            p = gluon.Parameter("ovd_%s_%d" % (tag, k), shape=shape)
            p.initialize(init=mx.init.Constant(0.0))
            p.set_data(mx.nd.array(rng.randn(*shape).astype(np.float32)))
            out.append(p)
        return out

    params = make_params("w")
    trainer = gluon.Trainer(
        params, "sgd", {"learning_rate": 0.05, "momentum": 0.9},
        kvstore=kv, update_on_kvstore=False,
        compression_params={"type": gc_type, "threshold": 0.05}
        if compressed else None)
    # Both ranks know both gradient streams (seeded per rank), so each
    # can compute the serial single-process reference locally.
    streams = [np.random.RandomState(100 + r)
               for r in range(kv.num_workers)]
    steps = 4
    grad_log = []
    for _ in range(steps):
        grads = [s.randn(n, *shape).astype(np.float32) for s in streams]
        grad_log.append(grads)
        for k, p in enumerate(params):
            p.grad()[:] = mx.nd.array(grads[kv.rank][k])
        trainer.step(2)
    assert not trainer._update_on_kvstore
    if compressed:
        res = kv._compression._residual
        bucket_keys = [k for k in res
                       if "__fused_grad_bucket" in str(k)]
        assert bucket_keys, "no per-bucket residuals: %r" % list(res)
        sig = {k: res[k].sum() for k in bucket_keys}
        # one more step: same keys, residuals still evolving in place
        for k, p in enumerate(params):
            p.grad()[:] = mx.nd.array(
                streams[kv.rank].randn(*shape).astype(np.float32))
        trainer.step(2)
        assert set(res) >= set(bucket_keys), "residual keys churned"
        assert any(res[k].sum() != sig[k] for k in bucket_keys), \
            "residuals never updated"
        log("rank", kv.rank, "compression residuals per bucket ok",
            len(bucket_keys))
    else:
        # Serial single-process reference over the summed grads.
        ref = make_params("ref")
        rtr = gluon.Trainer(ref, "sgd",
                            {"learning_rate": 0.05, "momentum": 0.9},
                            fused=False)
        for s in range(steps):
            for k, p in enumerate(ref):
                p.grad()[:] = mx.nd.array(
                    sum(grads[k] for grads in grad_log[s]))
            rtr.step(2)
        for p, q in zip(params, ref):
            check(p.data().asnumpy(), q.data().asnumpy(),
                  "overlapped dist vs serial reference")
        log("rank", kv.rank, "overlap matches serial reference")
    # Cross-worker weight equality through checksum files.
    tag = os.environ["DMLC_PS_ROOT_PORT"]
    sums = np.concatenate([p.data().asnumpy().reshape(-1)
                           for p in params])
    np.save("/tmp/dist_overlap_%s_r%d.npy" % (tag, kv.rank), sums)
    kv._barrier()
    if kv.rank == 0:
        ref0 = np.load("/tmp/dist_overlap_%s_r0.npy" % tag)
        for r in range(1, kv.num_workers):
            other = np.load("/tmp/dist_overlap_%s_r%d.npy" % (tag, r))
            check(other, ref0, "cross-worker weights rank %d" % r)
    kv._barrier()


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--kv-type", default="dist_sync")
    parser.add_argument("--mode", default="kvstore",
                        choices=["kvstore", "train", "failure",
                                 "server_restart", "server_profiling",
                                 "overlap", "overlap_compressed"])
    args = parser.parse_args()
    print("creating kv", file=sys.stderr, flush=True)
    kv = mx.kv.create(args.kv_type)
    print("kv created rank", kv.rank, file=sys.stderr, flush=True)
    assert kv.num_workers == int(os.environ["DMLC_NUM_WORKER"])
    assert 0 <= kv.rank < kv.num_workers
    if args.mode == "failure":
        run_failure(kv)
    elif args.mode == "server_restart":
        run_server_restart(kv)
    elif args.mode == "train":
        run_train(kv)
    elif args.mode == "server_profiling":
        run_server_profiling(kv)
    elif args.mode == "overlap":
        run_overlap(kv)
    elif args.mode == "overlap_compressed":
        run_overlap(kv, compressed=True)
    elif args.kv_type == "dist_async":
        run_async(kv)
    else:
        run_sync(kv)
    kv.close()


if __name__ == "__main__":
    main()
