"""gluon.data tests (reference: tests/python/unittest/test_gluon_data.py)."""
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon, recordio
from mxnet_tpu.gluon.data import (ArrayDataset, BatchSampler, DataLoader,
                                  RandomSampler, RecordFileDataset,
                                  SequentialSampler, SimpleDataset)
from mxnet_tpu.gluon.data.vision import (CIFAR10, MNIST, ImageRecordDataset,
                                         transforms)


def test_array_dataset_and_transform():
    X = np.random.rand(10, 3).astype(np.float32)
    Y = np.arange(10)
    ds = ArrayDataset(X, Y)
    assert len(ds) == 10
    x, y = ds[3]
    assert np.allclose(x, X[3]) and y == 3
    ds2 = ds.transform_first(lambda x: x * 2)
    x2, y2 = ds2[3]
    assert np.allclose(x2, X[3] * 2) and y2 == 3


def test_samplers():
    assert list(SequentialSampler(4)) == [0, 1, 2, 3]
    assert sorted(RandomSampler(10)) == list(range(10))
    bs = BatchSampler(SequentialSampler(10), 3, "keep")
    assert [len(b) for b in bs] == [3, 3, 3, 1]
    assert len(bs) == 4
    bs = BatchSampler(SequentialSampler(10), 3, "discard")
    assert [len(b) for b in bs] == [3, 3, 3]
    bs = BatchSampler(SequentialSampler(10), 3, "rollover")
    assert [len(b) for b in bs] == [3, 3, 3]
    assert [len(b) for b in bs] == [3, 3, 3]  # rolled-over 1 + 10 = 11 -> 3


def test_dataloader_basic():
    X = np.random.rand(25, 4).astype(np.float32)
    Y = np.arange(25).astype(np.int32)
    dl = DataLoader(ArrayDataset(X, Y), batch_size=10)
    batches = list(dl)
    assert [b[0].shape[0] for b in batches] == [10, 10, 5]
    # order preserved without shuffle
    np.testing.assert_allclose(batches[0][1].asnumpy(), np.arange(10))
    got = np.concatenate([b[1].asnumpy() for b in
                          DataLoader(ArrayDataset(X, Y), batch_size=10,
                                     shuffle=True)])
    assert sorted(got.tolist()) == list(range(25))


def test_dataloader_workers_and_crash():
    X = np.random.rand(30, 4).astype(np.float32)
    Y = np.arange(30).astype(np.int32)
    dl = DataLoader(ArrayDataset(X, Y), batch_size=8, num_workers=2)
    for _ in range(2):  # two epochs over the same pool
        got = np.concatenate([b[1].asnumpy() for b in dl])
        assert sorted(got.tolist()) == list(range(30))

    def boom(x):
        raise ValueError("intentional worker failure")

    bad = DataLoader(ArrayDataset(X, Y).transform_first(boom),
                     batch_size=8, num_workers=2)
    with pytest.raises(RuntimeError, match="intentional worker failure"):
        next(iter(bad))


def test_record_file_dataset(tmp_path):
    rec = str(tmp_path / "data.rec")
    idx = str(tmp_path / "data.idx")
    w = recordio.MXIndexedRecordIO(idx, rec, "w")
    for i in range(7):
        w.write_idx(i, b"payload-%d" % i)
    w.close()
    ds = RecordFileDataset(rec)
    assert len(ds) == 7
    assert ds[4] == b"payload-4"


def test_image_record_dataset_training(tmp_path):
    """End-to-end: synthetic images packed to .rec, read through
    ImageRecordDataset + transforms + DataLoader workers, conv net
    learns (VERDICT r1 item 4 'done' criterion)."""
    rec = str(tmp_path / "imgs.rec")
    idx = str(tmp_path / "imgs.idx")
    w = recordio.MXIndexedRecordIO(idx, rec, "w")
    rng = np.random.RandomState(0)
    # class 0 = dark images, class 1 = bright images
    for i in range(64):
        label = i % 2
        base = 40 if label == 0 else 200
        img = rng.randint(base - 30, base + 30,
                          size=(24, 24, 3)).astype(np.uint8)
        packed = recordio.pack_img(
            recordio.IRHeader(0, float(label), i, 0), img, quality=95)
        w.write_idx(i, packed)
    w.close()

    tfm = transforms.Compose([transforms.RandomFlipLeftRight(),
                              transforms.ToTensor()])
    ds = ImageRecordDataset(rec).transform_first(tfm)
    dl = DataLoader(ds, batch_size=16, shuffle=True, num_workers=2)

    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Conv2D(8, 3, activation="relu"),
            gluon.nn.GlobalAvgPool2D(), gluon.nn.Dense(2))
    net.initialize(mx.initializer.Xavier())
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 0.05})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    for epoch in range(4):
        for xb, yb in dl:
            with mx.autograd.record():
                loss = loss_fn(net(xb), yb).mean()
            loss.backward()
            trainer.step(1)
    correct = total = 0
    for xb, yb in dl:
        pred = net(xb).asnumpy().argmax(axis=1)
        correct += (pred == yb.asnumpy()).sum()
        total += len(pred)
    assert correct / total > 0.9, "rec->DataLoader training failed (%.2f)" \
        % (correct / total)


def test_mnist_dataset(tmp_path):
    """Synthetic idx-ubyte files exercise the real parser."""
    import gzip
    import struct

    root = str(tmp_path)
    images = np.random.randint(0, 255, size=(10, 28, 28),
                               dtype=np.uint8)
    labels = np.arange(10, dtype=np.uint8)
    with gzip.open(os.path.join(root, "train-images-idx3-ubyte.gz"),
                   "wb") as f:
        f.write(struct.pack(">IIII", 0x803, 10, 28, 28))
        f.write(images.tobytes())
    with gzip.open(os.path.join(root, "train-labels-idx1-ubyte.gz"),
                   "wb") as f:
        f.write(struct.pack(">II", 0x801, 10))
        f.write(labels.tobytes())
    ds = MNIST(root=root, train=True)
    assert len(ds) == 10
    img, label = ds[3]
    assert img.shape == (28, 28, 1) and label == 3
    np.testing.assert_array_equal(img[:, :, 0], images[3])


def test_cifar10_dataset(tmp_path):
    root = str(tmp_path)
    rng = np.random.RandomState(1)
    recs = []
    labels = []
    for i in range(8):
        labels.append(i % 10)
        img = rng.randint(0, 255, size=(3072,), dtype=np.uint8)
        recs.append(np.concatenate([[labels[-1]], img]).astype(np.uint8))
    blob = np.stack(recs).tobytes()
    for name in ["data_batch_%d.bin" % i for i in range(1, 6)]:
        with open(os.path.join(root, name), "wb") as f:
            f.write(blob)
    ds = CIFAR10(root=root, train=True)
    assert len(ds) == 40
    img, label = ds[0]
    assert img.shape == (32, 32, 3) and label == 0


def test_transforms_shapes():
    img = (np.random.rand(40, 30, 3) * 255).astype(np.uint8)
    t = transforms.ToTensor()(img)
    assert t.shape == (3, 40, 30) and t.max() <= 1.0
    n = transforms.Normalize([0.5] * 3, [0.25] * 3)(t)
    assert n.shape == (3, 40, 30)
    r = transforms.Resize(16)(img)
    assert r.shape == (16, 16, 3)
    rk = transforms.Resize(16, keep_ratio=True)(img)
    assert min(rk.shape[:2]) == 16
    c = transforms.CenterCrop(20)(img)
    assert c.shape == (20, 20, 3)
    rc = transforms.RandomResizedCrop(24)(img)
    assert rc.shape == (24, 24, 3)
    for t in (transforms.RandomBrightness(0.3),
              transforms.RandomContrast(0.3),
              transforms.RandomSaturation(0.3), transforms.RandomHue(0.1),
              transforms.RandomColorJitter(0.2, 0.2, 0.2, 0.1),
              transforms.RandomLighting(0.1)):
        out = t(img)
        assert out.shape == img.shape and out.dtype == np.uint8
