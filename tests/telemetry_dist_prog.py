"""Worker program for the 2-process pod-observability smoke test
(tests/test_telemetry_dist.py, launched via tools/launch.py roles).

Each rank: records its own metrics + trace spans, streams trace
segments to a shared directory, and pushes registry snapshots through
the dist kvstore's telemetry channel. Rank 0 merges the pod view and
writes ``scrape.txt`` (one exposition containing every rank's series)
and ``merged_trace.json`` (one Perfetto timeline with a lane per rank).

Modes:

* ``normal`` — both ranks run to completion; rank 0's outputs must show
  both ranks fresh.
* ``kill`` — rank 1 SIGKILLs itself mid-run (after at least one
  committed trace segment, with more spans buffered that never commit);
  rank 0 must mark rank 1 stale within one aggregation interval and
  still merge rank 1's committed segments.
"""
import os
import sys
import time

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _ROOT)
sys.path.insert(0, os.path.join(_ROOT, "tools"))

import mxnet_tpu as mx                                 # noqa: E402
from mxnet_tpu import telemetry                        # noqa: E402
from mxnet_tpu.telemetry import aggregate, trace       # noqa: E402
from mxnet_tpu.telemetry import metrics as tm          # noqa: E402


def main():
    out_dir = sys.argv[1]
    mode = sys.argv[2] if len(sys.argv) > 2 else "normal"
    kv = mx.kvstore.create("dist_sync")
    rank = kv.rank

    steps = tm.REGISTRY.counter("podtest_steps_total",
                                "per-rank step count", labels=("stage",))
    step_s = tm.REGISTRY.histogram("podtest_step_seconds",
                                   "per-rank step seconds")
    writer = telemetry.StreamingTraceWriter(
        out_dir, rank=rank, max_segment_age_s=0.0)  # commit every tick
    monitor = telemetry.StepMonitor(warn_interval_s=0.0)
    aggregator = aggregate.Aggregator(
        kv, interval_s=0.0, stale_after_s=30.0 if mode == "normal"
        else 1.0, monitor=monitor)

    for i in range(5):
        with trace.span("podtest::step", step=i, rank=rank):
            time.sleep(0.01)
        steps.labels(stage="train").inc()
        step_s.observe(0.01)
        aggregator.tick()
        writer.tick()

    if mode == "kill" and rank == 1:
        # Committed segments exist; buffer more spans that never commit
        # (the "mid-run" part), then die without any cleanup at all.
        with trace.span("podtest::never_committed"):
            pass
        os.kill(os.getpid(), 9)

    aggregator.step()               # final push
    writer.flush()

    if rank != 0:
        kv._barrier()
        return 0

    if mode == "kill":
        # Wait (bounded) for rank 1's silence to cross the staleness
        # bar — one aggregation interval after stale_after_s.
        deadline = time.time() + 60.0
        while time.time() < deadline:
            aggregator.step()
            if 'mx_rank_stale{rank="1"} 1' in aggregator.render_prometheus():
                break
            time.sleep(0.25)
    else:
        kv._barrier()               # peers' final pushes have landed
        aggregator.step()

    text = aggregator.render_prometheus()
    with open(os.path.join(out_dir, "scrape.txt"), "w") as f:
        f.write(text)

    import trace_merge

    trace_merge.merge([out_dir],
                      out=os.path.join(out_dir, "merged_trace.json"))
    anomalies = monitor.anomaly_counts if mode == "kill" else {}
    with open(os.path.join(out_dir, "rank0_done.txt"), "w") as f:
        f.write("rank_stale=%d\n" % anomalies.get("rank_stale", 0))
    return 0


if __name__ == "__main__":
    sys.exit(main())
