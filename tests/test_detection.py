"""Detection op family + SSD end-to-end (reference:
tests/python/unittest/test_contrib_operator.py multibox/box_nms cases;
north-star tracked config SSD-VGG16 — here a tiny SSD on synthetic
data, converging and detecting)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon


def test_multibox_prior_shapes_and_values():
    data = mx.nd.zeros((1, 3, 4, 4))
    anchors = mx.nd.contrib.MultiBoxPrior(data, sizes=(0.5, 0.25),
                                          ratios=(1.0, 2.0))
    # A = len(sizes) + len(ratios) - 1 = 3 per pixel
    assert anchors.shape == (1, 4 * 4 * 3, 4)
    a = anchors.asnumpy()[0]
    # first anchor at pixel (0,0): center (0.125, 0.125), size 0.5
    np.testing.assert_allclose(a[0], [0.125 - 0.25, 0.125 - 0.25,
                                      0.125 + 0.25, 0.125 + 0.25],
                               atol=1e-6)
    # ratio-2 anchor is wider than tall
    third = a[2]
    assert (third[2] - third[0]) > (third[3] - third[1])


def test_box_iou():
    a = mx.nd.array([[0, 0, 2, 2]], dtype="float32")
    b = mx.nd.array([[1, 1, 3, 3], [0, 0, 2, 2], [5, 5, 6, 6]],
                    dtype="float32")
    iou = mx.nd.contrib.box_iou(a, b).asnumpy()
    np.testing.assert_allclose(iou[0], [1 / 7, 1.0, 0.0], atol=1e-6)


def test_box_nms():
    # rows: [cls_id, score, x1, y1, x2, y2]
    boxes = mx.nd.array([
        [0, 0.9, 0.0, 0.0, 0.5, 0.5],
        [0, 0.8, 0.01, 0.01, 0.5, 0.5],   # overlaps #0 -> suppressed
        [0, 0.7, 0.6, 0.6, 0.9, 0.9],     # disjoint -> kept
        [1, 0.6, 0.0, 0.0, 0.5, 0.5],     # other class -> kept
    ], dtype="float32")
    out = mx.nd.contrib.box_nms(boxes, overlap_thresh=0.5, coord_start=2,
                                score_index=1, id_index=0).asnumpy()
    assert out[0][1] == pytest.approx(0.9)
    assert np.all(out[1] == -1)
    assert out[2][1] == pytest.approx(0.7)
    assert out[3][1] == pytest.approx(0.6)
    # force_suppress kills cross-class overlap too
    out2 = mx.nd.contrib.box_nms(boxes, overlap_thresh=0.5, coord_start=2,
                                 score_index=1, id_index=0,
                                 force_suppress=True).asnumpy()
    assert np.all(out2[3] == -1)


def test_bipartite_matching():
    dist = mx.nd.array([[0.5, 0.9], [0.1, 0.2], [0.0, 0.65]])
    row, col = mx.nd.contrib.bipartite_matching(dist, threshold=1e-12)
    r = row.asnumpy()
    # greedy: (0,1)=0.9 first, then (1,0)=0.1? no: next best among
    # remaining rows/cols is (2,0)=0.0 vs (1,0)=0.1 -> row1-col0
    assert r[0] == 1 and r[1] == 0 and r[2] == -1


def test_multibox_target_assigns():
    anchors = mx.nd.array(np.array(
        [[[0.0, 0.0, 0.5, 0.5], [0.5, 0.5, 1.0, 1.0],
          [0.0, 0.5, 0.5, 1.0]]], np.float32))
    # one gt box matching anchor 0 closely
    label = mx.nd.array(np.array(
        [[[1.0, 0.05, 0.05, 0.45, 0.45]]], np.float32))
    cls_pred = mx.nd.zeros((1, 3, 3))
    box_t, box_m, cls_t = mx.nd.contrib.MultiBoxTarget(
        anchors, label, cls_pred, overlap_threshold=0.5)
    ct = cls_t.asnumpy()[0]
    assert ct[0] == 2.0          # class 1 -> target 2 (bg=0 offset)
    assert ct[1] == 0.0 and ct[2] == 0.0
    bm = box_m.asnumpy()[0].reshape(3, 4)
    assert bm[0].all() and not bm[1].any()
    # encoded offsets decode back to the gt box
    bt = box_t.asnumpy()[0].reshape(3, 4)[0]
    acx, acy, aw, ah = 0.25, 0.25, 0.5, 0.5
    gcx = acx + bt[0] * 0.1 * aw
    gcy = acy + bt[1] * 0.1 * ah
    gw = aw * np.exp(bt[2] * 0.2)
    gh = ah * np.exp(bt[3] * 0.2)
    np.testing.assert_allclose([gcx - gw / 2, gcy - gh / 2,
                                gcx + gw / 2, gcy + gh / 2],
                               [0.05, 0.05, 0.45, 0.45], atol=1e-5)


def test_multibox_target_negative_mining():
    anchors = mx.nd.array(np.random.RandomState(0)
                          .rand(1, 20, 4).astype(np.float32))
    label = mx.nd.array(np.array([[[0.0, 0.1, 0.1, 0.4, 0.4]]], np.float32))
    cls_pred = mx.nd.array(np.random.RandomState(1)
                           .rand(1, 3, 20).astype(np.float32))
    _, _, cls_t = mx.nd.contrib.MultiBoxTarget(
        anchors, label, cls_pred, overlap_threshold=0.5,
        negative_mining_ratio=3.0, minimum_negative_samples=1)
    ct = cls_t.asnumpy()[0]
    n_pos = (ct > 0).sum()
    n_neg = (ct == 0).sum()
    n_ign = (ct == -1).sum()
    assert n_ign > 0 and n_neg <= max(3 * n_pos, 1)


def test_roi_pooling_and_align():
    data = mx.nd.array(np.arange(32, dtype=np.float32).reshape(1, 2, 4, 4))
    rois = mx.nd.array(np.array([[0, 0, 0, 3, 3]], np.float32))
    out = mx.nd.ROIPooling(data, rois, pooled_size=(2, 2),
                           spatial_scale=1.0)
    assert out.shape == (1, 2, 2, 2)
    # channel 0 is arange(16) over 4x4: max of each 2x2 quadrant
    np.testing.assert_allclose(out.asnumpy()[0, 0],
                               [[5, 7], [13, 15]])
    al = mx.nd.contrib.ROIAlign(data, rois, pooled_size=(2, 2),
                                spatial_scale=1.0, sample_ratio=2)
    assert al.shape == (1, 2, 2, 2)
    assert np.isfinite(al.asnumpy()).all()


def test_roi_pooling_gradient():
    x = mx.nd.array(np.random.RandomState(0)
                    .rand(1, 1, 6, 6).astype(np.float32))
    rois = mx.nd.array(np.array([[0, 0, 0, 5, 5]], np.float32))
    x.attach_grad()
    with autograd.record():
        out = mx.nd.ROIPooling(x, rois, pooled_size=(2, 2),
                               spatial_scale=1.0)
        loss = out.sum()
    loss.backward()
    g = x.grad.asnumpy()
    # max-pool gradient: exactly one 1 per output bin
    assert g.sum() == pytest.approx(4.0)


# ---------------------------------------------------------------------------
# tiny SSD end-to-end
# ---------------------------------------------------------------------------

class TinySSD(gluon.HybridBlock):
    """One-scale SSD head on a small conv trunk (the SSD-VGG16 recipe at
    toy size: trunk -> per-anchor class logits + box offsets)."""

    def __init__(self, num_classes=1, num_anchors=3, **kwargs):
        super().__init__(**kwargs)
        self.num_classes = num_classes
        self.num_anchors = num_anchors
        self.trunk = gluon.nn.HybridSequential()
        self.trunk.add(gluon.nn.Conv2D(16, 3, padding=1, activation="relu"),
                       gluon.nn.Conv2D(16, 3, padding=1, activation="relu"),
                       gluon.nn.MaxPool2D(2))
        self.register_child(self.trunk)
        self.cls_head = gluon.nn.Conv2D(num_anchors * (num_classes + 1), 3,
                                        padding=1)
        self.loc_head = gluon.nn.Conv2D(num_anchors * 4, 3, padding=1)
        self.register_child(self.cls_head)
        self.register_child(self.loc_head)

    def hybrid_forward(self, F, x):
        feat = self.trunk(x)
        cls = self.cls_head(feat)          # (B, A*(C+1), h, w)
        loc = self.loc_head(feat)          # (B, A*4, h, w)
        b = cls.shape[0]
        cls = cls.transpose((0, 2, 3, 1)).reshape(
            (b, -1, self.num_classes + 1))           # (B, hw*A, C+1)
        loc = loc.transpose((0, 2, 3, 1)).reshape((b, -1))
        anchors = F.contrib.MultiBoxPrior(
            feat, sizes=(0.4, 0.6), ratios=(1.0, 2.0))
        return anchors, cls, loc


def _make_ssd_data(n, rng):
    """Images with one bright square; label = its box, class 0."""
    X = (rng.rand(n, 1, 16, 16) * 0.2).astype(np.float32)
    labels = np.zeros((n, 1, 5), np.float32)
    for i in range(n):
        size = rng.randint(5, 9)
        r = rng.randint(0, 16 - size)
        c = rng.randint(0, 16 - size)
        X[i, 0, r:r + size, c:c + size] += 1.0
        labels[i, 0] = [0, c / 16, r / 16, (c + size) / 16, (r + size) / 16]
    return X, labels


def test_ssd_converges_and_detects():
    rng = np.random.RandomState(0)
    X, Y = _make_ssd_data(64, rng)
    net = TinySSD()
    net.initialize()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 0.005})
    ce = gluon.loss.SoftmaxCrossEntropyLoss()
    x = mx.nd.array(X)
    y = mx.nd.array(Y)
    first = last = None
    for it in range(60):
        with autograd.record():
            anchors, cls, loc = net(x)
            # targets computed outside the grad graph
            with autograd.pause():
                box_t, box_m, cls_t = mx.nd.contrib.MultiBoxTarget(
                    anchors, y, cls.transpose((0, 2, 1)),
                    overlap_threshold=0.5)
            cls_loss = ce(cls.reshape((-1, 2)), cls_t.reshape((-1,)))
            diff = (loc - box_t) * box_m
            adiff = diff.abs()
            loc_loss = mx.nd.where(
                adiff > 1.0, adiff - 0.5, 0.5 * adiff * adiff).mean()
            loss = cls_loss.mean() + loc_loss
        loss.backward()
        trainer.step(x.shape[0])
        last = float(loss.asnumpy())
        if first is None:
            first = last
    assert last < first * 0.5, "SSD loss %.4f -> %.4f" % (first, last)

    # detection: decoded top box overlaps ground truth
    anchors, cls, loc = net(x[:4])
    cls_prob = cls.softmax(axis=-1).transpose((0, 2, 1))
    det = mx.nd.contrib.MultiBoxDetection(cls_prob, loc, anchors,
                                          nms_threshold=0.45,
                                          threshold=0.01)
    det_np = det.asnumpy()
    hits = 0
    for i in range(4):
        rows = det_np[i]
        rows = rows[rows[:, 0] >= 0]
        assert len(rows), "no detections for sample %d" % i
        best = rows[np.argmax(rows[:, 1])]
        gt = Y[i, 0, 1:]
        x1, y1 = np.maximum(best[2:4], gt[:2])
        x2, y2 = np.minimum(best[4:6], gt[2:])
        inter = max(x2 - x1, 0) * max(y2 - y1, 0)
        area = ((best[4] - best[2]) * (best[5] - best[3])
                + (gt[2] - gt[0]) * (gt[3] - gt[1]) - inter)
        if inter / max(area, 1e-8) > 0.3:
            hits += 1
    assert hits >= 3, "only %d/4 detections overlap ground truth" % hits


def test_bipartite_matching_col_output():
    """col->row must keep real matches when other rows are unmatched
    (duplicate-scatter regression)."""
    dist = mx.nd.array([[0.9], [0.5]])
    row, col = mx.nd.contrib.bipartite_matching(dist, threshold=1e-12)
    assert row.asnumpy().tolist() == [0.0, -1.0]
    assert col.asnumpy().tolist() == [0.0]
    # topk caps greedy rounds
    dist2 = mx.nd.array(np.eye(4, dtype=np.float32))
    row2, _ = mx.nd.contrib.bipartite_matching(dist2, threshold=1e-12,
                                               topk=2)
    assert (row2.asnumpy() >= 0).sum() == 2


def test_box_nms_format_conversion():
    boxes = mx.nd.array([[0.9, 0.5, 0.5, 0.2, 0.2]])  # score, cx cy w h
    out = mx.nd.contrib.box_nms(boxes, coord_start=1, score_index=0,
                                in_format="center", out_format="corner")
    np.testing.assert_allclose(out.asnumpy()[0],
                               [0.9, 0.4, 0.4, 0.6, 0.6], atol=1e-6)
