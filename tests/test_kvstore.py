"""KVStore tests (reference: tests/python/unittest/test_kvstore.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx

SHAPE = (4, 4)


def test_single_kv_pair():
    kv = mx.kv.create("local")
    kv.init(3, mx.nd.ones(SHAPE))
    out = mx.nd.zeros(SHAPE)
    kv.pull(3, out=out)
    np.testing.assert_allclose(out.asnumpy(), 1.0)
    kv.push(3, mx.nd.ones(SHAPE) * 4)
    kv.pull(3, out=out)
    np.testing.assert_allclose(out.asnumpy(), 4.0)


def test_aggregate_push():
    kv = mx.kv.create("device")
    kv.init("a", mx.nd.zeros(SHAPE))
    devs = [mx.cpu(i) for i in range(4)]
    vals = [mx.nd.ones(SHAPE, ctx=d) for d in devs]
    kv.push("a", vals)
    out = mx.nd.zeros(SHAPE)
    kv.pull("a", out=out)
    np.testing.assert_allclose(out.asnumpy(), 4.0)


def test_list_kv_pairs():
    kv = mx.kv.create("local")
    keys = [5, 7, 9]
    kv.init(keys, [mx.nd.ones(SHAPE)] * 3)
    kv.push(keys, [mx.nd.ones(SHAPE) * 2] * 3)
    outs = [mx.nd.zeros(SHAPE) for _ in keys]
    kv.pull(keys, out=outs)
    for o in outs:
        np.testing.assert_allclose(o.asnumpy(), 2.0)


def test_updater():
    kv = mx.kv.create("local")
    kv.init("w", mx.nd.ones(SHAPE))

    def updater(key, recv, stored):
        stored += recv * 2

    kv.set_updater(updater)
    kv.push("w", mx.nd.ones(SHAPE))
    out = mx.nd.zeros(SHAPE)
    kv.pull("w", out=out)
    np.testing.assert_allclose(out.asnumpy(), 3.0)
    # aggregated push through updater
    kv.push("w", [mx.nd.ones(SHAPE), mx.nd.ones(SHAPE)])
    kv.pull("w", out=out)
    np.testing.assert_allclose(out.asnumpy(), 7.0)


def test_optimizer_on_kvstore():
    kv = mx.kv.create("local")
    kv.init(0, mx.nd.ones((2, 2)))
    kv.set_optimizer(mx.optimizer.SGD(learning_rate=0.1))
    kv.push(0, mx.nd.ones((2, 2)))
    out = mx.nd.zeros((2, 2))
    kv.pull(0, out=out)
    np.testing.assert_allclose(out.asnumpy(), 1.0 - 0.1, rtol=1e-5)


def test_row_sparse_pull():
    kv = mx.kv.create("local")
    w = np.arange(12).reshape(6, 2).astype(np.float32)
    kv.init("emb", mx.nd.array(w))
    out = mx.nd.zeros((6, 2))
    kv.row_sparse_pull("emb", out=out, row_ids=mx.nd.array([0, 2], dtype="int64"))
    # Only the requested rows are refreshed (reference PullRowSparse —
    # that is the bandwidth contract); others keep their values.
    expected = np.zeros_like(w)
    expected[[0, 2]] = w[[0, 2]]
    np.testing.assert_allclose(out.asnumpy(), expected)


def test_kvstore_types():
    assert mx.kv.create("local").type == "local"
    assert mx.kv.create("device").type == "device"
    assert mx.kv.create("nccl").type == "device"
    with pytest.raises(ValueError):
        mx.kv.create("bogus")


def test_trainer_multi_device_step():
    """Data-parallel trainer update across 4 virtual devices."""
    from mxnet_tpu import gluon

    net = gluon.nn.Dense(2, in_units=3)
    ctxs = [mx.cpu(i) for i in range(4)]
    net.initialize(ctx=ctxs)
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.5}, kvstore="device")
    data = [mx.nd.ones((2, 3), ctx=c) for c in ctxs]
    with mx.autograd.record():
        losses = []
        for x in data:
            out = net(x)
            losses.append((out * out).sum())
    for l in losses:
        l.backward()
    w_before = net.weight.data(ctxs[0]).asnumpy()
    trainer.step(batch_size=8)
    w_after = [net.weight.data(c).asnumpy() for c in ctxs]
    # all replicas identical after allreduce+update
    for w in w_after[1:]:
        np.testing.assert_allclose(w, w_after[0], rtol=1e-5)
    assert not np.allclose(w_before, w_after[0])
