"""Regression tests for review findings: initializer symmetry, per-mode
cached aux, CTC loss, Constant serialization."""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import gluon, initializer


def test_no_symmetric_init():
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(8, in_units=8))
    net.add(gluon.nn.Dense(8, in_units=8))
    net.initialize()
    w0 = net[0].weight.data().asnumpy()
    w1 = net[1].weight.data().asnumpy()
    assert not np.allclose(w0, w1)


def test_constant_initializer_roundtrip():
    init = initializer.Constant(3.5)
    arr = mx.nd.zeros((2, 2))
    init("test_weight", arr)
    np.testing.assert_allclose(arr.asnumpy(), 3.5)


def test_batchnorm_aux_after_mode_switch():
    """BatchNorm running stats must keep updating after alternating
    train/eval traces on a hybridized block."""
    bn = gluon.nn.BatchNorm(in_channels=4)
    bn.initialize()
    bn.hybridize()
    x = mx.nd.array(np.random.rand(2, 4, 3, 3).astype(np.float32) * 5)
    with mx.autograd.record():
        bn(x)
    rm1 = bn.running_mean.data().asnumpy().copy()
    bn(x)  # eval trace
    with mx.autograd.record():
        bn(x)  # back to train: stats must still update
    rm2 = bn.running_mean.data().asnumpy()
    assert not np.allclose(rm1, rm2)


def test_ctc_loss_values():
    """Check against directly-computed likelihoods for a tiny case."""
    loss_fn = gluon.loss.CTCLoss(layout="TNC", label_layout="NT")
    T, N, C = 2, 1, 3  # blank = 2
    pred = mx.nd.zeros((T, N, C))  # uniform: p = 1/3 each
    label = mx.nd.array([[0, -1]])
    out = loss_fn(pred, label).asnumpy()
    # Paths for label 'a' in 2 frames: (a,a),(a,blank),(blank,a) = 3/9
    expected = -np.log(3.0 / 9.0)
    np.testing.assert_allclose(out, [expected], rtol=1e-5)


def test_ctc_loss_batch_and_lengths():
    loss_fn = gluon.loss.CTCLoss()
    N, T, C = 3, 10, 5
    pred = mx.nd.array(np.random.randn(N, T, C).astype(np.float32))
    label = mx.nd.array([[1, 2, -1, -1], [0, 1, 2, 3], [2, -1, -1, -1]])
    out = loss_fn(pred, label).asnumpy()
    assert out.shape == (N,)
    assert (out > 0).all()


def test_ctc_loss_grad():
    pred = mx.nd.array(np.random.randn(4, 2, 5).astype(np.float32))
    pred.attach_grad()
    label = mx.nd.array([[1, 2], [3, -1]])
    loss_fn = gluon.loss.CTCLoss(layout="TNC")
    with mx.autograd.record():
        loss = loss_fn(pred, label).sum()
    loss.backward()
    g = pred.grad.asnumpy()
    assert np.isfinite(g).all()
    assert np.abs(g).sum() > 0


def test_bias_initializer_respected():
    net = gluon.nn.Dense(4, in_units=3, bias_initializer="ones")
    net.initialize()
    np.testing.assert_allclose(net.bias.data().asnumpy(), 1.0)


def test_optimizer_count_single_step_multi_ctx():
    net = gluon.nn.Dense(2, in_units=3)
    ctxs = [mx.cpu(0), mx.cpu(1)]
    net.initialize(ctx=ctxs)
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 0.1})
    with mx.autograd.record():
        losses = [(net(mx.nd.ones((2, 3), ctx=c)) ** 2).sum() for c in ctxs]
    for l in losses:
        l.backward()
    trainer.step(4)
    assert trainer._optimizer.num_update == 1


def test_layernorm_scale_center_off():
    ln = gluon.nn.LayerNorm(in_channels=4, scale=False, center=False)
    ln.initialize()
    assert ln.gamma.grad_req == "null"
    assert ln.beta.grad_req == "null"


def test_check_speed_utility():
    """test_utils.check_speed parity (reference test_utils.py:1131)."""
    from mxnet_tpu.test_utils import check_speed

    data = mx.sym.var("data")
    net = mx.sym.FullyConnected(data, num_hidden=8, name="fc")
    t_whole = check_speed(net, N=3, data=(4, 4))
    t_fwd = check_speed(net, N=3, typ="forward", data=(4, 4))
    assert t_whole > 0 and t_fwd > 0
    import pytest

    with pytest.raises(ValueError, match="typ"):
        check_speed(net, N=1, typ="bogus", data=(4, 4))


def test_export_hybridized_multi_input(tmp_path):
    """export() on a hybridized multi-input block that never ran the
    plain forward path (arity recorded in _call_cached_op too)."""
    import numpy as np

    class TwoIn(gluon.HybridBlock):
        def __init__(self):
            super().__init__()
            self.fc = gluon.nn.Dense(4, in_units=6)

        def hybrid_forward(self, F, a, b):
            return self.fc(F.concat(a, b, dim=1))

    net = TwoIn()
    net.initialize()
    net.hybridize()
    x = mx.nd.ones((2, 3))
    net(x, x)  # routes through _call_cached_op only
    sym_f, par_f = net.export(str(tmp_path / "two"))
    sym = mx.sym.load(sym_f)
    args = set(sym.list_arguments())
    assert "data0" in args and "data1" in args, args


def test_embedding_special_rows_get_unknown_init(tmp_path):
    """Reserved-token rows are filled with init_unknown_vec, not zeros."""
    import numpy as np
    from mxnet_tpu.contrib import text

    src = tmp_path / "vec.txt"
    src.write_text("hello 1 2 3\nworld 4 5 6\n")
    emb = text.embedding.CustomEmbedding(
        pretrained_file_path=str(src),
        init_unknown_vec=lambda d: np.full(d, 7.0, dtype=np.float32))
    vecs = emb.idx_to_vec.asnumpy() if hasattr(emb.idx_to_vec, "asnumpy") \
        else np.asarray(emb.idx_to_vec)
    n_special = vecs.shape[0] - 2
    assert n_special >= 1
    np.testing.assert_array_equal(vecs[:n_special],
                                  np.full((n_special, 3), 7.0))


def test_cached_op_retrace_only_on_new_signature():
    """Executable-cache contract behind serving warmup (PR 1): exactly
    one trace per (shape, train-mode) signature, repeats are cache hits,
    and the on_trace hook observes every compile."""
    from mxnet_tpu.cached_op import CachedOp

    traces = []
    cop = CachedOp(lambda x: x * 3.0)
    cop.on_trace = lambda c: traces.append(c.num_traces)
    for _ in range(4):
        cop(mx.nd.ones((2, 3)))
    assert cop.num_traces == 1
    cop(mx.nd.ones((5, 3)))            # new shape -> one new executable
    assert cop.num_traces == 2
    x = mx.nd.ones((2, 3))
    x.attach_grad()
    with mx.autograd.record():         # train-mode trace is distinct
        cop(x)
    assert cop.num_traces == 3
    cop.inference(mx.nd.ones((2, 3)))  # eval cache hit, no retrace
    assert cop.num_traces == 3
    assert traces == [1, 2, 3]


def test_ctc_loss_grad_long_sequences_no_nan():
    """Regression (r5): with realistic T≫S the DP has fully-dead states
    whose discarded logsumexp branch computed log(0) — autodiff's 0·inf
    through the `where` poisoned the ENTIRE gradient with NaN (the
    where-grad trap). Also sanity-check against finite differences."""
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.ops.contrib_ops import ctc_loss

    rng = np.random.RandomState(0)
    T, N, C, L = 30, 4, 11, 5
    pred = jnp.asarray(rng.randn(T, N, C).astype(np.float32))
    lab = np.full((N, L), -1, np.float32)
    for i in range(N):
        n = rng.randint(3, 6)
        lab[i, :n] = rng.randint(0, 10, n)
    label = jnp.asarray(lab)

    f = lambda p: ctc_loss(p, label).sum()
    g = jax.grad(f)(pred)
    assert np.isfinite(np.asarray(g)).all(), "CTC grad has NaN/inf"
    assert float(jnp.abs(g).sum()) > 0

    # central finite difference on a few coordinates
    eps = 1e-2
    for (t, n, c) in [(3, 2, 5), (0, 0, 10), (29, 3, 1)]:
        up = float(f(pred.at[t, n, c].add(eps)))
        dn = float(f(pred.at[t, n, c].add(-eps)))
        fd = (up - dn) / (2 * eps)
        np.testing.assert_allclose(fd, float(g[t, n, c]), rtol=0.05,
                                   atol=5e-3)
