"""Worker program for the 2-process causal-tracing acceptance test
(tests/test_xtrace_dist.py, launched via tools/launch.py roles).

Rank 0 roots two sampled traces against a real dist_sync kvstore:

* a training step — push into the sync round, pull the result. The
  server adopts the round's wire context (``kvstore::apply`` joins the
  flow on the server lane) and echoes it on pull replies, so the PEER
  rank's ``kvstore::pull`` slice gets ``link_trace_id`` stamped: one
  flow across worker 0, worker 1, and the server.
* a gateway-shaped request — request/device spans around a backend
  pull. The server records ``kvstore::serve_pull`` under the request's
  wire context: the request's flow reaches the server lane even though
  no apply ran for it.

Rank 0 then restarts its streaming writer (seq-resume) and records one
more span under the SAME step context — flow ids live in event args,
so the post-resume slice must still join the step's flow — flushes the
server lane over the command channel, and merges everything into one
Perfetto timeline.

Modes:

* ``normal`` — both ranks run to completion.
* ``kill`` — rank 1 SIGKILLs itself after committing its link-stamped
  pull slice (with another span buffered that never commits); rank 0
  must still merge one connected step flow from the committed anchors.
"""
import json
import os
import sys
import time

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _ROOT)
sys.path.insert(0, os.path.join(_ROOT, "tools"))

import jax

jax.config.update("jax_platforms", "cpu")

import mxnet_tpu as mx                                 # noqa: E402
from mxnet_tpu import telemetry                        # noqa: E402
from mxnet_tpu.telemetry import trace, xtrace          # noqa: E402

SHAPE = (8,)


def _wait_for_segments(out_dir, rank, deadline_s=60.0):
    """Block until a committed segment of ``rank`` exists (the peer's
    flush and rank 0's merge race in kill mode, where no barrier can
    order them)."""
    prefix = "trace.rank%d." % rank
    deadline = time.time() + deadline_s
    while time.time() < deadline:
        if any(f.startswith(prefix) and f.endswith(".jsonl")
               for f in os.listdir(out_dir)):
            return True
        time.sleep(0.1)
    return False


def main():
    out_dir = sys.argv[1]
    mode = sys.argv[2] if len(sys.argv) > 2 else "normal"
    kv = mx.kvstore.create("dist_sync")
    rank = kv.rank
    xtrace.set_sample_rate(1.0)
    writer = telemetry.StreamingTraceWriter(
        out_dir, rank=rank, max_segment_age_s=0.0)  # commit every tick

    kv.init("w", mx.nd.zeros(SHAPE))
    kv._barrier()               # both inits landed before any push
    out = mx.nd.zeros(SHAPE)
    ids = {}

    if rank == 0:
        # One training step, rooted here; the peer joins context-free.
        step_ctx = xtrace.new_root(sampled=True)
        ids["step"] = step_ctx.trace_id
        with xtrace.activate(step_ctx):
            with trace.span("xdist::train_step", rank=rank):
                kv.push("w", mx.nd.ones(SHAPE))
                kv.pull("w", out=out)
        writer.tick()
        # One gateway-shaped request: spans around a backend pull. The
        # server side joins via kvstore::serve_pull, not via an apply.
        gw_ctx = xtrace.new_root(sampled=True)
        ids["gateway"] = gw_ctx.trace_id
        with xtrace.activate(gw_ctx):
            with trace.span("xdist::gateway_request", rank=rank):
                with trace.span("xdist::gateway_device", rank=rank):
                    kv.pull("w", out=out)
        writer.tick()
        # Seq-resume: a restarted writer EXTENDS the segment set; a
        # span of the SAME trace recorded afterwards still joins its
        # flow (trace ids live in event args, not per-segment state).
        writer.close()
        writer = telemetry.StreamingTraceWriter(
            out_dir, rank=rank, max_segment_age_s=0.0)
        with xtrace.activate(step_ctx):
            with trace.span("xdist::post_resume", rank=rank):
                pass
        writer.flush()
    else:
        # The peer's push closes the sync round; its pull reply echoes
        # the applied round's context -> link_trace_id on the slice.
        with trace.span("xdist::peer_step", rank=rank):
            kv.push("w", mx.nd.ones(SHAPE))
            kv.pull("w", out=out)
        writer.flush()
        if mode == "kill":
            # Committed link anchor exists; buffer one more span that
            # never commits, then die without any cleanup at all.
            with trace.span("xdist::never_committed"):
                pass
            os.kill(os.getpid(), 9)

    if rank != 0:
        kv._barrier()
        return 0

    if mode != "kill":
        kv._barrier()           # the peer's flush has landed
    elif not _wait_for_segments(out_dir, 1):
        print("no committed segment from rank 1", file=sys.stderr)
        return 3

    # Commit the server lane's pending spans NOW (its writer's age
    # budget would otherwise hold them until shutdown), then merge.
    kv.server_profiler_command("trace_flush")

    with open(os.path.join(out_dir, "trace_ids.json"), "w") as f:
        json.dump(ids, f)

    import trace_merge

    trace_merge.merge([out_dir],
                      out=os.path.join(out_dir, "merged_trace.json"))
    return 0


if __name__ == "__main__":
    sys.exit(main())
