"""Test configuration.

Tests run on a virtual 8-device CPU mesh (the reference's GPU test suite
trick of re-running unit tests per context, tests/python/gpu/, maps to:
same tests, cpu backend, multi-device sharding exercised for real). The
driver's separate dryrun validates the multi-chip path too.
"""
import os
import sys

# Must be set before jax initializes its backends.
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
).strip()

import jax

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import pytest


@pytest.fixture(autouse=True)
def _seeded(request):
    """Reproducible-but-random seeds per test (reference:
    tests/python/unittest/common.py @with_seed). An MXNET_TEST_SEED
    env override reproduces a reported failure exactly."""
    env_seed = os.environ.get("MXNET_TEST_SEED")
    seed = int(env_seed) if env_seed else np.random.randint(0, 2 ** 31)
    np.random.seed(seed)
    import mxnet_tpu as mx

    mx.random.seed(seed)
    yield
    # On failure print the seed for reproduction (MXNET_TEST_SEED=N).
    rep = getattr(request.node, "rep_call", None)
    if rep is not None and rep.failed:
        print("\n*** test seed: %d (rerun with MXNET_TEST_SEED=%d) ***"
              % (seed, seed))


@pytest.hookimpl(hookwrapper=True)
def pytest_runtest_makereport(item, call):
    """Attach the call-phase report to the item so the seed fixture can
    see pass/fail (the non-wrapper form never populates rep_call)."""
    outcome = yield
    rep = outcome.get_result()
    setattr(item, "rep_" + rep.when, rep)


# ---------------------------------------------------------------------------
# Fault-injection filesystem (mxnet_tpu.checkpoint durability tests)
# ---------------------------------------------------------------------------

class FaultInjector:
    """Deterministic filesystem failures for the checkpoint write path.

    Drives the `_open_for_write` / `_rename` seams in
    mxnet_tpu.checkpoint.manager:

    * ``fail_next_writes(n)`` — the next `n` file.write() calls raise
      OSError (transient-IO retry behavior).
    * ``fail_next_renames(n)`` — the next `n` commit renames raise
      OSError (commit never lands → nothing partial becomes visible).
    * ``truncate_next_file(keep)`` — the next file opened for writing is
      truncated to `keep` bytes at close (a torn write that survives to
      "commit"; restore must detect it via length/CRC and skip).
    * ``corrupt(path, truncate_to=, flip_byte_at=)`` — damage an
      already-committed file directly.
    """

    def __init__(self):
        self.fail_writes = 0
        self.fail_renames = 0
        self.truncate_keep = None
        self.writes_failed = 0
        self.renames_failed = 0
        self.files_truncated = 0

    def fail_next_writes(self, n):
        self.fail_writes = int(n)

    def fail_next_renames(self, n):
        self.fail_renames = int(n)

    def truncate_next_file(self, keep_bytes):
        self.truncate_keep = int(keep_bytes)

    @staticmethod
    def corrupt(path, truncate_to=None, flip_byte_at=None):
        if truncate_to is not None:
            with open(path, "r+b") as f:
                f.truncate(truncate_to)
        if flip_byte_at is not None:
            with open(path, "r+b") as f:
                f.seek(flip_byte_at)
                b = f.read(1)
                f.seek(flip_byte_at)
                f.write(bytes([b[0] ^ 0xFF]))


class _FaultyFile:
    def __init__(self, f, injector, path):
        self._f = f
        self._inj = injector
        self._path = path
        self._truncate = injector.truncate_keep
        if self._truncate is not None:
            injector.truncate_keep = None

    def write(self, data):
        if self._inj.fail_writes > 0:
            self._inj.fail_writes -= 1
            self._inj.writes_failed += 1
            raise OSError("injected write failure")
        return self._f.write(data)

    def close(self):
        self._f.close()
        if self._truncate is not None:
            with open(self._path, "r+b") as f:
                f.truncate(self._truncate)
            self._inj.files_truncated += 1

    def __getattr__(self, name):
        return getattr(self._f, name)


@pytest.fixture
def fault_fs(monkeypatch):
    """Patch the checkpoint writer's IO seams with a FaultInjector."""
    from mxnet_tpu.checkpoint import manager as ckpt_manager

    inj = FaultInjector()
    real_open = ckpt_manager._open_for_write
    real_rename = ckpt_manager._rename

    def faulty_open(path):
        return _FaultyFile(real_open(path), inj, path)

    def faulty_rename(src, dst):
        if inj.fail_renames > 0:
            inj.fail_renames -= 1
            inj.renames_failed += 1
            raise OSError("injected rename failure")
        return real_rename(src, dst)

    monkeypatch.setattr(ckpt_manager, "_open_for_write", faulty_open)
    monkeypatch.setattr(ckpt_manager, "_rename", faulty_rename)
    yield inj
