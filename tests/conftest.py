"""Test configuration.

Tests run on a virtual 8-device CPU mesh (the reference's GPU test suite
trick of re-running unit tests per context, tests/python/gpu/, maps to:
same tests, cpu backend, multi-device sharding exercised for real). The
driver's separate dryrun validates the multi-chip path too.
"""
import os
import sys

# Must be set before jax initializes its backends.
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
).strip()

import jax

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import pytest


@pytest.fixture(autouse=True)
def _seeded(request):
    """Reproducible-but-random seeds per test (reference:
    tests/python/unittest/common.py @with_seed). An MXNET_TEST_SEED
    env override reproduces a reported failure exactly."""
    env_seed = os.environ.get("MXNET_TEST_SEED")
    seed = int(env_seed) if env_seed else np.random.randint(0, 2 ** 31)
    np.random.seed(seed)
    import mxnet_tpu as mx

    mx.random.seed(seed)
    yield
    # On failure print the seed for reproduction (MXNET_TEST_SEED=N).
    rep = getattr(request.node, "rep_call", None)
    if rep is not None and rep.failed:
        print("\n*** test seed: %d (rerun with MXNET_TEST_SEED=%d) ***"
              % (seed, seed))


@pytest.hookimpl(hookwrapper=True)
def pytest_runtest_makereport(item, call):
    """Attach the call-phase report to the item so the seed fixture can
    see pass/fail (the non-wrapper form never populates rep_call)."""
    outcome = yield
    rep = outcome.get_result()
    setattr(item, "rep_" + rep.when, rep)
