"""Continuous profiling & step-time attribution (ISSUE 12): the
always-on stack sampler (windows, retention, lane tagging, regression
sentinel), /debug/pprof + /debug/attribution endpoints, step-phase
attribution and the bound-cause classifier, executable-cost accounting,
decode-pool autoscaling, the Prometheus remote-write wire format, the
flamegraph frame-key fix, pod-profile collection and tools/profile_tool.
"""
import importlib.util
import json
import os
import socket
import sys
import threading
import time
import urllib.error
import urllib.request

import pytest

import mxnet_tpu as mx
from mxnet_tpu import telemetry
from mxnet_tpu.telemetry import aggregate, attribution, export
from mxnet_tpu.telemetry import flamegraph
from mxnet_tpu.telemetry import healthplane as hp
from mxnet_tpu.telemetry import metrics as tmetrics
from mxnet_tpu.telemetry import profiling, remote_write
from mxnet_tpu.telemetry import trace as ttrace
from mxnet_tpu.telemetry import watchdog as twd

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tools"))
from launch import launch_local  # noqa: E402

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(_ROOT, "tools", "%s.py" % name))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class _FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t


@pytest.fixture(autouse=True)
def _clean_state():
    twd.reset()
    hp.reset()
    attribution.set_device_spans(False)
    attribution.reset_costs()
    yield
    if profiling.active_profiler() is not None:
        profiling.active_profiler().close()
    twd.reset()
    hp.reset()
    attribution.set_device_spans(False)
    attribution.reset_costs()


def _can_bind_localhost():
    try:
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        s.close()
        return True
    except OSError:
        return False


def _http(url, accept=None):
    headers = {"Accept": accept} if accept else {}
    req = urllib.request.Request(url, headers=headers)
    try:
        with urllib.request.urlopen(req, timeout=10) as resp:
            return resp.status, resp.read(), \
                resp.headers.get("Content-Type", "")
    except urllib.error.HTTPError as e:
        return e.code, e.read(), e.headers.get("Content-Type", "")


def _busy_thread(name="prof_busy"):
    stop = threading.Event()

    def loop():
        while not stop.is_set():
            time.sleep(0.001)

    thread = threading.Thread(target=loop, name=name, daemon=True)
    thread.start()
    return stop, thread


# -- sampler mechanics --------------------------------------------------------

def test_fake_clock_window_rotation_and_retention_ring():
    clock = _FakeClock()
    profiler = telemetry.ContinuousProfiler(hz=100.0, window_s=10.0,
                                            retain=3, clock=clock)
    try:
        profiler._folded["root;f (x.py:1)"] = 100.0
        profiler._samples_in_window = 5
        assert profiler.maybe_rotate() is None       # t=0: not yet
        clock.t = 9.9
        assert profiler.maybe_rotate() is None
        clock.t = 10.0
        window = profiler.maybe_rotate()
        assert window is not None and window.seq == 1
        assert window.samples == 5
        assert window.folded == {"root;f (x.py:1)": 100.0}
        # Empty windows rotate silently (no blank ring entries).
        clock.t = 20.0
        assert profiler.maybe_rotate() is None
        assert len(profiler.windows) == 1
        # Retention ring: only the newest `retain` windows survive.
        for i in range(5):
            profiler._folded["root;g (y.py:2)"] = 10.0 * (i + 1)
            profiler._samples_in_window = 1
            profiler.rotate()
        assert len(profiler.windows) == 3
        assert [w.seq for w in profiler.windows] == [4, 5, 6]
    finally:
        profiler.close()


def test_sampler_counts_samples_and_overhead():
    before_samples = tmetrics.REGISTRY.get(
        "mx_profile_samples_total").value
    before_overhead = tmetrics.REGISTRY.get(
        "mx_profile_overhead_seconds").value
    stop, thread = _busy_thread()
    profiler = telemetry.ContinuousProfiler(hz=100.0, window_s=3600.0)
    try:
        for _ in range(10):
            profiler.sample()
        assert tmetrics.REGISTRY.get(
            "mx_profile_samples_total").value == before_samples + 10
        assert tmetrics.REGISTRY.get(
            "mx_profile_overhead_seconds").value > before_overhead
        window = profiler.rotate()
        assert window.samples == 10
        assert window.overhead_s > 0.0
        # Each sample charges one period (10 ms at 100 Hz) to the leaf.
        total_us = sum(window.folded.values())
        assert total_us >= 10 * 1e4     # >= 10 samples x 1 thread
    finally:
        stop.set()
        profiler.close()
        thread.join()


def test_lane_tagging_roots_threads_by_watchdog_lane():
    stop = threading.Event()

    def worker():
        twd.begin("step")       # this thread owns the step lane
        try:
            while not stop.is_set():
                time.sleep(0.001)
        finally:
            twd.end("step")

    thread = threading.Thread(target=worker, name="raw_thread_name",
                              daemon=True)
    thread.start()
    time.sleep(0.02)
    profiler = telemetry.ContinuousProfiler(hz=100.0, window_s=3600.0)
    try:
        for _ in range(5):
            profiler.sample()
        text = profiler.collapsed()
        assert any(line.startswith("step;") for line in
                   text.splitlines()), text
        assert "raw_thread_name" not in text
    finally:
        stop.set()
        thread.join()
        profiler.close()


def _spin_a(stop):
    def spin():
        while not stop.is_set():
            time.sleep(0.001)
    spin()


def _spin_b(stop):
    def spin():
        while not stop.is_set():
            time.sleep(0.001)
    spin()


def test_frame_keys_carry_file_lineno_so_same_names_stay_distinct():
    """ISSUE 12 satellite: two same-named functions (every worker loop
    is called `spin`/`run`) must fold into DISTINCT frames."""
    stop = threading.Event()
    threads = [threading.Thread(target=fn, args=(stop,), daemon=True)
               for fn in (_spin_a, _spin_b)]
    for t in threads:
        t.start()
    time.sleep(0.02)
    profiler = telemetry.ContinuousProfiler(hz=100.0, window_s=3600.0)
    try:
        for _ in range(5):
            profiler.sample()
        text = profiler.collapsed()
        spins = set()
        for line in text.splitlines():
            path = line.rsplit(" ", 1)[0]
            for frame in path.split(";"):
                if frame.startswith("spin ("):
                    spins.add(frame)
        assert len(spins) == 2, text    # merged pre-fix
    finally:
        stop.set()
        for t in threads:
            t.join()
        profiler.close()


def test_diff_top_keeps_located_frames_distinct_and_old_captures_diffable():
    # New-vs-new: same-named frames at different locations stay apart.
    before = "t;run (a.py:10) 100\nt;run (b.py:20) 100\n"
    after = "t;run (a.py:10) 50\nt;run (b.py:20) 150\n"
    rows = {r["op"]: r for r in flamegraph.diff_top(before, after)}
    assert "run (a.py:10)" in rows and "run (b.py:20)" in rows
    assert rows["run (b.py:20)"]["delta_pp"] == pytest.approx(25.0)
    # Old-vs-new (one side has no locations): fold both to bare names
    # instead of reading every frame as a 100% add/remove pair.
    old = "t;run 100\n"
    rows = flamegraph.diff_top(old, after)
    assert [r["op"] for r in rows] == ["run"]
    assert rows[0]["delta_pp"] == pytest.approx(0.0)


# -- regression sentinel + bundle section -------------------------------------

def test_profile_regression_anomaly_and_bundle_profile_section(tmp_path):
    monitor = telemetry.StepMonitor(warn_interval_s=1e9)
    recorder = telemetry.FlightRecorder(str(tmp_path), rank=0,
                                        rate_limit_s=0.0)
    recorder.attach(monitor)
    profiler = telemetry.ContinuousProfiler(
        hz=100.0, window_s=3600.0, monitor=monitor, regress_pp=10.0,
        min_samples=10)
    try:
        # Window 1 seeds the baseline: all self time in frame_x.
        profiler._folded = {"step;frame_x (a.py:1)": 1000.0}
        profiler._samples_in_window = 50
        profiler.rotate()
        assert monitor.anomaly_counts.get("profile_regression", 0) == 0
        # Window 2: the time moved to frame_y (+100pp share) -> anomaly
        # -> flight-recorder bundle whose profile section holds the
        # offending capture.
        profiler._folded = {"step;frame_y (a.py:9)": 1000.0}
        profiler._samples_in_window = 50
        profiler.rotate()
        assert monitor.anomaly_counts["profile_regression"] == 1
        assert len(recorder.bundles) == 1
        with open(recorder.bundles[0]) as f:
            bundle = json.load(f)
        assert bundle["meta"]["kind"] == "profile_regression"
        assert "frame_y (a.py:9)" in bundle["profile"]["collapsed"]
        assert bundle["profile"]["hz"] == 100.0
        # Below min_samples: shares are noise, the sentinel stays put.
        profiler._folded = {"step;frame_z (a.py:33)": 1000.0}
        profiler._samples_in_window = 3
        profiler.rotate()
        assert monitor.anomaly_counts["profile_regression"] == 1
    finally:
        profiler.close()


# -- /debug/pprof + /debug/attribution ----------------------------------------

@pytest.mark.skipif(not _can_bind_localhost(),
                    reason="localhost sockets unavailable")
def test_debug_pprof_endpoint_serves_collapsed_and_json(tmp_path):
    stop, thread = _busy_thread("pprof_busy")
    start_count = tmetrics.REGISTRY.get("mx_profile_samples_total").value
    profiler = telemetry.ContinuousProfiler(hz=200.0,
                                            window_s=3600.0).start()
    attr = telemetry.StepAttribution(interval_s=0.0,
                                     device_spans=False)
    plane = hp.HealthPlane(attribution=attr)
    server = tmetrics.start_http_server(0, health=plane)
    try:
        # Wait on THIS profiler's samples (the counter is global and
        # earlier tests may have advanced it).
        deadline = time.time() + 10.0
        while tmetrics.REGISTRY.get(
                "mx_profile_samples_total").value < start_count + 5 \
                and time.time() < deadline:
            time.sleep(0.01)
        before = tmetrics.REGISTRY.get("mx_profile_samples_total").value
        assert before >= start_count + 5, "sampler thread never ran"
        base = "http://%s:%d" % server.server_address
        status, body, ctype = _http(base + "/debug/pprof?seconds=60")
        assert status == 200
        assert ctype.startswith("text/plain")
        assert b"pprof_busy;" in body
        # format=json carries window metadata + the capture.
        status, body, ctype = _http(
            base + "/debug/pprof?seconds=60&format=json")
        assert status == 200 and ctype.startswith("application/json")
        state = json.loads(body)
        assert state["hz"] == 200.0
        assert "pprof_busy;" in state["collapsed"]
        assert state["captured_samples"] > 0
        # Bad params are 400s, not stack traces.
        assert _http(base + "/debug/pprof?seconds=nope")[0] == 400
        assert _http(base + "/debug/pprof?format=xml")[0] == 400
        # Overhead self-accounting keeps running WHILE captures are
        # served: the sampler thread advanced its counters across the
        # requests above.
        time.sleep(0.05)
        assert tmetrics.REGISTRY.get(
            "mx_profile_samples_total").value > before
        assert tmetrics.REGISTRY.get(
            "mx_profile_overhead_seconds").value > 0.0
        # /debug/attribution: the attributor's snapshot.
        status, body, _ = _http(base + "/debug/attribution")
        assert status == 200
        snap = json.loads(body)
        assert set(snap["phases"]) == set(attribution.PHASES)
    finally:
        server.close()
        profiler.close()
        attr.close()
        stop.set()
        thread.join()
    # No profiler running -> 404 with a hint, not a 500.
    plane2 = hp.HealthPlane()
    status, body = plane2.handle("GET", "/debug/pprof")
    assert status == 404


def test_healthplane_routes_strip_query_strings():
    plane = hp.HealthPlane()
    status, body = plane.handle("GET", "/healthz?verbose=1")
    assert status in (200, 503) and "lanes" in body


# -- step attribution ---------------------------------------------------------

def _span_events(*spans):
    """[(name, start_s, dur_s)] -> chrome events (µs)."""
    return [{"ph": "X", "name": name, "ts": start * 1e6,
             "dur": dur * 1e6} for name, start, dur in spans]


def test_attribution_phases_and_counters():
    attr = telemetry.StepAttribution(interval_s=0.0, device_spans=False)
    events = _span_events(
        ("data::wait", 0.0, 0.10),
        ("train_step::step", 0.10, 0.90),
        ("train_step::data_put", 0.10, 0.05),
        ("train_step::dispatch", 0.15, 0.20),
        ("train_step::device", 0.35, 0.60),
        ("checkpoint::snapshot", 0.95, 0.02),
    )
    sums = attr.update(events=events)
    assert sums["data_wait"] == pytest.approx(0.10)
    assert sums["h2d"] == pytest.approx(0.05)
    assert sums["dispatch"] == pytest.approx(0.20)
    assert sums["device_compute"] == pytest.approx(0.60)
    assert sums["checkpoint"] == pytest.approx(0.02)
    # other = step(0.90) - accounted-inside-step(0.87)
    assert sums["other"] == pytest.approx(0.03)
    assert attr.bound_cause == "compute-bound"
    shares = attr.last_shares
    assert shares["device_compute"] == pytest.approx(0.6, abs=0.01)
    snap = attr.snapshot()
    assert snap["bound_cause"] == "compute-bound"
    assert snap["phases"]["device_compute"] == pytest.approx(0.60)
    attr.close()


def test_attribution_watermark_consumes_each_span_once():
    attr = telemetry.StepAttribution(interval_s=0.0, device_spans=False)
    events = _span_events(("data::wait", 0.0, 0.5),
                          ("train_step::step", 0.5, 0.5))
    attr.update(events=events)
    first = attr.cumulative["data_wait"]
    attr.update(events=events)      # same events: nothing re-counted
    assert attr.cumulative["data_wait"] == first
    attr.close()


def test_attribution_input_bound_classifier_and_anomaly():
    monitor = telemetry.StepMonitor(warn_interval_s=1e9)
    attr = telemetry.StepAttribution(
        monitor=monitor, interval_s=0.0, input_bound_share=0.3,
        input_bound_windows=3, device_spans=False)
    t = [0.0]

    def window():
        events = _span_events(("data::wait", t[0], 0.6),
                              ("train_step::step", t[0] + 0.6, 0.4))
        t[0] += 1.0
        return events

    attr.update(events=window())
    attr.update(events=window())
    assert monitor.anomaly_counts.get("input_bound", 0) == 0
    attr.update(events=window())    # third consecutive window: fire
    assert monitor.anomaly_counts["input_bound"] == 1
    assert attr.bound_cause == "input-bound"
    gauge = tmetrics.REGISTRY.get("mx_step_bound")
    assert gauge.labels(cause="input-bound").value == 1
    assert gauge.labels(cause="compute-bound").value == 0
    # A healthy window resets the streak AND the cause.
    events = _span_events(("data::wait", t[0], 0.01),
                          ("train_step::step", t[0] + 0.01, 0.99),
                          ("train_step::device", t[0] + 0.01, 0.9))
    attr.update(events=events)
    assert attr.bound_cause == "compute-bound"
    assert attr._streak == 0
    attr.close()


def test_attribution_trainer_path_without_step_envelope():
    """Review regression: the imperative Trainer path emits
    trainer::allreduce but no train_step::step envelope — shares must
    stay <= 1 and a comm-dominated window must NOT page input-bound."""
    monitor = telemetry.StepMonitor(warn_interval_s=1e9)
    attr = telemetry.StepAttribution(
        monitor=monitor, interval_s=0.0, input_bound_windows=1,
        device_spans=False)
    attr.update(events=_span_events(("data::wait", 0.0, 0.5),
                                    ("trainer::allreduce", 0.5, 5.0)))
    shares = attr.last_shares
    assert all(0.0 <= s <= 1.0 for s in shares.values()), shares
    assert shares["allreduce"] == pytest.approx(5.0 / 5.5)
    assert attr.bound_cause == "comm-bound"
    assert monitor.anomaly_counts.get("input_bound", 0) == 0
    attr.close()


def test_constructed_profiler_does_not_hijack_active_slot():
    """Review regression: a built-but-never-started profiler must not
    steal /debug/pprof + bundle captures from the producing one."""
    live = telemetry.ContinuousProfiler(hz=100.0, window_s=3600.0)
    live.sample()
    assert profiling.active_profiler() is live
    idle = telemetry.ContinuousProfiler(hz=100.0, window_s=3600.0)
    assert profiling.active_profiler() is live
    idle.close()                    # closing the idle one: no stomp
    assert profiling.active_profiler() is live
    live.close()
    assert profiling.active_profiler() is None


def test_attribution_comm_and_host_bound_causes():
    attr = telemetry.StepAttribution(interval_s=0.0, device_spans=False)
    attr.update(events=_span_events(
        ("train_step::step", 0.0, 1.0),
        ("trainer::allreduce", 0.0, 0.8)))
    assert attr.bound_cause == "comm-bound"
    attr.update(events=_span_events(("train_step::step", 2.0, 1.0),
                                    ("train_step::device", 2.0, 0.1)))
    assert attr.bound_cause == "host-bound"
    attr.close()


def test_train_step_device_span_gated_by_attribution():
    import numpy as np

    from mxnet_tpu import gluon
    from mxnet_tpu.parallel import TrainStep, make_mesh

    import jax

    mx.random.seed(7)
    net = gluon.nn.Dense(4, in_units=8, prefix="attr_fc_")
    net.initialize(mx.init.Xavier())
    step = TrainStep(net, gluon.loss.L2Loss(), optimizer="sgd",
                     optimizer_params={"learning_rate": 0.01},
                     mesh=make_mesh())
    batch = 4 * jax.device_count()
    x = np.random.rand(batch, 8).astype(np.float32)
    y = np.random.rand(batch, 4).astype(np.float32)
    ttrace.clear()
    step(x, y)          # device spans off: no bracket
    names = {e["name"] for e in ttrace.chrome_trace()["traceEvents"]}
    assert "train_step::device" not in names
    with telemetry.StepAttribution(interval_s=0.0) as attr:
        assert attribution.device_spans_enabled()
        step(x, y)
        names = {e["name"]
                 for e in ttrace.chrome_trace()["traceEvents"]}
        assert "train_step::device" in names
        sums = attr.update()
        assert sums["device_compute"] >= 0.0
    assert not attribution.device_spans_enabled()   # restored


def test_executable_cost_recording_via_compile_seam(tmp_path):
    import jax.numpy as jnp

    from mxnet_tpu import compile as cc

    cc.reset()
    try:
        cc.configure(str(tmp_path / "cache"))
        fn = cc.maybe_cached_jit(lambda a: (a * 2.0).sum(),
                                 "prof_test_site")
        assert isinstance(fn, cc.CachedFunction)
        fn(jnp.ones((8, 8), jnp.float32))
        costs = attribution.executable_costs()
        assert "prof_test_site" in costs
        rec = costs["prof_test_site"]
        assert rec["flops"] is not None and rec["flops"] > 0
        gauge = tmetrics.REGISTRY.get("mx_executable_flops")
        assert gauge.labels(site="prof_test_site").value == \
            rec["flops"]
    finally:
        cc.reset()


# -- decode-pool autoscaling --------------------------------------------------

class _FakePool:
    def __init__(self, num_threads=2):
        self.num_threads = num_threads
        self.calls = []

    def resize(self, n):
        self.calls.append(n)
        self.num_threads = n
        return n


def test_autoscaler_hysteresis_grow_and_shrink():
    from mxnet_tpu.data.autoscale import DecodeAutoscaler

    pool = _FakePool(num_threads=2)
    scaler = DecodeAutoscaler(pool, min_workers=1, max_workers=4,
                              grow_share=0.25, shrink_share=0.05,
                              interval_s=10.0)
    # Input-bound windows grow one worker at a time, capped at max.
    assert scaler.observe(0.5, 0.5) == 3     # share 0.5 >= 0.25
    assert scaler.observe(0.5, 0.5) == 4
    assert scaler.observe(0.9, 0.1) == 4     # at the ceiling
    # The hysteresis band holds steady.
    assert scaler.observe(0.1, 0.9) == 4     # 0.05 < 0.1 < 0.25
    # Idle input shrinks back to the floor, one at a time.
    assert scaler.observe(0.01, 0.99) == 3
    assert scaler.observe(0.0, 1.0) == 2
    assert scaler.observe(0.0, 1.0) == 1
    assert scaler.observe(0.0, 1.0) == 1     # at the floor
    assert scaler.observe(0.0, 0.0) == 1     # idle window: no signal
    assert pool.calls == [3, 4, 3, 2, 1]


def test_autoscaler_tick_fake_clock_over_registry_deltas():
    from mxnet_tpu.data.autoscale import DecodeAutoscaler

    reg = tmetrics.Registry()
    wait = reg.histogram("mx_data_wait_seconds")
    step = reg.histogram("mx_train_step_seconds")
    pool = _FakePool(num_threads=1)
    clock = _FakeClock()
    scaler = DecodeAutoscaler(pool, max_workers=3, interval_s=10.0,
                              registry=reg, clock=clock)
    wait.observe(3.0)
    step.observe(1.0)
    assert scaler.tick() is None            # first window anchors
    clock.t = 5.0
    assert scaler.tick() is None            # inside the interval
    clock.t = 10.0
    wait.observe(3.0)                       # delta: wait 3, step 1
    step.observe(1.0)
    assert scaler.tick() == 2               # 0.75 share -> grow
    clock.t = 20.0
    step.observe(10.0)                      # delta: wait 0, step 10
    assert scaler.tick() == 1               # 0.0 share -> shrink
    assert pool.calls == [2, 1]


def test_decode_pool_resize_grows_live_pool():
    from mxnet_tpu.data.decode import DecodePool

    pool = DecodePool(lambda i: i * 2, num_threads=1)
    try:
        assert pool.resize(3) == 3
        assert pool.num_threads == 3 and pool.inflight == 6
        assert pool._pool._max_workers == 3
        assert list(pool.run(range(10))) == [i * 2 for i in range(10)]
        assert pool.resize(0) == 1          # floor at one worker
    finally:
        pool.close()


def test_autoscaler_default_ceiling_reads_env(monkeypatch):
    from mxnet_tpu.data.autoscale import DecodeAutoscaler

    scaler = DecodeAutoscaler(_FakePool())
    assert scaler.max_workers == 16         # catalogue default
    monkeypatch.setenv("MXNET_DATA_MAX_WORKERS", "5")
    scaler = DecodeAutoscaler(_FakePool())
    assert scaler.max_workers == 5


def test_data_pipeline_autoscale_wiring(tmp_path):
    from mxnet_tpu import recordio
    from mxnet_tpu.data.pipeline import DataPipeline

    rec = str(tmp_path / "t.rec")
    writer = recordio.MXRecordIO(rec, "w")
    for i in range(16):
        writer.write(("payload-%03d" % i).encode())
    writer.close()

    import numpy as np

    def decode(record):
        return (np.float32(float(record[-3:].decode())),
                np.zeros(2, np.float32))

    pipe = DataPipeline(
        [rec], decode,
        batch_size=4, shuffle=False, num_shards=1, shard_index=0,
        decode_threads=2, prefetch=0, place=False,
        autoscale={"interval_s": 0.0, "max_workers": 3})
    with pipe:
        next(pipe)
        next(pipe)
        assert pipe._autoscaler is not None
        assert pipe._autoscaler.pool is pipe._pool
        assert pipe._autoscaler.max_workers == 3


# -- Prometheus remote write --------------------------------------------------

def test_remote_write_protobuf_golden_bytes():
    """The WriteRequest encoding pinned byte-for-byte against the
    prompb schema (field numbers/wire types hand-assembled)."""
    reg = tmetrics.Registry()
    reg.counter("rw_total").inc(3)
    body = remote_write.encode_write_request(reg, 1700000000000,
                                             compress=False)
    golden = bytes.fromhex(
        "0a28"                              # WriteRequest.timeseries
        "0a14"                              # TimeSeries.labels[0]
        "0a085f5f6e616d655f5f"              # Label.name  "__name__"
        "120872775f746f74616c"              # Label.value "rw_total"
        "1210"                              # TimeSeries.samples[0]
        "090000000000000840"                # Sample.value double 3.0
        "1080d095ffbc31")                   # Sample.timestamp int64
    assert body == golden


def test_remote_write_labels_sorted_and_histograms_expanded():
    reg = tmetrics.Registry()
    h = reg.histogram("rw_lat_seconds", labels=("server",),
                      buckets=(0.1, 1.0))
    h.labels(server="s0").observe(0.05)
    h.labels(server="s0").observe(5.0)
    series = list(remote_write.registry_series(
        reg, extra_labels={"job": "aaa_job"}))
    names = [dict(labels)["__name__"] for labels, _ in series]
    assert names == ["rw_lat_seconds_bucket"] * 3 + \
        ["rw_lat_seconds_sum", "rw_lat_seconds_count"]
    labels, value = series[0]
    # __name__ first, the rest sorted by label name.
    assert [n for n, _ in labels] == ["__name__", "job", "le", "server"]
    assert value == 1                       # cumulative le=0.1
    assert dict(series[2][0])["le"] == "+Inf"
    assert series[2][1] == 2
    assert series[3][1] == pytest.approx(5.05)


def test_snappy_pure_python_literal_framing():
    try:
        import snappy  # noqa: F401

        pytest.skip("real snappy installed; literal framing unused")
    except ImportError:
        pass
    data = b"hello world"
    assert remote_write.snappy_compress(data) == b"\x0b\x28" + data
    # >60 bytes: 1-byte extended length (tag 60<<2, len-1).
    data = bytes(100)
    assert remote_write.snappy_compress(data) == \
        b"\x64" + bytes([60 << 2, 99]) + data
    assert remote_write.snappy_compress(b"") == b"\x00"


def test_push_exporter_remote_write_format_and_fallback():
    reg = tmetrics.Registry()
    reg.counter("rw_push_total").inc(9)
    sent = []
    exporter = export.PushExporter(
        "http://mimir:9009/api/v1/push", registry=reg, job="trainer",
        instance="r0", wire_format="remote_write",
        transport=lambda url, body: sent.append((url, body)))
    assert exporter.push() is True
    url, body = sent[0]
    assert url == "http://mimir:9009/api/v1/push"   # verbatim endpoint
    # Snappy literal framing leaves the protobuf readable: the series
    # carries __name__ + the job/instance labels.
    for needle in (b"rw_push_total", b"__name__", b"trainer", b"r0"):
        assert needle in body
    assert b"# HELP" not in body            # not the text format

    # A broken encode degrades to ONE classic-text snapshot, counted.
    class BadCollect:
        def collect(self):
            raise RuntimeError("no proto for you")

        def render_prometheus(self, openmetrics=False):
            return "fallback_metric 1\n"

    fails = tmetrics.REGISTRY.get("mx_export_failures_total").value
    exporter = export.PushExporter(
        "http://mimir:9009/api/v1/push", registry=BadCollect(),
        wire_format="remote_write",
        transport=lambda url, body: sent.append((url, body)))
    assert exporter.push() is True
    assert sent[-1][1] == b"fallback_metric 1\n"
    assert tmetrics.REGISTRY.get(
        "mx_export_failures_total").value == fails + 1

    with pytest.raises(ValueError):
        export.PushExporter("http://x", wire_format="msgpack")


# -- pod profiles over the diag channel ---------------------------------------

def _profiler_with(folded):
    profiler = telemetry.ContinuousProfiler(hz=100.0, window_s=3600.0)
    profiler._folded = dict(folded)
    profiler._samples_in_window = 50
    profiler.rotate()
    return profiler


def test_pod_profile_collection_over_local_bus(tmp_path):
    bus = aggregate.LocalBus(num_workers=2)
    profilers = [
        _profiler_with({"step;rank0_frame (a.py:1)": 2000.0}),
        _profiler_with({"data#2;rank1_frame (b.py:2)": 1000.0}),
    ]
    collectors = []
    for rank in (0, 1):
        rec = telemetry.FlightRecorder(
            str(tmp_path / ("local%d" % rank)), rank=rank,
            rate_limit_s=0.0)
        collectors.append(hp.DiagCollector(
            bus.endpoint(rank), rec, interval_s=0.0,
            profiler=profilers[rank],
            directory=str(tmp_path / "collected") if rank == 0
            else None))
    c0, c1 = collectors
    try:
        assert c0.request_pod_profile(seconds=600.0) == 1
        assert c1.poll_request() == "profile.rank1.000001.collapsed"
        assert c0.poll_request() == "profile.rank0.000001.collapsed"
        c0.collect()
        names = sorted(os.path.basename(p) for p in c0.collected)
        assert names == ["profile.rank0.000001.collapsed",
                         "profile.rank1.000001.collapsed"]
        merged = c0.merged_pod_profile()
        assert "rank0;step;rank0_frame (a.py:1)" in merged
        assert "rank1;data#2;rank1_frame (b.py:2)" in merged
        # A repeated poll without a new request pushes nothing.
        assert c1.poll_request() is None
    finally:
        for p in profilers:
            p.close()


def test_collector_gc_keeps_newest_per_kind(tmp_path):
    bus = aggregate.LocalBus(num_workers=1)
    rec = telemetry.FlightRecorder(str(tmp_path / "local"), rank=0,
                                   rate_limit_s=0.0)
    collector = hp.DiagCollector(bus.endpoint(0), rec, interval_s=0.0,
                                 keep_last=1,
                                 directory=str(tmp_path / "collected"))
    rank_dir = tmp_path / "collected" / "rank0"
    rank_dir.mkdir(parents=True)
    for name in ("diag.rank0.000001.json", "diag.rank0.000002.json",
                 "profile.rank0.000001.collapsed",
                 "profile.rank0.000002.collapsed"):
        (rank_dir / name).write_text("{}")
    removed = collector.gc()
    assert sorted(os.path.basename(p) for p in removed) == \
        ["diag.rank0.000001.json", "profile.rank0.000001.collapsed"]
    assert sorted(os.listdir(rank_dir)) == \
        ["diag.rank0.000002.json", "profile.rank0.000002.collapsed"]


# -- tools/profile_tool.py ----------------------------------------------------

def test_trace_exemplars_split_markers_from_hot_frames():
    """ISSUE 20 satellite: ``trace:<id>`` leaf markers become per-frame
    exemplars — the real hot frame keeps its self time instead of the
    marker swallowing it as the leaf."""
    folded = {
        "main;hot (x.py:1);trace:abc": 500.0,
        "main;hot (x.py:1);trace:def": 200.0,
        "main;hot (x.py:1)": 100.0,
        "main;cold (y.py:2)": 50.0,
    }
    clean, exemplars = flamegraph.trace_exemplars(folded)
    assert clean == {"main;hot (x.py:1)": 800.0,
                     "main;cold (y.py:2)": 50.0}
    assert exemplars == {"hot (x.py:1)": {"abc": 500.0, "def": 200.0}}
    leaf = flamegraph._by_leaf(clean)
    assert leaf["hot (x.py:1)"] == 800.0
    assert not any(f.startswith("trace:") for f in leaf)


def test_sampled_context_surfaces_as_exemplar_in_debug_state():
    from mxnet_tpu.telemetry import xtrace

    ctx = xtrace.new_root(sampled=True)
    stop = threading.Event()

    def traced_loop():
        with xtrace.activate(ctx):
            while not stop.is_set():
                time.sleep(0.001)

    thread = threading.Thread(target=traced_loop,
                              name="gp_exemplar", daemon=True)
    thread.start()
    profiler = telemetry.ContinuousProfiler(hz=200.0, window_s=3600.0)
    try:
        time.sleep(0.02)              # the loop is inside activate()
        for _ in range(10):
            profiler.sample()
        state = profiler.debug_state()
        hits = [frame for frame, ids in state["exemplars"].items()
                if any(e["trace_id"] == ctx.trace_id for e in ids)]
        assert hits, state["exemplars"]
        # the marker is exemplar metadata now, not a collapsed leaf
        assert "trace:%s" % ctx.trace_id not in state["collapsed"]
        # and each exemplar row carries its sampled self time
        for ids in state["exemplars"].values():
            assert all(e["self_us"] > 0 for e in ids)
    finally:
        profiler.close()
        stop.set()
        thread.join()


def test_profile_tool_top_prints_exemplars(tmp_path, capsys):
    tool = _tool("profile_tool")
    cap = tmp_path / "c.collapsed"
    cap.write_text("main;hot (x.py:1);trace:abc 900\n"
                   "main;hot (x.py:1);trace:ffe 300\n"
                   "main;cold (y.py:2) 100\n")
    assert tool.main(["top", str(cap), "-k", "5"]) == 0
    out = capsys.readouterr().out
    assert "hot (x.py:1)" in out
    assert "exemplars: trace:abc, trace:ffe" in out
    assert "trace:abc" not in out.splitlines()[2]  # not ranked as frame


def test_profile_tool_top_diff_merge(tmp_path, capsys):
    tool = _tool("profile_tool")
    a = tmp_path / "a.collapsed"
    b = tmp_path / "b.collapsed"
    a.write_text("main;fast (x.py:1) 900\nmain;slow (y.py:2) 100\n")
    b.write_text("main;fast (x.py:1) 100\nmain;slow (y.py:2) 900\n")

    assert tool.main(["top", str(a), "-k", "5"]) == 0
    out = capsys.readouterr().out
    assert "fast (x.py:1)" in out and "90.0%" in out

    assert tool.main(["diff", str(a), str(b)]) == 0
    out = capsys.readouterr().out
    assert "slow (y.py:2)" in out and "REGRESSED" in out

    merged = tmp_path / "merged.collapsed"
    assert tool.main(["merge", "-o", str(merged), str(a),
                      str(b)]) == 0
    folded = flamegraph._parse_collapsed(merged.read_text())
    assert folded == {"main;fast (x.py:1)": 1000.0,
                      "main;slow (y.py:2)": 1000.0}


# -- the "why is my step slow" loop, endpoints only ---------------------------

def _slow_decode(record):
    """The acceptance scenario's artificially slow decode."""
    import numpy as np

    time.sleep(0.02)
    return (np.float32(0.0), np.zeros(4, np.float32))


@pytest.mark.skipif(not _can_bind_localhost(),
                    reason="localhost sockets unavailable")
def test_acceptance_slow_decode_diagnosed_from_endpoints_alone(tmp_path):
    """ISSUE 12 acceptance: with an artificially slowed decode, (a)
    data_wait is the dominant phase, (b) mx_step_bound says
    input-bound, (c) /debug/pprof's top frames point into the decode
    path — all read from the HTTP endpoints, no local state."""
    from mxnet_tpu import recordio
    from mxnet_tpu.data.pipeline import DataPipeline

    rec = str(tmp_path / "slow.rec")
    writer = recordio.MXRecordIO(rec, "w")
    for i in range(64):
        writer.write(b"r%03d" % i)
    writer.close()

    profiler = telemetry.ContinuousProfiler(hz=200.0,
                                            window_s=3600.0).start()
    attr = telemetry.StepAttribution(interval_s=0.0,
                                     device_spans=False)
    plane = hp.HealthPlane(attribution=attr)
    server = tmetrics.start_http_server(0, health=plane)
    pipe = DataPipeline([rec], _slow_decode, batch_size=8,
                        shuffle=False, num_shards=1, shard_index=0,
                        decode_threads=2, prefetch=2, place=False)
    try:
        attr.update()                   # drain unrelated span backlog
        for _ in range(8):
            next(pipe)                  # data::wait recorded here
            with ttrace.span("train_step::step"):
                time.sleep(0.001)       # the "fast step"
        attr.update()
        base = "http://%s:%d" % server.server_address
        status, body, _ = _http(base + "/debug/attribution")
        assert status == 200
        snap = json.loads(body)
        shares = snap["last_shares"]
        assert shares["data_wait"] == max(shares.values())  # dominant
        assert snap["bound_cause"] == "input-bound"
        status, body, _ = _http(base + "/debug/pprof?seconds=60")
        assert status == 200
        assert b"_slow_decode (" in body        # the culprit, by name
    finally:
        pipe.close()
        server.close()
        profiler.close()
        attr.close()


# -- 2-process acceptance -----------------------------------------------------

_PROG = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "profiling_prog.py")
_ENV = {
    "JAX_PLATFORMS": "cpu",
    "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
}


def test_two_process_pod_profile_over_kvstore(tmp_path):
    """ISSUE 12 acceptance: rank 0's request_pod_profile fan-out pulls
    both ranks' profiler windows over the kvstore diag channel — one
    collected capture per rank, merged into a single pod profile whose
    stacks keep per-rank roots and rank-distinct frames."""
    if not _can_bind_localhost():
        pytest.skip("localhost sockets unavailable (multi-process "
                    "kvstore needs them)")
    codes = launch_local(2, 1, [sys.executable, _PROG, str(tmp_path)],
                         env_extra=_ENV, timeout=300)
    assert codes == [0, 0], codes
    result = json.loads((tmp_path / "result.json").read_text())
    names = sorted(os.path.basename(p) for p in result["collected"])
    assert names == ["profile.rank0.000001.collapsed",
                     "profile.rank1.000001.collapsed"]
    merged = result["merged"]
    assert "rank0;" in merged and "rank1;" in merged
    assert "rank_marker_0 (" in merged      # rank-distinct leaf frames
    assert "rank_marker_1 (" in merged
    for line in merged.splitlines():        # roots stay per-rank
        assert line.startswith(("rank0;", "rank1;"))


def test_adaptive_sampling_backs_off_and_recovers():
    """PR 12 follow-up: when the sampler's self-accounted overhead
    share exceeds its <=1% budget, the rate halves (down to min_hz);
    once the share falls well under budget it doubles back toward the
    configured rate. Driven entirely by the fake clock + fake perf
    counter, no thread."""
    clock = _FakeClock()
    profiler = telemetry.ContinuousProfiler(
        hz=64.0, window_s=10.0, retain=3, clock=clock,
        overhead_budget=0.01, min_hz=4.0)
    hz_gauge = tmetrics.REGISTRY.get("mx_profile_hz")
    adjusts = tmetrics.REGISTRY.get("mx_profile_rate_adjustments_total")
    down0 = adjusts.labels(direction="down").value
    try:
        # Window 1: overhead 5% of 10s wall — way over the 1% budget.
        profiler._samples_in_window = 20
        profiler._overhead_in_window = 0.5
        clock.t = 10.0
        profiler.rotate()
        assert profiler.hz == 32.0
        assert hz_gauge.value == 32.0
        assert adjusts.labels(direction="down").value == down0 + 1
        # Still over budget: halves again.
        profiler._samples_in_window = 20
        profiler._overhead_in_window = 0.5
        clock.t = 20.0
        profiler.rotate()
        assert profiler.hz == 16.0
        # Repeatedly over budget: never below min_hz.
        for i in range(6):
            profiler._samples_in_window = 20
            profiler._overhead_in_window = 0.5
            clock.t = 30.0 + 10.0 * i
            profiler.rotate()
        assert profiler.hz == 4.0
        # Healthy windows (share << budget/4): doubles back, capped at
        # the configured base rate.
        for i in range(8):
            profiler._samples_in_window = 20
            profiler._overhead_in_window = 0.0001
            clock.t = 100.0 + 10.0 * i
            profiler.rotate()
        assert profiler.hz == 64.0
        assert profiler.base_hz == 64.0
        # In the dead band (between budget/4 and budget): no change.
        profiler._samples_in_window = 20
        profiler._overhead_in_window = 0.05      # 0.5% of wall
        clock.t = 200.0
        profiler.rotate()
        assert profiler.hz == 64.0
    finally:
        profiler.close()


def test_adaptive_sampling_disabled_keeps_rate():
    clock = _FakeClock()
    profiler = telemetry.ContinuousProfiler(
        hz=64.0, window_s=10.0, retain=3, clock=clock, adaptive=False)
    try:
        profiler._samples_in_window = 20
        profiler._overhead_in_window = 5.0       # 50% overhead share
        clock.t = 10.0
        profiler.rotate()
        assert profiler.hz == 64.0
    finally:
        profiler.close()
