"""Model zoo forward tests (reference: tests/python/unittest/
test_gluon_model_zoo.py — forward each model on a small batch)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.gluon.model_zoo import vision

# One representative per family at standard resolution (the nightly
# covers all variants; keep unit runtime bounded).
SMALL_MODELS = [
    ("resnet18_v1", (1, 3, 32, 32), True),
    ("resnet18_v2", (1, 3, 32, 32), True),
    ("mobilenet0.25", (1, 3, 32, 32), False),
    ("mobilenetv2_0.25", (1, 3, 32, 32), False),
]

BIG_MODELS = [
    ("resnet50_v1", (1, 3, 224, 224)),
    ("vgg11", (1, 3, 224, 224)),
    ("alexnet", (1, 3, 224, 224)),
    ("squeezenet1.1", (1, 3, 224, 224)),
    ("densenet121", (1, 3, 224, 224)),
    ("inceptionv3", (1, 3, 299, 299)),
]


@pytest.mark.parametrize("name,shape,thumbnail", SMALL_MODELS)
def test_small_models_forward(name, shape, thumbnail):
    kwargs = {"classes": 10}
    if thumbnail:
        kwargs["thumbnail"] = True
    net = vision.get_model(name, **kwargs)
    net.initialize()
    out = net(mx.nd.array(np.random.rand(*shape).astype(np.float32)))
    assert out.shape == (shape[0], 10)
    assert np.isfinite(out.asnumpy()).all()


@pytest.mark.parametrize("name,shape", BIG_MODELS)
def test_big_models_construct_and_forward(name, shape):
    net = vision.get_model(name, classes=10)
    net.initialize()
    out = net(mx.nd.array(np.random.rand(*shape).astype(np.float32)))
    assert out.shape == (shape[0], 10)


def test_get_model_unknown():
    with pytest.raises(ValueError):
        vision.get_model("resnet999_v9")


def test_resnet50_hybridize_train_step():
    """Flagship model: hybridized forward+backward trains."""
    from mxnet_tpu import gluon

    net = vision.resnet50_v1(classes=10)
    net.initialize()
    net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1, "momentum": 0.9})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    x = mx.nd.array(np.random.rand(2, 3, 224, 224).astype(np.float32))
    y = mx.nd.array(np.array([1, 2], dtype=np.float32))
    with mx.autograd.record():
        out = net(x)
        loss = loss_fn(out, y)
    loss.backward()
    trainer.step(2)
    assert np.isfinite(loss.asnumpy()).all()
