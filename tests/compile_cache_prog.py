"""Worker program for the 2-process compile-cache acceptance test
(tests/test_compile_cache.py, launched via tools/launch.py roles).

Proves the ISSUE 11 distribution property over a REAL dist kvstore:
rank 0 compiles the shared executables (a CachedOp bucket ladder, a
fused-update chunk, a whole-step TrainStep) with the persistent cache
enabled and publishes every entry over ``cc_push``; rank 1 starts with
an EMPTY local cache directory, builds the same workload after a
barrier, and performs ZERO local compiles at the shared sites — every
executable arrives over ``cc_probe``/``cc_pull`` (and is committed to
rank 1's own disk, so its NEXT restart doesn't even need the pod).
"""
import json
import os
import sys

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _ROOT)

import numpy as np                                      # noqa: E402

import mxnet_tpu as mx                                  # noqa: E402
from mxnet_tpu import autograd, gluon, nd               # noqa: E402
from mxnet_tpu import compile as cc                     # noqa: E402
from mxnet_tpu.cached_op import CachedOp                # noqa: E402
from mxnet_tpu.gluon import nn                          # noqa: E402
from mxnet_tpu.gluon import loss as gloss               # noqa: E402
from mxnet_tpu.parallel import TrainStep                # noqa: E402
from mxnet_tpu.telemetry import memstats                # noqa: E402
from mxnet_tpu.telemetry import metrics as tmetrics     # noqa: E402

SITES = ("cached_op", "fused_apply", "train_step")


def build_workload(rng):
    """The shared executables: identical graphs on both ranks (fixed
    prefixes => restart/rank-stable param names => identical HLO)."""
    # CachedOp bucket ladder (the serving warmup shape).
    w = nd.array(rng.rand(16, 8).astype(np.float32))

    def fwd(w_, x):
        return nd.dot(x, w_)

    op = CachedOp(fwd, num_params=1)
    for rows in (1, 2, 4):
        op.inference(w, nd.array(rng.rand(rows, 16).astype(np.float32)))

    # Fused-update chunk.
    net = nn.Dense(8, in_units=16, prefix="ccprog_")
    net.initialize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1})
    with autograd.record():
        loss = net(nd.array(rng.rand(4, 16).astype(np.float32))).sum()
    loss.backward()
    trainer.step(4)

    # Whole-step TrainStep executable.
    net2 = nn.Dense(4, in_units=8, prefix="ccprog_step_")
    net2.initialize()
    step = TrainStep(net2, gloss.L2Loss(), optimizer="sgd",
                     optimizer_params={"learning_rate": 0.1})
    out = step(rng.rand(4, 8).astype(np.float32),
               rng.rand(4, 4).astype(np.float32))
    float(np.asarray(out))


def main():
    out_dir = sys.argv[1]
    kv = mx.kvstore.create("dist_sync")
    rank = kv.rank

    # Private per-rank cache directory — rank 1's starts EMPTY and
    # nothing below may read a peer's disk.
    local_dir = os.path.join(out_dir, "cache_rank%d" % rank)
    cc.configure(local_dir)
    cc.attach_kvstore(kv)

    rng = np.random.RandomState(7)      # identical shapes on both ranks
    if rank == 0:
        build_workload(rng)
        kv.barrier()                    # entries pushed + acked first
    else:
        kv.barrier()                    # wait for rank 0's publishes
        build_workload(rng)

    counts = {site: rec["count"]
              for site, rec in memstats.compile_stats().items()}
    hits = {}
    reg = tmetrics.REGISTRY.get("mx_compile_cache_hits_total")
    for (site, source), child in reg.collect():
        hits["%s/%s" % (site, source)] = child.value
    result = {
        "rank": rank,
        "compile_counts": counts,
        "hits": hits,
        "local_entries": sorted(os.listdir(local_dir))
        if os.path.isdir(local_dir) else [],
    }
    with open(os.path.join(out_dir, "result_rank%d.json" % rank),
              "w") as f:
        json.dump(result, f)

    kv.barrier()
    kv.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
