"""End-to-end causal tracing, 2-process acceptance (ISSUE 18): a
gateway-shaped request and a training step against a REAL dist_sync
kvstore each reconstruct as ONE connected flow in the merged Perfetto
timeline — across the rooting worker's lane, the peer worker's lane
(pull replies echo the applied round's context as ``link_trace_id``),
and the server's lane (``kvstore::apply`` under the round context,
``kvstore::serve_pull`` under the requester's context). Plus the
kill-mid-segment case: flow ids live in event args, so committed
anchors keep the flow connected after a SIGKILL and across a
writer seq-resume."""
import json
import os
import socket
import sys

import pytest

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tools"))
from launch import launch_local  # noqa: E402

_PROG = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "xtrace_dist_prog.py")
_BASE_ENV = {
    "JAX_PLATFORMS": "cpu",
    "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
}


def _can_bind_localhost():
    try:
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        s.close()
        return True
    except OSError:
        return False


def _launch(tmp_path, mode):
    if not _can_bind_localhost():
        pytest.skip("localhost sockets unavailable (multi-process "
                    "kvstore needs them)")
    env = dict(_BASE_ENV)
    env["MXNET_TRACE_DIR"] = str(tmp_path)   # server streams its lane
    return launch_local(
        2, 1, [sys.executable, _PROG, str(tmp_path), mode],
        env_extra=env, timeout=300)


def _load(tmp_path):
    with open(os.path.join(str(tmp_path), "trace_ids.json")) as f:
        ids = json.load(f)
    with open(os.path.join(str(tmp_path), "merged_trace.json")) as f:
        events = json.load(f)["traceEvents"]
    return ids, events


def _anchors(events, trace_id):
    """X slices stamped into this trace's flow — by ownership
    (``trace_id``) or by service (``link_trace_id``)."""
    out = []
    for e in events:
        if e.get("ph") != "X":
            continue
        args = e.get("args") or {}
        if trace_id in (args.get("trace_id"), args.get("link_trace_id")):
            out.append(e)
    return out


def _flows(events, trace_id):
    return [e for e in events
            if e.get("cat") == "xtrace" and e.get("id") == trace_id]


def test_two_process_step_and_request_each_one_flow(tmp_path):
    """ISSUE 18 acceptance: merged timeline from a 2-worker dist job
    shows a single training step and a single gateway request each as
    one flow spanning both worker lanes (and the server lane)."""
    codes = _launch(tmp_path, "normal")
    assert codes == [0, 0], codes
    ids, events = _load(tmp_path)

    # -- the training step: rooted on worker 0, one connected flow
    step = _anchors(events, ids["step"])
    named = {(e["name"], e["pid"]) for e in step}
    assert ("xdist::train_step", 0) in named, named
    assert ("kvstore::pull", 1) in named, named      # peer, via link
    assert ("kvstore::apply", 2) in named, named     # server lane
    # the slice recorded through a RESTARTED writer (seq-resume) still
    # joined the same flow
    assert ("xdist::post_resume", 0) in named, named
    flows = _flows(events, ids["step"])
    assert {f["ph"] for f in flows} == {"s", "t", "f"}, flows
    assert {f["pid"] for f in flows} >= {0, 1, 2}
    assert sum(1 for f in flows if f["ph"] == "s") == 1
    finish = [f for f in flows if f["ph"] == "f"]
    assert len(finish) == 1 and finish[0]["bp"] == "e"
    # arrows step forward in time
    ts = [f["ts"] for f in sorted(flows, key=lambda f: f["ts"])]
    assert ts == sorted(f["ts"] for f in flows)

    # -- the gateway request: its flow reaches the server lane through
    # kvstore::serve_pull (no apply ran for it)
    gw = _anchors(events, ids["gateway"])
    gnamed = {(e["name"], e["pid"]) for e in gw}
    assert ("xdist::gateway_request", 0) in gnamed, gnamed
    assert ("xdist::gateway_device", 0) in gnamed
    assert ("kvstore::serve_pull", 2) in gnamed, gnamed
    gflows = _flows(events, ids["gateway"])
    assert {f["pid"] for f in gflows} >= {0, 2}
    assert {f["ph"] for f in gflows} >= {"s", "f"}

    # the two traces are distinct flows, not one blob
    assert ids["step"] != ids["gateway"]
    lanes = {e["args"]["name"] for e in events
             if e.get("ph") == "M" and e["name"] == "process_name"}
    assert {"rank 0", "rank 1", "rank 2"} <= lanes, lanes


def test_two_process_sigkill_keeps_committed_flow_anchors(tmp_path):
    """SIGKILL of the peer mid-segment: its committed link-stamped
    pull slice keeps the step flow connected across both worker lanes;
    the never-committed span is gone."""
    codes = _launch(tmp_path, "kill")
    # kv ranks come from scheduler registration order, so EITHER worker
    # process may have drawn rank 1 (the SIGKILLed one).
    assert sorted(codes) == [-9, 0], codes
    ids, events = _load(tmp_path)
    step = _anchors(events, ids["step"])
    named = {(e["name"], e["pid"]) for e in step}
    assert ("xdist::train_step", 0) in named, named
    assert ("kvstore::pull", 1) in named, named
    flows = _flows(events, ids["step"])
    assert {f["pid"] for f in flows} >= {0, 1}
    assert not any(e.get("name") == "xdist::never_committed"
                   for e in events)
