"""tools/mxlint — the project-aware static analysis suite.

Three layers:

1. Per-checker fixture tests: each rule fires on a seeded violation,
   stays quiet on the fixed form, and honors a justified suppression.
2. Regression fixtures reproducing real past bug classes (the pre-PR-6
   PrefetchingIter joinless worker; a torn non-atomic state dump — the
   class fixed in PRs 2/5/7/9).
3. ``test_tree_is_clean``: the full suite over ``mxnet_tpu/`` reports
   ZERO findings — every invariant the checkers encode is pinned
   tier-1 from here on.
"""
import json
import os
import subprocess
import sys
import textwrap

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from tools.mxlint import run_suite  # noqa: E402
from tools.mxlint.core import render_json  # noqa: E402


def lint(tmp_path, source, checks=None, name="mod.py", root=None):
    """Write `source` as one module and run the (selected) suite."""
    p = tmp_path / name
    p.write_text(textwrap.dedent(source))
    res = run_suite([str(p)], checks=checks, root=str(root or tmp_path))
    return res


def checks_of(res):
    return [f.check for f in res.findings]


# ---------------------------------------------------------------------------
# lock-blocking / lock-order
# ---------------------------------------------------------------------------

class TestLockBlocking:
    def test_sleep_under_with_lock_fires(self, tmp_path):
        res = lint(tmp_path, """
            import threading, time
            class A:
                def __init__(self):
                    self._lock = threading.Lock()
                def f(self):
                    with self._lock:
                        time.sleep(1)
            """, checks=["lock-blocking"])
        assert checks_of(res) == ["lock-blocking"]

    def test_sleep_outside_lock_quiet(self, tmp_path):
        res = lint(tmp_path, """
            import threading, time
            class A:
                def __init__(self):
                    self._lock = threading.Lock()
                def f(self):
                    with self._lock:
                        x = 1
                    time.sleep(1)
            """, checks=["lock-blocking"])
        assert res.findings == []

    def test_joinless_join_and_queue_get_under_lock(self, tmp_path):
        res = lint(tmp_path, """
            import threading
            class A:
                def __init__(self):
                    self._lock = threading.RLock()
                def f(self, t, q):
                    with self._lock:
                        t.join()
                        q.get()
            """, checks=["lock-blocking"])
        assert checks_of(res) == ["lock-blocking", "lock-blocking"]

    def test_bounded_waits_quiet(self, tmp_path):
        # timeout'd join/get and block=False are bounded — no finding.
        res = lint(tmp_path, """
            import threading
            class A:
                def __init__(self):
                    self._lock = threading.Lock()
                def f(self, t, q):
                    with self._lock:
                        t.join(timeout=5)
                        q.get(timeout=1)
                        q.get(block=False)
            """, checks=["lock-blocking"])
        assert res.findings == []

    def test_nested_def_resets_held_set(self, tmp_path):
        # A closure *defined* under the lock runs later, lock-free.
        res = lint(tmp_path, """
            import threading, time
            class A:
                def __init__(self):
                    self._lock = threading.Lock()
                def f(self):
                    with self._lock:
                        def worker():
                            time.sleep(1)
                        return worker
            """, checks=["lock-blocking"])
        assert res.findings == []

    def test_block_until_ready_and_subprocess(self, tmp_path):
        res = lint(tmp_path, """
            import subprocess, threading
            _lock = threading.Lock()
            def f(x):
                with _lock:
                    x.block_until_ready()
                    subprocess.run(["ls"])          # unbounded: fires
                    subprocess.run(["ls"], timeout=5)  # bounded: quiet
            """, checks=["lock-blocking"])
        assert checks_of(res) == ["lock-blocking", "lock-blocking"]

    def test_lock_order_inversion_across_functions(self, tmp_path):
        res = lint(tmp_path, """
            import threading
            class A:
                def __init__(self):
                    self._a = threading.Lock()
                    self._b = threading.Lock()
                def f(self):
                    with self._a:
                        with self._b:
                            pass
                def g(self):
                    with self._b:
                        with self._a:
                            pass
            """, checks=["lock-order"])
        # Both sites of the inversion are flagged.
        assert checks_of(res) == ["lock-order", "lock-order"]

    def test_lock_order_is_per_module(self, tmp_path):
        # 'self._a'/'self._b' in two different files are UNRELATED
        # locks — no cross-module pairing on bare attribute names.
        (tmp_path / "m1.py").write_text(textwrap.dedent("""
            import threading
            class A:
                def __init__(self):
                    self._a = threading.Lock()
                    self._b = threading.Lock()
                def f(self):
                    with self._a:
                        with self._b:
                            pass
            """))
        (tmp_path / "m2.py").write_text(textwrap.dedent("""
            import threading
            class Unrelated:
                def __init__(self):
                    self._a = threading.Lock()
                    self._b = threading.Lock()
                def g(self):
                    with self._b:
                        with self._a:
                            pass
            """))
        res = run_suite([str(tmp_path)], checks=["lock-order"],
                        root=str(tmp_path))
        assert res.findings == []

    def test_consistent_order_quiet(self, tmp_path):
        res = lint(tmp_path, """
            import threading
            class A:
                def __init__(self):
                    self._a = threading.Lock()
                    self._b = threading.Lock()
                def f(self):
                    with self._a:
                        with self._b:
                            pass
                def g(self):
                    with self._a:
                        with self._b:
                            pass
            """, checks=["lock-order"])
        assert res.findings == []

    def test_suppression_with_justification(self, tmp_path):
        res = lint(tmp_path, """
            import threading, time
            _lock = threading.Lock()
            def f():
                with _lock:
                    time.sleep(1)  # mxlint: disable=lock-blocking -- test fixture
            """, checks=["lock-blocking"])
        assert res.findings == [] and res.suppressed == 1


# ---------------------------------------------------------------------------
# signal-safety
# ---------------------------------------------------------------------------

class TestSignalSafety:
    def test_logging_in_handler_fires(self, tmp_path):
        res = lint(tmp_path, """
            import logging, signal
            log = logging.getLogger(__name__)
            def handler(signum, frame):
                log.warning("caught %d", signum)
            def install():
                signal.signal(signal.SIGTERM, handler)
            """, checks=["signal-safety"])
        assert checks_of(res) == ["signal-safety"]

    def test_transitive_reachability(self, tmp_path):
        # Violation two hops away via self.method chains still found.
        res = lint(tmp_path, """
            import signal, threading
            class H:
                def install(self):
                    signal.signal(signal.SIGTERM, self._handler)
                def _handler(self, signum, frame):
                    self._helper()
                def _helper(self):
                    self._deep()
                def _deep(self):
                    open("/tmp/x", "r")
            """, checks=["signal-safety"])
        assert checks_of(res) == ["signal-safety"]

    def test_os_write_pattern_quiet(self, tmp_path):
        # The sanctioned handler vocabulary (os.write, flag sets).
        res = lint(tmp_path, """
            import os, signal
            class H:
                def install(self):
                    signal.signal(signal.SIGTERM, self._handler)
                def _handler(self, signum, frame):
                    self.fired = True
                    os.write(2, b"preempted\\n")
            """, checks=["signal-safety"])
        assert res.findings == []

    def test_module_level_registration_checked(self, tmp_path):
        # The most common registration shape: signal.signal at module
        # level (no enclosing def) — the handler is still checked.
        res = lint(tmp_path, """
            import logging, signal
            log = logging.getLogger(__name__)
            def handler(signum, frame):
                log.warning("caught %d", signum)
            signal.signal(signal.SIGTERM, handler)
            """, checks=["signal-safety"])
        assert checks_of(res) == ["signal-safety"]

    def test_same_code_unregistered_quiet(self, tmp_path):
        # Identical body NOT registered as a handler: no findings.
        res = lint(tmp_path, """
            import logging
            log = logging.getLogger(__name__)
            def handler(signum, frame):
                log.warning("caught %d", signum)
            """, checks=["signal-safety"])
        assert res.findings == []


# ---------------------------------------------------------------------------
# atomic-write
# ---------------------------------------------------------------------------

class TestAtomicWrite:
    def test_write_mode_open_fires(self, tmp_path):
        res = lint(tmp_path, """
            def save(path, blob):
                with open(path, "wb") as f:
                    f.write(blob)
            """, checks=["atomic-write"])
        assert checks_of(res) == ["atomic-write"]

    def test_read_mode_quiet(self, tmp_path):
        res = lint(tmp_path, """
            def load(path):
                with open(path, "rb") as f:
                    return f.read()
            def load2(path):
                return open(path).read()
            """, checks=["atomic-write"])
        assert res.findings == []

    def test_append_and_plus_modes_fire(self, tmp_path):
        res = lint(tmp_path, """
            def f(path):
                a = open(path, "ab")
                b = open(path, "r+")
            """, checks=["atomic-write"])
        assert len(res.findings) == 2

    def test_sanctioned_seam_quiet(self, tmp_path):
        # Same code, but inside the real seam file+function: allowed.
        d = tmp_path / "mxnet_tpu" / "checkpoint"
        d.mkdir(parents=True)
        (tmp_path / "mxnet_tpu" / "env.py").write_text("CATALOGUE = []\n")
        (d / "manager.py").write_text(textwrap.dedent("""
            def _open_for_write(path):
                return open(path, "wb")
            """))
        res = run_suite([str(d / "manager.py")], checks=["atomic-write"],
                        root=str(tmp_path))
        assert res.findings == []

    def test_regression_torn_state_dump(self, tmp_path):
        # The bug class fixed in PRs 2/5/7/9 and again this PR
        # (kvstore_dist.save_optimizer_states): pickle straight into
        # the destination — a crash mid-dump leaves a torn file that
        # unpickles as garbage at restore.
        res = lint(tmp_path, """
            import pickle
            def save_optimizer_states(fname, blobs):
                with open(fname, "wb") as f:
                    pickle.dump(blobs, f)
            """, checks=["atomic-write"])
        assert checks_of(res) == ["atomic-write"]


# ---------------------------------------------------------------------------
# env-knob
# ---------------------------------------------------------------------------

@pytest.fixture
def knob_project(tmp_path):
    """Mini project: env.py declaring one knob, README documenting it."""
    pkg = tmp_path / "mxnet_tpu"
    pkg.mkdir()
    (pkg / "env.py").write_text(textwrap.dedent("""
        from collections import namedtuple
        Knob = namedtuple("Knob", "name typ default where doc subsumed")
        CATALOGUE = [
            Knob("MXNET_DECLARED", int, 1, "x.py", "a knob", False),
            Knob("MXNET_UNDOCUMENTED", int, 1, "x.py", "hidden", False),
        ]
        """))
    (tmp_path / "README.md").write_text("| `MXNET_DECLARED` | a knob |\n")
    return tmp_path


@pytest.fixture()
def stale_project(tmp_path):
    """Mini project for the stale-knob rule: env.py declares a read
    knob, a dead knob, and a subsumed knob; x.py reads only the first."""
    pkg = tmp_path / "mxnet_tpu"
    pkg.mkdir()
    (pkg / "env.py").write_text(textwrap.dedent("""
        from collections import namedtuple
        Knob = namedtuple("Knob", "name typ default where doc subsumed")
        CATALOGUE = [
            Knob("MXNET_LIVE", int, 1, "x.py", "still read", False),
            Knob("MXNET_DEAD", int, 1, "gone.py", "refactored", False),
            Knob("MXNET_INERT", int, 1, "(subsumed)", "PJRT owns it",
                 True),
        ]
        """))
    (pkg / "x.py").write_text(textwrap.dedent("""
        import os
        v = os.environ.get("MXNET_LIVE", "1")
        """))
    (tmp_path / "README.md").write_text(
        "| `MXNET_LIVE` | x | `MXNET_DEAD` | x | `MXNET_INERT` | x |\n")
    return tmp_path


class TestStaleKnob:
    def test_dead_knob_fires_subsumed_exempt(self, stale_project):
        res = run_suite([str(stale_project / "mxnet_tpu")],
                        checks=["stale-knob"], root=str(stale_project))
        assert checks_of(res) == ["stale-knob"]
        assert "MXNET_DEAD" in res.findings[0].message
        assert res.findings[0].path == "mxnet_tpu/env.py"

    def test_read_anywhere_in_tree_counts(self, stale_project):
        # a knob read only by a driver under tools/ is NOT stale — the
        # scan covers the whole project regardless of the run's paths
        tools = stale_project / "tools"
        tools.mkdir()
        (tools / "drv.py").write_text(textwrap.dedent("""
            import os
            v = os.environ.get("MXNET_DEAD")
            """))
        res = run_suite([str(stale_project / "mxnet_tpu")],
                        checks=["stale-knob"], root=str(stale_project))
        assert res.findings == []

    def test_justified_suppression_on_knob_line(self, stale_project):
        env_py = stale_project / "mxnet_tpu" / "env.py"
        src = env_py.read_text().replace(
            '"refactored", False),',
            '"refactored", False),  '
            '# mxlint: disable=stale-knob -- forward declaration')
        env_py.write_text(src)
        res = run_suite([str(stale_project / "mxnet_tpu")],
                        checks=["stale-knob"], root=str(stale_project))
        assert res.findings == []
        assert res.suppressed == 1

    def test_suppression_honored_outside_scanned_paths(self, stale_project):
        """Cross-module findings anchor to env.py even when env.py is
        NOT among the linted paths — its justified suppressions must
        still apply (run() parses the anchor file on demand)."""
        env_py = stale_project / "mxnet_tpu" / "env.py"
        src = env_py.read_text().replace(
            '"refactored", False),',
            '"refactored", False),  '
            '# mxlint: disable=stale-knob -- forward declaration')
        env_py.write_text(src)
        tools = stale_project / "tools"
        tools.mkdir()
        (tools / "t.py").write_text("x = 1\n")
        res = run_suite([str(tools)], checks=["stale-knob"],
                        root=str(stale_project))
        assert res.findings == []
        assert res.suppressed == 1


class TestEnvKnob:
    def test_undeclared_read_fires(self, knob_project):
        res = lint(knob_project, """
            import os
            x = os.environ.get("MXNET_NOT_DECLARED", "0")
            """, checks=["env-knob"], root=knob_project)
        assert checks_of(res) == ["env-knob"]
        assert "MXNET_NOT_DECLARED" in res.findings[0].message

    def test_declared_read_quiet(self, knob_project):
        res = lint(knob_project, """
            import os
            x = os.environ.get("MXNET_DECLARED", "0")
            y = os.environ["MXNET_DECLARED"]
            z = os.getenv("MXNET_DECLARED")
            """, checks=["env-knob"], root=knob_project)
        assert res.findings == []

    def test_typo_is_caught(self, knob_project):
        # The motivating failure: a typo silently reads its default.
        res = lint(knob_project, """
            import os
            x = os.environ.get("MXNET_DECLRED", "0")
            """, checks=["env-knob"], root=knob_project)
        assert len(res.findings) == 1

    def test_catalogue_entry_missing_from_readme(self, knob_project):
        env_py = knob_project / "mxnet_tpu" / "env.py"
        res = run_suite([str(env_py)], checks=["env-knob"],
                        root=str(knob_project))
        assert len(res.findings) == 1
        assert "MXNET_UNDOCUMENTED" in res.findings[0].message

    def test_dynamic_read_out_of_scope(self, knob_project):
        res = lint(knob_project, """
            import os
            def probe(name):
                return os.environ.get(name)
            """, checks=["env-knob"], root=knob_project)
        assert res.findings == []


# ---------------------------------------------------------------------------
# thread-lifecycle
# ---------------------------------------------------------------------------

class TestThreadLifecycle:
    def test_regression_pre_pr6_prefetching_iter(self, tmp_path):
        # The real pre-PR-6 shape: non-daemon workers started with no
        # join path — wedged interpreter at exit, swallowed errors.
        res = lint(tmp_path, """
            import threading
            class PrefetchingIter:
                def __init__(self, n):
                    self.threads = []
                    for i in range(n):
                        t = threading.Thread(target=self._worker)
                        t.start()
                        self.threads.append(t)
                def _worker(self):
                    pass
            """, checks=["thread-lifecycle"])
        assert checks_of(res) == ["thread-lifecycle"]

    def test_daemon_kwarg_quiet(self, tmp_path):
        res = lint(tmp_path, """
            import threading
            threading.Thread(target=print, daemon=True).start()
            """, checks=["thread-lifecycle"])
        assert res.findings == []

    def test_daemon_attr_quiet(self, tmp_path):
        res = lint(tmp_path, """
            import threading
            def go():
                t = threading.Thread(target=print)
                t.daemon = True
                t.start()
            """, checks=["thread-lifecycle"])
        assert res.findings == []

    def test_join_path_quiet(self, tmp_path):
        res = lint(tmp_path, """
            import threading
            class W:
                def start(self):
                    self._thread = threading.Thread(target=self._run)
                    self._thread.start()
                def close(self):
                    self._thread.join(timeout=5)
                def _run(self):
                    pass
            """, checks=["thread-lifecycle"])
        assert res.findings == []


# ---------------------------------------------------------------------------
# telemetry-naming
# ---------------------------------------------------------------------------

class TestTelemetryNaming:
    def test_bad_family_prefix_fires(self, tmp_path):
        res = lint(tmp_path, """
            from mxnet_tpu.telemetry import metrics
            c = metrics.REGISTRY.counter("train_steps_total", "steps")
            """, checks=["telemetry-naming"])
        assert checks_of(res) == ["telemetry-naming"]

    def test_good_family_quiet(self, tmp_path):
        res = lint(tmp_path, """
            from mxnet_tpu.telemetry import metrics
            c = metrics.REGISTRY.counter("mx_train_steps_total", "steps")
            """, checks=["telemetry-naming"])
        assert res.findings == []

    def test_bare_span_name_fires(self, tmp_path):
        res = lint(tmp_path, """
            from mxnet_tpu.telemetry import trace
            def step():
                with trace.span("step"):
                    pass
            """, checks=["telemetry-naming"])
        assert checks_of(res) == ["telemetry-naming"]

    def test_span_format_template_followed(self, tmp_path):
        res = lint(tmp_path, """
            from mxnet_tpu.telemetry import trace
            def f(i):
                with trace.span("serving::bucket_%d" % i):
                    pass
                with trace.span("bucket_%d" % i):
                    pass
            """, checks=["telemetry-naming"])
        assert len(res.findings) == 1

    def test_conflicting_label_sets_fire(self, tmp_path):
        res = lint(tmp_path, """
            from mxnet_tpu.telemetry import metrics
            a = metrics.REGISTRY.counter("mx_foo_total", "x", labels=("site",))
            b = metrics.REGISTRY.counter("mx_foo_total", "x", labels=("rank",))
            """, checks=["telemetry-naming"])
        assert checks_of(res) == ["telemetry-naming"]
        assert "label" in res.findings[0].message

    def test_omitted_labels_is_empty_label_set(self, tmp_path):
        # The real API defaults labels=(): omitting it still conflicts
        # with a labeled registration of the same family.
        res = lint(tmp_path, """
            from mxnet_tpu.telemetry import metrics
            a = metrics.REGISTRY.counter("mx_foo_total", "x")
            b = metrics.REGISTRY.counter("mx_foo_total", "x", labels=("rank",))
            """, checks=["telemetry-naming"])
        assert checks_of(res) == ["telemetry-naming"]

    def test_same_label_set_quiet(self, tmp_path):
        res = lint(tmp_path, """
            from mxnet_tpu.telemetry import metrics
            a = metrics.REGISTRY.counter("mx_foo_total", "x", labels=("site",))
            b = metrics.REGISTRY.counter("mx_foo_total", "x", labels=("site",))
            """, checks=["telemetry-naming"])
        assert res.findings == []


# ---------------------------------------------------------------------------
# trace-propagation
# ---------------------------------------------------------------------------

class TestTracePropagation:
    def test_payload_without_ctx_fires(self, tmp_path):
        res = lint(tmp_path, """
            class KV:
                def push(self, key, value):
                    self._post(0, ("push", key, value))
            """, checks=["trace-propagation"])
        assert checks_of(res) == ["trace-propagation"]
        assert "push" in res.findings[0].message

    def test_inject_call_quiet(self, tmp_path):
        res = lint(tmp_path, """
            from mxnet_tpu.telemetry import xtrace as _xtrace
            class KV:
                def push(self, key, value):
                    self._post(0, ("push", key, value, _xtrace.inject()))
                def pull(self, key):
                    return self._call(0, ("pull", key, _xtrace.inject()))
            """, checks=["trace-propagation"])
        assert res.findings == []

    def test_forwarded_ctx_name_quiet(self, tmp_path):
        # Re-sending an already-extracted wire context (the server's
        # pull-reply echo shape) counts as carrying one.
        res = lint(tmp_path, """
            class KV:
                def forward(self, key, value, wire_ctx):
                    self._post(0, ("push_rsp", key, value, wire_ctx))
                def echo(self, state):
                    self._post(0, ("val", state.value, state.applied_ctx))
            """, checks=["trace-propagation"])
        assert res.findings == []

    def test_call_without_ctx_fires(self, tmp_path):
        res = lint(tmp_path, """
            class KV:
                def pull(self, key):
                    return self._call(0, ("pull", key))
            """, checks=["trace-propagation"])
        assert checks_of(res) == ["trace-propagation"]

    def test_opaque_payload_quiet(self, tmp_path):
        # A payload built elsewhere and passed by name is opaque — the
        # build site is where the tuple literal (and a finding) lives.
        res = lint(tmp_path, """
            class KV:
                def send(self, msg):
                    self._post(0, msg)
                def splice(self, head, rest):
                    self._post(0, ("cmd", *rest))
            """, checks=["trace-propagation"])
        assert res.findings == []

    def test_non_command_tuple_quiet(self, tmp_path):
        # Only command tuples (string head) are framing; a bare data
        # tuple is not a payload this rule owns.
        res = lint(tmp_path, """
            class KV:
                def send(self, a, b):
                    self._post(0, (a, b))
            """, checks=["trace-propagation"])
        assert res.findings == []

    def test_justified_suppression_honored(self, tmp_path):
        res = lint(tmp_path, """
            class KV:
                def ping(self):
                    # mxlint: disable=trace-propagation -- liveness
                    # probe, never part of a causal chain
                    self._post(0, ("ping",))
            """, checks=["trace-propagation"])
        assert res.findings == [] and res.suppressed == 1


# ---------------------------------------------------------------------------
# retrace-hazard
# ---------------------------------------------------------------------------

class TestRetraceHazard:
    def test_if_on_traced_arg_fires(self, tmp_path):
        res = lint(tmp_path, """
            from mxnet_tpu.compile import maybe_cached_jit
            def step(state, tokens):
                if tokens > 0:
                    return state + 1
                return state
            _step = maybe_cached_jit(step, "decode_step")
            """, checks=["retrace-hazard"])
        assert checks_of(res) == ["retrace-hazard"]
        assert "'tokens'" in res.findings[0].message

    def test_nested_closure_target_fires(self, tmp_path):
        # The dominant repo idiom: the pure fn is a closure built in
        # __init__ and handed to the jit seam by name.
        res = lint(tmp_path, """
            from mxnet_tpu.compile import maybe_cached_jit
            class Backend:
                def __init__(self, cfg):
                    def step_pure(params, x):
                        if x.sum() > 0:
                            return x * params
                        return x
                    self._step = maybe_cached_jit(step_pure, "s")
            """, checks=["retrace-hazard"])
        assert checks_of(res) == ["retrace-hazard"]

    def test_safe_predicates_quiet(self, tmp_path):
        # is-None pytree dispatch, isinstance/len, and static metadata
        # attributes are part of the trace SIGNATURE, not traced values.
        res = lint(tmp_path, """
            import jax
            def step(state, x, aux):
                if aux is None:
                    x = x + 1
                if isinstance(state, tuple) and len(state) > 1:
                    x = x * 2
                if x.ndim == 2 and x.shape[0] > 4:
                    x = x.sum(axis=0)
                if x.dtype == "float32" and not x.weak_type:
                    x = x * 3
                return state, x
            _f = jax.jit(step)
            """, checks=["retrace-hazard"])
        assert res.findings == []

    def test_static_argnames_exempt(self, tmp_path):
        res = lint(tmp_path, """
            import jax
            def step(x, mode):
                if mode == "train":
                    return x * 2
                return x
            _f = jax.jit(step, static_argnames=("mode",))
            """, checks=["retrace-hazard"])
        assert res.findings == []

    def test_jit_decorator_fires(self, tmp_path):
        res = lint(tmp_path, """
            import jax
            from functools import partial

            @jax.jit
            def f(x):
                if x > 0:
                    return x
                return -x

            @partial(jax.jit, static_argnums=(1,))
            def g(x, n):
                if n > 2:       # static by contract: quiet
                    x = x + 1
                return x
            """, checks=["retrace-hazard"])
        assert checks_of(res) == ["retrace-hazard"]
        assert res.findings[0].message.count("'x'") == 1

    def test_closure_and_free_names_quiet(self, tmp_path):
        # Branching on config captured by closure (not a traced arg)
        # is trace-time specialization by design.
        res = lint(tmp_path, """
            from mxnet_tpu.compile import maybe_cached_jit
            def build(cfg):
                def step(state, x):
                    if cfg.single_state:
                        return state + x
                    return tuple(s + x for s in state)
                return maybe_cached_jit(step, "site")
            """, checks=["retrace-hazard"])
        assert res.findings == []

    def test_justified_suppression_honored(self, tmp_path):
        res = lint(tmp_path, """
            from mxnet_tpu.compile import maybe_cached_jit
            def step(x):
                # mxlint: disable=retrace-hazard -- x is always a
                # concrete host scalar at this seam, two traces total
                if x > 0:
                    return x
                return -x
            _f = maybe_cached_jit(step, "site")
            """, checks=["retrace-hazard"])
        assert res.findings == [] and res.suppressed == 1


# ---------------------------------------------------------------------------
# suppressions
# ---------------------------------------------------------------------------

class TestSuppressions:
    def test_unjustified_suppression_is_a_finding(self, tmp_path):
        res = lint(tmp_path, """
            def save(path, blob):
                f = open(path, "wb")  # mxlint: disable=atomic-write
            """, checks=["atomic-write"])
        assert checks_of(res) == ["bad-suppression"]

    def test_next_line_comment_form(self, tmp_path):
        res = lint(tmp_path, """
            def save(path, blob):
                # mxlint: disable=atomic-write -- streaming writer,
                # append semantics are the API
                f = open(path, "wb")
            """, checks=["atomic-write"])
        assert res.findings == [] and res.suppressed == 1

    def test_wrong_check_name_does_not_suppress(self, tmp_path):
        res = lint(tmp_path, """
            def save(path, blob):
                f = open(path, "wb")  # mxlint: disable=lock-blocking -- nope
            """, checks=["atomic-write"])
        assert checks_of(res) == ["atomic-write"]

    def test_stacked_suppression_comments_merge(self, tmp_path):
        # Two whole-line disables for the same next code line: both
        # apply (neither silently shadows the other).
        res = lint(tmp_path, """
            import threading, time
            _lock = threading.Lock()
            def f(path):
                with _lock:
                    # mxlint: disable=lock-blocking -- fixture
                    # mxlint: disable=atomic-write -- fixture
                    open(path, "wb") and time.sleep(1)
            """, checks=["atomic-write", "lock-blocking"])
        assert res.findings == [] and res.suppressed == 2


# ---------------------------------------------------------------------------
# CLI + tree gate
# ---------------------------------------------------------------------------

class TestCli:
    def _run(self, *args, cwd=REPO):
        return subprocess.run(
            [sys.executable, "-m", "tools.mxlint", *args],
            cwd=cwd, capture_output=True, text=True, timeout=120)

    def test_json_output_stable_and_exit_codes(self, tmp_path):
        bad = tmp_path / "bad.py"
        bad.write_text('f = open("x", "wb")\n')
        proc = self._run("--format=json", str(bad))
        assert proc.returncode == 1
        out = json.loads(proc.stdout)
        assert out["version"] == 1
        assert out["counts"] == {"atomic-write": 1}
        assert [f["check"] for f in out["findings"]] == ["atomic-write"]
        # Byte-stable across runs (bench --compare-style diffing).
        assert proc.stdout == self._run("--format=json", str(bad)).stdout

    def test_check_subset_and_unknown_check(self, tmp_path):
        bad = tmp_path / "bad.py"
        bad.write_text('f = open("x", "wb")\n')
        assert self._run("--check=thread-lifecycle",
                         str(bad)).returncode == 0
        assert self._run("--check=nonsense", str(bad)).returncode == 2

    def test_check_subset_filters_secondary_kinds(self, tmp_path):
        # --check=lock-blocking must not report lock-order findings.
        p = tmp_path / "inv.py"
        p.write_text(textwrap.dedent("""
            import threading
            class A:
                def __init__(self):
                    self._a = threading.Lock()
                    self._b = threading.Lock()
                def f(self):
                    with self._a:
                        with self._b:
                            pass
                def g(self):
                    with self._b:
                        with self._a:
                            pass
            """))
        res = run_suite([str(p)], checks=["lock-blocking"],
                        root=str(tmp_path))
        assert res.findings == []

    def test_zero_files_is_loud(self, tmp_path):
        # A clean report that analyzed nothing must not exit 0 (wrong
        # cwd would otherwise green-light CI forever).
        empty = tmp_path / "empty"
        empty.mkdir()
        proc = self._run(str(empty))
        assert proc.returncode == 2
        assert "no .py files" in proc.stderr

    def test_relative_project_root_still_checks_catalogue(self, tmp_path):
        # A RELATIVE --project-root must not silently skip the env.py
        # catalogue-vs-README check (abspath normalization): seed an
        # undocumented knob and demand the finding surfaces.
        pkg = tmp_path / "mxnet_tpu"
        pkg.mkdir()
        (pkg / "env.py").write_text(textwrap.dedent("""
            from collections import namedtuple
            Knob = namedtuple("Knob", "name typ default where doc subsumed")
            CATALOGUE = [Knob("MXNET_HIDDEN", int, 1, "x", "d", False)]
            """))
        # One unrelated knob token: an entirely token-free README reads
        # as "no env table yet" and skips the check by design.
        (tmp_path / "README.md").write_text("| `MXNET_OTHER` | x |\n")
        proc = subprocess.run(
            [sys.executable, "-m", "tools.mxlint", "--project-root=.",
             "mxnet_tpu/env.py"],
            cwd=str(tmp_path), capture_output=True, text=True, timeout=120,
            env={**os.environ, "PYTHONPATH": REPO})
        assert proc.returncode == 1, proc.stdout + proc.stderr
        assert "MXNET_HIDDEN" in proc.stdout

    def test_syntax_error_reported_not_crash(self, tmp_path):
        bad = tmp_path / "broken.py"
        bad.write_text("def f(:\n")
        proc = self._run(str(bad))
        assert proc.returncode == 1
        assert "parse-error" in proc.stdout


def test_render_json_sorted(tmp_path):
    (tmp_path / "b.py").write_text('f = open("x", "wb")\n')
    (tmp_path / "a.py").write_text('g = open("y", "wb")\n')
    res = run_suite([str(tmp_path)], checks=["atomic-write"],
                    root=str(tmp_path))
    paths = [f.path for f in res.findings]
    assert paths == sorted(paths)
    json.loads(render_json(res))  # valid JSON


# ---------------------------------------------------------------------------
# stale-suppression
# ---------------------------------------------------------------------------

class TestStaleSuppression:
    def _lint(self, tmp_path, source):
        p = tmp_path / "mod.py"
        p.write_text(textwrap.dedent(source))
        return run_suite([str(p)], checks=["stale-suppression"],
                         root=str(tmp_path))

    def test_dead_symbol_fires(self, tmp_path):
        res = self._lint(tmp_path, """
            f = open("x", "wb")  # mxlint: disable=atomic-write -- safe: GhostWriter re-frames on read
            """)
        assert checks_of(res) == ["stale-suppression"]
        assert "GhostWriter" in res.findings[0].message
        assert res.findings[0].line == 2

    def test_live_symbol_quiet(self, tmp_path):
        res = self._lint(tmp_path, """
            class FrameWriter:
                pass
            f = open("x", "wb")  # mxlint: disable=atomic-write -- safe: FrameWriter re-frames on read
            """)
        assert res.findings == []

    def test_prose_only_justification_quiet(self, tmp_path):
        # No concrete references => nothing to audit. This rule grades
        # reference freshness, not writing style.
        res = self._lint(tmp_path, """
            f = open("x", "wb")  # mxlint: disable=atomic-write -- a barrier blocks by definition
            """)
        assert res.findings == []

    def test_dead_file_path_fires(self, tmp_path):
        res = self._lint(tmp_path, """
            f = open("x", "wb")  # mxlint: disable=atomic-write -- tools/vanished_helper.py tails this
            """)
        assert checks_of(res) == ["stale-suppression"]
        assert "tools/vanished_helper.py" in res.findings[0].message

    def test_live_file_path_quiet(self, tmp_path):
        tools = tmp_path / "tools"
        tools.mkdir()
        (tools / "tailer.py").write_text("pass\n")
        res = self._lint(tmp_path, """
            f = open("x", "wb")  # mxlint: disable=atomic-write -- tools/tailer.py tails this
            """)
        assert res.findings == []

    def test_continuation_comment_lines_are_part_of_the_why(self, tmp_path):
        # The justification spans comment-only follow-on lines (that's
        # how multi-line whys are written in-tree); a live reference on
        # a continuation line keeps the suppression fresh.
        res = self._lint(tmp_path, """
            def framed_append():
                pass
            # mxlint: disable=atomic-write -- incremental append is
            # the API: framed_append() recovers torn tails on read
            f = open("x", "wb")
            """)
        assert res.findings == []

    def test_one_live_reference_keeps_it_alive(self, tmp_path):
        # none-resolve rule: prose words that merely look like symbols
        # must not flag a justification that still cites something real.
        res = self._lint(tmp_path, """
            class FrameWriter:
                pass
            f = open("x", "wb")  # mxlint: disable=atomic-write -- FrameWriter took over from OldGhostPath
            """)
        assert res.findings == []

    def test_dead_knob_reference_fires(self, tmp_path):
        pkg = tmp_path / "mxnet_tpu"
        pkg.mkdir()
        (pkg / "env.py").write_text(textwrap.dedent("""
            from collections import namedtuple
            Knob = namedtuple("Knob", "name typ default where doc subsumed")
            CATALOGUE = [
                Knob("MXNET_LIVE_KNOB", int, 1, "x.py", "a knob", False),
            ]
            """))
        res = self._lint(tmp_path, """
            f = open("x", "wb")  # mxlint: disable=atomic-write -- MXNET_VANISHED_KNOB gates this path
            """)
        assert checks_of(res) == ["stale-suppression"]
        assert "MXNET_VANISHED_KNOB" in res.findings[0].message


def test_tree_is_clean():
    """The tier-1 gate: the full suite over mxnet_tpu/ is ZERO findings.

    A finding here is a real invariant violation (or a new intentional
    pattern needing a justified `# mxlint: disable=<check> -- why`
    suppression) — run `python -m tools.mxlint mxnet_tpu/` for the
    annotated report.
    """
    res = run_suite([os.path.join(REPO, "mxnet_tpu")], root=REPO)
    msgs = ["%s:%d: [%s] %s" % (f.path, f.line, f.check, f.message)
            for f in res.findings]
    assert not msgs, "mxlint findings on the tree:\n" + "\n".join(msgs)
    assert not res.errors, res.errors
    assert res.files > 150  # the walk actually covered the tree
