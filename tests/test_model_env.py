"""Legacy FeedForward API + env-var catalogue (reference: model.py
FeedForward, docs/faq/env_var.md)."""
import numpy as np

import mxnet_tpu as mx


def _mlp():
    data = mx.sym.var("data")
    net = mx.sym.FullyConnected(data, num_hidden=16, name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=2, name="fc2")
    return mx.sym.SoftmaxOutput(net, name="softmax")


def test_feedforward_fit_predict_score(tmp_path):
    rng = np.random.RandomState(0)
    X = rng.rand(120, 8).astype(np.float32)
    y = (X.sum(axis=1) > 4).astype(np.float32)
    # Uniform(0.01) default init + 60 adam updates is marginal for the
    # 0.85 bar (fails ~40% of seeds); Xavier + 40 epochs trains clear of
    # it while also exercising the initializer pass-through.
    model = mx.model.FeedForward(_mlp(), ctx=mx.cpu(), num_epoch=40,
                                 initializer=mx.init.Xavier(),
                                 optimizer="adam", learning_rate=0.01)
    model.fit(mx.io.NDArrayIter(X, y, batch_size=30, shuffle=True,
                                label_name="softmax_label"))
    acc = model.score(mx.io.NDArrayIter(X, y, batch_size=30,
                                        label_name="softmax_label"))
    assert acc > 0.85, "FeedForward accuracy %.3f" % acc
    preds = model.predict(mx.io.NDArrayIter(X, y, batch_size=30,
                                            label_name="softmax_label"))
    assert preds.shape == (120, 2)

    prefix = str(tmp_path / "ff")
    model.save(prefix, 1)
    loaded = mx.model.FeedForward.load(prefix, 1, ctx=mx.cpu())
    assert set(loaded.arg_params) == set(model.arg_params)


def test_env_catalogue():
    from mxnet_tpu import env

    table = env.describe()
    assert "MXNET_ENGINE_TYPE" in table
    assert "[subsumed]" in table
    assert env.get("MXNET_KVSTORE_BIGARRAY_BOUND") == 1000000
    import os

    os.environ["MXNET_KVSTORE_BIGARRAY_BOUND"] = "42"
    try:
        assert env.get("MXNET_KVSTORE_BIGARRAY_BOUND") == 42
    finally:
        del os.environ["MXNET_KVSTORE_BIGARRAY_BOUND"]


def test_log_and_libinfo():
    """Reference parity shims: mx.log.get_logger and mx.libinfo
    (python/mxnet/log.py, libinfo.py)."""
    import logging

    logger = mx.log.get_logger("mxtest", level=mx.log.INFO)
    assert logger.level == logging.INFO
    # idempotent: second call must not stack handlers
    again = mx.log.get_logger("mxtest", level=mx.log.DEBUG)
    assert again is logger and len(logger.handlers) == 1

    feats = mx.libinfo.features()
    assert feats["XLA"] and feats["SPMD"] and not feats["CUDA"]
    assert feats["DIST_KVSTORE"] and feats["BF16"]
    assert isinstance(mx.libinfo.find_lib_path(), list)
