"""Worker program for the 2-process fleet goodput acceptance test
(tests/test_goodput.py, launched via tools/launch.py roles — the
telemetry_dist_prog pattern).

Each rank runs a direct-mode GoodputLedger against the process-global
registry (so `mx_goodput_seconds_total{category}` is published), books
rank-distinct badput, and pushes snapshots through the dist kvstore's
telemetry channel. Rank 0 writes:

* ``scrape.txt``  — the merged exposition: per-rank goodput series AND
  the summed ``rank="all"`` series the counter merge adds.
* ``fleet.json``  — ``goodput.fleet_snapshot(aggregator.fleet)``: the
  pod-level categories/ratio the test cross-checks against the ranks'
  own committed ledger files.

Every rank also commits its durable ``goodput.rank<R>.json`` into the
shared directory, so the test can verify the fleet view and the ledger
files tell the same story.
"""
import json
import os
import sys
import time

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _ROOT)
sys.path.insert(0, os.path.join(_ROOT, "tools"))

import mxnet_tpu as mx                                 # noqa: E402
from mxnet_tpu.telemetry import aggregate, goodput     # noqa: E402


def main():
    out_dir = sys.argv[1]
    kv = mx.kvstore.create("dist_sync")
    rank = kv.rank

    ledger = goodput.GoodputLedger(directory=out_dir, rank=rank,
                                   interval_s=0.0)
    aggregator = aggregate.Aggregator(kv, interval_s=0.0)

    for i in range(5):
        time.sleep(0.002)
        ledger.observe_step(i, seconds=0.1)  # booked, not slept: exact
    # rank-distinct badput so per-rank series are tellable-apart
    ledger.book("compile" if rank == 0 else "input_stall",
                0.5 * (rank + 1))
    ledger.commit()                          # durable + publishes
    aggregator.step()                        # final push
    kv._barrier()                            # peers' pushes have landed

    if rank == 0:
        aggregator.step()                    # fold the landed pushes
        fleet = goodput.fleet_snapshot(aggregator.fleet)
        with open(os.path.join(out_dir, "scrape.txt"), "w") as f:
            f.write(aggregator.render_prometheus())
        with open(os.path.join(out_dir, "fleet.json"), "w") as f:
            json.dump(fleet, f)
    ledger.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
