"""Goodput ledger (ISSUE 20): MECE wall-clock accounting, durability
across SIGKILL, rank-0 fleet aggregation, and the read surfaces
(/debug/goodput, flight-recorder bundles, tools/goodput_report.py)
all rendering the same ledger."""
import json
import os
import socket
import subprocess
import sys

import pytest

from mxnet_tpu import telemetry
from mxnet_tpu.telemetry import aggregate, goodput
from mxnet_tpu.telemetry import metrics as tmetrics

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(_ROOT, "tools"))
from launch import launch_local  # noqa: E402


class _FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t


def _ledger(tmp_path=None, **kw):
    kw.setdefault("registry", tmetrics.Registry())
    kw.setdefault("interval_s", 0.0)
    return goodput.GoodputLedger(
        directory=str(tmp_path) if tmp_path is not None else None,
        rank=kw.pop("rank", 0), **kw)


# -- taxonomy + closure -------------------------------------------------------

def test_direct_mode_books_steps_and_derives_idle():
    clock = _FakeClock()
    led = _ledger(clock=clock)
    for i in range(4):
        clock.t += 1.0
        led.observe_step(i, seconds=1.0)
    led.book("checkpoint", 0.5)
    clock.t += 1.0                    # 0.5 checkpointing + 0.5 nothing
    snap = led.snapshot(serving=False)
    assert snap["wall_s"] == pytest.approx(5.0)
    assert snap["categories"]["device_compute"] == pytest.approx(4.0)
    assert snap["categories"]["checkpoint"] == pytest.approx(0.5)
    assert snap["categories"]["idle"] == pytest.approx(0.5)
    assert snap["goodput_ratio"] == pytest.approx(0.8)
    assert snap["closure_pct"] == 0.0 and snap["closure_ok"]
    # MECE: categories sum exactly to wall (idle is the derived rest)
    assert sum(snap["categories"].values()) == pytest.approx(
        snap["wall_s"])


def test_closure_detects_overcount_only():
    clock = _FakeClock()
    led = _ledger(clock=clock)
    clock.t += 1.0
    led.book("compile", 1.5)          # overcounts wall by 0.5s
    snap = led.snapshot(serving=False)
    assert snap["categories"]["idle"] == 0.0   # clamped, never negative
    assert snap["closure_pct"] == pytest.approx(50.0)
    assert not snap["closure_ok"]


def test_book_rejects_idle_and_unknown():
    led = _ledger()
    with pytest.raises(ValueError):
        led.book("idle", 1.0)         # derived — booking it would hide
    with pytest.raises(ValueError):   # double-counting
        led.book("naps", 1.0)


# -- attribution-mode folding -------------------------------------------------

class _StubAttr:
    def update(self):
        pass


def test_fold_maps_phases_and_deoverlaps_compile():
    clock = _FakeClock()
    reg = tmetrics.Registry()
    phase = reg.counter("mx_step_phase_seconds",
                        "per-phase step seconds", labels=("phase",))
    compile_h = reg.histogram("mx_compile_seconds", "compile seconds",
                              labels=("site",))
    led = _ledger(registry=reg, clock=clock, attribution=_StubAttr())
    # One attributed window: 6s compute, 1s data wait, 0.5s h2d,
    # 0.5s allreduce, 2s dispatch/other — of which 1.5s was really a
    # compile (recorded at the jit seam) that must not double-book.
    phase.labels(phase="device_compute").inc(6.0)
    phase.labels(phase="data_wait").inc(1.0)
    phase.labels(phase="h2d").inc(0.5)
    phase.labels(phase="allreduce").inc(0.5)
    phase.labels(phase="dispatch").inc(0.5)
    phase.labels(phase="other").inc(1.5)
    compile_h.labels(site="train_step").observe(1.5)
    clock.t += 10.0
    snap = led.update()
    cats = snap["categories"]
    assert cats["device_compute"] == pytest.approx(6.0)
    assert cats["input_stall"] == pytest.approx(1.0)
    assert cats["h2d"] == pytest.approx(0.5)
    assert cats["exposed_comm"] == pytest.approx(0.5)
    assert cats["compile"] == pytest.approx(1.5)
    assert cats["other"] == pytest.approx(0.5)  # 2.0 pool - 1.5 compile
    assert cats["idle"] == pytest.approx(0.0)
    assert snap["closure_pct"] == 0.0


def test_cursors_ignore_history_before_construction():
    reg = tmetrics.Registry()
    phase = reg.counter("mx_step_phase_seconds", "x", labels=("phase",))
    phase.labels(phase="device_compute").inc(100.0)   # pre-ledger past
    clock = _FakeClock()
    led = _ledger(registry=reg, clock=clock, attribution=_StubAttr())
    phase.labels(phase="device_compute").inc(2.0)
    clock.t += 2.0
    snap = led.update()
    assert snap["categories"]["device_compute"] == pytest.approx(2.0)


def test_exposed_comm_is_reduce_minus_hidden():
    reg = tmetrics.Registry()
    red = reg.counter("mx_trainer_reduce_seconds_total", "x")
    hid = reg.counter("mx_trainer_reduce_hidden_seconds_total", "x")
    clock = _FakeClock()
    led = _ledger(registry=reg, clock=clock, attribution=_StubAttr())
    red.inc(3.0)
    hid.inc(2.0)
    clock.t += 4.0
    snap = led.update()
    assert snap["categories"]["exposed_comm"] == pytest.approx(1.0)


def test_watchdog_fired_books_hang_recovery():
    class _WD:
        fired = [("step", "hang", 9.0)]   # consumed pre-construction

    clock = _FakeClock()
    led = _ledger(clock=clock, watchdog=_WD())
    _WD.fired.append(("data#0", "hang", 3.0))
    clock.t += 5.0
    snap = led.update()
    assert snap["categories"]["hang_recovery"] == pytest.approx(3.0)


# -- durability + replay ------------------------------------------------------

def test_commit_resume_baseline_roundtrip(tmp_path):
    clock = _FakeClock()
    led = _ledger(tmp_path, clock=clock)
    for i in range(3):
        clock.t += 1.0
        led.observe_step(i, seconds=1.0)
    path = led.commit()
    assert path and os.path.exists(path)
    assert os.path.basename(path) == goodput.ledger_name(0)

    led2 = _ledger(tmp_path, clock=clock)
    assert led2.loaded_last_step == 2
    snap = led2.snapshot(serving=False)
    assert snap["categories"]["device_compute"] == pytest.approx(3.0)
    assert snap["wall_s"] == pytest.approx(3.0)


def test_replay_window_books_restart_replay(tmp_path):
    clock = _FakeClock()
    led = _ledger(tmp_path, clock=clock)
    for i in range(5):
        clock.t += 1.0
        led.observe_step(i, seconds=1.0)
    led.commit()                       # last committed step: 4

    led2 = _ledger(tmp_path, clock=clock)
    assert led2.resume_from(2) == 4    # replay watermark armed
    for i in range(3, 8):
        clock.t += 1.0
        led2.observe_step(i, seconds=1.0)
    snap = led2.snapshot(serving=False)
    assert snap["restart_replay_steps"] == 2          # steps 3, 4
    assert snap["categories"]["restart_replay"] == pytest.approx(2.0)
    assert snap["categories"]["device_compute"] == pytest.approx(
        5.0 + 3.0)                     # baseline + steps 5..7
    assert snap["resumes"] == 1
    assert not snap["replaying"]


def test_corrupt_ledger_starts_fresh(tmp_path):
    p = tmp_path / goodput.ledger_name(0)
    p.write_text("{not json")
    led = _ledger(tmp_path)
    assert led.loaded_last_step is None
    snap = led.snapshot(serving=False)
    assert snap["categories"]["device_compute"] == 0.0


def test_commit_failure_warns_keeps_running(tmp_path, monkeypatch):
    led = _ledger(tmp_path)
    led.observe_step(0, seconds=0.1)
    from mxnet_tpu.telemetry import export

    def boom(path, data):
        raise OSError("disk full")

    monkeypatch.setattr(export, "commit_bytes", boom)
    assert led.commit() is None        # warned, not raised
    led.observe_step(1, seconds=0.1)   # ledger still books


def test_tick_respects_cadence(tmp_path):
    clock = _FakeClock()
    led = _ledger(tmp_path, interval_s=30.0, clock=clock)
    assert led.tick(step=0) is not None      # first tick commits
    clock.t += 1.0
    assert led.tick(step=1) is None          # within cadence
    clock.t += 30.0
    assert led.tick(step=2) is not None


# -- metric publication -------------------------------------------------------

def test_published_counters_monotonic_and_match_snapshot():
    clock = _FakeClock()
    reg = tmetrics.Registry()
    led = _ledger(registry=reg, clock=clock)
    clock.t += 2.0
    led.observe_step(0, seconds=1.5)
    led.update()
    fam = reg.get("mx_goodput_seconds_total")
    dc = fam.labels(category="device_compute")
    idle = fam.labels(category="idle")
    assert dc.value == pytest.approx(1.5)
    assert idle.value == pytest.approx(0.5)
    # a later fold claims previously-idle seconds: the idle counter is
    # a high-watermark (documented), it must not move backward
    led.book("checkpoint", 0.4)
    led.update()
    assert idle.value == pytest.approx(0.5)
    assert fam.labels(category="checkpoint").value == pytest.approx(0.4)
    wall = reg.get("mx_goodput_wall_seconds_total").labels()
    assert wall.value == pytest.approx(2.0)
    assert reg.get("mx_goodput_ratio").labels().value == pytest.approx(
        0.75)


# -- serving analog -----------------------------------------------------------

def test_serving_snapshot_none_without_serving_families():
    assert goodput.serving_snapshot(tmetrics.Registry()) is None


def test_serving_snapshot_padding_shed_and_slot_idle():
    reg = tmetrics.Registry()
    rows = reg.counter("mx_serving_gateway_rows_total", "x",
                       labels=("model",))
    batches = reg.counter("mx_serving_gateway_batches_total", "x",
                          labels=("model", "bucket"))
    shed = reg.counter("mx_serving_gateway_shed_total", "x",
                       labels=("model", "reason", "deadline_class"))
    occ = reg.gauge("mx_decode_slot_occupancy", "x", labels=("model",))
    slots = reg.gauge("mx_decode_slots", "x", labels=("model",))
    rows.labels(model="m").inc(12)
    batches.labels(model="m", bucket="8").inc(2)     # capacity 16
    shed.labels(model="m", reason="queue_full",
                deadline_class="batch").inc(3)
    occ.labels(model="m").set(2)
    slots.labels(model="m").set(8)
    s = goodput.serving_snapshot(reg)
    gw = s["gateway"]
    assert gw["rows_total"] == 12
    assert gw["padded_rows_total"] == pytest.approx(4)
    assert gw["padding_fraction"] == pytest.approx(4 / 16)
    assert gw["shed"] == {"queue_full": 3}
    dec = s["decode"]
    assert dec["models"]["m"]["idle_fraction"] == pytest.approx(0.75)
    assert dec["idle_fraction"] == pytest.approx(0.75)


# -- fleet aggregation (in-process) -------------------------------------------

def test_fleet_merge_sums_counters_and_rank_all():
    clock = _FakeClock()
    bus = aggregate.LocalBus(num_workers=2, clock=clock)
    regs, aggs = [], []
    for r in (0, 1):
        reg = tmetrics.Registry()
        led = goodput.GoodputLedger(rank=r, interval_s=0.0,
                                    registry=reg, clock=clock)
        regs.append((reg, led))
        aggs.append(aggregate.Aggregator(bus.endpoint(r), registry=reg,
                                         interval_s=0.0, clock=clock))
    clock.t += 2.0
    for r, (reg, led) in enumerate(regs):
        led.observe_step(0, seconds=1.0 + r)     # rank1 books 2s
        led.update()
    aggs[1].step()
    aggs[0].step()
    fleet = goodput.fleet_snapshot(aggs[0].fleet)
    assert set(fleet["ranks"]) == {"0", "1"}
    assert fleet["ranks"]["0"]["device_compute"] == pytest.approx(1.0)
    assert fleet["ranks"]["1"]["device_compute"] == pytest.approx(2.0)
    assert fleet["all"]["device_compute"] == pytest.approx(3.0)
    assert fleet["wall_all_s"] == pytest.approx(4.0)
    assert fleet["goodput_ratio"] == pytest.approx(3.0 / 4.0)
    text = aggs[0].render_prometheus()
    assert ('mx_goodput_seconds_total{category="device_compute",'
            'rank="all"}') in text
    assert ('mx_goodput_seconds_total{category="device_compute",'
            'rank="1"} 2') in text


def test_fleet_snapshot_none_before_any_publication():
    assert goodput.fleet_snapshot(None) is None
    assert goodput.fleet_snapshot(tmetrics.Registry()) is None


# -- read surfaces render the same ledger -------------------------------------

def test_debug_goodput_bundle_and_cli_render_same_numbers(tmp_path):
    from mxnet_tpu.telemetry import healthplane as hp
    from mxnet_tpu.telemetry import recorder as rec

    clock = _FakeClock()
    led = _ledger(tmp_path, clock=clock)
    clock.t += 2.0
    led.observe_step(0, seconds=1.0)
    led.book("compile", 0.5)
    path = led.commit()
    goodput.install(led)
    try:
        plane = hp.HealthPlane()
        status, body = plane.handle("GET", "/debug/goodput")
        assert status == 200
        assert body["categories"]["device_compute"] == pytest.approx(
            1.0)

        recorder = rec.FlightRecorder(str(tmp_path / "bundles"))
        bpath = recorder.capture(kind="manual")
        with open(bpath) as f:
            bundle = json.load(f)
        assert bundle["goodput"]["categories"]["compile"] == \
            pytest.approx(0.5)

        out = subprocess.run(
            [sys.executable, os.path.join(_ROOT, "tools",
                                          "goodput_report.py"),
             "summary", path],
            capture_output=True, text=True, cwd=_ROOT, timeout=120)
        assert out.returncode == 0, out.stderr
        assert "device_compute" in out.stdout
        # all three surfaces agree on the ratio from the same ledger
        ratio = body["goodput_ratio"]
        assert bundle["goodput"]["goodput_ratio"] == pytest.approx(
            ratio)
        assert ("%.1f %%" % (ratio * 100.0)) in out.stdout
    finally:
        goodput.uninstall(led)


def test_debug_goodput_404_without_ledger():
    from mxnet_tpu.telemetry import healthplane as hp

    assert goodput.active_ledger() is None
    status, body = hp.HealthPlane().handle("GET", "/debug/goodput")
    assert status == 404 and "error" in body


def test_report_cli_merge_and_compare(tmp_path):
    clock = _FakeClock()
    paths = []
    for r in (0, 1):
        led = goodput.GoodputLedger(directory=str(tmp_path), rank=r,
                                    interval_s=0.0,
                                    registry=tmetrics.Registry(),
                                    clock=clock)
        clock.t += 1.0
        led.observe_step(0, seconds=0.5 * (r + 1))
        paths.append(led.commit())

    def run(*argv):
        out = subprocess.run(
            [sys.executable,
             os.path.join(_ROOT, "tools", "goodput_report.py")]
            + list(argv),
            capture_output=True, text=True, cwd=_ROOT, timeout=120)
        assert out.returncode == 0, out.stderr
        return out.stdout

    merged = run("merge", *paths)
    assert "2 ranks merged" in merged
    # merge is the file analog of the fleet counter sum
    assert "1.500" in merged               # 0.5 + 1.0 device seconds
    cmp_out = run("compare", paths[0], paths[1])
    assert "goodput ratio" in cmp_out and "device_compute" in cmp_out


# -- SIGKILL mid-epoch resume (acceptance) ------------------------------------

_RESUME_PROG = os.path.join(_ROOT, "tests", "goodput_resume_prog.py")
_FLEET_PROG = os.path.join(_ROOT, "tests", "goodput_fleet_prog.py")
_ENV = {
    "JAX_PLATFORMS": "cpu",
    "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
}


def _run_prog(tmp_path, mode, expect):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, _RESUME_PROG, "--dir", str(tmp_path),
         "--mode", mode, "--steps", "14", "--kill-after", "8",
         "--ckpt-every", "3"],
        env=env, cwd=_ROOT, timeout=180)
    assert proc.returncode in expect, proc.returncode


def test_sigkill_resume_books_restart_replay(tmp_path):
    """ISSUE 20 acceptance: SIGKILL mid-epoch, resume from the
    checkpoint, and the new incarnation books the re-run steps as
    restart_replay within one step of the true gap."""
    _run_prog(tmp_path, "kill", {-9})
    # kill-after=8: ledger committed through step 7; ckpt-every=3:
    # restore lands at step 5 -> true replay gap = 2 steps (6, 7).
    prior = goodput.load_ledger(
        os.path.join(str(tmp_path), goodput.ledger_name(0)))
    true_gap = prior["last_step"] - 5
    assert true_gap == 2

    _run_prog(tmp_path, "resume", {0})
    with open(os.path.join(str(tmp_path), "result.json")) as f:
        result = json.load(f)
    assert abs(result["restart_replay_steps"] - true_gap) <= 1
    assert result["categories"]["restart_replay"] > 0.0
    assert result["resumes"] == 1
    assert result["last_step"] == 13
    # the durable file agrees with the in-process snapshot
    final = goodput.load_ledger(
        os.path.join(str(tmp_path), goodput.ledger_name(0)))
    assert final["restart_replay_steps"] == \
        result["restart_replay_steps"]


# -- 2-process fleet ledger (acceptance) --------------------------------------

def _can_bind_localhost():
    try:
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        s.close()
        return True
    except OSError:
        return False


def test_two_process_fleet_ledger(tmp_path):
    """ISSUE 20 acceptance: a 2-process dist job yields one rank-0
    fleet view with per-rank goodput series, the summed rank="all"
    series, and per-rank durable ledger files that agree with it."""
    if not _can_bind_localhost():
        pytest.skip("localhost sockets unavailable (multi-process "
                    "kvstore needs them)")
    codes = launch_local(
        2, 1, [sys.executable, _FLEET_PROG, str(tmp_path)],
        env_extra=_ENV, timeout=300)
    assert codes == [0, 0], codes

    text = (tmp_path / "scrape.txt").read_text()
    for rank in (0, 1):
        assert ('mx_goodput_seconds_total{category="device_compute",'
                'rank="%d"} 0.5' % rank) in text, text
    assert ('mx_goodput_seconds_total{category="device_compute",'
            'rank="all"} 1') in text
    assert ('mx_goodput_seconds_total{category="compile",rank="0"} 0.5'
            in text)
    assert ('mx_goodput_seconds_total{category="input_stall",'
            'rank="1"} 1') in text

    with open(os.path.join(str(tmp_path), "fleet.json")) as f:
        fleet = json.load(f)
    assert set(fleet["ranks"]) == {"0", "1"}
    assert fleet["all"]["device_compute"] == pytest.approx(1.0)
    assert fleet["all"]["compile"] == pytest.approx(0.5)
    assert fleet["all"]["input_stall"] == pytest.approx(1.0)

    # the durable per-rank files tell the same story as the fleet view
    for rank in (0, 1):
        led = goodput.load_ledger(os.path.join(
            str(tmp_path), goodput.ledger_name(rank)))
        assert led["categories"]["device_compute"] == pytest.approx(
            0.5)
        assert led["categories"][
            "compile" if rank == 0 else "input_stall"] == \
            pytest.approx(0.5 * (rank + 1))
