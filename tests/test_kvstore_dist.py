"""Distributed kvstore: real multi-process parameter-server traffic on one
host (the reference's tests/nightly/dist_sync_kvstore.py pattern via
tools/launch.py --launcher local).

Each case spawns scheduler + 2 servers + 2 workers; workers run the
numerical equality checks in tests/dist_prog.py and their exit codes are
asserted here. MXNET_KVSTORE_BIGARRAY_BOUND is lowered so the big key
exercises cross-server sharding without megabyte payloads.
"""
import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tools"))

from launch import launch_local  # noqa: E402

_PROG = os.path.join(os.path.dirname(os.path.abspath(__file__)), "dist_prog.py")

_ENV = {
    "JAX_PLATFORMS": "cpu",
    "MXNET_KVSTORE_BIGARRAY_BOUND": "4000",
    # Workers need only a couple of virtual devices; keep spawn cheap.
    "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
}


def _run(kv_type, num_workers=2, num_servers=2, mode="kvstore"):
    codes = launch_local(
        num_workers, num_servers,
        [sys.executable, _PROG, "--kv-type", kv_type, "--mode", mode],
        env_extra=_ENV, timeout=300)
    assert codes == [0] * num_workers, \
        "worker exit codes for %s: %s" % (kv_type, codes)


def test_dist_sync_kvstore():
    _run("dist_sync")


def test_dist_device_sync_kvstore():
    _run("dist_device_sync")


def test_dist_async_kvstore():
    _run("dist_async")


def test_dist_failure_detection():
    """A worker that dies without finalize is reported by get_dead_nodes
    and breaks barriers loudly instead of hanging (reference: ps-lite
    heartbeats -> GetDeadNodes, kvstore_dist.h:121-123)."""
    _run("dist_sync", mode="failure")


def test_dist_overlapped_fused_training():
    """ISSUE 13 acceptance: the overlapped bucketed reduce->apply over
    a REAL 2-process parameter-server store — ranks end identical and
    match a single-process serial reference bit-for-bit-close."""
    _run("dist_sync", mode="overlap")


@pytest.mark.parametrize("gc_type", ["2bit", "1bit"])
def test_dist_compression_composes_with_bucketed_fusion(gc_type):
    """2bit/1bit gradient compression rides the coalesced flat-bucket
    path: per-bucket error-feedback residuals survive across steps and
    ranks stay weight-identical."""
    env = dict(_ENV, MXNET_TEST_GC_TYPE=gc_type)
    codes = launch_local(
        2, 2, [sys.executable, _PROG, "--kv-type", "dist_sync",
               "--mode", "overlap_compressed"],
        env_extra=env, timeout=300)
    assert codes == [0, 0], codes


def test_dist_sync_training():
    """Gluon Trainer end-to-end over dist_sync: optimizer-on-server,
    per-worker shards, identical weights across workers."""
    _run("dist_sync", mode="train")


def test_two_bit_compression_codec():
    """Codec unit test (reference tests/nightly/test_kvstore.py
    compute_expected_2bit_quantization)."""
    from mxnet_tpu.gradient_compression import GradientCompression

    gc = GradientCompression({"type": "2bit", "threshold": 0.5})
    grad = np.array([[0.7, -0.9, 0.1], [-0.2, 0.55, -3.0]], dtype=np.float32)
    packed, meta = gc.compress("k", grad)
    # 4x compression on the wire (2 bits/elem, byte-packed).
    assert len(packed) == (grad.size + 3) // 4
    dec = GradientCompression.decompress(packed, meta)
    expected = np.where(grad >= 0.5, 0.5, np.where(grad <= -0.5, -0.5, 0.0))
    np.testing.assert_allclose(dec, expected)
    # Error feedback invariant: residual == accumulated-input minus
    # accumulated-output after every round, so nothing is ever lost.
    np.testing.assert_allclose(gc._residual["k"], grad - dec, atol=1e-6)
    packed2, meta2 = gc.compress("k", grad)
    dec2 = GradientCompression.decompress(packed2, meta2)
    np.testing.assert_allclose(gc._residual["k"], 2 * grad - dec - dec2,
                               atol=1e-6)
    # A saturated element (|g| >> t) keeps transferring ±t every round.
    assert dec2[1, 2] == -0.5


def test_server_restart_recovery(tmp_path):
    """Kill -9 a parameter server mid-training; a replacement started
    with DMLC_SERVER_RECOVERY restores its snapshot and rejoins; the
    worker reconnects through the scheduler and training continues
    (reference: server-side is_recovery, kvstore_dist.h:52-55)."""
    import subprocess
    import time

    from launch import _free_port

    port = _free_port()
    marker_dir = str(tmp_path)
    base = dict(os.environ, **_ENV)
    base.update({
        "DMLC_PS_ROOT_URI": "127.0.0.1",
        "DMLC_PS_ROOT_PORT": str(port),
        "DMLC_NUM_WORKER": "1",
        "DMLC_NUM_SERVER": "1",
        "MXNET_PS_SNAPSHOT_DIR": marker_dir,
        "MXNET_TEST_MARKER_DIR": marker_dir,
    })
    cmd = [sys.executable, _PROG, "--kv-type", "dist_sync",
           "--mode", "server_restart"]

    def spawn(role, extra=None):
        env = dict(base, DMLC_ROLE=role)
        env.update(extra or {})
        return subprocess.Popen(cmd, env=env)

    sched = spawn("scheduler")
    server = spawn("server")
    worker = spawn("worker")
    try:
        deadline = time.time() + 180
        while not os.path.exists(os.path.join(marker_dir, "phase1_done")):
            assert time.time() < deadline, "worker never finished phase 1"
            assert worker.poll() is None, "worker died in phase 1"
            time.sleep(0.2)
        server.kill()                      # SIGKILL: no goodbye
        server.wait(timeout=30)
        server = spawn("server", {"DMLC_SERVER_RECOVERY": "0"})
        open(os.path.join(marker_dir, "server_restarted"), "w").close()
        assert worker.wait(timeout=180) == 0, "worker failed after restart"
    finally:
        for p in (worker, server, sched):
            if p.poll() is None:
                p.terminate()
        for p in (worker, server, sched):
            try:
                p.wait(timeout=20)
            except subprocess.TimeoutExpired:
                p.kill()


def test_server_profiling_command():
    """Workers toggle the SERVERS' profiler through the kvstore command
    channel and pull back server-side op-span tables (reference
    KVStoreServerProfilerCommand + tests/nightly/test_server_profiling.py)."""
    _run("dist_sync", mode="server_profiling")
