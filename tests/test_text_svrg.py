"""contrib.text (vocabulary/embeddings, reference
python/mxnet/contrib/text/) and contrib.svrg_optimization (SVRGModule,
reference python/mxnet/contrib/svrg_optimization/)."""
import collections

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu.contrib import text
from mxnet_tpu.contrib.svrg_optimization import SVRGModule


# -- text.vocab ---------------------------------------------------------------

def test_vocabulary_ordering_and_thresholds():
    counter = collections.Counter(
        ["b", "b", "b", "a", "a", "c", "c", "c", "c", "rare"])
    v = text.Vocabulary(counter, min_freq=2, unknown_token="<unk>",
                        reserved_tokens=["<pad>"])
    # unk=0, reserved next, then freq desc with alpha tie-break
    assert v.idx_to_token == ["<unk>", "<pad>", "c", "b", "a"]
    assert v.to_indices("c") == 2
    assert v.to_indices(["a", "zzz"]) == [4, 0]
    assert v.to_tokens([2, 3]) == ["c", "b"]
    assert len(v) == 5
    # most_freq_count cap
    v2 = text.Vocabulary(counter, most_freq_count=1)
    assert v2.idx_to_token == ["<unk>", "c"]


def test_vocabulary_validation():
    import pytest

    with pytest.raises(ValueError):
        text.Vocabulary(min_freq=0)
    with pytest.raises(ValueError):
        text.Vocabulary(reserved_tokens=["<unk>"])
    with pytest.raises(ValueError):
        text.Vocabulary(reserved_tokens=["a", "a"])


def test_count_tokens_from_str():
    c = text.utils.count_tokens_from_str("Life is Life\nis good",
                                         to_lower=True)
    assert c == collections.Counter(
        {"life": 2, "is": 2, "good": 1})


# -- text.embedding -----------------------------------------------------------

def _write_embedding_file(path):
    with open(path, "w") as f:
        f.write("hello 0.1 0.2 0.3\n")
        f.write("world 1.0 2.0 3.0\n")
        f.write("tpu 7.0 8.0 9.0\n")
    return str(path)


def test_custom_embedding_loads_and_queries(tmp_path):
    fname = _write_embedding_file(tmp_path / "emb.txt")
    emb = text.embedding.CustomEmbedding(fname)
    assert emb.vec_len == 3
    assert len(emb) == 4                       # <unk> + 3 tokens
    np.testing.assert_allclose(
        emb.get_vecs_by_tokens("world").asnumpy(), [1.0, 2.0, 3.0])
    # unknown -> zeros (init_unknown_vec default)
    np.testing.assert_allclose(
        emb.get_vecs_by_tokens("missing").asnumpy(), [0, 0, 0])
    two = emb.get_vecs_by_tokens(["hello", "tpu"]).asnumpy()
    np.testing.assert_allclose(two, [[0.1, 0.2, 0.3], [7, 8, 9]],
                               rtol=1e-6)
    assert emb.idx_to_vec.shape == (4, 3)


def test_embedding_update_and_registry(tmp_path):
    fname = _write_embedding_file(tmp_path / "emb.txt")
    emb = text.embedding.create("customembedding",
                                pretrained_file_path=fname)
    emb.update_token_vectors("hello", mx.nd.array([[9., 9., 9.]]))
    np.testing.assert_allclose(
        emb.get_vecs_by_tokens("hello").asnumpy(), [9, 9, 9])
    import pytest

    with pytest.raises(ValueError):
        emb.update_token_vectors("nope", mx.nd.array([[1., 2., 3.]]))
    names = text.embedding.get_pretrained_file_names()
    assert "glove" in names and "fasttext" in names


def test_composite_embedding_with_vocabulary(tmp_path):
    f1 = _write_embedding_file(tmp_path / "e1.txt")
    with open(tmp_path / "e2.txt", "w") as f:
        f.write("hello 5 5\nmars 6 6\n")
    e1 = text.embedding.CustomEmbedding(f1)
    e2 = text.embedding.CustomEmbedding(str(tmp_path / "e2.txt"))
    vocab = text.Vocabulary(collections.Counter(
        ["hello", "hello", "mars"]))
    comp = text.embedding.CompositeEmbedding(vocab, [e1, e2])
    assert comp.vec_len == 5
    got = comp.get_vecs_by_tokens("hello").asnumpy()
    np.testing.assert_allclose(got, [0.1, 0.2, 0.3, 5, 5], rtol=1e-6)
    # token present in vocab but only in one source: other half zeros
    got = comp.get_vecs_by_tokens("mars").asnumpy()
    np.testing.assert_allclose(got, [0, 0, 0, 6, 6])


# -- svrg ---------------------------------------------------------------------

def _linreg_symbol():
    data = mx.sym.var("data")
    out = mx.sym.FullyConnected(data, num_hidden=1, name="fc")
    return mx.sym.LinearRegressionOutput(out, name="lin_reg")


def test_svrg_module_converges_and_reduces_variance():
    rng = np.random.RandomState(0)
    n = 64
    X = rng.rand(n, 4).astype(np.float32)
    w_true = np.array([1.5, -2.0, 0.5, 3.0], np.float32)
    y = X @ w_true + 0.01 * rng.randn(n).astype(np.float32)
    it = mx.io.NDArrayIter(X, y.reshape(-1, 1), batch_size=16,
                           shuffle=True, label_name="lin_reg_label")

    mod = SVRGModule(_linreg_symbol(), data_names=("data",),
                     label_names=("lin_reg_label",), update_freq=2)
    mod.fit(it, num_epoch=30, optimizer="sgd",
            optimizer_params={"learning_rate": 0.5},
            eval_metric="mse")
    arg, _ = mod.get_params()
    w = arg["fc_weight"].asnumpy().ravel()
    np.testing.assert_allclose(w, w_true, atol=0.25)


def test_svrg_full_grads_match_batch_mean():
    """The stored full gradient equals the mean of per-batch gradients
    computed at the snapshot weights."""
    rng = np.random.RandomState(1)
    X = rng.rand(32, 3).astype(np.float32)
    y = X.sum(axis=1, keepdims=True).astype(np.float32)
    it = mx.io.NDArrayIter(X, y, batch_size=8,
                           label_name="lin_reg_label")
    mod = SVRGModule(_linreg_symbol(), data_names=("data",),
                     label_names=("lin_reg_label",), update_freq=1)
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod.init_params()
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.0})
    mod.update_full_grads(it)
    full = mod._param_dict["fc_weight"].asnumpy()

    # manual mean of batch grads through the plain Module path
    it.reset()
    acc, nb = 0, 0
    for batch in it:
        mod._mod_aux.forward(batch, is_train=True)
        mod._mod_aux.backward()
        acc = acc + mod._mod_aux._execs[0].grad_dict["fc_weight"].asnumpy()
        nb += 1
    np.testing.assert_allclose(full, acc / nb, rtol=1e-5, atol=1e-6)


def test_svrg_optimizer_routing():
    from mxnet_tpu.contrib.svrg_optimization import _SVRGOptimizer

    opt = _SVRGOptimizer("sgd", learning_rate=0.5,
                         param_idx2name={0: "w", 1: "w_full"})
    w = mx.nd.array([1.0])
    g = mx.nd.array([0.5])
    st = opt.create_state(0, w)
    opt.update(0, w, g, st)
    # sgd with rescale 1: w -= lr * g  (no wd)
    np.testing.assert_allclose(w.asnumpy(), [0.75])
    wf = mx.nd.array([1.0])
    gf = mx.nd.array([0.125])
    opt.update(1, wf, gf, opt.create_state(1, wf))
    np.testing.assert_allclose(wf.asnumpy(), [0.125])  # assignment
