"""End-to-end causal tracing (ISSUE 18 tentpole): TraceContext
propagation + head sampling, span linkage, wire inject/extract,
tail-based capture into FlightRecorder bundles (local and cross-rank
over the diag channel), trace-anchored exemplars, profiler trace
tagging, and the POST /debug/xprof endpoint."""
import json
import os
import threading
import time

import numpy as np
import pytest

import mxnet_tpu as mx  # noqa: F401  (backend init before telemetry)
from mxnet_tpu import telemetry
from mxnet_tpu.telemetry import aggregate
from mxnet_tpu.telemetry import healthplane as hp
from mxnet_tpu.telemetry import metrics as tmetrics
from mxnet_tpu.telemetry import trace, xtrace


@pytest.fixture(autouse=True)
def _clean_trace_state():
    trace.clear()
    xtrace.clear_flags()
    yield
    trace.clear()
    xtrace.clear_flags()


def _spans_by_name():
    return {e["name"]: e for e in trace.chrome_trace()["traceEvents"]
            if e.get("ph") == "X"}


# -- context + linkage --------------------------------------------------------

def test_spans_under_context_record_parent_child_linkage():
    with xtrace.start() as ctx:
        assert ctx.sampled            # default head rate is 1.0
        with trace.span("xt::parent"):
            with trace.span("xt::child"):
                pass
    spans = _spans_by_name()
    parent, child = spans["xt::parent"], spans["xt::child"]
    assert parent["args"]["trace_id"] == ctx.trace_id
    assert child["args"]["trace_id"] == ctx.trace_id
    # the root position parents the outer span; the outer span's fresh
    # id parents the inner one
    assert parent["args"]["parent_span_id"] == ctx.span_id
    assert child["args"]["parent_span_id"] == parent["args"]["span_id"]
    assert parent["args"]["span_id"] != child["args"]["span_id"]


def test_spans_outside_any_context_stay_unstamped():
    with trace.span("xt::plain"):
        pass
    assert "trace_id" not in (_spans_by_name()["xt::plain"]
                              .get("args") or {})


def test_inject_extract_roundtrip_and_junk_tolerance():
    ctx = xtrace.new_root(sampled=True)
    back = xtrace.extract(xtrace.inject(ctx))
    assert (back.trace_id, back.span_id, back.sampled) == \
        (ctx.trace_id, ctx.span_id, True)
    # no active context -> no wire payload
    assert xtrace.inject() is None
    with xtrace.activate(ctx):
        wire = xtrace.inject()
        assert wire is not None and xtrace.extract(wire).trace_id \
            == ctx.trace_id
    # a malformed peer must never break the receiver
    for junk in (None, 42, "x", ("x",), (99, "a", "b", True),
                 (1, 7, "s", True), [1, "a", "b", True]):
        assert xtrace.extract(junk) is None


def test_activation_masks_and_restores_even_across_threads_table():
    me = threading.get_ident()
    ctx = xtrace.new_root(sampled=True)
    with xtrace.activate(ctx):
        assert xtrace.current() is ctx
        assert xtrace.context_of_thread(me).trace_id == ctx.trace_id
        with xtrace.activate(None):   # mask (worker-thread isolation)
            assert xtrace.current() is None
            assert xtrace.context_of_thread(me) is None
        assert xtrace.current() is ctx
    assert xtrace.current() is None
    assert xtrace.context_of_thread(me) is None


# -- head sampling ------------------------------------------------------------

def test_sample_rate_zero_roots_unsampled_and_skips_stamping():
    prev = xtrace.set_sample_rate(0.0)
    try:
        assert xtrace.new_root().sampled is False
        with xtrace.start():
            with trace.span("xt::unsampled"):
                pass
        assert "trace_id" not in (_spans_by_name()["xt::unsampled"]
                                  .get("args") or {})
        xtrace.set_sample_rate(1.0)
        assert xtrace.new_root().sampled is True
        # an explicit decision overrides the coin
        xtrace.set_sample_rate(0.0)
        assert xtrace.new_root(sampled=True).sampled is True
    finally:
        xtrace.set_sample_rate(prev)


def test_sample_rate_env_knob_clamped_and_junk_tolerant(monkeypatch):
    prev = xtrace.set_sample_rate(None)   # re-read env on next use
    try:
        monkeypatch.setenv("MXNET_TRACE_SAMPLE", "0.25")
        assert xtrace.sample_rate() == 0.25
        xtrace.set_sample_rate(None)
        monkeypatch.setenv("MXNET_TRACE_SAMPLE", "7")
        assert xtrace.sample_rate() == 1.0     # clamped into [0, 1]
        xtrace.set_sample_rate(None)
        monkeypatch.setenv("MXNET_TRACE_SAMPLE", "junk")
        assert xtrace.sample_rate() == 1.0     # junk -> default
    finally:
        xtrace.set_sample_rate(prev)


# -- tail-based capture -------------------------------------------------------

def test_flagging_and_collect_spans():
    ctx = xtrace.new_root(sampled=True)
    with xtrace.activate(ctx):
        with trace.span("xt::anomalous", step=7):
            pass
        entry = xtrace.flag_current("deadline_exceeded", note="m=x")
    assert entry["trace_id"] == ctx.trace_id
    flags = xtrace.flagged()
    assert flags[-1]["kind"] == "deadline_exceeded"
    assert flags[-1]["note"] == "m=x"
    spans = xtrace.collect_spans(ctx.trace_id)
    assert [e["name"] for e in spans] == ["xt::anomalous"]
    # flag by bare id works; an empty id is refused
    assert xtrace.flag(ctx.trace_id, "again")["trace_id"] == ctx.trace_id
    assert xtrace.flag("", "nope") is None
    # drain-on-read clears; plain read does not
    assert xtrace.flagged(clear=True)
    assert xtrace.flagged() == []


def test_recorder_bundle_carries_flagged_trace_span_tree(tmp_path):
    mon = telemetry.StepMonitor(warn_interval_s=1e9)
    rec = telemetry.FlightRecorder(str(tmp_path), rank=0,
                                   rate_limit_s=0.0)
    rec.attach(mon)
    ctx = xtrace.new_root(sampled=True)
    with xtrace.activate(ctx):
        with trace.span("xt::doomed_step"):
            pass
    xtrace.flag(ctx, "deadline_exceeded")
    mon.record_anomaly("deadline_exceeded", "boom")
    with open(rec.bundles[0]) as f:
        bundle = json.load(f)
    sec = bundle["xtrace"]
    assert any(e["trace_id"] == ctx.trace_id for e in sec["flagged"])
    assert [e["name"] for e in sec["spans"][ctx.trace_id]] \
        == ["xt::doomed_step"]


def test_gateway_deadline_exceeded_flags_trace_into_bundle(tmp_path):
    """ISSUE 18 acceptance (local half): a deadline-exceeded request's
    FlightRecorder bundle contains that request's span tree."""
    from mxnet_tpu.serving import (DeadlineExceededError, ModelGateway,
                                   ModelSpec)

    mon = telemetry.StepMonitor(warn_interval_s=1e9)
    rec = telemetry.FlightRecorder(str(tmp_path), rank=0,
                                   rate_limit_s=0.0)
    rec.attach(mon)
    w = mx.nd.array(np.arange(12, dtype=np.float32).reshape(4, 3))
    gw = ModelGateway(monitor=mon)
    try:
        gw.register(ModelSpec("xt_doomed_model",
                              fn=lambda w, x: mx.nd.dot(x, w),
                              params=[w], item_shape=(4,),
                              max_batch=8))
        gw.pause()
        with xtrace.start(sampled=True) as ctx:
            doomed = gw.submit("xt_doomed_model",
                               np.ones((1, 4), np.float32),
                               timeout_ms=30)
        time.sleep(0.08)
        gw.resume()
        with pytest.raises(DeadlineExceededError):
            doomed.result(timeout=30)
        deadline = time.time() + 10.0
        while not rec.bundles and time.time() < deadline:
            time.sleep(0.01)
        assert rec.bundles, "no bundle captured for the shed request"
        with open(rec.bundles[-1]) as f:
            bundle = json.load(f)
        assert bundle["meta"]["kind"] == "deadline_exceeded"
        sec = bundle["xtrace"]
        assert any(e["trace_id"] == ctx.trace_id for e in sec["flagged"])
        spans = sec["spans"][ctx.trace_id]
        assert spans, "flagged request has no span tree in the bundle"
        assert all(e["args"]["trace_id"] == ctx.trace_id for e in spans)
    finally:
        gw.shutdown()


def test_collect_trace_assembles_peer_spans_over_diag_channel(tmp_path):
    """Cross-rank tail capture: rank 0 requests a flagged trace's spans
    over the diag channel; peers push their local span trees; the
    assembled view carries rank-stamped spans, and feed_recorder routes
    it into subsequent bundles."""
    bus = aggregate.LocalBus(num_workers=2)
    recs, cols = [], []
    for rank in (0, 1):
        r = telemetry.FlightRecorder(
            str(tmp_path / ("local%d" % rank)), rank=rank,
            rate_limit_s=0.0)
        recs.append(r)
        cols.append(hp.DiagCollector(
            bus.endpoint(rank), r, interval_s=0.0,
            directory=str(tmp_path / "collected") if rank == 0 else None))
    c0, c1 = cols
    ctx = xtrace.new_root(sampled=True)
    with xtrace.activate(ctx):
        with trace.span("xt::pod_step"):
            pass
    with pytest.raises(ValueError):
        c1.collect_trace(ctx.trace_id)       # rank-0-only entry point
    stop = threading.Event()

    def peer_loop():                         # rank 1's duty loop
        while not stop.is_set():
            c1.step()
            time.sleep(0.005)

    t = threading.Thread(target=peer_loop, daemon=True)
    t.start()
    try:
        res = c0.collect_trace(ctx.trace_id, timeout_s=30.0)
    finally:
        stop.set()
        t.join()
    assert res["trace_id"] == ctx.trace_id
    assert res["ranks"] == [0, 1]
    assert {e["rank"] for e in res["spans"]} == {0, 1}
    assert all(e["name"] == "xt::pod_step" for e in res["spans"])
    # subsequent bundles carry the already-collected peer view
    xtrace.flag(ctx, "slow_step")
    c0.feed_recorder(recs[0])
    path = recs[0].capture("manual", "inspect")
    with open(path) as f:
        peers = json.load(f)["extra"]["xtrace_peers"]
    assert set(peers[ctx.trace_id]) == {"0", "1"} or \
        set(peers[ctx.trace_id]) == {0, 1}


# -- trace-anchored exemplars -------------------------------------------------

def test_exemplars_record_trace_ids_on_histograms_and_counters():
    reg = tmetrics.Registry()
    lat = reg.histogram("xt_lat_seconds", "d", buckets=(0.1, 1.0))
    red = reg.counter("xt_reduce_seconds_total", "d")
    xtrace.install_exemplars(True)
    try:
        ctx = xtrace.new_root(sampled=True)
        with xtrace.activate(ctx):
            lat.observe(0.05)
            red.inc(0.25)
        assert red.exemplar[0] == ctx.trace_id
        text = reg.render_prometheus(openmetrics=True)
        bucket = [l for l in text.splitlines()
                  if l.startswith("xt_lat_seconds_bucket") and " # " in l]
        assert bucket and ctx.trace_id in bucket[0]
        counter = [l for l in text.splitlines()
                   if l.startswith("xt_reduce_seconds_total") and
                   " # " in l]
        assert counter and ctx.trace_id in counter[0]
        # classic exposition never carries exemplar syntax
        assert " # " not in reg.render_prometheus()
        ex = tmetrics.collect_exemplars(reg)
        by_metric = {e["metric"]: e for e in ex}
        assert by_metric["xt_reduce_seconds_total"]["span_id"] \
            == ctx.trace_id
        assert "le" not in by_metric["xt_reduce_seconds_total"]
        assert by_metric["xt_lat_seconds"]["span_id"] == ctx.trace_id
    finally:
        xtrace.install_exemplars(False)


# -- profiler linkage ---------------------------------------------------------

def test_continuous_profiler_tags_traced_threads_with_trace_leaf():
    ctx = xtrace.new_root(sampled=True)
    cold = xtrace.new_root(sampled=False)
    stop = threading.Event()

    def traced():
        with xtrace.activate(ctx):
            while not stop.is_set():
                time.sleep(0.001)

    def unsampled():
        with xtrace.activate(cold):
            while not stop.is_set():
                time.sleep(0.001)

    threads = [threading.Thread(target=traced, daemon=True),
               threading.Thread(target=unsampled, daemon=True)]
    for t in threads:
        t.start()
    time.sleep(0.02)
    profiler = telemetry.ContinuousProfiler(hz=100.0, window_s=3600.0)
    try:
        for _ in range(5):
            profiler.sample()
        text = profiler.collapsed()
        tagged = [l for l in text.splitlines()
                  if "trace:%s" % ctx.trace_id in l]
        assert tagged, text
        stack = tagged[0].rsplit(" ", 1)[0]
        assert stack.endswith("trace:%s" % ctx.trace_id)  # the LEAF
        assert "trace:%s" % cold.trace_id not in text     # unsampled
    finally:
        stop.set()
        for t in threads:
            t.join()
        profiler.close()


# -- POST /debug/xprof --------------------------------------------------------

def test_xprof_endpoint_validation_and_capture(tmp_path, monkeypatch):
    monkeypatch.delenv("MXNET_XPROF_DIR", raising=False)
    bare = hp.HealthPlane()
    status, body = bare.xprof(seconds=0.05)
    assert status == 404 and "error" in body     # no capture root
    assert bare.xprof(seconds="junk")[0] == 400

    plane = hp.HealthPlane(xprof_dir=str(tmp_path / "prof"))
    before = tmetrics.REGISTRY.get("mx_xprof_failures_total").value
    status, body = plane.xprof(seconds=0.05)
    if status == 200:
        assert os.path.isdir(body["dir"])
        assert body["dir"].startswith(str(tmp_path / "prof"))
        assert body["seconds"] == 0.05
    else:
        # CPU-only jaxlib without a profiler backend degrades to 501
        # and counts the failure — never crashes the plane
        assert status == 501, (status, body)
        assert tmetrics.REGISTRY.get("mx_xprof_failures_total").value \
            == before + 1
    # the POST route parses the query string like /debug/pprof does
    assert plane.handle("POST", "/debug/xprof?seconds=abc")[0] == 400
    assert plane.handle("POST", "/debug/xprof?seconds=0.05")[0] \
        in (200, 501)
    assert plane.handle("POST", "/nonsense") is None


def test_xprof_concurrent_captures_conflict(tmp_path):
    plane = hp.HealthPlane(xprof_dir=str(tmp_path))
    assert plane._xprof_lock.acquire(blocking=False)
    try:
        status, body = plane.xprof(seconds=0.05)
        assert status == 409
    finally:
        plane._xprof_lock.release()
