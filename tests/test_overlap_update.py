"""Comm/compute overlap for the fused step (ISSUE 13).

Contracts under test:

- The overlapped reduce->apply pipeline (MXNET_FUSED_OVERLAP_DEPTH > 0)
  produces BIT-IDENTICAL parameters to the serial fused step and to the
  reference-shaped per-param loop, for every fused optimizer family —
  including a mid-run depth toggle (the acceptance criterion; params
  are vector-aligned, the regime PR 4's bit-identity contract covers).
- Reduce time actually hides: with a latency-injecting store the
  overlap-efficiency metric reports hidden > 0 and per-bucket
  trainer::bucket_overlap spans are emitted; a transport error inside
  the window surfaces on step().
- The fused global-norm clip (ONE tree-reduce per flat bucket, scale
  rides the chunk executable as a runtime scalar) matches
  gluon.utils.clip_global_norm + the per-param loop within an ulp, and
  is bit-identical between overlapped and serial runs.
- fp16/bf16 master weights fuse (mp_* specs over the flat vector):
  bit-identical to update_multi_precision's per-param loop, state keeps
  the (inner, master) nesting, save/load states round-trips.
- update_on_kvstore folds into bucketed flat pushes/pulls for
  elementwise families (server stores flat weight vectors), with the
  per-key path kept for ineligible optimizers.
- 1-bit gradient compression codec: 8 codes/byte packing and the
  error-feedback invariant.
"""
import os
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon, nd
from mxnet_tpu import kvstore as kvs
from mxnet_tpu.telemetry import metrics as tm


TWO_CTX = [mx.cpu(0), mx.cpu(1)]


@pytest.fixture
def depth_env(monkeypatch):
    def set_depth(d):
        monkeypatch.setenv("MXNET_FUSED_OVERLAP_DEPTH", str(d))
    return set_depth


def _make_params(tag, n=6, shapes=None, dtype=np.float32, ctx=None):
    rng = np.random.RandomState(11)
    params = []
    for k in range(n):
        shape = shapes[k % len(shapes)] if shapes else \
            ((4, 4) if k % 2 else (8,))
        p = gluon.Parameter("ovl_%s_%d" % (tag, k), shape=shape,
                            dtype=dtype)
        p.initialize(ctx=ctx, init=mx.init.Constant(0.0))
        p.set_data(nd.array(rng.randn(*shape).astype(dtype)))
        params.append(p)
    return params


def _run_steps(tag, optimizer, opt_params, fused=True, steps=5, n=6,
               ctx=TWO_CTX, grad_seed=42, **trainer_kwargs):
    params = _make_params(tag, n=n, ctx=ctx)
    trainer = gluon.Trainer(params, optimizer, dict(opt_params),
                            fused=fused, **trainer_kwargs)
    rng = np.random.RandomState(grad_seed)
    for _ in range(steps):
        for p in params:
            for g in p.list_grad():
                g[:] = rng.randn(*p.shape).astype(np.float32)
        trainer.step(2)
    return [p.data().asnumpy().copy() for p in params], trainer


@pytest.mark.parametrize("optimizer,opt_params", [
    ("sgd", {"learning_rate": 0.1, "momentum": 0.9}),
    ("sgd", {"learning_rate": 0.05, "wd": 1e-3, "clip_gradient": 0.5}),
    ("adam", {"learning_rate": 0.01}),
    ("rmsprop", {"learning_rate": 0.01}),
    ("adagrad", {"learning_rate": 0.1}),
    ("adadelta", {}),
    ("nag", {"learning_rate": 0.1, "momentum": 0.9}),
    ("signum", {"learning_rate": 0.01}),
])
def test_overlapped_bit_identical_all_families(optimizer, opt_params,
                                               depth_env):
    """THE acceptance cross-check: overlapped (depth 2) == serial
    (depth 0) == per-param loop, in every bit, per fused family."""
    depth_env(2)
    overlapped, tr = _run_steps("o_" + optimizer, optimizer, opt_params)
    assert tr._applier.num_compiles >= 1
    depth_env(0)
    serial, _ = _run_steps("s_" + optimizer, optimizer, opt_params)
    loop, _ = _run_steps("l_" + optimizer, optimizer, opt_params,
                         fused=False)
    for a, b in zip(overlapped, serial):
        np.testing.assert_array_equal(a, b)
    for a, b in zip(overlapped, loop):
        np.testing.assert_array_equal(a, b)


def test_overlap_depth_toggle_midrun(monkeypatch):
    """MXNET_FUSED_OVERLAP_DEPTH flips mid-run without perturbing a
    single bit (the knob is read per step)."""
    params = _make_params("toggle", ctx=TWO_CTX)
    trainer = gluon.Trainer(params, "adam", {"learning_rate": 0.01})
    rng = np.random.RandomState(42)
    for s in range(6):
        monkeypatch.setenv("MXNET_FUSED_OVERLAP_DEPTH",
                           "0" if s in (2, 3) else "2")
        for p in params:
            for g in p.list_grad():
                g[:] = rng.randn(*p.shape).astype(np.float32)
        trainer.step(2)
    mixed = [p.data().asnumpy() for p in params]
    monkeypatch.setenv("MXNET_FUSED_OVERLAP_DEPTH", "2")
    pure, _ = _run_steps("toggle_ref", "adam", {"learning_rate": 0.01},
                         steps=6)
    for a, b in zip(mixed, pure):
        np.testing.assert_array_equal(a, b)


class _LatencyStore(kvs.KVStoreLocal):
    """Local store plus a synthetic wire delay per push/pull leg, and
    optional fault injection on pull."""

    def __init__(self, latency=0.002, **kwargs):
        super().__init__(**kwargs)
        self.latency = latency
        self.fail_pulls_after = None
        self.pulls = 0

    @property
    def type(self):
        return "dist_test_latency"    # "dist" => engaged on 1 context

    def push(self, key, value, priority=0):
        time.sleep(self.latency / 2)
        super().push(key, value, priority)

    def pull(self, key, out=None, priority=0, ignore_sparse=True):
        self.pulls += 1
        if self.fail_pulls_after is not None and \
                self.pulls > self.fail_pulls_after:
            raise ConnectionResetError("injected transport failure")
        time.sleep(self.latency / 2)
        super().pull(key, out=out, priority=priority,
                     ignore_sparse=ignore_sparse)


def _overlap_workload(tag, store, monkeypatch, depth=2, n=512, steps=3,
                      **trainer_kwargs):
    monkeypatch.setenv("MXNET_FUSED_OVERLAP_DEPTH", str(depth))
    monkeypatch.setenv("MXNET_FUSED_BUCKET_MB", "1")
    params = []
    rng = np.random.RandomState(3)
    for k in range(n):
        p = gluon.Parameter("lat_%s_%d" % (tag, k), shape=(2048,))
        p.initialize(init=mx.init.Constant(0.0))
        p.set_data(nd.array(rng.randn(2048).astype(np.float32)))
        params.append(p)
    trainer = gluon.Trainer(params, "sgd",
                            {"learning_rate": 0.05, "momentum": 0.9},
                            kvstore=store, update_on_kvstore=False,
                            **trainer_kwargs)
    for _ in range(steps):
        for p in params:
            p.grad()[:] = rng.randn(2048).astype(np.float32)
        trainer.step(1)
    return params, trainer


def test_overlap_hides_reduce_time_and_emits_spans(monkeypatch):
    """With a latency store and several buckets, the runtime accounting
    must report reduce time hidden (> 0 share) and per-bucket
    trainer::bucket_overlap spans."""
    from mxnet_tpu.telemetry import trace

    red = tm.REGISTRY.counter("mx_trainer_reduce_seconds_total", "")
    hid = tm.REGISTRY.counter("mx_trainer_reduce_hidden_seconds_total", "")
    eff = tm.REGISTRY.gauge("mx_trainer_overlap_efficiency", "")
    r0, h0 = red.value, hid.value
    prev = trace.set_enabled(True)
    try:
        _overlap_workload("hide", _LatencyStore(device_mode=True),
                          monkeypatch)
    finally:
        drained = trace.drain()
        trace.set_enabled(prev)
    assert red.value > r0
    assert hid.value > h0, "no reduce time was hidden"
    assert 0.0 < eff.value <= 1.0
    names = {e[1] for _, _, events in drained for e in events}
    assert "trainer::bucket_overlap" in names
    assert "trainer::allreduce" in names


def test_overlap_serial_reports_zero_hidden(monkeypatch):
    """depth=0 with the pipelined route engaged (a global-norm clip
    forces it): every reduce second is exposed main-thread wait, so
    hidden stays ~0 and the efficiency gauge reads 0."""
    hid = tm.REGISTRY.counter("mx_trainer_reduce_hidden_seconds_total", "")
    eff = tm.REGISTRY.gauge("mx_trainer_overlap_efficiency", "")
    h0 = hid.value
    _overlap_workload("ser", _LatencyStore(device_mode=True),
                      monkeypatch, depth=0, global_norm_clip=1e9)
    assert hid.value - h0 < 1e-3
    assert eff.value < 0.05


def test_overlap_transport_error_surfaces_on_step(monkeypatch):
    """A pull that dies inside the overlap window must raise from
    step(), not hang or vanish on the comm thread."""
    store = _LatencyStore(device_mode=True)
    params, trainer = _overlap_workload("err", store, monkeypatch,
                                        steps=1)
    store.fail_pulls_after = store.pulls + 1   # fail the window's 2nd pull
    rng = np.random.RandomState(9)
    for p in params:
        p.grad()[:] = rng.randn(2048).astype(np.float32)
    with pytest.raises(ConnectionResetError):
        trainer.step(1)


# -- fused global-norm clip ---------------------------------------------------

def _clip_run(tag, fused, clip, depth, monkeypatch, ctx=TWO_CTX,
              use_utils=False, steps=4):
    monkeypatch.setenv("MXNET_FUSED_OVERLAP_DEPTH", str(depth))
    params = _make_params(tag, ctx=ctx)
    trainer = gluon.Trainer(
        params, "sgd", {"learning_rate": 0.1, "momentum": 0.9},
        fused=fused, global_norm_clip=None if use_utils else clip)
    rng = np.random.RandomState(17)
    for _ in range(steps):
        for p in params:
            for g in p.list_grad():
                g[:] = 3.0 * rng.randn(*p.shape).astype(np.float32)
        if use_utils:
            # The reference recipe (single context): clip_global_norm
            # on the raw grads, then an unclipped step.
            gluon.utils.clip_global_norm(
                [p.list_grad()[0] for p in params], clip)
        trainer.step(1)
    return [p.data().asnumpy().copy() for p in params]


def test_global_norm_clip_overlap_equals_serial(monkeypatch):
    a = _clip_run("gn_o", True, 0.75, 2, monkeypatch)
    b = _clip_run("gn_s", True, 0.75, 0, monkeypatch)
    for x, y in zip(a, b):
        np.testing.assert_array_equal(x, y)


def test_global_norm_clip_matches_reference_single_ctx(monkeypatch):
    """Single-context: fused trainer clip vs the reference recipe
    (gluon.utils.clip_global_norm + unclipped loop trainer). The fused
    norm accumulates per-param f32 sums the same way utils does, so
    the match is ulp-tight."""
    a = _clip_run("gn_f1", True, 0.75, 0, monkeypatch, ctx=None)
    b = _clip_run("gn_r1", False, 0.75, 0, monkeypatch, ctx=None,
                  use_utils=True)
    for x, y in zip(a, b):
        np.testing.assert_allclose(x, y, rtol=1e-6, atol=1e-7)


def test_global_norm_clip_actually_clips(monkeypatch):
    """With clip smaller than the raw norm, the update magnitude must
    shrink accordingly vs the unclipped run."""
    clipped = _clip_run("gn_c", True, 0.5, 2, monkeypatch, steps=1)
    unclipped = _clip_run("gn_u", True, 1e9, 2, monkeypatch, steps=1)
    d_c = sum(float(np.abs(x).sum()) for x in clipped)
    d_u = sum(float(np.abs(x).sum()) for x in unclipped)
    assert d_c != d_u


def test_global_norm_clip_rejects_sparse(monkeypatch):
    from mxnet_tpu.gluon.contrib.nn import SparseEmbedding
    from mxnet_tpu import autograd

    emb = SparseEmbedding(10, 4)
    emb.initialize()
    trainer = gluon.Trainer(emb.collect_params(), "sgd",
                            {"learning_rate": 0.1}, global_norm_clip=1.0)
    with autograd.record():
        loss = (emb(nd.array(np.array([1.0, 2.0], np.float32))) ** 2).sum()
    loss.backward()
    with pytest.raises(ValueError, match="dense"):
        trainer.step(1)


# -- mixed-precision master weights -------------------------------------------

def _mp_run(tag, fused, dtype, optimizer="sgd", opt_params=None, n=4,
            steps=5):
    rng = np.random.RandomState(5)
    params = []
    for k in range(n):
        p = gluon.Parameter("mp_%s_%d" % (tag, k), shape=(8,),
                            dtype=dtype)
        p.initialize(init=mx.init.Constant(0.0))
        p.set_data(nd.array(rng.randn(8).astype(np.float32).astype(dtype)))
        params.append(p)
    op = dict(opt_params or {"learning_rate": 0.1, "momentum": 0.9})
    op["multi_precision"] = True
    trainer = gluon.Trainer(params, optimizer, op, fused=fused)
    g = np.random.RandomState(23)
    for _ in range(steps):
        for p in params:
            p.grad()[:] = nd.array(
                g.randn(8).astype(np.float32)).astype(dtype)
        trainer.step(2)
    return params, trainer


@pytest.mark.parametrize("optimizer,opt_params", [
    ("sgd", {"learning_rate": 0.1, "momentum": 0.9}),
    ("sgd", {"learning_rate": 0.1}),
    ("adam", {"learning_rate": 0.01}),
    ("rmsprop", {"learning_rate": 0.01}),
])
def test_mp_master_weights_fuse_bit_identical(optimizer, opt_params):
    """fp16 weights + fp32 master flats through the fused path match
    the per-param update_multi_precision loop in every bit — and the
    fused path actually compiled (no silent fallback)."""
    import jax.numpy as jnp

    fp, ftr = _mp_run("f_" + optimizer, True, np.float16, optimizer,
                      opt_params)
    lp, _ = _mp_run("l_" + optimizer, False, np.float16, optimizer,
                    opt_params)
    assert ftr._applier.num_compiles >= 1, "mp entries fell back"
    for a, b in zip(fp, lp):
        np.testing.assert_array_equal(a.data().asnumpy(),
                                      b.data().asnumpy())
    # the master copy stays fp32 under the (inner, master) nesting
    state = ftr._updater.states[0]
    assert isinstance(state, tuple) and len(state) == 2
    assert jnp.dtype(state[1].dtype) == jnp.float32


def test_mp_bf16_master_weights(monkeypatch):
    """bf16 weights get fp32 masters too (MXNET_MP_LOWP_DTYPES default)
    — the TPU-native case the reference never covered."""
    import jax.numpy as jnp

    fp, ftr = _mp_run("bf16_f", True, jnp.bfloat16)
    lp, _ = _mp_run("bf16_l", False, jnp.bfloat16)
    assert ftr._applier.num_compiles >= 1
    for a, b in zip(fp, lp):
        np.testing.assert_array_equal(
            a.data().asnumpy().astype(np.float32),
            b.data().asnumpy().astype(np.float32))
    state = ftr._updater.states[0]
    assert jnp.dtype(state[1].dtype) == jnp.float32


def test_mp_save_load_states_roundtrip(tmp_path):
    params, trainer = _mp_run("ckpt", True, np.float16)
    fname = str(tmp_path / "mp.states")
    trainer.save_states(fname)
    import pickle

    blob = pickle.loads(open(fname, "rb").read())
    inner, master = blob[0]
    assert np.asarray(master).dtype == np.float32
    assert np.abs(np.asarray(inner)).sum() > 0      # momentum moved
    trainer.load_states(fname)
    for p in params:
        p.grad()[:] = nd.array(
            np.ones(8, np.float32)).astype(np.float16)
    trainer.step(1)                                  # still steps


# -- bucketed update_on_kvstore ----------------------------------------------

def test_update_on_kvstore_bucketed_traffic_and_values(monkeypatch):
    """Optimizer-on-server over flat buckets: server holds ONE flat
    weight vector per bucket (no per-param keys for bucketed params),
    and the trained values match the per-param server path within the
    PR 4 ulp contract (the server applies the same elementwise body to
    a concatenation)."""
    def run(fused):
        params = _make_params("uokv_%s" % fused, ctx=TWO_CTX)
        store = kvs.create("device")
        trainer = gluon.Trainer(params, "sgd",
                                {"learning_rate": 0.1, "momentum": 0.9},
                                kvstore=store, update_on_kvstore=True,
                                fused=fused)
        rng = np.random.RandomState(29)
        for _ in range(4):
            for p in params:
                for g in p.list_grad():
                    g[:] = rng.randn(*p.shape).astype(np.float32)
            trainer.step(2)
        return [p.data().asnumpy().copy() for p in params], store, trainer

    bucketed, store_b, tr_b = run(True)
    per_param, store_p, _ = run(False)
    assert tr_b._uokv_bucketed
    bucket_keys = [k for k in store_b._store
                   if str(k).startswith("__fused_grad_bucket")]
    assert bucket_keys, "no flat weight buckets on the server"
    assert not any(isinstance(k, int) for k in store_b._store), \
        "bucketed uokv still initialized per-param keys"
    assert all(isinstance(k, int) for k in store_p._store)
    for a, b in zip(bucketed, per_param):
        np.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-7)


def test_update_on_kvstore_ineligible_keeps_per_param():
    """Per-key lr multipliers can't ride a flat bucket: the trainer
    must fall back to the reference per-param server path."""
    params = _make_params("uokv_mult", ctx=TWO_CTX)
    store = kvs.create("device")
    trainer = gluon.Trainer(params, "sgd", {"learning_rate": 0.1},
                            kvstore=store, update_on_kvstore=True)
    trainer._optimizer.set_lr_mult({0: 0.5})
    rng = np.random.RandomState(31)
    for p in params:
        for g in p.list_grad():
            g[:] = rng.randn(*p.shape).astype(np.float32)
    trainer.step(2)
    assert not trainer._uokv_bucketed
    assert all(isinstance(k, int) for k in store._store)


# -- 1-bit compression codec --------------------------------------------------

def test_one_bit_compression_codec():
    """8 codes per byte, sign quantization, error-feedback invariant:
    residual always equals accumulated input minus accumulated
    output, and the time-average converges to clip(g, -t, t)."""
    from mxnet_tpu.gradient_compression import GradientCompression

    gc = GradientCompression({"type": "1bit", "threshold": 0.25})
    assert gc.get_params() == {"type": "1bit", "threshold": 0.25}
    g = np.array([[0.2, -0.15, 0.0], [-0.05, 0.24, -3.0]], np.float32)
    packed, meta = gc.compress("k", g)
    assert len(packed) == 1                     # 6 bits -> 1 byte
    dec = GradientCompression.decompress(packed, meta)
    np.testing.assert_array_equal(dec, np.where(g > 0, 0.25, -0.25))
    np.testing.assert_allclose(gc._residual["k"], g - dec, atol=1e-6)
    total = dec.copy()
    for _ in range(63):
        p2, m2 = gc.compress("k", g)
        total += GradientCompression.decompress(p2, m2)
    # EF makes the stream unbiased within the codec's range: the
    # time-average converges to the saturating clip of the input.
    np.testing.assert_allclose(total / 64, np.clip(g, -0.25, 0.25),
                               atol=0.26 / 8)
    with pytest.raises(ValueError):
        GradientCompression({"type": "4bit"})


def test_two_bit_meta_carries_type():
    from mxnet_tpu.gradient_compression import GradientCompression

    gc = GradientCompression({"type": "2bit", "threshold": 0.5})
    packed, meta = gc.compress("k", np.ones((3,), np.float32))
    assert meta["type"] == "2bit"
    # old metas without a type still decompress as 2bit
    meta.pop("type")
    out = GradientCompression.decompress(packed, meta)
    np.testing.assert_array_equal(out, np.full((3,), 0.5, np.float32))


# -- donation knob ------------------------------------------------------------

def test_donation_knob(monkeypatch):
    from mxnet_tpu import fused_update as fu

    monkeypatch.setenv("MXNET_FUSED_DONATE", "1")
    assert fu.donate_enabled()
    monkeypatch.setenv("MXNET_FUSED_DONATE", "0")
    assert not fu.donate_enabled()
    monkeypatch.setenv("MXNET_FUSED_DONATE", "auto")
    assert not fu.donate_enabled()      # CPU backend: donation inert


def test_donation_on_still_bit_identical(monkeypatch):
    """Forcing donation on (CPU ignores the aliasing but accepts the
    executable) must not change a single bit."""
    monkeypatch.setenv("MXNET_FUSED_DONATE", "1")
    monkeypatch.setenv("MXNET_FUSED_OVERLAP_DEPTH", "2")
    a, _ = _run_steps("don_f", "adam", {"learning_rate": 0.01})
    monkeypatch.setenv("MXNET_FUSED_DONATE", "0")
    b, _ = _run_steps("don_o", "adam", {"learning_rate": 0.01})
    for x, y in zip(a, b):
        np.testing.assert_array_equal(x, y)
