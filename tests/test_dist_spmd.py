"""Multi-host SPMD over DCN — the dist_sync path that spans processes.

Reference analogue: tests/nightly/dist_sync_kvstore.py runs real
multi-process parameter-server traffic on one host via
`tools/launch.py --launcher local`. Here the same launcher (with
``-s 0``) spawns a pure SPMD group: 2 processes × 4 virtual CPU devices
joined by `parallel.dist.initialize` into one 8-device mesh, training
through `TrainStep` with gradient aggregation riding XLA collectives
(gloo across the process boundary — DCN's stand-in on a dev box).

The bar (VERDICT r4 #1): the 2-process run must match the 1-process
8-device run bit-for-bit on params, optimizer state, aux, and the loss
trace after N steps.
"""
import os
import subprocess
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tools"))
from launch import launch_local  # noqa: E402

PROG = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                    "dist_spmd_prog.py")


def _clean_env():
    env = {k: v for k, v in os.environ.items() if not k.startswith("DMLC_")}
    # Override (not just drop): launch_local merges os.environ, where
    # conftest already forced the 8-device flag for THIS process.
    env["XLA_FLAGS"] = ""
    env["JAX_PLATFORMS"] = ""  # prog pins cpu itself (axon override-safe)
    return env


def _run_single(out, steps):
    env = _clean_env()
    rc = subprocess.call([sys.executable, PROG, out, str(steps)], env=env,
                         timeout=420)
    assert rc == 0


def _run_multi(out, steps, num_workers=2):
    codes = launch_local(
        num_workers, 0, [sys.executable, PROG, out, str(steps)],
        env_extra=_clean_env(), timeout=420)
    assert codes == [0] * num_workers, codes


STEPS = 6   # shared by the baseline fixture and every parametrization


@pytest.fixture(scope="module")
def single_proc_baseline(tmp_path_factory):
    """One deterministic 1-process reference run shared by every
    worker-count parametrization."""
    path = str(tmp_path_factory.mktemp("spmd") / "single.npz")
    _run_single(path, STEPS)
    return path


@pytest.mark.parametrize("num_workers", [2, 4])
def test_multi_process_spmd_matches_single_process(tmp_path, num_workers,
                                                   single_proc_baseline):
    a = single_proc_baseline
    b = str(tmp_path / "multi.npz")
    _run_multi(b, STEPS, num_workers=num_workers)
    za, zb = np.load(a), np.load(b)
    assert sorted(za.files) == sorted(zb.files)
    exact, close = [], []
    for k in za.files:
        if np.array_equal(za[k], zb[k]):
            exact.append(k)
        else:
            close.append(k)
            np.testing.assert_allclose(
                za[k], zb[k], rtol=1e-6, atol=1e-7,
                err_msg="%s diverged between 1-proc and 2-proc" % k)
    # The training state must be bitwise identical: same mesh, same
    # reduction shape — only the transport differs.
    assert not close, ("bitwise mismatch (within 1e-6) on: %s" % close)


def test_dist_initialize_noop_single():
    """Without a process-group contract, initialize() is a no-op and the
    same script stays single-controller."""
    env = _clean_env()
    code = ("import sys; sys.path.insert(0, %r); "
            "from mxnet_tpu.parallel import dist; "
            "assert dist.initialize(local_device_count=8, platform='cpu') "
            "is False; "
            "assert dist.rank() == 0 and dist.num_processes() == 1; "
            "assert dist.local_slice(64) == (0, 64)"
            % os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    rc = subprocess.call([sys.executable, "-c", code], env=env, timeout=120)
    assert rc == 0
