"""Worker program for the 2-rank data-pipeline resume test
(tests/test_data_pipeline.py; the telemetry_dist_prog subprocess
pattern).

Each rank consumes its shard of a shared RecordIO dataset through a
full DataPipeline (parallel decode + prefetch), appending every
delivered batch's sample ids to ``ids.rank<R>.txt``. Modes:

* ``run``     — consume ``--batches`` batches uninterrupted (golden).
* ``kill``    — checkpoint the iterator state through CheckpointManager
  after every batch, then SIGKILL itself mid-epoch after
  ``--kill-after`` batches (no cleanup, like a real preemption).
* ``resume``  — restore the newest checkpoint, seek the pipeline there,
  and consume the REMAINING batches.

The test asserts the concatenated kill+resume sample-id stream is
bit-identical to the golden run on both ranks: preemption-safe resume
replays the exact remaining sample sequence.
"""
import argparse
import os
import sys

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _ROOT)

import numpy as np                                     # noqa: E402

from mxnet_tpu import data                             # noqa: E402
from mxnet_tpu.checkpoint import CheckpointManager     # noqa: E402


def payload_decode(record):
    """Decode the test records: payload is the ascii sample id, label
    is the id too — cheap, deterministic, and self-checking."""
    from mxnet_tpu import recordio

    header, payload = recordio.unpack(record)
    sid = int(payload.decode())
    arr = np.full((2, 2), sid, dtype=np.float32)
    return np.float32(header.label), arr


def build_pipeline(args):
    return data.DataPipeline(
        args.rec, payload_decode, batch_size=args.batch_size,
        shuffle=True, seed=args.seed, num_shards=args.num_shards,
        shard_index=args.rank, decode_threads=2, prefetch=2,
        place=False)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--rec", required=True)
    ap.add_argument("--out-dir", required=True)
    ap.add_argument("--ckpt-dir", required=True)
    ap.add_argument("--rank", type=int, required=True)
    ap.add_argument("--num-shards", type=int, default=2)
    ap.add_argument("--mode", choices=("run", "kill", "resume"),
                    required=True)
    ap.add_argument("--batches", type=int, required=True)
    ap.add_argument("--kill-after", type=int, default=2)
    ap.add_argument("--batch-size", type=int, default=4)
    ap.add_argument("--seed", type=int, default=11)
    args = ap.parse_args()

    pipe = build_pipeline(args)
    ids_path = os.path.join(args.out_dir, "ids.rank%d.txt" % args.rank)
    done = 0
    if args.mode == "resume":
        mgr = CheckpointManager(args.ckpt_dir)
        step, state = mgr.restore()
        pipe.load_state_dict(state["data"])
        mgr.close()
        done = int(step)
        # A sanity pin: the batch data must encode the batch ids — a
        # decode/id mismatch would pass the stream comparison silently.
    mgr = CheckpointManager(args.ckpt_dir) if args.mode == "kill" else None

    with open(ids_path, "a") as out, pipe:
        while done < args.batches:
            batch = next(pipe)
            ids = np.asarray(batch.index).tolist()
            first = int(np.asarray(batch.data[0]).ravel()[0])
            assert first == ids[0], (first, ids)
            done += 1
            out.write(" ".join(str(i) for i in ids) + "\n")
            out.flush()
            if mgr is not None:
                mgr.save(done, {"data": pipe.state_dict()}, sync=True)
                if done >= args.kill_after:
                    os.kill(os.getpid(), 9)   # preemption, no cleanup
    return 0


if __name__ == "__main__":
    sys.exit(main())
