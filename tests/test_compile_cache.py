"""Persistent compilation cache (ISSUE 11): disk store durability +
corruption handling under fault_fs, warm-reload at all three compile
seams (cached_op / fused_apply / train_step), pad-to-bucket shape
canonicalization, LRU retention, the inspect/GC/verify CLI, and
pod-wide distribution (LocalBus + 2-process kvstore acceptance)."""
import importlib.util
import json
import os
import socket
import sys
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd
from mxnet_tpu import compile as cc
from mxnet_tpu.cached_op import CachedOp
from mxnet_tpu.compile.distribute import CacheDistributor
from mxnet_tpu.compile.store import CompileCacheStore, make_key
from mxnet_tpu.gluon import nn
from mxnet_tpu.gluon import loss as gloss
from mxnet_tpu.parallel import TrainStep
from mxnet_tpu.telemetry import memstats
from mxnet_tpu.telemetry import metrics as tmetrics
from mxnet_tpu.telemetry.aggregate import LocalBus

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tools"))
from launch import launch_local  # noqa: E402

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _cc_isolated():
    """Every test starts (and leaves) with the cache disabled and no
    distributor; tests that want it call cc.configure themselves."""
    cc.reset()
    yield
    cc.reset()


def _counter(name, **labels):
    fam = tmetrics.REGISTRY.get(name)
    if fam is None:
        return 0
    return fam.labels(**labels).value


def _site_count(site):
    return {s: r["count"]
            for s, r in memstats.compile_stats().items()}.get(site, 0)


def _jnp():
    import jax.numpy as jnp

    return jnp


# -- store durability ----------------------------------------------------------

def test_store_roundtrip_and_key_anatomy(tmp_path):
    store = CompileCacheStore(str(tmp_path))
    key = make_key([["site"], "fingerprint", {"jaxlib": "1"}])
    path = store.put(key, b"payload-bytes", {"site": "cached_op"})
    assert os.path.basename(path) == "cc.%s.bin" % key
    meta, payload = store.get(key)
    assert payload == b"payload-bytes"
    assert meta["site"] == "cached_op"
    # Any key ingredient changing — here the backend version — is a
    # different key: version skew can never load a stale executable.
    assert make_key([["site"], "fingerprint", {"jaxlib": "2"}]) != key
    assert store.get("0" * 32) is None          # absent = plain miss


def test_store_lru_gc_by_mtime(tmp_path):
    store = CompileCacheStore(str(tmp_path))
    now = time.time()
    for i in range(4):
        key = make_key(["entry", i])
        store.put(key, b"x" * 100, {"i": i})
        os.utime(store.path_for(key), (now - 100 + i, now - 100 + i))
    removed = store.gc(max_bytes=2 * (100 + 120))   # ~2 entries' worth
    assert removed                                   # oldest went first
    left = {store.get(k, touch=False)[0]["i"] for k in store.keys()}
    assert 3 in left and 0 not in left


def test_store_corruption_truncated_and_crc(tmp_path, fault_fs):
    store = CompileCacheStore(str(tmp_path))
    k1, k2 = make_key(["a"]), make_key(["b"])
    store.put(k1, b"p" * 64, {})
    store.put(k2, b"q" * 64, {})
    # Truncation (torn tail that survived to "commit").
    fault_fs.corrupt(store.path_for(k1), truncate_to=30)
    assert store.get(k1) is None
    assert not os.path.exists(store.path_for(k1))   # quarantined
    # Single-bit payload damage caught by CRC.
    fault_fs.corrupt(store.path_for(k2),
                     flip_byte_at=os.path.getsize(store.path_for(k2)) - 3)
    assert store.get(k2) is None
    assert not os.path.exists(store.path_for(k2))


def test_store_key_mismatch_never_serves_wrong_executable(tmp_path):
    """An entry file renamed/copied under another key (rsync of a
    half-GC'd dir, manual restore) fails the header key cross-check —
    payload CRC alone cannot catch a whole-file swap."""
    store = CompileCacheStore(str(tmp_path))
    k1, k2 = make_key(["one"]), make_key(["two"])
    store.put(k1, b"executable-one", {})
    os.rename(store.path_for(k1), store.path_for(k2))
    assert store.get(k2) is None
    assert not os.path.exists(store.path_for(k2))   # quarantined


def test_store_get_without_quarantine_keeps_evidence(tmp_path, fault_fs):
    store = CompileCacheStore(str(tmp_path))
    key = make_key(["ev"])
    store.put(key, b"payload" * 10, {})
    fault_fs.corrupt(store.path_for(key), truncate_to=40)
    assert store.get(key, quarantine=False) is None
    assert os.path.exists(store.path_for(key))      # evidence kept
    assert store.get(key) is None                   # runtime read GCs it
    assert not os.path.exists(store.path_for(key))


def test_store_version_skew_is_a_miss(tmp_path):
    store = CompileCacheStore(str(tmp_path))
    key = make_key(["v"])
    store.put(key, b"payload", {})
    path = store.path_for(key)
    with open(path, "rb") as f:
        header, payload = f.readline(), f.read()
    rec = json.loads(header)
    rec["format"] = "mxnet_tpu.compile_cache/999"
    with open(path, "wb") as f:
        f.write(json.dumps(rec).encode() + b"\n" + payload)
    assert store.get(key) is None                   # skew never loads


def test_kill_mid_commit_leaves_no_torn_entry(tmp_path, fault_fs):
    """A commit that dies at the rename (== a kill between write and
    rename) must leave the cache exactly as before: no entry, no
    staging litter, and the NEXT start commits cleanly."""
    store = CompileCacheStore(str(tmp_path))
    key = make_key(["torn"])
    fault_fs.fail_next_renames(1)
    with pytest.raises(OSError):
        store.put(key, b"payload", {})
    assert os.listdir(str(tmp_path)) == []          # nothing torn, no tmp
    assert store.get(key) is None
    store.put(key, b"payload", {})                  # next start is clean
    assert store.get(key)[1] == b"payload"


# -- the cached-jit wrapper ----------------------------------------------------

def test_cached_function_hit_miss_counters(tmp_path):
    cc.configure(str(tmp_path))
    jnp = _jnp()

    def f(x):
        return jnp.tanh(x) * 2

    x = jnp.ones((8,))
    miss0 = _counter("mx_compile_cache_misses_total", site="t1")
    cf1 = cc.cached_compile(f, "t1")
    out1 = cf1(x)
    assert cf1.num_compiles == 1 and cf1.num_hits == 0
    assert _counter("mx_compile_cache_misses_total", site="t1") \
        == miss0 + 1
    hit0 = _counter("mx_compile_cache_hits_total", site="t1",
                    source="local")
    cf2 = cc.cached_compile(f, "t1")
    out2 = cf2(x)
    assert cf2.num_compiles == 0 and cf2.num_hits == 1
    assert _counter("mx_compile_cache_hits_total", site="t1",
                    source="local") == hit0 + 1
    assert np.allclose(np.asarray(out1), np.asarray(out2))
    # Steady state: the second call of the same signature is a dict hit.
    cf2(x)
    assert cf2.num_hits == 1


def test_truncated_entry_is_counted_miss_and_recompiles(tmp_path,
                                                        fault_fs):
    """fault_fs truncate-on-close: the entry commits TORN; the next
    start detects it (CRC/size), counts a miss, recompiles and heals
    the cache."""
    cc.configure(str(tmp_path))
    jnp = _jnp()

    def f(x):
        return x * 3 + 1

    x = jnp.ones((4,))
    fault_fs.truncate_next_file(20)     # tears the entry's commit
    cf1 = cc.cached_compile(f, "t2")
    cf1(x)
    assert fault_fs.files_truncated == 1
    miss0 = _counter("mx_compile_cache_misses_total", site="t2")
    cf2 = cc.cached_compile(f, "t2")
    out = cf2(x)
    assert cf2.num_compiles == 1        # recompiled, didn't crash
    assert _counter("mx_compile_cache_misses_total", site="t2") \
        == miss0 + 1
    assert np.allclose(np.asarray(out), 4.0)
    cf3 = cc.cached_compile(f, "t2")    # healed: now a clean hit
    cf3(x)
    assert cf3.num_compiles == 0 and cf3.num_hits == 1


def test_serialize_unsupported_backend_falls_back(tmp_path, monkeypatch):
    """A backend that cannot serialize executables still computes —
    counted, and the cache simply stays cold."""
    cc.configure(str(tmp_path))
    jnp = _jnp()

    def boom(compiled):
        raise NotImplementedError("backend cannot serialize")

    monkeypatch.setattr(cc, "_serialize", boom)
    err0 = _counter("mx_compile_cache_errors_total", site="t3",
                    kind="serialize_unsupported")
    cf = cc.cached_compile(lambda x: x + 1, "t3")
    out = cf(jnp.ones((4,)))
    assert np.allclose(np.asarray(out), 2.0)
    assert _counter("mx_compile_cache_errors_total", site="t3",
                    kind="serialize_unsupported") == err0 + 1
    assert CompileCacheStore(str(tmp_path)).keys() == []


def test_deserialize_failure_recompiles(tmp_path, monkeypatch):
    cc.configure(str(tmp_path))
    jnp = _jnp()

    def f(x):
        return x - 5

    x = jnp.ones((4,))
    cc.cached_compile(f, "t4")(x)

    def boom(blob):
        raise ValueError("bitrot")

    monkeypatch.setattr(cc, "_deserialize", boom)
    err0 = _counter("mx_compile_cache_errors_total", site="t4",
                    kind="deserialize")
    cf = cc.cached_compile(f, "t4")
    out = cf(x)
    assert cf.num_compiles == 1
    assert np.allclose(np.asarray(out), -4.0)
    assert _counter("mx_compile_cache_errors_total", site="t4",
                    kind="deserialize") == err0 + 1


def test_disabled_cache_is_plain_jit(tmp_path):
    jnp = _jnp()
    fn = cc.maybe_cached_jit(lambda x: x * 2, "t5")
    assert not isinstance(fn, cc.CachedFunction)
    assert np.allclose(np.asarray(fn(jnp.ones((2,)))), 2.0)


# -- the three seams warm-reload -----------------------------------------------

def test_cached_op_warm_reload_compiles_nothing(tmp_path):
    cc.configure(str(tmp_path))
    w = nd.array(np.random.rand(6, 3).astype(np.float32))

    def fwd(w_, x):
        return nd.dot(x, w_)

    op1 = CachedOp(fwd, num_params=1)
    x = nd.array(np.random.rand(2, 6).astype(np.float32))
    out1 = op1.inference(w, x)
    count = _site_count("cached_op")
    assert count >= 1
    op2 = CachedOp(fwd, num_params=1)
    out2 = op2.inference(w, x)
    # The warm op TRACED (num_traces counts signatures for the serving
    # warmup contract) but did not COMPILE.
    assert op2.num_traces == 1
    assert _site_count("cached_op") == count
    assert np.allclose(out1.asnumpy(), out2.asnumpy())


def test_executor_warm_reload_compiles_nothing(tmp_path):
    """ISSUE 15 satellite: simple_bind Executors (the serving
    checkpoint-model path) build their whole-graph forward through the
    cached seam — a second Executor of the same symbol loads its
    executable instead of compiling, so gateway warmup after a warm
    restart compiles nothing."""
    cc.configure(str(tmp_path))
    data = mx.sym.var("data")
    net = mx.sym.FullyConnected(data, num_hidden=5, name="ccx_fc")
    args = {"ccx_fc_weight": nd.array(np.random.rand(5, 7)
                                      .astype(np.float32)),
            "ccx_fc_bias": nd.zeros((5,)),
            "data": nd.array(np.random.rand(3, 7).astype(np.float32))}

    ex1 = net.bind(mx.cpu(), args)
    out1 = ex1.forward(is_train=False)[0]
    fn1 = ex1._fwd_cache[False]
    assert fn1.num_compiles == 1 and fn1.num_hits == 0

    ex2 = net.bind(mx.cpu(), args)
    out2 = ex2.forward(is_train=False)[0]
    fn2 = ex2._fwd_cache[False]
    assert fn2.num_compiles == 0 and fn2.num_hits == 1
    np.testing.assert_array_equal(out1.asnumpy(), out2.asnumpy())


def test_fused_apply_warm_reload_compiles_nothing(tmp_path):
    cc.configure(str(tmp_path))

    def one_step():
        net = nn.Dense(8, in_units=16, prefix="cc_fused_")
        net.initialize(force_reinit=True)
        trainer = gluon.Trainer(net.collect_params(), "sgd",
                                {"learning_rate": 0.1})
        with autograd.record():
            loss = net(nd.array(
                np.random.rand(4, 16).astype(np.float32))).sum()
        loss.backward()
        trainer.step(4)

    one_step()
    count = _site_count("fused_apply")
    assert count >= 1
    one_step()
    assert _site_count("fused_apply") == count


def test_train_step_warm_reload_and_identical_math(tmp_path):
    """The warm TrainStep compiles nothing AND the deserialized
    executable computes the exact same training trajectory as the
    freshly compiled one."""
    cc.configure(str(tmp_path))
    x = np.random.rand(8, 8).astype(np.float32)
    y = np.random.rand(8, 4).astype(np.float32)

    def run(seed):
        mx.random.seed(seed)
        net = nn.Dense(4, in_units=8, prefix="cc_step_")
        net.initialize(force_reinit=True)
        step = TrainStep(net, gloss.L2Loss(), optimizer="sgd",
                         optimizer_params={"learning_rate": 0.1})
        losses = [float(np.asarray(step(x, y))) for _ in range(3)]
        return losses

    cold = run(11)
    count = _site_count("train_step")
    assert count >= 1
    warm = run(11)
    assert _site_count("train_step") == count   # loaded, not compiled
    assert warm == cold                          # bit-identical math


# -- pad-to-bucket canonicalization --------------------------------------------

def test_pad_to_buckets_eliminates_off_ladder_traces(tmp_path):
    w = nd.array(np.random.rand(4, 3).astype(np.float32))

    def fwd(w_, x):
        return nd.dot(x, w_)

    op = CachedOp(fwd, num_params=1).pad_to_buckets(8)
    for rows in (1, 2, 4, 8):                   # warm the ladder
        op.inference(w, nd.array(
            np.random.rand(rows, 4).astype(np.float32)))
    warm = op.num_traces
    assert warm == 4
    for rows in (3, 5, 6, 7):                   # off-ladder shapes
        xv = np.random.rand(rows, 4).astype(np.float32)
        out = op.inference(w, nd.array(xv))
        assert out.shape == (rows, 3)
        assert np.allclose(out.asnumpy(), xv @ w.asnumpy(), atol=1e-5)
    assert op.num_traces == warm                # zero new traces


def test_pad_to_buckets_multi_output_and_overflow():
    w = nd.array(np.random.rand(4, 3).astype(np.float32))

    def fwd(w_, x):
        h = nd.dot(x, w_)
        return [h, h * 2]

    op = CachedOp(fwd, num_params=1).pad_to_buckets([2, 4])
    op.inference(w, nd.array(np.random.rand(4, 4).astype(np.float32)))
    t = op.num_traces
    xv = np.random.rand(3, 4).astype(np.float32)
    o1, o2 = op.inference(w, nd.array(xv))
    assert op.num_traces == t
    assert o1.shape == (3, 3) and o2.shape == (3, 3)
    assert np.allclose(o2.asnumpy(), 2 * o1.asnumpy(), atol=1e-6)
    # Above the ladder: runs unpadded (its own signature), never rejects.
    b1, _ = op.inference(w, nd.array(
        np.random.rand(6, 4).astype(np.float32)))
    assert b1.shape == (6, 3)
    assert op.num_traces == t + 1


# -- distribution --------------------------------------------------------------

def test_localbus_rank1_pulls_rank0_entries(tmp_path):
    jnp = _jnp()
    bus = LocalBus(num_workers=2)

    def f(x):
        return jnp.sqrt(x + 3)

    x = jnp.ones((8,))
    # Rank 0 compiles + publishes.
    cc.configure(str(tmp_path / "rank0"))
    cc.set_distributor(CacheDistributor(bus.endpoint(0)))
    cf0 = cc.cached_compile(f, "dist")
    out0 = cf0(x)
    assert cf0.num_compiles == 1
    assert len(bus._cc) == 1
    # Rank 1, empty local cache, pulls instead of compiling.
    cc.reset()
    cc.configure(str(tmp_path / "rank1"))
    cc.set_distributor(CacheDistributor(bus.endpoint(1)))
    hit0 = _counter("mx_compile_cache_hits_total", site="dist",
                    source="remote")
    cf1 = cc.cached_compile(f, "dist")
    out1 = cf1(x)
    assert cf1.num_compiles == 0 and cf1.num_hits == 1
    assert _counter("mx_compile_cache_hits_total", site="dist",
                    source="remote") == hit0 + 1
    assert np.allclose(np.asarray(out0), np.asarray(out1))
    # The pulled entry was committed locally: NEXT start needs no pod.
    cc.set_distributor(None)
    cf2 = cc.cached_compile(f, "dist")
    cf2(x)
    assert cf2.num_compiles == 0 and cf2.num_hits == 1


def test_attach_kvstore_prefetch_warms_joiner_store(tmp_path):
    """Pod prefetch: attach_kvstore runs ONE cc_probe(None) enumeration
    round and commits every missing entry to the joiner's disk store —
    so a later start hits disk with no pod traffic at all."""
    jnp = _jnp()
    bus = LocalBus(num_workers=2)

    def f(x):
        return jnp.sqrt(x + 3)

    def g(x):
        return jnp.cos(x) * 2

    x = jnp.ones((8,))
    # Rank 0 compiles + publishes two entries.
    cc.configure(str(tmp_path / "rank0"))
    cc.set_distributor(CacheDistributor(bus.endpoint(0)))
    cc.cached_compile(f, "pf_a")(x)
    cc.cached_compile(g, "pf_b")(x)
    assert len(bus._cc) == 2
    # cc_probe(None) enumerates every held key in one round.
    assert sorted(bus.cc_probe(None)) == sorted(bus._cc)
    # Rank 1 joins with an EMPTY store: attach prefetches both entries
    # onto disk before any trace happens.
    cc.reset()
    cc.configure(str(tmp_path / "rank1"))
    pre0 = _counter("mx_compile_cache_prefetched_total")
    dist = cc.attach_kvstore(bus.endpoint(1))
    assert dist is not None
    assert _counter("mx_compile_cache_prefetched_total") == pre0 + 2
    assert len(cc.active_store().keys()) == 2
    # Disk-only from here: drop the distributor, both sites still hit.
    cc.set_distributor(None)
    cf = cc.cached_compile(f, "pf_a")
    cf(x)
    assert cf.num_compiles == 0 and cf.num_hits == 1
    # Re-attach is idempotent: everything already local, nothing pulled.
    cc.attach_kvstore(bus.endpoint(1))
    assert _counter("mx_compile_cache_prefetched_total") == pre0 + 2


def test_shared_filesystem_mode_skips_kvstore_channel(tmp_path,
                                                      monkeypatch):
    """MXNET_COMPILE_CACHE_SHARED=1 (every rank's cache dir is one
    shared filesystem): attach_kvstore becomes a no-op — the common
    directory already distributes entries, and pushing them over the
    kvstore would only duplicate bytes."""
    bus = LocalBus(num_workers=2)
    cc.configure(str(tmp_path / "shared"))
    monkeypatch.setenv("MXNET_COMPILE_CACHE_SHARED", "1")
    assert cc.shared_filesystem()
    assert cc.attach_kvstore(bus.endpoint(0)) is None
    assert cc._active_distributor() is None
    jnp = _jnp()
    cf = cc.cached_compile(lambda x: jnp.cos(x) + 1, "shared_site")
    cf(jnp.ones((4,)))
    assert cf.num_compiles == 1
    assert bus._cc == {}, "entry leaked onto the kvstore channel"
    # Without the flag the same call wires a distributor.
    monkeypatch.setenv("MXNET_COMPILE_CACHE_SHARED", "0")
    assert cc.attach_kvstore(bus.endpoint(0)) is not None


def test_shared_directory_serves_two_ranks(tmp_path, monkeypatch):
    """Two 'ranks' (two stores) pointed at ONE directory: rank 0's
    commit is rank 1's local hit — the shared-filesystem distribution
    story, with no kvstore at all. Entries commit atomically, so a
    concurrent double-compile of the same key is just a benign
    double-commit of identical bytes."""
    jnp = _jnp()
    shared = str(tmp_path / "nfs")

    def f(x):
        return jnp.sqrt(x + 7)

    x = jnp.ones((8,))
    cc.configure(shared)
    cf0 = cc.cached_compile(f, "nfs_site")
    out0 = cf0(x)
    assert cf0.num_compiles == 1
    # "Another rank": fresh process-level state, same directory.
    cc.reset()
    cc.configure(shared)
    cf1 = cc.cached_compile(f, "nfs_site")
    out1 = cf1(x)
    assert cf1.num_compiles == 0 and cf1.num_hits == 1
    assert np.allclose(np.asarray(out0), np.asarray(out1))
    # Concurrent same-key commits (the NFS race): both writers go
    # through tmp+rename, the survivor is a valid entry.
    store = cc.active_store()
    key = make_key(["race"])
    import threading

    def put():
        store.put(key, b"payload-bytes", {"site": "race"})

    threads = [threading.Thread(target=put) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    meta, payload = store.get(key)
    assert payload == b"payload-bytes"


def test_distributor_entry_size_bound(tmp_path):
    bus = LocalBus(num_workers=2)
    dist = CacheDistributor(bus.endpoint(0), max_entry_bytes=64)
    assert not dist.publish("k" * 32, {}, b"x" * 100)   # over bound
    assert bus._cc == {}
    assert dist.publish("k" * 32, {}, b"x" * 10)
    assert dist.fetch("k" * 32)[1] == b"x" * 10
    assert dist.fetch("absent") is None


def test_localbus_cc_drop_oldest(monkeypatch):
    bus = LocalBus(num_workers=1)
    monkeypatch.setattr(LocalBus, "MAX_CC_BYTES", 250)
    for i in range(4):
        bus.cc_push("key%d" % i, {}, b"x" * 100)
    assert list(bus._cc) == ["key2", "key3"]    # oldest dropped
    assert bus.cc_probe(["key0", "key3"]) == ["key3"]


# -- the CLI -------------------------------------------------------------------

def _tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(_ROOT, "tools", "%s.py" % name))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_compile_cache_tool_inspect_verify_gc(tmp_path, fault_fs):
    cc.configure(str(tmp_path))
    jnp = _jnp()
    for i in range(3):
        cc.cached_compile(lambda x, i=i: x + i, "tool_site")(
            jnp.ones((4,)))
    tool = _tool("compile_cache")
    info = tool.inspect(str(tmp_path))
    assert info["entries"] == 3
    assert info["by_site"]["tool_site"]["entries"] == 3
    assert info["warm_restart_saves_seconds"] > 0
    # Damage one entry: inspect reports it WITHOUT deleting it (a
    # read-only diagnostic must keep the evidence for verify).
    store = CompileCacheStore(str(tmp_path))
    victim = store.keys()[0]
    fault_fs.corrupt(store.path_for(victim), flip_byte_at=200)
    info = tool.inspect(str(tmp_path))
    assert sum(1 for e in info["detail"] if e["damaged"]) == 1
    assert os.path.exists(store.path_for(victim))
    rep = tool.verify(str(tmp_path))
    assert rep["valid"] == 2 and rep["damaged"] == 1
    assert rep["damaged_keys"] == [victim]
    rep = tool.verify(str(tmp_path), remove=True)
    assert rep["damaged"] == 1
    assert len(store.keys()) == 2
    # GC down to (almost) nothing keeps the newest entry only.
    out = tool.gc(str(tmp_path), max_mb=0)
    assert out["bytes_after"] == 0 and out["removed_entries"] == 2


# -- 2-process acceptance ------------------------------------------------------

_PROG = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "compile_cache_prog.py")
_ENV = {
    "JAX_PLATFORMS": "cpu",
    "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
}


def _can_bind_localhost():
    try:
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        s.close()
        return True
    except OSError:
        return False


def test_two_process_rank1_compiles_nothing(tmp_path):
    """ISSUE 11 acceptance: rank 1 starts with an EMPTY local cache,
    pulls rank 0's entries over the kvstore cc channel, and performs
    ZERO local compiles at the shared sites — and the pulled entries
    land on rank 1's own disk for its next restart."""
    if not _can_bind_localhost():
        pytest.skip("localhost sockets unavailable (multi-process "
                    "kvstore needs them)")
    codes = launch_local(2, 1, [sys.executable, _PROG, str(tmp_path)],
                         env_extra=_ENV, timeout=300)
    assert codes == [0, 0], codes
    results = {}
    for rank in (0, 1):
        with open(str(tmp_path / ("result_rank%d.json" % rank))) as f:
            results[rank] = json.load(f)
    # Rank 0 paid the compiles (3 ladder buckets + 1 chunk + 1 step).
    r0 = results[0]["compile_counts"]
    assert r0.get("cached_op", 0) == 3
    assert r0.get("fused_apply", 0) == 1
    assert r0.get("train_step", 0) == 1
    # Rank 1 compiled NOTHING at the shared sites.
    r1 = results[1]["compile_counts"]
    assert r1.get("cached_op", 0) == 0, results[1]
    assert r1.get("fused_apply", 0) == 0, results[1]
    assert r1.get("train_step", 0) == 0, results[1]
    # Every executable was a remote hit (counted), committed to rank
    # 1's own disk: its entry set ends up identical to rank 0's, so
    # rank 1's NEXT restart doesn't even need the pod.
    remote_hits = sum(v for k, v in results[1]["hits"].items()
                      if k.endswith("/remote"))
    assert results[1]["local_entries"] == results[0]["local_entries"]
    assert remote_hits == len(results[1]["local_entries"]) >= 5, \
        results[1]["hits"]
