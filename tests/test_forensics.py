"""Flight recorder & failure forensics (ISSUE 7): anomaly-triggered
diagnostic bundles, hang watchdog, numeric-health guards, memory/compile
accounting — plus the telemetry follow-ups (exemplars, cross-rank
histogram merge, flamegraph diffing) and the StepMonitor resume-EWMA
bugfix. Includes the induced-failure acceptance tests: a hung step, a
NaN gradient and a recompile storm each auto-produce an atomically
committed bundle readable by tools/diagnose.py."""
import importlib.util
import json
import os
import sys
import threading
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import data, gluon, nd, recordio, telemetry
from mxnet_tpu.telemetry import aggregate as tagg
from mxnet_tpu.telemetry import flamegraph as tflame
from mxnet_tpu.telemetry import memstats as tmem
from mxnet_tpu.telemetry import metrics as tmetrics
from mxnet_tpu.telemetry import trace
from mxnet_tpu.telemetry import watchdog as twd

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _tool(name):
    """Import a tools/ script as a module (the test_export pattern)."""
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(_ROOT, "tools", "%s.py" % name))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class _FakeClock:
    def __init__(self):
        self.t = 1000.0

    def __call__(self):
        return self.t


@pytest.fixture(autouse=True)
def _clean_watchdog_lanes():
    twd.reset()
    yield
    twd.reset()


def _monitor_recorder(tmp_path, **recorder_kw):
    mon = telemetry.StepMonitor(warn_interval_s=1e9)
    rec = telemetry.FlightRecorder(str(tmp_path), rank=0,
                                   rate_limit_s=0.0, **recorder_kw)
    rec.attach(mon)
    return mon, rec


# -- flight recorder ----------------------------------------------------------

def test_bundle_contents_and_atomic_name(tmp_path):
    """An anomaly produces one diag.rank<R>.<seq>.json holding thread
    stacks, buffered spans, a registry snapshot, anomaly history and
    env/knob state."""
    mon, rec = _monitor_recorder(tmp_path)
    with trace.span("forensic_probe", step=3):
        pass
    mon.record_anomaly("probe", "something broke")
    assert len(rec.bundles) == 1
    path = rec.bundles[0]
    assert os.path.basename(path) == "diag.rank0.000001.json"
    with open(path) as f:
        bundle = json.load(f)
    meta = bundle["meta"]
    assert meta["format"] == "mxnet_tpu.diag_bundle/1"
    assert meta["kind"] == "probe" and meta["rank"] == 0
    # this (the detecting) thread's stack is present, with real frames
    me = [t for t in bundle["threads"]
          if t["thread_id"] == threading.get_ident()]
    assert me and any("test_forensics" in f["file"]
                      for f in me[0]["stack"])
    assert any(e["name"] == "forensic_probe" for e in bundle["spans"])
    names = {fam["name"] for fam in bundle["registry"]["counters"]}
    assert "mx_anomalies_total" in names
    hist = bundle["anomalies"]["history"]
    assert hist and hist[-1]["kind"] == "probe"
    assert bundle["env"]["knobs"]["MXNET_FUSED_UPDATE"] in (True, False)
    assert bundle["device_memory"]


def test_recorder_rate_limit_per_kind(tmp_path):
    clock = _FakeClock()
    mon = telemetry.StepMonitor(warn_interval_s=1e9)
    rec = telemetry.FlightRecorder(str(tmp_path), rank=0,
                                   rate_limit_s=60.0, clock=clock)
    rec.attach(mon)
    mon.record_anomaly("kind_a", "first")
    mon.record_anomaly("kind_a", "suppressed")
    mon.record_anomaly("kind_b", "other kind fires immediately")
    assert len(rec.bundles) == 2
    # the suppressed anomaly is accounted on the NEXT committed bundle
    # (kind_b's) — suppression loses the bundle, never the count
    with open(rec.bundles[1]) as f:
        assert json.load(f)["meta"]["suppressed_since_last"] == \
            {"kind_a": 1}
    clock.t += 61.0
    mon.record_anomaly("kind_a", "after window")
    assert len(rec.bundles) == 3
    with open(rec.bundles[-1]) as f:
        bundle = json.load(f)
    # full history kept regardless of suppression
    assert len(bundle["anomalies"]["history"]) == 4


def test_recorder_sequence_resumes_across_restart(tmp_path):
    mon, rec = _monitor_recorder(tmp_path)
    mon.record_anomaly("x", "one")
    rec2 = telemetry.FlightRecorder(str(tmp_path), rank=0)
    path = rec2.capture("y", "after restart")
    assert os.path.basename(path) == "diag.rank0.000002.json"


def test_kill_mid_bundle_leaves_no_torn_json(tmp_path, fault_fs):
    """A crash at any byte of a bundle commit leaves either a complete
    bundle or nothing: the rename fails -> no diag.*.json appears, no
    stray staging file survives, and the next capture succeeds."""
    from mxnet_tpu.telemetry.recorder import DIAG_RE

    mon, rec = _monitor_recorder(tmp_path)
    fault_fs.fail_next_renames(1)
    assert rec.capture("hang", "doomed commit") is None
    assert fault_fs.renames_failed == 1
    leftovers = os.listdir(str(tmp_path))
    assert not [n for n in leftovers if DIAG_RE.match(n)], leftovers
    assert not [n for n in leftovers if ".tmp." in n], leftovers
    path = rec.capture("hang", "retry")
    assert path is not None and os.path.exists(path)
    with open(path) as f:
        assert json.load(f)["meta"]["kind"] == "hang"


def test_failed_commit_short_backoff_not_full_window(tmp_path, fault_fs):
    """A transient commit failure must not suppress the kind for the
    whole rate_limit_s window with zero evidence on disk: only a short
    failure backoff applies (bounding repeated collection cost while
    storage is down), then the next anomaly retries and commits."""
    clock = _FakeClock()
    mon = telemetry.StepMonitor(warn_interval_s=1e9)
    rec = telemetry.FlightRecorder(str(tmp_path), rank=0,
                                   rate_limit_s=600.0, fail_backoff_s=5.0,
                                   clock=clock)
    rec.attach(mon)
    fault_fs.fail_next_renames(1)
    mon.record_anomaly("blip", "disk hiccup")
    assert rec.bundles == []
    # inside the failure backoff: collection cost is NOT re-paid
    mon.record_anomaly("blip", "still backing off")
    assert rec.bundles == []
    clock.t += 6.0                 # past fail_backoff_s, << rate_limit_s
    mon.record_anomaly("blip", "disk recovered")
    assert len(rec.bundles) == 1
    mon.record_anomaly("blip", "now rate limited")     # limiter armed
    assert len(rec.bundles) == 1


def test_recorder_extra_sources_and_failure_isolation(tmp_path):
    mon, rec = _monitor_recorder(tmp_path)
    rec.add_source("lr", lambda: 0.125)
    rec.add_source("broken", lambda: 1 / 0)
    path = rec.capture("manual", "")
    with open(path) as f:
        bundle = json.load(f)
    assert bundle["extra"]["lr"] == 0.125
    assert "error" in bundle["extra"]["broken"]


# -- hang watchdog ------------------------------------------------------------

def _stuck_step(event):
    """A deliberately hung 'step': begins the lane and blocks."""
    twd.begin("step")
    try:
        event.wait(10.0)
    finally:
        twd.end("step")


def test_hung_step_produces_bundle_with_stuck_stack(tmp_path):
    """ACCEPTANCE: a hung step fires `step_hang` and the bundle holds
    the stuck thread's stack (the frame that is actually blocked)."""
    mon, rec = _monitor_recorder(tmp_path)
    event = threading.Event()
    thread = threading.Thread(target=_stuck_step, args=(event,),
                              name="hung-step-thread", daemon=True)
    thread.start()
    try:
        time.sleep(0.05)
        wd = telemetry.HangWatchdog(monitor=mon, min_deadline_s=0.01)
        fired = wd.check()
        assert fired == ["step"]
        assert mon.anomaly_counts.get("step_hang") == 1
        with open(rec.bundles[-1]) as f:
            bundle = json.load(f)
        assert bundle["meta"]["kind"] == "step_hang"
        stuck = [t for t in bundle["threads"]
                 if t["name"] == "hung-step-thread"]
        assert stuck, [t["name"] for t in bundle["threads"]]
        assert any(f["func"] == "_stuck_step"
                   for f in stuck[0]["stack"])
        # the lane state names the stuck thread
        lane = bundle["watchdog"]["step"]
        assert lane["busy_s"] > 0 and lane["thread_id"] == thread.ident
        # readable by the diagnose tool
        diagnose = _tool("diagnose")
        text = diagnose.summarize(diagnose.load(rec.bundles[-1]))
        assert "step_hang" in text and "_stuck_step" in text
        assert "IN FLIGHT" in text
    finally:
        event.set()
        thread.join()


def test_watchdog_idle_and_completed_lanes_never_fire(tmp_path):
    mon, rec = _monitor_recorder(tmp_path)
    wd = telemetry.HangWatchdog(monitor=mon, min_deadline_s=0.0)
    assert wd.check() == []                  # no lanes at all
    twd.begin("step")
    twd.end("step")
    assert wd.check() == []                  # completed work is idle
    assert rec.bundles == []


def test_watchdog_ewma_deadline_and_refire_backoff():
    for _ in range(3):
        twd.begin("lane_x")
        time.sleep(0.02)
        twd.end("lane_x")
    wd = telemetry.HangWatchdog(min_deadline_s=0.001, factor=5.0)
    deadline = wd.deadline_for("lane_x")
    # factor x EWMA of the ~20ms completions, not the 1ms floor
    assert 0.05 < deadline < 1.0
    # in-flight past the deadline fires once, then backs off a full
    # deadline before refiring
    twd.begin("lane_y")
    wd.watch("lane_y", min_deadline_s=0.01)
    time.sleep(0.02)
    assert wd.check() == ["lane_y"]
    assert wd.check() == []                  # within backoff window
    time.sleep(0.02)
    assert wd.check() == ["lane_y"]          # persistent hang refires
    twd.end("lane_y")


def test_one_watchdog_firing_does_not_suppress_another():
    """Refire bookkeeping is per-instance: a second watchdog over the
    same (shared) lane must still see and record the hang."""
    twd.begin("lane_z")
    time.sleep(0.02)
    first = telemetry.HangWatchdog(min_deadline_s=0.01)
    second = telemetry.HangWatchdog(min_deadline_s=0.01)
    assert first.check() == ["lane_z"]
    assert second.check() == ["lane_z"]
    twd.end("lane_z")


def test_unique_lanes_keep_instances_apart():
    """A lane is a single slot: two instruments of the same kind claim
    distinct lanes, so instance B completing cannot clear instance A's
    in-flight marker (and A's hang still fires with B healthy)."""
    lane_a = twd.unique_lane("serving")
    lane_b = twd.unique_lane("serving")
    assert lane_a == "serving" and lane_b == "serving#2"
    twd.begin(lane_a)              # A wedges mid-batch
    time.sleep(0.02)
    twd.begin(lane_b)              # B turns over a healthy batch
    twd.end(lane_b)
    wd = telemetry.HangWatchdog(min_deadline_s=0.01)
    assert wd.check() == [lane_a]
    # instance lanes inherit the base kind
    assert wd.fired[-1][1] == "serving_hang"
    twd.end(lane_a)


def test_train_step_heartbeats_the_step_lane():
    net = gluon.nn.HybridSequential(prefix="wd_hb_")
    net.add(gluon.nn.Dense(4, in_units=8, prefix="fc_"))
    net.initialize(mx.init.Xavier())
    from mxnet_tpu.parallel import TrainStep, make_mesh

    step = TrainStep(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                     optimizer="sgd", mesh=make_mesh())
    x = np.random.rand(8, 8).astype(np.float32)
    y = np.random.randint(0, 4, 8)
    float(np.asarray(step(x, y)))
    lanes = twd.lane_snapshot()
    assert lanes["step"]["completed"] >= 1
    assert lanes["step"]["busy_s"] is None   # idle after the step
    assert lanes["step"]["ewma_s"] > 0


# -- numeric guards -----------------------------------------------------------

def test_check_flat_defers_sync_until_flush(tmp_path):
    """The fused-apply hook path queues device-side results; the
    violation (and its one host sync) lands at flush(), after every
    bucket has dispatched."""
    import jax.numpy as jnp

    mon, rec = _monitor_recorder(tmp_path)
    guard = telemetry.NumericGuard(monitor=mon, every=1)
    guard.check_flat(jnp.array([1.0, np.nan]), optimizer="sgd")
    guard.check_flat(jnp.array([1.0, 2.0]), optimizer="sgd")
    assert not mon.anomaly_counts.get("nonfinite")     # still queued
    assert guard.flush() is False
    assert mon.anomaly_counts.get("nonfinite") == 1
    assert guard.flush() is True                       # queue drained


def test_numeric_guard_loss_cadence_and_halt(tmp_path):
    mon, rec = _monitor_recorder(tmp_path)
    guard = telemetry.NumericGuard(monitor=mon, every=2, halt=False)
    assert guard.check_loss(1.25, step=1)            # cadence: skipped
    assert guard.check_loss(float("nan"), step=2) is False
    assert mon.anomaly_counts.get("nonfinite") == 1
    halting = telemetry.NumericGuard(monitor=mon, every=1, halt=True)
    with pytest.raises(telemetry.NonFiniteError):
        halting.check_loss(float("inf"), step=3, batch_ids=[9, 4])
    with open(rec.bundles[-1]) as f:
        bundle = json.load(f)
    assert "step 3" in bundle["meta"]["msg"]
    assert "[9, 4]" in bundle["meta"]["msg"]


def _pack_records(td, n):
    rec = os.path.join(str(td), "poison.rec")
    idx = os.path.join(str(td), "poison.idx")
    w = recordio.MXIndexedRecordIO(idx, rec, "w")
    for i in range(n):
        w.write_idx(i, recordio.pack(
            recordio.IRHeader(0, float(i), i, 0), str(i).encode()))
    w.close()
    return rec


def test_nan_grad_bundle_names_batch_ids(tmp_path):
    """ACCEPTANCE: an injected NaN gradient through the fused update
    produces a `nonfinite` bundle naming the in-flight batch ids from
    the real data pipeline."""
    def decode(record):
        header, payload = recordio.unpack(record)
        sid = int(payload.decode())
        return np.float32(header.label), np.full((3,), sid, np.float32)

    mon, rec = _monitor_recorder(tmp_path / "diag")
    pipe = data.DataPipeline(_pack_records(tmp_path, 12), decode,
                             batch_size=4, shuffle=True, seed=3,
                             num_shards=1, shard_index=0,
                             decode_threads=0, prefetch=0, place=False)
    rec.watch_pipeline(pipe)

    p = gluon.Parameter("poison_w", shape=(16,))
    p.initialize(init=mx.init.Constant(1.0))
    trainer = gluon.Trainer([p], "sgd", {"learning_rate": 0.1},
                            fused=True)
    guard = telemetry.NumericGuard(monitor=mon, every=1)
    guard.install(trainer._applier)
    guard.watch_pipeline(pipe)

    with pipe:
        batch = next(pipe)                   # the poison batch
        p.grad()[:] = np.ones(16, np.float32)
        trainer.step(1)                      # clean step passes
        assert not mon.anomaly_counts.get("nonfinite")
        grad = np.ones(16, np.float32)
        grad[7] = np.nan
        p.grad()[:] = grad
        trainer.step(1)
    assert mon.anomaly_counts.get("nonfinite") == 1
    expected_ids = [int(i) for i in np.asarray(batch.index).ravel()]
    with open(rec.bundles[-1]) as f:
        bundle = json.load(f)
    assert bundle["meta"]["kind"] == "nonfinite"
    assert str(expected_ids) in bundle["meta"]["msg"]
    # pipeline provenance rides in the bundle's data section too
    assert bundle["data"][0]["last_batch"]["ids"] == expected_ids
    # guarded weights: check cost is O(buckets) — exactly one grad-site
    # check ran per armed apply
    checks = tmetrics.REGISTRY.get("mx_numeric_checks_total")
    assert checks.labels(site="grad").value >= 2


def test_recompile_storm_bundle(tmp_path):
    """ACCEPTANCE: a shape-churn recompile storm auto-produces a bundle
    through the existing StepMonitor recompile detector."""
    from mxnet_tpu.cached_op import CachedOp

    mon, rec = _monitor_recorder(tmp_path)
    op = mon.attach(CachedOp(lambda a: a * 2 + 1))
    for n in (3, 5, 7):                      # three shape signatures
        op(nd.array(np.ones(n, np.float32))).asnumpy()
    assert mon.anomaly_counts.get("recompile") == 2
    with open(rec.bundles[-1]) as f:
        bundle = json.load(f)
    assert bundle["meta"]["kind"] == "recompile"
    assert bundle["threads"] and bundle["registry"]["counters"]
    diagnose = _tool("diagnose")
    text = diagnose.summarize(diagnose.load(rec.bundles[-1]))
    assert "recompile" in text


# -- memory & compile accounting ----------------------------------------------

def test_device_memory_gauges_and_peak():
    import jax.numpy as jnp

    keep = jnp.ones((256, 256), jnp.float32) + 0
    keep.block_until_ready()
    sample = tmem.sample_device_memory()
    assert sample
    # the array lives on ONE of the virtual mesh devices
    dev, rec = max(sample.items(), key=lambda kv: kv[1]["bytes"])
    assert rec["bytes"] >= keep.nbytes
    assert rec["peak_bytes"] >= rec["bytes"]
    gauge = tmetrics.REGISTRY.get("mx_device_live_bytes")
    assert gauge.labels(device=dev).value == rec["bytes"]
    del keep


def test_compile_seconds_sites():
    from mxnet_tpu.cached_op import CachedOp

    fam = tmetrics.REGISTRY.get("mx_compile_seconds")
    before = fam.labels(site="cached_op").snapshot()["count"]
    op = CachedOp(lambda a: a + 1)
    op(nd.array(np.ones(4, np.float32))).asnumpy()   # compile
    op(nd.array(np.ones(4, np.float32))).asnumpy()   # cache hit
    after = fam.labels(site="cached_op").snapshot()["count"]
    assert after == before + 1
    # fused apply site: one fill per chunk executable
    fused_before = fam.labels(site="fused_apply").snapshot()["count"]
    p = gluon.Parameter("cmp_w", shape=(8,))
    p.initialize(init=mx.init.Constant(1.0))
    tr = gluon.Trainer([p], "sgd", {"learning_rate": 0.1}, fused=True)
    p.grad()[:] = np.ones(8, np.float32)
    tr.step(1)
    tr.step(1)
    fused_after = fam.labels(site="fused_apply").snapshot()["count"]
    assert fused_after == fused_before + 1
    stats = tmem.compile_stats()
    assert stats["cached_op"]["count"] >= 1
    assert stats["fused_apply"]["total_s"] > 0


# -- exemplars (ROADMAP telemetry follow-up) ----------------------------------

def test_histogram_exemplars_link_spans(tmp_path):
    prev = tmetrics.set_exemplars(True)
    try:
        reg = tmetrics.Registry()
        h = reg.histogram("exemplar_seconds", "probe",
                          labels=("phase",))
        with trace.span("exemplar_span"):
            sid = trace.current_span_id()
            assert sid is not None
            h.labels(phase="p99").observe(0.2)
        h.labels(phase="p99").observe(0.3)   # outside any span: no link
        # exemplar syntax is only legal in OpenMetrics: the classic
        # 0.0.4 exposition must stay clean (a real Prometheus scraper
        # rejects the whole scrape otherwise), the openmetrics=True
        # rendering carries the links + the required # EOF terminator
        assert "span_id" not in reg.render_prometheus()
        text = reg.render_prometheus(openmetrics=True)
        assert '# {span_id="%s"} 0.2' % sid in text
        assert text.endswith("# EOF\n")
        collected = tmetrics.collect_exemplars(reg)
        assert collected and collected[0]["span_id"] == sid
        assert collected[0]["labels"] == {"phase": "p99"}
        # the span event carries the matching id for cross-lookup
        events = trace.chrome_trace()["traceEvents"]
        linked = [e for e in events
                  if (e.get("args") or {}).get("span_id") == sid]
        assert linked and linked[0]["name"] == "exemplar_span"
        # recorder bundles include the exemplars
        mon, rec = _monitor_recorder(tmp_path)
        rec._registry = reg
        path = rec.capture("probe", "")
        with open(path) as f:
            assert json.load(f)["exemplars"][0]["span_id"] == sid
    finally:
        tmetrics.set_exemplars(prev)
        trace.set_span_ids(False)


def test_metrics_endpoint_negotiates_openmetrics():
    """The /metrics endpoint serves exemplars ONLY to scrapers whose
    Accept header asks for OpenMetrics; classic scrapers keep getting
    clean 0.0.4 text."""
    import urllib.request

    prev = tmetrics.set_exemplars(True)
    reg = tmetrics.Registry()
    h = reg.histogram("negotiate_seconds", "probe")
    try:
        with trace.span("negotiate_span"):
            h.observe(0.01)
        server = tmetrics.start_http_server(port=0, registry=reg)
        try:
            plain = urllib.request.urlopen(server.url, timeout=5)
            body = plain.read().decode()
            assert "span_id" not in body and "# EOF" not in body
            assert "0.0.4" in plain.headers["Content-Type"]
            req = urllib.request.Request(server.url, headers={
                "Accept": "application/openmetrics-text; version=1.0.0"})
            om = urllib.request.urlopen(req, timeout=5)
            om_body = om.read().decode()
            assert "span_id" in om_body and om_body.endswith("# EOF\n")
            assert "openmetrics-text" in om.headers["Content-Type"]
        finally:
            server.close()
    finally:
        tmetrics.set_exemplars(prev)
        trace.set_span_ids(False)


def test_exemplars_off_by_default():
    reg = tmetrics.Registry()
    h = reg.histogram("no_exemplar_seconds", "probe")
    with trace.span("unlinked"):
        h.observe(0.01)
    assert "span_id" not in reg.render_prometheus()
    assert tmetrics.collect_exemplars(reg) == []


# -- cross-rank histogram aggregation (ROADMAP follow-up) ---------------------

def test_fleet_histogram_sum_without_rank_two_ranks():
    regs = {0: tmetrics.Registry(), 1: tmetrics.Registry()}
    for rank, reg in regs.items():
        h = reg.histogram("fleet_lat_seconds", "latency",
                          labels=("server",))
        for i in range(10):
            # rank 0 fast, rank 1 slow — the merged p99 must see both
            h.labels(server="s1").observe(0.001 if rank == 0 else 0.1)
    bus = tagg.LocalBus(num_workers=2)
    agg1 = tagg.Aggregator(bus.endpoint(1), registry=regs[1],
                           interval_s=1e9)
    agg0 = tagg.Aggregator(bus.endpoint(0), registry=regs[0],
                           interval_s=1e9)
    agg1.step()
    fleet = agg0.step()
    fam = fleet.get("fleet_lat_seconds")
    per_rank = {v for v, _ in fam.collect()}
    assert ("s1", "0") in per_rank and ("s1", "1") in per_rank
    merged = fam.labels(server="s1", rank="all")
    assert merged.snapshot()["count"] == 20
    assert merged.snapshot()["sum"] == pytest.approx(10 * 0.001 + 10 * 0.1)
    assert merged.snapshot()["min"] == pytest.approx(0.001)
    assert merged.snapshot()["max"] == pytest.approx(0.1)
    # one honest fleet quantile: the p99 lives in rank 1's regime
    assert agg0.merged_quantile("fleet_lat_seconds", 0.99,
                                server="s1") > 0.05
    # exposition carries the merged series next to the per-rank ones
    assert 'rank="all"' in fleet.render_prometheus()


# -- flamegraph diffing (ROADMAP follow-up) -----------------------------------

def test_flame_diff_top_ranks_regressions(tmp_path, capsys):
    before = "main;fwd;opA 900\nmain;fwd;opB 90\nmain;io 10\n"
    after = "main;fwd;opA 450\nmain;fwd;opB 540\nmain;io 10\n"
    rows = tflame.diff_top(before, after)
    assert rows[0]["op"] == "opB"
    assert rows[0]["delta_pp"] == pytest.approx(45.0)
    assert rows[-1]["op"] == "opA"
    assert rows[-1]["delta_pp"] == pytest.approx(-45.0)
    text = tflame.render_diff(before, after)
    assert "opB" in text and "REGRESSED" in text
    # the CLI over two capture files
    b = tmp_path / "before.folded"
    a = tmp_path / "after.folded"
    b.write_text(before)
    a.write_text(after)
    flame_diff = _tool("flame_diff")
    assert flame_diff.main([str(b), str(a), "-k", "5"]) == 0
    out = capsys.readouterr().out
    assert "opB" in out and "+45.00pp" in out


def test_flame_diff_skips_garbage_lines():
    rows = tflame.diff_top("ok;x 100\nnot a valid line\n", "ok;x 50\n")
    assert [r["op"] for r in rows] == ["x"]


# -- StepMonitor resume-EWMA bugfix -------------------------------------------

def test_monitor_resume_does_not_flag_first_post_restore_step():
    """Regression (fake clock): a restored StepMonitor must not flag the
    first post-resume step — which pays restore + recompile cost — as a
    slow_step outlier against the pre-crash steady-state EWMA."""
    clock = _FakeClock()
    mon = telemetry.StepMonitor(slow_factor=3.0, warmup_steps=3,
                                warn_interval_s=1e9, clock=clock)
    for _ in range(10):
        assert mon.observe_step(0.010) == []
    # sanity: mid-run, a 10x step IS an outlier (detector armed)
    assert mon.observe_step(0.100) == ["slow_step"]
    state = mon.state_dict()
    assert state["ewma"] == pytest.approx(mon.ewma_seconds)

    resumed = telemetry.StepMonitor(slow_factor=3.0, warmup_steps=3,
                                    warn_interval_s=1e9, clock=clock)
    resumed.load_state_dict(state)
    # EWMA seeds from the checkpoint, warmup re-arms
    assert resumed.ewma_seconds == pytest.approx(state["ewma"])
    # the slow restore/recompile step: NOT flagged
    assert resumed.observe_step(0.150) == []
    # detection re-arms after warmup and still catches real outliers
    for _ in range(4):
        resumed.observe_step(0.010)
    assert resumed.observe_step(0.200) == ["slow_step"]


def test_monitor_reset_baseline_reenters_warmup():
    mon = telemetry.StepMonitor(warmup_steps=2, warn_interval_s=1e9,
                                clock=_FakeClock())
    for _ in range(5):
        mon.observe_step(0.01)
    mon.reset_baseline()
    assert mon.ewma_seconds is None and mon.steps == 0
    assert mon.observe_step(1.0) == []       # fresh warmup, no flag


# -- diagnose tool: incident merge --------------------------------------------

def test_diagnose_merges_per_rank_bundles_into_one_incident(tmp_path,
                                                            capsys):
    diagnose = _tool("diagnose")
    for rank, ids in ((0, [1, 2]), (1, [7, 8])):
        mon = telemetry.StepMonitor(warn_interval_s=1e9)
        rec = telemetry.FlightRecorder(str(tmp_path), rank=rank,
                                       rate_limit_s=0.0)
        rec.attach(mon)
        guard = telemetry.NumericGuard(monitor=mon, every=1)
        guard.observe_batch(step=5, batch_ids=ids)
        guard.check_loss(float("nan"))
    names = sorted(os.listdir(str(tmp_path)))
    assert names == ["diag.rank0.000001.json", "diag.rank1.000001.json"]
    assert diagnose.main(["--merge", str(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert "INCIDENT kind=nonfinite" in out
    assert "rank(s) [0, 1]" in out
    # the union of in-flight ids across ranks — but only via the msg
    # provenance here; per-rank sections still name their own ids
    assert "[1, 2]" in out and "[7, 8]" in out
    assert "1 bundle(s)" not in out          # both bundles summarized
    assert "2 bundle(s) summarized" in out
