"""Chip-independent performance evidence (VERDICT r4 #4).

The TPU has been unreachable for several rounds, so the perf-critical
properties are asserted at the artifact level instead: what we hand XLA
(StableHLO) and what comes back from compilation (optimized HLO with
buffer assignment) prove the MXU/bandwidth story without a chip:

- TrainStep's jitted step donates parameter/optimizer/aux buffers —
  in-place updates, no double-buffered HBM (the engine-var mutation
  semantics of the reference expressed as XLA aliasing).
- bf16 training emits bf16 dots/convolutions end to end (forward AND
  backward) — the MXU's bf16 path, not f32 upcasts around casts.
- int8 contraction keeps its s8 operands + s32 accumulator through the
  whole compile pipeline (fusion included), not just in the emitted
  StableHLO.

Reference analogue: the cudnn autotune registry trusted cudnnFind's
choice per shape (cudnn_algoreg-inl.h); here the compiler is trusted but
*verified* per artifact.
"""
import re

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import mxnet_tpu as mx
from mxnet_tpu import gluon
from mxnet_tpu.parallel import make_mesh, TrainStep


def _built_step(net, dtype=None, mesh_axes=None):
    """Run one step so TrainStep builds, then return (step, lowered)."""
    step = TrainStep(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                     optimizer="sgd",
                     optimizer_params={"learning_rate": 0.1,
                                       "momentum": 0.9},
                     mesh=make_mesh(mesh_axes or {"dp": 8}), dtype=dtype)
    shape = getattr(net, "_ev_input_shape", (16, 16))
    X = np.random.rand(*shape).astype(np.float32)
    Y = np.zeros(shape[0], dtype=np.float32)
    step(X, Y)
    args = (step._param_vals, step._opt_state, step._aux_vals,
            jax.device_put(jnp.asarray(X), step._data_sharding),
            jax.device_put(jnp.asarray(Y), step._data_sharding),
            jnp.float32(0.1), jnp.float32(1.0), mx.random.next_key())
    return step, step._jitted.lower(*args)


def _mlp():
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(32, activation="relu", in_units=16))
    net.add(gluon.nn.Dense(2, in_units=32))
    net.initialize()
    return net


def test_trainstep_donates_param_and_state_buffers():
    """Every donated argument (params, optimizer state, aux) must alias
    an output in the compiled artifact: the training step updates
    weights in place instead of allocating a second copy of the model."""
    step, lowered = _built_step(_mlp())
    txt = lowered.compile().as_text()
    m = re.search(r"input_output_alias=\{([^}]*(?:\}[^}]*)*?)\}\n", txt) \
        or re.search(r"input_output_alias=\{(.*?)\},", txt, re.S)
    assert m, "no input_output_alias in compiled HLO"
    aliases = m.group(0).count("(")
    n_donated = (len(step._param_vals)
                 + sum(len(t) for t in step._opt_state.values())
                 + len(step._aux_vals))
    assert aliases >= n_donated, (aliases, n_donated)


def test_bf16_training_step_is_bf16_end_to_end():
    """dtype='bfloat16' must reach XLA as bf16 dot_generals for forward
    AND backward — an f32 dot with casts around it would run the MXU in
    fp32 and halve its throughput."""
    _, lowered = _built_step(_mlp(), dtype="bfloat16")
    shlo = lowered.as_text()
    dots = [l for l in shlo.splitlines() if "stablehlo.dot_general" in l]
    # fwd: 2 layers; bwd: dgrad+wgrad chains — at least 4 contractions.
    assert len(dots) >= 4, shlo[:500]
    f32_dots = [l for l in dots if "xbf16" not in l]
    assert not f32_dots, "non-bf16 contractions:\n" + "\n".join(f32_dots)


def test_bf16_conv_step_is_bf16_end_to_end():
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Conv2D(8, 3, padding=1, in_channels=4),
            gluon.nn.Activation("relu"),
            gluon.nn.Flatten(),
            gluon.nn.Dense(2))
    net.initialize()
    net._ev_input_shape = (16, 4, 8, 8)
    _, lowered = _built_step(net, dtype="bfloat16")
    shlo = lowered.as_text()
    convs = [l for l in shlo.splitlines() if "stablehlo.convolution" in l]
    assert convs, "no convolutions found"
    bad = [l for l in convs if "xbf16" not in l]
    assert not bad, "non-bf16 convolutions:\n" + "\n".join(bad)


def test_int8_contraction_survives_compilation():
    """The s8×s8→s32 contraction must still be integer after XLA's
    optimization/fusion pipeline — if a pass rewrote it to f32 the MXU
    int8 path (2× bf16 throughput) would silently vanish."""
    from mxnet_tpu.ops.quantization_ops import (_quantized_conv,
                                                _quantized_fc)

    x = jnp.ones((4, 32), jnp.float32)
    w = jnp.ones((8, 32), jnp.int8)
    txt = jax.jit(lambda a, b: _quantized_fc(
        a, b, num_hidden=8, no_bias=True, min_data=-1.0, max_data=1.0,
        w_scale=1.0)).lower(x, w).compile().as_text()
    assert re.search(r"s32\[[\d,]*\][^\n]*dot", txt), \
        "no s32-accumulating dot in optimized HLO"

    xc = jnp.ones((1, 4, 8, 8), jnp.float32)
    wc = jnp.ones((8, 4, 3, 3), jnp.int8)
    txt = jax.jit(lambda a, b: _quantized_conv(
        a, b, kernel=(3, 3), num_filter=8, no_bias=True,
        min_data=-1.0, max_data=1.0, w_scale=1.0)).lower(
            xc, wc).compile().as_text()
    assert re.search(r"s32\[[\d,]*\][^\n]*convolution", txt) or \
        re.search(r"convolution[^\n]*s8", txt), \
        "no integer convolution in optimized HLO"


def test_loss_scalar_stays_replicated():
    """The returned loss is replicated (P()): reading it never triggers
    a cross-device gather on the step's critical path."""
    step, lowered = _built_step(_mlp())
    out_sh = step._jitted(step._param_vals, step._opt_state,
                          step._aux_vals,
                          jax.device_put(jnp.zeros((16, 16)),
                                         step._data_sharding),
                          jax.device_put(jnp.zeros(16),
                                         step._data_sharding),
                          jnp.float32(0.1), jnp.float32(2.0),
                          mx.random.next_key())
    loss = out_sh[3]
    assert loss.sharding.is_fully_replicated


def test_cached_op_dead_key_elision_keeps_dropout_fresh():
    """Deterministic graphs skip per-call key derivation (rng_static),
    but a graph that consumes randomness must still draw fresh keys
    every call — same executable, different masks."""
    from mxnet_tpu.cached_op import CachedOp
    from mxnet_tpu import autograd

    det = CachedOp(lambda a: a * 2.0 + 1.0, num_params=0)
    x = mx.nd.array(np.ones((4, 4), np.float32))
    r1, r2 = det(x).asnumpy(), det(x).asnumpy()
    np.testing.assert_array_equal(r1, r2)
    assert any(det._op.rng_static.values())

    drop = CachedOp(lambda a: mx.nd.Dropout(a, p=0.5), num_params=0)
    with autograd.train_mode():
        m1 = drop(x).asnumpy()
        m2 = drop(x).asnumpy()
    assert not np.array_equal(m1, m2), "dropout mask froze across calls"
    key = [k for k in drop._op.rng_static][0]
    assert drop._op.rng_static[key] is False


def test_cached_op_dispatch_not_slower_than_eager():
    """The whole point of the CachedOp seam: one executable launch must
    beat N eager dispatches (SURVEY §7 per-op dispatch hard part).
    Lenient bound — this box has one core and noisy timers."""
    import time
    from mxnet_tpu.cached_op import CachedOp

    def chain(a):
        y = mx.nd.relu(a)
        for _ in range(4):
            y = y * 0.5 + 1.0
            y = mx.nd.tanh(y)
        return mx.nd.sum(y)

    x = mx.nd.array(np.random.rand(128, 128).astype(np.float32))
    op = CachedOp(chain, num_params=0)
    op(x).asnumpy()
    chain(x).asnumpy()

    def clock(fn=None, n=60):
        t0 = time.monotonic()
        for _ in range(n):
            out = fn(x)
        out.asnumpy()
        return time.monotonic() - t0

    # Best-of-3 with a loose bound: one core, noisy timers — the exact
    # ratio is tools/dispatch_bench.py's job; this only guards against
    # the cached path regressing to slower-than-eager territory.
    t_eager = min(clock(fn=chain) for _ in range(3))
    t_cached = min(clock(fn=op) for _ in range(3))
    assert t_cached < t_eager * 1.5, (t_cached, t_eager)


# -- fused imperative update path (mxnet_tpu.fused_update) --------------------
#
# The dispatch-count story is chip-independent the same way the HLO
# artifacts above are: whatever the accelerator, the host issues one
# coalesced launch per (ctx, dtype) group instead of one per parameter,
# and compiles once per param-set signature. Asserted against the same
# counters production telemetry watches.

def _grad_params(n, size=16):
    params = []
    rng = np.random.RandomState(n)
    for k in range(n):
        p = gluon.Parameter("pe_fused%d_%d" % (n, k), shape=(size,))
        p.initialize(init=mx.init.Constant(0.0))
        p.set_data(mx.nd.array(rng.randn(size).astype(np.float32)))
        params.append(p)
    return params


def _fill_grads(params, seed=0):
    rng = np.random.RandomState(seed)
    for p in params:
        p.grad()[:] = rng.randn(*p.shape).astype(np.float32)


def test_fused_update_dispatches_flat_in_param_count():
    """One step of the fused Trainer issues <= ceil(params/bucket) + 1
    executable launches REGARDLESS of parameter count — the multi-
    tensor-apply contract (per-param loop: one per parameter)."""
    import math

    from mxnet_tpu.fused_update import bucket_bytes
    from mxnet_tpu.test_utils import count_dispatches

    counts = {}
    for n in (8, 64):
        params = _grad_params(n)
        trainer = gluon.Trainer(params, "adam", {"learning_rate": 0.01})
        _fill_grads(params)
        trainer.step(1)                      # warmup compile
        with count_dispatches() as c:
            trainer.step(1)
        per_bucket = max(1, bucket_bytes() // (16 * 4))
        assert c.count <= math.ceil(n / per_bucket) + 1, (n, c.count)
        counts[n] = c.count
    assert counts[8] == counts[64], counts


def test_fused_update_compiles_once_per_param_set_signature():
    """Executable-cache discipline at the optimizer-apply level: N steps
    over a stable param set fill the cache exactly once (the CachedOp
    one-compile-per-bucket contract, fused-update edition), visible both
    on the applier hook and in mx_fused_apply_compiles_total."""
    from mxnet_tpu.telemetry import metrics as tm

    fam = tm.REGISTRY.counter("mx_fused_apply_compiles_total", "",
                              labels=("optimizer",))
    before = fam.labels(optimizer="sgd").value
    params = _grad_params(6)
    trainer = gluon.Trainer(params, "sgd",
                            {"learning_rate": 0.1, "momentum": 0.9})
    for s in range(5):
        _fill_grads(params, seed=s)
        trainer.step(1)
    assert trainer._applier.num_compiles == 1
    assert fam.labels(optimizer="sgd").value == before + 1
    # A genuinely new signature (new trainer, different shapes) is one
    # more fill — not one per step.
    params2 = _grad_params(6, size=32)
    trainer2 = gluon.Trainer(params2, "sgd",
                             {"learning_rate": 0.1, "momentum": 0.9})
    for s in range(3):
        _fill_grads(params2, seed=s)
        trainer2.step(1)
    assert trainer2._applier.num_compiles == 1
    assert fam.labels(optimizer="sgd").value == before + 2
