"""Pallas flash attention kernel (interpret mode on cpu; compiled on
TPU). TPU-first flagship kernel — no reference counterpart."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import mxnet_tpu as mx
from mxnet_tpu.ops.pallas_attention import flash_attention


def _dense(q, k, v, causal=False, scale=None):
    scale = scale if scale is not None else q.shape[-1] ** -0.5
    s = np.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if causal:
        t = q.shape[2]
        s = np.where(np.tril(np.ones((t, t), bool)), s, -1e30)
    p = np.exp(s - s.max(-1, keepdims=True))
    return np.einsum("bhqk,bhkd->bhqd", p / p.sum(-1, keepdims=True), v)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_attention_matches_dense(causal):
    rng = np.random.RandomState(0)
    q, k, v = (rng.randn(2, 2, 64, 16).astype(np.float32)
               for _ in range(3))
    got = np.asarray(flash_attention(jnp.asarray(q), jnp.asarray(k),
                                     jnp.asarray(v), causal=causal,
                                     block_q=16, block_k=16))
    np.testing.assert_allclose(got, _dense(q, k, v, causal),
                               rtol=2e-4, atol=2e-5)


def test_flash_attention_uneven_blocks_rejected():
    rng = np.random.RandomState(1)
    q = jnp.asarray(rng.randn(1, 1, 48, 8).astype(np.float32))
    with pytest.raises(ValueError, match="divide"):
        flash_attention(q, q, q, block_q=32, block_k=32)


def test_flash_attention_gradients_match_dense():
    rng = np.random.RandomState(2)
    q, k, v = (jnp.asarray(rng.randn(1, 1, 32, 8).astype(np.float32))
               for _ in range(3))

    def flash_loss(q_, k_, v_):
        return (flash_attention(q_, k_, v_, causal=True, block_q=8,
                                block_k=8) ** 2).mean()

    def dense_loss(q_, k_, v_):
        scale = q_.shape[-1] ** -0.5
        s = jnp.einsum("bhqd,bhkd->bhqk", q_, k_) * scale
        t = q_.shape[2]
        s = jnp.where(jnp.tril(jnp.ones((t, t), bool)), s, -1e30)
        out = jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(s, -1), v_)
        return (out ** 2).mean()

    g = jax.grad(flash_loss, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(dense_loss, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-4, atol=1e-5)


def test_flash_attention_nd_op_surface():
    rng = np.random.RandomState(3)
    q = mx.nd.array(rng.randn(1, 2, 32, 8).astype(np.float32))
    out = mx.nd.contrib.flash_attention(q, q, q, causal=True,
                                        block_q=16, block_k=16)
    want = _dense(q.asnumpy(), q.asnumpy(), q.asnumpy(), causal=True)
    np.testing.assert_allclose(out.asnumpy(), want, rtol=2e-4, atol=2e-5)


def test_flash_attention_cross_attention_with_gradients():
    """tq != tk (decoder cross-attention): forward AND backward work."""
    rng = np.random.RandomState(4)
    q = jnp.asarray(rng.randn(1, 2, 16, 8).astype(np.float32))
    k = jnp.asarray(rng.randn(1, 2, 48, 8).astype(np.float32))
    v = jnp.asarray(rng.randn(1, 2, 48, 8).astype(np.float32))
    got = np.asarray(flash_attention(q, k, v, block_q=8, block_k=16))
    want = _dense(np.asarray(q), np.asarray(k), np.asarray(v))
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-5)

    g = jax.grad(lambda a, b, c: (flash_attention(
        a, b, c, block_q=8, block_k=16) ** 2).mean(),
        argnums=(0, 1, 2))(q, k, v)
    assert all(float(jnp.abs(x).sum()) > 0 for x in g)


def test_flash_backward_memory_is_sub_quadratic():
    """The flash backward's compiled artifact must NOT carry O(T²)
    temporaries — the old fallback (jax.vjp through blockwise_attention)
    stored per-block probabilities across scan steps, ~20× the memory at
    T=4k (VERDICT r4 #5). Asserted on XLA's buffer assignment."""
    import jax
    from mxnet_tpu.ops.pallas_attention import flash_attention
    from mxnet_tpu.parallel.ring_attention import blockwise_attention

    T, D = 2048, 32
    q = jnp.ones((1, 1, T, D), jnp.float32)

    flash = jax.jit(jax.grad(
        lambda a, b, c: flash_attention(a, b, c, causal=True).sum(),
        argnums=(0, 1, 2)))
    fallback = jax.jit(jax.grad(
        lambda a, b, c: blockwise_attention(a, b, c, block=128,
                                            causal=True).sum(),
        argnums=(0, 1, 2)))
    flash_tmp = flash.lower(q, q, q).compile() \
        .memory_analysis().temp_size_in_bytes
    fb_tmp = fallback.lower(q, q, q).compile() \
        .memory_analysis().temp_size_in_bytes
    # The O(T²) probability tensor alone is T*T*4 bytes.
    assert flash_tmp < T * T * 4, flash_tmp
    assert flash_tmp * 4 < fb_tmp, (flash_tmp, fb_tmp)


def test_flash_backward_matches_blockwise_vjp():
    """Interpret-mode parity of the Pallas backward against autodiff
    through the XLA blockwise formulation (same math, independent
    implementation)."""
    import jax
    from mxnet_tpu.ops.pallas_attention import flash_attention
    from mxnet_tpu.parallel.ring_attention import blockwise_attention

    rng = np.random.RandomState(11)
    q, k, v = (jnp.asarray(rng.randn(2, 2, 64, 16).astype(np.float32))
               for _ in range(3))
    g = jnp.asarray(rng.randn(2, 2, 64, 16).astype(np.float32))
    for causal in (False, True):
        _, vjp_f = jax.vjp(lambda a, b, c: flash_attention(
            a, b, c, causal=causal, block_q=16, block_k=16), q, k, v)
        _, vjp_b = jax.vjp(lambda a, b, c: blockwise_attention(
            a, b, c, block=16, causal=causal), q, k, v)
        for gf, gb, name in zip(vjp_f(g), vjp_b(g), "qkv"):
            np.testing.assert_allclose(
                np.asarray(gf), np.asarray(gb), rtol=2e-4, atol=2e-5,
                err_msg="d%s diverged (causal=%s)" % (name, causal))
