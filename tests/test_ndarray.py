"""NDArray unit tests (reference: tests/python/unittest/test_ndarray.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd


def test_creation():
    a = nd.zeros((2, 3))
    assert a.shape == (2, 3)
    assert a.dtype == np.float32
    assert np.allclose(a.asnumpy(), 0)

    b = nd.ones((4,), dtype="int32")
    assert b.dtype == np.int32
    assert b.sum().asscalar() == 4

    c = nd.full((2, 2), 7.5)
    assert np.allclose(c.asnumpy(), 7.5)

    d = nd.array([[1, 2], [3, 4]])
    assert d.shape == (2, 2)
    assert d.dtype == np.int64 or d.dtype == np.int32

    e = nd.array(np.random.rand(3, 3))
    assert e.dtype == np.float32  # float64 downcast like the reference

    f = nd.arange(0, 10, 2)
    assert np.allclose(f.asnumpy(), [0, 2, 4, 6, 8])


def test_arithmetic():
    x = nd.array([[1.0, 2.0], [3.0, 4.0]])
    y = nd.array([[10.0, 20.0], [30.0, 40.0]])
    assert np.allclose((x + y).asnumpy(), [[11, 22], [33, 44]])
    assert np.allclose((y - x).asnumpy(), [[9, 18], [27, 36]])
    assert np.allclose((x * y).asnumpy(), [[10, 40], [90, 160]])
    assert np.allclose((y / x).asnumpy(), [[10, 10], [10, 10]])
    assert np.allclose((x + 1).asnumpy(), [[2, 3], [4, 5]])
    assert np.allclose((1 + x).asnumpy(), [[2, 3], [4, 5]])
    assert np.allclose((2 - x).asnumpy(), [[1, 0], [-1, -2]])
    assert np.allclose((x ** 2).asnumpy(), [[1, 4], [9, 16]])
    assert np.allclose((-x).asnumpy(), [[-1, -2], [-3, -4]])
    assert np.allclose((x > 2).asnumpy(), [[0, 0], [1, 1]])
    assert np.allclose((x == 2).asnumpy(), [[0, 1], [0, 0]])


def test_inplace_versioning():
    x = nd.ones((2, 2))
    v0 = x.version
    x += 1
    assert x.version == v0 + 1
    assert np.allclose(x.asnumpy(), 2)
    y = x  # alias sees the mutation (same NDArray object)
    x *= 2
    assert np.allclose(y.asnumpy(), 4)


def test_broadcast():
    x = nd.ones((2, 1, 3))
    y = nd.ones((1, 4, 3))
    z = x + y
    assert z.shape == (2, 4, 3)
    b = nd.ones((1, 3)).broadcast_to((5, 3))
    assert b.shape == (5, 3)


def test_shape_ops():
    x = nd.arange(24).reshape((2, 3, 4))
    assert x.reshape((4, 6)).shape == (4, 6)
    assert x.reshape((-1, 4)).shape == (6, 4)
    assert x.reshape((0, -1)).shape == (2, 12)
    assert x.transpose().shape == (4, 3, 2)
    assert x.transpose((1, 0, 2)).shape == (3, 2, 4)
    assert x.flatten().shape == (2, 12)
    assert x.expand_dims(0).shape == (1, 2, 3, 4)
    assert x.swapaxes(0, 2).shape == (4, 3, 2)
    assert nd.concat(x, x, dim=1).shape == (2, 6, 4)
    assert nd.stack(x, x, axis=0).shape == (2, 2, 3, 4)
    parts = x.split(3, axis=1)
    assert len(parts) == 3 and parts[0].shape == (2, 1, 4)


def test_reduce():
    x = nd.array(np.arange(12).reshape(3, 4))
    assert x.sum().asscalar() == 66
    assert np.allclose(x.sum(axis=0).asnumpy(), [12, 15, 18, 21])
    assert np.allclose(x.mean(axis=1).asnumpy(), [1.5, 5.5, 9.5])
    assert x.max().asscalar() == 11
    assert x.min().asscalar() == 0
    assert np.allclose(x.argmax(axis=1).asnumpy(), [3, 3, 3])
    n = x.norm().asscalar()
    assert abs(n - np.linalg.norm(np.arange(12))) < 1e-4


def test_dot():
    a = nd.array(np.random.rand(3, 4))
    b = nd.array(np.random.rand(4, 5))
    c = nd.dot(a, b)
    assert c.shape == (3, 5)
    assert np.allclose(c.asnumpy(), a.asnumpy() @ b.asnumpy(), atol=1e-5)
    # batch_dot
    x = nd.array(np.random.rand(2, 3, 4))
    y = nd.array(np.random.rand(2, 4, 5))
    z = nd.batch_dot(x, y)
    assert z.shape == (2, 3, 5)


def test_indexing():
    x = nd.arange(24).reshape((4, 6))
    assert x[1].shape == (6,)
    assert x[1, 2].asscalar() == 8
    assert x[1:3].shape == (2, 6)
    assert x[:, 2:4].shape == (4, 2)
    idx = nd.array([0, 2], dtype="int32")
    assert nd.take(x, idx, axis=0).shape == (2, 6)
    x[0] = 100.0
    assert np.allclose(x.asnumpy()[0], 100)
    x[1, 1] = -1.0
    assert x.asnumpy()[1, 1] == -1


def test_context_and_copy():
    x = nd.ones((2, 2), ctx=mx.cpu(0))
    assert x.context.device_type == "cpu"
    y = x.copyto(mx.cpu(0))
    assert np.allclose(y.asnumpy(), 1)
    z = x.as_in_context(mx.cpu(0))
    assert z is x
    c = x.copy()
    c += 1
    assert np.allclose(x.asnumpy(), 1)  # copy is deep


def test_astype():
    x = nd.ones((2, 2))
    y = x.astype("float16")
    assert y.dtype == np.float16
    z = x.astype(np.int32)
    assert z.dtype == np.int32


def test_save_load(tmp_path):
    fname = str(tmp_path / "arrays")
    a = nd.array(np.random.rand(3, 3))
    b = nd.array(np.random.rand(2,))
    nd.save(fname, [a, b])
    out = nd.load(fname)
    assert isinstance(out, list) and len(out) == 2
    assert np.allclose(out[0].asnumpy(), a.asnumpy())
    nd.save(fname, {"w": a, "b": b})
    out = nd.load(fname)
    assert set(out) == {"w", "b"}
    assert np.allclose(out["b"].asnumpy(), b.asnumpy())


def test_waitall_and_naive_engine():
    x = nd.ones((8, 8))
    y = nd.dot(x, x)
    nd.waitall()
    mx.engine.set_engine_type("NaiveEngine")
    try:
        z = nd.dot(y, y)
        assert z.shape == (8, 8)
    finally:
        mx.engine.set_engine_type("ThreadedEnginePerDevice")


def test_random_ops():
    mx.random.seed(42)
    a = nd.random.uniform(0, 1, shape=(100,))
    b = nd.random.uniform(0, 1, shape=(100,))
    assert not np.allclose(a.asnumpy(), b.asnumpy())  # fresh keys per call
    mx.random.seed(42)
    a2 = nd.random.uniform(0, 1, shape=(100,))
    assert np.allclose(a.asnumpy(), a2.asnumpy())  # reproducible
    n = nd.random.normal(0, 1, shape=(1000,))
    assert abs(float(n.mean().asscalar())) < 0.2
    r = nd.random.randint(0, 10, shape=(50,))
    assert r.dtype == np.int32
    assert int(r.max().asscalar()) < 10


def test_one_hot_take_pick():
    idx = nd.array([0, 2, 1], dtype="int32")
    oh = nd.one_hot(idx, 3)
    assert np.allclose(oh.asnumpy(), np.eye(3)[[0, 2, 1]])
    x = nd.array([[1, 2, 3], [4, 5, 6]])
    p = nd.pick(x, nd.array([0, 2]), axis=1)
    assert np.allclose(p.asnumpy(), [1, 6])


def test_topk_sort():
    x = nd.array([[3.0, 1.0, 2.0], [0.5, 2.5, 1.5]])
    v = nd.topk(x, k=2, ret_typ="value")
    assert np.allclose(v.asnumpy(), [[3, 2], [2.5, 1.5]])
    s = nd.sort(x, axis=1)
    assert np.allclose(s.asnumpy(), [[1, 2, 3], [0.5, 1.5, 2.5]])


def test_where_clip():
    x = nd.array([-2.0, -1.0, 0.0, 1.0, 2.0])
    c = x.clip(-1, 1)
    assert np.allclose(c.asnumpy(), [-1, -1, 0, 1, 1])
    w = nd.where(x > 0, x, -x)
    assert np.allclose(w.asnumpy(), [2, 1, 0, 1, 2])


def test_save_load_reference_wire_format(tmp_path):
    """.params files use the reference's binary layout: list magic 0x112,
    per-array V2 magic 0xF993fac9 (src/ndarray/ndarray.cc:1537-1745)."""
    import struct

    path = str(tmp_path / "x.params")
    d = {"arg:w": nd.array([[1.0, 2.0], [3.0, 4.0]]),
         "aux:m": nd.array([5, 6], dtype="int64")}
    nd.save(path, d)
    raw = open(path, "rb").read()
    assert struct.unpack("<Q", raw[:8])[0] == 0x112
    assert struct.unpack("<I", raw[24:28])[0] == 0xF993FAC9
    out = nd.load(path)
    assert set(out) == {"arg:w", "aux:m"}
    assert np.allclose(out["arg:w"].asnumpy(), [[1, 2], [3, 4]])
    # jax runs with x64 disabled, so int64 payloads surface as int32
    assert np.issubdtype(out["aux:m"].dtype, np.integer)

    # list round-trip
    lst_path = str(tmp_path / "l.params")
    nd.save(lst_path, [nd.ones((3,)), nd.zeros((2, 2))])
    lst = nd.load(lst_path)
    assert isinstance(lst, list) and len(lst) == 2
    assert np.allclose(lst[0].asnumpy(), 1)


def test_save_load_sparse_wire_format(tmp_path):
    from mxnet_tpu.ndarray import sparse

    path = str(tmp_path / "sp.params")
    rsp = sparse.row_sparse_array(
        np.array([[0, 0], [1, 2], [0, 0], [3, 4]], np.float32))
    csr = sparse.csr_matrix(
        np.array([[1, 0, 2], [0, 0, 3]], np.float32))
    nd.save(path, {"rsp": rsp, "csr": csr})
    out = nd.load(path)
    assert out["rsp"].stype == "row_sparse"
    assert np.allclose(out["rsp"].asnumpy(),
                       [[0, 0], [1, 2], [0, 0], [3, 4]])
    assert out["csr"].stype == "csr"
    assert np.allclose(out["csr"].asnumpy(), [[1, 0, 2], [0, 0, 3]])
