"""Profiler, Monitor, visualization, util, name — SURVEY §5.1/§5.5
subsystems (reference tests: test_profiler.py, monitor usage in
test_monitor-ish flows)."""
import glob
import os
import tempfile

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import profiler


def test_profiler_trace_and_aggregate():
    with tempfile.TemporaryDirectory() as d:
        trace_dir = os.path.join(d, "prof")
        profiler.set_config(filename=trace_dir, aggregate_stats=True)
        profiler.set_state("run")
        a = mx.nd.ones((32, 32))
        for _ in range(3):
            a = mx.nd.dot(a, a) * 0.01
        a.wait_to_read()
        profiler.set_state("stop")
        stats = profiler.dumps()
        assert "dot" in stats and "Calls" in stats
        # device trace written (xplane/tensorboard layout)
        produced = glob.glob(os.path.join(trace_dir, "**", "*"),
                             recursive=True)
        assert produced, "no trace output in %s" % trace_dir


def test_profiler_pause_resume():
    profiler.dumps(reset=True)
    profiler.set_state("run")
    profiler.pause()
    b = mx.nd.ones((4, 4)).exp()
    b.wait_to_read()
    profiler.resume()
    c = mx.nd.ones((4, 4)).tanh()
    c.wait_to_read()
    profiler.set_state("stop")
    stats = profiler.dumps(reset=True)
    assert "tanh" in stats
    assert "exp" not in stats


def test_profiler_domains_counters():
    dom = profiler.Domain("test_domain")
    counter = dom.new_counter("ops_done", 0)
    counter.increment(5)
    task = dom.new_task("phase1")
    profiler.set_state("run")
    with task:
        mx.nd.ones((2, 2)).sum().wait_to_read()
    profiler.set_state("stop")
    stats = profiler.dumps()
    assert "test_domain::ops_done" in stats


def test_profiler_dumps_json_format():
    """dumps(format='json') returns the aggregate stats machine-readable
    (the bench harness and serving dashboards consume this)."""
    import json

    import pytest

    profiler.dumps(reset=True)
    dom = profiler.Domain("jsontest")
    dom.new_counter("widgets", 7)
    profiler.set_state("run")
    x = mx.nd.ones((8, 8)).tanh()
    x.wait_to_read()
    profiler.set_state("stop")

    payload = json.loads(profiler.dumps(format="json"))
    assert set(payload) == {"trace_dir", "ops", "counters"}
    tanh_keys = [k for k in payload["ops"] if "tanh" in k]
    assert tanh_keys, sorted(payload["ops"])
    st = payload["ops"][tanh_keys[0]]
    assert st["calls"] >= 1
    assert 0 <= st["min_ms"] <= st["max_ms"] <= st["total_ms"] + 1e-9
    assert payload["counters"]["jsontest::widgets"] == 7

    # reset through the json path clears op stats like the table path
    json.loads(profiler.dumps(format="json", reset=True))
    assert not json.loads(profiler.dumps(format="json"))["ops"]
    with pytest.raises(ValueError):
        profiler.dumps(format="xml")


def test_dumps_json_includes_histogram_percentiles():
    """ISSUE 5 satellite schema regression: the histogram-derived
    p50/p99 the table shows must ride the JSON payload too."""
    import json

    profiler.dumps(reset=True)
    for ms in (1, 1, 1, 1, 50):
        profiler.record_op_span("pctl_op", ms / 1e3)
    payload = json.loads(profiler.dumps(format="json"))
    st = payload["ops"]["pctl_op"]
    assert set(st) == {"calls", "total_ms", "min_ms", "max_ms",
                       "p50_ms", "p99_ms"}
    assert st["min_ms"] <= st["p50_ms"] <= st["p99_ms"] <= st["max_ms"]
    assert st["p99_ms"] > st["p50_ms"]      # the outlier shows up
    # the table renders the same columns
    table = profiler.dumps()
    header = table.splitlines()[1]
    assert "P50(ms)" in header and "P99(ms)" in header
    profiler.dumps(reset=True)


def test_dumps_reset_keeps_counters():
    """Pinned behavior (ISSUE 3 satellite): dumps(reset=True) clears the
    per-op dispatch stats but NOT user-defined Counters — they are live
    process-global gauges (checkpoint::pending, serving::requests)
    shared across subsystems."""
    import json

    dom = profiler.Domain("resetpin")
    dom.new_counter("kept", 11)
    profiler.record_op_span("resetpin_op", 0.001)
    payload = json.loads(profiler.dumps(format="json", reset=True))
    assert payload["ops"]["resetpin_op"]["calls"] == 1
    after = json.loads(profiler.dumps(format="json"))
    assert "resetpin_op" not in after["ops"]
    assert after["counters"]["resetpin::kept"] == 11
    # the table path resets identically
    profiler.record_op_span("resetpin_op", 0.001)
    profiler.dumps(reset=True)
    table = profiler.dumps()
    assert "resetpin_op" not in table
    assert "resetpin::kept" in table


def test_dump_finished_false_keeps_profiler_usable():
    """dump(finished=False) flushes a chrome-trace snapshot but leaves
    the profiler running (reference semantics: the `finished` argument
    was previously accepted and ignored); dump() with the default
    finished=True stops it."""
    import json

    with tempfile.TemporaryDirectory() as d:
        trace_dir = os.path.join(d, "prof")
        profiler.set_config(filename=trace_dir)
        profiler.set_state("run")
        try:
            mx.nd.ones((4, 4)).tanh().wait_to_read()
            profiler.dump(finished=False)
            assert profiler.is_recording()          # still usable
            path = os.path.join(trace_dir, "chrome_trace.json")
            assert os.path.isfile(path)
            with open(path) as f:
                data = json.load(f)
            assert isinstance(data["traceEvents"], list)
            mx.nd.ones((4, 4)).exp().wait_to_read() # records after dump
            assert "exp" in profiler.dumps()
            profiler.dump()                         # finished=True
            assert not profiler.is_recording()
        finally:
            profiler.set_state("stop")
        profiler.set_config(filename="profile_output")


def test_profiler_events_bounded():
    """Task/Frame/Marker events land in the bounded telemetry trace
    rings — the old module-level `_events` list (appended without a lock
    and never drained: a leak in any long-running server) is gone."""
    from mxnet_tpu.telemetry import trace

    assert not hasattr(profiler, "_events")
    trace.clear()        # other suites' worker threads left events
    dom = profiler.Domain("bounded")
    marker = dom.new_marker("tick")
    cap = trace.capacity()
    for _ in range(cap + 500):
        marker.mark()
    # this thread's ring is full at cap; other registered (now idle)
    # thread rings were cleared above, so the global count stays bounded
    assert trace.event_count() <= cap
    with dom.new_task("work"):
        pass
    names = [e["name"] for e in trace.chrome_trace()["traceEvents"]]
    assert "bounded::tick" in names and "bounded::work" in names
    trace.clear()


def test_monitor_collects_stats():
    from mxnet_tpu.monitor import Monitor

    data = mx.sym.var("data")
    fc = mx.sym.FullyConnected(data, num_hidden=4, name="fc1")
    out = mx.sym.softmax(fc, name="sm")
    ex = out.bind(mx.cpu(), {"data": mx.nd.ones((2, 3)),
                             "fc1_weight": mx.nd.ones((4, 3)),
                             "fc1_bias": mx.nd.zeros((4,))})
    mon = Monitor(interval=1, pattern=".*")
    mon.install(ex)
    mon.tic()
    ex.forward()
    res = mon.toc()
    assert res, "monitor collected nothing"
    names = [r[1] for r in res]
    assert any("output" in n for n in names)


def test_print_summary_and_plot(capsys):
    data = mx.sym.var("data")
    net = mx.sym.FullyConnected(data, num_hidden=8, name="fc1")
    net = mx.sym.Activation(net, act_type="relu", name="relu1")
    net = mx.sym.FullyConnected(net, num_hidden=2, name="fc2")
    total = mx.viz.print_summary(net, shape={"data": (1, 16)})
    cap = capsys.readouterr().out
    assert "fc1" in cap and "Total params" in cap
    # 16*8+8 + 8*2+2 = 154
    assert total == 154
    dot = mx.viz.plot_network(net)
    src = dot if isinstance(dot, str) else dot.source
    assert "fc1" in src and "->" in src


def test_util_and_name():
    from mxnet_tpu import util

    assert util.get_gpu_count() >= 0
    with mx.name.Prefix("scope_"):
        s = mx.sym.FullyConnected(mx.sym.var("data"), num_hidden=2)
        assert s.name.startswith("scope_")
    s2 = mx.sym.FullyConnected(mx.sym.var("data"), num_hidden=2)
    assert not s2.name.startswith("scope_")
