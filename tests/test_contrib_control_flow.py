"""Control-flow op semantics, ported from the reference
tests/python/unittest/test_contrib_control_flow.py (foreach with states,
while_loop exact/padded semantics, cond branch selection, gradients
through the loop, symbolic bind + backward)."""
import numpy as np
import pytest

import jax

import mxnet_tpu as mx
from mxnet_tpu import autograd
from mxnet_tpu.ndarray import contrib as ndc


def test_foreach_cumsum():
    data = mx.nd.array(np.arange(12).reshape(4, 3).astype(np.float32))
    init = mx.nd.zeros((3,))

    def body(x, s):
        new_s = s + x
        return new_s * 1.0, new_s

    outs, final = ndc.foreach(body, data, init)
    expected = np.cumsum(np.arange(12).reshape(4, 3), axis=0)
    np.testing.assert_allclose(outs.asnumpy(), expected)
    np.testing.assert_allclose(final.asnumpy(), expected[-1])


def test_foreach_multi_data_multi_state():
    a = mx.nd.array(np.arange(6).reshape(3, 2).astype(np.float32))
    b = mx.nd.array(np.ones((3, 2), np.float32))
    s1 = mx.nd.zeros((2,))
    s2 = mx.nd.ones((2,))

    def body(xs, states):
        xa, xb = xs
        t1, t2 = states
        return [xa + xb + t1, xa * t2], [t1 + xa, t2 * 2]

    outs, finals = ndc.foreach(body, [a, b], [s1, s2])
    assert len(outs) == 2 and len(finals) == 2
    an = np.arange(6).reshape(3, 2).astype(np.float32)
    t1 = np.zeros(2, np.float32)
    t2 = np.ones(2, np.float32)
    o1, o2 = [], []
    for t in range(3):
        o1.append(an[t] + 1 + t1)
        o2.append(an[t] * t2)
        t1 = t1 + an[t]
        t2 = t2 * 2
    np.testing.assert_allclose(outs[0].asnumpy(), np.stack(o1))
    np.testing.assert_allclose(outs[1].asnumpy(), np.stack(o2))
    np.testing.assert_allclose(finals[0].asnumpy(), t1)
    np.testing.assert_allclose(finals[1].asnumpy(), t2)


def test_foreach_gradient_matches_unrolled():
    """Gradient through foreach == gradient of a hand-unrolled loop."""
    np.random.seed(0)
    data_np = np.random.rand(5, 4).astype(np.float32)
    w_np = np.random.rand(4).astype(np.float32)

    def run_foreach():
        data = mx.nd.array(data_np)
        w = mx.nd.array(w_np)
        w.attach_grad()
        with autograd.record():
            outs, final = ndc.foreach(
                lambda x, s: (x * w, s + (x * w).sum()),
                data, mx.nd.zeros((1,)))
            loss = (outs * outs).sum() + final.sum()
        loss.backward()
        return w.grad.asnumpy()

    def run_unrolled():
        data = mx.nd.array(data_np)
        w = mx.nd.array(w_np)
        w.attach_grad()
        with autograd.record():
            s = mx.nd.zeros((1,))
            outs = []
            for t in range(5):
                o = data[t] * w
                s = s + o.sum()
                outs.append(o)
            stacked = mx.nd.stack(*outs, axis=0)
            loss = (stacked * stacked).sum() + s.sum()
        loss.backward()
        return w.grad.asnumpy()

    np.testing.assert_allclose(run_foreach(), run_unrolled(), rtol=1e-5)


def test_while_loop_imperative_exact_length():
    """Imperative while_loop returns exactly the executed steps
    (reference: nd while_loop semantics)."""
    def cond(i, s):
        return i < 5

    def func(i, s):
        return i * 2.0, [i + 1, s + i]

    outs, finals = ndc.while_loop(
        cond, func, [mx.nd.array([0.0]), mx.nd.array([0.0])],
        max_iterations=10)
    assert outs.shape == (5, 1)
    np.testing.assert_allclose(outs.asnumpy().reshape(-1),
                               [0, 2, 4, 6, 8])
    np.testing.assert_allclose(finals[0].asnumpy(), [5.0])
    np.testing.assert_allclose(finals[1].asnumpy(), [10.0])


def test_while_loop_traced_padded():
    """Traced while_loop pads outputs to max_iterations with zeros."""
    def run(i0):
        outs, finals = ndc.while_loop(
            lambda i: i < 5, lambda i: (i * 2.0, [i + 1]),
            [mx.nd.from_jax(i0)], max_iterations=8)
        return outs._data, finals[0]._data

    outs, final = jax.jit(run)(jax.numpy.asarray([0.0]))
    np.testing.assert_allclose(np.asarray(outs).reshape(-1),
                               [0, 2, 4, 6, 8, 0, 0, 0])
    np.testing.assert_allclose(np.asarray(final), [5.0])


def test_cond_imperative_and_traced():
    x = mx.nd.array([2.0])
    y = mx.nd.array([3.0])
    out = ndc.cond(lambda: x.sum() < y.sum(),
                   lambda: x * 2, lambda: y * 2)
    np.testing.assert_allclose(out.asnumpy(), [4.0])

    def run(xv, yv):
        xa, ya = mx.nd.from_jax(xv), mx.nd.from_jax(yv)
        out = ndc.cond(lambda: xa.sum() < ya.sum(),
                       lambda: xa * 2, lambda: ya * 2)
        return out._data

    r = jax.jit(run)(jax.numpy.asarray([5.0]), jax.numpy.asarray([3.0]))
    np.testing.assert_allclose(np.asarray(r), [6.0])


def test_cond_records_gradient():
    x = mx.nd.array([2.0])
    x.attach_grad()
    with autograd.record():
        out = ndc.cond(lambda: x.sum() > 0,
                       lambda: x * x, lambda: x * 4)
    out.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), [4.0])


def test_sym_foreach_bind():
    """Symbolic foreach: RNN-ish accumulation with a captured weight,
    bound and executed (+ backward)."""
    data = mx.sym.var("data")
    w = mx.sym.var("w")
    init = mx.sym.var("init")

    def body(x, s):
        h = mx.sym.broadcast_mul(x, w) + s
        return h, h

    outs, final = mx.sym.contrib.foreach(body, data, init)
    out = mx.sym.Group([outs, final])
    data_np = np.arange(6).reshape(3, 2).astype(np.float32)
    w_np = np.array([2.0, 0.5], np.float32)
    ex = out.bind(mx.cpu(), {"data": mx.nd.array(data_np),
                             "w": mx.nd.array(w_np),
                             "init": mx.nd.zeros((2,))},
                  args_grad={"w": mx.nd.zeros((2,))})
    res = ex.forward(is_train=True)
    s = np.zeros(2, np.float32)
    expect = []
    for t in range(3):
        s = data_np[t] * w_np + s
        expect.append(s)
    np.testing.assert_allclose(res[0].asnumpy(), np.stack(expect),
                               rtol=1e-6)
    np.testing.assert_allclose(res[1].asnumpy(), s, rtol=1e-6)
    ex.backward(out_grads=[mx.nd.ones((3, 2)), mx.nd.zeros((2,))])
    # d(sum of outs)/dw: each out_t = sum_{i<=t} x_i * w  =>
    # grad_w = sum_t sum_{i<=t} x_i
    gw = sum(data_np[i] * (3 - i) for i in range(3))
    np.testing.assert_allclose(ex.grad_dict["w"].asnumpy(), gw, rtol=1e-5)


def test_sym_while_loop_bind():
    i = mx.sym.var("i")
    outs, finals = mx.sym.contrib.while_loop(
        lambda i_: i_ < 4, lambda i_: (i_ * 3.0, [i_ + 1]),
        [i], max_iterations=6)
    grp = mx.sym.Group([outs] + finals)
    ex = grp.bind(mx.cpu(), {"i": mx.nd.array([0.0])})
    res = ex.forward()
    np.testing.assert_allclose(res[0].asnumpy().reshape(-1),
                               [0, 3, 6, 9, 0, 0])
    np.testing.assert_allclose(res[1].asnumpy(), [4.0])


def test_sym_cond_bind():
    a = mx.sym.var("a")
    b = mx.sym.var("b")
    out = mx.sym.contrib.cond(lambda: mx.sym.sum(a) > mx.sym.sum(b),
                              lambda: a * 2, lambda: b * 3)
    ex = out.bind(mx.cpu(), {"a": mx.nd.array([4.0]),
                             "b": mx.nd.array([1.0])})
    res = ex.forward()
    np.testing.assert_allclose(res[0].asnumpy(), [8.0])
    ex2 = out.bind(mx.cpu(), {"a": mx.nd.array([0.5]),
                              "b": mx.nd.array([1.0])})
    np.testing.assert_allclose(ex2.forward()[0].asnumpy(), [3.0])


def test_foreach_in_hybrid_block():
    """foreach inside a hybridized block fuses into the cached executable
    (the CachedOp seam: whole loop = one lax.scan in one XLA program)."""
    from mxnet_tpu import gluon

    class Cumul(gluon.HybridBlock):
        def hybrid_forward(self, F, x):
            outs, final = ndc.foreach(
                lambda xt, s: (s + xt, s + xt), x, mx.nd.zeros((2,)))
            return outs

    net = Cumul()
    net.hybridize()
    x = mx.nd.array(np.ones((4, 2), np.float32))
    out = net(x)
    np.testing.assert_allclose(
        out.asnumpy(), np.cumsum(np.ones((4, 2)), axis=0))


def test_multi_output_node_evaluates_once():
    """Output views (node[i]) share evaluation: a foreach consumed via
    several outputs runs its scan exactly once per forward, and
    outs[-1] == final state even with RNG in the body."""
    calls = {"n": 0}
    data = mx.sym.var("data")
    init = mx.sym.var("init")

    def body(x, s):
        h = x + s
        return h, h

    outs, final = mx.sym.contrib.foreach(body, data, init)
    # count scan traces via the subgraph callable
    node_attrs = outs._attrs
    orig = node_attrs["body"]

    class Counting:
        def __call__(self, args, captured):
            calls["n"] += 1
            return orig(args, captured)

    node_attrs["body"] = Counting()
    grp = mx.sym.Group([outs, final])
    ex = grp.bind(mx.cpu(), {"data": mx.nd.ones((3, 2)),
                             "init": mx.nd.zeros((2,))})
    res = ex.forward()
    np.testing.assert_allclose(res[0].asnumpy()[-1], res[1].asnumpy())
    # lax.scan traces the body a few times for one compilation, but a
    # second consumed output must NOT double it.
    first = calls["n"]
    assert first > 0
    ex2 = grp.bind(mx.cpu(), {"data": mx.nd.ones((3, 2)),
                              "init": mx.nd.zeros((2,))})
    ex2.forward()
    assert calls["n"] == 2 * first  # once per bind/compile, not per output


def test_foreach_empty_data():
    outs, final = ndc.foreach(lambda x, s: (x + s, s + 1),
                              mx.nd.zeros((0, 3)), mx.nd.zeros((3,)))
    assert outs == []
    np.testing.assert_allclose(final.asnumpy(), np.zeros(3))
