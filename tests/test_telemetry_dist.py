"""Pod-scale observability (ISSUE 5): cross-process metric aggregation,
streaming span export with atomic segment commit, SLO burn-rate alerts,
and the op flamegraph views."""
import json
import os
import socket
import sys
import threading

import pytest

import mxnet_tpu as mx
from mxnet_tpu import telemetry
from mxnet_tpu.telemetry import aggregate, export, flamegraph, slo, trace
from mxnet_tpu.telemetry import metrics as tmetrics

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tools"))
import trace_merge  # noqa: E402
from launch import launch_local  # noqa: E402


class _FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t


# -- aggregation --------------------------------------------------------------

def _mini_registry():
    reg = tmetrics.Registry()
    reg.counter("agg_steps_total", "steps", labels=("stage",)) \
        .labels(stage="train").inc(3)
    reg.gauge("agg_pending").set(2)
    reg.histogram("agg_lat_seconds", buckets=(0.01, 0.1, 1.0)) \
        .observe(0.05)
    return reg


def test_snapshot_merge_labels_every_series_by_rank():
    reg = _mini_registry()
    snap = aggregate.snapshot_registry(reg)
    # snapshots must survive a pickle hop (the kvstore wire)
    import pickle

    snap = pickle.loads(pickle.dumps(snap))
    fleet = aggregate.merge_snapshots({0: snap, 3: snap})
    text = fleet.render_prometheus()
    assert 'agg_steps_total{stage="train",rank="0"} 3' in text
    assert 'agg_steps_total{stage="train",rank="3"} 3' in text
    assert 'agg_pending{rank="3"} 2' in text
    # full histogram bucket vectors survive the merge, per rank
    assert 'agg_lat_seconds_bucket{rank="0",le="0.1"} 1' in text
    assert 'agg_lat_seconds_count{rank="3"} 1' in text
    fam = fleet.get("agg_lat_seconds")
    assert fam.labels(rank="0").quantile(0.5) == pytest.approx(
        0.05, rel=0.7)   # interpolated within the owning bucket


def test_merge_rank_label_collision_uses_src_rank():
    reg = tmetrics.Registry()
    reg.gauge("already_ranked", labels=("rank",)).labels(rank="9").set(1)
    fleet = aggregate.merge_snapshots(
        {2: aggregate.snapshot_registry(reg)})
    assert 'already_ranked{rank="9",src_rank="2"} 1' \
        in fleet.render_prometheus()


def test_aggregator_fleet_scrape_and_staleness():
    """Two logical ranks over a LocalBus: one rank-0 scrape shows both;
    a silent rank is marked stale within one aggregation interval and
    feeds the StepMonitor's anomaly stream."""
    clock = _FakeClock()
    reg = _mini_registry()
    bus = aggregate.LocalBus(num_workers=2, clock=clock)
    monitor = telemetry.StepMonitor(clock=clock, warn_interval_s=1e9)
    a0 = aggregate.Aggregator(bus.endpoint(0), registry=reg,
                              interval_s=5.0, monitor=monitor,
                              clock=clock)
    a1 = aggregate.Aggregator(bus.endpoint(1), registry=reg,
                              interval_s=5.0, clock=clock)
    a1.step()
    a0.step()
    text = a0.render_prometheus()
    assert 'agg_steps_total{stage="train",rank="0"} 3' in text
    assert 'agg_steps_total{stage="train",rank="1"} 3' in text
    assert 'mx_rank_stale{rank="1"} 0' in text
    assert a1.fleet is None          # only rank 0 merges

    # rank 1 goes silent: one aggregation interval past stale_after_s
    # (default 3x interval) it is marked, its series stay visible, and
    # the monitor hears about it
    before = monitor.anomaly_counts.get("rank_stale", 0)
    clock.t += 16.0
    a0.step()
    text = a0.render_prometheus()
    assert 'mx_rank_stale{rank="1"} 1' in text
    assert 'mx_rank_stale{rank="0"} 0' in text
    assert 'agg_steps_total{stage="train",rank="1"} 3' in text
    age = [l for l in text.splitlines()
           if l.startswith('mx_rank_last_report_age_seconds{rank="1"}')]
    assert age and float(age[0].split()[-1]) >= 16.0
    assert monitor.anomaly_counts["rank_stale"] == before + 1


def test_aggregator_tick_cadence_and_fallback_render():
    clock = _FakeClock()
    reg = _mini_registry()
    bus = aggregate.LocalBus(num_workers=1, clock=clock)
    agg = aggregate.Aggregator(bus.endpoint(0), registry=reg,
                               interval_s=5.0, clock=clock)
    # before any merge, a scrape falls back to the local registry
    assert "agg_steps_total" in agg.render_prometheus()
    assert agg.fleet is None
    assert agg.tick() is not None    # first tick runs
    assert agg.tick() is None        # within the interval: no-op
    clock.t += 5.1
    assert agg.tick() is not None


def test_aggregator_never_reported_rank_counts_as_stale():
    clock = _FakeClock()
    bus = aggregate.LocalBus(num_workers=2, clock=clock)
    agg = aggregate.Aggregator(bus.endpoint(0),
                               registry=_mini_registry(),
                               interval_s=1.0, clock=clock)
    clock.t += 10.0                  # rank 1 never pushed at all
    agg.step()
    assert 'mx_rank_stale{rank="1"} 1' in agg.render_prometheus()


# -- streaming span export ----------------------------------------------------

def test_streaming_writer_rotates_and_segments_are_loadable(tmp_path):
    clock = _FakeClock()
    trace.clear()
    w = export.StreamingTraceWriter(str(tmp_path), rank=0,
                                    max_segment_bytes=1,  # every tick
                                    max_segment_age_s=1e9, clock=clock)
    for i in range(3):
        with trace.span("stream::step", step=i):
            pass
        w.tick()
    assert len(w.committed) == 3
    names = []
    for path in w.committed:
        with open(path) as f:
            lines = [json.loads(l) for l in f]
        meta = lines[0]["meta"]
        assert meta["format"] == export.SEGMENT_FORMAT
        assert meta["rank"] == 0
        assert "wall_anchor_us" in meta and "perf_anchor_us" in meta
        names += [e["name"] for e in lines[1:] if e.get("ph") == "X"]
    assert names.count("stream::step") == 3
    # rings were drained, not copied: nothing duplicated at dump time
    assert trace.event_count() == 0
    w.close()


def test_streaming_writer_age_budget_and_seq_resume(tmp_path):
    clock = _FakeClock()
    trace.clear()
    w = export.StreamingTraceWriter(str(tmp_path), rank=1,
                                    max_segment_age_s=10.0, clock=clock)
    trace.instant("stream::early")
    assert w.tick() is None          # age budget not hit yet
    assert w.pending_events > 0
    clock.t += 11.0
    path = w.tick()
    assert path and os.path.basename(path) == "trace.rank1.000001.jsonl"
    w.close()
    # a restarted writer EXTENDS the segment set (no overwrite)
    w2 = export.StreamingTraceWriter(str(tmp_path), rank=1, clock=clock)
    trace.instant("stream::later")
    p2 = w2.flush()
    assert os.path.basename(p2) == "trace.rank1.000002.jsonl"
    w2.close()


def test_streaming_commit_failure_keeps_events_and_retries(tmp_path,
                                                           fault_fs):
    """A failed segment commit (kill/EIO at the rename) leaves no
    partial .jsonl, keeps the pending events, and the next flush
    commits them."""
    trace.clear()
    w = export.StreamingTraceWriter(str(tmp_path), rank=0)
    trace.instant("faulty::mark")
    fault_fs.fail_next_renames(1)
    with pytest.raises(OSError):
        w.flush()
    assert not [f for f in os.listdir(str(tmp_path))
                if f.endswith(".jsonl")]
    assert w.pending_events > 0
    path = w.flush()                 # retry succeeds, nothing lost
    with open(path) as f:
        lines = [json.loads(l) for l in f]
    assert any(e.get("name") == "faulty::mark" for e in lines)
    w.close()


def test_streaming_writer_survives_non_json_span_args(tmp_path):
    """span(**args) is an open API: a numpy scalar arg must degrade to
    its string form, not raise out of tick()/flush() with the batch
    already drained from the rings."""
    import numpy as np

    trace.clear()
    w = export.StreamingTraceWriter(str(tmp_path), rank=0)
    trace.instant("np::mark", v=np.int64(3), a=np.ones(2))
    path = w.flush()                 # must not raise
    with open(path) as f:
        lines = [json.loads(l) for l in f]
    mark = [e for e in lines if e.get("name") == "np::mark"][0]
    assert mark["args"]["v"] == "3"
    w.close()
    # trace.dump() shares the open-args contract
    trace.instant("np::dumped", v=np.int64(7))
    data = json.load(open(trace.dump(str(tmp_path / "d.json"))))
    assert any(e["name"] == "np::dumped" for e in data["traceEvents"])


def test_trace_dump_atomic_under_kill_mid_dump(tmp_path, fault_fs):
    """ISSUE 5 satellite: a crash mid-``trace.dump()`` must leave the
    previous dump intact — never a truncated, unloadable JSON."""
    trace.clear()
    path = str(tmp_path / "chrome_trace.json")
    trace.instant("atomic::first")
    assert trace.dump(path) == path
    before = open(path).read()
    json.loads(before)

    trace.instant("atomic::second")
    fault_fs.fail_next_writes(1)     # dies at the first staged byte
    with pytest.raises(OSError):
        trace.dump(path)
    assert open(path).read() == before      # old dump untouched
    assert not [f for f in os.listdir(str(tmp_path)) if ".tmp" in f]

    fault_fs.fail_next_renames(1)    # dies at the commit rename
    with pytest.raises(OSError):
        trace.dump(path)
    json.loads(open(path).read())    # still the old, loadable dump

    out = trace.dump(path)           # clean retry wins
    data = json.load(open(out))
    assert any(e["name"] == "atomic::second"
               for e in data["traceEvents"])


# -- trace merge --------------------------------------------------------------

def test_trace_merge_two_ranks_one_timeline(tmp_path):
    trace.clear()
    # two writers standing in for two ranks' processes
    for rank in (0, 1):
        w = export.StreamingTraceWriter(str(tmp_path), rank=rank)
        with trace.span("merge::step", rank=rank):
            pass
        trace.instant("merge::mark", rank=rank)
        w.flush()
        w.close()
    out = str(tmp_path / "merged.json")
    merged = trace_merge.merge([str(tmp_path)], out=out)
    data = json.load(open(out))      # loadable chrome trace JSON
    events = data["traceEvents"]
    pids = {e["pid"] for e in events if e.get("ph") != "M"}
    assert pids == {0, 1}            # one lane per rank
    pnames = {(e["pid"], e["args"]["name"]) for e in events
              if e.get("ph") == "M" and e["name"] == "process_name"}
    assert (0, "rank 0") in pnames and (1, "rank 1") in pnames
    spans = [e for e in events if e.get("ph") == "X"]
    assert {e["pid"] for e in spans} == {0, 1}
    assert all(e["ts"] >= 0 for e in spans)   # rebased to a shared zero
    assert merged["traceEvents"] == events


def test_trace_merge_skips_torn_lines_and_takes_plain_dumps(tmp_path):
    trace.clear()
    trace.instant("dumped::mark")
    dump = trace.dump(str(tmp_path / "chrome_trace.json"))
    # an anchored streamed segment alongside the anchorless dump
    w = export.StreamingTraceWriter(str(tmp_path / "seg"), rank=0)
    trace.instant("streamed::mark")
    w.flush()
    w.close()
    # a torn segment: valid header, one valid line, one truncated line
    torn = tmp_path / "trace.rank7.000001.jsonl"
    torn.write_text(
        json.dumps({"meta": {"rank": 7}}) + "\n"
        + json.dumps({"ph": "i", "name": "torn::ok", "ts": 1.0,
                      "pid": 1, "tid": 1}) + "\n"
        + '{"ph": "i", "name": "torn::lost", "ts"')
    merged = trace_merge.merge([dump, str(torn),
                                str(tmp_path / "seg")])
    by_name = {e["name"]: e for e in merged["traceEvents"]}
    assert "torn::ok" in by_name
    assert "torn::lost" not in by_name
    assert "dumped::mark" in by_name
    # mixed time bases land on ONE usable timeline: anchorless inputs
    # are aligned at their first event, so nothing sits wall-clock
    # epochs away from the anchored (wall-rebased) lanes
    spans = [e for e in merged["traceEvents"] if e.get("ph") != "M"]
    assert all(0 <= e["ts"] < 60e6 for e in spans), \
        [(e["name"], e["ts"]) for e in spans]


# -- SLO burn rate ------------------------------------------------------------

def test_slo_threshold_snaps_up_and_label_filter():
    reg = tmetrics.Registry()
    fam = reg.histogram("slo_lat_seconds", labels=("server",),
                        buckets=(0.1, 0.25, 0.5))
    fam.labels(server="a").observe(0.2)      # good under 0.25
    fam.labels(server="b").observe(0.4)      # bad under 0.25
    s = slo.ServiceLevelObjective("lat", 0.99, 0.2, fam)
    assert s.effective_threshold == 0.25     # snapped up
    assert s.totals() == (1, 2)
    scoped = slo.ServiceLevelObjective("lat_a", 0.99, 0.2, fam,
                                       labels={"server": "a"})
    assert scoped.totals() == (0, 1)
    # lazy name resolution: family may not exist yet
    lazy = slo.ServiceLevelObjective("lazy", 0.9, 0.1, "nope_seconds",
                                     registry=reg)
    assert lazy.totals() == (0, 0)
    with pytest.raises(ValueError):
        slo.ServiceLevelObjective("bad", 1.5, 0.1, fam)


def test_slo_burn_rate_crosses_threshold_and_alerts_rate_limited(caplog):
    """ISSUE 5 acceptance: fake-clock burn: the gauge crosses the alert
    threshold on sustained errors, the alert fires rate-limited (one
    line per window), and mx_anomalies_total counts every firing."""
    clock = _FakeClock(1000.0)
    reg = tmetrics.Registry()
    fam = reg.histogram("burn_lat_seconds", buckets=(0.1, 0.25, 1.0))
    import logging

    logger = logging.getLogger("slo_burn_test")
    burn = slo.BurnRateMonitor(windows=(300.0, 3600.0),
                               alert_burn_rate=5.0, eval_interval_s=10.0,
                               warn_interval_s=60.0, registry=reg,
                               clock=clock, logger=logger)
    burn.add_latency_slo("lat", 0.99, 0.25, fam)
    gauge = reg.get("mx_slo_burn_rate")

    # healthy traffic: burn stays 0, no alerts
    for _ in range(10):
        clock.t += 10.0
        fam.observe(0.05)
        burn.evaluate()
    assert gauge.labels(slo="lat", window="5m").value == 0.0
    assert reg.get("mx_slo_alerts_total").labels(slo="lat").value == 0

    # sustained 100% errors: both windows burn at 1/0.01 = 100x
    with caplog.at_level("WARNING", logger="slo_burn_test"):
        for _ in range(12):
            clock.t += 10.0
            fam.observe(5.0)
            burn.evaluate()
        assert gauge.labels(slo="lat", window="5m").value \
            > burn.alert_burn_rate
        assert gauge.labels(slo="lat", window="1h").value \
            > burn.alert_burn_rate
        fired = reg.get("mx_slo_alerts_total").labels(slo="lat").value
        assert fired >= 2
        emitted = [r for r in caplog.records
                   if "burning error budget" in r.getMessage()]
        # rate-limited: many firings, few lines (one per 60s window)
        assert 1 <= len(emitted) < fired
        assert reg.get("mx_anomalies_total")
        assert reg.get("mx_anomalies_total").labels(
            kind="slo_burn").value == fired

    # recovery: healthy traffic drains the short window back under
    for _ in range(31):
        clock.t += 10.0
        fam.observe(0.05)
        burn.evaluate()
    assert gauge.labels(slo="lat", window="5m").value \
        < burn.alert_burn_rate


def test_slo_tick_cadence_and_monitor_routing():
    clock = _FakeClock()
    reg = tmetrics.Registry()
    fam = reg.histogram("tick_lat_seconds", buckets=(0.1, 1.0))
    monitor = telemetry.StepMonitor(clock=clock, warn_interval_s=1e9)
    burn = slo.BurnRateMonitor(windows=(10.0,), alert_burn_rate=1.0,
                               eval_interval_s=5.0, registry=reg,
                               clock=clock, monitor=monitor)
    burn.add_latency_slo("t", 0.5, 0.1, fam)
    assert burn.tick() is not None
    assert burn.tick() is None       # inside eval_interval_s
    clock.t += 5.0
    fam.observe(9.0)                 # 100% bad, budget 0.5 -> burn 2.0
    clock.t += 5.0
    out = burn.tick()
    assert out["t"]["10s"] == pytest.approx(2.0)
    # alert routed through the StepMonitor's anomaly path
    assert monitor.anomaly_counts.get("slo_burn", 0) == 1


def test_serving_latency_slo_helper_scopes_to_one_server():
    from mxnet_tpu.serving.metrics import ServingMetrics

    m1, m2 = ServingMetrics(), ServingMetrics()
    try:
        m1.record_request_latency(4, 0.5)    # slow on server 1
        m2.record_request_latency(4, 0.01)   # fast on server 2
        s = m1.latency_slo(0.99, 0.1)
        bad, total = s.totals()
        assert (bad, total) == (1, 1)        # m2's traffic not counted
        assert s.name == "serving_latency_%s" % m1.server_id
    finally:
        m1.close()
        m2.close()


# -- flamegraph ---------------------------------------------------------------

def test_flamegraph_top_ranks_by_self_time():
    reg = tmetrics.Registry()
    fam = reg.histogram("mx_dispatch_seconds", labels=("op",))
    for _ in range(10):
        fam.labels(op="heavy").observe(0.1)
    fam.labels(op="light").observe(0.001)
    rows = flamegraph.top(k=5, registry=reg)
    assert [r["op"] for r in rows] == ["heavy", "light"]
    assert rows[0]["calls"] == 10
    assert rows[0]["share"] > 0.99
    assert rows[0]["p99_ms"] >= rows[0]["p50_ms"] > 0
    text = flamegraph.render_top(k=1, registry=reg)
    assert "heavy" in text and "light" not in text


def test_flamegraph_collapsed_self_time_nesting(tmp_path):
    events = [
        {"ph": "M", "name": "thread_name", "pid": 1, "tid": 7, "ts": 0,
         "args": {"name": "worker"}},
        {"ph": "X", "name": "outer", "pid": 1, "tid": 7, "ts": 0.0,
         "dur": 100.0},
        {"ph": "X", "name": "inner", "pid": 1, "tid": 7, "ts": 10.0,
         "dur": 30.0},
        {"ph": "X", "name": "inner", "pid": 1, "tid": 7, "ts": 50.0,
         "dur": 20.0},
    ]
    folded = flamegraph.collapsed({"traceEvents": events})
    lines = dict(l.rsplit(" ", 1) for l in folded.strip().splitlines())
    assert lines["worker;outer"] == "50"         # 100 - 30 - 20
    assert lines["worker;outer;inner"] == "50"   # 30 + 20
    # a bare traceEvents list (json.load(f)["traceEvents"]) works too
    assert flamegraph.collapsed(events) == folded
    path = flamegraph.dump_collapsed(str(tmp_path / "x.collapsed"),
                                     {"traceEvents": events})
    assert "worker;outer;inner 50" in open(path).read()


def test_profiler_dumps_top_format():
    mx.profiler.dumps(reset=True)
    mx.profiler.record_op_span("fg_op", 0.02)
    text = mx.profiler.dumps(format="top")
    assert "fg_op" in text and "Share" in text
    with pytest.raises(ValueError):
        mx.profiler.dumps(format="flame")


# -- http server handle (ISSUE 5 satellite) -----------------------------------

def test_http_server_handle_scrape_close_restart_same_port():
    reg = tmetrics.Registry()
    reg.counter("handle_total").inc(5)
    try:
        srv = tmetrics.start_http_server(0, registry=reg)
    except OSError as exc:
        pytest.skip("cannot bind localhost: %s" % exc)
    try:
        import urllib.request

        port = srv.port
        assert port > 0                      # real bound port, not 0
        assert srv.url.endswith(":%d/metrics" % port)
        body = urllib.request.urlopen(srv.url, timeout=10).read()
        assert b"handle_total 5" in body
    finally:
        srv.close()
    # close() released the socket AND joined the thread: the same port
    # binds again immediately, and no serving thread lingers
    assert not any(t.name == "mx-telemetry-http"
                   for t in threading.enumerate())
    srv2 = tmetrics.start_http_server(port, registry=reg)
    try:
        assert srv2.port == port
        import urllib.request

        body = urllib.request.urlopen(srv2.url, timeout=10).read()
        assert b"handle_total 5" in body
    finally:
        srv2.close()
        srv2.close()                         # idempotent


# -- 2-process acceptance -----------------------------------------------------

_PROG = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "telemetry_dist_prog.py")
_ENV = {
    "JAX_PLATFORMS": "cpu",
    "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
}


def _can_bind_localhost():
    try:
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        s.close()
        return True
    except OSError:
        return False


def _launch(tmp_path, mode):
    if not _can_bind_localhost():
        pytest.skip("localhost sockets unavailable (multi-process "
                    "kvstore needs them)")
    return launch_local(
        2, 1, [sys.executable, _PROG, str(tmp_path), mode],
        env_extra=_ENV, timeout=300)


def test_two_process_pod_scrape_and_merged_trace(tmp_path):
    """ISSUE 5 acceptance: a 2-process dist job yields ONE rank-0
    scrape containing both ranks' series and ONE merged
    Perfetto-loadable trace."""
    codes = _launch(tmp_path, "normal")
    assert codes == [0, 0], codes
    text = (tmp_path / "scrape.txt").read_text()
    for rank in (0, 1):
        assert 'podtest_steps_total{stage="train",rank="%d"} 5' % rank \
            in text, text
        assert 'podtest_step_seconds_count{rank="%d"} 5' % rank in text
        assert 'mx_rank_stale{rank="%d"} 0' % rank in text
    with open(os.path.join(str(tmp_path), "merged_trace.json")) as f:
        events = json.load(f)["traceEvents"]
    span_pids = {e["pid"] for e in events
                 if e.get("ph") == "X" and e["name"] == "podtest::step"}
    assert span_pids == {0, 1}, span_pids    # one lane per rank
    lanes = {e["args"]["name"] for e in events
             if e.get("ph") == "M" and e["name"] == "process_name"}
    assert {"rank 0", "rank 1"} <= lanes


def test_two_process_sigkill_leaves_segments_and_marks_stale(tmp_path):
    """ISSUE 5 acceptance: SIGKILL of a rank mid-run leaves loadable
    committed segments, and the survivor marks the dead rank stale
    within one aggregation interval."""
    codes = _launch(tmp_path, "kill")
    # kv ranks come from scheduler registration order, so EITHER worker
    # process may have drawn rank 1 (the SIGKILLed one): exactly one
    # worker dies by signal, the rank-0 survivor exits clean.
    assert sorted(codes) == [-9, 0], codes
    text = (tmp_path / "scrape.txt").read_text()
    assert 'mx_rank_stale{rank="1"} 1' in text, text
    assert 'mx_rank_stale{rank="0"} 0' in text
    # the dead rank's last reported series are still in the scrape
    assert 'podtest_steps_total{stage="train",rank="1"}' in text
    assert int((tmp_path / "rank0_done.txt").read_text()
               .split("=")[1]) >= 1                 # anomaly fed
    with open(os.path.join(str(tmp_path), "merged_trace.json")) as f:
        events = json.load(f)["traceEvents"]
    killed = [e for e in events if e.get("ph") == "X" and e["pid"] == 1]
    assert killed, "rank 1's committed segments were lost"
    assert not any(e["name"] == "podtest::never_committed"
                   for e in events)
