"""RNN stack tests (reference: tests/python/unittest/test_gluon_rnn.py,
test_rnn.py, test_operator.py RNN cases)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon
from mxnet_tpu.ops.rnn_ops import rnn_param_size, rnn_param_layout


def test_rnn_param_size_matches_layout():
    for mode in ("rnn_relu", "lstm", "gru"):
        for bidir in (False, True):
            size = rnn_param_size(3, 7, 5, mode, bidir)
            layout = rnn_param_layout(3, 7, 5, mode, bidir)
            last_name, last_shape, last_off = layout[-1]
            assert last_off + int(np.prod(last_shape)) == size


@pytest.mark.parametrize("mode", ["rnn_tanh", "lstm", "gru"])
def test_fused_op_shapes(mode):
    T, N, I, H, L = 4, 2, 3, 5, 2
    psz = rnn_param_size(L, H, I, mode)
    out = mx.nd.RNN(mx.nd.random.normal(0, 1, shape=(T, N, I)),
                    mx.nd.random.normal(0, 0.1, shape=(psz,)),
                    mx.nd.zeros((L, N, H)),
                    *([mx.nd.zeros((L, N, H))] if mode == "lstm" else []),
                    state_size=H, num_layers=L, mode=mode,
                    state_outputs=True)
    assert out[0].shape == (T, N, H)
    assert out[1].shape == (L, N, H)


@pytest.mark.parametrize("mode", ["lstm", "gru", "rnn_tanh"])
def test_fused_equals_unfused(mode):
    """The fused lax.scan kernel and the per-step cells share parameters
    via unfuse() and must agree numerically (reference
    test_gluon_rnn.py:check_rnn_consistency)."""
    layer_cls = {"lstm": gluon.rnn.LSTM, "gru": gluon.rnn.GRU,
                 "rnn_tanh": lambda h, **kw: gluon.rnn.RNN(
                     h, activation="tanh", **kw)}[mode]
    layer = layer_cls(8, num_layers=2)
    layer.initialize(mx.initializer.Xavier())
    x = mx.nd.random.normal(0, 1, shape=(6, 3, 4))
    fused_out = layer(x)
    stack = layer.unfuse()
    # params are shared by construction; no copying needed
    unfused_out, _ = stack.unroll(6, x, layout="TNC", merge_outputs=True)
    np.testing.assert_allclose(fused_out.asnumpy(), unfused_out.asnumpy(),
                               rtol=1e-4, atol=1e-5)


def test_fused_equals_unfused_bidirectional():
    layer = gluon.rnn.LSTM(5, num_layers=1, bidirectional=True)
    layer.initialize(mx.initializer.Xavier())
    x = mx.nd.random.normal(0, 1, shape=(4, 2, 3))
    fused_out = layer(x)
    unfused_out, _ = layer.unfuse().unroll(4, x, layout="TNC",
                                           merge_outputs=True)
    np.testing.assert_allclose(fused_out.asnumpy(), unfused_out.asnumpy(),
                               rtol=1e-4, atol=1e-5)


def test_rnn_autograd_and_training():
    """A tiny LSTM regressor must fit a memorization task — exercises
    gradient flow through the scan."""
    T, N, I, H = 5, 8, 3, 16
    rng = np.random.RandomState(0)
    X = rng.randn(T, N, I).astype(np.float32)
    target = rng.randn(N, 1).astype(np.float32)

    net = gluon.rnn.LSTM(H)
    dense = gluon.nn.Dense(1)
    net.initialize(mx.initializer.Xavier())
    dense.initialize(mx.initializer.Xavier())
    params = net.collect_params()
    params.update(dense.collect_params())
    trainer = gluon.Trainer(params, "adam", {"learning_rate": 0.02})
    loss_fn = gluon.loss.L2Loss()
    xs = mx.nd.array(X)
    ys = mx.nd.array(target)
    first = None
    for i in range(60):
        with mx.autograd.record():
            h = net(xs)          # (T, N, H)
            last = h[-1]         # (N, H)
            out = dense(last)
            loss = loss_fn(out, ys).mean()
        loss.backward()
        trainer.step(1)
        v = float(loss.asscalar())
        if first is None:
            first = v
    assert v < first * 0.1, "LSTM failed to fit: %.4f -> %.4f" % (first, v)


def test_gluon_rnn_save_load(tmp_path):
    layer = gluon.rnn.GRU(7, num_layers=2, bidirectional=True)
    layer.initialize()
    x = mx.nd.random.normal(0, 1, shape=(4, 2, 3))
    out1 = layer(x)
    f = str(tmp_path / "gru.params")
    layer.save_parameters(f)
    layer2 = gluon.rnn.GRU(7, num_layers=2, bidirectional=True)
    layer2.load_parameters(f)
    out2 = layer2(x)
    np.testing.assert_allclose(out1.asnumpy(), out2.asnumpy(), rtol=1e-6)


def test_rnn_dropout_modes():
    layer = gluon.rnn.LSTM(8, num_layers=2, dropout=0.5)
    layer.initialize()
    x = mx.nd.ones((4, 2, 3))
    eval_out1 = layer(x).asnumpy()
    eval_out2 = layer(x).asnumpy()
    np.testing.assert_allclose(eval_out1, eval_out2)  # eval: deterministic
    with mx.autograd.record(train_mode=True):
        train_out1 = layer(x).asnumpy()
        train_out2 = layer(x).asnumpy()
    assert not np.allclose(train_out1, train_out2)  # train: stochastic


def test_hybridized_cell_unroll():
    cell = gluon.rnn.LSTMCell(8)
    cell.initialize()
    x = mx.nd.random.normal(0, 1, shape=(3, 5, 4))  # NTC
    out_e, st_e = cell.unroll(5, x, layout="NTC", merge_outputs=True)
    cell.hybridize()
    out_h, st_h = cell.unroll(5, x, layout="NTC", merge_outputs=True)
    # atol: eager-vs-compiled fusion reordering can drift near-zero
    # elements past any pure-rtol bound (seed-dependent flake)
    np.testing.assert_allclose(out_e.asnumpy(), out_h.asnumpy(),
                               rtol=1e-5, atol=1e-6)
    for a, b in zip(st_e, st_h):   # final (h, c) states must match too
        np.testing.assert_allclose(a.asnumpy(), b.asnumpy(),
                                   rtol=1e-5, atol=1e-6)


def test_legacy_symbolic_cells():
    data = mx.sym.Variable("data")
    cell = mx.rnn.LSTMCell(8, prefix="lstm_")
    outputs, _ = cell.unroll(5, data, layout="NTC", merge_outputs=True)
    ex = outputs.simple_bind(ctx=mx.cpu(), data=(3, 5, 4))
    ex.arg_dict["data"][:] = np.random.randn(3, 5, 4).astype(np.float32)
    assert ex.forward()[0].shape == (3, 5, 8)


def test_legacy_fused_cell_pack_unpack():
    fcell = mx.rnn.FusedRNNCell(6, num_layers=2, mode="lstm",
                                prefix="lstm_")
    data = mx.sym.Variable("data")
    out, _ = fcell.unroll(4, data, layout="NTC", merge_outputs=True)
    ex = out.simple_bind(ctx=mx.cpu(), data=(2, 4, 3))
    args = {"lstm_parameters": ex.arg_dict["lstm_parameters"].copy()}
    unpacked = fcell.unpack_weights(args)
    assert "lstm_l0_i2h_weight" in unpacked
    assert unpacked["lstm_l0_i2h_weight"].shape == (24, 3)
    repacked = fcell.pack_weights(unpacked)
    np.testing.assert_allclose(
        repacked["lstm_parameters"].asnumpy(),
        args.get("lstm_parameters",
                 ex.arg_dict["lstm_parameters"]).asnumpy())


def test_bucket_sentence_iter():
    sents = [[1, 2, 3], [4, 5], [1, 2, 3, 4, 5, 6], [7, 8, 9], [2, 3],
             [5, 6, 7], [9, 9, 9, 9]]
    it = mx.rnn.BucketSentenceIter(sents, batch_size=2, buckets=[3, 6])
    seen = set()
    for b in it:
        seen.add(b.bucket_key)
        assert b.data[0].shape == (2, b.bucket_key)
        # label is input shifted left by one
        d = b.data[0].asnumpy()
        l = b.label[0].asnumpy()
        np.testing.assert_allclose(l[:, :-1], d[:, 1:])
    assert 3 in seen


def test_encode_sentences():
    from mxnet_tpu.rnn import encode_sentences

    coded, vocab = encode_sentences([["a", "b"], ["b", "c"]])
    assert len(vocab) >= 3
    assert coded[0][1] == coded[1][0]  # "b" consistent


def test_bucketing_module_lstm_lm():
    """LSTM LM through BucketingModule — the reference's north-star
    bucketing use-case now runs (VERDICT r1 §5.7)."""
    from mxnet_tpu.module import BucketingModule

    V, E, H = 20, 8, 16
    rng = np.random.RandomState(0)
    sents = [list(rng.randint(1, V, size=rng.choice([3, 6]))) for _ in
             range(64)]
    it = mx.rnn.BucketSentenceIter(sents, batch_size=8, buckets=[3, 6])

    def sym_gen(seq_len):
        data = mx.sym.Variable("data")
        label = mx.sym.Variable("softmax_label")
        embed = mx.sym.Embedding(data, input_dim=V, output_dim=E,
                                 name="embed")
        cell = mx.rnn.FusedRNNCell(H, num_layers=1, mode="lstm",
                                   prefix="lstm_")
        output, _ = cell.unroll(seq_len, embed, layout="NTC",
                                merge_outputs=True)
        pred = mx.sym.reshape(output, shape=(-1, H))
        pred = mx.sym.FullyConnected(pred, num_hidden=V, name="pred")
        label_flat = mx.sym.reshape(label, shape=(-1,))
        out = mx.sym.SoftmaxOutput(pred, label_flat, name="softmax")
        return out, ("data",), ("softmax_label",)

    mod = BucketingModule(sym_gen, default_bucket_key=6, context=mx.cpu())
    from mxnet_tpu.io import DataDesc

    mod.bind(data_shapes=[DataDesc("data", (8, 6))],
             label_shapes=[DataDesc("softmax_label", (8, 6))])
    mod.init_params(initializer=mx.initializer.Xavier())
    mod.init_optimizer(optimizer="adam",
                       optimizer_params={"learning_rate": 0.01})
    losses = []
    for epoch in range(3):
        it.reset()
        tot, n = 0.0, 0
        for batch in it:
            mod.forward(batch, is_train=True)
            out = mod.get_outputs()[0].asnumpy()
            labels = batch.label[0].asnumpy().reshape(-1).astype(int)
            probs = out[np.arange(len(labels)), labels]
            tot += -np.log(np.maximum(probs, 1e-9)).mean()
            n += 1
            mod.backward()
            mod.update()
        losses.append(tot / n)
    assert losses[-1] < losses[0], "LM loss did not drop: %s" % losses


def test_unroll_valid_length():
    """Masking + true-last-state semantics (reference
    test_gluon_rnn.py:test_cell_fill_shape / valid_length cases)."""
    cell = gluon.rnn.LSTMCell(6)
    cell.initialize()
    x = mx.nd.random.normal(0, 1, shape=(3, 5, 4))  # NTC
    vl = mx.nd.array([2, 5, 3])
    out, states = cell.unroll(5, x, layout="NTC", merge_outputs=True,
                              valid_length=vl)
    o = out.asnumpy()  # (N, T, C)
    assert o.shape == (3, 5, 6)
    assert np.allclose(o[0, 2:], 0) and np.allclose(o[2, 3:], 0)
    assert not np.allclose(o[0, 1], 0)
    # state equals the hidden at the true last step
    full_out, _ = cell.unroll(5, x, layout="NTC", merge_outputs=True)
    np.testing.assert_allclose(states[0].asnumpy()[0],
                               full_out.asnumpy()[0, 1], rtol=1e-5)


def test_bidirectional_valid_length_ignores_padding():
    """Reverse direction must not consume padding (r1 review finding)."""
    lcell, rcell = gluon.rnn.RNNCell(4), gluon.rnn.RNNCell(4)
    bi = gluon.rnn.BidirectionalCell(lcell, rcell)
    bi.initialize()
    T = 6
    x = mx.nd.random.normal(0, 1, shape=(2, T, 3))
    vl = mx.nd.array([3, 6])
    out, _ = bi.unroll(T, x, layout="NTC", merge_outputs=True,
                       valid_length=vl)
    # Corrupt the padding of sequence 0; valid outputs must not change.
    x2 = x.asnumpy().copy()
    x2[0, 3:] = 77.0
    out2, _ = bi.unroll(T, mx.nd.array(x2), layout="NTC",
                        merge_outputs=True, valid_length=vl)
    np.testing.assert_allclose(out.asnumpy()[0, :3],
                               out2.asnumpy()[0, :3], rtol=1e-5)


def test_legacy_graph_json_serializable(tmp_path):
    """Init-carrying variables must not break tojson (r1 review
    finding: Initializer objects in __init__ attrs)."""
    data = mx.sym.Variable("data")
    cell = mx.rnn.LSTMCell(4, prefix="lstm_")
    out, _ = cell.unroll(3, data, layout="NTC", merge_outputs=True)
    js = out.tojson()
    assert "lstm_" in js
    fcell = mx.rnn.FusedRNNCell(4, mode="lstm", prefix="flstm_")
    fout, _ = fcell.unroll(3, data, layout="NTC", merge_outputs=True)
    fout.save(str(tmp_path / "f.json"))
    loaded = mx.sym.load(str(tmp_path / "f.json"))
    assert "flstm_parameters" in loaded.list_arguments()


def test_shared_params_donor_semantics():
    """Dense(params=other.params) must share the donor's weight
    (reference parameter-sharing semantics; r1 review finding)."""
    d0 = gluon.nn.Dense(4, in_units=3)
    d1 = gluon.nn.Dense(4, in_units=3, params=d0.collect_params())
    d0.initialize()
    x = mx.nd.random.normal(0, 1, shape=(2, 3))
    np.testing.assert_allclose(d0(x).asnumpy(), d1(x).asnumpy())
    assert d1.weight is d0.weight or \
        d1.params.get("weight") is d0.params.get("weight")


def test_fused_rnn_initializer():
    from mxnet_tpu.initializer import FusedRNN, InitDesc, Uniform
    from mxnet_tpu.ops.rnn_ops import rnn_param_size, rnn_param_layout

    H, I, L = 4, 3, 2
    init = FusedRNN(Uniform(0.1), num_hidden=H, num_layers=L, mode="lstm")
    arr = np.zeros((rnn_param_size(L, H, I, "lstm"),), np.float32)
    init(InitDesc("lstm_parameters"), arr)
    # forget-gate bias slice == 1.0, other bias entries 0, weights nonzero
    for name, shape, off in rnn_param_layout(L, H, I, "lstm"):
        n = int(np.prod(shape))
        blk = arr[off:off + n].reshape(shape)
        if name.endswith("i2h_bias"):
            assert np.allclose(blk[H:2 * H], 1.0)
            assert np.allclose(blk[:H], 0.0)
        elif name.endswith("weight"):
            assert np.abs(blk).max() > 0
    # round-trips through dumps
    spec = init.dumps()
    from mxnet_tpu.initializer import _from_spec

    init2 = _from_spec(spec)
    assert init2._num_hidden == H


def test_fused_cell_get_next_state():
    """Slice-indexing multi-output RNN symbols (r2 review finding)."""
    data = mx.sym.Variable("data")
    fcell = mx.rnn.FusedRNNCell(5, num_layers=2, mode="lstm",
                                prefix="lstm_", get_next_state=True)
    out, states = fcell.unroll(4, data, layout="NTC", merge_outputs=True)
    assert len(states) == 2
    grp = mx.sym.Group([out] + states)
    ex = grp.simple_bind(ctx=mx.cpu(), data=(3, 4, 6))
    outs = ex.forward()
    assert outs[0].shape == (3, 4, 5)
    assert outs[1].shape == (2, 3, 5)  # state h
    assert outs[2].shape == (2, 3, 5)  # state c


def test_residual_cell_valid_length_masking():
    cell = gluon.rnn.ResidualCell(gluon.rnn.RNNCell(3))
    cell.initialize()
    x = mx.nd.random.normal(0, 1, shape=(2, 4, 3))
    vl = mx.nd.array([2, 4])
    out, _ = cell.unroll(4, x, layout="NTC", merge_outputs=True,
                         valid_length=vl)
    o = out.asnumpy()
    assert np.allclose(o[0, 2:], 0), "padded residual steps must be zero"


def test_lstm_state_clip_per_step():
    from mxnet_tpu.ops.rnn_ops import rnn_param_size

    T, N, I, H = 6, 2, 3, 4
    psz = rnn_param_size(1, H, I, "lstm")
    data = mx.nd.random.normal(0, 5, shape=(T, N, I))
    params = mx.nd.random.normal(0, 2, shape=(psz,))
    out = mx.nd.RNN(data, params, mx.nd.zeros((1, N, H)),
                    mx.nd.zeros((1, N, H)), state_size=H, num_layers=1,
                    mode="lstm", state_outputs=True,
                    lstm_state_clip_min=-0.01, lstm_state_clip_max=0.01)
    # if c is clipped per step, |h| <= sigmoid * tanh(0.01) ~ 0.01
    assert np.abs(out[0].asnumpy()).max() <= 0.011


def test_subclass_initializer_dumps_roundtrip():
    from mxnet_tpu.initializer import MSRAPrelu, _from_spec

    spec = MSRAPrelu().dumps()
    init2 = _from_spec(spec)
    assert type(init2).__name__ == "MSRAPrelu"
