"""SPMD parallel layer tests on the 8-device CPU mesh
(reference analogue: tests/python/gpu multi-device + dist kvstore
nightlies — here sharded-executable based)."""
import numpy as np
import pytest

import jax

import mxnet_tpu as mx
from mxnet_tpu import gluon
from mxnet_tpu.parallel import make_mesh, TrainStep, shard_params
from mxnet_tpu.parallel.mesh import P


def test_make_mesh_infer():
    mesh = make_mesh({"dp": -1})
    assert mesh.shape["dp"] == 8
    mesh = make_mesh({"dp": -1, "tp": 2})
    assert mesh.shape == {"dp": 4, "tp": 2}
    with pytest.raises(AssertionError):
        make_mesh({"dp": 3})


def test_shard_params_rule():
    mesh = make_mesh({"dp": 4, "tp": 2})
    shardings = shard_params(mesh, {"dense_w": (64, 32), "bias": (64,),
                                    "conv_w": (64, 3, 3, 3)})
    assert shardings["dense_w"].spec == P("tp", None)
    assert shardings["bias"].spec == P()
    assert shardings["conv_w"].spec == P("tp", None, None, None)


def test_train_step_dp_converges():
    """Pure data-parallel training step drives loss down."""
    mesh = make_mesh({"dp": 8})
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(32, activation="relu"))
    net.add(gluon.nn.Dense(2))
    net.initialize()
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    step = TrainStep(net, loss_fn, optimizer="adam",
                     optimizer_params={"learning_rate": 0.05}, mesh=mesh)
    rng = np.random.RandomState(0)
    X = rng.randn(64, 10).astype(np.float32)
    w = rng.randn(10).astype(np.float32)
    Y = (X @ w > 0).astype(np.float32)
    losses = [float(jax.device_get(step(X, Y))) for _ in range(30)]
    assert losses[-1] < losses[0] * 0.5, losses[::10]


def test_train_step_tp_matches_dp():
    """dp×tp sharded step computes the same math as pure dp."""
    rng = np.random.RandomState(1)
    X = rng.randn(16, 12).astype(np.float32)
    Y = (rng.rand(16) > 0.5).astype(np.float32)

    def build():
        mx.random.seed(7)
        net = gluon.nn.HybridSequential()
        net.add(gluon.nn.Dense(16, activation="relu", in_units=12))
        net.add(gluon.nn.Dense(2, in_units=16))
        net.initialize(force_reinit=True)
        return net

    losses = {}
    for name, axes in [("dp", {"dp": 8}), ("tp", {"dp": 4, "tp": 2})]:
        net = build()
        step = TrainStep(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                         optimizer="sgd",
                         optimizer_params={"learning_rate": 0.1,
                                           "momentum": 0.9},
                         mesh=make_mesh(axes))
        losses[name] = [float(jax.device_get(step(X, Y))) for _ in range(5)]
    np.testing.assert_allclose(losses["dp"], losses["tp"], rtol=2e-4)


def test_train_step_batchnorm_aux():
    """BN running stats update inside the compiled sharded step."""
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(8, in_units=4))
    net.add(gluon.nn.BatchNorm())
    net.add(gluon.nn.Dense(2, in_units=8))
    net.initialize()
    step = TrainStep(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                     mesh=make_mesh({"dp": 8}))
    X = np.random.rand(16, 4).astype(np.float32) * 3 + 1
    Y = np.zeros(16, dtype=np.float32)
    step(X, Y)
    step(X, Y)
    step.sync_to_net()
    bn = net[1]
    rm = bn.running_mean.data().asnumpy()
    assert np.abs(rm).sum() > 0, "running stats never updated"


def test_train_step_sync_to_net():
    net = gluon.nn.Dense(2, in_units=3)
    net.initialize()
    w0 = net.weight.data().asnumpy().copy()
    step = TrainStep(net, gluon.loss.L2Loss(), mesh=make_mesh({"dp": 8}),
                     optimizer_params={"learning_rate": 0.5})
    X = np.random.rand(8, 3).astype(np.float32)
    Y = np.random.rand(8, 2).astype(np.float32)
    step(X, Y)
    step.sync_to_net()
    assert not np.allclose(w0, net.weight.data().asnumpy())


def test_train_step_bf16_mixed_precision():
    """dtype='bfloat16' keeps fp32 master weights (mp_sgd contract:
    reference optimizer.py:201-266) while computing in bf16, and still
    converges."""
    mesh = make_mesh({"dp": 8})
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(32, activation="relu"))
    net.add(gluon.nn.Dense(2))
    net.initialize()
    step = TrainStep(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                     optimizer="sgd",
                     optimizer_params={"learning_rate": 0.5},
                     mesh=mesh, dtype="bfloat16")
    rng = np.random.RandomState(3)
    X = rng.randn(64, 10).astype(np.float32)
    w = rng.randn(10).astype(np.float32)
    Y = (X @ w > 0).astype(np.float32)
    losses = [float(jax.device_get(step(X, Y))) for _ in range(30)]
    # Masters and optimizer state stayed fp32.
    for n, v in step._param_vals.items():
        assert v.dtype == np.float32, (n, v.dtype)
    for n, st in step._opt_state.items():
        for s in st:
            assert s.dtype == np.float32, (n, s.dtype)
    # Loss is fp32 and training progressed.
    assert losses[-1] < losses[0] * 0.7, losses[::10]


def test_train_step_resnet_block_tp_state_equivalence():
    """dp×tp == pure dp after FOUR steps, compared on the full training
    state: parameters, momentum buffers, and BatchNorm running stats —
    not just the loss trace (VERDICT r3 next #9)."""
    from jax import tree_util as jtu

    rng = np.random.RandomState(5)
    X = rng.rand(16, 4, 8, 8).astype(np.float32)
    Y = (np.arange(16) % 4).astype(np.float32)

    def build():
        mx.random.seed(11)
        net = gluon.nn.HybridSequential()
        net.add(gluon.nn.Conv2D(8, 3, padding=1, in_channels=4),
                gluon.nn.BatchNorm(),
                gluon.nn.Activation("relu"),
                gluon.nn.Conv2D(8, 3, padding=1, in_channels=8),
                gluon.nn.BatchNorm(),
                gluon.nn.Flatten(),
                gluon.nn.Dense(4))
        net.initialize(force_reinit=True)
        return net

    states = {}
    for name, axes in [("dp", {"dp": 8}), ("tp", {"dp": 2, "tp": 4})]:
        net = build()
        step = TrainStep(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                         optimizer="sgd",
                         optimizer_params={"learning_rate": 0.1,
                                           "momentum": 0.9},
                         mesh=make_mesh(axes))
        for _ in range(4):
            loss = step(X, Y)
        states[name] = (jax.device_get(step._param_vals),
                        jax.device_get(step._opt_state),
                        jax.device_get(step._aux_vals),
                        float(jax.device_get(loss)))

    p_dp, m_dp, a_dp, l_dp = states["dp"]
    p_tp, m_tp, a_tp, l_tp = states["tp"]
    assert abs(l_dp - l_tp) < 2e-4 * max(1.0, abs(l_dp))
    # block-scope counters differ between the two builds
    # (conv0/conv2, ...), but sorted name order aligns structurally
    for nd, nt in zip(sorted(p_dp), sorted(p_tp)):
        np.testing.assert_allclose(p_dp[nd], p_tp[nt], rtol=2e-4,
                                   atol=1e-5,
                                   err_msg="param %s/%s" % (nd, nt))
    for nd, nt in zip(sorted(a_dp), sorted(a_tp)):
        np.testing.assert_allclose(a_dp[nd], a_tp[nt], rtol=2e-4,
                                   atol=1e-5,
                                   err_msg="aux %s/%s" % (nd, nt))
    flat_dp = jtu.tree_leaves(m_dp)
    flat_tp = jtu.tree_leaves(m_tp)
    assert len(flat_dp) == len(flat_tp) and flat_dp
    for i, (a, b) in enumerate(zip(flat_dp, flat_tp)):
        np.testing.assert_allclose(a, b, rtol=2e-4, atol=1e-5,
                                   err_msg="momentum leaf %d" % i)


@pytest.mark.parametrize("opt,opt_params", [
    ("nag", {"learning_rate": 0.05, "momentum": 0.9}),
    ("rmsprop", {"learning_rate": 0.01}),
    ("rmsprop", {"learning_rate": 0.01, "centered": True}),
    ("adagrad", {"learning_rate": 0.05}),
    ("adadelta", {}),
    ("ftrl", {"learning_rate": 0.1}),
    ("signum", {"learning_rate": 0.01, "momentum": 0.9}),
    ("signsgd", {"learning_rate": 0.01}),
])
def test_train_step_matches_trainer(opt, opt_params):
    """Every TrainStep optimizer family reproduces the imperative
    Trainer path exactly (same FCompute bodies, VERDICT r3 weak #7)."""
    rng = np.random.RandomState(3)
    X = rng.randn(8, 5).astype(np.float32)
    Y = rng.rand(8, 3).astype(np.float32)

    def build():
        mx.random.seed(21)
        net = gluon.nn.Dense(3, in_units=5)
        net.initialize(force_reinit=True)
        return net

    # imperative Trainer reference
    net_a = build()
    # Trainer.step(8) sets rescale_grad = 1/8 internally
    tr = gluon.Trainer(net_a.collect_params(), opt, dict(opt_params))
    for _ in range(4):
        with mx.autograd.record():
            loss = gluon.loss.L2Loss()(net_a(mx.nd.array(X)),
                                       mx.nd.array(Y)).sum()
        loss.backward()
        tr.step(8, ignore_stale_grad=True)

    # fused TrainStep: mean-loss => grads are already 1/batch scaled,
    # so rescale_grad stays 1 while the Trainer divides by batch.
    net_b = build()
    step = TrainStep(net_b, lambda p, l: gluon.loss.L2Loss()(p, l) * 8,
                     optimizer=opt,
                     optimizer_params=dict(opt_params,
                                           rescale_grad=1.0 / 8),
                     mesh=make_mesh({"dp": 1},
                                    devices=[jax.devices()[0]]))
    for _ in range(4):
        step(X, Y)
    step.sync_to_net()

    wa = net_a.weight.data().asnumpy()
    wb = net_b.weight.data().asnumpy()
    np.testing.assert_allclose(wa, wb, rtol=1e-5, atol=1e-6,
                               err_msg="optimizer %s diverged" % opt)


@pytest.mark.parametrize("opt,opt_params,single_param", [
    ("ftml", {"learning_rate": 0.02}, False),
    ("nadam", {"learning_rate": 0.01}, True),   # shared-schedule quirk
    ("dcasgd", {"learning_rate": 0.05, "momentum": 0.9}, False),
    ("dcasgd", {"learning_rate": 0.05}, False),
    ("lbsgd", {"learning_rate": 0.05, "momentum": 0.9}, False),
])
def test_train_step_matches_trainer_extended(opt, opt_params, single_param):
    """The five families added by VERDICT r4 #6 reproduce the imperative
    Trainer path (NADAM: single-parameter group, see TrainStep
    docstring for the documented schedule deviation)."""
    rng = np.random.RandomState(13)
    X = rng.randn(8, 5).astype(np.float32)
    Y = rng.rand(8, 3).astype(np.float32)

    def build():
        mx.random.seed(29)
        net = gluon.nn.Dense(3, in_units=5, use_bias=not single_param)
        net.initialize(force_reinit=True)
        return net

    net_a = build()
    tr = gluon.Trainer(net_a.collect_params(), opt, dict(opt_params))
    for _ in range(4):
        with mx.autograd.record():
            loss = gluon.loss.L2Loss()(net_a(mx.nd.array(X)),
                                       mx.nd.array(Y)).sum()
        loss.backward()
        tr.step(8, ignore_stale_grad=True)

    net_b = build()
    step = TrainStep(net_b, lambda p, l: gluon.loss.L2Loss()(p, l) * 8,
                     optimizer=opt,
                     optimizer_params=dict(opt_params,
                                           rescale_grad=1.0 / 8),
                     mesh=make_mesh({"dp": 1},
                                    devices=[jax.devices()[0]]))
    for _ in range(4):
        step(X, Y)
    step.sync_to_net()

    wa = net_a.weight.data().asnumpy()
    wb = net_b.weight.data().asnumpy()
    np.testing.assert_allclose(wa, wb, rtol=1e-5, atol=1e-6,
                               err_msg="optimizer %s diverged" % opt)


def test_train_step_sgld_noise_statistics():
    """SGLD is stochastic (excluded from bit-equivalence): the injected
    noise must have std ~= sqrt(lr) around the deterministic update, and
    reseeding reproduces it exactly."""
    lr = 0.04
    mx.random.seed(5)
    net = gluon.nn.Dense(1, in_units=400, use_bias=False)
    net.initialize(force_reinit=True)
    w_before = net.weight.data().asnumpy().copy()
    step = TrainStep(net, lambda p, l: gluon.loss.L2Loss()(p, l),
                     optimizer="sgld",
                     optimizer_params={"learning_rate": lr, "wd": 0.0},
                     mesh=make_mesh({"dp": 1},
                                    devices=[jax.devices()[0]]))
    X = np.zeros((4, 400), np.float32)
    Y = np.zeros((4, 1), np.float32)
    step(X, Y)
    step.sync_to_net()
    noise = net.weight.data().asnumpy() - w_before
    assert abs(noise.std() - np.sqrt(lr)) < 0.2 * np.sqrt(lr), noise.std()
    assert abs(noise.mean()) < 0.05, noise.mean()


@pytest.mark.parametrize("opt", ["adam", "sgld"])
def test_train_step_checkpoint_roundtrip(tmp_path, opt):
    """save_checkpoint/load_checkpoint restore the FULL training state
    (params + optimizer moments + aux + step counter + RNG stream):
    resuming from a checkpoint continues bit-for-bit like the
    uninterrupted run — including STOCHASTIC optimizers, whose noise
    keys must replay from the checkpointed stream position."""
    rng = np.random.RandomState(17)
    X = rng.randn(16, 6).astype(np.float32)
    Y = (rng.rand(16) > 0.5).astype(np.float32)

    def build():
        mx.random.seed(31)
        # fixed prefix: checkpoint keys are param names, which must be
        # stable across builds (as they are across process restarts)
        net = gluon.nn.HybridSequential(prefix="ckpt_")
        net.add(gluon.nn.Dense(8, activation="relu", in_units=6,
                               prefix="ckpt_d1_"),
                gluon.nn.BatchNorm(prefix="ckpt_bn_"),
                gluon.nn.Dense(2, in_units=8, prefix="ckpt_d2_"))
        net.initialize(force_reinit=True)
        return TrainStep(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                         optimizer=opt,
                         optimizer_params={"learning_rate": 0.05 if
                                           opt == "adam" else 1e-3},
                         mesh=make_mesh({"dp": 8}))

    # Uninterrupted: 6 steps.
    ref = build()
    for _ in range(6):
        ref(X, Y)
    want_p, want_s, want_a = ref.state_to_host()

    # Interrupted: 3 steps, checkpoint, fresh step, restore, 3 more.
    a = build()
    for _ in range(3):
        a(X, Y)
    ckpt = str(tmp_path / "step.params")
    a.save_checkpoint(ckpt)
    b = build()
    b(X, Y)                      # materialize (divergent step, discarded)
    b.load_checkpoint(ckpt)
    assert b.num_update == 3
    for _ in range(3):
        b(X, Y)
    got_p, got_s, got_a = b.state_to_host()

    for n in want_p:
        np.testing.assert_array_equal(want_p[n], got_p[n])
    for n in want_a:
        np.testing.assert_array_equal(want_a[n], got_a[n])
    for n in want_s:
        for x, y in zip(want_s[n], got_s[n]):
            np.testing.assert_array_equal(x, y)
