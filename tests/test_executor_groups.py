"""group2ctx model-parallel placement + AttrScope + engine error
propagation (round-3 fixes for silently-ignored placement and swallowed
exceptions)."""
import numpy as np
import pytest

import mxnet_tpu as mx


def _two_group_symbol():
    x = mx.sym.Variable("x")
    with mx.AttrScope(ctx_group="dev1"):
        h = mx.sym.FullyConnected(x, num_hidden=6, name="fc1")
        h = mx.sym.Activation(h, act_type="relu", name="act1")
    with mx.AttrScope(ctx_group="dev2"):
        out = mx.sym.FullyConnected(h, num_hidden=3, name="fc2")
    return out


def test_attr_scope_stamps_ctx_group():
    sym = _two_group_symbol()
    attrs = sym.attr_dict()
    assert attrs["fc1"]["__ctx_group__"] == "dev1"
    assert attrs["fc2"]["__ctx_group__"] == "dev2"


def test_group2ctx_places_and_computes():
    """Placement across two real devices of the 8-device CPU mesh; the
    forward/backward numbers must match a single-device bind."""
    sym = _two_group_symbol()
    rng = np.random.RandomState(7)
    args = {
        "x": mx.nd.array(rng.randn(4, 5).astype(np.float32)),
        "fc1_weight": mx.nd.array(rng.randn(6, 5).astype(np.float32)),
        "fc1_bias": mx.nd.zeros((6,)),
        "fc2_weight": mx.nd.array(rng.randn(3, 6).astype(np.float32)),
        "fc2_bias": mx.nd.zeros((3,)),
    }
    grads = {k: mx.nd.zeros(v.shape) for k, v in args.items()}
    exe_mp = sym.bind(mx.cpu(), dict(args), args_grad=dict(grads),
                      group2ctx={"dev1": mx.Context("cpu", 1),
                                 "dev2": mx.Context("cpu", 2)})
    exe_ref = sym.bind(mx.cpu(), dict(args),
                       args_grad={k: mx.nd.zeros(v.shape)
                                  for k, v in args.items()})
    out_mp = exe_mp.forward(is_train=True)[0]
    out_ref = exe_ref.forward(is_train=True)[0]
    np.testing.assert_allclose(out_mp.asnumpy(), out_ref.asnumpy(),
                               rtol=1e-5, atol=1e-5)
    # output of the dev2 group genuinely lives on cpu device 2
    devs = {d.id for d in out_mp._data.devices()}
    assert devs == {2}
    exe_mp.backward()
    exe_ref.backward()
    for k in args:
        np.testing.assert_allclose(exe_mp.grad_dict[k].asnumpy(),
                                   exe_ref.grad_dict[k].asnumpy(),
                                   rtol=1e-4, atol=1e-5)


def test_group2ctx_unknown_group_raises():
    sym = _two_group_symbol()
    args = {
        "x": mx.nd.zeros((2, 5)),
        "fc1_weight": mx.nd.zeros((6, 5)),
        "fc1_bias": mx.nd.zeros((6,)),
        "fc2_weight": mx.nd.zeros((3, 6)),
        "fc2_bias": mx.nd.zeros((3,)),
    }
    with pytest.raises(mx.MXNetError):
        sym.bind(mx.cpu(), args, group2ctx={"dev1": mx.cpu(1)})


def test_wait_for_all_propagates():
    """wait_for_all must not swallow failures (reference rethrows async
    exceptions at wait points, src/engine/threaded_engine.h:180)."""
    from mxnet_tpu import engine

    engine.wait_for_all()  # healthy path: no error, returns


def test_batch_sampler_policies():
    from mxnet_tpu.gluon.data.sampler import (BatchSampler,
                                              SequentialSampler)

    s = SequentialSampler(7)
    assert [len(b) for b in BatchSampler(s, 3, "keep")] == [3, 3, 1]
    assert [len(b) for b in BatchSampler(s, 3, "discard")] == [3, 3]
    bs = BatchSampler(s, 3, "rollover")
    assert [len(b) for b in bs] == [3, 3]
    assert [len(b) for b in bs] == [3, 3]  # 1 carried + 7 = 8 -> 2 full
    assert len(bs) == 3  # 2 now carried: (2 + 7) // 3
    with pytest.raises(ValueError):
        BatchSampler(s, 3, "bogus")
