"""Native RecordIO core (src/recordio_core.cc via ctypes) vs the
pure-python implementation — identical wire format, byte-identical
reads (reference: dmlc-core RecordIO framing)."""
import struct

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import recordio_native
from mxnet_tpu.recordio import _encode_lrec, _kMagic

pytestmark = pytest.mark.skipif(
    not recordio_native.available(),
    reason="g++ unavailable: native recordio core cannot build")


def _write_rec(path, payloads):
    rec = mx.recordio.MXRecordIO(str(path), "w")
    for p in payloads:
        rec.write(p)
    rec.close()


def test_native_index_matches_python_scan(tmp_path):
    rng = np.random.RandomState(0)
    payloads = [bytes(rng.randint(0, 256, rng.randint(1, 300),
                                  dtype=np.uint8)) for _ in range(25)]
    path = tmp_path / "a.rec"
    _write_rec(path, payloads)

    offsets = recordio_native.native_index(path)
    assert len(offsets) == 25
    # python reader agrees record-by-record at each native offset
    reader = mx.recordio.MXRecordIO(str(path), "r")
    for i, payload in enumerate(payloads):
        got, _ = recordio_native.native_read_at(path, offsets[i])
        assert got == payload
        assert reader.read() == payload
    reader.close()


def test_native_reads_chunked_records(tmp_path):
    """Continuation chunks (cflag begin/middle/end) reassemble exactly
    like the python reader."""
    path = tmp_path / "chunked.rec"
    parts = [b"A" * 10, b"B" * 7, b"C" * 5]
    with open(path, "wb") as f:
        for cflag, data in zip((1, 2, 3), parts):     # begin/middle/end
            f.write(struct.pack("<II", _kMagic,
                                _encode_lrec(cflag, len(data))))
            f.write(data)
            f.write(b"\x00" * ((4 - len(data) % 4) % 4))
        f.write(struct.pack("<II", _kMagic, _encode_lrec(0, 3)))
        f.write(b"end\x00")

    offsets = recordio_native.native_index(path)
    assert len(offsets) == 2                  # one chunked + one whole
    assert recordio_native.native_read_at(path, offsets[0])[0] == \
        b"".join(parts)
    assert recordio_native.native_read_at(path, offsets[1])[0] == b"end"
    reader = mx.recordio.MXRecordIO(str(path), "r")
    assert reader.read() == b"".join(parts)
    assert reader.read() == b"end"
    reader.close()


def test_native_rejects_corrupt_files(tmp_path):
    path = tmp_path / "bad.rec"
    path.write_bytes(b"\x00" * 16)            # wrong magic
    with pytest.raises(IOError, match="magic"):
        recordio_native.native_index(path)
    trunc = tmp_path / "trunc.rec"
    trunc.write_bytes(struct.pack("<II", _kMagic, _encode_lrec(0, 100)))
    with pytest.raises(IOError, match="runcated"):
        recordio_native.native_read_at(trunc, 0)
    # the index scan must also refuse a header whose payload is missing
    # (fseek past EOF succeeds, so this needs the size bounds check)
    with pytest.raises(IOError, match="runcated"):
        recordio_native.native_index(trunc)


def test_rec2idx_uses_native_path(tmp_path):
    import os
    import subprocess
    import sys

    payloads = [b"x" * (i + 1) for i in range(9)]
    path = tmp_path / "d.rec"
    _write_rec(path, payloads)
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    r = subprocess.run([sys.executable, "tools/rec2idx.py", str(path)],
                       capture_output=True, text=True, cwd=root)
    assert r.returncode == 0, r.stderr
    reader = mx.recordio.MXIndexedRecordIO(
        str(tmp_path / "d.idx"), str(path), "r")
    for i in (8, 0, 4):
        assert reader.read_idx(i) == payloads[i]
    reader.close()


def test_native_reads_large_records(tmp_path):
    """Records bigger than the first-try 1MB buffer take the exact-size
    retry path."""
    rng = np.random.RandomState(7)
    big = bytes(rng.randint(0, 256, 3 * 1024 * 1024, dtype=np.uint8))
    path = tmp_path / "big.rec"
    _write_rec(path, [b"small", big, b"tail"])
    offsets = recordio_native.native_index(path)
    assert recordio_native.native_read_at(path, offsets[1])[0] == big
    assert recordio_native.native_read_at(path, offsets[2])[0] == b"tail"


def test_indexed_reader_native_path_matches_python(tmp_path):
    """MXIndexedRecordIO.read_idx returns identical bytes through the
    native fast path and the forced-python path."""
    rng = np.random.RandomState(9)
    payloads = [bytes(rng.randint(0, 256, rng.randint(1, 2000),
                                  dtype=np.uint8)) for _ in range(12)]
    rec_path, idx_path = str(tmp_path / "r.rec"), str(tmp_path / "r.idx")
    w = mx.recordio.MXIndexedRecordIO(idx_path, rec_path, "w")
    for i, p in enumerate(payloads):
        w.write_idx(i, p)
    w.close()

    r = mx.recordio.MXIndexedRecordIO(idx_path, rec_path, "r")
    native = [r.read_idx(i) for i in (5, 0, 11, 3)]
    r.close()
    old = mx.recordio.MXIndexedRecordIO._native_ok
    mx.recordio.MXIndexedRecordIO._native_ok = False     # force python
    try:
        r = mx.recordio.MXIndexedRecordIO(idx_path, rec_path, "r")
        python = [r.read_idx(i) for i in (5, 0, 11, 3)]
        r.close()
    finally:
        mx.recordio.MXIndexedRecordIO._native_ok = old
    assert native == python == [payloads[i] for i in (5, 0, 11, 3)]


def test_indexed_reader_position_parity_and_closed_handle(tmp_path):
    """read_idx leaves the sequential position just past the record on
    BOTH backends, and closed readers fail on both."""
    payloads = [b"one1", b"two22222", b"three"]
    rec_path, idx_path = str(tmp_path / "p.rec"), str(tmp_path / "p.idx")
    w = mx.recordio.MXIndexedRecordIO(idx_path, rec_path, "w")
    for i, p in enumerate(payloads):
        w.write_idx(i, p)
    w.close()

    for force_python in (False, True):
        old = mx.recordio.MXIndexedRecordIO._native_ok
        if force_python:
            mx.recordio.MXIndexedRecordIO._native_ok = False
        try:
            r = mx.recordio.MXIndexedRecordIO(idx_path, rec_path, "r")
            assert r.read_idx(0) == payloads[0]
            # sequential read continues AFTER record 0 on either path
            assert r.read() == payloads[1]
            # closed handles auto-reopen on the next read (the python
            # path's _check_pid reset; the native path matches)
            r.close()
            assert r.read_idx(1) == payloads[1]
            r.close()
        finally:
            mx.recordio.MXIndexedRecordIO._native_ok = old
