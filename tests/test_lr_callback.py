"""LR schedulers and training callbacks (reference:
python/mxnet/lr_scheduler.py, callback.py + their unittests in
tests/python/unittest/test_lr_scheduler.py)."""
import logging

import numpy as np
import pytest

import mxnet_tpu as mx


def _ref_factor(num_updates, step, factor, base, stop):
    """Literal replay of the reference's stateful loop."""
    lr, count, out = base, 0, []
    for n in num_updates:
        while n > count + step:
            count += step
            lr = max(lr * factor, stop)
        out.append(lr)
    return out


def test_factor_scheduler_matches_reference_loop():
    sched = mx.lr_scheduler.FactorScheduler(step=10, factor=0.5,
                                            base_lr=1.0,
                                            stop_factor_lr=0.02)
    updates = [1, 5, 10, 11, 20, 21, 35, 80, 200]
    got = [sched(u) for u in updates]
    want = _ref_factor(updates, 10, 0.5, 1.0, 0.02)
    np.testing.assert_allclose(got, want)
    assert got[-1] == 0.02            # floored at stop_factor_lr


def test_multi_factor_scheduler_boundaries():
    sched = mx.lr_scheduler.MultiFactorScheduler(step=[5, 9], factor=0.1,
                                                 base_lr=1.0)
    # decay fires strictly AFTER each boundary
    assert sched(5) == 1.0
    assert abs(sched(6) - 0.1) < 1e-12
    assert abs(sched(9) - 0.1) < 1e-12
    assert abs(sched(10) - 0.01) < 1e-12
    assert abs(sched(100) - 0.01) < 1e-12


def test_poly_and_cosine_schedulers():
    poly = mx.lr_scheduler.PolyScheduler(max_update=100, base_lr=1.0,
                                         pwr=2, final_lr=0.1)
    assert abs(poly(0) - 1.0) < 1e-12
    assert abs(poly(50) - (0.1 + 0.9 * 0.25)) < 1e-12
    assert abs(poly(100) - 0.1) < 1e-12
    assert abs(poly(500) - 0.1) < 1e-12   # holds final value

    cos = mx.lr_scheduler.CosineScheduler(max_update=100, base_lr=1.0,
                                          final_lr=0.0)
    assert abs(cos(0) - 1.0) < 1e-12
    assert abs(cos(50) - 0.5) < 1e-12
    assert abs(cos(100) - 0.0) < 1e-12
    assert abs(cos(400) - 0.0) < 1e-12


def test_scheduler_warmup():
    sched = mx.lr_scheduler.FactorScheduler(step=100, factor=0.9,
                                            base_lr=1.0, warmup_steps=10,
                                            warmup_begin_lr=0.0)
    assert sched(0) == 0.0
    assert abs(sched(5) - 0.5) < 1e-12
    assert sched(10) == 1.0
    with pytest.raises(ValueError):
        mx.lr_scheduler.FactorScheduler(step=5, warmup_mode="bogus")


def test_scheduler_drives_optimizer():
    """lr_scheduler plugs into the optimizer the reference way."""
    sched = mx.lr_scheduler.MultiFactorScheduler(step=[2], factor=0.1)
    opt = mx.optimizer.SGD(learning_rate=1.0, lr_scheduler=sched)
    w = mx.nd.array([0.0])
    g = mx.nd.array([1.0])
    st = opt.create_state(0, w)
    deltas = []
    for _ in range(4):
        before = float(w.asnumpy()[0])
        opt.update(0, w, g, st)
        deltas.append(before - float(w.asnumpy()[0]))
    # steps 1,2 at lr=1.0; steps 3,4 at lr=0.1
    np.testing.assert_allclose(deltas, [1.0, 1.0, 0.1, 0.1], rtol=1e-6)


class _Param:
    def __init__(self, epoch, nbatch, metric):
        self.epoch = epoch
        self.nbatch = nbatch
        self.eval_metric = metric


def test_speedometer_reports_on_frequency(caplog):
    meter = mx.callback.Speedometer(batch_size=4, frequent=2,
                                    auto_reset=True)
    metric = mx.metric.Accuracy()
    metric.update([mx.nd.array([0.0])],
                  [mx.nd.array([[0.9, 0.1]]).argmax(axis=1) * 0])
    with caplog.at_level(logging.INFO):
        for nbatch in range(1, 7):
            meter(_Param(0, nbatch, metric))
    msgs = [r.message for r in caplog.records if "samples/sec" in r.message]
    # batch 1 opens the window; reports fire at batches 2, 4, 6
    assert len(msgs) == 3
    assert all("Epoch[0]" in m and "accuracy" in m for m in msgs)


def test_speedometer_resets_across_epochs(caplog):
    meter = mx.callback.Speedometer(batch_size=4, frequent=2,
                                    auto_reset=False)
    with caplog.at_level(logging.INFO):
        for nbatch in range(1, 5):
            meter(_Param(0, nbatch, None))
        for nbatch in range(1, 5):   # new epoch: counter restarts
            meter(_Param(1, nbatch, None))
    msgs = [r.message for r in caplog.records if "samples/sec" in r.message]
    assert len(msgs) == 4
    assert all("Iter[0]" in m for m in msgs[:2])
    assert all("Iter[1]" in m for m in msgs[2:])
