"""gluon.contrib, mx.operator (CustomOp), mx.rtc (Pallas) — the
advertised-surface completion batch (reference tests:
test_gluon_contrib.py, test_operator.py custom-op section, test_rtc.py).
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon


# ---------------------------------------------------------------------------
# gluon.contrib.nn
# ---------------------------------------------------------------------------

def test_concurrent_and_identity():
    from mxnet_tpu.gluon.contrib.nn import (HybridConcurrent, Concurrent,
                                            Identity)

    for cls in (Concurrent, HybridConcurrent):
        net = cls(axis=-1)
        net.add(gluon.nn.Dense(4, in_units=3))
        net.add(Identity())
        net.add(gluon.nn.Dense(2, in_units=3))
        net.initialize()
        x = mx.nd.ones((5, 3))
        out = net(x)
        assert out.shape == (5, 4 + 3 + 2)


def test_sparse_embedding_and_sync_bn():
    from mxnet_tpu.gluon.contrib.nn import SparseEmbedding, SyncBatchNorm

    emb = SparseEmbedding(20, 6)
    emb.initialize()
    idx = mx.nd.array(np.array([1, 3, 1], np.float32))
    out = emb(idx)
    assert out.shape == (3, 6)
    assert emb.weight.grad_stype == "row_sparse"

    bn = SyncBatchNorm(in_channels=4, num_devices=8)
    bn.initialize()
    y = bn(mx.nd.ones((2, 4, 3, 3)))
    assert y.shape == (2, 4, 3, 3)


# ---------------------------------------------------------------------------
# gluon.contrib.rnn
# ---------------------------------------------------------------------------

def test_variational_dropout_cell():
    from mxnet_tpu.gluon.contrib.rnn import VariationalDropoutCell

    cell = VariationalDropoutCell(gluon.rnn.LSTMCell(8, input_size=6),
                                  drop_inputs=0.3, drop_states=0.3,
                                  drop_outputs=0.3)
    cell.initialize()
    x = mx.nd.ones((2, 5, 6))          # NTC
    with autograd.record():            # dropout active in train mode
        outputs, states = cell.unroll(5, x, merge_outputs=True)
    assert outputs.shape == (2, 5, 8)
    assert len(states) == 2
    # inference: masks are no-ops
    outputs2, _ = cell.unroll(5, x, merge_outputs=True)
    assert np.isfinite(outputs2.asnumpy()).all()


def test_lstmp_cell():
    from mxnet_tpu.gluon.contrib.rnn import LSTMPCell

    cell = LSTMPCell(hidden_size=12, projection_size=5, input_size=4)
    cell.initialize()
    x = mx.nd.ones((3, 4))
    states = cell.begin_state(batch_size=3)
    out, new_states = cell(x, states)
    assert out.shape == (3, 5)                 # projected
    assert new_states[0].shape == (3, 5)       # r
    assert new_states[1].shape == (3, 12)      # c
    # unrolls like any cell
    seq = mx.nd.ones((3, 6, 4))
    outputs, _ = cell.unroll(6, seq, merge_outputs=True)
    assert outputs.shape == (3, 6, 5)


@pytest.mark.parametrize("dims", [1, 2])
def test_conv_rnn_cells(dims):
    from mxnet_tpu.gluon.contrib import rnn as crnn

    spatial = (10,) if dims == 1 else (8, 8)
    in_shape = (3,) + spatial
    for name in ("RNN", "LSTM", "GRU"):
        cls = getattr(crnn, "Conv%dD%sCell" % (dims, name))
        cell = cls(in_shape, hidden_channels=5, i2h_kernel=3,
                   h2h_kernel=3, i2h_pad=1)
        cell.initialize()
        x = mx.nd.ones((2,) + in_shape)
        states = cell.begin_state(batch_size=2)
        out, new_states = cell(x, states)
        assert out.shape == (2, 5) + spatial, (name, out.shape)
        for s in new_states:
            assert s.shape == (2, 5) + spatial


def test_conv_lstm_unroll_trains():
    from mxnet_tpu.gluon.contrib.rnn import Conv1DLSTMCell

    cell = Conv1DLSTMCell((2, 6), hidden_channels=3, i2h_kernel=3,
                          h2h_kernel=3, i2h_pad=1)
    cell.initialize()
    seq = mx.nd.array(np.random.RandomState(0).rand(2, 4, 2, 6)
                      .astype(np.float32))
    with autograd.record():
        outputs, _ = cell.unroll(4, seq, merge_outputs=True)
        loss = (outputs * outputs).mean()
    loss.backward()
    g = cell.i2h_weight.grad()
    assert np.isfinite(g.asnumpy()).all() and float(
        g.abs().sum().asnumpy()) > 0


def test_interval_sampler():
    from mxnet_tpu.gluon.contrib.data import IntervalSampler

    s = list(IntervalSampler(10, 3))
    assert s == [0, 3, 6, 9, 1, 4, 7, 2, 5, 8]
    s2 = list(IntervalSampler(10, 3, rollover=False))
    assert s2 == [0, 3, 6, 9]


# ---------------------------------------------------------------------------
# mx.operator custom ops
# ---------------------------------------------------------------------------

def _register_sigmoid():
    _ = mx.operator  # trigger the lazy mx.operator module alias

    class MySigmoid(mx.operator.CustomOp):
        def forward(self, is_train, req, in_data, out_data, aux):
            y = 1.0 / (1.0 + (-in_data[0]).exp())
            self.assign(out_data[0], req[0], y)

        def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
            y = out_data[0]
            self.assign(in_grad[0], req[0], out_grad[0] * y * (1 - y))

    @mx.operator.register("my_sigmoid")
    class MySigmoidProp(mx.operator.CustomOpProp):
        def __init__(self):
            super().__init__(need_top_grad=True)

        def list_arguments(self):
            return ["data"]

        def list_outputs(self):
            return ["output"]

        def infer_shape(self, in_shape):
            return in_shape, [in_shape[0]], []

        def create_operator(self, ctx, shapes, dtypes):
            return MySigmoid()

    return MySigmoidProp


def test_custom_op_forward_backward():
    _register_sigmoid()
    x_np = np.random.RandomState(0).randn(3, 4).astype(np.float32)
    x = mx.nd.array(x_np)
    x.attach_grad()
    with autograd.record():
        y = mx.nd.Custom(x, op_type="my_sigmoid")
        loss = y.sum()
    loss.backward()
    expected = 1 / (1 + np.exp(-x_np))
    np.testing.assert_allclose(y.asnumpy(), expected, rtol=1e-5)
    np.testing.assert_allclose(x.grad.asnumpy(),
                               expected * (1 - expected), rtol=1e-5)


def test_custom_op_symbolic():
    _register_sigmoid()
    data = mx.sym.var("data")
    out = mx.sym.Custom(data, op_type="my_sigmoid", name="cust")
    x_np = np.array([[0.0, 1.0]], np.float32)
    ex = out.bind(mx.cpu(), {"data": mx.nd.array(x_np)},
                  args_grad={"data": mx.nd.zeros((1, 2))})
    res = ex.forward(is_train=True)
    np.testing.assert_allclose(res[0].asnumpy(), 1 / (1 + np.exp(-x_np)),
                               rtol=1e-5)
    ex.backward(out_grads=[mx.nd.ones((1, 2))])
    s = 1 / (1 + np.exp(-x_np))
    np.testing.assert_allclose(ex.grad_dict["data"].asnumpy(),
                               s * (1 - s), rtol=1e-5)


# ---------------------------------------------------------------------------
# mx.rtc Pallas kernels
# ---------------------------------------------------------------------------

def test_pallas_kernel_launch():
    def scale_add(x_ref, y_ref, o_ref):
        o_ref[:] = x_ref[:] * 2.0 + y_ref[:]

    mod = mx.rtc.PallasModule(scale_add=scale_add)
    k = mod.get_kernel("scale_add")
    a = mx.nd.array(np.arange(8, dtype=np.float32).reshape(1, 8))
    b = mx.nd.ones((1, 8))
    out = k.launch([a, b])
    np.testing.assert_allclose(out.asnumpy(),
                               np.arange(8).reshape(1, 8) * 2 + 1)


def test_cuda_module_raises():
    with pytest.raises(NotImplementedError):
        mx.rtc.CudaModule("__global__ void k() {}")


# -- contrib tail: adaptive pool / resize / fft / index_copy / count_sketch --

def test_adaptive_avg_pooling2d_oracle():
    rng = np.random.RandomState(0)
    x = rng.randn(2, 3, 7, 5).astype(np.float32)
    out = mx.nd.contrib.AdaptiveAvgPooling2D(
        mx.nd.array(x), output_size=(3, 2)).asnumpy()
    want = np.zeros((2, 3, 3, 2), np.float32)
    for oh in range(3):
        a, b = int(np.floor(oh * 7 / 3)), int(np.ceil((oh + 1) * 7 / 3))
        for ow in range(2):
            c, d = int(np.floor(ow * 5 / 2)), int(np.ceil((ow + 1) * 5 / 2))
            want[:, :, oh, ow] = x[:, :, a:b, c:d].mean(axis=(2, 3))
    np.testing.assert_allclose(out, want, rtol=1e-5, atol=1e-6)
    # global pooling special case == mean
    g = mx.nd.contrib.AdaptiveAvgPooling2D(mx.nd.array(x),
                                           output_size=1).asnumpy()
    np.testing.assert_allclose(g[:, :, 0, 0], x.mean(axis=(2, 3)),
                               rtol=1e-5, atol=1e-6)


def test_bilinear_resize2d_align_corners():
    rng = np.random.RandomState(1)
    x = rng.randn(1, 2, 4, 4).astype(np.float32)
    out = mx.nd.contrib.BilinearResize2D(mx.nd.array(x), height=7,
                                         width=7).asnumpy()
    # align_corners: corners map exactly
    np.testing.assert_allclose(out[..., 0, 0], x[..., 0, 0], rtol=1e-5)
    np.testing.assert_allclose(out[..., -1, -1], x[..., -1, -1], rtol=1e-5)
    np.testing.assert_allclose(out[..., 0, -1], x[..., 0, -1], rtol=1e-5)
    # midpoints on a 4->7 grid interpolate between neighbours
    want_mid = 0.5 * (x[..., 0, 0] + x[..., 0, 1])
    np.testing.assert_allclose(out[..., 0, 1], want_mid, rtol=1e-4)
    # identity when sizes match
    same = mx.nd.contrib.BilinearResize2D(mx.nd.array(x), height=4,
                                          width=4).asnumpy()
    np.testing.assert_allclose(same, x)
    # scale_* spelling
    up = mx.nd.contrib.BilinearResize2D(mx.nd.array(x), scale_height=2.0,
                                        scale_width=2.0).asnumpy()
    assert up.shape == (1, 2, 8, 8)


def test_contrib_fft_ifft_roundtrip():
    rng = np.random.RandomState(2)
    x = rng.randn(3, 8).astype(np.float32)
    f = mx.nd.contrib.fft(mx.nd.array(x)).asnumpy()
    assert f.shape == (3, 16)
    ref = np.fft.fft(x, axis=-1)
    np.testing.assert_allclose(f[:, 0::2], ref.real, rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(f[:, 1::2], ref.imag, rtol=1e-4, atol=1e-4)
    # reference ifft is unnormalized: divide by d to invert (the
    # reference's own example does the same)
    back = mx.nd.contrib.ifft(mx.nd.array(f)).asnumpy() / 8.0
    np.testing.assert_allclose(back, x, rtol=1e-4, atol=1e-4)


def test_contrib_index_copy():
    old = mx.nd.zeros((5, 3))
    new = mx.nd.array(np.arange(6, dtype=np.float32).reshape(2, 3))
    idx = mx.nd.array(np.array([4, 0], np.float32))
    out = mx.nd.contrib.index_copy(old, idx, new).asnumpy()
    want = np.zeros((5, 3), np.float32)
    want[4] = [0, 1, 2]
    want[0] = [3, 4, 5]
    np.testing.assert_allclose(out, want)


def test_contrib_count_sketch():
    x = np.array([[1., 2., 3., 4.]], np.float32)
    h = np.array([[0, 1, 1, 2]], np.float32)
    s = np.array([[1, -1, 1, 1]], np.float32)
    out = mx.nd.contrib.count_sketch(
        mx.nd.array(x), mx.nd.array(h), mx.nd.array(s),
        out_dim=3).asnumpy()
    # bucket0: +1*1 ; bucket1: -1*2 + 1*3 ; bucket2: +1*4
    np.testing.assert_allclose(out, [[1., 1., 4.]])
