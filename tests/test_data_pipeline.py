"""mxnet_tpu.data (ISSUE 6): sharded streaming reader, parallel decode
pool, async device prefetch, and checkpointable iterator state — incl.
the 2-rank SIGKILL resume acceptance test (data order bit-exact)."""
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import data, recordio
from mxnet_tpu.data import (epoch_order, num_padded, shard_indices,
                            shard_slice)

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _pack(td, name, n, start=0):
    """n records whose payload is the ascii global sample id."""
    rec = os.path.join(str(td), name + ".rec")
    idx = os.path.join(str(td), name + ".idx")
    w = recordio.MXIndexedRecordIO(idx, rec, "w")
    for i in range(n):
        sid = start + i
        w.write_idx(i, recordio.pack(
            recordio.IRHeader(0, float(sid), sid, 0),
            str(sid).encode()))
    w.close()
    return rec


def _decode(record):
    header, payload = recordio.unpack(record)
    sid = int(payload.decode())
    return np.float32(header.label), np.full((2, 2), sid, np.float32)


def _pipe(rec, **kw):
    kw.setdefault("batch_size", 4)
    kw.setdefault("seed", 11)
    kw.setdefault("num_shards", 1)
    kw.setdefault("shard_index", 0)
    kw.setdefault("decode_threads", 2)
    kw.setdefault("prefetch", 2)
    kw.setdefault("place", False)
    return data.DataPipeline(rec, _decode, **kw)


# -- sharding -----------------------------------------------------------------

@pytest.mark.parametrize("n,k", [(10, 3), (7, 2), (8, 4), (5, 5), (3, 4),
                                 (100, 7)])
def test_shards_equal_size_and_cover_everything(n, k):
    shards = [shard_indices(n, k, i, epoch=2, seed=5) for i in range(k)]
    assert {len(s) for s in shards} == {-(-n // k)}
    assert set(np.concatenate(shards).tolist()) == set(range(n))
    # wrap-tail: at most one extra occurrence per sample
    ids, counts = np.unique(np.concatenate(shards), return_counts=True)
    assert counts.max() <= 2
    assert counts.sum() == num_padded(n, k)


def test_epoch_order_deterministic_and_epoch_dependent():
    a = epoch_order(50, epoch=3, seed=9)
    assert (a == epoch_order(50, epoch=3, seed=9)).all()
    assert not (a == epoch_order(50, epoch=4, seed=9)).all()
    assert not (a == epoch_order(50, epoch=3, seed=10)).all()
    assert (epoch_order(6, epoch=7, seed=0, shuffle=False)
            == np.arange(6)).all()


def test_shard_slice_wraps_tail_preserving_type():
    assert shard_slice(list(range(10)), 3, 2) == [8, 9, 0, 1]
    out = shard_slice(np.arange(10) * 10, 3, 0)
    assert isinstance(out, np.ndarray) and out.tolist() == [0, 10, 20, 30]
    assert shard_slice([1, 2], 1, 0) == [1, 2]          # no-op passthrough
    with pytest.raises(ValueError):
        shard_slice([1, 2], 2, 2)


# -- reader -------------------------------------------------------------------

def test_record_dataset_multi_file_global_ids(tmp_path):
    r1 = _pack(tmp_path, "a", 7, start=0)
    r2 = _pack(tmp_path, "b", 5, start=7)
    ds = data.RecordDataset([r1, r2])
    assert len(ds) == 12
    for i in (0, 6, 7, 11):
        _, payload = recordio.unpack(ds.read(i))
        assert int(payload.decode()) == i
    with pytest.raises(IndexError):
        ds.read(12)
    fp = ds.fingerprint()
    assert [(name, count) for name, count, _ in fp] \
        == [("a.rec", 7), ("b.rec", 5)]
    assert all(size > 0 for _, _, size in fp)   # content-sensitive part
    # a short idx list must fail loudly, not silently drop rec files
    with pytest.raises(ValueError, match="one-to-one"):
        data.RecordDataset([r1, r2], idx_paths=[r1[:-4] + ".idx"])


def test_record_dataset_python_scan_matches_idx(tmp_path, monkeypatch):
    rec = _pack(tmp_path, "scan", 9)
    ds_idx = data.RecordDataset([rec])
    monkeypatch.setenv("MXNET_USE_NATIVE_RECORDIO", "0")
    monkeypatch.setattr(data.reader.RecordDataset, "_native_ok", None)
    # no .idx -> pure-python frame scan must find the same records
    ds_scan = data.RecordDataset([rec], idx_paths=[str(tmp_path / "no")])
    assert [ds_scan.read(i) for i in range(9)] \
        == [ds_idx.read(i) for i in range(9)]


def test_record_dataset_threaded_reads(tmp_path):
    rec = _pack(tmp_path, "thr", 40)
    ds = data.RecordDataset([rec])
    got, errs = {}, []

    def read_some(lo):
        try:
            for i in range(lo, 40, 4):
                got[i] = int(recordio.unpack(ds.read(i))[1].decode())
        except Exception as exc:   # pragma: no cover - failure detail
            errs.append(exc)

    threads = [threading.Thread(target=read_some, args=(lo,))
               for lo in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs and got == {i: i for i in range(40)}


def test_sharded_stream_state_roundtrip_and_mismatch(tmp_path):
    rec = _pack(tmp_path, "st", 11)
    ds = data.RecordDataset([rec])
    st = data.ShardedRecordStream(ds, num_shards=2, shard_index=1, seed=4)
    # peeks match what next_raw later delivers, across epoch boundaries
    peeked = [st.peek_id(k) for k in range(13)]
    assert peeked == [st.next_raw()[1] for _ in range(13)]
    st.seek(0, 0)
    for _ in range(7):                       # into epoch 1 (per-shard 6)
        st.next_raw()
    state = st.state_dict()
    st2 = data.ShardedRecordStream(ds, num_shards=2, shard_index=1, seed=4)
    st2.load_state_dict(state)
    assert [st.next_raw()[:2] for _ in range(8)] \
        == [st2.next_raw()[:2] for _ in range(8)]
    other = data.ShardedRecordStream(ds, num_shards=2, shard_index=0,
                                     seed=4)
    with pytest.raises(ValueError, match="shard_index"):
        other.load_state_dict(state)
    grown = _pack(tmp_path, "st2", 13)
    other = data.ShardedRecordStream(data.RecordDataset([grown]),
                                     num_shards=2, shard_index=1, seed=4)
    with pytest.raises(ValueError, match="dataset changed"):
        other.load_state_dict(state)


# -- decode pool --------------------------------------------------------------

def test_record_dataset_rejects_stale_idx(tmp_path):
    """A writer killed mid-pack leaves the .rec longer than its
    buffered .idx — serving the indexed prefix silently would shrink
    the sample space, so the dataset must refuse the sidecar."""
    rec = _pack(tmp_path, "stale", 9)
    idx = rec[:-4] + ".idx"
    with open(idx) as f:
        lines = f.read().splitlines()
    with open(idx, "w") as f:
        f.write("\n".join(lines[:-2]) + "\n")
    with pytest.raises(IOError, match="stale"):
        data.RecordDataset([rec])


def test_stream_rejects_pipeline_kind_state(tmp_path):
    """A DataPipeline cursor (delivered-sample units incl. batch pad)
    must not restore onto a ShardedRecordStream — the kind tag is
    validated, not just the shared geometry keys."""
    rec = _pack(tmp_path, "kind", 12)
    with _pipe(rec, num_shards=2, shard_index=0) as pipe:
        next(pipe)
        state = pipe.state_dict()
    st = data.ShardedRecordStream(data.RecordDataset([rec]),
                                  num_shards=2, shard_index=0, seed=11)
    with pytest.raises(ValueError, match="not interchangeable"):
        st.load_state_dict(state)


def test_decode_pool_ordered_preserves_order_under_skew():
    def slow_evens(x):
        time.sleep(0.02 if x % 2 == 0 else 0.0)
        return x * 3

    with data.DecodePool(slow_evens, num_threads=4, ordered=True) as pool:
        assert list(pool.run(range(12))) == [x * 3 for x in range(12)]


def test_decode_pool_unordered_completes_all():
    with data.DecodePool(lambda x: x, num_threads=4, ordered=False) as p:
        assert sorted(p.run(range(25))) == list(range(25))


@pytest.mark.parametrize("ordered", [True, False])
def test_decode_pool_errors_reach_consumer(ordered):
    def boom(x):
        if x == 5:
            raise ValueError("decode boom")
        return x

    with data.DecodePool(boom, num_threads=2, ordered=ordered) as pool:
        with pytest.raises(ValueError, match="decode boom"):
            list(pool.run(range(10)))
    pool.close()                              # idempotent


# -- prefetcher ---------------------------------------------------------------

def test_prefetcher_order_place_and_stop():
    pf = data.DevicePrefetcher(iter(range(6)), depth=2,
                               place=lambda x: x + 100)
    assert list(pf) == [100 + i for i in range(6)]
    with pytest.raises(StopIteration):        # terminal, not hanging
        next(pf)
    pf.close()
    pf.close()                                # idempotent


def test_prefetcher_producer_error_reraises_in_consumer():
    def gen():
        yield "ok"
        raise RuntimeError("producer died")

    with data.DevicePrefetcher(gen(), depth=2) as pf:
        assert next(pf) == "ok"
        with pytest.raises(RuntimeError, match="producer died"):
            next(pf)
        with pytest.raises(RuntimeError, match="producer died"):
            next(pf)                          # stays broken, never hangs


def test_prefetcher_reads_ahead_bounded():
    pulled = []

    def gen():
        for i in range(50):
            pulled.append(i)
            yield i

    with data.DevicePrefetcher(gen(), depth=2) as pf:
        assert next(pf) == 0
        time.sleep(0.2)                       # let the producer run ahead
        # double buffer: at most depth queued + 1 in flight past the
        # consumer — never the whole source
        assert len(pulled) <= 5


# -- pipeline -----------------------------------------------------------------

def test_pipeline_geometry_batches_and_pad(tmp_path):
    rec = _pack(tmp_path, "geo", 13)
    with _pipe(rec, num_shards=2, shard_index=1) as pipe:
        assert pipe.samples_per_shard == 7
        assert pipe.batches_per_epoch == 2
        assert pipe.samples_per_epoch == 8
        b1, b2 = next(pipe), next(pipe)
        assert b1.data[0].shape == (4, 2, 2)
        assert b1.label[0].shape == (4,)
        assert (b1.pad, b2.pad) == (0, 1)     # tail wraps, pad reported
        # delivered ids == the shard order (incl. one wrap duplicate)
        order = shard_indices(13, 2, 1, epoch=0, seed=11)
        want = order.tolist() + [int(order[0])]
        got = np.concatenate([b1.index, b2.index]).tolist()
        assert got == want
        # batch payloads encode their ids (decode really ran); with
        # place=False batches are raw host numpy — no device round-trip
        assert isinstance(b1.data[0], np.ndarray)
        assert int(b1.data[0][2, 0, 0]) == got[2]
        assert pipe.epoch == 1


def test_pipeline_epoch_reshuffles_and_covers(tmp_path):
    rec = _pack(tmp_path, "cov", 12)
    with _pipe(rec, batch_size=3) as pipe:
        e0 = [next(pipe).index for _ in range(pipe.batches_per_epoch)]
        e1 = [next(pipe).index for _ in range(pipe.batches_per_epoch)]
    e0 = np.concatenate(e0).tolist()
    e1 = np.concatenate(e1).tolist()
    assert sorted(e0) == sorted(e1) == list(range(12))
    assert e0 != e1                           # reshuffled per epoch


def test_pipeline_two_shards_union_covers_dataset(tmp_path):
    rec = _pack(tmp_path, "union", 10)
    seen = []
    for r in (0, 1):
        with _pipe(rec, num_shards=2, shard_index=r) as pipe:
            for _ in range(pipe.batches_per_epoch):
                seen.extend(np.asarray(next(pipe).index).tolist())
    assert set(seen) == set(range(10))


@pytest.mark.parametrize("ordered", [True, False])
def test_pipeline_decode_modes_deliver_everything(tmp_path, ordered):
    rec = _pack(tmp_path, "modes", 16)
    with _pipe(rec, ordered=ordered, decode_threads=3) as pipe:
        ids = [np.asarray(next(pipe).index)
               for _ in range(pipe.batches_per_epoch)]
    assert sorted(np.concatenate(ids).tolist()) == list(range(16))


def test_pipeline_resume_mid_epoch_replays_exact_tail(tmp_path):
    rec = _pack(tmp_path, "res", 23)
    with _pipe(rec, num_shards=2, shard_index=0) as pipe:
        golden = [np.asarray(next(pipe).index).tolist() for _ in range(9)]

    with _pipe(rec, num_shards=2, shard_index=0) as pipe:
        first = [np.asarray(next(pipe).index).tolist() for _ in range(4)]
        state = pipe.state_dict()
    with _pipe(rec, num_shards=2, shard_index=0) as pipe:
        pipe.load_state_dict(state)
        rest = [np.asarray(next(pipe).index).tolist() for _ in range(5)]
    assert first + rest == golden


def test_pipeline_state_roundtrips_through_checkpoint_manager(tmp_path):
    from mxnet_tpu.checkpoint import CheckpointManager, state as ckstate

    rec = _pack(tmp_path, "ckpt", 17)
    with _pipe(rec, batch_size=5) as pipe:
        for _ in range(2):
            next(pipe)
        with CheckpointManager(str(tmp_path / "ck")) as mgr:
            mgr.save(2, {"data": ckstate.state_dict(pipe)}, sync=True)
        want = [np.asarray(next(pipe).index).tolist() for _ in range(4)]
    with _pipe(rec, batch_size=5) as pipe:
        with CheckpointManager(str(tmp_path / "ck")) as mgr:
            step, state = mgr.restore()
        assert step == 2
        ckstate.load_state_dict(pipe, state["data"])
        got = [np.asarray(next(pipe).index).tolist() for _ in range(4)]
    assert got == want


def test_pipeline_load_validates_geometry(tmp_path):
    rec = _pack(tmp_path, "val", 12)
    with _pipe(rec) as pipe:
        next(pipe)
        state = pipe.state_dict()
    with _pipe(rec, batch_size=3) as other:
        with pytest.raises(ValueError, match="batch_size"):
            other.load_state_dict(state)
    with _pipe(rec, seed=99) as other:
        with pytest.raises(ValueError, match="seed"):
            other.load_state_dict(state)
    grown = _pack(tmp_path, "val2", 14)
    with _pipe(grown) as other:
        with pytest.raises(ValueError, match="dataset changed"):
            other.load_state_dict(state)


def test_pipeline_device_placement_default(tmp_path):
    import jax

    rec = _pack(tmp_path, "dev", 8)
    with _pipe(rec, place=True) as pipe:
        batch = next(pipe)
    assert isinstance(batch.data[0], mx.nd.NDArray)
    assert isinstance(batch.data[0]._data, jax.Array)
    assert batch.data[0].shape == (4, 2, 2)


def test_pipeline_decode_error_surfaces(tmp_path):
    rec = _pack(tmp_path, "err", 8)

    def bad(record):
        raise ValueError("bad record")

    with data.DataPipeline(rec, bad, batch_size=2, num_shards=1,
                           shard_index=0, decode_threads=2,
                           prefetch=2, place=False) as pipe:
        with pytest.raises(ValueError, match="bad record"):
            next(pipe)


def test_image_record_decoder_shapes(tmp_path):
    cv2 = pytest.importorskip("cv2")          # noqa: F841
    rng = np.random.RandomState(3)
    rec = os.path.join(str(tmp_path), "img.rec")
    idx = os.path.join(str(tmp_path), "img.idx")
    w = recordio.MXIndexedRecordIO(idx, rec, "w")
    for i in range(6):
        img = (rng.rand(40, 36, 3) * 255).astype(np.uint8)
        w.write_idx(i, recordio.pack_img(
            recordio.IRHeader(0, float(i), i, 0), img, img_fmt=".png"))
    w.close()
    dec = data.ImageRecordDecoder((3, 32, 32), mean=np.zeros(3))
    with data.DataPipeline(rec, dec, batch_size=3, num_shards=1,
                           shard_index=0, decode_threads=2,
                           place=False) as pipe:
        batch = next(pipe)
    assert batch.data[0].shape == (3, 3, 32, 32)
    assert np.asarray(batch.data[0]).dtype == np.float32


def test_stall_fraction_from_spans():
    events = [
        {"ph": "X", "name": "train_step::step", "dur": 100.0},
        {"ph": "X", "name": "train_step::step", "dur": 100.0},
        {"ph": "X", "name": "data::wait", "dur": 60.0},
        {"ph": "X", "name": "train_step::data_put", "dur": 20.0},
        {"ph": "M", "name": "thread_name"},
    ]
    # blocked-on-data (60 wait + 20 put) over loop wall (60 + 200)
    assert data.stall_fraction(events) == pytest.approx(80.0 / 260.0)
    assert data.stall_fraction([]) == 0.0


def test_pipeline_emits_wait_and_decode_metrics(tmp_path):
    from mxnet_tpu.telemetry import metrics as tm

    rec = _pack(tmp_path, "tel", 8)
    wait = tm.REGISTRY.get("mx_data_wait_seconds")
    decode = tm.REGISTRY.get("mx_data_decode_seconds")
    w0, d0 = wait.snapshot()["count"], decode.snapshot()["count"]
    with _pipe(rec) as pipe:
        for _ in range(pipe.batches_per_epoch):
            next(pipe)
    assert wait.snapshot()["count"] > w0
    assert decode.snapshot()["count"] >= d0 + 8


# -- 2-rank SIGKILL resume (the acceptance test) ------------------------------

def _launch_rank(rec, out_dir, ckpt_root, rank, mode, batches,
                 kill_after=2):
    env = dict(os.environ, JAX_PLATFORMS="cpu", MXNET_DEVICE="cpu")
    return subprocess.Popen(
        [sys.executable, os.path.join(_ROOT, "tests", "data_resume_prog.py"),
         "--rec", rec, "--out-dir", out_dir,
         "--ckpt-dir", os.path.join(ckpt_root, "rank%d" % rank),
         "--rank", str(rank), "--num-shards", "2", "--mode", mode,
         "--batches", str(batches), "--kill-after", str(kill_after)],
        env=env, cwd=_ROOT)


def _wait_all(procs, expect, timeout=180):
    for p in procs:
        assert p.wait(timeout=timeout) in expect, \
            "rank exited %s (want %s)" % (p.returncode, expect)


def test_two_rank_kill_resume_stream_bit_identical(tmp_path):
    """Kill a 2-rank run mid-epoch, restore from CheckpointManager, and
    the concatenated per-rank sample-id stream must be bit-identical to
    an uninterrupted run (ISSUE 6 acceptance)."""
    rec = _pack(tmp_path, "pod", 23)          # per-shard 12, 3 batches/epoch
    batches = 6                               # two full epochs per rank
    golden_dir = str(tmp_path / "golden")
    run_dir = str(tmp_path / "resumed")
    ckpt_root = str(tmp_path / "ck")
    os.makedirs(golden_dir)
    os.makedirs(run_dir)

    _wait_all([_launch_rank(rec, golden_dir, ckpt_root + "_g", r, "run",
                            batches) for r in (0, 1)], {0})
    # mid-epoch preemption: SIGKILL after 2 of 3 epoch-0 batches
    _wait_all([_launch_rank(rec, run_dir, ckpt_root, r, "kill", batches)
               for r in (0, 1)], {-9})
    _wait_all([_launch_rank(rec, run_dir, ckpt_root, r, "resume", batches)
               for r in (0, 1)], {0})

    for r in (0, 1):
        with open(os.path.join(golden_dir, "ids.rank%d.txt" % r)) as f:
            golden = f.read()
        with open(os.path.join(run_dir, "ids.rank%d.txt" % r)) as f:
            resumed = f.read()
        assert golden.count("\n") == batches
        assert resumed == golden, \
            "rank %d stream diverged after resume" % r


# -- rec_shard manifest consumption (ISSUE 11 satellite) ----------------------

def _split_manifest(tmp_path, n_records=10, n_shards=3):
    sys.path.insert(0, os.path.join(_ROOT, "tools"))
    import rec_shard

    rec = _pack(tmp_path, "manifested", n_records)
    out_prefix = os.path.join(str(tmp_path), "shards", "manifested")
    rec_shard.split(rec, n_shards, out_prefix)
    return out_prefix + "-manifest.json"


def test_record_dataset_opens_rec_shard_manifest(tmp_path):
    """RecordDataset accepts a tools/rec_shard.py manifest directly:
    open the manifest, get the whole shard set as one sample space."""
    mpath = _split_manifest(tmp_path)
    ds = data.RecordDataset.from_manifest(mpath)
    assert len(ds) == 10
    payloads = {recordio.unpack(ds.read(i))[1] for i in range(len(ds))}
    assert payloads == {str(i).encode() for i in range(10)}
    # The bare-path spelling works too (a lone .json rec_path).
    assert len(data.RecordDataset(mpath)) == 10
    # A manifest names its own idx files; extra idx_paths are an error.
    with pytest.raises(ValueError):
        data.RecordDataset([mpath], idx_paths=["x.idx"])


def test_record_dataset_manifest_fingerprint_check(tmp_path):
    """A shard set that changed since the split fails loudly — the
    manifest's per-shard record counts are the fingerprint."""
    import json as _json

    mpath = _split_manifest(tmp_path)
    with open(mpath) as f:
        manifest = _json.load(f)
    manifest["shards"][1]["records"] += 1
    with open(mpath, "w") as f:
        _json.dump(manifest, f)
    with pytest.raises(ValueError, match="manifest mismatch"):
        data.RecordDataset.from_manifest(mpath)


def test_record_dataset_rejects_non_manifest_json(tmp_path):
    bogus = os.path.join(str(tmp_path), "not_manifest.json")
    with open(bogus, "w") as f:
        f.write("{}")
    with pytest.raises(ValueError, match="shards"):
        data.RecordDataset(bogus)
