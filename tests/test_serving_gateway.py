"""mxnet_tpu.serving.gateway — multi-model inference gateway (ISSUE 15
tentpole): registry + fair-share scheduling + deadline classes +
SLO-coupled shedding + per-model readiness + quantized/mesh-sharded
backends + zero-drop hot reload. Every gateway is shut down in a
finally/with; model names are minted per test so the process-global
registry families never blend across tests."""
import gc
import itertools
import json
import os
import sys
import threading
import time
import weakref
from concurrent.futures import ThreadPoolExecutor

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import serving
from mxnet_tpu.serving import (DeadlineExceededError, GatewayResult,
                               ModelGateway, ModelSpec, QueueFullError,
                               ServiceUnavailableError, hot_swap)
from mxnet_tpu.serving import gateway as gwmod

_names = itertools.count()


def _name(base="m"):
    return "%s%d" % (base, next(_names))


_W = None


def _weight():
    global _W
    if _W is None:
        _W = mx.nd.array(np.arange(12, dtype=np.float32).reshape(4, 3))
    return _W


def _dot(w, x):
    return mx.nd.dot(x, w)


def _spec(name, w=None, **kw):
    kw.setdefault("item_shape", (4,))
    kw.setdefault("max_batch", 8)
    return ModelSpec(name, fn=_dot,
                     params=[w if w is not None else _weight()], **kw)


# -- spec / registry validation ---------------------------------------------

def test_spec_validation():
    with pytest.raises(ValueError):
        ModelSpec("x", item_shape=(4,))                    # no source
    with pytest.raises(ValueError):
        ModelSpec("x", fn=_dot, checkpoint="p", item_shape=(4,))
    with pytest.raises(ValueError):
        ModelSpec("x", fn=_dot, item_shape=(4,), quantize="fp8")
    with pytest.raises(ValueError):
        ModelSpec("x", checkpoint="p", item_shape=(4,), quantize="int8")
    with pytest.raises(ValueError):
        ModelSpec("x", checkpoint="p", item_shape=(4,),
                  mesh_axes={"tp": 2})
    with pytest.raises(ValueError):
        ModelSpec("x", fn=_dot, item_shape=(4,), weight=0)
    with pytest.raises(ValueError):
        ModelSpec("x", fn=_dot, item_shape=(4,), deadline_classes=())
    with pytest.raises(ValueError):
        ModelSpec("x", fn=_dot, item_shape=(4,),
                  deadline_classes=(("a", 1), ("a", 2)))


def test_registry_dup_and_unknown():
    gw = ModelGateway(start=False)
    try:
        a = _name()
        gw.register(_spec(a), warmup=False)
        with pytest.raises(ValueError):
            gw.register(_spec(a), warmup=False)
        with pytest.raises(KeyError):
            gw.predict(_name("ghost"), np.ones((1, 4), np.float32))
        desc = gw.registry.describe()
        assert desc[a]["generation"] == 1
        assert desc[a]["buckets"] == [1, 2, 4, 8]
    finally:
        gw.shutdown()


# -- two models, one pool ----------------------------------------------------

def test_two_models_serve_independently():
    gw = ModelGateway()
    try:
        a, b = _name("a"), _name("b")
        gw.register(_spec(a))
        gw.register(_spec(b, w=_weight() * 2))
        x = np.random.rand(2, 4).astype(np.float32)
        ra = gw.predict(a, x)
        rb = gw.predict(b, x)
        assert isinstance(ra, GatewayResult)
        assert ra.model == a and ra.generation == 1
        w = _weight().asnumpy()
        np.testing.assert_allclose(ra.output.asnumpy(), x @ w, rtol=1e-5)
        np.testing.assert_allclose(rb.output.asnumpy(), x @ (2 * w),
                                   rtol=1e-5)
        st = gw.stats()
        assert st[a]["buckets"][2]["batches"] == 1
        assert st[b]["generation"] == 1 and st[b]["ready"]
    finally:
        gw.shutdown()


def test_concurrent_submits_coalesce_per_model():
    """17 batch-1 submits per model coalesce into <= ceil(17/8) device
    calls EACH, and no batch ever mixes models (every result decodes
    with its own model's weights)."""
    gw = ModelGateway()
    try:
        a, b = _name("a"), _name("b")
        gw.register(_spec(a))
        gw.register(_spec(b, w=_weight() * 3))
        gw.pause()
        xs = [np.random.rand(1, 4).astype(np.float32) for _ in range(17)]
        futs_a = [gw.submit(a, x) for x in xs]
        futs_b = [gw.submit(b, x) for x in xs]
        gw.resume()
        w = _weight().asnumpy()
        for x, f in zip(xs, futs_a):
            np.testing.assert_allclose(f.result(timeout=30).output.asnumpy(),
                                       x @ w, rtol=1e-5)
        for x, f in zip(xs, futs_b):
            np.testing.assert_allclose(f.result(timeout=30).output.asnumpy(),
                                       x @ (3 * w), rtol=1e-5)
        st = gw.stats()
        for m in (a, b):
            calls = sum(v["batches"] for v in st[m]["buckets"].values())
            assert calls <= -(-17 // 8), \
                "%s: 17 singles took %d device calls" % (m, calls)
    finally:
        gw.shutdown()


class _Recorder:
    """Wraps a backend to record dispatch order (the worker snapshots
    st.backend per batch, so wrapping between pause/resume is safe)."""

    def __init__(self, inner, name, log):
        self._inner = inner
        self._name = name
        self._log = log

    def __call__(self, batch):
        self._log.append(self._name)
        return self._inner(batch)

    @property
    def compile_count(self):
        return self._inner.compile_count


def test_fair_share_weighted_round_robin():
    """Weights 3:1 — with both queues busy the smooth-WRR pick sequence
    serves a and b 3:1 deterministically; a hot model cannot starve the
    other."""
    gw = ModelGateway(max_queue=64)
    try:
        a, b = _name("a"), _name("b")
        gw.register(_spec(a, weight=3.0))
        gw.register(_spec(b, weight=1.0))
        log = []
        gw._state(a).backend = _Recorder(gw._state(a).backend, "a", log)
        gw._state(b).backend = _Recorder(gw._state(b).backend, "b", log)
        gw.pause()
        x = np.ones((8, 4), np.float32)   # full bucket -> one dispatch each
        futs = [gw.submit(a, x) for _ in range(12)] \
            + [gw.submit(b, x) for _ in range(4)]
        gw.resume()
        for f in futs:
            f.result(timeout=30)
        assert log.count("a") == 12 and log.count("b") == 4
        # b is served at its 1-in-4 share from the start, not last:
        assert "b" in log[:4], log
    finally:
        gw.shutdown()


def test_global_admission_pool_bound():
    gw = ModelGateway(max_queue=4)
    try:
        a, b = _name("a"), _name("b")
        gw.register(_spec(a))
        gw.register(_spec(b))
        gw.pause()
        futs = [gw.submit(a, np.ones((1, 4), np.float32)) for _ in range(2)]
        futs += [gw.submit(b, np.ones((1, 4), np.float32))
                 for _ in range(2)]
        # The POOL is full: either model's next request sheds.
        with pytest.raises(QueueFullError):
            gw.submit(b, np.ones((1, 4), np.float32))
        gw.resume()
        for f in futs:
            assert f.result(timeout=30).output.shape == (1, 3)
        assert gw.stats()[b]["shed"].get("queue_full:default") == 1
    finally:
        gw.shutdown()


# -- deadline classes --------------------------------------------------------

def test_deadline_classes():
    gw = ModelGateway()
    try:
        a = _name()
        gw.register(_spec(a, deadline_classes=(("interactive", 30),
                                               ("batch", None))))
        with pytest.raises(ValueError):
            gw.submit(a, np.ones((1, 4), np.float32),
                      deadline_class="nope")
        gw.pause()
        doomed = gw.submit(a, np.ones((1, 4), np.float32),
                           deadline_class="interactive")
        survivor = gw.submit(a, np.ones((1, 4), np.float32),
                             deadline_class="batch")
        time.sleep(0.08)
        gw.resume()
        with pytest.raises(DeadlineExceededError):
            doomed.result(timeout=30)
        assert survivor.result(timeout=30).output.shape == (1, 3)
        assert gw.stats()[a]["shed"].get("deadline:interactive") == 1
        # explicit timeout_ms overrides the class deadline
        assert gw.predict(a, np.ones((1, 4), np.float32),
                          deadline_class="interactive",
                          timeout_ms=5000).output.shape == (1, 3)
    finally:
        gw.shutdown()


# -- SLO-coupled shedding ----------------------------------------------------

def test_slo_burn_sheds_lowest_class_only():
    """While a model's burn rate exceeds budget, admission sheds ITS
    lowest deadline class; higher classes and other models admit
    normally — and shedding clears when the burn subsides."""
    clk = {"t": 0.0}
    gw = ModelGateway(burn_windows=(1.0, 5.0), eval_interval_s=0.01,
                      shed_burn_rate=2.0, clock=lambda: clk["t"])
    try:
        hot, steady = _name("hot"), _name("steady")
        gw.register(_spec(hot, slo=(0.9, 0.001),
                          deadline_classes=(("interactive", None),
                                            ("best_effort", None))))
        gw.register(_spec(steady))
        lat = gwmod._gw_latency.labels(model=hot)
        gw._burn_tick()                      # baseline sample at t=0
        for _ in range(20):
            lat.observe(0.5)                 # every event blows the SLO
        clk["t"] = 0.5
        gw._burn_tick()
        assert gw.stats()[hot]["shedding"]
        with pytest.raises(ServiceUnavailableError):
            gw.submit(hot, np.ones((1, 4), np.float32),
                      deadline_class="best_effort")
        # higher class still admits; the other model is untouched
        assert gw.predict(hot, np.ones((1, 4), np.float32),
                          deadline_class="interactive").output.shape \
            == (1, 3)
        assert gw.predict(steady,
                          np.ones((1, 4), np.float32)).output.shape \
            == (1, 3)
        assert gw.stats()[hot]["shed"].get("slo_burn:best_effort", 0) >= 1
        # recovery: good traffic + time -> shedding clears
        for _ in range(200):
            lat.observe(0.0)
        clk["t"] = 2.5
        gw._burn_tick()
        assert not gw.stats()[hot]["shedding"]
        assert gw.predict(hot, np.ones((1, 4), np.float32),
                          deadline_class="best_effort").output.shape \
            == (1, 3)
        # unregister drops the SLO's emitted burn-rate series too
        from mxnet_tpu.telemetry import metrics as tm

        gw.unregister(hot)
        fam = tm.REGISTRY.get("mx_slo_burn_rate")
        assert not [v for v, _ in fam.collect()
                    if v[0] == "gateway_%s" % hot]
    finally:
        gw.shutdown()


# -- per-model readiness (ISSUE 15 satellite) --------------------------------

def test_readiness_is_per_model():
    """A model mid-warmup sheds 503 for ITSELF only; other models keep
    serving (the server-global shed_unready fix), and unregister
    releases the model's readiness slot."""
    from mxnet_tpu.telemetry import healthplane as hp

    hp.reset()
    try:
        gw = ModelGateway()
        try:
            a, cold = _name("a"), _name("cold")
            gw.register(_spec(a))
            gw.register(_spec(cold), warmup=False)
            comp = "gateway/%s" % cold
            assert hp.readiness()[comp] is False
            with pytest.raises(ServiceUnavailableError):
                gw.submit(cold, np.ones((1, 4), np.float32))
            # model a serves fine DESPITE the pod-level /readyz being
            # false — readiness is per model at the gateway
            assert not hp.is_ready()
            assert gw.predict(a, np.ones((1, 4), np.float32)) \
                .output.shape == (1, 3)
            assert gw.stats()[cold]["shed"].get("unready:default") == 1
            gw.warmup(cold)
            assert hp.readiness()[comp] is True
            assert gw.predict(cold, np.ones((1, 4), np.float32)) \
                .output.shape == (1, 3)
            gw.unregister(cold)
            assert comp not in hp.readiness()   # slot RELEASED
            assert hp.is_ready()
        finally:
            gw.shutdown()
        # shutdown releases the remaining model slots too
        assert not [c for c in hp.readiness() if c.startswith("gateway/")]
    finally:
        hp.reset()


def test_unregister_fails_queued_and_drops_series():
    gw = ModelGateway()
    try:
        a = _name()
        gw.register(_spec(a))
        gw.pause()
        fut = gw.submit(a, np.ones((1, 4), np.float32))
        gw.unregister(a)
        gw.resume()
        with pytest.raises(ServiceUnavailableError):
            fut.result(timeout=5)
        assert a not in gw.models()
        assert a not in gw.stats()
        # labeled series left the registry families
        assert not [v for v, _ in gwmod._gw_requests.collect()
                    if v[0] == a]
        # re-registering the same name works (SLO slot freed too)
        gw.register(_spec(a, slo=(0.99, 0.25)))
        assert gw.predict(a, np.ones((1, 4), np.float32)) \
            .output.shape == (1, 3)
    finally:
        gw.shutdown()


# -- drain-aware unregister (ISSUE 20 satellite) -----------------------------

def _counter_total(fam):
    return sum(c.value for _, c in fam.collect())


def test_unregister_drains_queued_work_first():
    """Queued-and-accepted requests are SERVED before the model leaves;
    the served count lands on mx_gateway_unregister_drained_total."""
    def slow_dot(w, x):
        time.sleep(0.05)
        return _dot(w, x)

    gw = ModelGateway()
    try:
        a = _name("drain")
        gw.register(ModelSpec(a, fn=slow_dot, params=[_weight()],
                              item_shape=(4,), max_batch=1))
        drained0 = _counter_total(gwmod._gw_unreg_drained)
        shed0 = _counter_total(gwmod._gw_unreg_shed)
        gw.pause()
        futs = [gw.submit(a, np.ones((1, 4), np.float32))
                for _ in range(4)]
        gw.resume()
        gw.unregister(a)                 # default timeout: plenty
        for fut in futs:
            assert fut.result(timeout=10).output.shape == (1, 3)
        assert _counter_total(gwmod._gw_unreg_drained) - drained0 >= 2
        assert _counter_total(gwmod._gw_unreg_shed) == shed0
        assert a not in gw.models()
    finally:
        gw.shutdown()


def test_unregister_drain_timeout_sheds_remainder():
    """A drain bounded by MXNET_GATEWAY_DRAIN_TIMEOUT_S (here the
    explicit override) strands what it cannot serve in time: those fail
    ServiceUnavailable and count on mx_gateway_unregister_shed_total —
    the gateway-badput feed."""
    class _SleepyBackend:
        # Plain-Python backend: unlike an fn (traced once into a
        # CachedOp at warmup, then microseconds per batch), this sleeps
        # on EVERY call — so the worker is held mid-batch long past the
        # drain deadline and the rest of the queue is stranded.
        compile_count = 0

        def __call__(self, batch):
            time.sleep(1.0)
            return mx.nd.array(np.ones((batch.shape[0], 3), np.float32))

    gw = ModelGateway()
    try:
        a = _name("slowdrain")
        gw.register(ModelSpec(a, fn=_dot, params=[_weight()],
                              item_shape=(4,), max_batch=1))
        gw.swap_backend(a, _SleepyBackend())
        shed0 = _counter_total(gwmod._gw_unreg_shed)
        gw.pause()
        futs = [gw.submit(a, np.ones((1, 4), np.float32))
                for _ in range(4)]
        gw.resume()
        gw.unregister(a, drain_timeout=0.3)
        outcomes = {"served": 0, "shed": 0}
        for fut in futs:
            try:
                fut.result(timeout=10)
                outcomes["served"] += 1
            except ServiceUnavailableError:
                outcomes["shed"] += 1
        assert outcomes["shed"] >= 1, outcomes    # timeout stranded some
        assert _counter_total(gwmod._gw_unreg_shed) - shed0 == \
            outcomes["shed"]
        assert a not in gw.models()
    finally:
        gw.shutdown()


def test_draining_model_rejects_new_admissions():
    gw = ModelGateway()
    try:
        a = _name("gate")
        gw.register(_spec(a))
        gw.pause()
        gw._models[a].draining = True    # what unregister arms first
        with pytest.raises(ServiceUnavailableError, match="draining"):
            gw.submit(a, np.ones((1, 4), np.float32))
    finally:
        gw.shutdown()


# -- quantized bucket ladders ------------------------------------------------

def test_quantized_int8_backend():
    rng = np.random.RandomState(0)
    w = mx.nd.array(rng.randn(16, 8).astype(np.float32))
    gw = ModelGateway()
    try:
        q = _name("q8")
        gw.register(ModelSpec(q, fn=_dot, params=[w], item_shape=(16,),
                              max_batch=4, quantize="int8"))
        st = gw._state(q)
        # the executable's weights ARE int8 (weight-only quantization)
        assert str(st.backend._params[0].dtype) == "int8"
        x = rng.rand(3, 16).astype(np.float32)
        ref = x @ w.asnumpy()
        out = gw.predict(q, x).output.asnumpy()
        assert out.dtype == np.float32
        assert np.max(np.abs(out - ref)) <= 0.05 * np.max(np.abs(ref))
        # warmed ladder: later traffic compiles nothing
        n = st.backend.compile_count
        gw.predict(q, x)
        assert st.backend.compile_count == n == len(st.spec.policy.buckets)
    finally:
        gw.shutdown()


def test_quantized_bf16_backend():
    rng = np.random.RandomState(1)
    w = mx.nd.array(rng.randn(16, 8).astype(np.float32))
    gw = ModelGateway()
    try:
        b = _name("b16")
        gw.register(ModelSpec(b, fn=_dot, params=[w], item_shape=(16,),
                              max_batch=4, quantize="bf16"))
        assert str(gw._state(b).backend._params[0].dtype) == "bfloat16"
        x = rng.rand(3, 16).astype(np.float32)
        ref = x @ w.asnumpy()
        out = gw.predict(b, x).output.asnumpy()
        assert out.dtype == np.float32   # cast back at the boundary
        assert np.max(np.abs(out - ref)) <= 0.05 * np.max(np.abs(ref))
    finally:
        gw.shutdown()


# -- mesh-sharded serving ----------------------------------------------------

def test_mesh_sharded_model_single_process():
    """Bucket executables compiled over a 2-device tp mesh: params are
    REALLY sharded (2 addressable shards on 2 devices), results match
    the unsharded reference."""
    rng = np.random.RandomState(2)
    w = mx.nd.array(rng.randn(16, 8).astype(np.float32))
    gw = ModelGateway()
    try:
        m = _name("mesh")
        gw.register(ModelSpec(m, fn=_dot, params=[w], item_shape=(16,),
                              max_batch=4, mesh_axes={"tp": 2}))
        st = gw._state(m)
        pv = st.backend._param_vals[0]
        shards = pv.addressable_shards
        assert len(shards) == 2
        assert len({s.device for s in shards}) == 2
        assert shards[0].data.shape == (8, 8)     # dim0 split over tp
        x = rng.rand(3, 16).astype(np.float32)
        out = gw.predict(m, x).output.asnumpy()
        np.testing.assert_allclose(out, x @ w.asnumpy(), rtol=1e-5)
        assert st.backend.compile_count == len(st.spec.policy.buckets)
    finally:
        gw.shutdown()


# -- hot reload --------------------------------------------------------------

def test_hot_swap_bumps_generation_and_bit_matches():
    gw = ModelGateway()
    try:
        a = _name()
        gw.register(_spec(a))
        x = np.random.rand(2, 4).astype(np.float32)
        r1 = gw.predict(a, x)
        assert r1.generation == 1
        w2 = _weight() * 5
        gen = hot_swap(gw, a, params=[w2])
        assert gen == 2 == gw.registry.describe()[a]["generation"]
        r2 = gw.predict(a, x)
        assert r2.generation == 2
        # post-swap responses bit-match a FRESH load of the new weights
        fresh = gw.registry.spec(a).build_backend(params=[w2])
        want = fresh(mx.nd.array(np.vstack([x, np.zeros((2, 4),
                                                        np.float32)])))
        np.testing.assert_array_equal(r2.output.asnumpy(),
                                      want.asnumpy()[:2])
    finally:
        gw.shutdown()


def test_hot_swap_under_fire_zero_drops():
    """ISSUE 15 satellite: concurrent requests hammering the gateway
    across a mid-run swap() — zero QueueFullError/dropped futures, no
    cross-version batch (every response tagged exactly one
    generation), and the old backend (its whole executable cache) is
    released after drain."""
    gw = ModelGateway(max_queue=10000, max_delay_ms=1.0)
    try:
        a = _name()
        gw.register(_spec(a))
        old_ref = weakref.ref(gw._state(a).backend)
        stop = threading.Event()
        results, errors = [], []

        def hammer():
            x = np.random.rand(1, 4).astype(np.float32)
            while not stop.is_set():
                try:
                    results.append(gw.predict(a, x))
                except Exception as exc:   # any shed/drop fails the test
                    errors.append(exc)

        threads = [threading.Thread(target=hammer) for _ in range(6)]
        for t in threads:
            t.start()
        time.sleep(0.15)
        gen = hot_swap(gw, a, params=[_weight() * 7])
        time.sleep(0.15)
        stop.set()
        for t in threads:
            t.join(30)
        assert not errors, errors[:3]
        assert len(results) > 0
        gens = {r.generation for r in results}
        assert gens <= {1, 2} and 2 in gens, gens
        assert all(isinstance(r.generation, int) for r in results)
        assert gen == 2
        # old executables released after drain
        gc.collect()
        assert old_ref() is None, "old backend still referenced"
    finally:
        gw.shutdown()


def test_hot_swap_checkpoint_model(tmp_path):
    """Checkpoint-backed model: register epoch 0, hot swap to epoch 1;
    post-swap responses bit-match a fresh load of the new checkpoint."""
    data = mx.sym.var("data")
    net = mx.sym.FullyConnected(data, num_hidden=3, name="gwfc")
    rng = np.random.RandomState(3)
    prefix = str(tmp_path / "gwmlp")
    for epoch in (0, 1):
        args = {"gwfc_weight": mx.nd.array(rng.randn(3, 4)
                                           .astype(np.float32)),
                "gwfc_bias": mx.nd.array(rng.randn(3)
                                         .astype(np.float32))}
        mx.model.save_checkpoint(prefix, epoch, net, args, {})

    gw = ModelGateway()
    try:
        c = _name("ckpt")
        spec = ModelSpec(c, checkpoint=prefix, epoch=0, item_shape=(4,),
                         max_batch=4)
        gw.register(spec)
        x = np.random.rand(2, 4).astype(np.float32)
        r1 = gw.predict(c, x)
        with pytest.raises(ValueError):
            hot_swap(gw, c, params=[_weight()])   # wrong source kind
        gen = hot_swap(gw, c, checkpoint=True, epoch=1)
        assert gen == 2
        r2 = gw.predict(c, x)
        assert not np.array_equal(r1.output.asnumpy(),
                                  r2.output.asnumpy())
        fresh = spec.build_backend(checkpoint=prefix, epoch=1)
        want = fresh(mx.nd.array(x))
        np.testing.assert_array_equal(r2.output.asnumpy(), want.asnumpy())
    finally:
        gw.shutdown()


def test_hot_swap_from_checkpoint_manager(tmp_path):
    """The training-commits-flow-into-serving path: restore() through a
    CheckpointManager, extract serving params, zero-drop swap."""
    from mxnet_tpu import checkpoint

    mgr = checkpoint.CheckpointManager(str(tmp_path / "ckpt"),
                                       keep_last=2)
    try:
        w2 = (_weight() * 9).asnumpy()
        mgr.save(7, {"w": w2}, sync=True)
        gw = ModelGateway()
        try:
            a = _name()
            gw.register(_spec(a))
            with pytest.raises(ValueError):
                hot_swap(gw, a, manager=mgr)      # extract= required
            gen = hot_swap(
                gw, a, manager=mgr,
                extract=lambda state: [mx.nd.array(state["w"])])
            assert gen == 2
            x = np.random.rand(1, 4).astype(np.float32)
            out = gw.predict(a, x).output.asnumpy()
            np.testing.assert_allclose(out, x @ w2, rtol=1e-5)
        finally:
            gw.shutdown()
    finally:
        mgr.close()


# -- lifecycle hygiene -------------------------------------------------------

def test_shutdown_drains_and_rejects_new():
    gw = ModelGateway()
    a = _name()
    gw.register(_spec(a))
    gw.pause()
    futs = [gw.submit(a, np.ones((1, 4), np.float32)) for _ in range(3)]
    gw.shutdown(drain=True)
    for f in futs:
        assert f.result(timeout=1).output.shape == (1, 3)
    with pytest.raises(RuntimeError):
        gw.submit(a, np.ones((1, 4), np.float32))


def test_shutdown_without_drain_fails_pending():
    gw = ModelGateway()
    a = _name()
    gw.register(_spec(a))
    gw.pause()
    fut = gw.submit(a, np.ones((1, 4), np.float32))
    gw.shutdown(drain=False)
    with pytest.raises(RuntimeError):
        fut.result(timeout=1)


def test_register_after_shutdown_leaves_no_ghosts():
    """A refused registration must unwind every side effect: no ghost
    registry entry, no permanently not-ready /readyz component."""
    from mxnet_tpu.telemetry import healthplane as hp

    hp.reset()
    try:
        gw = ModelGateway(start=False)
        gw.shutdown()
        a = _name()
        with pytest.raises(RuntimeError):
            gw.register(_spec(a, slo=(0.99, 0.25)))
        assert a not in gw.registry.names()
        assert not [c for c in hp.readiness()
                    if c.startswith("gateway/")]
        assert hp.is_ready()
    finally:
        hp.reset()


def test_request_validation():
    gw = ModelGateway(start=False)
    try:
        a = _name()
        gw.register(_spec(a))
        with pytest.raises(ValueError):
            gw.submit(a, np.ones((1, 5), np.float32))    # wrong shape
        with pytest.raises(ValueError):
            gw.submit(a, np.ones((9, 4), np.float32))    # > max_batch
    finally:
        gw.shutdown()


def test_worker_thread_daemonized():
    gw = ModelGateway()
    try:
        assert gw._thread.daemon
        assert any(t.name == "mx-serving-gateway"
                   for t in threading.enumerate())
    finally:
        gw.shutdown()


def test_two_process_mesh_gateway_acceptance(tmp_path):
    """ISSUE 15 acceptance: 2 processes x 1 CPU device form one 2-device
    tp mesh; each rank's gateway serves a mesh-sharded model in
    lockstep (each process holds ONE weight shard) while rank 0 also
    hammers an int8-quantized local model across a mid-run hot swap fed
    by a CheckpointManager commit — zero dropped requests, both
    generations observed, post-swap responses bit-match a fresh load of
    the new checkpoint. All assertions live in the prog; this test
    checks the exit codes and the rank-0 report."""
    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "tools"))
    from launch import launch_local

    prog = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "gateway_mesh_prog.py")
    out = str(tmp_path / "report.json")
    env = {k: v for k, v in os.environ.items()
           if not k.startswith("DMLC_")}
    env["XLA_FLAGS"] = ""       # override conftest's 8-device force
    env["JAX_PLATFORMS"] = ""   # prog pins cpu itself
    codes = launch_local(2, 0, [sys.executable, prog, out],
                         env_extra=env, timeout=240)
    assert codes == [0, 0], codes
    with open(out) as f:
        report = json.load(f)
    assert report["errors"] == []
    assert report["mesh_requests"] == 20
    assert report["addressable_shards"] == 1     # sharded ACROSS ranks
    assert report["quant_dropped"] == 0
    assert report["generations"] == [1, 2]
    assert report["quant_requests"] > 0


def test_mixed_load_many_threads():
    """Stress shape of the acceptance: 2 models (one quantized), mixed
    concurrent load, every response correct for ITS model and tagged
    with the serving generation."""
    rng = np.random.RandomState(4)
    w = mx.nd.array(rng.randn(4, 3).astype(np.float32))
    gw = ModelGateway(max_queue=4096)
    try:
        a, q = _name("a"), _name("q")
        gw.register(_spec(a, w=w))
        gw.register(ModelSpec(q, fn=_dot, params=[w * 2], item_shape=(4,),
                              max_batch=8, quantize="int8"))
        xs = [rng.rand(rng.randint(1, 4), 4).astype(np.float32)
              for _ in range(60)]
        with ThreadPoolExecutor(12) as pool:
            futs_a = [pool.submit(gw.predict, a, x) for x in xs]
            futs_q = [pool.submit(gw.predict, q, x) for x in xs]
            res_a = [f.result(timeout=60) for f in futs_a]
            res_q = [f.result(timeout=60) for f in futs_q]
        wn = w.asnumpy()
        for x, r in zip(xs, res_a):
            assert r.model == a and r.generation == 1
            np.testing.assert_allclose(r.output.asnumpy(), x @ wn,
                                       rtol=1e-5)
        for x, r in zip(xs, res_q):
            ref = x @ (2 * wn)
            assert np.max(np.abs(r.output.asnumpy() - ref)) \
                <= 0.05 * max(np.max(np.abs(ref)), 1e-6)
    finally:
        gw.shutdown()
