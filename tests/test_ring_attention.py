"""Ring attention / sequence parallelism (TPU-first long-context
capability; no reference counterpart — SURVEY.md §5.7 bucketing is the
reference's only long-sequence story)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from mxnet_tpu.parallel import (blockwise_attention, make_mesh,
                                ring_self_attention)


def _dense_attention(q, k, v, causal=False):
    scale = q.shape[-1] ** -0.5
    s = np.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if causal:
        t = q.shape[2]
        mask = np.tril(np.ones((t, t), bool))
        s = np.where(mask, s, -1e30)
    p = np.exp(s - s.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    return np.einsum("bhqk,bhkd->bhqd", p, v)


def _qkv(rng, b=2, h=2, t=32, d=8):
    return (rng.randn(b, h, t, d).astype(np.float32),
            rng.randn(b, h, t, d).astype(np.float32),
            rng.randn(b, h, t, d).astype(np.float32))


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_dense(causal):
    rng = np.random.RandomState(0)
    q, k, v = _qkv(rng)
    want = _dense_attention(q, k, v, causal)
    mesh = make_mesh({"dp": 2, "sp": 4})
    got = np.asarray(ring_self_attention(
        mesh, jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
        causal=causal))
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-5)


def test_ring_attention_sp_only_mesh():
    rng = np.random.RandomState(1)
    q, k, v = _qkv(rng, b=1, t=64)
    mesh = make_mesh({"sp": 8})
    got = np.asarray(ring_self_attention(
        mesh, jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
        causal=True, dp_axis="dp"))   # dp absent: batch replicated
    want = _dense_attention(q, k, v, causal=True)
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_blockwise_attention_matches_dense(causal):
    rng = np.random.RandomState(2)
    q, k, v = _qkv(rng, t=64)
    got = np.asarray(blockwise_attention(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), block=16,
        causal=causal))
    want = _dense_attention(q, k, v, causal)
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-5)


def test_blockwise_rejects_ragged_blocks():
    rng = np.random.RandomState(3)
    q, k, v = _qkv(rng, t=30)
    with pytest.raises(ValueError, match="not divisible"):
        blockwise_attention(jnp.asarray(q), jnp.asarray(k),
                            jnp.asarray(v), block=16)


def test_ring_attention_gradients_flow():
    """Training usability: grads flow through the ring collectives."""
    rng = np.random.RandomState(4)
    q, k, v = _qkv(rng, b=1, h=1, t=16, d=4)
    mesh = make_mesh({"sp": 8})

    def loss(qq, kk, vv):
        out = ring_self_attention(mesh, qq, kk, vv, causal=True)
        return (out ** 2).mean()

    g = jax.grad(loss, argnums=(0, 1, 2))(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))

    def dense_loss(qq, kk, vv):
        scale = qq.shape[-1] ** -0.5
        s = jnp.einsum("bhqd,bhkd->bhqk", qq, kk) * scale
        t = qq.shape[2]
        mask = jnp.tril(jnp.ones((t, t), bool))
        s = jnp.where(mask, s, -1e30)
        p = jax.nn.softmax(s, axis=-1)
        out = jnp.einsum("bhqk,bhkd->bhqd", p, vv)
        return (out ** 2).mean()

    g_ref = jax.grad(dense_loss, argnums=(0, 1, 2))(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
    for a, b in zip(g, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-4, atol=1e-5)
