"""Gluon tests (reference: tests/python/unittest/test_gluon.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, autograd, gluon
from mxnet_tpu.gluon import nn


def test_dense_forward():
    layer = nn.Dense(4, in_units=3)
    layer.initialize()
    x = nd.ones((2, 3))
    out = layer(x)
    assert out.shape == (2, 4)
    w = layer.weight.data().asnumpy()
    b = layer.bias.data().asnumpy()
    assert np.allclose(out.asnumpy(), np.ones((2, 3)) @ w.T + b, atol=1e-5)


def test_deferred_init():
    layer = nn.Dense(4)
    layer.initialize()
    out = layer(nd.ones((2, 7)))
    assert out.shape == (2, 4)
    assert layer.weight.shape == (4, 7)


def test_sequential_mlp_training():
    net = nn.HybridSequential()
    net.add(nn.Dense(16, activation="relu"),
            nn.Dense(2))
    net.initialize()
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.5})
    np.random.seed(0)
    X = np.random.randn(64, 10).astype(np.float32)
    W = np.random.randn(10, 2).astype(np.float32)
    y = (X @ W).argmax(1).astype(np.float32)
    xs, ys = nd.array(X), nd.array(y)
    first = None
    for _ in range(40):
        with autograd.record():
            out = net(xs)
            loss = loss_fn(out, ys).mean()
        loss.backward()
        trainer.step(1)
        if first is None:
            first = float(loss.asscalar())
    last = float(loss.asscalar())
    assert last < first * 0.5, (first, last)


def test_hybridize_equivalence():
    np.random.seed(1)
    net = nn.HybridSequential()
    net.add(nn.Dense(8, activation="relu"), nn.Dense(3))
    net.initialize()
    x = nd.array(np.random.rand(4, 5).astype(np.float32))
    eager = net(x).asnumpy()
    net.hybridize()
    hybrid = net(x).asnumpy()  # first call compiles
    hybrid2 = net(x).asnumpy()  # second call uses cache
    assert np.allclose(eager, hybrid, atol=1e-5)
    assert np.allclose(hybrid, hybrid2, atol=1e-6)


def test_hybridize_training():
    np.random.seed(2)
    net = nn.HybridSequential()
    net.add(nn.Dense(16, activation="relu"), nn.Dense(2))
    net.initialize()
    net.hybridize()
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    trainer = gluon.Trainer(net.collect_params(), "sgd", {"learning_rate": 0.5})
    X = np.random.randn(32, 6).astype(np.float32)
    y = (X.sum(1) > 0).astype(np.float32)
    xs, ys = nd.array(X), nd.array(y)
    losses = []
    for _ in range(30):
        with autograd.record():
            loss = loss_fn(net(xs), ys).mean()
        loss.backward()
        trainer.step(1)
        losses.append(float(loss.asscalar()))
    assert losses[-1] < losses[0] * 0.5


def test_batchnorm_layer():
    net = nn.BatchNorm(in_channels=3)
    net.initialize()
    x = nd.array(np.random.rand(4, 3, 2, 2).astype(np.float32) * 10)
    rm_before = net.running_mean.data().asnumpy().copy()
    with autograd.record():
        out = net(x)
    rm_after = net.running_mean.data().asnumpy()
    assert not np.allclose(rm_before, rm_after)  # stats updated in train mode
    out_eval = net(x)  # eval mode uses running stats
    assert out_eval.shape == x.shape


def test_batchnorm_hybrid_aux_update():
    net = nn.BatchNorm(in_channels=2)
    net.initialize()
    net.hybridize()
    x = nd.array(np.random.rand(8, 2, 3, 3).astype(np.float32) * 5 + 3)
    rm0 = net.running_mean.data().asnumpy().copy()
    with autograd.record():
        net(x)
    rm1 = net.running_mean.data().asnumpy().copy()
    assert not np.allclose(rm0, rm1)
    with autograd.record():
        net(x)
    rm2 = net.running_mean.data().asnumpy()
    assert not np.allclose(rm1, rm2)  # keeps moving across calls


def test_conv_net():
    net = nn.HybridSequential()
    net.add(nn.Conv2D(8, kernel_size=3, padding=1, activation="relu"),
            nn.MaxPool2D(),
            nn.Conv2D(16, kernel_size=3, padding=1),
            nn.BatchNorm(),
            nn.Activation("relu"),
            nn.GlobalAvgPool2D(),
            nn.Flatten(),
            nn.Dense(10))
    net.initialize()
    x = nd.ones((2, 3, 16, 16))
    out = net(x)
    assert out.shape == (2, 10)
    net.hybridize()
    out2 = net(x)
    assert out2.shape == (2, 10)


def test_save_load_parameters(tmp_path):
    net = nn.HybridSequential()
    net.add(nn.Dense(4, in_units=3), nn.Dense(2, in_units=4))
    net.initialize()
    fname = str(tmp_path / "model.params")
    net.save_parameters(fname)
    net2 = nn.HybridSequential()
    net2.add(nn.Dense(4, in_units=3), nn.Dense(2, in_units=4))
    net2.load_parameters(fname)
    x = nd.ones((1, 3))
    assert np.allclose(net(x).asnumpy(), net2(x).asnumpy(), atol=1e-6)


def test_dropout_layer_modes():
    layer = nn.Dropout(0.5)
    layer.initialize()
    x = nd.ones((40, 40))
    out_eval = layer(x)
    assert np.allclose(out_eval.asnumpy(), 1.0)  # inference: identity
    with autograd.record():
        out_train = layer(x)
    frac = (out_train.asnumpy() == 0).mean()
    assert 0.25 < frac < 0.75


def test_embedding_layer():
    layer = nn.Embedding(10, 4)
    layer.initialize()
    idx = nd.array([1, 2, 3])
    out = layer(idx)
    assert out.shape == (3, 4)


def test_trainer_optimizer_states(tmp_path):
    net = nn.Dense(2, in_units=3)
    net.initialize()
    trainer = gluon.Trainer(net.collect_params(), "adam", {"learning_rate": 0.1})
    x = nd.ones((4, 3))
    with autograd.record():
        loss = net(x).sum()
    loss.backward()
    trainer.step(4)
    fname = str(tmp_path / "trainer.states")
    trainer.save_states(fname)
    trainer.load_states(fname)
    with autograd.record():
        loss = net(x).sum()
    loss.backward()
    trainer.step(4)


def test_parameter_grad_req():
    net = nn.Dense(2, in_units=2)
    net.initialize()
    net.weight.grad_req = "null"
    x = nd.ones((1, 2))
    with autograd.record():
        loss = net(x).sum()
    loss.backward()
    assert np.allclose(net.bias.grad().asnumpy(), 1)


def test_clip_global_norm():
    arrays = [nd.array([[3.0, 4.0]]), nd.array([[0.0]])]
    norm = gluon.utils.clip_global_norm(arrays, 1.0)
    assert abs(norm - 5.0) < 1e-5
    assert np.allclose(arrays[0].asnumpy(), [[0.6, 0.8]], atol=1e-4)


def test_split_and_load():
    data = nd.arange(12).reshape((6, 2))
    slices = gluon.utils.split_and_load(data, [mx.cpu(0), mx.cpu(0)])
    assert len(slices) == 2
    assert slices[0].shape == (3, 2)
