"""Worker program for the goodput restart-replay acceptance test
(tests/test_goodput.py; the data_resume_prog SIGKILL-harness pattern).

One rank runs a direct-mode step loop with a per-step-committing
GoodputLedger and a sparser CheckpointManager cadence. Modes:

* ``kill``   — checkpoint every ``--ckpt-every`` steps, tick the ledger
  every step (interval 0 => durable commit per step), then SIGKILL
  itself after ``--kill-after`` steps (no cleanup, like a preemption).
* ``resume`` — restore the newest checkpoint, resume the ledger from
  the restore step, run to ``--steps``, and write ``result.json`` with
  the final snapshot. The steps between the checkpoint-restore step and
  the dead run's last committed ledger step re-run as
  ``restart_replay`` badput — the test asserts that count matches the
  true gap within one step (the kill step's own commit may or may not
  have landed).
"""
import argparse
import json
import os
import sys
import time

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _ROOT)

from mxnet_tpu.checkpoint import CheckpointManager     # noqa: E402
from mxnet_tpu.telemetry import goodput                # noqa: E402
from mxnet_tpu.telemetry import metrics as tm          # noqa: E402


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--dir", required=True)
    ap.add_argument("--mode", choices=("kill", "resume"), required=True)
    ap.add_argument("--steps", type=int, default=14)
    ap.add_argument("--kill-after", type=int, default=8)
    ap.add_argument("--ckpt-every", type=int, default=3)
    args = ap.parse_args()

    ckpt_dir = os.path.join(args.dir, "ckpt")
    ledger = goodput.GoodputLedger(directory=args.dir, rank=0,
                                   interval_s=0.0,
                                   registry=tm.Registry())
    mgr = CheckpointManager(ckpt_dir)

    start = 0
    if args.mode == "resume":
        restored = mgr.restore()
        assert restored is not None, "no checkpoint to resume from"
        step, _state = restored
        start = int(step)
        ledger.resume_from(start)

    for i in range(start, args.steps):
        time.sleep(0.005)
        ledger.observe_step(i, seconds=0.005)
        ledger.tick(step=i)                  # interval 0: commits now
        if args.mode == "kill":
            if (i + 1) % args.ckpt_every == 0:
                mgr.save(i, {"w": [i]}, sync=True)
            if i + 1 >= args.kill_after:
                os.kill(os.getpid(), 9)      # preemption, no cleanup

    snap = ledger.snapshot(serving=False)
    with open(os.path.join(args.dir, "result.json"), "w") as f:
        json.dump(snap, f)
    mgr.close()
    ledger.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
