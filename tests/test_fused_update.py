"""Fused imperative update path (mxnet_tpu.fused_update).

Contracts under test:

- Fused and loop update paths produce BIT-IDENTICAL parameter values
  (the acceptance criterion: 5 steps of SGD-momentum and Adam agree
  exactly; same for RMSProp/AdaGrad/Signum and mixed shapes).
- Per-step dispatch count on the fused path is independent of the
  parameter count (multi-tensor apply = one executable per group).
- One compile per param-set signature (executable-cache discipline).
- Bucketed gradient aggregation matches per-key kvstore aggregation
  bitwise, and the bucket plan splits at the configured byte budget.
- The row-sparse update path never round-trips the gradient payload
  through host memory (no `asnumpy` during step).
- `Trainer.step` finalizes `rescale_grad` BEFORE the kvstore pickles
  the optimizer to dist servers (ordering pinned by test).
- Optimizer state written by the fused path is the same state the loop
  path reads: toggling fused mid-run and save/load_states stay exact.
- StepMonitor.attach_fused flags fused-apply recompile storms.
"""
import pickle

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd
from mxnet_tpu import kvstore as kvs
from mxnet_tpu.test_utils import count_dispatches


def _make_params(n, seed=0, shapes=None):
    """Default shapes are vector-width-aligned (multiples of 8 floats):
    the regime where fused and loop paths are BIT-identical by
    construction (see fused_update._build_chunk's pad rationale).
    Unaligned shapes get the ulp-bounded contract, tested separately."""
    rng = np.random.RandomState(seed)
    params = []
    for k in range(n):
        shape = shapes[k % len(shapes)] if shapes else \
            ((4, 4) if k % 2 else (8,))
        p = gluon.Parameter("fused_p%d" % k, shape=shape)
        p.initialize(init=mx.init.Constant(0.0))
        p.set_data(nd.array(rng.randn(*shape).astype(np.float32)))
        params.append(p)
    return params


def _run_steps(optimizer, opt_params, fused, steps=5, n=6, grad_seed=42,
               trainer_kwargs=None, shapes=None):
    params = _make_params(n, shapes=shapes)
    trainer = gluon.Trainer(params, optimizer, dict(opt_params),
                            fused=fused, **(trainer_kwargs or {}))
    rng = np.random.RandomState(grad_seed)
    for _ in range(steps):
        for p in params:
            p.grad()[:] = rng.randn(*p.shape).astype(np.float32)
        trainer.step(2)
    return [p.data().asnumpy().copy() for p in params], trainer


@pytest.mark.parametrize("optimizer,opt_params", [
    ("sgd", {"learning_rate": 0.1, "momentum": 0.9}),
    ("sgd", {"learning_rate": 0.05, "wd": 1e-3, "clip_gradient": 0.5}),
    ("adam", {"learning_rate": 0.01}),
    ("rmsprop", {"learning_rate": 0.01}),
    ("adagrad", {"learning_rate": 0.1}),
    ("adadelta", {}),
    ("nag", {"learning_rate": 0.1, "momentum": 0.9}),
    ("signum", {"learning_rate": 0.01}),
])
def test_fused_bit_identical_to_loop(optimizer, opt_params):
    """THE cross-check: 5 steps fused vs 5 steps per-param loop must
    agree in every bit — the fused executable runs the same FCompute
    bodies in the same order. (Centered RMSProp's divide-by-sqrt chain
    is codegen-sensitive at the last bit and carries the ulp contract
    instead — see test_fused_unaligned_shapes_within_an_ulp.)"""
    fused, tr = _run_steps(optimizer, opt_params, fused=True)
    loop, _ = _run_steps(optimizer, opt_params, fused=False)
    assert tr._applier is not None and tr._applier.num_compiles >= 1
    for a, b in zip(fused, loop):
        np.testing.assert_array_equal(a, b)


@pytest.mark.parametrize("shapes", [None, [(3, 4), (5,), (7, 3), (10,)]])
def test_fused_within_an_ulp_everywhere(shapes):
    """The general-case bound, aligned or not: fused may differ from
    the loop path by at most last-bit rounding. Two sources, both
    XLA:CPU codegen artifacts the flat kernel cannot control: FMA
    contraction differs between the vector body and a standalone
    kernel's remainder lanes (non-multiple-of-8 sizes), and
    divide-by-sqrt chains (centered RMSProp) lower differently per
    kernel shape — the same documented contract as PyTorch's
    fused/foreach optimizers. This pins the bound: ulp-scale, never
    more."""
    for optimizer, opt_params in (
            ("sgd", {"learning_rate": 0.1, "momentum": 0.9}),
            ("adam", {"learning_rate": 0.01}),
            ("rmsprop", {"learning_rate": 0.01, "centered": True})):
        fused, _ = _run_steps(optimizer, opt_params, fused=True,
                              shapes=shapes)
        loop, _ = _run_steps(optimizer, opt_params, fused=False,
                             shapes=shapes)
        for a, b in zip(fused, loop):
            np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-7)


def test_fused_respects_lr_wd_multipliers():
    """Per-param lr_mult/wd_mult ride the runtime lr/wd vectors."""
    def run(fused):
        params = _make_params(4)
        params[1].lr_mult = 0.25
        params[2].wd_mult = 3.0
        trainer = gluon.Trainer(params, "sgd",
                                {"learning_rate": 0.2, "wd": 1e-2},
                                fused=fused)
        rng = np.random.RandomState(3)
        for _ in range(3):
            for p in params:
                p.grad()[:] = rng.randn(*p.shape).astype(np.float32)
            trainer.step(1)
        return [p.data().asnumpy() for p in params]

    for a, b in zip(run(True), run(False)):
        np.testing.assert_array_equal(a, b)


def test_fused_lr_schedule_does_not_retrace():
    """learning_rate is a runtime input: set_learning_rate between
    steps must not grow the executable cache."""
    params = _make_params(4)
    trainer = gluon.Trainer(params, "adam", {"learning_rate": 0.01})
    rng = np.random.RandomState(1)
    for step in range(4):
        trainer.set_learning_rate(0.01 / (step + 1))
        for p in params:
            p.grad()[:] = rng.randn(*p.shape).astype(np.float32)
        trainer.step(1)
    assert trainer._applier.num_compiles == 1


def test_fused_dispatch_count_independent_of_param_count():
    """Acceptance criterion: per-step dispatch count on the fused path
    does not scale with parameter count (<= ceil(params/bucket) + 1;
    single ctx + one dtype = one group = ONE dispatch)."""
    counts = {}
    for n in (4, 32):
        params = _make_params(n)
        trainer = gluon.Trainer(params, "sgd",
                                {"learning_rate": 0.1, "momentum": 0.9})
        rng = np.random.RandomState(7)
        for p in params:
            p.grad()[:] = rng.randn(*p.shape).astype(np.float32)
        trainer.step(1)                      # warmup: compile
        with count_dispatches() as c:
            trainer.step(1)
        counts[n] = c.count
    assert counts[4] == counts[32], counts
    assert counts[32] <= 2, counts           # ceil(32/bucket) + 1 = 2


def test_loop_dispatch_count_scales_with_params():
    """The baseline the fused path beats: the per-param loop issues at
    least one dispatch per parameter."""
    params = _make_params(12)
    trainer = gluon.Trainer(params, "sgd",
                            {"learning_rate": 0.1, "momentum": 0.9},
                            fused=False)
    rng = np.random.RandomState(7)
    for p in params:
        p.grad()[:] = rng.randn(*p.shape).astype(np.float32)
    trainer.step(1)
    with count_dispatches() as c:
        trainer.step(1)
    assert c.count >= 12, c.count


def test_fused_compiles_once_per_signature():
    """Executable-cache discipline: repeated steps on the same param
    set never recompile; mx_fused_apply_compiles_total tracks fills."""
    from mxnet_tpu.telemetry import metrics as tm

    params = _make_params(5)
    trainer = gluon.Trainer(params, "adam", {"learning_rate": 0.01})
    rng = np.random.RandomState(11)
    fam = tm.REGISTRY.counter(
        "mx_fused_apply_compiles_total", "", labels=("optimizer",))
    before = fam.labels(optimizer="adam").value
    for _ in range(4):
        for p in params:
            p.grad()[:] = rng.randn(*p.shape).astype(np.float32)
        trainer.step(1)
    assert trainer._applier.num_compiles == 1
    assert fam.labels(optimizer="adam").value == before + 1


def test_fused_escape_hatch_and_env(monkeypatch):
    """fused=False and MXNET_FUSED_UPDATE=0 both restore the loop (the
    applier object exists for monitoring hooks but never compiles)."""
    _, tr = _run_steps("sgd", {"learning_rate": 0.1}, fused=False,
                       steps=1)
    assert not tr._fused and tr._applier.num_compiles == 0
    monkeypatch.setenv("MXNET_FUSED_UPDATE", "0")
    _, tr = _run_steps("sgd", {"learning_rate": 0.1}, fused=None,
                       steps=1)
    assert not tr._fused and tr._applier.num_compiles == 0


def test_fused_unsupported_optimizer_falls_back():
    """Optimizers outside the table (FTML bakes t per step, Nadam has
    shared host state, Ftrl divides by lr so a runtime-lr executable
    would drift an ulp) take the per-param loop — and still match the
    fused=False run exactly."""
    for name in ("ftml", "nadam", "ftrl"):
        a, tra = _run_steps(name, {}, fused=True, steps=3, n=3)
        b, _ = _run_steps(name, {}, fused=False, steps=3, n=3)
        assert tra._applier is None or tra._applier.num_compiles == 0
        for x, y in zip(a, b):
            np.testing.assert_array_equal(x, y)


def test_fused_toggle_midrun_shares_state():
    """The applier writes the SAME updater state dict the loop reads:
    3 fused steps + 2 loop steps == 5 loop steps."""
    params = _make_params(4)
    trainer = gluon.Trainer(params, "sgd",
                            {"learning_rate": 0.1, "momentum": 0.9})
    rng = np.random.RandomState(21)
    for s in range(5):
        if s == 3:
            trainer._fused = False           # flip the hatch mid-run
        for p in params:
            p.grad()[:] = rng.randn(*p.shape).astype(np.float32)
        trainer.step(2)                      # same rescale as _run_steps
    mixed = [p.data().asnumpy() for p in params]
    pure, _ = _run_steps("sgd", {"learning_rate": 0.1, "momentum": 0.9},
                         fused=False, n=4, grad_seed=21)
    for a, b in zip(mixed, pure):
        np.testing.assert_array_equal(a, b)


def test_fused_save_load_states_roundtrip(tmp_path):
    """Momentum written by the fused executable pickles/restores through
    the standard Trainer.save_states path."""
    params = _make_params(3)
    trainer = gluon.Trainer(params, "sgd",
                            {"learning_rate": 0.1, "momentum": 0.9})
    rng = np.random.RandomState(5)
    for _ in range(3):
        for p in params:
            p.grad()[:] = rng.randn(*p.shape).astype(np.float32)
        trainer.step(1)
    fname = str(tmp_path / "trainer.states")
    trainer.save_states(fname)
    blob = pickle.loads(open(fname, "rb").read())
    assert set(blob) == {0, 1, 2}
    mom0 = np.asarray(blob[0])
    assert np.abs(mom0).sum() > 0            # momentum actually moved
    trainer.load_states(fname)
    for p in params:                         # next step still works
        p.grad()[:] = rng.randn(*p.shape).astype(np.float32)
    trainer.step(1)


# -- bucketed gradient aggregation -------------------------------------------

def test_bucket_plan_splits_at_budget():
    from mxnet_tpu.fused_update import GradBucketer

    entries = [(i, (256,), np.float32) for i in range(10)]  # 1KiB each
    b = GradBucketer(entries, max_bytes=4096)
    assert len(b) == 3                       # 4+4+2
    assert [len(x.keys) for x in b.buckets] == [4, 4, 2]
    assert sum(len(x.keys) for x in b.buckets) == 10
    # mixed dtypes never share a bucket (can't concat flat)
    mixed = [(0, (8,), np.float32), (1, (8,), np.float16),
             (2, (8,), np.float32)]
    b2 = GradBucketer(mixed, max_bytes=1 << 20)
    assert len(b2) == 2
    assert {tuple(x.keys) for x in b2.buckets} == {(1,), (0, 2)}


def test_bucketed_allreduce_matches_per_key():
    """Multi-device training through flat buckets lands on the same
    bits as the reference-shaped per-key push/pull."""
    def run(fused):
        net = gluon.nn.Dense(2, in_units=3)
        ctxs = [mx.cpu(0), mx.cpu(1)]
        net.initialize(ctx=ctxs)
        for k, p in enumerate(net.collect_params().values()):
            p.set_data(nd.array(
                np.random.RandomState(k).randn(*p.shape)
                .astype(np.float32)))
        trainer = gluon.Trainer(net.collect_params(), "sgd",
                                {"learning_rate": 0.1, "momentum": 0.9},
                                fused=fused)
        for s in range(3):
            with autograd.record():
                losses = [(net(nd.ones((2, 3), ctx=c) * (0.3 + s))
                           ** 2).sum() for c in ctxs]
            for l in losses:
                l.backward()
            trainer.step(4)
        return [p.data().asnumpy()
                for p in net.collect_params().values()]

    for a, b in zip(run(True), run(False)):
        np.testing.assert_array_equal(a, b)


def test_bucket_generation_drift_frees_old_store_keys():
    """Signature drift retires the old generation's coalesced buckets
    from the kvstore (discard) instead of leaking them, and the new
    generation registers fresh keys via contains()/init."""
    params = [gluon.Parameter("gen_p%d" % k, shape=(8,)) for k in range(3)]
    for p in params:
        p.initialize(ctx=[mx.cpu(0), mx.cpu(1)], init=mx.init.Constant(0.1))
    trainer = gluon.Trainer(params, "sgd", {"learning_rate": 0.1})
    rng = np.random.RandomState(9)
    for p in params:
        for g in p.list_grad():
            g[:] = rng.randn(8).astype(np.float32)
    trainer.step(1)
    kv = trainer._kvstore
    old_keys = set(trainer._bucket_keys_inited)
    assert old_keys and all(kv.contains(k) for k in old_keys)
    # Drift: a late param joins -> new generation, old keys freed.
    late = gluon.Parameter("gen_late", shape=(8,))
    late.initialize(ctx=[mx.cpu(0), mx.cpu(1)], init=mx.init.Constant(0.1))
    trainer._params.append(late)
    for g in late.list_grad():
        g[:] = rng.randn(8).astype(np.float32)
    trainer.step(1)
    new_keys = set(trainer._bucket_keys_inited)
    assert new_keys and new_keys.isdisjoint(old_keys)
    assert all(not kv.contains(k) for k in old_keys)
    assert all(kv.contains(k) for k in new_keys)


def test_bucketed_allreduce_dispatch_count():
    """Allreduce launches scale with bucket count, not param count:
    same dispatch total for 4 and 16 params (one bucket)."""
    def count_for(n):
        params = []
        for k in range(n):
            p = gluon.Parameter("bk%d_%d" % (n, k), shape=(6,))
            p.initialize(ctx=[mx.cpu(0), mx.cpu(1)],
                         init=mx.init.Constant(0.1))
            params.append(p)
        trainer = gluon.Trainer(params, "sgd", {"learning_rate": 0.1})
        rng = np.random.RandomState(2)
        for p in params:
            for g in p.list_grad():
                g[:] = rng.randn(*p.shape).astype(np.float32)
        trainer.step(1)                      # init store + compile
        with count_dispatches() as c:
            trainer.allreduce_grads()
        return c.count

    assert count_for(4) == count_for(16)


# -- row-sparse device path --------------------------------------------------

def test_row_sparse_step_never_touches_host(monkeypatch):
    """Regression (satellite): the row-sparse branch used to call
    grad.asnumpy() — a full host round trip of the gradient — every
    step. The device-side extraction must issue ZERO asnumpy calls
    during step()."""
    from mxnet_tpu.gluon.contrib.nn import SparseEmbedding
    from mxnet_tpu.ndarray.ndarray import NDArray

    emb = SparseEmbedding(50, 4)
    emb.initialize()
    w0 = emb.weight.data().asnumpy().copy()
    trainer = gluon.Trainer(emb.collect_params(), "sgd",
                            {"learning_rate": 1.0, "momentum": 0.9})
    with autograd.record():
        loss = (emb(nd.array(np.array([3, 7, 3], np.float32))) ** 2).sum()
    loss.backward()

    calls = []
    orig = NDArray.asnumpy
    monkeypatch.setattr(NDArray, "asnumpy",
                        lambda self: calls.append(1) or orig(self))
    trainer.step(1)
    monkeypatch.undo()
    assert not calls, "row-sparse update transferred %d arrays to host" \
        % len(calls)
    changed = np.where(np.abs(emb.weight.data().asnumpy() - w0)
                       .sum(axis=1) > 0)[0]
    assert set(changed.tolist()) == {3, 7}   # lazy update: seen rows only


@pytest.mark.parametrize("optimizer,opt_params", [
    ("sgd", {"learning_rate": 0.5, "momentum": 0.9}),
    ("adam", {"learning_rate": 0.1}),
])
def test_row_sparse_device_path_matches_host_path(optimizer, opt_params):
    from mxnet_tpu.gluon.contrib.nn import SparseEmbedding

    def run(fused):
        emb = SparseEmbedding(20, 3)
        emb.initialize()
        emb.weight.set_data(nd.array(
            np.random.RandomState(9).randn(20, 3).astype(np.float32)))
        trainer = gluon.Trainer(emb.collect_params(), optimizer,
                                dict(opt_params), fused=fused)
        for _ in range(4):
            with autograd.record():
                loss = (emb(nd.array(
                    np.array([1, 4, 4, 9], np.float32))) ** 2).sum()
            loss.backward()
            trainer.step(1)
        return emb.weight.data().asnumpy()

    np.testing.assert_array_equal(run(True), run(False))


def test_dense_to_rsp_device_semantics():
    """Padded lanes are exact no-ops: out-of-range ids, todense drops
    them, values match a host-side conversion."""
    from mxnet_tpu.ndarray import sparse as sp

    dense = np.zeros((8, 3), np.float32)
    dense[2] = 1.5
    dense[5] = -2.0
    dense[6] = 0.25
    rsp = sp.dense_to_rsp_device(nd.array(dense))
    assert rsp.stype == "row_sparse" and rsp._rows_ready
    idx = np.asarray(rsp.indices._data)
    assert len(idx) == 4                     # padded 3 -> pow2
    assert idx[:3].tolist() == [2, 5, 6]
    assert idx[3] == 8                       # out-of-range pad id
    np.testing.assert_array_equal(rsp.todense().asnumpy(), dense)
    # all-zero gradient: single pad lane, still a no-op
    zero = sp.dense_to_rsp_device(nd.array(np.zeros((4, 2), np.float32)))
    np.testing.assert_array_equal(zero.todense().asnumpy(),
                                  np.zeros((4, 2), np.float32))


# -- rescale_grad / kvstore pickle ordering (satellite) ----------------------

class _PickleCapturingStore(kvs.KVStore):
    """Dist-shaped store that captures the optimizer pickle the way
    KVStoreDist.set_optimizer ships it to servers."""

    def __init__(self):
        super().__init__()
        self.blobs = []
        self._stored = {}

    @property
    def type(self):
        return "dist_sync_capture"

    def init(self, key, value):
        self._stored[key] = value

    def push(self, key, value, priority=0):
        pass

    def pull(self, key, out=None, priority=0, ignore_sparse=True):
        pass

    def set_optimizer(self, optimizer):
        self._optimizer = optimizer
        param_dict = optimizer.param_dict
        optimizer.param_dict = {}            # live Parameters don't pickle
        try:
            self.blobs.append(pickle.dumps(optimizer))
        finally:
            optimizer.param_dict = param_dict


def test_step_finalizes_rescale_before_kvstore_pickles_optimizer():
    """trainer.py pins _init_kvstore AFTER rescale_grad is final so the
    one-shot optimizer pickle dist servers receive carries the real
    rescale (the comment claimed it; this pins it)."""
    store = _PickleCapturingStore()
    params = _make_params(2)
    trainer = gluon.Trainer(params, "sgd", {"learning_rate": 0.1},
                            kvstore=store)
    rng = np.random.RandomState(0)
    for p in params:
        p.grad()[:] = rng.randn(*p.shape).astype(np.float32)
    assert not trainer._kv_initialized and not store.blobs
    trainer.step(5)
    assert len(store.blobs) == 1             # pickled exactly once...
    shipped = pickle.loads(store.blobs[0])
    assert shipped.rescale_grad == pytest.approx(1.0 / 5)  # ...final value
    # later steps re-rescale locally but never re-pickle
    trainer.step(10)
    assert len(store.blobs) == 1


# -- telemetry follow-through ------------------------------------------------

def test_step_monitor_flags_fused_recompile_storm():
    from mxnet_tpu.telemetry import StepMonitor

    params = _make_params(3)
    trainer = gluon.Trainer(params, "sgd",
                            {"learning_rate": 0.1, "momentum": 0.9})
    # The applier exists from construction, so monitoring wires up
    # BEFORE the first step (the README pattern).
    assert trainer._applier is not None
    monitor = StepMonitor(expected_traces=1, warn_interval_s=0)
    fired = []
    trainer._applier.on_compile = lambda a: fired.append(a.num_compiles)
    monitor.attach_fused(trainer._applier)   # chains, keeps prior hook

    rng = np.random.RandomState(4)
    for p in params:
        p.grad()[:] = rng.randn(*p.shape).astype(np.float32)
    trainer.step(1)                          # warmup compile: free
    assert monitor.anomaly_counts.get("fused_recompile") is None
    # signature churn: momentum changes re-bake statics -> recompiles.
    # The first post-warmup compile is within the default budget (1);
    # the second is the storm.
    trainer._optimizer.momentum = 0.5
    trainer.step(1)
    assert monitor.anomaly_counts.get("fused_recompile") is None
    trainer._optimizer.momentum = 0.3
    trainer.step(1)
    assert monitor.anomaly_counts.get("fused_recompile") == 1
    assert fired == [1, 2, 3]                # prior hook kept firing


def test_trainer_update_metrics_recorded():
    from mxnet_tpu.telemetry import metrics as tm

    hist = tm.REGISTRY.histogram("mx_trainer_update_seconds", "")
    disp = tm.REGISTRY.counter("mx_trainer_fused_dispatches", "")
    h0, d0 = hist.snapshot()["count"], disp.value
    params = _make_params(2)
    trainer = gluon.Trainer(params, "sgd", {"learning_rate": 0.1})
    rng = np.random.RandomState(6)
    for p in params:
        p.grad()[:] = rng.randn(*p.shape).astype(np.float32)
    trainer.step(1)
    assert hist.snapshot()["count"] == h0 + 1
    assert disp.value >= d0 + 1
