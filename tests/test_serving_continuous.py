"""mxnet_tpu.serving.continuous — continuous batching (ISSUE 19
tentpole): per-iteration slot scheduling, paged per-slot state, and the
zero-steady-state-retrace contract; plus the gateway seams (admission
pool + queue-share, deadline shedding mid-decode, hot reload draining
in-flight sequences on the old generation) and the per-model
`max_delay_ms` batcher override. Model names are minted per test so the
process-global metric families never blend across tests."""
import itertools
import threading
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import serving
from mxnet_tpu.serving import (DeadlineExceededError, DecodeConfig,
                               DecodeLoop, ModelGateway, ModelSpec,
                               PagedSlotAllocator, QueueFullError,
                               SequenceResult, ServiceUnavailableError,
                               hot_swap)

_names = itertools.count()


def _name(base="dm"):
    return "%s%d" % (base, next(_names))


H = 4            # per-slot state width


def _w(fill=1.0):
    return mx.nd.array(np.full((H,), fill, np.float32))


def _step(w, state, tokens, pos):
    """Counter decoder: state accumulates w, next token = last + 1 —
    fully deterministic, so expected outputs are computable host-side."""
    return state + w, tokens + 1


def _cfg(**kw):
    kw.setdefault("state_shape", (H,))
    kw.setdefault("page_slots", 4)
    kw.setdefault("max_tokens", 4)
    return DecodeConfig(_step, **kw)


def _spec(name, w=None, **kw):
    kw.setdefault("max_batch", 8)
    kw.setdefault("decode", _cfg())
    return ModelSpec(name, params=[w if w is not None else _w()], **kw)


def _loop(name=None, w=None, spec_kw=None, **kw):
    spec = _spec(name or _name(), w=w, **(spec_kw or {}))
    return DecodeLoop(spec, spec.build_backend(), **kw)


def _expect(prompt, n):
    """Tokens the counter decoder emits for `prompt`, n tokens total."""
    last = int(np.asarray(prompt).reshape(-1)[-1])
    return [last + 1 + i for i in range(n)]


# -- PagedSlotAllocator ------------------------------------------------------

class TestPagedSlotAllocator:
    def test_lowest_first_and_reuse(self):
        a = PagedSlotAllocator(8, 4)
        assert [a.alloc() for _ in range(4)] == [0, 1, 2, 3]
        a.free(1)
        a.free(0)
        # Freed slots come back lowest-first: occupancy stays
        # prefix-compact so the stepped page count tracks load DOWN.
        assert a.alloc() == 0
        assert a.alloc() == 1
        assert a.occupancy == 4 and a.high_water == 4

    def test_exhaustion_returns_none(self):
        a = PagedSlotAllocator(2, 4)
        assert a.alloc() == 0 and a.alloc() == 1
        assert a.alloc() is None
        a.free(0)
        assert a.alloc() == 0

    def test_double_free_raises(self):
        a = PagedSlotAllocator(4, 2)
        s = a.alloc()
        a.free(s)
        with pytest.raises(ValueError):
            a.free(s)
        with pytest.raises(ValueError):
            a.free(99)

    def test_pages_and_high_water(self):
        a = PagedSlotAllocator(8, 4)
        assert a.num_pages == 2
        assert a.high_water == 0
        for _ in range(5):
            a.alloc()
        assert a.high_water == 5
        assert a.pages_for(a.high_water) == 2
        for s in (4, 3, 2):
            a.free(s)
        assert a.high_water == 2 and a.pages_for(a.high_water) == 1


# -- config / spec validation ------------------------------------------------

def test_decode_config_validation():
    with pytest.raises(ValueError):
        DecodeConfig("nope", state_shape=(4,))
    with pytest.raises(ValueError):
        DecodeConfig(_step, state_shape=())
    with pytest.raises(ValueError):
        DecodeConfig(_step, state_shape=(4,), page_slots=0)
    with pytest.raises(ValueError):
        DecodeConfig(_step, state_shape=(4,), max_tokens=0)
    with pytest.raises(ValueError):
        DecodeConfig(_step, state_shape=(4,), init="nope")
    d = DecodeConfig(_step, state_shape=(4,), page_slots=2,
                     stop_token=0)
    assert d.describe()["page_slots"] == 2
    assert d.single_state


def test_decode_spec_validation():
    with pytest.raises(ValueError):        # decode excludes fn=
        ModelSpec("x", fn=_step, decode=_cfg(), params=[_w()])
    with pytest.raises(ValueError):        # ... and checkpoint=
        ModelSpec("x", checkpoint="p", decode=_cfg())
    with pytest.raises(ValueError):        # ... and quantize=
        ModelSpec("x", decode=_cfg(), params=[_w()], quantize="int8")
    with pytest.raises(ValueError):        # ... and mesh_axes=
        ModelSpec("x", decode=_cfg(), params=[_w()],
                  mesh_axes={"tp": 2})
    with pytest.raises(ValueError):
        ModelSpec("x", fn=_step, params=[_w()], item_shape=(4,),
                  max_delay_ms=-1)
    with pytest.raises(ValueError):
        ModelSpec("x", fn=_step, params=[_w()], item_shape=(4,),
                  queue_share=0)
    with pytest.raises(ValueError):
        ModelSpec("x", fn=_step, params=[_w()], item_shape=(4,),
                  queue_share=1.5)
    # dict coercion + describe round-trip.
    sp = ModelSpec(_name(), params=[_w()], max_batch=4,
                   decode={"step": _step, "state_shape": (H,)},
                   max_delay_ms=2.5, queue_share=0.5)
    d = sp.describe()
    assert d["kind"] == "decode"
    assert d["max_delay_ms"] == 2.5 and d["queue_share"] == 0.5
    assert d["decode"]["state_shape"] == [[H]]


# -- standalone loop ---------------------------------------------------------

def test_loop_generates_expected_tokens():
    loop = _loop()
    try:
        seqs = [loop.submit([3, 5], max_tokens=3),
                loop.submit([10], max_tokens=5)]
        r0 = seqs[0].future.result(timeout=30)
        r1 = seqs[1].future.result(timeout=30)
        assert isinstance(r0, SequenceResult)
        assert r0.tokens == _expect([3, 5], 3)
        assert r1.tokens == _expect([10], 5)
        assert r0.generation == 1 and r0.ttft_s >= 0
    finally:
        loop.close()


def test_stop_token_terminates_early():
    loop = _loop(spec_kw={"decode": _cfg(stop_token=7, max_tokens=50)})
    try:
        # Counter decoder from 4 hits 7 after 3 tokens (5, 6, 7).
        r = loop.submit([4]).future.result(timeout=30)
        assert r.tokens == [5, 6, 7]
    finally:
        loop.close()


def test_slot_churn_zero_retrace():
    """THE contract: after warm(), admit/retire churn at every step
    (mixed lengths, mixed prompts, occupancy crossing page boundaries)
    adds ZERO compiles — page-count canonicalization means slot churn
    is data, never shape."""
    spec = _spec(_name())
    backend = spec.build_backend()
    warmed = backend.warm()
    assert warmed == set(spec.policy.buckets)
    base = backend.compile_count
    loop = DecodeLoop(spec, backend)
    try:
        rng = np.random.RandomState(7)
        seqs = [loop.submit(rng.randint(1, 100, size=rng.randint(1, 4)),
                            max_tokens=int(rng.randint(1, 7)))
                for _ in range(32)]
        for s in seqs:
            r = s.future.result(timeout=60)
            assert r.tokens == _expect(s.prompt, s.max_tokens)
        steps = loop.stats()
        assert steps["compile_count"] == base, \
            "slot churn retraced: %d -> %d compiles" \
            % (base, steps["compile_count"])
        assert loop.occupancy == 0 and loop.pending == 0
    finally:
        loop.close()


def test_exhaustion_queues_not_drops():
    """More sequences than slots: the surplus WAITS in the pending
    queue and every one completes — exhaustion is backpressure, never
    a drop."""
    loop = _loop(spec_kw={"max_batch": 2,
                          "decode": _cfg(page_slots=2, max_tokens=3)})
    try:
        seqs = [loop.submit([i], max_tokens=3) for i in range(6)]
        assert loop.alloc.max_slots == 2
        for i, s in enumerate(seqs):
            r = s.future.result(timeout=30)
            assert r.tokens == _expect([i], 3)
    finally:
        loop.close()


def test_deadline_mid_decode_sheds_and_frees_slot():
    shed = []
    loop = _loop(shed=lambda seq, reason: shed.append(reason))
    try:
        # An effectively endless sequence with a near-instant deadline:
        # the mid-decode check retires the slot and sheds.
        s = loop.submit([1], max_tokens=100000,
                        deadline=time.perf_counter() + 0.05)
        with pytest.raises(DeadlineExceededError):
            s.future.result(timeout=30)
        assert "deadline" in shed
        # The slot came back: a healthy sequence serves right after.
        r = loop.submit([2], max_tokens=2).future.result(timeout=30)
        assert r.tokens == _expect([2], 2)
        assert loop.occupancy == 0
    finally:
        loop.close()


def test_expired_in_queue_sheds_without_slot():
    loop = _loop()
    try:
        s = loop.submit([1], max_tokens=5,
                        deadline=time.perf_counter() - 1.0)
        with pytest.raises(DeadlineExceededError):
            s.future.result(timeout=30)
    finally:
        loop.close()


def test_close_fails_pending_and_active():
    loop = _loop()
    s = loop.submit([1], max_tokens=10 ** 6)
    time.sleep(0.05)
    loop.close(drain=False)
    with pytest.raises(ServiceUnavailableError):
        s.future.result(timeout=30)
    with pytest.raises(ServiceUnavailableError):
        loop.submit([2])


def test_swap_backend_drains_in_flight():
    spec = _spec(_name())
    loop = DecodeLoop(spec, spec.build_backend())
    try:
        a = loop.submit([1], max_tokens=600)
        time.sleep(0.02)
        new_backend = spec.build_backend(params=[_w(2.0)])
        drained = loop.swap_backend(new_backend, 2, drain_timeout=60)
        assert drained
        ra = a.future.result(timeout=30)
        assert ra.generation == 1 and len(ra.tokens) == 600
        rb = loop.submit([5], max_tokens=2).future.result(timeout=30)
        assert rb.generation == 2
        assert loop.stats()["generation"] == 2
    finally:
        loop.close()


# -- gateway integration -----------------------------------------------------

def test_gateway_generate_and_stats():
    gw = ModelGateway()
    name = _name()
    try:
        gw.register(_spec(name))
        r = gw.generate(name, [2, 9], max_tokens=3)
        assert r.tokens == _expect([2, 9], 3)
        assert r.model == name and r.generation == 1
        st = gw.stats()[name]
        assert st["decode"]["slots"] == 8
        assert st["decode"]["occupancy"] == 0
        assert st["decode"]["compile_count"] >= 1
        # Wrong-kind routing is an error both ways.
        with pytest.raises(ValueError):
            gw.submit(name, mx.nd.array(np.zeros((1, 4), np.float32)))
        fname = _name("fn")
        gw.register(ModelSpec(
            fname, fn=lambda w, x: mx.nd.dot(x, w),
            params=[mx.nd.array(np.zeros((4, 2), np.float32))],
            item_shape=(4,), max_batch=4))
        with pytest.raises(ValueError):
            gw.submit_sequence(fname, [1])
    finally:
        gw.shutdown()


def test_gateway_hot_reload_drains_old_generation():
    """In-flight sequences finish on their admit-time generation; the
    swap commits only after the old generation drains; post-swap
    sequences carry the new one."""
    gw = ModelGateway()
    name = _name()
    try:
        gw.register(_spec(name))
        fut = gw.submit_sequence(name, [1], max_tokens=800)
        time.sleep(0.02)
        assert not fut.done(), "sequence finished before the swap began"
        gen = hot_swap(gw, name, params=[_w(3.0)])
        assert gen == 2
        ra = fut.result(timeout=30)
        assert ra.generation == 1 and len(ra.tokens) == 800
        rb = gw.generate(name, [1], max_tokens=2)
        assert rb.generation == 2
    finally:
        gw.shutdown()


def test_gateway_queue_share_caps_decode_queue():
    gw = ModelGateway(max_queue=8)
    name = _name()
    try:
        gw.register(_spec(name, queue_share=0.25, max_batch=1,
                          decode=_cfg(page_slots=1, max_tokens=10 ** 6)))
        # One endless sequence occupies the single slot...
        holder = gw.submit_sequence(name, [1])
        deadline = time.monotonic() + 10
        while gw.stats()[name]["decode"]["occupancy"] < 1:
            assert time.monotonic() < deadline
            time.sleep(0.005)
        # ...then 0.25 * max_queue = 2 sequences may QUEUE; the third
        # sheds at this model's door, far below the global pool bound.
        queued = [gw.submit_sequence(name, [2]) for _ in range(2)]
        with pytest.raises(QueueFullError) as exc:
            gw.submit_sequence(name, [3])
        assert "queue share" in str(exc.value)
        holder.cancel()
        for q in queued:
            q.cancel()
    finally:
        gw.shutdown(drain=False)


def test_gateway_per_model_max_delay_override():
    """A latency-class model flushes its partial batch at ITS delay,
    not the gateway-wide one."""
    gw = ModelGateway(max_delay_ms=400.0)
    fast = _name("fast")
    try:
        gw.register(ModelSpec(
            fast, fn=lambda w, x: mx.nd.dot(x, w),
            params=[mx.nd.array(np.eye(4, dtype=np.float32))],
            item_shape=(4,), max_batch=8, max_delay_ms=2.0))
        x = mx.nd.array(np.ones((1, 4), np.float32))
        gw.predict(fast, x)                 # warm the bucket
        t0 = time.perf_counter()
        gw.predict(fast, x)
        took = time.perf_counter() - t0
        assert took < 0.25, \
            "max_delay_ms=2 override ignored: partial batch waited " \
            "%.0f ms (gateway default is 400)" % (took * 1e3)
    finally:
        gw.shutdown()


def test_decode_metrics_present_and_dropped_on_unregister():
    from mxnet_tpu.telemetry import metrics as tm

    gw = ModelGateway()
    name = _name()
    try:
        gw.register(_spec(name))
        gw.generate(name, [1], max_tokens=2)
        fam = tm.REGISTRY.get("mx_decode_tokens_total")
        assert fam.labels(model=name).value >= 2
        assert tm.REGISTRY.get(
            "mx_decode_steps_total").labels(model=name).value >= 1
        gw.unregister(name)
        assert all(v[0] != name for v, _ in fam.collect()), \
            "unregister left decode series behind"
    finally:
        gw.shutdown()
