"""Autograd tests (reference: tests/python/unittest/test_autograd.py)."""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import nd, autograd


def test_simple_grad():
    x = nd.array([1.0, 2.0, 3.0])
    x.attach_grad()
    with autograd.record():
        y = (x * x).sum()
    y.backward()
    assert np.allclose(x.grad.asnumpy(), [2, 4, 6])


def test_chain():
    x = nd.array([[1.0, 2.0], [3.0, 4.0]])
    x.attach_grad()
    with autograd.record():
        y = nd.exp(x.sum())
    y.backward()
    expect = np.exp(10.0)
    assert np.allclose(x.grad.asnumpy(), expect, rtol=1e-4)


def test_head_grad():
    x = nd.array([1.0, 2.0])
    x.attach_grad()
    with autograd.record():
        y = 3 * x
    y.backward(nd.array([10.0, 100.0]))
    assert np.allclose(x.grad.asnumpy(), [30, 300])


def test_multiple_uses():
    x = nd.array([2.0])
    x.attach_grad()
    with autograd.record():
        y = x * x + x
    y.backward()
    assert np.allclose(x.grad.asnumpy(), [5.0])  # 2x + 1


def test_dot_grad():
    a = nd.array(np.random.rand(3, 4).astype(np.float32))
    b = nd.array(np.random.rand(4, 2).astype(np.float32))
    a.attach_grad()
    b.attach_grad()
    with autograd.record():
        c = nd.dot(a, b).sum()
    c.backward()
    assert np.allclose(a.grad.asnumpy(), b.asnumpy().sum(axis=1)[None, :].repeat(3, 0),
                       atol=1e-5)


def test_grad_req_add():
    x = nd.array([1.0, 2.0])
    x.attach_grad(grad_req="add")
    for _ in range(3):
        with autograd.record():
            y = (2 * x).sum()
        y.backward()
    assert np.allclose(x.grad.asnumpy(), [6, 6])


def test_pause():
    x = nd.array([1.0])
    x.attach_grad()
    with autograd.record():
        y = x * 2
        with autograd.pause():
            z = y * 10  # not recorded
        w = y + 1
    w.backward()
    assert np.allclose(x.grad.asnumpy(), [2.0])


def test_training_modes():
    assert not autograd.is_training()
    assert not autograd.is_recording()
    with autograd.record():
        assert autograd.is_recording()
        assert autograd.is_training()
    with autograd.record(train_mode=False):
        assert not autograd.is_training()
    with autograd.train_mode():
        assert autograd.is_training()


def test_grad_function():
    x = nd.array([1.0, 2.0, 3.0])
    with autograd.record():
        y = (x * x).sum()
    grads = autograd.grad([y], [x])
    assert np.allclose(grads[0].asnumpy(), [2, 4, 6])


def test_mark_variables():
    x = nd.array([4.0])
    g = nd.zeros((1,))
    autograd.mark_variables([x], [g])
    with autograd.record():
        y = nd.sqrt(x)
    y.backward()
    assert np.allclose(x.grad.asnumpy(), [0.25])


def test_mutation_does_not_corrupt_tape():
    x = nd.array([2.0])
    x.attach_grad()
    with autograd.record():
        y = x * x
    x += 100  # mutate after recording — tape must keep the snapshot
    y.backward()
    assert np.allclose(x.grad.asnumpy(), [4.0])


def test_custom_function():
    class Sigmoid(autograd.Function):
        def forward(self, x):
            y = 1.0 / (1.0 + nd.exp(-x))
            self._saved = y
            return y

        def backward(self, dy):
            y = self._saved
            return dy * y * (1 - y)

    f = Sigmoid()
    x = nd.array([0.0, 1.0])
    x.attach_grad()
    with autograd.record():
        y = f(x)
        z = y.sum()
    z.backward()
    sig = 1 / (1 + np.exp(-x.asnumpy()))
    assert np.allclose(x.grad.asnumpy(), sig * (1 - sig), atol=1e-5)


def test_softmax_output_grad():
    data = nd.array(np.random.randn(4, 5).astype(np.float32))
    label = nd.array([0, 1, 2, 3], dtype="float32")
    data.attach_grad()
    with autograd.record():
        out = nd.SoftmaxOutput(data, label)
    out.backward()
    sm = np.exp(data.asnumpy())
    sm /= sm.sum(1, keepdims=True)
    expect = sm.copy()
    expect[np.arange(4), [0, 1, 2, 3]] -= 1
    assert np.allclose(data.grad.asnumpy(), expect, atol=1e-5)


def test_detach():
    x = nd.array([3.0])
    x.attach_grad()
    with autograd.record():
        y = x * x
        z = y.detach() * x
    z.backward()
    assert np.allclose(x.grad.asnumpy(), [9.0])  # only d(9*x)/dx
