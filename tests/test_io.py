"""Tests for io/recordio/metric (reference: tests/python/unittest/test_io.py,
test_metric.py)."""
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import recordio


def test_ndarray_iter():
    data = np.arange(1000).reshape((100, 10)).astype(np.float32)
    label = np.arange(100).astype(np.float32)
    it = mx.io.NDArrayIter(data, label, batch_size=32, shuffle=False,
                           last_batch_handle="pad")
    batches = list(it)
    assert len(batches) == 4
    assert batches[0].data[0].shape == (32, 10)
    assert batches[-1].pad == 28
    np.testing.assert_allclose(batches[0].data[0].asnumpy(), data[:32])
    np.testing.assert_allclose(batches[0].label[0].asnumpy(), label[:32])
    # reset and re-iterate
    it.reset()
    assert len(list(it)) == 4


def test_ndarray_iter_discard_shuffle():
    data = np.random.rand(100, 3).astype(np.float32)
    it = mx.io.NDArrayIter(data, batch_size=30, shuffle=True,
                           last_batch_handle="discard")
    batches = list(it)
    assert len(batches) == 3
    assert all(b.pad == 0 for b in batches)


def test_ndarray_iter_dict_input():
    it = mx.io.NDArrayIter({"a": np.zeros((10, 2)), "b": np.ones((10, 3))},
                           batch_size=5)
    b = next(it)
    names = sorted(d.name for d in b.provide_data)
    assert names == ["a", "b"]


def test_resize_iter():
    data = np.zeros((20, 2), dtype=np.float32)
    inner = mx.io.NDArrayIter(data, batch_size=5)
    it = mx.io.ResizeIter(inner, 10)
    assert len(list(it)) == 10


def test_prefetching_iter():
    data = np.arange(60).reshape((20, 3)).astype(np.float32)
    inner = mx.io.NDArrayIter(data, batch_size=5)
    it = mx.io.PrefetchingIter(inner)
    batches = list(it)
    assert len(batches) == 4
    it.reset()
    assert len(list(it)) == 4


class _BoomIter(mx.io.DataIter):
    """Yields one good batch, then raises — the decode-failure shape."""

    def __init__(self, inner, boom_at=1):
        super().__init__(inner.batch_size)
        self.inner = inner
        self.boom_at = boom_at
        self.count = 0

    @property
    def provide_data(self):
        return self.inner.provide_data

    @property
    def provide_label(self):
        return self.inner.provide_label

    def reset(self):
        self.count = 0
        self.inner.reset()

    def next(self):
        self.count += 1
        if self.count - 1 == self.boom_at:   # raise once, then recover
            self.inner.next()   # record consumed, then decode failed
            raise ValueError("decode exploded")
        return self.inner.next()


def test_prefetching_iter_worker_error_reraises_not_hangs():
    """Regression: prefetch_func caught only StopIteration, so any
    decode exception killed the worker thread and next() blocked on
    data_ready forever. The error must surface in the consumer."""
    inner = mx.io.NDArrayIter(np.zeros((20, 3), np.float32), batch_size=5)
    with mx.io.PrefetchingIter(_BoomIter(inner)) as it:
        next(it)                               # the good batch
        with pytest.raises(ValueError, match="decode exploded"):
            next(it)                           # re-raised, not a hang
        # the iterator recovers: worker keeps producing after the error
        assert next(it) is not None


def test_prefetching_iter_close_idempotent_and_context_manager():
    inner = mx.io.NDArrayIter(np.zeros((20, 3), np.float32), batch_size=5)
    with mx.io.PrefetchingIter(inner) as it:
        next(it)
    assert not it.started
    for t in it.prefetch_threads:
        assert not t.is_alive()
    it.close()                                 # idempotent
    it.close()
    with pytest.raises(StopIteration):         # never a stale batch or
        next(it)                               # an unfillable wait()
    with pytest.raises(RuntimeError, match="closed"):
        it.reset()


class _SlowIter(mx.io.DataIter):
    """Takes a while per batch — close() lands mid-produce."""

    def __init__(self, inner, delay=0.15):
        super().__init__(inner.batch_size)
        self.inner = inner
        self.delay = delay

    @property
    def provide_data(self):
        return self.inner.provide_data

    @property
    def provide_label(self):
        return self.inner.provide_label

    def reset(self):
        self.inner.reset()

    def next(self):
        import time

        time.sleep(self.delay)
        return self.inner.next()


def test_prefetching_iter_close_mid_produce_joins_worker():
    """Regression: a worker mid-produce clears data_taken on its way
    back to wait(), clobbering a one-shot close() set() — the thread
    then leaked forever. close() must keep signalling until the worker
    exits."""
    inner = mx.io.NDArrayIter(np.zeros((20, 3), np.float32), batch_size=5)
    it = mx.io.PrefetchingIter(_SlowIter(inner))
    it.close(timeout=5.0)                      # immediately: mid-produce
    for t in it.prefetch_threads:
        assert not t.is_alive(), "worker leaked past close()"


def test_prefetching_iter_error_keeps_multi_iter_streams_aligned():
    """After one sub-iterator errors, EVERY sub-iterator's slot is
    recycled — otherwise stream i's batch k+1 pairs with peer streams'
    stale batch k forever."""
    a = np.arange(20, dtype=np.float32).reshape(20, 1)
    good = mx.io.NDArrayIter(a, batch_size=5, data_name="g")
    flaky = _BoomIter(mx.io.NDArrayIter(a + 100, batch_size=5,
                                        data_name="f"), boom_at=1)
    with mx.io.PrefetchingIter([flaky, good]) as it:
        b0 = next(it)
        assert float(b0.data[0].asnumpy()[0, 0]) == 100.0   # flaky k=0
        assert float(b0.data[1].asnumpy()[0, 0]) == 0.0     # good  k=0
        with pytest.raises(ValueError, match="decode exploded"):
            next(it)
        b2 = next(it)       # round k=1 is consumed by the error on BOTH
        assert float(b2.data[0].asnumpy()[0, 0]) == 110.0   # flaky k=2
        assert float(b2.data[1].asnumpy()[0, 0]) == 10.0    # good  k=2


def test_prefetching_iter_both_workers_error_one_raise_no_stale():
    """When BOTH sub-iterators error in the same round, one exception
    surfaces and the round is consumed — no stale second error raised a
    batch late, no silently dropped good batch after it."""
    a = np.arange(20, dtype=np.float32).reshape(20, 1)
    f1 = _BoomIter(mx.io.NDArrayIter(a, batch_size=5, data_name="x"),
                   boom_at=1)
    f2 = _BoomIter(mx.io.NDArrayIter(a + 100, batch_size=5,
                                     data_name="y"), boom_at=1)
    with mx.io.PrefetchingIter([f1, f2]) as it:
        next(it)                                   # round 0
        with pytest.raises(ValueError, match="decode exploded"):
            next(it)                               # round 1: ONE raise
        b2 = next(it)                              # round 2, not stale
        assert float(b2.data[0].asnumpy()[0, 0]) == 10.0
        assert float(b2.data[1].asnumpy()[0, 0]) == 110.0


def test_recordio_roundtrip(tmp_path):
    path = str(tmp_path / "test.rec")
    writer = recordio.MXRecordIO(path, "w")
    for i in range(5):
        writer.write(b"record-%d" % i)
    writer.close()
    reader = recordio.MXRecordIO(path, "r")
    for i in range(5):
        assert reader.read() == b"record-%d" % i
    assert reader.read() is None
    reader.close()


def test_indexed_recordio(tmp_path):
    path = str(tmp_path / "test.rec")
    idx_path = str(tmp_path / "test.idx")
    writer = recordio.MXIndexedRecordIO(idx_path, path, "w")
    for i in range(10):
        writer.write_idx(i, b"rec%d" % i)
    writer.close()
    reader = recordio.MXIndexedRecordIO(idx_path, path, "r")
    assert reader.keys == list(range(10))
    assert reader.read_idx(7) == b"rec7"
    assert reader.read_idx(2) == b"rec2"
    reader.close()


def test_recordio_pack_unpack():
    header = recordio.IRHeader(0, 3.0, 7, 0)
    s = recordio.pack(header, b"payload")
    h2, payload = recordio.unpack(s)
    assert h2.label == 3.0 and h2.id == 7 and payload == b"payload"
    # vector label
    header = recordio.IRHeader(0, np.array([1.0, 2.0], dtype=np.float32), 1, 0)
    s = recordio.pack(header, b"xy")
    h2, payload = recordio.unpack(s)
    np.testing.assert_allclose(h2.label, [1.0, 2.0])
    assert payload == b"xy"


def test_csv_iter(tmp_path):
    data = np.random.rand(20, 4).astype(np.float32)
    label = np.arange(20, dtype=np.float32).reshape(20, 1)
    data_path = str(tmp_path / "data.csv")
    label_path = str(tmp_path / "label.csv")
    np.savetxt(data_path, data, delimiter=",")
    np.savetxt(label_path, label, delimiter=",")
    it = mx.io.CSVIter(data_csv=data_path, data_shape=(4,),
                       label_csv=label_path, batch_size=4)
    b = next(it)
    np.testing.assert_allclose(b.data[0].asnumpy(), data[:4], rtol=1e-5)


def test_libsvm_iter(tmp_path):
    path = str(tmp_path / "data.libsvm")
    with open(path, "w") as f:
        f.write("1 0:1.5 3:2.0\n0 1:1.0\n1 2:3.0\n")
    it = mx.io.LibSVMIter(data_libsvm=path, data_shape=(4,), batch_size=3)
    b = next(it)
    dense = b.data[0].asnumpy() if hasattr(b.data[0], "asnumpy") else b.data[0]
    np.testing.assert_allclose(np.asarray(dense)[0], [1.5, 0, 0, 2.0])


def test_metric_accuracy():
    m = mx.metric.create("acc")
    m.update([mx.nd.array([1, 0, 1])],
             [mx.nd.array([[0.2, 0.8], [0.9, 0.1], [0.3, 0.7]])])
    assert m.get()[1] == 1.0
    m.reset()
    m.update([mx.nd.array([0, 0])], [mx.nd.array([[0.2, 0.8], [0.9, 0.1]])])
    assert m.get()[1] == 0.5


def test_metric_topk():
    m = mx.metric.TopKAccuracy(top_k=2)
    pred = mx.nd.array([[0.1, 0.2, 0.7], [0.6, 0.3, 0.1]])
    m.update([mx.nd.array([1, 2])], [pred])
    assert m.get()[1] == 0.5


def test_metric_composite_and_regression():
    m = mx.metric.create(["acc", "mse", "mae"])
    label = mx.nd.array([1, 0])
    pred = mx.nd.array([[0.0, 1.0], [1.0, 0.0]])
    # Accuracy sees argmax; MSE/MAE see raw values vs labels broadcast.
    m.metrics[0].update([label], [pred])
    names, values = m.get()
    assert "accuracy" in names[0]


def test_metric_perplexity():
    m = mx.metric.Perplexity(ignore_label=None)
    pred = mx.nd.array([[1.0, 0.0], [0.0, 1.0]])
    m.update([mx.nd.array([0, 1])], [pred])
    assert abs(m.get()[1] - 1.0) < 1e-5


def test_metric_f1():
    m = mx.metric.F1()
    m.update([mx.nd.array([1, 0, 1, 1])],
             [mx.nd.array([[0.1, 0.9], [0.8, 0.2], [0.3, 0.7], [0.6, 0.4]])])
    assert 0 < m.get()[1] <= 1.0


def test_custom_metric():
    m = mx.metric.create(lambda label, pred: float(np.abs(label - pred).mean()))
    m.update([mx.nd.array([1.0])], [mx.nd.array([0.5])])
    assert abs(m.get()[1] - 0.5) < 1e-6


def _make_mnist(tmp_path, n=50):
    """Synthetic IDX files (iter_mnist.cc format); labels are unique so
    coverage is checkable through the label stream."""
    import struct

    images = (np.random.rand(n, 28, 28) * 255).astype(np.uint8)
    labels = np.arange(n, dtype=np.uint8)
    img_path = str(tmp_path / "images-idx3-ubyte")
    lbl_path = str(tmp_path / "labels-idx1-ubyte")
    with open(img_path, "wb") as f:
        f.write(struct.pack(">HBB", 0, 8, 3))
        f.write(struct.pack(">III", n, 28, 28))
        f.write(images.tobytes())
    with open(lbl_path, "wb") as f:
        f.write(struct.pack(">HBB", 0, 8, 1))
        f.write(struct.pack(">I", n))
        f.write(labels.tobytes())
    return img_path, lbl_path, labels


def test_mnist_iter_synthetic(tmp_path):
    """MNISTIter over synthetic IDX files (iter_mnist.cc format)."""
    img_path, lbl_path, _ = _make_mnist(tmp_path)
    it = mx.io.MNISTIter(image=img_path, label=lbl_path, batch_size=10,
                         shuffle=False, flat=True)
    b = next(it)
    assert b.data[0].shape == (10, 784)
    assert b.label[0].shape == (10,)


def test_mnist_iter_num_parts_equal_and_total(tmp_path):
    """num_parts shards are equal-size wrap-tail (data.sharding): with
    50 samples over 3 parts every part sees 17 (not 16 with 2 records
    silently unreachable) and the union covers every sample."""
    img_path, lbl_path, labels = _make_mnist(tmp_path)
    seen = []
    for part in range(3):
        it = mx.io.MNISTIter(image=img_path, label=lbl_path, batch_size=17,
                             shuffle=False, flat=True, num_parts=3,
                             part_index=part)
        got = []
        for b in it:
            got.extend(np.asarray(b.label[0].asnumpy()).tolist())
        assert len(got) == 17                  # ceil(50/3), every part
        # each part is the contiguous wrap-tail slice — deterministic
        want = [float(labels[(part * 17 + j) % 50]) for j in range(17)]
        assert got == want, "part %d is not the wrap-tail slice" % part
        seen.extend(got)
    assert set(seen) == set(float(l) for l in labels)   # total coverage
    assert len(seen) == 51                              # one wrap dup
