"""Tests for io/recordio/metric (reference: tests/python/unittest/test_io.py,
test_metric.py)."""
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import recordio


def test_ndarray_iter():
    data = np.arange(1000).reshape((100, 10)).astype(np.float32)
    label = np.arange(100).astype(np.float32)
    it = mx.io.NDArrayIter(data, label, batch_size=32, shuffle=False,
                           last_batch_handle="pad")
    batches = list(it)
    assert len(batches) == 4
    assert batches[0].data[0].shape == (32, 10)
    assert batches[-1].pad == 28
    np.testing.assert_allclose(batches[0].data[0].asnumpy(), data[:32])
    np.testing.assert_allclose(batches[0].label[0].asnumpy(), label[:32])
    # reset and re-iterate
    it.reset()
    assert len(list(it)) == 4


def test_ndarray_iter_discard_shuffle():
    data = np.random.rand(100, 3).astype(np.float32)
    it = mx.io.NDArrayIter(data, batch_size=30, shuffle=True,
                           last_batch_handle="discard")
    batches = list(it)
    assert len(batches) == 3
    assert all(b.pad == 0 for b in batches)


def test_ndarray_iter_dict_input():
    it = mx.io.NDArrayIter({"a": np.zeros((10, 2)), "b": np.ones((10, 3))},
                           batch_size=5)
    b = next(it)
    names = sorted(d.name for d in b.provide_data)
    assert names == ["a", "b"]


def test_resize_iter():
    data = np.zeros((20, 2), dtype=np.float32)
    inner = mx.io.NDArrayIter(data, batch_size=5)
    it = mx.io.ResizeIter(inner, 10)
    assert len(list(it)) == 10


def test_prefetching_iter():
    data = np.arange(60).reshape((20, 3)).astype(np.float32)
    inner = mx.io.NDArrayIter(data, batch_size=5)
    it = mx.io.PrefetchingIter(inner)
    batches = list(it)
    assert len(batches) == 4
    it.reset()
    assert len(list(it)) == 4


def test_recordio_roundtrip(tmp_path):
    path = str(tmp_path / "test.rec")
    writer = recordio.MXRecordIO(path, "w")
    for i in range(5):
        writer.write(b"record-%d" % i)
    writer.close()
    reader = recordio.MXRecordIO(path, "r")
    for i in range(5):
        assert reader.read() == b"record-%d" % i
    assert reader.read() is None
    reader.close()


def test_indexed_recordio(tmp_path):
    path = str(tmp_path / "test.rec")
    idx_path = str(tmp_path / "test.idx")
    writer = recordio.MXIndexedRecordIO(idx_path, path, "w")
    for i in range(10):
        writer.write_idx(i, b"rec%d" % i)
    writer.close()
    reader = recordio.MXIndexedRecordIO(idx_path, path, "r")
    assert reader.keys == list(range(10))
    assert reader.read_idx(7) == b"rec7"
    assert reader.read_idx(2) == b"rec2"
    reader.close()


def test_recordio_pack_unpack():
    header = recordio.IRHeader(0, 3.0, 7, 0)
    s = recordio.pack(header, b"payload")
    h2, payload = recordio.unpack(s)
    assert h2.label == 3.0 and h2.id == 7 and payload == b"payload"
    # vector label
    header = recordio.IRHeader(0, np.array([1.0, 2.0], dtype=np.float32), 1, 0)
    s = recordio.pack(header, b"xy")
    h2, payload = recordio.unpack(s)
    np.testing.assert_allclose(h2.label, [1.0, 2.0])
    assert payload == b"xy"


def test_csv_iter(tmp_path):
    data = np.random.rand(20, 4).astype(np.float32)
    label = np.arange(20, dtype=np.float32).reshape(20, 1)
    data_path = str(tmp_path / "data.csv")
    label_path = str(tmp_path / "label.csv")
    np.savetxt(data_path, data, delimiter=",")
    np.savetxt(label_path, label, delimiter=",")
    it = mx.io.CSVIter(data_csv=data_path, data_shape=(4,),
                       label_csv=label_path, batch_size=4)
    b = next(it)
    np.testing.assert_allclose(b.data[0].asnumpy(), data[:4], rtol=1e-5)


def test_libsvm_iter(tmp_path):
    path = str(tmp_path / "data.libsvm")
    with open(path, "w") as f:
        f.write("1 0:1.5 3:2.0\n0 1:1.0\n1 2:3.0\n")
    it = mx.io.LibSVMIter(data_libsvm=path, data_shape=(4,), batch_size=3)
    b = next(it)
    dense = b.data[0].asnumpy() if hasattr(b.data[0], "asnumpy") else b.data[0]
    np.testing.assert_allclose(np.asarray(dense)[0], [1.5, 0, 0, 2.0])


def test_metric_accuracy():
    m = mx.metric.create("acc")
    m.update([mx.nd.array([1, 0, 1])],
             [mx.nd.array([[0.2, 0.8], [0.9, 0.1], [0.3, 0.7]])])
    assert m.get()[1] == 1.0
    m.reset()
    m.update([mx.nd.array([0, 0])], [mx.nd.array([[0.2, 0.8], [0.9, 0.1]])])
    assert m.get()[1] == 0.5


def test_metric_topk():
    m = mx.metric.TopKAccuracy(top_k=2)
    pred = mx.nd.array([[0.1, 0.2, 0.7], [0.6, 0.3, 0.1]])
    m.update([mx.nd.array([1, 2])], [pred])
    assert m.get()[1] == 0.5


def test_metric_composite_and_regression():
    m = mx.metric.create(["acc", "mse", "mae"])
    label = mx.nd.array([1, 0])
    pred = mx.nd.array([[0.0, 1.0], [1.0, 0.0]])
    # Accuracy sees argmax; MSE/MAE see raw values vs labels broadcast.
    m.metrics[0].update([label], [pred])
    names, values = m.get()
    assert "accuracy" in names[0]


def test_metric_perplexity():
    m = mx.metric.Perplexity(ignore_label=None)
    pred = mx.nd.array([[1.0, 0.0], [0.0, 1.0]])
    m.update([mx.nd.array([0, 1])], [pred])
    assert abs(m.get()[1] - 1.0) < 1e-5


def test_metric_f1():
    m = mx.metric.F1()
    m.update([mx.nd.array([1, 0, 1, 1])],
             [mx.nd.array([[0.1, 0.9], [0.8, 0.2], [0.3, 0.7], [0.6, 0.4]])])
    assert 0 < m.get()[1] <= 1.0


def test_custom_metric():
    m = mx.metric.create(lambda label, pred: float(np.abs(label - pred).mean()))
    m.update([mx.nd.array([1.0])], [mx.nd.array([0.5])])
    assert abs(m.get()[1] - 0.5) < 1e-6


def test_mnist_iter_synthetic(tmp_path):
    """MNISTIter over synthetic IDX files (iter_mnist.cc format)."""
    import struct

    images = (np.random.rand(50, 28, 28) * 255).astype(np.uint8)
    labels = np.random.randint(0, 10, 50).astype(np.uint8)
    img_path = str(tmp_path / "images-idx3-ubyte")
    lbl_path = str(tmp_path / "labels-idx1-ubyte")
    with open(img_path, "wb") as f:
        f.write(struct.pack(">HBB", 0, 8, 3))
        f.write(struct.pack(">III", 50, 28, 28))
        f.write(images.tobytes())
    with open(lbl_path, "wb") as f:
        f.write(struct.pack(">HBB", 0, 8, 1))
        f.write(struct.pack(">I", 50))
        f.write(labels.tobytes())
    it = mx.io.MNISTIter(image=img_path, label=lbl_path, batch_size=10,
                         shuffle=False, flat=True)
    b = next(it)
    assert b.data[0].shape == (10, 784)
    assert b.label[0].shape == (10,)
