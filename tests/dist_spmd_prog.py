"""Worker program for the multi-host SPMD equivalence test.

Launched by tools/launch.py with ``-s 0`` (pure SPMD: N worker
processes, no parameter server), or run directly as the 1-process
reference. Either way it trains the same tiny model as
tests/test_parallel.py's convergence case for a fixed number of steps
over an 8-device 'dp' mesh — 8 local devices single-process, or
N processes × (8/N) local devices each after `dist.initialize` — and
writes the final params + optimizer state + loss trace to an .npz.

The single-process and multi-process runs must agree (the reference's
dist_sync contract: tests/nightly/dist_sync_kvstore.py asserts pushed
gradients aggregate identically whatever the worker count).

Usage: dist_spmd_prog.py OUT.npz [steps]
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from mxnet_tpu.parallel import dist

# Pin CPU + per-process virtual device count before any backend touch.
_, nproc, _ = dist.env_spec()
nproc = nproc or 1
if 8 % nproc:
    sys.exit("worker count %d must divide the 8-device mesh" % nproc)
dist.initialize(local_device_count=8 // nproc, platform="cpu")

import jax  # noqa: E402  (backend config above must come first)

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import gluon  # noqa: E402
from mxnet_tpu.parallel import make_mesh, TrainStep  # noqa: E402


def main():
    out_path = sys.argv[1]
    steps = int(sys.argv[2]) if len(sys.argv) > 2 else 6

    assert len(jax.devices()) == 8, jax.devices()
    mesh = make_mesh({"dp": 8})

    mx.random.seed(42)
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(32, activation="relu", in_units=10))
    net.add(gluon.nn.Dense(2, in_units=32))
    net.initialize()

    # deterministic_reduction: gradient aggregation in explicit shard
    # order, so 1-process and N-process runs agree bit-for-bit (the
    # transport — shared memory vs gloo/DCN — stops mattering).
    step = TrainStep(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                     optimizer="adam",
                     optimizer_params={"learning_rate": 0.05,
                                       "wd": 1e-4},
                     mesh=mesh, deterministic_reduction=True)

    rng = np.random.RandomState(0)
    losses = []
    for _ in range(steps):
        X = rng.randn(64, 10).astype(np.float32)
        w = rng.randn(10).astype(np.float32)
        Y = (X @ w > 0).astype(np.float32)
        lo, hi = dist.local_slice(64)
        loss = step(X[lo:hi], Y[lo:hi])
        losses.append(float(np.asarray(jax.device_get(loss))))

    # Multi-host checkpoint round-trip: rank 0 writes, every rank
    # restores, and the restored state must equal the live state.
    ckpt = out_path + ".ckpt"
    step.save_checkpoint(ckpt)
    before = step.state_to_host()
    step.load_checkpoint(ckpt)
    after = step.state_to_host()
    for d1, d2 in zip(before, after):
        for k in d1:
            v1, v2 = d1[k], d2[k]
            if isinstance(v1, tuple):
                assert all(np.array_equal(a, b)
                           for a, b in zip(v1, v2)), k
            else:
                assert np.array_equal(v1, v2), k

    params, opt_state, aux = step.state_to_host()
    if dist.rank() == 0:
        flat = {"loss": np.asarray(losses)}
        for n, v in params.items():
            flat["param:" + n] = v
        for n, st in opt_state.items():
            for i, s in enumerate(st):
                flat["opt:%s:%d" % (n, i)] = s
        for n, v in aux.items():
            flat["aux:" + n] = v
        np.savez(out_path, **flat)
    dist.barrier("dist_spmd_done")


if __name__ == "__main__":
    main()
