"""Subgraph partition extension point (reference
src/operator/subgraph/subgraph_property.h) and contrib NCE loss
(reference example/nce-loss)."""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, subgraph


def _dense_relu_sym():
    data = mx.sym.var("data")
    fc = mx.sym.FullyConnected(data, num_hidden=8, name="fc")
    act = mx.sym.Activation(fc, act_type="relu", name="act")
    return mx.sym.FullyConnected(act, num_hidden=3, name="out")


class FuseDenseRelu(subgraph.SubgraphProperty):
    """Fuse Activation(FullyConnected) into one custom region."""

    def __init__(self, with_fn=True):
        self.calls = []
        self._with_fn = with_fn

    def select(self, node):
        return node._op == "Activation"

    def select_input(self, node, inp):
        return inp._op == "FullyConnected"

    def create_fn(self, sub_sym, arg_names):
        if not self._with_fn:
            return None
        calls = self.calls

        def fused(x, w, b):
            import jax.numpy as jnp

            calls.append(arg_names)
            return jnp.maximum(x @ w.T + b, 0.0)

        return fused


def _run_sym(sym, x, params):
    args = dict(params)
    args["data"] = mx.nd.array(x)
    ex = sym.bind(args={k: (v if isinstance(v, mx.nd.NDArray)
                            else mx.nd.array(v)) for k, v in args.items()},
                  grad_req="null")
    return ex.forward(is_train=False)[0].asnumpy()


def _init_params(sym, x):
    rng = np.random.RandomState(0)
    shapes, _, _ = sym.infer_shape(data=x.shape)
    return {n: rng.randn(*s).astype(np.float32) * 0.3
            for n, s in zip(sym.list_arguments(), shapes)
            if n != "data"}


def test_partition_custom_fn_runs_and_matches():
    sym = _dense_relu_sym()
    x = np.random.RandomState(1).rand(4, 6).astype(np.float32)
    params = _init_params(sym, x)
    want = _run_sym(sym, x, params)

    prop = subgraph.register_backend("dense_relu_fused", FuseDenseRelu())
    psym = subgraph.partition(sym, "dense_relu_fused")
    got = _run_sym(psym, x, params)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)
    assert prop.calls, "custom fused fn never ran"
    # the fragment saw (data-in, weight, bias)
    assert len(prop.calls[0]) == 3
    # graph structure: an actual _subgraph node exists
    assert any(n._op == "_subgraph" for n in psym._topo())


def test_partition_fallback_evaluates_subdag():
    sym = _dense_relu_sym()
    x = np.random.RandomState(2).rand(5, 6).astype(np.float32)
    params = _init_params(sym, x)
    want = _run_sym(sym, x, params)
    psym = subgraph.partition(sym, FuseDenseRelu(with_fn=False))
    got = _run_sym(psym, x, params)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)
    assert any(n._op == "_subgraph" for n in psym._topo())


def test_partition_exposes_external_consumers_as_outputs():
    """A producer consumed outside the fragment still fuses — its value
    becomes a second OUTPUT of the subgraph node (reference
    SubgraphSelector connected sets are multi-output; VERDICT r4 #7 —
    the old implementation refused to fuse here)."""
    data = mx.sym.var("data")
    fc = mx.sym.FullyConnected(data, num_hidden=4, name="fc")
    act = mx.sym.Activation(fc, act_type="relu", name="act")
    # fc's value is ALSO used directly: the fragment must expose it.
    both = act + fc
    psym = subgraph.partition(both, FuseDenseRelu(with_fn=False))
    subs = {n._uid: n for n in psym._topo() if n._op == "_subgraph"}
    assert len(subs) == 1, subs
    assert next(iter(subs.values()))._num_outputs == 2
    x = np.random.RandomState(3).rand(2, 6).astype(np.float32)
    params = _init_params(both, x)
    np.testing.assert_allclose(_run_sym(psym, x, params),
                               _run_sym(both, x, params), rtol=1e-5)


def test_partition_pallas_backend():
    """The rtc story: a Pallas kernel (interpret mode on cpu) as the
    fused region's executor."""
    import functools

    class PallasDenseRelu(FuseDenseRelu):
        def create_fn(self, sub_sym, arg_names):
            from mxnet_tpu import rtc

            def relu_kernel(x_ref, o_ref):
                o_ref[:] = jnp_max(x_ref[:], 0.0)

            import jax.numpy as jnp

            def jnp_max(a, b):
                return jnp.maximum(a, b)

            mod = rtc.PallasModule(fused_relu=relu_kernel)
            k = mod.get_kernel("fused_relu")

            def fused(x, w, b):
                from mxnet_tpu.ndarray.ndarray import NDArray

                pre = x @ w.T + b           # MXU matmul
                return k.launch([NDArray(pre)])._data

            return fused

    sym = _dense_relu_sym()
    x = np.random.RandomState(4).rand(4, 6).astype(np.float32)
    params = _init_params(sym, x)
    want = _run_sym(sym, x, params)
    got = _run_sym(subgraph.partition(sym, PallasDenseRelu()), x, params)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


# -- NCE ----------------------------------------------------------------------

def test_nce_loss_matches_manual():
    from mxnet_tpu.gluon.contrib.loss import NCELoss

    rng = np.random.RandomState(5)
    B, D, V, K = 6, 8, 40, 4
    embed = rng.randn(B, D).astype(np.float32)
    weight = (rng.randn(V, D) * 0.2).astype(np.float32)
    bias = (rng.randn(V) * 0.1).astype(np.float32)
    label = rng.randint(0, V, B).astype(np.float32)
    noise = rng.randint(0, V, (B, K)).astype(np.float32)

    loss = NCELoss(num_sampled=K, num_classes=V)
    got = loss(mx.nd.array(embed), mx.nd.array(weight),
               mx.nd.array(bias), mx.nd.array(label),
               mx.nd.array(noise)).asnumpy()

    def sigm(v):
        return 1.0 / (1.0 + np.exp(-v))

    want = np.zeros(B, np.float32)
    for i in range(B):
        st = embed[i] @ weight[int(label[i])] + bias[int(label[i])]
        want[i] = -np.log(sigm(st))
        for j in range(K):
            sn = embed[i] @ weight[int(noise[i, j])] + \
                bias[int(noise[i, j])]
            want[i] -= np.log(1 - sigm(sn))
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_nce_trains_large_vocab_classifier():
    """NCE-trained output embedding separates true classes from noise
    without ever computing a |V|-wide softmax."""
    from mxnet_tpu.gluon.contrib.loss import NCELoss

    rng = np.random.RandomState(6)
    B, D, V, K = 32, 16, 100, 8
    # each class has a prototype; embeddings near prototype => class
    protos = rng.randn(V, D).astype(np.float32)
    loss_fn = NCELoss(num_sampled=K, num_classes=V)

    class Model(gluon.HybridBlock):
        def __init__(self):
            super().__init__()
            self.weight = self.params.get("out_weight", shape=(V, D))
            self.bias = self.params.get("out_bias", shape=(V,))

        def hybrid_forward(self, F, embed, label, noise, weight, bias):
            return loss_fn(embed, weight, bias, label, noise)

    model = Model()
    model.initialize(mx.init.Normal(0.1))
    trainer = gluon.Trainer(model.collect_params(), "adam",
                            {"learning_rate": 0.05})
    first = last = None
    for step in range(60):
        y = rng.randint(0, V, B)
        x = protos[y] + 0.1 * rng.randn(B, D).astype(np.float32)
        noise = rng.randint(0, V, (B, K))
        with autograd.record():
            l = model(mx.nd.array(x), mx.nd.array(y.astype(np.float32)),
                      mx.nd.array(noise.astype(np.float32))).mean()
        l.backward()
        trainer.step(B)
        last = float(l.asnumpy().ravel()[0])
        if first is None:
            first = last
    assert last < first * 0.6, "NCE loss %.4f -> %.4f" % (first, last)


def test_env_subgraph_backend_autopartitions():
    """MXNET_SUBGRAPH_BACKEND partitions at bind (reference
    build_subgraph env pass)."""
    import os

    prop = subgraph.register_backend("autotest_fuse", FuseDenseRelu())
    sym = _dense_relu_sym()
    x = np.random.RandomState(7).rand(3, 6).astype(np.float32)
    params = _init_params(sym, x)
    want = _run_sym(sym, x, params)
    os.environ["MXNET_SUBGRAPH_BACKEND"] = "autotest_fuse"
    try:
        got = _run_sym(sym, x, params)   # bind partitions internally
    finally:
        del os.environ["MXNET_SUBGRAPH_BACKEND"]
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)
    assert prop.calls, "env-selected backend never ran"


def test_partition_preserves_multi_output_views():
    """Multi-output views (shared producer uid, distinct out_index)
    upstream of a fused fragment must keep their slots."""
    data = mx.sym.var("data")
    a, bpart = mx.sym.split(data, num_outputs=2, axis=1)
    fc = mx.sym.FullyConnected(a, num_hidden=4, name="fc")
    act = mx.sym.Activation(fc, act_type="relu", name="act")
    out = act + mx.sym.sum(bpart, axis=1, keepdims=True)
    x = np.random.RandomState(8).rand(3, 6).astype(np.float32)
    params = _init_params(out, x)
    want = _run_sym(out, x, params)
    psym = subgraph.partition(out, FuseDenseRelu())
    assert any(n._op == "_subgraph" for n in psym._topo())
    got = _run_sym(psym, x, params)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_partition_shape_inference_through_subgraph():
    sym = _dense_relu_sym()
    psym = subgraph.partition(sym, FuseDenseRelu(with_fn=False))
    shapes, out_shapes, _ = psym.infer_shape(data=(4, 6))
    assert out_shapes[0] == (4, 3)


def test_partition_excludes_batchnorm_fragments():
    """Aux-consuming ops never join a fragment (their moving-stat
    writes would be dropped)."""
    class GreedyFuse(FuseDenseRelu):
        def select_input(self, node, inp):
            return True               # try to swallow everything

    data = mx.sym.var("data")
    fc = mx.sym.FullyConnected(data, num_hidden=4, name="fc")
    bn = mx.sym.BatchNorm(fc, name="bn")
    act = mx.sym.Activation(bn, act_type="relu", name="act")
    psym = subgraph.partition(act, GreedyFuse(with_fn=False))
    for n in psym._topo():
        if n._op == "_subgraph":
            inner_ops = {m._op for m in n._sub_sym._topo()}
            assert "BatchNorm" not in inner_ops


def test_partition_select_output_growth():
    """Fragments grow DOWNWARD through select_output (reference
    SubgraphSelector::SelectOutput) — seed at FullyConnected, absorb the
    consumer chain relu -> *2."""

    class GrowDown(subgraph.SubgraphProperty):
        def select(self, node):
            return node._op == "FullyConnected"

        def select_output(self, node, output_node):
            return output_node._op in ("Activation", "_mul_scalar")

    data = mx.sym.var("data")
    fc = mx.sym.FullyConnected(data, num_hidden=4, name="fc")
    act = mx.sym.Activation(fc, act_type="relu", name="act")
    out = act * 2.0
    psym = subgraph.partition(out, GrowDown())
    subs = {n._uid: n for n in psym._topo() if n._op == "_subgraph"}
    assert len(subs) == 1
    sub = next(iter(subs.values()))
    # all three ops inside one fragment
    inner_ops = [n._op for n in sub._sub_sym._topo() if n._op]
    assert set(inner_ops) >= {"FullyConnected", "Activation"}, inner_ops
    x = np.random.RandomState(5).rand(2, 6).astype(np.float32)
    params = _init_params(out, x)
    np.testing.assert_allclose(_run_sym(psym, x, params),
                               _run_sym(out, x, params), rtol=1e-5)


def test_partition_conv_bn_relu_fused_fn():
    """The pattern-library story (VERDICT r4 #7 done-bar): conv+bn+relu
    matched as one fragment and swapped for a single fused function
    (folded conv, inference mode)."""
    import jax
    import jax.numpy as jnp

    class ConvBnRelu(subgraph.SubgraphProperty):
        inference_only = True   # BN moving stats become plain inputs

        def select(self, node):
            return node._op == "Activation"

        def select_input(self, node, input_node):
            return ((node._op == "Activation"
                     and input_node._op == "BatchNorm")
                    or (node._op == "BatchNorm"
                        and input_node._op == "Convolution"))

        def create_fn(self, sub_sym, arg_names):
            order = {n: i for i, n in enumerate(arg_names)}

            def fused(*vals):
                def get(frag):
                    hits = [v for n, v in zip(arg_names, vals)
                            if frag in n]
                    assert len(hits) == 1, (frag, arg_names)
                    return hits[0]
                x = get("data")
                w, b = get("conv_weight"), get("conv_bias")
                gamma, beta = get("gamma"), get("beta")
                mean, var = get("moving_mean"), get("moving_var")
                # BN folding: scale conv weights by gamma/sqrt(var+eps)
                s = gamma / jnp.sqrt(var + 1e-3)  # BN default eps
                wf = w * s[:, None, None, None]
                bf = (b - mean) * s + beta
                y = jax.lax.conv_general_dilated(
                    x, wf, (1, 1), "VALID",
                    dimension_numbers=("NCHW", "OIHW", "NCHW"))
                return jnp.maximum(y + bf[None, :, None, None], 0.0)

            return fused

    data = mx.sym.var("data")
    conv = mx.sym.Convolution(data, kernel=(3, 3), num_filter=4,
                              name="conv")
    bn = mx.sym.BatchNorm(conv, name="bn", fix_gamma=False)
    act = mx.sym.Activation(bn, act_type="relu", name="act")

    psym = subgraph.partition(act, ConvBnRelu())
    subs = {n._uid for n in psym._topo() if n._op == "_subgraph"}
    assert len(subs) == 1

    x = np.random.RandomState(7).rand(2, 3, 8, 8).astype(np.float32)
    arg_shapes, _, aux_shapes = act.infer_shape(data=x.shape)
    rng = np.random.RandomState(8)
    params = {}
    for n, s in zip(act.list_arguments(), arg_shapes):
        if n != "data":
            params[n] = mx.nd.array(rng.rand(*s).astype(np.float32) * 0.5)
    aux = {}
    for n, s in zip(act.list_auxiliary_states(), aux_shapes):
        aux[n] = mx.nd.array((rng.rand(*s).astype(np.float32) * 0.5 + 0.5)
                             if "var" in n else
                             rng.rand(*s).astype(np.float32) * 0.1)

    def run(sym):
        ex = sym.bind(mx.cpu(), dict(params, data=mx.nd.array(x)),
                      aux_states=dict(aux))
        return ex.forward(is_train=False)[0].asnumpy()

    np.testing.assert_allclose(run(psym), run(act), rtol=1e-4, atol=1e-5)


def test_partition_multi_output_producer_via_views():
    """A multi-output op (SliceChannel) referenced only through views
    must stay visible to the consumer map: fc feeds BOTH the fused
    relu and a slice whose pieces are consumed separately, so fc is a
    fragment output and the slice still reads the right slots."""
    data = mx.sym.var("data")
    fc = mx.sym.FullyConnected(data, num_hidden=4, name="fc")
    act = mx.sym.Activation(fc, act_type="relu", name="act")
    sl = mx.sym.SliceChannel(fc, num_outputs=2, axis=1, name="sl")
    out = act + mx.sym.concat(sl[0], sl[1], dim=1)
    psym = subgraph.partition(out, FuseDenseRelu(with_fn=False))
    x = np.random.RandomState(9).rand(2, 6).astype(np.float32)
    params = _init_params(out, x)
    np.testing.assert_allclose(_run_sym(psym, x, params),
                               _run_sym(out, x, params), rtol=1e-5)


def test_partition_untouched_view_consumers_keep_slots():
    """An UNFUSED multi-output region entered through a view first must
    not alias the base clone onto that view (both slots read back
    correctly)."""
    data = mx.sym.var("data")
    fc = mx.sym.FullyConnected(data, num_hidden=4, name="fc")
    act = mx.sym.Activation(fc, act_type="relu", name="act")
    d2 = mx.sym.var("d2")
    sl = mx.sym.SliceChannel(d2, num_outputs=2, axis=1, name="sl")
    out = mx.sym.concat(act, sl[1] * 1.0, sl[0] * 1.0, dim=1)
    psym = subgraph.partition(out, FuseDenseRelu(with_fn=False))
    x = np.random.RandomState(10).rand(2, 6).astype(np.float32)
    d2v = np.arange(8, dtype=np.float32).reshape(2, 4)
    shapes, _, _ = out.infer_shape(data=x.shape, d2=d2v.shape)
    rng = np.random.RandomState(0)
    params = {n: rng.randn(*s).astype(np.float32) * 0.3
              for n, s in zip(out.list_arguments(), shapes)
              if n not in ("data", "d2")}
    params["d2"] = d2v
    np.testing.assert_allclose(_run_sym(psym, x, params),
                               _run_sym(out, x, params), rtol=1e-5)
