"""Faster-RCNN op family: Proposal/MultiProposal, DeformableConvolution,
DeformablePSROIPooling, Correlation — each checked against an
independent numpy oracle that re-derives the reference semantics
(src/operator/contrib/proposal.cc, deformable_psroi_pooling.cu,
src/operator/correlation.cc), plus a tiny two-stage detector that
converges on synthetic data (sibling of test_detection.py's tiny-SSD).
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon


# ---------------------------------------------------------------------------
# anchors
# ---------------------------------------------------------------------------

def test_generate_anchors_classic_values():
    """base 16, ratios (.5,1,2), scales (8,16,32) must reproduce the
    canonical py-faster-rcnn table (proposal-inl.h:170-223 math)."""
    from mxnet_tpu.ops.rcnn_ops import _generate_anchors

    got = _generate_anchors(16, (0.5, 1.0, 2.0), (8.0, 16.0, 32.0))
    want = np.array([
        [-84., -40., 99., 55.], [-176., -88., 191., 103.],
        [-360., -184., 375., 199.], [-56., -56., 71., 71.],
        [-120., -120., 135., 135.], [-248., -248., 263., 263.],
        [-36., -80., 51., 95.], [-80., -168., 95., 183.],
        [-168., -344., 183., 359.]], np.float32)
    np.testing.assert_allclose(got, want)


# ---------------------------------------------------------------------------
# Proposal — numpy oracle re-deriving proposal.cc
# ---------------------------------------------------------------------------

def _np_proposal(cls_prob, bbox_pred, im_info, anchors, stride, pre_nms,
                 post_nms, thresh, min_size):
    """Single-image oracle following proposal.cc step by step."""
    A = anchors.shape[0]
    H, W = cls_prob.shape[2], cls_prob.shape[3]
    scores = np.transpose(cls_prob[0, A:], (1, 2, 0)).reshape(-1).copy()
    deltas = np.transpose(bbox_pred[0].reshape(A, 4, H, W),
                          (2, 3, 0, 1)).reshape(-1, 4)
    shifts = np.stack(np.meshgrid(np.arange(W) * stride,
                                  np.arange(H) * stride), -1)  # (H,W,2) x,y
    boxes = (anchors[None, None] + np.concatenate(
        [shifts, shifts], -1)[:, :, None].transpose(0, 1, 2, 3)).reshape(-1, 4)
    im_h, im_w, im_scale = im_info
    bw = boxes[:, 2] - boxes[:, 0] + 1
    bh = boxes[:, 3] - boxes[:, 1] + 1
    cx = boxes[:, 0] + 0.5 * (bw - 1)
    cy = boxes[:, 1] + 0.5 * (bh - 1)
    pcx = deltas[:, 0] * bw + cx
    pcy = deltas[:, 1] * bh + cy
    pw = np.exp(deltas[:, 2]) * bw
    ph = np.exp(deltas[:, 3]) * bh
    pred = np.stack([pcx - 0.5 * (pw - 1), pcy - 0.5 * (ph - 1),
                     pcx + 0.5 * (pw - 1), pcy + 0.5 * (ph - 1)], -1)
    pred[:, 0::2] = np.clip(pred[:, 0::2], 0, im_w - 1)
    pred[:, 1::2] = np.clip(pred[:, 1::2], 0, im_h - 1)
    real_h, real_w = int(im_h / stride), int(im_w / stride)
    hh = np.repeat(np.arange(H), W * A)
    ww = np.tile(np.repeat(np.arange(W), A), H)
    scores[(hh >= real_h) | (ww >= real_w)] = -1
    ms = min_size * im_scale
    iw = pred[:, 2] - pred[:, 0] + 1
    ih = pred[:, 3] - pred[:, 1] + 1
    small = (iw < ms) | (ih < ms)
    pred[small, 0] -= ms / 2
    pred[small, 1] -= ms / 2
    pred[small, 2] += ms / 2
    pred[small, 3] += ms / 2
    scores[small] = -1
    order = np.argsort(-scores, kind="stable")[:pre_nms]
    dets = np.concatenate([pred[order], scores[order, None]], 1)
    areas = (dets[:, 2] - dets[:, 0] + 1) * (dets[:, 3] - dets[:, 1] + 1)
    suppressed = np.zeros(len(dets), bool)
    keep = []
    for i in range(len(dets)):
        if len(keep) >= min(post_nms, pre_nms):
            break
        if suppressed[i]:
            continue
        keep.append(i)
        xx1 = np.maximum(dets[i, 0], dets[i + 1:, 0])
        yy1 = np.maximum(dets[i, 1], dets[i + 1:, 1])
        xx2 = np.minimum(dets[i, 2], dets[i + 1:, 2])
        yy2 = np.minimum(dets[i, 3], dets[i + 1:, 3])
        inter = np.maximum(xx2 - xx1 + 1, 0) * np.maximum(yy2 - yy1 + 1, 0)
        iou = inter / (areas[i] + areas[i + 1:] - inter)
        suppressed[i + 1:] |= iou > thresh
    out = np.zeros((post_nms, 5), np.float32)
    scr = np.zeros((post_nms,), np.float32)
    for i in range(post_nms):
        j = keep[i % len(keep)]
        out[i, 1:] = dets[j, :4]
        scr[i] = dets[j, 4]
    return out, scr


def test_proposal_matches_numpy_oracle():
    from mxnet_tpu.ops.rcnn_ops import _generate_anchors

    rng = np.random.RandomState(7)
    A, H, W, stride = 3, 6, 7, 8
    scales, ratios = (2.0, 4.0, 8.0), (1.0,)
    cls_prob = rng.rand(1, 2 * A, H, W).astype(np.float32)
    bbox_pred = (rng.randn(1, 4 * A, H, W) * 0.3).astype(np.float32)
    im_info = np.array([[44.0, 52.0, 1.0]], np.float32)

    rois, scores = mx.nd.contrib.Proposal(
        mx.nd.array(cls_prob), mx.nd.array(bbox_pred),
        mx.nd.array(im_info), rpn_pre_nms_top_n=40, rpn_post_nms_top_n=12,
        threshold=0.7, rpn_min_size=4, scales=scales, ratios=ratios,
        feature_stride=stride, output_score=True)

    anchors = _generate_anchors(stride, ratios, scales)
    want, want_s = _np_proposal(cls_prob, bbox_pred, im_info[0], anchors,
                                stride, 40, 12, 0.7, 4)
    np.testing.assert_allclose(rois.asnumpy(), want, rtol=1e-4, atol=1e-3)
    np.testing.assert_allclose(scores.asnumpy().ravel(), want_s,
                               rtol=1e-4, atol=1e-5)


def test_multi_proposal_batches():
    from mxnet_tpu.ops.rcnn_ops import _generate_anchors

    rng = np.random.RandomState(3)
    A, H, W, stride = 2, 5, 5, 16
    scales, ratios = (4.0, 8.0), (1.0,)
    cls_prob = rng.rand(2, 2 * A, H, W).astype(np.float32)
    bbox_pred = (rng.randn(2, 4 * A, H, W) * 0.2).astype(np.float32)
    im_info = np.array([[70.0, 70.0, 1.0], [60.0, 76.0, 1.2]], np.float32)

    rois = mx.nd.contrib.MultiProposal(
        mx.nd.array(cls_prob), mx.nd.array(bbox_pred), mx.nd.array(im_info),
        rpn_pre_nms_top_n=30, rpn_post_nms_top_n=8, threshold=0.6,
        rpn_min_size=8, scales=scales, ratios=ratios,
        feature_stride=stride).asnumpy()
    assert rois.shape == (16, 5)
    anchors = _generate_anchors(stride, ratios, scales)
    for n in range(2):
        want, _ = _np_proposal(cls_prob[n:n + 1], bbox_pred[n:n + 1],
                               im_info[n], anchors, stride, 30, 8, 0.6, 8)
        blk = rois[n * 8:(n + 1) * 8]
        assert np.all(blk[:, 0] == n)
        np.testing.assert_allclose(blk[:, 1:], want[:, 1:],
                                   rtol=1e-4, atol=1e-3)


# ---------------------------------------------------------------------------
# Correlation — numpy oracle re-deriving correlation.cc:41-82
# ---------------------------------------------------------------------------

def _np_correlation(d1, d2, k, md, s1, s2, pad, is_mult):
    N, C, H, W = d1.shape
    p1 = np.pad(d1, ((0, 0), (0, 0), (pad, pad), (pad, pad)))
    p2 = np.pad(d2, ((0, 0), (0, 0), (pad, pad), (pad, pad)))
    PH, PW = H + 2 * pad, W + 2 * pad
    kr = (k - 1) // 2
    border = md + kr
    th = int(np.ceil((PH - 2 * border) / s1))
    tw = int(np.ceil((PW - 2 * border) / s1))
    gr = md // s2
    gw = 2 * gr + 1
    out = np.zeros((N, gw * gw, th, tw), np.float32)
    for i in range(th):
        for j in range(tw):
            x1, y1 = j * s1 + md, i * s1 + md
            for tc in range(gw * gw):
                s2o = (tc % gw - gr) * s2
                s2p = (tc // gw - gr) * s2
                x2, y2 = x1 + s2o, y1 + s2p
                a = p1[:, :, y1:y1 + k, x1:x1 + k]
                # displacement windows never cross the padded border
                b = p2[:, :, y2:y2 + k, x2:x2 + k]
                v = a * b if is_mult else np.abs(a - b)
                out[:, tc, i, j] = v.sum(axis=(1, 2, 3))
    return out / (k * k * C)


@pytest.mark.parametrize("k,md,s1,s2,pad,mult", [
    (1, 2, 1, 1, 2, True),
    (1, 2, 1, 2, 2, True),
    (3, 2, 2, 1, 3, True),
    (1, 1, 1, 1, 1, False),
])
def test_correlation_matches_numpy(k, md, s1, s2, pad, mult):
    rng = np.random.RandomState(11)
    d1 = rng.randn(2, 3, 8, 9).astype(np.float32)
    d2 = rng.randn(2, 3, 8, 9).astype(np.float32)
    out = mx.nd.Correlation(mx.nd.array(d1), mx.nd.array(d2),
                            kernel_size=k, max_displacement=md,
                            stride1=s1, stride2=s2, pad_size=pad,
                            is_multiply=mult).asnumpy()
    want = _np_correlation(d1, d2, k, md, s1, s2, pad, mult)
    assert out.shape == want.shape
    np.testing.assert_allclose(out, want, rtol=1e-4, atol=1e-5)


def test_correlation_gradient_flows():
    d1 = mx.nd.array(np.random.RandomState(0).randn(1, 2, 6, 6)
                     .astype(np.float32))
    d2 = mx.nd.array(np.random.RandomState(1).randn(1, 2, 6, 6)
                     .astype(np.float32))
    d1.attach_grad()
    d2.attach_grad()
    with autograd.record():
        out = mx.nd.Correlation(d1, d2, kernel_size=1, max_displacement=1,
                                pad_size=1)
        loss = (out * out).sum()
    loss.backward()
    assert float(mx.nd.abs(d1.grad).sum().asnumpy()) > 0
    assert float(mx.nd.abs(d2.grad).sum().asnumpy()) > 0


# ---------------------------------------------------------------------------
# DeformableConvolution
# ---------------------------------------------------------------------------

def test_deformable_conv_zero_offset_is_conv():
    """With zero offsets the op must equal a regular Convolution."""
    rng = np.random.RandomState(5)
    x = rng.randn(2, 4, 9, 9).astype(np.float32)
    w = (rng.randn(6, 4, 3, 3) * 0.2).astype(np.float32)
    b = rng.randn(6).astype(np.float32)
    off = np.zeros((2, 2 * 9, 7, 7), np.float32)
    got = mx.nd.contrib.DeformableConvolution(
        mx.nd.array(x), mx.nd.array(off), mx.nd.array(w), mx.nd.array(b),
        kernel=(3, 3), num_filter=6).asnumpy()
    want = mx.nd.Convolution(mx.nd.array(x), mx.nd.array(w),
                             mx.nd.array(b), kernel=(3, 3),
                             num_filter=6).asnumpy()
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


def test_deformable_conv_integer_offset_shifts():
    """A constant integer offset of (0, +1) samples one pixel right —
    identical to convolving the shifted image (interior pixels)."""
    rng = np.random.RandomState(6)
    x = rng.randn(1, 2, 8, 8).astype(np.float32)
    w = (rng.randn(3, 2, 3, 3) * 0.3).astype(np.float32)
    off = np.zeros((1, 18, 6, 6), np.float32)
    off[:, 1::2] = 1.0                      # x-offset channels
    got = mx.nd.contrib.DeformableConvolution(
        mx.nd.array(x), mx.nd.array(off), mx.nd.array(w),
        kernel=(3, 3), num_filter=3, no_bias=True).asnumpy()
    xs = np.roll(x, -1, axis=3)             # shift left = sample right
    want = mx.nd.Convolution(mx.nd.array(xs), mx.nd.array(w), None,
                             kernel=(3, 3), num_filter=3,
                             no_bias=True).asnumpy()
    np.testing.assert_allclose(got[:, :, :, :5], want[:, :, :, :5],
                               rtol=1e-4, atol=1e-4)


def test_deformable_conv_pad_stride_groups():
    rng = np.random.RandomState(8)
    x = rng.randn(2, 4, 7, 7).astype(np.float32)
    w = (rng.randn(4, 4, 3, 3) * 0.2).astype(np.float32)
    off = np.zeros((2, 2 * 2 * 9, 4, 4), np.float32)  # 2 deformable groups
    got = mx.nd.contrib.DeformableConvolution(
        mx.nd.array(x), mx.nd.array(off), mx.nd.array(w),
        kernel=(3, 3), stride=(2, 2), pad=(1, 1), num_filter=4,
        num_deformable_group=2, no_bias=True).asnumpy()
    want = mx.nd.Convolution(mx.nd.array(x), mx.nd.array(w), None,
                             kernel=(3, 3), stride=(2, 2), pad=(1, 1),
                             num_filter=4, no_bias=True).asnumpy()
    assert got.shape == want.shape == (2, 4, 4, 4)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


def test_deformable_conv_gradient():
    """Numeric gradient of a scalar loss w.r.t. offsets (the deformable
    part) — checks the bilinear-sampling backward path."""
    rng = np.random.RandomState(9)
    x = rng.randn(1, 2, 5, 5).astype(np.float32)
    w = (rng.randn(2, 2, 3, 3) * 0.4).astype(np.float32)
    # offsets in [0.05, 0.35]: far enough from integer sampling points
    # that the eps=1e-2 finite difference never crosses a bilinear kink
    off0 = (rng.rand(1, 18, 3, 3) * 0.3 + 0.05).astype(np.float32)

    def loss_of(offv):
        out = mx.nd.contrib.DeformableConvolution(
            mx.nd.array(x), mx.nd.array(offv), mx.nd.array(w),
            kernel=(3, 3), num_filter=2, no_bias=True)
        return float((out * out).sum().asnumpy())

    off = mx.nd.array(off0)
    off.attach_grad()
    with autograd.record():
        out = mx.nd.contrib.DeformableConvolution(
            mx.nd.array(x), off, mx.nd.array(w),
            kernel=(3, 3), num_filter=2, no_bias=True)
        loss = (out * out).sum()
    loss.backward()
    g = off.grad.asnumpy()
    eps = 1e-2
    for idx in [(0, 0, 1, 1), (0, 5, 2, 0), (0, 17, 0, 2)]:
        pert = off0.copy()
        pert[idx] += eps
        up = loss_of(pert)
        pert[idx] -= 2 * eps
        dn = loss_of(pert)
        num = (up - dn) / (2 * eps)
        assert abs(num - g[idx]) < 2e-2 + 0.05 * abs(num), \
            "offset grad mismatch at %s: %f vs %f" % (idx, g[idx], num)


# ---------------------------------------------------------------------------
# DeformablePSROIPooling — numpy oracle re-deriving the CUDA kernel
# ---------------------------------------------------------------------------

def _np_psroi(data, rois, trans, scale, od, gs, ps, part, spp, tstd,
              no_trans):
    N, C, H, W = data.shape
    R = rois.shape[0]
    ncls = 1 if no_trans else trans.shape[1] // 2
    ceach = max(od // ncls, 1)
    out = np.zeros((R, od, ps, ps), np.float32)
    cnt = np.zeros((R, od, ps, ps), np.float32)
    for n in range(R):
        bi = int(rois[n, 0])
        x1 = round(rois[n, 1]) * scale - 0.5
        y1 = round(rois[n, 2]) * scale - 0.5
        x2 = (round(rois[n, 3]) + 1.0) * scale - 0.5
        y2 = (round(rois[n, 4]) + 1.0) * scale - 0.5
        rw = max(x2 - x1, 0.1)
        rh = max(y2 - y1, 0.1)
        bh, bw = rh / ps, rw / ps
        sh, sw = bh / spp, bw / spp
        for ctop in range(od):
            for phh in range(ps):
                for pww in range(ps):
                    ph_ = int(np.floor(phh / ps * part))
                    pw_ = int(np.floor(pww / ps * part))
                    cid = ctop // ceach
                    tx = 0.0 if no_trans else \
                        trans[n, cid * 2, ph_, pw_] * tstd
                    ty = 0.0 if no_trans else \
                        trans[n, cid * 2 + 1, ph_, pw_] * tstd
                    ws = pww * bw + x1 + tx * rw
                    hs = phh * bh + y1 + ty * rh
                    gw = min(max(int(pww * gs // ps), 0), gs - 1)
                    gh = min(max(int(phh * gs // ps), 0), gs - 1)
                    c = (ctop * gs + gh) * gs + gw
                    s = 0.0
                    k = 0
                    for ih in range(spp):
                        for iw in range(spp):
                            w_ = ws + iw * sw
                            h_ = hs + ih * sh
                            if w_ < -0.5 or w_ > W - 0.5 or \
                               h_ < -0.5 or h_ > H - 0.5:
                                continue
                            w_ = min(max(w_, 0.0), W - 1.0)
                            h_ = min(max(h_, 0.0), H - 1.0)
                            h0, w0 = int(h_), int(w_)
                            h1, w1 = min(h0 + 1, H - 1), min(w0 + 1, W - 1)
                            dh, dw = h_ - h0, w_ - w0
                            v = (data[bi, c, h0, w0] * (1 - dh) * (1 - dw)
                                 + data[bi, c, h0, w1] * (1 - dh) * dw
                                 + data[bi, c, h1, w0] * dh * (1 - dw)
                                 + data[bi, c, h1, w1] * dh * dw)
                            s += v
                            k += 1
                    out[n, ctop, phh, pww] = 0.0 if k == 0 else s / k
                    cnt[n, ctop, phh, pww] = k
    return out, cnt


@pytest.mark.parametrize("no_trans", [True, False])
def test_deformable_psroi_matches_numpy(no_trans):
    rng = np.random.RandomState(13)
    od, gs, ps, part, spp = 3, 2, 4, 4, 2
    data = rng.randn(2, od * gs * gs, 10, 10).astype(np.float32)
    rois = np.array([[0, 2, 2, 7, 8], [1, 0, 1, 9, 9],
                     [0, 4, 4, 5, 5]], np.float32)
    trans = (rng.rand(3, 2, part, part).astype(np.float32) - 0.5)
    args = [mx.nd.array(data), mx.nd.array(rois)]
    kw = dict(spatial_scale=0.8, output_dim=od, group_size=gs,
              pooled_size=ps, part_size=part, sample_per_part=spp,
              trans_std=0.3, no_trans=no_trans)
    if not no_trans:
        args.append(mx.nd.array(trans))
    got, got_cnt = mx.nd.contrib.DeformablePSROIPooling(*args, **kw)
    want, want_cnt = _np_psroi(data, rois, trans, 0.8, od, gs, ps, part,
                               spp, 0.3, no_trans)
    np.testing.assert_allclose(got_cnt.asnumpy(), want_cnt)
    np.testing.assert_allclose(got.asnumpy(), want, rtol=1e-4, atol=1e-4)


def test_deformable_psroi_gradient_flows():
    rng = np.random.RandomState(14)
    data = mx.nd.array(rng.randn(1, 4, 8, 8).astype(np.float32))
    rois = mx.nd.array(np.array([[0, 1, 1, 6, 6]], np.float32))
    trans = mx.nd.array((rng.rand(1, 2, 2, 2) * 0.2).astype(np.float32))
    data.attach_grad()
    trans.attach_grad()
    with autograd.record():
        out, _ = mx.nd.contrib.DeformablePSROIPooling(
            data, rois, trans, spatial_scale=1.0, output_dim=1,
            group_size=2, pooled_size=2, part_size=2, sample_per_part=2,
            trans_std=0.5)
        loss = (out * out).sum()
    loss.backward()
    assert float(mx.nd.abs(data.grad).sum().asnumpy()) > 0
    assert float(mx.nd.abs(trans.grad).sum().asnumpy()) > 0


# ---------------------------------------------------------------------------
# tiny two-stage detector (RPN + Proposal + ROIAlign head)
# ---------------------------------------------------------------------------

class TinyRPN(gluon.HybridBlock):
    """Conv trunk (stride 4) + RPN heads; A=1 anchor per position."""

    def __init__(self):
        super().__init__()
        self.trunk = gluon.nn.HybridSequential()
        self.trunk.add(gluon.nn.Conv2D(16, 3, padding=1, activation="relu"),
                       gluon.nn.MaxPool2D(2),
                       gluon.nn.Conv2D(32, 3, padding=1, activation="relu"),
                       gluon.nn.MaxPool2D(2))
        self.register_child(self.trunk)
        self.cls = gluon.nn.Conv2D(2, 1)    # 2A channels, A=1
        self.loc = gluon.nn.Conv2D(4, 1)    # 4A channels
        self.register_child(self.cls)
        self.register_child(self.loc)

    def hybrid_forward(self, F, x):
        feat = self.trunk(x)
        return feat, self.cls(feat), self.loc(feat)


def _make_rcnn_data(n, rng):
    """16x16 images with one 6x6 bright square; two classes by texture:
    class 0 = solid, class 1 = striped."""
    X = (rng.rand(n, 1, 16, 16) * 0.2).astype(np.float32)
    boxes = np.zeros((n, 4), np.float32)
    cls = np.zeros((n,), np.int64)
    for i in range(n):
        r, c = rng.randint(0, 10, 2)
        cls[i] = rng.randint(0, 2)
        patch = np.ones((6, 6), np.float32)
        if cls[i] == 1:
            patch[::2] = 0.25
        X[i, 0, r:r + 6, c:c + 6] += patch
        boxes[i] = [c, r, c + 5, r + 5]     # pixel corners
    return X, boxes, cls


def test_tiny_faster_rcnn_converges():
    """Two-stage pipeline end-to-end: RPN trains binary
    objectness + bbox deltas; Proposal decodes rois; ROIAlign + dense
    head classifies the texture class. Training drives both losses
    down and the final proposals localize the object."""
    rng = np.random.RandomState(0)
    n = 48
    X, gt_boxes, gt_cls = _make_rcnn_data(n, rng)
    stride, A = 4, 1

    from mxnet_tpu.ops.rcnn_ops import _generate_anchors

    anchors = _generate_anchors(stride, (1.0,), (1.5,))   # one 6x6-ish
    H = W = 16 // stride
    shifts_x = np.arange(W) * stride
    shifts_y = np.arange(H) * stride
    all_anchors = (anchors[None, None] + np.stack(
        [np.tile(shifts_x, (H, 1)), np.tile(shifts_y[:, None], (1, W)),
         np.tile(shifts_x, (H, 1)), np.tile(shifts_y[:, None], (1, W))],
        -1)[:, :, None]).reshape(-1, 4)                   # (H*W*A, 4)

    # RPN targets: positive = IoU > 0.5 with gt
    def iou_with(gt):
        x1 = np.maximum(all_anchors[:, 0], gt[0])
        y1 = np.maximum(all_anchors[:, 1], gt[1])
        x2 = np.minimum(all_anchors[:, 2], gt[2])
        y2 = np.minimum(all_anchors[:, 3], gt[3])
        inter = np.maximum(x2 - x1 + 1, 0) * np.maximum(y2 - y1 + 1, 0)
        aa = (all_anchors[:, 2] - all_anchors[:, 0] + 1) * \
             (all_anchors[:, 3] - all_anchors[:, 1] + 1)
        ab = (gt[2] - gt[0] + 1) * (gt[3] - gt[1] + 1)
        return inter / (aa + ab - inter)

    cls_t = np.zeros((n, H * W * A), np.float32)
    loc_t = np.zeros((n, H * W * A, 4), np.float32)
    loc_m = np.zeros((n, H * W * A, 1), np.float32)
    for i in range(n):
        ious = iou_with(gt_boxes[i])
        # best anchor is always positive; others need IoU >= 0.35
        pos = ious >= min(0.35, ious.max() - 1e-6)
        cls_t[i, pos] = 1
        aw = all_anchors[:, 2] - all_anchors[:, 0] + 1
        ah = all_anchors[:, 3] - all_anchors[:, 1] + 1
        acx = all_anchors[:, 0] + 0.5 * (aw - 1)
        acy = all_anchors[:, 1] + 0.5 * (ah - 1)
        gw = gt_boxes[i, 2] - gt_boxes[i, 0] + 1
        gh = gt_boxes[i, 3] - gt_boxes[i, 1] + 1
        gcx = gt_boxes[i, 0] + 0.5 * (gw - 1)
        gcy = gt_boxes[i, 1] + 0.5 * (gh - 1)
        loc_t[i, :, 0] = (gcx - acx) / aw
        loc_t[i, :, 1] = (gcy - acy) / ah
        loc_t[i, :, 2] = np.log(gw / aw)
        loc_t[i, :, 3] = np.log(gh / ah)
        loc_m[i, pos] = 1

    net = TinyRPN()
    head = gluon.nn.HybridSequential()
    head.add(gluon.nn.Dense(32, activation="relu"), gluon.nn.Dense(2))
    net.initialize()
    head.initialize()
    params = list(net.collect_params().values()) + \
        list(head.collect_params().values())
    trainer = gluon.Trainer({p.name: p for p in params}, "adam",
                            {"learning_rate": 0.01})
    ce = gluon.loss.SoftmaxCrossEntropyLoss()
    x = mx.nd.array(X)
    ct = mx.nd.array(cls_t)
    lt = mx.nd.array(loc_t.reshape(n, -1))
    lm = mx.nd.array(np.repeat(loc_m, 4, axis=2).reshape(n, -1))
    ycls = mx.nd.array(gt_cls.astype(np.float32))
    im_info = mx.nd.array(np.tile([16.0, 16.0, 1.0], (n, 1)))
    gt_rois = np.concatenate(
        [np.arange(n, dtype=np.float32)[:, None],
         gt_boxes / stride], axis=1)        # feature-map coords
    gt_rois_nd = mx.nd.array(gt_rois)

    first = last = None
    for it in range(60):
        with autograd.record():
            feat, rpn_cls, rpn_loc = net(x)
            rc = rpn_cls.transpose((0, 2, 3, 1)).reshape((-1, 2))
            cls_loss = ce(rc, ct.reshape((-1,))).mean()
            diff = (rpn_loc.transpose((0, 2, 3, 1)).reshape((n, -1)) - lt) \
                * lm
            loc_loss = (diff * diff).sum() / mx.nd.maximum(
                lm.sum(), mx.nd.array([1.0]))
            # stage 2: head trains on ground-truth rois (standard
            # alternating scheme; proposals are used at inference)
            pooled = mx.nd.contrib.ROIAlign(
                feat, gt_rois_nd, pooled_size=(3, 3), spatial_scale=1.0)
            head_loss = ce(head(pooled.reshape((n, -1))), ycls).mean()
            loss = cls_loss + 0.5 * loc_loss + head_loss
        loss.backward()
        trainer.step(n)
        last = float(loss.asnumpy().ravel()[0])
        if first is None:
            first = last
    assert last < first * 0.5, "rcnn loss %.4f -> %.4f" % (first, last)

    # inference through Proposal: objectness softmax over 2A channels
    feat, rpn_cls, rpn_loc = net(x)
    probs = rpn_cls.reshape((n, 2, -1)).softmax(axis=1).reshape(
        (n, 2, H, W))
    rois = mx.nd.contrib.MultiProposal(
        probs, rpn_loc, im_info, rpn_pre_nms_top_n=16,
        rpn_post_nms_top_n=1, threshold=0.7, rpn_min_size=2,
        scales=(1.5,), ratios=(1.0,), feature_stride=stride).asnumpy()
    hits = 0
    cls_hits = 0
    pooled = mx.nd.contrib.ROIAlign(
        feat, mx.nd.array(np.concatenate(
            [rois[:, :1], rois[:, 1:] / stride], axis=1)),
        pooled_size=(3, 3), spatial_scale=1.0)
    pred_cls = head(pooled.reshape((n, -1))).asnumpy().argmax(axis=1)
    for i in range(n):
        x1 = max(rois[i, 1], gt_boxes[i, 0])
        y1 = max(rois[i, 2], gt_boxes[i, 1])
        x2 = min(rois[i, 3], gt_boxes[i, 2])
        y2 = min(rois[i, 4], gt_boxes[i, 3])
        inter = max(x2 - x1 + 1, 0) * max(y2 - y1 + 1, 0)
        ra = (rois[i, 3] - rois[i, 1] + 1) * (rois[i, 4] - rois[i, 2] + 1)
        ga = 36.0
        if inter / (ra + ga - inter) > 0.3:
            hits += 1
        if pred_cls[i] == gt_cls[i]:
            cls_hits += 1
    assert hits >= n * 0.7, "proposal localization %d/%d" % (hits, n)
    assert cls_hits >= n * 0.8, "head accuracy %d/%d" % (cls_hits, n)
