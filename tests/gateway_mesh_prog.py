"""Worker program for the 2-process gateway acceptance (ISSUE 15).

Launched by tools/launch.py with ``-s 0``: N processes x 1 local CPU
device join one SPMD group, and each rank runs a ModelGateway with TWO
registered models:

* ``mesh`` — mesh-sharded over a {"tp": N} mesh SPANNING the
  processes (each rank holds ONE shard of the weight: the
  model-too-large-for-one-chip shape). Every rank drives the same
  deterministic request schedule in lockstep — each device call is an
  SPMD collective, the TrainStep discipline.
* ``quant`` — int8 weight-only quantized, registered on rank 0 only
  (purely local executables), hammered by concurrent threads for the
  whole run; mid-run its weights hot-swap from a training-style
  CheckpointManager commit.

Checks (verified AFTER the lockstep schedule completes, so a failed
check can never strand the peer inside an unmatched collective): mesh
results match the unsharded numpy reference on EVERY rank; the weight
is genuinely sharded across processes (one addressable shard each);
the swap drops ZERO requests; responses span both generations, each
tagged with exactly one; and post-swap responses bit-match a fresh
load of the new checkpoint.

Usage: gateway_mesh_prog.py OUT.json
"""
import json
import os
import sys
import threading
import traceback

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

from mxnet_tpu.parallel import dist

_, nproc, _ = dist.env_spec()
nproc = nproc or 1
dist.initialize(local_device_count=2 // nproc if nproc <= 2 else 1,
                platform="cpu")

import jax  # noqa: E402  (backend config above must come first)

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import checkpoint, serving  # noqa: E402
from mxnet_tpu.serving import ModelSpec, hot_swap  # noqa: E402

MESH_REQUESTS = 20
SWAP_AT = 8


def _dot(w, x):
    return mx.nd.dot(x, w)


def main():
    out_path = sys.argv[1]
    rank = dist.rank()
    rng = np.random.RandomState(7)
    w_mesh = rng.randn(16, 8).astype(np.float32)
    w_q1 = rng.randn(16, 8).astype(np.float32)
    w_q2 = rng.randn(16, 8).astype(np.float32)

    errors = []
    report = {"rank": rank, "mesh_requests": 0}

    assert len(jax.devices()) == 2, jax.devices()
    gw = serving.ModelGateway(max_queue=4096, max_delay_ms=1.0)
    gw.register(ModelSpec("mesh", fn=_dot, params=[mx.nd.array(w_mesh)],
                          item_shape=(16,), max_batch=4,
                          mesh_axes={"tp": 2}))
    pv = gw._state("mesh").backend._param_vals[0]
    report["addressable_shards"] = len(pv.addressable_shards)

    quant_errors, quant_results = [], []
    stop = threading.Event()
    threads = []
    mgr = None
    swap_gen = [None]
    if rank == 0:
        gw.register(ModelSpec("quant", fn=_dot,
                              params=[mx.nd.array(w_q1)],
                              item_shape=(16,), max_batch=8,
                              quantize="int8"))
        # One synchronous pre-hammer request pins a generation-1
        # response regardless of thread-start timing.
        quant_results.append(gw.predict(
            "quant", rng.rand(2, 16).astype(np.float32)))

        def hammer():
            xq = rng.rand(2, 16).astype(np.float32)
            while not stop.is_set():
                try:
                    quant_results.append(gw.predict("quant", xq))
                except Exception as exc:
                    quant_errors.append(repr(exc))

        threads = [threading.Thread(target=hammer) for _ in range(4)]
        for t in threads:
            t.start()

    # -- deterministic lockstep schedule against the mesh model --------------
    # (identical on every rank; each predict is one SPMD device call.
    # NOTHING inside this loop may raise on one rank only — a dead rank
    # strands the peer inside an unmatched collective.)
    mesh_xs = [np.random.RandomState(100 + i).rand(3, 16)
               .astype(np.float32) for i in range(MESH_REQUESTS)]
    mesh_out = []
    for i, x in enumerate(mesh_xs):
        mesh_out.append(gw.predict("mesh", x))
        report["mesh_requests"] += 1
        if i == SWAP_AT and rank == 0:
            # mid-run hot swap of the OTHER model, under fire, from a
            # training-style checkpoint commit
            try:
                # Rank-0-local serving weights, NOT a sharded SPMD
                # save: pin process_count=1 or the manager would wait
                # for the other rank's shard.
                mgr = checkpoint.CheckpointManager(
                    os.path.join(os.path.dirname(out_path) or ".",
                                 "gw_ckpt_r%d" % rank), keep_last=2,
                    process_index=0, process_count=1)
                mgr.save(1, {"w": w_q2}, sync=True)
                swap_gen[0] = hot_swap(
                    gw, "quant", manager=mgr,
                    extract=lambda state: [mx.nd.array(state["w"])])
            except Exception:
                errors.append(traceback.format_exc())

    if rank == 0:
        stop.set()
        for t in threads:
            t.join(30)

    # -- checks (the lockstep schedule is complete on every rank) ------------
    try:
        for x, res in zip(mesh_xs, mesh_out):
            assert res.generation == 1
            np.testing.assert_allclose(res.output.asnumpy(), x @ w_mesh,
                                       rtol=1e-4, atol=1e-5)
        if dist.num_processes() > 1:
            # sharded ACROSS processes: one addressable shard per rank
            assert len(pv.addressable_shards) == 1, pv.addressable_shards
            assert pv.addressable_shards[0].data.shape == (8, 8)
        if rank == 0:
            assert not quant_errors, quant_errors[:3]
            gens = {r.generation for r in quant_results}
            assert gens == {1, 2}, gens
            assert swap_gen[0] == 2, swap_gen
            report["quant_requests"] = len(quant_results)
            report["quant_dropped"] = len(quant_errors)
            report["generations"] = sorted(gens)
            # post-swap responses bit-match a FRESH load of the new
            # checkpoint (same quantized build path, same executables)
            _, state = mgr.restore()
            fresh = gw.registry.spec("quant").build_backend(
                params=[mx.nd.array(state["w"])])
            xq = rng.rand(2, 16).astype(np.float32)
            got = gw.predict("quant", xq)
            assert got.generation == 2
            pad = np.zeros((2, 16), np.float32)
            want = fresh(mx.nd.array(np.vstack([xq, pad])))
            np.testing.assert_array_equal(got.output.asnumpy(),
                                          want.asnumpy()[:2])
    except Exception:
        errors.append(traceback.format_exc())
    finally:
        if mgr is not None:
            mgr.close()
        gw.shutdown()

    # Every rank reaches the barrier whatever its checks found — error
    # signaling is the exit code AFTER the collective plane is quiet.
    dist.barrier("gateway_mesh_done")
    if rank == 0:
        report["errors"] = errors
        with open(out_path, "w") as f:
            json.dump(report, f)
    if errors:
        sys.stderr.write("\n".join(errors))
        sys.exit(1)


if __name__ == "__main__":
    main()
