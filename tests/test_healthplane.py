"""Fleet health plane (ISSUE 8): push-gateway metric export, fleet-level
SLO evaluation on the merged registry, pod-wide forensics collection
over the kvstore diag channel, live /healthz-/readyz-/debug endpoints on
the MetricsServer, data-pipeline watchdog lanes, and the bench
compile-accounting diff."""
import importlib.util
import json
import os
import socket
import sys
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, telemetry
from mxnet_tpu.data.decode import DecodePool
from mxnet_tpu.data.prefetch import DevicePrefetcher
from mxnet_tpu.telemetry import aggregate, export
from mxnet_tpu.telemetry import healthplane as hp
from mxnet_tpu.telemetry import metrics as tmetrics
from mxnet_tpu.telemetry import watchdog as twd

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tools"))
from launch import launch_local  # noqa: E402

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _tool(name, path=None):
    """Import a repo script as a module (the test_forensics pattern)."""
    spec = importlib.util.spec_from_file_location(
        name, path or os.path.join(_ROOT, "tools", "%s.py" % name))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class _FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t


@pytest.fixture(autouse=True)
def _clean_plane():
    twd.reset()
    hp.reset()
    yield
    twd.reset()
    hp.reset()


def _can_bind_localhost():
    try:
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        s.close()
        return True
    except OSError:
        return False


def _http(url, method="GET", accept=None):
    """(status, body_bytes) — 4xx/5xx come back as values, not raises."""
    headers = {"Accept": accept} if accept else {}
    req = urllib.request.Request(url, method=method, headers=headers,
                                 data=b"" if method == "POST" else None)
    try:
        with urllib.request.urlopen(req, timeout=10) as resp:
            return resp.status, resp.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


# -- push exporter ------------------------------------------------------------

def test_push_exporter_posts_gateway_url_and_body():
    reg = tmetrics.Registry()
    reg.counter("pushex_probe_total").inc(7)
    sent = []
    exporter = export.PushExporter(
        "http://gw:9091", registry=reg, job="trainer", instance="r0",
        transport=lambda url, body: sent.append((url, body)))
    assert exporter.push() is True
    url, body = sent[0]
    assert url == "http://gw:9091/metrics/job/trainer/instance/r0"
    assert b"pushex_probe_total 7" in body
    assert exporter.pending == 0


def test_push_exporter_gateway_down_backoff_and_bounded_buffer():
    """ISSUE 8 satellite: gateway 500s -> exponential backoff between
    attempts, bounded buffer (oldest dropped), failures counted; a
    recovered gateway drains the backlog in order."""
    reg = tmetrics.Registry()
    beat = reg.counter("pushex_beat_total")
    clock = _FakeClock()
    calls = []
    healthy = [False]

    def transport(url, body):
        calls.append(body)
        if not healthy[0]:
            raise OSError("HTTP 500 from gateway")

    fail0 = tmetrics.REGISTRY.get("mx_export_failures_total").value
    exporter = export.PushExporter(
        "http://gw:9091", registry=reg, interval_s=10.0, max_buffer=3,
        backoff_s=1.0, max_backoff_s=4.0, transport=transport,
        clock=clock)

    beat.inc()
    exporter.tick()                         # t=0: render + attempt 1
    assert len(calls) == 1 and exporter.pending == 1
    clock.t = 0.5
    exporter.tick()                         # inside backoff: no attempt
    assert len(calls) == 1
    clock.t = 1.5
    exporter.tick()                         # backoff passed: attempt 2
    assert len(calls) == 2
    assert tmetrics.REGISTRY.get("mx_export_failures_total").value \
        - fail0 == 2
    # Backoff doubled (2s): the t=2.0 retry is suppressed.
    clock.t = 2.0
    exporter.tick()
    assert len(calls) == 2

    # Fill past the buffer bound: only the newest 3 snapshots survive.
    for i in range(5):
        clock.t = 100.0 + 10.0 * i          # each tick renders one more
        beat.inc()
        exporter.tick()
    assert exporter.pending == 3

    healthy[0] = True
    clock.t = 1000.0
    exporter.tick()                         # drains the whole backlog
    assert exporter.pending == 0
    # Delivered oldest-first: the last three delivered bodies are the
    # three newest snapshots, in render order.
    def _beat(body):
        for line in body.splitlines():
            if line.startswith(b"pushex_beat_total"):
                return int(line.split()[-1])

    counts = [_beat(b) for b in calls[-3:]]
    assert counts == sorted(counts) and counts[-1] == 6
    # Recovered: next failure starts from the base backoff again.
    healthy[0] = False
    clock.t = 1010.0
    exporter.tick()
    assert exporter._backoff == 1.0


def test_push_exporter_tick_never_blocks_behind_inflight_delivery():
    """A slow/blackholing gateway must not stall a step-loop tick():
    the network call runs outside the state lock, and a tick that finds
    another thread mid-delivery skips instead of queueing behind it."""
    reg = tmetrics.Registry()
    reg.counter("pushex_slow_total").inc()
    in_post = threading.Event()
    release = threading.Event()

    def transport(url, body):
        in_post.set()
        assert release.wait(10.0)

    exporter = export.PushExporter(
        "http://gw:9091", registry=reg, interval_s=0.0,
        transport=transport)
    t = threading.Thread(target=exporter.push, daemon=True)
    t.start()
    assert in_post.wait(10.0)               # delivery now in flight
    t0 = time.perf_counter()
    assert exporter.tick() is None          # skips, doesn't queue
    assert exporter.pending >= 1            # state lock was free too
    assert time.perf_counter() - t0 < 5.0
    release.set()
    t.join(10.0)
    assert not t.is_alive()


def test_diag_buffer_bound_zero_keeps_nothing(monkeypatch):
    """bound <= 0 means keep NOTHING — the naive del q[:-0] would keep
    everything, turning the anti-hoard bound into an unbounded buffer."""
    bus = aggregate.LocalBus()
    monkeypatch.setattr(type(bus), "MAX_DIAG_PER_RANK", 0)
    for i in range(4):
        bus.diag_push(1, "diag.%d.json" % i, b"{}")
    assert bus.diag_pull() in ({}, {1: []})
    monkeypatch.setattr(type(bus), "MAX_DIAG_PER_RANK", 2)
    for i in range(5):
        bus.diag_push(1, "diag.%d.json" % i, b"{}")
    assert [n for n, _ in bus.diag_pull()[1]] == \
        ["diag.3.json", "diag.4.json"]


# -- readiness + healthz ------------------------------------------------------

def test_readiness_registry_unique_components():
    a = hp.unique_component("serving")
    b = hp.unique_component("serving")
    assert (a, b) == ("serving", "serving#2")
    assert hp.is_ready() is False           # both start not-ready
    hp.set_ready(a)
    assert hp.is_ready() is False
    hp.set_ready(b)
    assert hp.is_ready() is True
    hp.clear_ready(a)
    hp.clear_ready(b)
    assert hp.readiness() == {} and hp.is_ready() is True  # vacuous


def test_healthz_flips_within_one_deadline_and_recovers():
    """ISSUE 8 test satellite: /healthz goes unhealthy within one
    watchdog deadline of an induced hang and recovers the moment the
    lane completes."""
    plane = hp.HealthPlane(
        watchdog=telemetry.HangWatchdog(min_deadline_s=0.05))
    ok, body = plane.healthz()
    assert ok and body["healthy"]

    twd.begin("step")
    ok, _ = plane.healthz()                 # fresh work: still healthy
    assert ok
    time.sleep(0.06)                        # one deadline later
    ok, body = plane.healthz()
    assert not ok
    assert body["lanes"]["step"]["overdue"] is True
    assert body["lanes"]["step"]["deadline_s"] == pytest.approx(0.05)

    twd.end("step")
    ok, body = plane.healthz()              # lane completed: recovered
    assert ok and not body["lanes"]["step"]["overdue"]


def test_train_step_and_serving_flip_ready():
    from mxnet_tpu import gluon, serving
    from mxnet_tpu.parallel import TrainStep, make_mesh

    net = gluon.nn.Dense(4, in_units=4)
    net.initialize()
    step = TrainStep(net, gluon.loss.L2Loss(), optimizer="sgd",
                     mesh=make_mesh())
    # The slot is claimed lazily at the FIRST __call__: a TrainStep
    # built but never stepped (eval-only, a discarded retune) must not
    # leave a permanently not-ready ghost in /readyz.
    assert step._hp_component is None
    assert not any(c.startswith("train_step")
                   for c in hp.readiness())
    batch = 2 * len(step.mesh.devices.flat)   # divisible by the dp axis
    step(np.ones((batch, 4), np.float32),
         np.zeros((batch, 4), np.float32))
    assert hp.readiness()[step._hp_component] is True

    srv = serving.InferenceServer(
        fn=lambda w, x: x * w, params=[nd.array(np.ones((1,), "float32"))],
        item_shape=(1,), max_batch=4, warmup=True)
    try:
        assert hp.readiness()[srv._hp_component] is True  # ladder warm
    finally:
        srv.shutdown()
    assert srv._hp_component not in hp.readiness()  # slot released


# -- HTTP endpoints on the MetricsServer --------------------------------------

def test_metrics_server_health_and_debug_endpoints(tmp_path):
    """The full endpoint table on ONE server, plus the /metrics
    Accept-negotiation regression with the health plane mounted."""
    if not _can_bind_localhost():
        pytest.skip("localhost sockets unavailable")
    recorder = telemetry.FlightRecorder(str(tmp_path), rank=0,
                                        rate_limit_s=0.0)

    class _Pipe:
        def debug_state(self):
            return {"watermark": {"epoch": 1}, "last_batch": {"ids": [3]}}

    plane = hp.HealthPlane(
        watchdog=telemetry.HangWatchdog(min_deadline_s=0.05),
        recorder=recorder)
    plane.watch_pipeline(_Pipe())
    tmetrics.REGISTRY.counter("hp_endpoint_probe_total").inc(2)
    server = telemetry.start_http_server(0, health=plane)
    base = "http://%s:%d" % server.server_address
    try:
        # /metrics negotiation unchanged with health mounted.
        status, body = _http(base + "/metrics")
        assert status == 200 and b"hp_endpoint_probe_total 2" in body
        assert b"# EOF" not in body
        status, body = _http(base + "/metrics",
                             accept="application/openmetrics-text")
        assert status == 200 and body.rstrip().endswith(b"# EOF")

        status, body = _http(base + "/healthz")
        assert status == 200 and json.loads(body)["healthy"] is True

        # Induce a hang: liveness flips 503 within one deadline.
        twd.begin("step")
        time.sleep(0.06)
        status, body = _http(base + "/healthz")
        assert status == 503 and json.loads(body)["healthy"] is False
        twd.end("step")
        status, _ = _http(base + "/healthz")
        assert status == 200

        comp = hp.unique_component("warming")
        status, body = _http(base + "/readyz")
        assert status == 503
        assert json.loads(body)["components"] == {"warming": False}
        hp.set_ready(comp)
        status, body = _http(base + "/readyz")
        assert status == 200 and json.loads(body)["ready"] is True

        status, body = _http(base + "/debug/stacks")
        names = [t["name"] for t in json.loads(body)["threads"]]
        assert status == 200 and "MainThread" in names
        status, body = _http(base + "/debug/watchdog")
        assert status == 200 and "step" in json.loads(body)["lanes"]
        status, body = _http(base + "/debug/pipeline")
        assert json.loads(body)["pipelines"][0]["last_batch"]["ids"] == [3]
        status, body = _http(base + "/debug/memory")
        payload = json.loads(body)
        assert status == 200 and "device_memory" in payload \
            and "compile" in payload

        status, body = _http(base + "/debug/bundle", method="POST")
        bundle = json.loads(body)["bundle"]
        assert status == 200 and os.path.exists(bundle)
        with open(bundle) as f:
            assert json.load(f)["meta"]["kind"] == "manual_http"

        assert _http(base + "/nonsense")[0] == 404
        assert _http(base + "/nonsense", method="POST")[0] == 404
    finally:
        server.close()


def test_metrics_server_without_health_post_404():
    if not _can_bind_localhost():
        pytest.skip("localhost sockets unavailable")
    server = telemetry.start_http_server(0)
    base = "http://%s:%d" % server.server_address
    try:
        assert _http(base + "/healthz")[0] == 404
        assert _http(base + "/debug/bundle", method="POST")[0] == 404
        assert _http(base + "/metrics")[0] == 200
    finally:
        server.close()


# -- diag collection over the LocalBus ----------------------------------------

def _collectors(tmp_path, rate_limit_s=0.0):
    bus = aggregate.LocalBus(num_workers=2)
    out = []
    for rank in (0, 1):
        rec = telemetry.FlightRecorder(
            str(tmp_path / ("local%d" % rank)), rank=rank,
            rate_limit_s=rate_limit_s)
        out.append(hp.DiagCollector(
            bus.endpoint(rank), rec, interval_s=0.0,
            directory=str(tmp_path / "collected") if rank == 0 else None))
    return out


def test_pod_snapshot_collects_one_bundle_per_rank(tmp_path):
    c0, c1 = _collectors(tmp_path)
    assert c0.request_pod_bundle("pod_snapshot", "dump the pod") == 1
    c1.step()                               # rank 1: capture + push
    c0.step()                               # rank 0: capture+push+collect
    collected = sorted(c0.collected)
    assert len(collected) == 2
    for rank, path in enumerate(collected):
        assert os.path.dirname(path).endswith("rank%d" % rank)
        with open(path) as f:
            bundle = json.load(f)
        assert bundle["meta"]["kind"] == "pod_snapshot"
        assert bundle["meta"]["rank"] == rank
    # Drain semantics: nothing re-collects without new pushes.
    assert c0.collect() == []


def test_pod_snapshot_requests_ride_recorder_rate_limit(tmp_path):
    c0, c1 = _collectors(tmp_path, rate_limit_s=1e9)
    c0.request_pod_bundle()
    c1.step()
    c0.step()
    assert len(c0.collected) == 2
    suppressed0 = tmetrics.REGISTRY.get("mx_diag_suppressed_total") \
        .labels(kind="pod_snapshot").value
    c0.request_pod_bundle()                 # a flapping operator
    c1.step()
    c0.step()
    assert len(c0.collected) == 2           # no new bundles
    assert tmetrics.REGISTRY.get("mx_diag_suppressed_total") \
        .labels(kind="pod_snapshot").value - suppressed0 == 2


def test_diagnose_expands_collected_layout_and_merges(tmp_path, capsys):
    """ISSUE 8 satellite: tools/diagnose.py reads the rank-0 collected
    tree (rank<R>/ subdirs) and --merges it with a locally committed
    bundle directory into one incident."""
    c0, c1 = _collectors(tmp_path)
    c0.request_pod_bundle("pod_snapshot", "incident probe")
    c1.step()
    c0.step()
    # A local-only bundle of the same kind, moments later.
    local_extra = tmp_path / "local_extra"
    rec = telemetry.FlightRecorder(str(local_extra), rank=2,
                                   rate_limit_s=0.0)
    rec.capture("pod_snapshot", "local capture")

    diagnose = _tool("diagnose")
    found = diagnose._expand([str(tmp_path / "collected")])
    assert len(found) == 2 and all(p.endswith(".json") for p in found)

    rc = diagnose.main(["--merge", str(tmp_path / "collected"),
                        str(local_extra)])
    out = capsys.readouterr().out
    assert rc == 0
    assert "INCIDENT kind=pod_snapshot" in out
    assert "rank(s) [0, 1, 2]" in out
    assert "3 bundle(s) summarized" in out


# -- fleet SLO evaluation -----------------------------------------------------

def test_fleet_slo_alerts_on_merged_rank_all_series():
    """The rank-0 BurnRateMonitor evaluates the pod's combined traffic:
    rank 0 all-good + rank 1 all-bad = 50% pod error rate -> one
    alert, even though rank 0's own series is clean."""
    bus = aggregate.LocalBus(num_workers=2)
    regs = [tmetrics.Registry() for _ in range(2)]
    aggs = [telemetry.Aggregator(bus.endpoint(r), registry=regs[r],
                                 interval_s=0.0) for r in range(2)]
    fams = [reg.histogram("fleet_lat_seconds", "latency",
                          buckets=(0.1, 1.0)) for reg in regs]

    monitor = telemetry.StepMonitor(warn_interval_s=1e9)
    burn = telemetry.BurnRateMonitor(monitor=monitor, eval_interval_s=0.0,
                                     registry=tmetrics.Registry())
    slo = burn.add(aggs[0].fleet_slo("fleet", 0.99, 0.1,
                                     "fleet_lat_seconds"))
    burn.evaluate(now=1000.0)               # baseline: no fleet yet

    for _ in range(50):
        fams[0].observe(0.05)               # rank 0: good
        fams[1].observe(0.5)                # rank 1: bad
    aggs[1].step()
    aggs[0].step()                          # merge -> rank="all" series
    assert slo.effective_threshold == pytest.approx(0.1)
    burns = burn.evaluate(now=1060.0)
    assert burns["fleet"]["5m"] == pytest.approx(50.0)
    assert monitor.anomaly_counts.get("slo_burn") == 1

    # Per-rank scoping still works off the same fleet view: rank 0
    # alone is 0% bad.
    solo = telemetry.ServiceLevelObjective(
        "solo", 0.99, 0.1, "fleet_lat_seconds", labels={"rank": "0"},
        registry=aggs[0])
    assert solo.totals() == (0, 50)


def test_fleet_slo_follows_src_rank_for_natively_rank_labeled_family():
    """When the histogram already uses a "rank" label natively, the
    merge files the source process under "src_rank" — the fleet SLO's
    rank="all" filter must follow it there (regression: the redirect
    used to require "rank" absent from labelnames, which is never true
    in exactly this case, so totals() was silently (0, 0))."""
    bus = aggregate.LocalBus(num_workers=2)
    regs = [tmetrics.Registry() for _ in range(2)]
    aggs = [telemetry.Aggregator(bus.endpoint(r), registry=regs[r],
                                 interval_s=0.0) for r in range(2)]
    fams = [reg.histogram("fleet_ranked_lat_seconds", "latency",
                          labels=("rank",), buckets=(0.1, 1.0))
            for reg in regs]
    for _ in range(10):
        fams[0].labels(rank="x").observe(0.05)
        fams[1].labels(rank="y").observe(0.5)
    aggs[1].step()
    aggs[0].step()
    slo = aggs[0].fleet_slo("ranked", 0.99, 0.1,
                            "fleet_ranked_lat_seconds")
    assert slo.totals() == (10, 20)


def test_push_exporter_backoff_resets_on_any_successful_delivery():
    """A flapping gateway that accepts every other POST must not climb
    toward max_backoff_s: ANY success resets the backoff to base."""
    reg = tmetrics.Registry()
    beat = reg.counter("pushex_flap_total")
    clock = _FakeClock()
    flip = [False]

    def transport(url, body):
        flip[0] = not flip[0]
        if not flip[0]:
            raise OSError("gateway flapped")

    exporter = export.PushExporter(
        "http://gw:9091", registry=reg, interval_s=1.0, max_buffer=8,
        backoff_s=1.0, max_backoff_s=300.0, transport=transport,
        clock=clock)
    for i in range(12):
        clock.t = 10.0 * (i + 1)
        beat.inc()
        exporter.tick()
        assert exporter._backoff in (None, 1.0)


# -- data-pipeline watchdog lanes ---------------------------------------------

def test_decode_pool_hang_fires_data_hang_and_close_releases_lanes():
    """ISSUE 8 satellite: a wedged decode worker fires `data_hang`
    (was: visible only as data::wait); close() releases the lanes."""
    release = threading.Event()

    def fn(i):
        if i == 0:
            release.wait(5.0)
        return i

    pool = DecodePool(fn, num_threads=2, ordered=True)
    results = []
    consumer = threading.Thread(
        target=lambda: results.extend(pool.run(range(4))), daemon=True)
    consumer.start()
    deadline = time.time() + 5.0
    while time.time() < deadline:
        lanes = twd.lane_snapshot()
        if any(n.split("#")[0] == "data" and s["busy_s"] is not None
               for n, s in lanes.items()):
            break
        time.sleep(0.01)
    monitor = telemetry.StepMonitor(warn_interval_s=1e9)
    watchdog = telemetry.HangWatchdog(monitor=monitor,
                                      min_deadline_s=0.01)
    time.sleep(0.05)
    fired = watchdog.check()
    assert any(n.split("#")[0] == "data" for n in fired), fired
    assert monitor.anomaly_counts.get("data_hang", 0) >= 1

    release.set()
    consumer.join(5.0)
    assert sorted(results) == [0, 1, 2, 3]
    pool.close()
    assert not any(n.split("#")[0] == "data"
                   for n in twd.lane_snapshot())


def test_prefetcher_stall_fires_data_hang_and_close_releases_lane():
    release = threading.Event()

    def source():
        yield {"x": 1}
        release.wait(5.0)
        yield {"x": 2}

    prefetcher = DevicePrefetcher(source(), depth=2, place=None)
    assert next(prefetcher) == {"x": 1}
    deadline = time.time() + 5.0
    while time.time() < deadline:           # producer wedged in source
        lanes = twd.lane_snapshot()
        if lanes.get("data", {}).get("busy_s") is not None:
            break
        time.sleep(0.01)
    monitor = telemetry.StepMonitor(warn_interval_s=1e9)
    watchdog = telemetry.HangWatchdog(monitor=monitor,
                                      min_deadline_s=0.01)
    time.sleep(0.05)
    assert "data" in watchdog.check()
    assert monitor.anomaly_counts.get("data_hang", 0) >= 1
    release.set()
    assert next(prefetcher) == {"x": 2}
    prefetcher.close()
    assert "data" not in twd.lane_snapshot()


# -- bench compile-accounting diff --------------------------------------------

def test_bench_compare_emits_per_site_deltas(tmp_path, capsys):
    bench = _tool("bench", os.path.join(_ROOT, "bench.py"))
    a, b = tmp_path / "a.json", tmp_path / "b.json"
    a.write_text(
        json.dumps({"metric": "compile_count[train_step]", "value": 3,
                    "unit": "compiles"}) + "\n" +
        json.dumps({"metric": "compile_seconds[train_step]",
                    "value": 4.5, "unit": "s"}) + "\n" +
        "stderr noise that is not json\n")
    b.write_text(
        json.dumps({"metric": "compile_count[train_step]", "value": 0,
                    "unit": "compiles"}) + "\n" +
        json.dumps({"metric": "compile_seconds[train_step]",
                    "value": 0.0, "unit": "s"}) + "\n" +
        json.dumps({"metric": "compile_count[cached_op]", "value": 2,
                    "unit": "compiles"}) + "\n")
    assert bench.compare(str(a), str(b)) == 0
    rows = {r["metric"]: r for r in
            map(json.loads, capsys.readouterr().out.splitlines())}
    assert rows["compile_count_delta[train_step]"]["value"] == -3.0
    assert rows["compile_seconds_delta[train_step]"]["value"] == -4.5
    assert rows["compile_count_delta[cached_op]"]["value"] == 2.0
    assert rows["compile_count_delta_total"]["value"] == -1.0
    # No accounting rows at all -> explicit error row, rc 1.
    empty = tmp_path / "empty.json"
    empty.write_text("{}\n")
    assert bench.compare(str(empty), str(empty)) == 1


# -- 2-process acceptance -----------------------------------------------------

_PROG = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "healthplane_prog.py")
_ENV = {
    "JAX_PLATFORMS": "cpu",
    "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
}


def test_two_process_pod_snapshot_and_fleet_slo(tmp_path):
    """ISSUE 8 acceptance: a rank-0 `request_bundle` pod snapshot
    yields one diag bundle per rank collected over the kvstore (each
    rank's recorder wrote only its private directory), and a fleet SLO
    violation synthesized across both ranks' histograms fires exactly
    one alert from the rank-0 monitor."""
    if not _can_bind_localhost():
        pytest.skip("localhost sockets unavailable (multi-process "
                    "kvstore needs them)")
    codes = launch_local(2, 1, [sys.executable, _PROG, str(tmp_path)],
                         env_extra=_ENV, timeout=300)
    assert codes == [0, 0], codes

    slo = json.loads((tmp_path / "slo.txt").read_text())
    assert slo["alerts"] == 1               # exactly one pod-level alert
    assert slo["burn_5m"] == pytest.approx(50.0)
    assert 0.1 < slo["merged_p99"] <= 1.0   # pod p99 is in the bad bucket

    collected = [l for l in
                 (tmp_path / "collected.txt").read_text().splitlines()
                 if l]
    assert len(collected) == 2, collected
    ranks = set()
    for path in collected:
        with open(path) as f:
            bundle = json.load(f)
        assert bundle["meta"]["kind"] == "pod_snapshot"
        ranks.add(bundle["meta"]["rank"])
        assert os.path.dirname(path).endswith(
            "rank%d" % bundle["meta"]["rank"])
    assert ranks == {0, 1}
    # The collected tree reads straight into the diagnose tool.
    diagnose = _tool("diagnose")
    found = diagnose._expand([str(tmp_path / "collected")])
    assert len(found) == 2


# -- collected-tree retention (ISSUE 11 satellite) ----------------------------

def _collector_with_retention(tmp_path, **kw):
    bus = aggregate.LocalBus(num_workers=2)
    recs, cols = [], []
    for rank in (0, 1):
        rec = telemetry.FlightRecorder(
            str(tmp_path / ("local%d" % rank)), rank=rank,
            rate_limit_s=0.0)
        recs.append(rec)
        cols.append(hp.DiagCollector(
            bus.endpoint(rank), rec, interval_s=0.0,
            directory=str(tmp_path / "collected") if rank == 0 else None,
            **(kw if rank == 0 else {})))
    return recs, cols


def test_diag_collector_keep_last_per_rank(tmp_path):
    """keep_last retention mirrors checkpoint GC: after every collect,
    only the newest N bundles survive in each rank<R>/ directory."""
    recs, (c0, c1) = _collector_with_retention(tmp_path, keep_last=2)
    for i in range(5):
        recs[0].capture("probe", "r0 #%d" % i)
        recs[1].capture("probe", "r1 #%d" % i)
        c1.step()
        c0.step()
    root = tmp_path / "collected"
    for rank in (0, 1):
        names = sorted(os.listdir(str(root / ("rank%d" % rank))))
        assert len(names) == 2, names
        # The newest sequence numbers survived (zero-padded names sort).
        assert names[-1].endswith("%06d.json" % 5)


def test_diag_collector_bytes_cap_across_ranks(tmp_path):
    """The max_bytes budget bounds the WHOLE collected tree,
    oldest-by-mtime first regardless of rank."""
    recs, (c0, c1) = _collector_with_retention(tmp_path, max_bytes=1)
    recs[0].capture("probe", "r0")
    recs[1].capture("probe", "r1")
    c1.step()
    c0.step()
    root = tmp_path / "collected"
    total = sum(
        os.path.getsize(os.path.join(str(root), rd, n))
        for rd in os.listdir(str(root))
        for n in os.listdir(os.path.join(str(root), rd)))
    # A 1-byte budget can keep nothing: every bundle was retired.
    assert total == 0
    # The collector still records what it collected (audit trail).
    assert len(c0.collected) == 2
