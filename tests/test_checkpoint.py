"""mxnet_tpu.checkpoint — fault-tolerant async checkpointing.

Covers the durability contract end to end: atomic commit (nothing
partial is ever restorable), bounded-retry on transient IO failures,
checksum-verified restore that skips corrupt/torn checkpoints,
retention GC, sharded per-process SPMD saves with manifest stitching,
the SIGTERM preemption hook, and the state adapters for every training
frontend (Module, gluon Block/Trainer, parallel.TrainStep)."""
import os
import signal

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon
from mxnet_tpu.checkpoint import (CheckpointManager, CheckpointNotFoundError,
                                  PreemptionHook, Shard, block_state,
                                  load_block_state, load_state_dict,
                                  load_trainer_state, module_state,
                                  state_dict, trainer_state)
from mxnet_tpu.parallel import TrainStep, make_mesh


def _state(step=0):
    rng = np.random.RandomState(42 + step)
    return {"params": {"w": rng.rand(8, 4).astype(np.float32),
                       "b": rng.rand(4).astype(np.float32)},
            "meta": {"step": step, "lr": 0.1, "tag": "run-a",
                     "blob": b"\x00pickled\xff", "ok": True}}


# -- core save/restore --------------------------------------------------------

def test_save_restore_roundtrip_kinds(tmp_path):
    m = CheckpointManager(str(tmp_path))
    st = _state(3)
    m.save(3, st, sync=True)
    step, out = m.restore()
    assert step == 3
    np.testing.assert_array_equal(out["params"]["w"], st["params"]["w"])
    np.testing.assert_array_equal(out["params"]["b"], st["params"]["b"])
    # scalar kinds survive with their python types
    assert out["meta"] == st["meta"]
    assert isinstance(out["meta"]["step"], int)
    assert isinstance(out["meta"]["lr"], float)
    assert isinstance(out["meta"]["blob"], bytes)
    assert isinstance(out["meta"]["ok"], bool)


def test_async_saves_commit_in_order(tmp_path):
    # max_pending high enough that no backpressure drop kicks in — the
    # drop-oldest path has its own test (test_async_backlog_drops_oldest)
    m = CheckpointManager(str(tmp_path), keep_last=10, max_pending=10)
    for s in range(1, 6):
        m.save(s, _state(s))
    m.wait()
    assert m.pending == 0
    assert m.all_steps() == [1, 2, 3, 4, 5]
    assert m.latest_step() == 5
    step, out = m.restore()
    assert step == 5 and out["meta"]["step"] == 5
    m.close()


def test_restore_specific_step(tmp_path):
    m = CheckpointManager(str(tmp_path), keep_last=10)
    for s in (1, 2, 3):
        m.save(s, _state(s), sync=True)
    step, out = m.restore(step=2)
    assert step == 2 and out["meta"]["step"] == 2


def test_restore_empty_dir_raises(tmp_path):
    m = CheckpointManager(str(tmp_path))
    assert m.latest_step() is None
    with pytest.raises(CheckpointNotFoundError):
        m.restore()


def test_retention_gc(tmp_path):
    m = CheckpointManager(str(tmp_path), keep_last=2, keep_every=4)
    for s in range(1, 9):
        m.save(s, _state(s), sync=True)
    # newest 2 = {7, 8}; keep_every=4 archives {4, 8}
    assert m.all_steps() == [4, 7, 8]


def test_uncommitted_dirs_invisible(tmp_path):
    """A step dir without a manifest (kill between mkdir and commit)
    and tmp staging dirs are never restorable."""
    m = CheckpointManager(str(tmp_path), keep_last=10)
    m.save(1, _state(1), sync=True)
    os.makedirs(str(tmp_path / "step-00000099"))          # no manifest
    os.makedirs(str(tmp_path / "tmp.step-00000098.123"))  # torn staging
    assert m.latest_step() == 1
    step, _ = m.restore()
    assert step == 1


# -- fault injection: retries, atomicity, corruption --------------------------

def test_transient_write_failure_retried(tmp_path, fault_fs):
    m = CheckpointManager(str(tmp_path), max_retries=3, retry_backoff=0.001)
    fault_fs.fail_next_writes(2)
    m.save(1, _state(1), sync=True)       # retries absorb both failures
    assert fault_fs.writes_failed == 2
    step, out = m.restore()
    assert step == 1
    np.testing.assert_array_equal(out["params"]["w"],
                                  _state(1)["params"]["w"])


def test_retry_budget_exhausted(tmp_path, fault_fs):
    m = CheckpointManager(str(tmp_path), max_retries=2, retry_backoff=0.001)
    fault_fs.fail_next_writes(100)
    with pytest.raises(OSError):
        m.save(1, _state(1), sync=True)
    # nothing partial became visible, and the failure is recorded
    assert m.latest_step() is None
    assert isinstance(m.last_error, OSError)


def test_async_failure_keeps_trainer_alive(tmp_path, fault_fs):
    m = CheckpointManager(str(tmp_path), max_retries=1, retry_backoff=0.001)
    fault_fs.fail_next_writes(100)
    m.save(1, _state(1))                  # async: must not raise
    m.wait()
    assert m.latest_step() is None
    assert isinstance(m.last_error, OSError)
    fault_fs.fail_next_writes(0)
    fault_fs.fail_writes = 0
    m.save(2, _state(2))                  # next save succeeds
    m.wait()
    assert m.latest_step() == 2
    m.close()


def test_failed_commit_rename_is_invisible(tmp_path, fault_fs):
    """The commit IS the rename: if it never happens, restore() still
    lands on the previous step and no step dir appears."""
    m = CheckpointManager(str(tmp_path), max_retries=0)
    m.save(1, _state(1), sync=True)
    fault_fs.fail_next_renames(1)
    with pytest.raises(OSError):
        m.save(2, _state(2), sync=True)
    assert m.all_steps() == [1]
    step, _ = m.restore()
    assert step == 1


def test_torn_write_detected_and_skipped(tmp_path, fault_fs):
    """A shard truncated mid-write (torn page-cache flush) commits but
    fails length/CRC verification; restore falls back to the previous
    committed step."""
    m = CheckpointManager(str(tmp_path), keep_last=10)
    m.save(1, _state(1), sync=True)
    fault_fs.truncate_next_file(10)       # next opened file = step 2 shard
    m.save(2, _state(2), sync=True)
    assert fault_fs.files_truncated == 1
    assert m.latest_step() == 2           # committed...
    step, out = m.restore()               # ...but not restorable
    assert step == 1
    assert out["meta"]["step"] == 1


def test_corrupt_committed_checkpoint_skipped(tmp_path, fault_fs):
    """Bit-rot in a committed shard: CRC catches it, restore skips to
    the next older step; restore(step=) raises explicitly."""
    from mxnet_tpu.checkpoint import CheckpointCorruptError

    m = CheckpointManager(str(tmp_path), keep_last=10)
    m.save(1, _state(1), sync=True)
    m.save(2, _state(2), sync=True)
    shard = str(tmp_path / "step-00000002" / "shard-00000-of-00001.bin")
    fault_fs.corrupt(shard, flip_byte_at=8)
    step, _ = m.restore()
    assert step == 1
    with pytest.raises(CheckpointCorruptError):
        m.restore(step=2)


# -- sharded SPMD saves -------------------------------------------------------

def test_sharded_save_manifest_stitching(tmp_path):
    """Two 'processes' each write only their addressable shards; the
    stitched manifest restores the full global arrays on read."""
    full = np.arange(64, dtype=np.float32).reshape(8, 8)
    scalar_meta = {"step": 5, "note": "spmd"}

    # rank 1 writes first (rank 0 polls for every part before commit)
    m1 = CheckpointManager(str(tmp_path), process_index=1, process_count=2)
    m1.save(5, {"w": Shard(full.shape, full.dtype,
                           [(((4, 8), (0, 8)), full[4:8])])}, sync=True)
    m0 = CheckpointManager(str(tmp_path), process_index=0, process_count=2)
    m0.save(5, {"w": Shard(full.shape, full.dtype,
                           [(((0, 4), (0, 8)), full[0:4])]),
                "meta": scalar_meta}, sync=True)

    step, out = m0.restore()
    assert step == 5
    np.testing.assert_array_equal(out["w"], full)
    assert out["meta"] == scalar_meta
    # exactly one shard file per process, stitched by one manifest
    names = sorted(os.listdir(str(tmp_path / "step-00000005")))
    assert "shard-00000-of-00002.bin" in names
    assert "shard-00001-of-00002.bin" in names
    assert "manifest.json" in names


def test_sharded_incomplete_coverage_detected(tmp_path):
    """If chunks do not cover the global array the checkpoint is
    corrupt, not silently zero-filled."""
    from mxnet_tpu.checkpoint import CheckpointCorruptError

    full = np.ones((4, 4), np.float32)
    m1 = CheckpointManager(str(tmp_path), process_index=1, process_count=2)
    m1.save(1, {"w": Shard(full.shape, full.dtype, [])}, sync=True)
    m0 = CheckpointManager(str(tmp_path), process_index=0, process_count=2)
    m0.save(1, {"w": Shard(full.shape, full.dtype,
                           [(((0, 2), (0, 4)), full[0:2])])}, sync=True)
    with pytest.raises(CheckpointCorruptError):
        m0.restore(step=1)


def test_stitch_timeout_fails_save(tmp_path):
    """Process 0 must not commit a checkpoint missing another process's
    shards — a straggler beyond the timeout fails the save cleanly."""
    m0 = CheckpointManager(str(tmp_path), process_index=0, process_count=2,
                           stitch_timeout=0.05, max_retries=0)
    with pytest.raises(OSError):
        m0.save(1, {"w": np.ones(3, np.float32)}, sync=True)
    assert m0.latest_step() is None


# -- preemption hook ----------------------------------------------------------

def test_preemption_hook_final_save(tmp_path):
    state = {"calls": 0}

    def state_fn():
        state["calls"] += 1
        return _state(7)

    m = CheckpointManager(str(tmp_path))
    hook = PreemptionHook(m, state_fn=state_fn, step_fn=lambda: 7,
                          exit=False)
    with hook:
        os.kill(os.getpid(), signal.SIGTERM)
    assert hook.preempted and hook.saved_step == 7
    assert state["calls"] == 1
    step, out = m.restore()
    assert step == 7 and out["meta"]["step"] == 7


def test_preemption_hook_flushes_pending_async(tmp_path):
    m = CheckpointManager(str(tmp_path), keep_last=10)
    m.save(1, _state(1))                  # queued async
    hook = PreemptionHook(m, state_fn=lambda: _state(2),
                          step_fn=lambda: 2, exit=False)
    with hook:
        os.kill(os.getpid(), signal.SIGTERM)
    assert m.all_steps() == [1, 2]        # async landed AND final save


# -- profiler surface ---------------------------------------------------------

def test_profiler_counters(tmp_path):
    import json

    m = CheckpointManager(str(tmp_path))
    m.save(1, _state(1), sync=True)
    payload = json.loads(mx.profiler.dumps(format="json"))
    counters = payload["counters"]
    assert counters["checkpoint::bytes"] > 0
    assert counters["checkpoint::save_seconds"] > 0
    # The gauge is best-effort telemetry (ticks are dropped rather than
    # ever blocking on the profiler lock — see CheckpointManager._bump),
    # so earlier preemption tests may have left process-global drift;
    # the manager's own pending count is the authoritative value.
    assert counters["checkpoint::pending"] >= 0
    assert m.pending == 0
    assert m.total_bytes > 0 and m.total_save_seconds > 0


# -- state adapters -----------------------------------------------------------

def _toy_module(seed=0):
    data = mx.sym.Variable("data")
    fc1 = mx.sym.FullyConnected(data, num_hidden=16, name="fc1")
    act = mx.sym.Activation(fc1, act_type="relu")
    fc2 = mx.sym.FullyConnected(act, num_hidden=2, name="fc2")
    out = mx.sym.SoftmaxOutput(fc2, name="softmax")
    from mxnet_tpu.module import Module

    mod = Module(out, context=mx.cpu())
    mod.bind(data_shapes=[("data", (8, 6))],
             label_shapes=[("softmax_label", (8,))])
    mod.init_params(initializer=mx.init.Uniform(0.1))
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.5,
                                         "momentum": 0.9})
    return mod


def _module_train_steps(mod, n, seed=1):
    from mxnet_tpu.io import DataBatch

    rng = np.random.RandomState(seed)
    for _ in range(n):
        x = mx.nd.array(rng.rand(8, 6).astype(np.float32))
        y = mx.nd.array(rng.randint(0, 2, 8).astype(np.float32))
        mod.forward(DataBatch(data=[x], label=[y]), is_train=True)
        mod.backward()
        mod.update()


def test_module_adapter_roundtrip(tmp_path):
    mod = _toy_module()
    _module_train_steps(mod, 3)
    m = CheckpointManager(str(tmp_path))
    m.save(3, state_dict(mod), sync=True)
    _, st = m.restore()

    mod2 = _toy_module(seed=9)
    load_state_dict(mod2, st)
    a1, x1 = mod.get_params()
    a2, x2 = mod2.get_params()
    for k in a1:
        np.testing.assert_array_equal(a1[k].asnumpy(), a2[k].asnumpy())
    # optimizer momentum came back too: one more identical step matches
    _module_train_steps(mod, 1, seed=5)
    _module_train_steps(mod2, 1, seed=5)
    b1, _ = mod.get_params()
    b2, _ = mod2.get_params()
    for k in b1:
        np.testing.assert_array_equal(b1[k].asnumpy(), b2[k].asnumpy())


def test_block_trainer_adapter_roundtrip(tmp_path):
    def build():
        net = gluon.nn.HybridSequential(prefix="ck_")
        net.add(gluon.nn.Dense(16, activation="relu", in_units=6,
                               prefix="fc1_"))
        net.add(gluon.nn.Dense(2, in_units=16, prefix="fc2_"))
        net.initialize(mx.init.Xavier())
        tr = gluon.Trainer(net.collect_params(), "sgd",
                           {"learning_rate": 0.5, "momentum": 0.9})
        return net, tr

    def train(net, tr, n, seed):
        from mxnet_tpu import autograd

        loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
        rng = np.random.RandomState(seed)
        for _ in range(n):
            x = mx.nd.array(rng.rand(8, 6).astype(np.float32))
            y = mx.nd.array(rng.randint(0, 2, 8))
            with autograd.record():
                loss = loss_fn(net(x), y)
            loss.backward()
            tr.step(8)

    mx.random.seed(4)
    net1, tr1 = build()
    train(net1, tr1, 3, seed=1)
    m = CheckpointManager(str(tmp_path))
    m.save(3, {"net": block_state(net1), "trainer": trainer_state(tr1)},
           sync=True)
    _, st = m.restore()

    mx.random.seed(11)
    net2, tr2 = build()
    train(net2, tr2, 1, seed=2)           # diverge first, then restore
    load_block_state(net2, st["net"])
    load_trainer_state(tr2, st["trainer"])
    train(net1, tr1, 1, seed=5)
    train(net2, tr2, 1, seed=5)
    p1 = net1._collect_params_with_prefix()
    p2 = net2._collect_params_with_prefix()
    for k in p1:
        np.testing.assert_array_equal(p1[k].data().asnumpy(),
                                      p2[k].data().asnumpy())


def _build_train_step(seed, lr=0.1, mesh_axes=None):
    mx.random.seed(seed)
    np.random.seed(seed)
    net = gluon.nn.HybridSequential(prefix="ts_")
    net.add(gluon.nn.Dense(32, activation="relu", in_units=16,
                           prefix="fc1_"))
    net.add(gluon.nn.Dense(4, in_units=32, prefix="fc2_"))
    net.initialize(mx.init.Xavier())
    return TrainStep(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                     optimizer="sgd",
                     optimizer_params={"learning_rate": lr,
                                       "momentum": 0.9},
                     mesh=make_mesh(mesh_axes))


def _ts_batch(s):
    rng = np.random.RandomState(1000 + s)
    return rng.rand(8, 16).astype(np.float32), rng.randint(0, 4, 8)


def test_trainstep_bit_exact_resume(tmp_path):
    """Kill/resume == uninterrupted: params, momentum, step counter and
    RNG stream all continue bit-for-bit through a checkpoint."""
    ts = _build_train_step(3)
    losses = []
    for s in range(6):
        x, y = _ts_batch(s)
        losses.append(float(np.asarray(ts(x, y))))

    ts1 = _build_train_step(3)
    for s in range(3):
        x, y = _ts_batch(s)
        ts1(x, y)
    m = CheckpointManager(str(tmp_path))
    m.save(3, ts1.state_dict(), sync=True)

    step, st = m.restore()
    ts2 = _build_train_step(99)           # different seed: must not matter
    ts2.load_state_dict(st)
    assert ts2.num_update == 3
    tail = []
    for s in range(3, 6):
        x, y = _ts_batch(s)
        tail.append(float(np.asarray(ts2(x, y))))
    assert tail == losses[3:]


def test_trainstep_sharded_state_roundtrip(tmp_path):
    """Tensor-parallel mesh: state_dict(sharded=True) yields Shard
    leaves per addressable piece; the stitched restore matches the
    gathered full state."""
    ts = _build_train_step(5, mesh_axes={"dp": 2, "tp": 4})
    for s in range(2):
        x, y = _ts_batch(s)
        ts(x, y)
    sd = ts.state_dict(sharded=True)
    assert any(isinstance(v, Shard) for v in sd["params"].values())

    m = CheckpointManager(str(tmp_path))
    m.save(2, sd, sync=True)
    _, st = m.restore()
    full = ts.state_dict(sharded=False)
    for name in full["params"]:
        np.testing.assert_array_equal(st["params"][name],
                                      full["params"][name])
    ts2 = _build_train_step(6, mesh_axes={"dp": 2, "tp": 4})
    ts2.load_state_dict(st)
    x, y = _ts_batch(2)
    l_a = float(np.asarray(ts(x, y)))
    l_b = float(np.asarray(ts2(x, y)))
    assert l_a == l_b


# -- callback wiring ----------------------------------------------------------

def test_do_checkpoint_manager_path(tmp_path):
    sym = mx.sym.Variable("data") * 2
    arg = {"w": mx.nd.array([1.0, 2.0])}
    m = CheckpointManager(str(tmp_path), keep_last=10)
    cb = mx.callback.do_checkpoint("unused-prefix", period=2, manager=m)
    for epoch in range(4):
        cb(epoch, sym, arg, {})
    m.wait()
    assert m.all_steps() == [2, 4]
    _, st = m.restore()
    assert "data" in st["symbol"]
    np.testing.assert_array_equal(st["arg"]["w"], [1.0, 2.0])
    # no legacy prefix files were written on the manager path
    assert not [f for f in os.listdir(".") if f.startswith("unused-prefix")]


def test_module_checkpoint_manager_path(tmp_path):
    mod = _toy_module()
    _module_train_steps(mod, 2)
    m = CheckpointManager(str(tmp_path), keep_last=10)
    cb = mx.callback.module_checkpoint(mod, "unused", period=1,
                                       save_optimizer_states=True,
                                       manager=m)
    cb(0)
    m.wait()
    step, st = m.restore()
    assert step == 1
    assert "opt_states" in st
    mod2 = _toy_module(seed=3)
    load_state_dict(mod2, st)
    a1, _ = mod.get_params()
    a2, _ = mod2.get_params()
    for k in a1:
        np.testing.assert_array_equal(a1[k].asnumpy(), a2[k].asnumpy())


# -- kill-during-save ---------------------------------------------------------

def test_sigkill_mid_save_never_corrupts(tmp_path):
    """The acceptance bar: a hard kill at ANY byte of a save leaves the
    store restorable at the last fully committed step. A child process
    commits checkpoints in a tight loop and is SIGKILLed mid-flight; the
    parent then restores and verifies content integrity."""
    import subprocess
    import sys as _sys
    import time as _time

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    prog = (
        "import sys, numpy as np\n"
        "sys.path.insert(0, %r)\n"
        "from mxnet_tpu.checkpoint import CheckpointManager\n"
        "m = CheckpointManager(sys.argv[1], keep_last=10000)\n"
        "s = 0\n"
        "while True:\n"
        "    s += 1\n"
        "    state = {'step': s,\n"
        "             'w': np.full(500_000, s, dtype=np.float32)}\n"
        "    m.save(s, state, sync=True)\n"
        "    print(s, flush=True)\n" % root)
    child = subprocess.Popen([_sys.executable, "-c", prog, str(tmp_path)],
                             stdout=subprocess.PIPE, text=True, bufsize=1)
    try:
        # let a few commits land, then kill somewhere mid-save
        for line in child.stdout:
            if int(line) >= 3:
                break
        _time.sleep(0.005)                # land inside a later write
        child.kill()
        child.wait()
    finally:
        if child.poll() is None:
            child.kill()

    m = CheckpointManager(str(tmp_path))
    step, st = m.restore()
    assert step >= 3
    # the restored checkpoint is internally consistent, not torn
    assert st["step"] == step
    np.testing.assert_array_equal(
        st["w"], np.full(500_000, step, dtype=np.float32))
    # and every committed step restores clean too
    for s in m.all_steps():
        got_step, got = m.restore(step=s)
        assert got["step"] == s
        np.testing.assert_array_equal(
            got["w"], np.full(500_000, s, dtype=np.float32))


def test_torn_commit_can_be_resaved(tmp_path, fault_fs):
    """A committed-but-torn step must not block its own re-save: the
    preemption hook's final sync save at that step verifies the existing
    commit, finds it corrupt, and atomically replaces it."""
    m = CheckpointManager(str(tmp_path), keep_last=10)
    fault_fs.truncate_next_file(10)       # step 3 commits torn
    m.save(3, _state(3), sync=True)
    with pytest.raises(Exception):
        m.restore(step=3)
    m.save(3, _state(3), sync=True)       # e.g. the preempt final save
    step, out = m.restore()
    assert step == 3
    np.testing.assert_array_equal(out["params"]["w"],
                                  _state(3)["params"]["w"])


def test_multiproc_retry_preserves_peer_shards(tmp_path, fault_fs):
    """A transient failure on one process's write must not destroy the
    shards a peer already staged (the retry cleanup is per-process)."""
    full = np.arange(16, dtype=np.float32).reshape(4, 4)
    m1 = CheckpointManager(str(tmp_path), process_index=1, process_count=2)
    m1.save(1, {"w": Shard(full.shape, full.dtype,
                           [(((2, 4), (0, 4)), full[2:4])])}, sync=True)
    m0 = CheckpointManager(str(tmp_path), process_index=0, process_count=2,
                           max_retries=2, retry_backoff=0.001)
    fault_fs.fail_next_writes(1)          # rank 0's first attempt fails
    m0.save(1, {"w": Shard(full.shape, full.dtype,
                           [(((0, 2), (0, 4)), full[0:2])])}, sync=True)
    step, out = m0.restore()
    assert step == 1
    np.testing.assert_array_equal(out["w"], full)


def test_preemption_snapshot_race_retried(tmp_path):
    """A SIGTERM landing mid-step sees donated (deleted) buffers and the
    snapshot raises; the handler must re-deliver the signal after the
    step commits and still land the final save."""
    import time

    calls = {"n": 0}

    def flaky_state_fn():
        calls["n"] += 1
        if calls["n"] == 1:
            raise RuntimeError("Array has been deleted (donated)")
        return _state(9)

    m = CheckpointManager(str(tmp_path))
    hook = PreemptionHook(m, state_fn=flaky_state_fn, step_fn=lambda: 9,
                          exit=False, snapshot_retry_delay=0.05)
    with hook:
        os.kill(os.getpid(), signal.SIGTERM)
        # first delivery fails and schedules a re-delivery
        deadline = time.monotonic() + 5.0
        while hook.saved_step is None and time.monotonic() < deadline:
            time.sleep(0.02)
    assert calls["n"] == 2
    assert hook.saved_step == 9
    step, out = m.restore()
    assert step == 9 and out["meta"]["step"] == 9


def test_async_backlog_drops_oldest(tmp_path, fault_fs):
    """A writer slower than the save cadence must not accumulate
    unbounded host snapshots: the oldest queued save is dropped."""
    import threading

    from mxnet_tpu.checkpoint import manager as ckpt_manager

    gate = threading.Event()
    real_open = ckpt_manager._open_for_write

    def slow_open(path):
        gate.wait(timeout=10)
        return real_open(path)

    m = CheckpointManager(str(tmp_path), keep_last=100, max_pending=2)
    try:
        orig = ckpt_manager._open_for_write
        ckpt_manager._open_for_write = slow_open
        for s in range(1, 8):           # writer stalled on the gate
            m.save(s, _state(s))
        assert m.pending <= 3           # 1 in-flight + max_pending queued
        assert m.dropped_saves > 0
    finally:
        ckpt_manager._open_for_write = orig
        gate.set()
    m.wait()
    # the newest save survived the backlog
    assert m.latest_step() == 7
    m.close()


def test_module_restore_before_init_optimizer(tmp_path):
    """The natural restore order — load_state_dict on a bound module,
    THEN init_optimizer — must still apply the checkpointed optimizer
    state (momentum), not silently drop it."""
    mod = _toy_module()
    _module_train_steps(mod, 3)
    m = CheckpointManager(str(tmp_path))
    m.save(3, state_dict(mod), sync=True)
    _, st = m.restore()

    from mxnet_tpu.module import Module

    data = mx.sym.Variable("data")
    fc1 = mx.sym.FullyConnected(data, num_hidden=16, name="fc1")
    act = mx.sym.Activation(fc1, act_type="relu")
    fc2 = mx.sym.FullyConnected(act, num_hidden=2, name="fc2")
    out = mx.sym.SoftmaxOutput(fc2, name="softmax")
    mod2 = Module(out, context=mx.cpu())
    mod2.bind(data_shapes=[("data", (8, 6))],
              label_shapes=[("softmax_label", (8,))])
    mod2.init_params(initializer=mx.init.Uniform(0.1))
    load_state_dict(mod2, st)             # optimizer NOT initialized yet
    mod2.init_optimizer(optimizer="sgd",
                        optimizer_params={"learning_rate": 0.5,
                                          "momentum": 0.9})
    _module_train_steps(mod, 1, seed=5)
    _module_train_steps(mod2, 1, seed=5)
    a1, _ = mod.get_params()
    a2, _ = mod2.get_params()
    for k in a1:
        np.testing.assert_array_equal(a1[k].asnumpy(), a2[k].asnumpy())


def test_async_save_copies_numpy_leaves(tmp_path):
    """mgr.save must snapshot host numpy leaves at call time — a caller
    mutating the array afterwards must not corrupt the queued save."""
    import threading

    from mxnet_tpu.checkpoint import manager as ckpt_manager

    gate = threading.Event()
    real_open = ckpt_manager._open_for_write

    def gated_open(path):
        gate.wait(timeout=10)
        return real_open(path)

    w = np.zeros(64, np.float32)
    m = CheckpointManager(str(tmp_path))
    try:
        ckpt_manager._open_for_write = gated_open
        m.save(1, {"w": w})               # queued; writer blocked
        w[:] = 999.0                      # caller mutates AFTER save()
    finally:
        ckpt_manager._open_for_write = real_open
        gate.set()
    m.wait()
    _, st = m.restore()
    np.testing.assert_array_equal(st["w"], np.zeros(64, np.float32))
    m.close()


def test_module_kvstore_path_restore_bit_exact(tmp_path):
    """Multi-context Module with update_on_kvstore: the checkpoint must
    capture the kvstore's LIVE updater (not the module's pristine one)
    and a restore onto a live module must refresh the store's weight
    copies — otherwise momentum restarts at zero / the next update
    reverts the restore, both silently."""
    from mxnet_tpu.io import DataBatch
    from mxnet_tpu.module import Module

    def build():
        d = mx.sym.Variable("data")
        sy = mx.sym.SoftmaxOutput(
            mx.sym.FullyConnected(d, num_hidden=2, name="fc"),
            name="softmax")
        mod = Module(sy, context=[mx.cpu(0), mx.cpu(1)])
        mod.bind(data_shapes=[("data", (8, 3))],
                 label_shapes=[("softmax_label", (8,))])
        mod.init_params(initializer=mx.init.Uniform(0.1))
        mod.init_optimizer(kvstore="device", optimizer="sgd",
                           optimizer_params={"learning_rate": 0.5,
                                             "momentum": 0.9})
        return mod

    def train(mod, n, seed):
        r = np.random.RandomState(seed)
        for _ in range(n):
            b = DataBatch(data=[mx.nd.array(r.rand(8, 3))],
                          label=[mx.nd.array(r.randint(0, 2, 8))])
            mod.forward(b, is_train=True)
            mod.backward()
            mod.update()

    mod = build()
    train(mod, 3, 1)
    assert mod._update_on_kvstore     # premise: kvstore update path
    m = CheckpointManager(str(tmp_path))
    m.save(3, state_dict(mod), sync=True)
    _, st = m.restore()

    mod2 = build()
    train(mod2, 1, 2)                 # diverge the live kvstore module
    load_state_dict(mod2, st)
    train(mod, 1, 7)
    train(mod2, 1, 7)
    a1, _ = mod.get_params()
    a2, _ = mod2.get_params()
    for k in a1:
        np.testing.assert_array_equal(a1[k].asnumpy(), a2[k].asnumpy())


def test_preemption_hook_exit_false_swallows_sigint(tmp_path):
    """Cooperative mode (exit=False): Ctrl-C must only set the flag —
    chaining to the default SIGINT handler would throw KeyboardInterrupt
    into the loop the flag asks to stop gracefully."""
    m = CheckpointManager(str(tmp_path))
    hook = PreemptionHook(m, state_fn=lambda: _state(1),
                          step_fn=lambda: 1, exit=False,
                          signals=(signal.SIGINT,))
    with hook:
        os.kill(os.getpid(), signal.SIGINT)   # must NOT raise
    assert hook.preempted and hook.saved_step == 1
