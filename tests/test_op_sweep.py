"""Per-op numeric sweep (reference: tests/python/unittest/test_operator.py
— finite-difference gradient checks per op plus the cpu-oracle
check_consistency pattern). Specs are family-driven; the final test
asserts the sweep touches >= 150 distinct registered ops."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.test_utils import (assert_almost_equal,
                                  check_numeric_gradient,
                                  check_consistency)

R = np.random.RandomState(7)


def _pos(*s):
    return R.rand(*s).astype(np.float32) + 0.5


def _sym(*s):
    return (R.rand(*s) * 2 - 1).astype(np.float32)


def _away_from_kinks(*s):
    x = _sym(*s)
    x[np.abs(x) < 0.15] += 0.3
    return x


# op -> (inputs builder, attrs, mode)  mode: grad | fwd
SPECS = {}


def spec(name, make, attrs=None, mode="grad", tol=None):
    SPECS[name] = (make, attrs or {}, mode, tol or {})


# -- unary, differentiable ---------------------------------------------------
for op in ["exp", "tanh", "sigmoid", "softsign", "erf", "square", "sin",
           "cos", "negative", "expm1", "cbrt", "arctan",
           "arcsinh", "degrees", "radians", "identity", "_copy",
           "make_loss", "MakeLoss"]:
    spec(op, lambda: [_sym(3, 4)])
# gradient is zero by design for these: forward-only
for op in ["stop_gradient", "BlockGrad"]:
    spec(op, lambda: [_sym(3, 4)], mode="fwd")
for op in ["log", "log2", "log10", "sqrt", "rsqrt", "reciprocal", "gamma",
           "gammaln", "rcbrt", "log1p"]:
    spec(op, lambda: [_pos(3, 4)])
for op in ["arcsin", "arccos", "arctanh"]:
    spec(op, lambda: [(_sym(3, 4) * 0.8)])
spec("arccosh", lambda: [_pos(3, 4) + 1.0])
spec("abs", lambda: [_away_from_kinks(3, 4)])
spec("relu", lambda: [_away_from_kinks(3, 4)])
spec("tan", lambda: [(_sym(3, 4) * 0.5)])
spec("sinh", lambda: [_sym(3, 4)])
spec("cosh", lambda: [_sym(3, 4)])
spec("erfinv", lambda: [(_sym(3, 4) * 0.5)])
spec("clip", lambda: [_away_from_kinks(3, 4) * 3],
     {"a_min": -1.0, "a_max": 1.0}, "fwd")

# -- unary, non-differentiable ----------------------------------------------
for op in ["sign", "round", "ceil", "floor", "trunc", "fix", "rint",
           "logical_not", "isnan", "isinf", "shape_array", "size_array"]:
    spec(op, lambda: [_sym(3, 4)], mode="fwd")

# -- binary broadcast + elemwise ---------------------------------------------
for op in ["broadcast_add", "broadcast_sub", "broadcast_mul",
           "broadcast_plus", "broadcast_minus", "broadcast_maximum",
           "broadcast_minimum", "broadcast_hypot",
           "elemwise_add", "elemwise_sub", "elemwise_mul",
           "_maximum", "_minimum", "_hypot", "maximum", "minimum"]:
    spec(op, lambda: [_away_from_kinks(3, 4), _away_from_kinks(3, 4) + .1])
for op in ["broadcast_div", "elemwise_div"]:
    spec(op, lambda: [_sym(3, 4), _pos(3, 4)])
# mod gradients are distributional wrt the divisor: forward-only
for op in ["_mod", "broadcast_mod"]:
    spec(op, lambda: [_sym(3, 4), _pos(3, 4)], mode="fwd")
spec("broadcast_power", lambda: [_pos(3, 4), _sym(3, 4)])
spec("_arctan2", lambda: [_pos(3, 4), _pos(3, 4)])
spec("arctan2", lambda: [_pos(3, 4), _pos(3, 4)])
for op in ["broadcast_equal", "broadcast_not_equal", "broadcast_greater",
           "broadcast_greater_equal", "broadcast_lesser",
           "broadcast_lesser_equal", "broadcast_logical_and",
           "broadcast_logical_or", "broadcast_logical_xor",
           "_logical_and", "_logical_or", "_logical_xor"]:
    spec(op, lambda: [_sym(3, 4), _sym(3, 4)], mode="fwd")

# -- scalar ops ---------------------------------------------------------------
for op in ["_plus_scalar", "_minus_scalar", "_rminus_scalar", "_mul_scalar",
           "_div_scalar", "_rdiv_scalar", "_power_scalar"]:
    spec(op, lambda: [_pos(3, 4)], {"scalar": 1.7})
spec("_rpower_scalar", lambda: [_sym(3, 4)], {"scalar": 1.7})
spec("_maximum_scalar", lambda: [_away_from_kinks(3, 4)], {"scalar": 0.0})
spec("_minimum_scalar", lambda: [_away_from_kinks(3, 4)], {"scalar": 0.0})
for op in ["_equal_scalar", "_greater_scalar", "_lesser_scalar"]:
    spec(op, lambda: [_sym(3, 4)], {"scalar": 0.1}, "fwd")

# -- reductions ---------------------------------------------------------------
for op in ["sum", "mean", "nansum", "sum_axis"]:
    spec(op, lambda: [_sym(3, 4, 2)], {"axis": 1})
spec("prod", lambda: [_pos(2, 3)], {"axis": 1})
spec("nanprod", lambda: [_pos(2, 3)], {"axis": 1})
spec("max", lambda: [np.arange(24, dtype=np.float32).reshape(2, 3, 4)],
     {"axis": 2})
spec("min", lambda: [np.arange(24, dtype=np.float32).reshape(2, 3, 4)],
     {"axis": 2})
spec("norm", lambda: [_pos(3, 4)], {"axis": 1})
spec("argmax", lambda: [_sym(3, 4)], {"axis": 1}, "fwd")
spec("argmin", lambda: [_sym(3, 4)], {"axis": 1}, "fwd")
spec("argmax_channel", lambda: [_sym(3, 4)], mode="fwd")
spec("cumsum", lambda: [_sym(3, 4)], {"axis": 1})

# -- shape manipulation -------------------------------------------------------
spec("reshape", lambda: [_sym(3, 4)], {"shape": (4, 3)})
spec("Reshape", lambda: [_sym(3, 4)], {"shape": (2, 6)})
spec("reshape_like", lambda: [_sym(3, 4), _sym(2, 6)])
spec("transpose", lambda: [_sym(3, 4)])
spec("flatten", lambda: [_sym(2, 3, 4)])
spec("Flatten", lambda: [_sym(2, 3, 4)])
spec("expand_dims", lambda: [_sym(3, 4)], {"axis": 1})
spec("squeeze", lambda: [_sym(3, 1, 4)], {"axis": 1})
spec("tile", lambda: [_sym(2, 3)], {"reps": (2, 2)})
spec("repeat", lambda: [_sym(2, 3)], {"repeats": 2, "axis": 1})
spec("pad", lambda: [_sym(1, 2, 4, 4)],
     {"mode": "constant", "pad_width": (0, 0, 0, 0, 1, 1, 1, 1)})
spec("Pad", lambda: [_sym(1, 2, 4, 4)],
     {"mode": "edge", "pad_width": (0, 0, 0, 0, 1, 1, 1, 1)})
spec("flip", lambda: [_sym(3, 4)], {"axis": 1})
spec("reverse", lambda: [_sym(3, 4)], {"axis": 1})
spec("slice", lambda: [_sym(4, 5)], {"begin": (1, 0), "end": (3, 4)})
spec("slice_axis", lambda: [_sym(4, 5)], {"axis": 1, "begin": 1, "end": 4})
spec("slice_like", lambda: [_sym(4, 5), _sym(2, 3)])
spec("crop", lambda: [_sym(4, 5)], {"begin": (0, 1), "end": (2, 4)})
spec("broadcast_to", lambda: [_sym(1, 4)], {"shape": (3, 4)})
spec("broadcast_axis", lambda: [_sym(1, 4)], {"axis": 0, "size": 3})
spec("broadcast_axes", lambda: [_sym(1, 4)], {"axis": 0, "size": 3})
spec("broadcast_like", lambda: [_sym(1, 4), _sym(3, 4)])
spec("swapaxes", lambda: [_sym(2, 3, 4)], {"dim1": 0, "dim2": 2})
spec("SwapAxis", lambda: [_sym(2, 3, 4)], {"dim1": 1, "dim2": 2})
spec("moveaxis", lambda: [_sym(2, 3, 4)], {"source": 0, "destination": 2})
spec("depth_to_space", lambda: [_sym(1, 8, 2, 2)], {"block_size": 2})
spec("space_to_depth", lambda: [_sym(1, 2, 4, 4)], {"block_size": 2})
spec("diag", lambda: [_sym(4, 4)], mode="fwd")
spec("where", lambda: [(R.rand(3, 4) > 0.5).astype(np.float32),
                       _sym(3, 4), _sym(3, 4)])
spec("concat", lambda: [_sym(2, 3), _sym(2, 3)], {"dim": 1})
spec("Concat", lambda: [_sym(2, 3), _sym(2, 3)], {"dim": 0})
spec("stack", lambda: [_sym(2, 3), _sym(2, 3)], {"axis": 1})
spec("split", lambda: [_sym(4, 6)], {"num_outputs": 2, "axis": 1}, "fwd")
spec("SliceChannel", lambda: [_sym(4, 6)],
     {"num_outputs": 3, "axis": 1}, "fwd")

# -- indexing -----------------------------------------------------------------
spec("take", lambda: [_sym(5, 3),
                      np.array([0, 2, 4], np.float32)], {"axis": 0},
     "fwd")
spec("batch_take", lambda: [_sym(3, 4),
                            np.array([0, 2, 1], np.float32)], mode="fwd")
spec("one_hot", lambda: [np.array([0, 2, 1], np.float32)],
     {"depth": 4}, "fwd")
spec("pick", lambda: [_sym(3, 4), np.array([0, 2, 1], np.float32)],
     {"axis": 1}, "fwd")
spec("gather_nd", lambda: [_sym(4, 5),
                           np.array([[0, 1], [2, 3]], np.float32)],
     mode="fwd")
spec("scatter_nd", lambda: [_sym(2), np.array([[0, 3]], np.float32)],
     {"shape": (5,)}, "fwd")
spec("topk", lambda: [_sym(3, 6)], {"k": 2, "axis": 1}, "fwd")
spec("sort", lambda: [_sym(3, 6)], {"axis": 1}, "fwd")
spec("argsort", lambda: [_sym(3, 6)], {"axis": 1}, "fwd")
spec("unravel_index", lambda: [np.array([3, 7], np.float32)],
     {"shape": (3, 4)}, "fwd")
spec("ravel_multi_index", lambda: [np.array([[1, 2], [1, 1]], np.float32)],
     {"shape": (3, 4)}, "fwd")
spec("histogram", lambda: [_sym(20)], {"bin_cnt": 5, "range": (-1, 1)},
     "fwd")

# -- neural network -----------------------------------------------------------
spec("FullyConnected", lambda: [_sym(2, 5), _sym(4, 5), _sym(4)],
     {"num_hidden": 4})
spec("fully_connected", lambda: [_sym(2, 5), _sym(4, 5), _sym(4)],
     {"num_hidden": 4})
spec("Convolution", lambda: [_sym(1, 2, 5, 5), _sym(3, 2, 3, 3), _sym(3)],
     {"kernel": (3, 3), "num_filter": 3})
spec("Deconvolution", lambda: [_sym(1, 3, 3, 3), _sym(3, 2, 3, 3), _sym(2)],
     {"kernel": (3, 3), "num_filter": 2})
spec("Pooling", lambda: [_sym(1, 2, 4, 4)],
     {"kernel": (2, 2), "stride": (2, 2), "pool_type": "avg"})
spec("pooling", lambda: [np.arange(32, dtype=np.float32)
                         .reshape(1, 2, 4, 4)],
     {"kernel": (2, 2), "stride": (2, 2), "pool_type": "max"})
spec("Activation", lambda: [_away_from_kinks(3, 4)], {"act_type": "tanh"})
spec("activation", lambda: [_away_from_kinks(3, 4)],
     {"act_type": "sigmoid"})
spec("LeakyReLU", lambda: [_away_from_kinks(3, 4)],
     {"act_type": "leaky", "slope": 0.1})
spec("leaky_relu", lambda: [_away_from_kinks(3, 4)],
     {"act_type": "elu", "slope": 1.0})
spec("softmax", lambda: [_sym(3, 4)], {"axis": -1})
# "Softmax" is the deprecated alias of SoftmaxOutput (takes a label)
spec("Softmax", lambda: [_sym(3, 4), np.array([0, 2, 1], np.float32)],
     mode="fwd")
spec("log_softmax", lambda: [_sym(3, 4)], {"axis": -1})
spec("softmin", lambda: [_sym(3, 4)], {"axis": -1})
spec("SoftmaxActivation", lambda: [_sym(3, 4)])
spec("softmax_cross_entropy", lambda: [_sym(3, 4),
                                       np.array([0, 2, 1], np.float32)],
     mode="fwd")
spec("BatchNorm", lambda: [_sym(2, 3, 4, 4), _pos(3), _sym(3),
                           _sym(3), _pos(3)],
     {"fix_gamma": False, "training": False}, "fwd")
spec("LayerNorm", lambda: [_sym(3, 6), _pos(6), _sym(6)])
spec("layer_norm", lambda: [_sym(3, 6), _pos(6), _sym(6)])
spec("InstanceNorm", lambda: [_sym(2, 3, 5), _pos(3), _sym(3)])
spec("L2Normalization", lambda: [_pos(3, 4)])
spec("l2_normalization", lambda: [_pos(3, 4)])
spec("LRN", lambda: [_pos(1, 4, 3, 3)], {"nsize": 3}, "fwd")
spec("Embedding", lambda: [np.array([0, 2, 1], np.float32), _sym(5, 4)],
     {"input_dim": 5, "output_dim": 4}, "fwd")
spec("Dropout", lambda: [_sym(3, 4)], {"p": 0.5, "training": False})
spec("SequenceMask",
     lambda: [_sym(4, 2, 3), np.array([2, 4], np.float32)],
     {"use_sequence_length": True}, "fwd")
spec("SequenceLast",
     lambda: [_sym(4, 2, 3), np.array([2, 4], np.float32)],
     {"use_sequence_length": True}, "fwd")
spec("SequenceReverse",
     lambda: [_sym(4, 2, 3), np.array([2, 4], np.float32)],
     {"use_sequence_length": True}, "fwd")
spec("GridGenerator", lambda: [_sym(1, 6)],
     {"transform_type": "affine", "target_shape": (4, 4)}, "fwd")
spec("UpSampling", lambda: [_sym(1, 2, 3, 3)],
     {"scale": 2, "sample_type": "nearest"})
spec("SoftmaxOutput", lambda: [_sym(3, 4),
                               np.array([0, 2, 1], np.float32)],
     mode="fwd")
spec("LinearRegressionOutput", lambda: [_sym(3, 4), _sym(3, 4)],
     mode="fwd")
spec("LogisticRegressionOutput", lambda: [_sym(3, 4), _sym(3, 4)],
     mode="fwd")
spec("MAERegressionOutput", lambda: [_sym(3, 4), _sym(3, 4)], mode="fwd")

# -- linalg -------------------------------------------------------------------
def _spd(n):
    a = _sym(n, n)
    return (a @ a.T + n * np.eye(n)).astype(np.float32)


spec("linalg_gemm2", lambda: [_sym(3, 4), _sym(4, 2)])
spec("linalg_gemm", lambda: [_sym(3, 4), _sym(4, 2), _sym(3, 2)])
spec("linalg_potrf", lambda: [_spd(3)], mode="fwd")
spec("linalg_potri", lambda: [np.linalg.cholesky(_spd(3))
                              .astype(np.float32)], mode="fwd")
spec("linalg_trmm", lambda: [np.tril(_pos(3, 3)), _sym(3, 3)], mode="fwd")
spec("linalg_trsm", lambda: [np.tril(_pos(3, 3)) + 2 * np.eye(3,
                             dtype=np.float32), _sym(3, 3)], mode="fwd")
spec("linalg_syrk", lambda: [_sym(3, 4)], mode="fwd")
spec("linalg_det", lambda: [_spd(3)])
spec("linalg_slogdet", lambda: [_spd(3)], mode="fwd")
spec("linalg_inverse", lambda: [_spd(3)])
spec("linalg_sumlogdiag", lambda: [_spd(3)])
spec("linalg_syevd", lambda: [_spd(3)], mode="fwd")
spec("linalg_gelqf", lambda: [_sym(2, 4)], mode="fwd")
spec("dot", lambda: [_sym(3, 4), _sym(4, 2)])
spec("batch_dot", lambda: [_sym(2, 3, 4), _sym(2, 4, 2)])
spec("khatri_rao", lambda: [_sym(2, 3), _sym(4, 3)], mode="fwd")

# -- random (shape/dtype checks only) ----------------------------------------
for op in ["random_uniform", "random_normal", "random_exponential",
           "random_poisson", "random_gamma", "random_negative_binomial",
           "random_generalized_negative_binomial"]:
    spec(op, lambda: [], {"shape": (3, 4)}, "fwd")
spec("random_randint", lambda: [], {"low": 0, "high": 5, "shape": (3, 4)},
     "fwd")
for op in ["sample_uniform", "sample_normal", "sample_gamma"]:
    spec(op, lambda: [_pos(3), _pos(3) + 1.0], {"shape": (4,)}, "fwd")
for op in ["sample_exponential", "sample_poisson"]:
    spec(op, lambda: [_pos(3)], {"shape": (4,)}, "fwd")
spec("sample_multinomial", lambda: [np.array([[0.2, 0.8], [0.5, 0.5]],
                                             np.float32)],
     {"shape": (6,)}, "fwd")
spec("shuffle", lambda: [_sym(6, 2)], mode="fwd")
spec("multinomial", lambda: [np.array([[0.3, 0.7]], np.float32)],
     {"shape": (5,)}, "fwd")

# -- optimizer update ops (forward-only semantics checks elsewhere) ----------
for op in ["sgd_update", "signsgd_update"]:
    spec(op, lambda: [_sym(3, 4), _sym(3, 4)], {"lr": 0.1}, "fwd")
spec("sgd_mom_update", lambda: [_sym(3, 4), _sym(3, 4), _sym(3, 4)],
     {"lr": 0.1, "momentum": 0.9}, "fwd")
spec("adam_update",
     lambda: [_sym(3, 4), _sym(3, 4), _sym(3, 4), _pos(3, 4)],
     {"lr": 0.1}, "fwd")
spec("rmsprop_update", lambda: [_sym(3, 4), _sym(3, 4), _pos(3, 4)],
     {"lr": 0.1}, "fwd")
spec("mp_sgd_update",
     lambda: [_sym(3, 4).astype(np.float16), _sym(3, 4), _sym(3, 4)],
     {"lr": 0.1}, "fwd")

# -- misc ---------------------------------------------------------------------
spec("Cast", lambda: [_sym(3, 4)], {"dtype": "float16"}, "fwd")
spec("cast", lambda: [_sym(3, 4)], {"dtype": "int32"}, "fwd")
spec("zeros_like", lambda: [_sym(3, 4)], mode="fwd")
spec("ones_like", lambda: [_sym(3, 4)], mode="fwd")
spec("smooth_l1", lambda: [_away_from_kinks(3, 4) * 2])
spec("ctc_loss", lambda: [_sym(5, 2, 4),
                          np.array([[1, 2], [2, 3]], np.float32)],
     mode="fwd")

# -- contrib tail (adaptive pool, resize, fft, index_copy, count_sketch) -----
spec("_contrib_AdaptiveAvgPooling2D", lambda: [_sym(2, 3, 7, 5)],
     {"output_size": (3, 2)})
spec("_contrib_BilinearResize2D", lambda: [_sym(2, 3, 5, 4)],
     {"height": 9, "width": 7})
spec("_contrib_fft", lambda: [_sym(3, 8)], mode="fwd")
spec("_contrib_ifft", lambda: [_sym(3, 16)], mode="fwd")
spec("_contrib_index_copy",
     lambda: [_sym(5, 3), np.array([0, 3], np.float32), _sym(2, 3)],
     mode="fwd")
spec("_contrib_count_sketch",
     lambda: [_sym(3, 6), np.array([[0, 2, 1, 3, 2, 0]], np.float32),
              np.array([[1, -1, 1, 1, -1, 1]], np.float32)],
     {"out_dim": 4}, "fwd")


@pytest.mark.parametrize("name", sorted(SPECS))
def test_op(name):
    make, attrs, mode, tol = SPECS[name]
    fn = getattr(mx.nd, name)
    inputs = make()
    nds = [mx.nd.array(x) for x in inputs]
    out = fn(*nds, **attrs)
    outs = out if isinstance(out, (tuple, list)) else [out]
    for o in outs:
        a = o.asnumpy()
        assert a.size > 0
        assert np.isfinite(a.astype(np.float64)).all(), \
            "%s produced non-finite output" % name
    if mode == "grad":
        def wrapped(*xs):
            res = fn(*xs, **attrs)
            res = res[0] if isinstance(res, (tuple, list)) else res
            return res

        check_numeric_gradient(wrapped, inputs,
                               rtol=tol.get("rtol", 2e-2),
                               atol=tol.get("atol", 2e-3))


@pytest.mark.parametrize("name", ["dot", "Convolution", "softmax",
                                  "BatchNorm", "linalg_gemm2", "take",
                                  "Pooling", "LayerNorm", "broadcast_mul",
                                  "sum"])
def test_op_consistency_across_devices(name):
    """Same op on two virtual devices agrees bit-for-bit-ish (the
    reference's check_consistency oracle pattern)."""
    make, attrs, mode, tol = SPECS[name]
    fn = getattr(mx.nd, name)
    inputs = make()

    def wrapped(*xs):
        res = fn(*xs, **attrs)
        return res[0] if isinstance(res, (tuple, list)) else res

    check_consistency(wrapped, inputs,
                      ctx_list=[mx.cpu(0), mx.cpu(1)])


def test_sweep_coverage():
    from mxnet_tpu.ops import registry

    covered = set()
    for name in SPECS:
        covered.add(registry.get(name).name)   # canonical names
    assert len(covered) >= 150, \
        "sweep covers %d distinct ops (<150)" % len(covered)
