"""Symbol/Executor/Module tests (reference:
tests/python/unittest/test_module.py, test_executor.py,
tests/python/train/test_mlp.py)."""
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.module import Module, BucketingModule


def _mlp_symbol(hidden=32, classes=2):
    data = mx.sym.Variable("data")
    fc1 = mx.sym.FullyConnected(data, num_hidden=hidden, name="fc1")
    act = mx.sym.Activation(fc1, act_type="relu")
    fc2 = mx.sym.FullyConnected(act, num_hidden=classes, name="fc2")
    return mx.sym.SoftmaxOutput(fc2, name="softmax")


def _toy_data(n=200, d=10, seed=0):
    rng = np.random.RandomState(seed)
    X = rng.randn(n, d).astype(np.float32)
    w = rng.randn(d).astype(np.float32)
    Y = (X @ w > 0).astype(np.float32)
    return X, Y


def test_symbol_compose_infer():
    out = _mlp_symbol()
    assert out.list_arguments() == ["data", "fc1_weight", "fc1_bias",
                                    "fc2_weight", "fc2_bias",
                                    "softmax_label"]
    arg_shapes, out_shapes, _ = out.infer_shape(data=(4, 10))
    assert arg_shapes[1] == (32, 10)
    assert out_shapes == [(4, 2)]


def test_symbol_json_roundtrip(tmp_path):
    out = _mlp_symbol()
    path = str(tmp_path / "sym.json")
    out.save(path)
    loaded = mx.sym.load(path)
    assert loaded.list_arguments() == out.list_arguments()
    a1, o1, _ = loaded.infer_shape(data=(2, 10))
    a2, o2, _ = out.infer_shape(data=(2, 10))
    assert a1 == a2 and o1 == o2


def test_symbol_arith_operators():
    a = mx.sym.Variable("a")
    b = mx.sym.Variable("b")
    c = (a + b) * 2.0 - a / b
    ex = c.bind(ctx=mx.cpu(), args={"a": mx.nd.array([4.0]),
                                    "b": mx.nd.array([2.0])})
    out = ex.forward()[0].asnumpy()
    np.testing.assert_allclose(out, [(4 + 2) * 2 - 4 / 2])


def test_symbol_group_internals():
    out = _mlp_symbol()
    internals = out.get_internals()
    names = [s.name for s in internals.outputs]
    assert "fc1" in names


def test_executor_grad():
    data = mx.sym.Variable("data")
    w = mx.sym.Variable("w")
    loss = mx.sym.LinearRegressionOutput(data * w, name="lro")
    ex = loss.bind(ctx=mx.cpu(),
                   args={"data": mx.nd.array([2.0]), "w": mx.nd.array([3.0]),
                         "lro_label": mx.nd.array([10.0])},
                   grad_req="write")
    ex.forward(is_train=True)
    ex.backward()
    # d/dw of 0.5*(w*d - y)^2-ish: reference grad = (out - label) * d
    g = ex.grad_dict["w"].asnumpy()
    np.testing.assert_allclose(g, [(6.0 - 10.0) * 2.0], rtol=1e-5)


def test_executor_reshape():
    out = _mlp_symbol()
    ex = out.simple_bind(ctx=mx.cpu(), data=(8, 10))
    ex2 = ex.reshape(data=(4, 10))
    assert ex2.arg_dict["data"].shape == (4, 10)
    # weights shared by reference
    assert ex2.arg_dict["fc1_weight"] is ex.arg_dict["fc1_weight"]


def test_module_fit():
    X, Y = _toy_data()
    it = mx.io.NDArrayIter(X, Y, batch_size=20, shuffle=True,
                           last_batch_handle="discard")
    mod = Module(_mlp_symbol(), context=mx.cpu())
    mod.fit(it, num_epoch=10, optimizer="sgd",
            optimizer_params={"learning_rate": 0.5})
    it.reset()
    score = mod.score(it, "acc")
    assert score[0][1] > 0.9, score


def test_module_predict_and_checkpoint(tmp_path):
    X, Y = _toy_data(50)
    it = mx.io.NDArrayIter(X, Y, batch_size=10)
    mod = Module(_mlp_symbol(), context=mx.cpu())
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod.init_params()
    preds = mod.predict(it)
    assert preds.shape == (50, 2)

    prefix = str(tmp_path / "model")
    mod.save_checkpoint(prefix, 1)
    sym2, arg2, aux2 = mx.model.load_checkpoint(prefix, 1)
    assert sym2.list_arguments() == mod.symbol.list_arguments()

    mod2 = Module.load(prefix, 1, context=mx.cpu())
    mod2.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod2.init_params_from_preload()
    it.reset()
    preds2 = mod2.predict(it)
    np.testing.assert_allclose(preds.asnumpy(), preds2.asnumpy(), rtol=1e-5)


def test_module_multi_device():
    X, Y = _toy_data()
    it = mx.io.NDArrayIter(X, Y, batch_size=40, last_batch_handle="discard")
    mod = Module(_mlp_symbol(), context=[mx.cpu(0), mx.cpu(1)])
    mod.fit(it, num_epoch=8, kvstore="device", optimizer="sgd",
            optimizer_params={"learning_rate": 0.5})
    it.reset()
    score = mod.score(it, "acc")
    assert score[0][1] > 0.9, score


def test_module_batchnorm_aux():
    data = mx.sym.Variable("data")
    bn = mx.sym.BatchNorm(data, name="bn", fix_gamma=False)
    fc = mx.sym.FullyConnected(bn, num_hidden=2, name="fc")
    out = mx.sym.SoftmaxOutput(fc, name="softmax")
    assert out.list_auxiliary_states() == ["bn_moving_mean", "bn_moving_var"]
    ex = out.simple_bind(ctx=mx.cpu(), data=(4, 3))
    ex.arg_dict["bn_gamma"][:] = 1.0
    ex.arg_dict["fc_weight"][:] = 0.1
    ex.arg_dict["data"][:] = np.random.rand(4, 3).astype(np.float32) * 5
    ex.forward(is_train=True)
    mm = ex.aux_dict["bn_moving_mean"].asnumpy()
    assert np.abs(mm).sum() > 0  # moving stats updated in train mode


def test_bucketing_module():
    def sym_gen(seq_len):
        # Param shapes must be bucket-invariant (reference bucketing
        # contract): reduce over the variable axis before the FC.
        data = mx.sym.Variable("data")
        pooled = mx.sym.mean(data, axis=1, keepdims=True)
        fc = mx.sym.FullyConnected(pooled, num_hidden=2, name="fc")
        out = mx.sym.SoftmaxOutput(fc, name="softmax")
        return out, ("data",), ("softmax_label",)

    mod = BucketingModule(sym_gen, default_bucket_key=10, context=mx.cpu())
    from mxnet_tpu.io import DataDesc, DataBatch

    mod.bind(data_shapes=[DataDesc("data", (4, 10))],
             label_shapes=[DataDesc("softmax_label", (4,))])
    mod.init_params()
    mod.init_optimizer(optimizer_params={"learning_rate": 0.1})
    for key in (10, 5, 10):
        batch = DataBatch(
            data=[mx.nd.ones((4, key))], label=[mx.nd.zeros((4,))],
            bucket_key=key,
            provide_data=[DataDesc("data", (4, key))],
            provide_label=[DataDesc("softmax_label", (4,))])
        mod.forward(batch, is_train=True)
        mod.backward()
        mod.update()
    assert set(mod._buckets) == {10, 5}


def test_module_multi_device_convergence():
    """2-device DP training converges on a separable toy problem
    (reference: tests/nightly/multi_lenet.py's multi-GPU DP check)."""
    X, Y = _toy_data(n=256, d=10, seed=3)
    mod = Module(_mlp_symbol(), context=[mx.cpu(0), mx.cpu(1)])
    from mxnet_tpu.io import DataBatch

    mod.bind(data_shapes=[("data", (64, 10))],
             label_shapes=[("softmax_label", (64,))])
    mod.init_params(initializer=mx.initializer.Xavier())
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.5})
    for epoch in range(30):
        for i in range(0, 256, 64):
            batch = DataBatch(data=[mx.nd.array(X[i:i + 64])],
                              label=[mx.nd.array(Y[i:i + 64])])
            mod.forward(batch, is_train=True)
            mod.backward()
            mod.update()
    out = []
    for i in range(0, 256, 64):
        batch = DataBatch(data=[mx.nd.array(X[i:i + 64])],
                          label=[mx.nd.array(Y[i:i + 64])])
        mod.forward(batch, is_train=False)
        out.append(mod.get_outputs()[0].asnumpy())
    pred = np.concatenate(out).argmax(axis=1)
    acc = (pred == Y).mean()
    assert acc > 0.9, "2-device DP failed to converge: acc=%.3f" % acc


def test_module_fixed_params_kvstore():
    """Frozen params must not move under the kvstore update path
    (ADVICE r1: fixed_param_names ignored in kvstore branch)."""
    from mxnet_tpu.io import DataBatch

    X, Y = _toy_data(n=64, d=10, seed=5)
    mod = Module(_mlp_symbol(), context=[mx.cpu(0), mx.cpu(1)],
                 fixed_param_names=["fc1_weight", "fc1_bias"])
    mod.bind(data_shapes=[("data", (64, 10))],
             label_shapes=[("softmax_label", (64,))])
    mod.init_params(initializer=mx.initializer.Xavier())
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.5})
    before = mod._execs[0].arg_dict["fc1_weight"].asnumpy().copy()
    moved = mod._execs[0].arg_dict["fc2_weight"].asnumpy().copy()
    batch = DataBatch(data=[mx.nd.array(X)], label=[mx.nd.array(Y)])
    mod.forward(batch, is_train=True)
    mod.backward()
    mod.update()
    after = mod._execs[0].arg_dict["fc1_weight"].asnumpy()
    assert np.allclose(before, after), "fixed param was updated"
    assert not np.allclose(moved, mod._execs[0].arg_dict["fc2_weight"].asnumpy())


def test_module_optimizer_states_checkpoint_roundtrip(tmp_path):
    """save_checkpoint(save_optimizer_states=True) → load_checkpoint +
    load_optimizer_states: momentum state survives the file round-trip,
    so one more identical update matches bit-for-bit (the module.py:340
    path — previously untested)."""
    from mxnet_tpu.io import DataBatch

    X, Y = _toy_data(n=40, d=10, seed=3)
    rng = np.random.RandomState(3)

    def one_step(mod, seed):
        r = np.random.RandomState(seed)
        idx = r.randint(0, len(X), 20)
        batch = DataBatch(data=[mx.nd.array(X[idx])],
                          label=[mx.nd.array(Y[idx])])
        mod.forward(batch, is_train=True)
        mod.backward()
        mod.update()

    mod = Module(_mlp_symbol(), context=mx.cpu())
    mod.bind(data_shapes=[("data", (20, 10))],
             label_shapes=[("softmax_label", (20,))])
    mod.init_params(initializer=mx.initializer.Xavier())
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.5,
                                         "momentum": 0.9})
    for s in range(3):
        one_step(mod, 100 + s)

    prefix = str(tmp_path / "opt_ckpt")
    mod.save_checkpoint(prefix, 3, save_optimizer_states=True)
    assert os.path.exists(prefix + "-0003.states")

    mod2 = Module.load(prefix, 3, context=mx.cpu())
    mod2.bind(data_shapes=[("data", (20, 10))],
              label_shapes=[("softmax_label", (20,))])
    mod2.init_params_from_preload()
    mod2.init_optimizer(optimizer="sgd",
                        optimizer_params={"learning_rate": 0.5,
                                          "momentum": 0.9})
    mod2.load_optimizer_states("%s-%04d.states" % (prefix, 3))

    # updater momentum buffers restored exactly
    s1 = mod._updater.states
    s2 = mod2._updater.states
    assert set(s1) == set(s2)

    def _flat(state):
        if isinstance(state, (list, tuple)):
            out = []
            for x in state:
                out.extend(_flat(x))
            return out
        return [state] if state is not None else []

    for k in s1:
        for a, b in zip(_flat(s1[k]), _flat(s2[k])):
            np.testing.assert_array_equal(np.asarray(a.asnumpy()),
                                          np.asarray(b.asnumpy()))

    # and the restored module continues identically to the original
    one_step(mod, 777)
    one_step(mod2, 777)
    a1, x1 = mod.get_params()
    a2, x2 = mod2.get_params()
    for k in a1:
        np.testing.assert_array_equal(a1[k].asnumpy(), a2[k].asnumpy())
