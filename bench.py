"""Benchmark: ResNet-50 v1 ImageNet-shape throughput, single chip —
against the reference's published numbers (docs/faq/perf.md; BASELINE.md):

- training  b32  fp32: 298.51 img/s (perf.md:214, 1x V100)
- training  b128 fp32: 363.69 img/s (perf.md:216)
- inference b32  fp32: 1076.81 img/s (perf.md:156)
- inference b32  fp16: 2085.51 img/s (perf.md:170) — our bf16 row
- training  b32  bf16: vs the same 298.51 fp32 row (reference published
  no fp16 training number; bf16-vs-their-best-fp32 is the honest compare)

Training steps are whole-step XLA executables (fwd + softmax CE + bwd +
SGD-momentum update, mxnet_tpu.parallel.TrainStep; bf16 rows use fp32
master weights — mp_sgd semantics). Inference is one jitted forward.

Measurement discipline (the chip is reached via an async relay where
``block_until_ready`` can ack before compute completes): every timed
window ends with a *host readback* of a scalar that data-depends on the
window's last step, and inference calls are chained through a scalar
carry so the whole window is one dependency chain. Inputs are placed on
device before timing (the reference's numbers are likewise
compute-bound, fed by a prefetching iterator).

Prints one JSON line per row; the LAST line is the headline metric
(train b32 fp32) for continuity with BENCH_r01/r02. Each row carries
est_mfu_bf16: achieved FLOP/s over the chip's bf16 peak (v5e ≈ 197
TFLOP/s), using 4.09 GFLOP/img forward and 3x that for training.
"""
from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

WARMUP = 3
WINDOWS = 7   # median-of-windows is robust to shared-chip contention
FWD_GFLOP_PER_IMG = 4.09          # ResNet-50 224x224 forward
TRAIN_GFLOP_PER_IMG = 3 * FWD_GFLOP_PER_IMG
PEAK_TFLOPS_BF16 = {"v5e": 197.0, "v5p": 459.0, "v4": 275.0}.get(
    os.environ.get("PALLAS_AXON_TPU_GEN", "v5e"), 197.0)


def _measure(run_once, read_scalar, batch, iters):
    """Median img/s over WINDOWS; each window = `iters` dependent calls
    closed by a host readback (`read_scalar`) proving completion."""
    for _ in range(WARMUP):
        out = run_once()
    read_scalar(out)
    rates = []
    for _ in range(WINDOWS):
        t0 = time.perf_counter()
        for _ in range(iters):
            out = run_once()
        read_scalar(out)
        rates.append(batch * iters / (time.perf_counter() - t0))
    return sorted(rates)[len(rates) // 2]


def _emit(metric, value, unit):
    print(json.dumps({"metric": metric, "value": value, "unit": unit}),
          flush=True)


def _row(metric, img_s, baseline, gflop_per_img):
    print(json.dumps({
        "metric": metric,
        "value": round(img_s, 2),
        "unit": "img/s",
        "vs_baseline": round(img_s / baseline, 4),
        "est_mfu_bf16": round(img_s * gflop_per_img / 1e3
                              / PEAK_TFLOPS_BF16, 4),
    }), flush=True)
    return img_s


def _train_rate(batch, dtype, device):
    """Training rows run THROUGH the example driver (the reference's
    numbers are measured through train_imagenet.py the same way)."""
    import sys

    examples_dir = os.path.join(os.path.dirname(
        os.path.abspath(__file__)), "examples")
    if examples_dir not in sys.path:
        sys.path.insert(0, examples_dir)
    from train_imagenet import benchmark_rate

    # Small batches get longer windows: per-step dispatch latency
    # through the device tunnel is the noise floor.
    return benchmark_rate("resnet50", batch, dtype, device=device,
                          iters=16 if batch <= 32 else 10,
                          windows=WINDOWS, warmup=WARMUP)


def _infer_rate(batch, dtype, device):
    import jax
    import jax.numpy as jnp
    import mxnet_tpu as mx
    from mxnet_tpu import autograd
    from mxnet_tpu.gluon.model_zoo import vision
    from mxnet_tpu.gluon.parameter import override
    from mxnet_tpu.ndarray.ndarray import NDArray

    net = vision.resnet50_v1(classes=1000)
    net.initialize()
    with autograd.pause():
        net(mx.nd.ones((1, 3, 224, 224)))
    params = list(net.collect_params().values())
    cdt = jnp.dtype(dtype) if dtype else jnp.float32
    pvals = {p.name: jax.device_put(
        p.data()._data.astype(cdt)
        if jnp.issubdtype(p.data()._data.dtype, jnp.floating)
        else p.data()._data, device) for p in params}

    def fwd(pv, xb, carry):
        # carry chains successive calls into one dependency chain.
        xb = xb + jnp.asarray(carry, xb.dtype)
        mapping = {p: NDArray(pv[p.name]) for p in params}
        with autograd.pause(train_mode=False), override(mapping):
            out = net(NDArray(xb))._data
        return jnp.mean(out.astype(jnp.float32)) * 1e-6

    jfwd = jax.jit(fwd)
    rng = np.random.RandomState(0)
    xs = [jax.device_put(
        rng.rand(batch, 3, 224, 224).astype(np.float32), device).astype(cdt)
        for _ in range(4)]
    carry = {"i": 0, "v": jnp.float32(0)}

    def run_once():
        carry["v"] = jfwd(pvals, xs[carry["i"] % len(xs)], carry["v"])
        carry["i"] += 1
        return carry["v"]

    return _measure(run_once, lambda tap: float(tap), batch, iters=20)


def _serving_rows():
    """Serving section (mxnet_tpu.serving): single-request latency vs
    batched throughput at bucket sizes 1/8/32, plus the coalescing rate
    under concurrent batch-1 load. Rows ride the default device; the
    measured path includes host batch assembly + one upload per device
    call — the real serving hot path, not just the executable."""
    from concurrent.futures import ThreadPoolExecutor

    import mxnet_tpu as mx
    from mxnet_tpu import serving

    rng = np.random.RandomState(0)
    w1 = mx.nd.array(rng.randn(784, 256).astype(np.float32) * 0.05)
    b1 = mx.nd.zeros((256,))
    w2 = mx.nd.array(rng.randn(256, 10).astype(np.float32) * 0.05)

    def fwd(w1, b1, w2, x):
        return mx.nd.dot(mx.nd.relu(mx.nd.dot(x, w1) + b1), w2)

    # Per-bucket device throughput: a single-bucket server makes every
    # sequential full-bucket predict() dispatch immediately (rows ==
    # max_batch) — no max_delay_ms batching-window stall in the number.
    for b in (1, 8, 32):
        sb = serving.InferenceServer(fwd, [w1, b1, w2], item_shape=(784,),
                                     buckets=(b,), max_delay_ms=0)
        try:
            xb = rng.rand(b, 784).astype(np.float32)
            for _ in range(3):
                sb.predict(xb)                # warm the path
            t0 = time.perf_counter()
            n = 30
            for _ in range(n):
                sb.predict(xb)
            _emit("serving_mlp_rows_per_sec_b%d" % b,
                  round(b * n / (time.perf_counter() - t0), 1), "rows/s")
        finally:
            sb.shutdown()

    srv = serving.InferenceServer(fwd, [w1, b1, w2], item_shape=(784,),
                                  buckets=(1, 8, 32), max_delay_ms=2,
                                  max_queue=1024)
    try:
        # Single-request latency INCLUDES the batching window — the
        # real cost a lone client pays on a ladder server.
        lat = []
        x1 = rng.rand(1, 784).astype(np.float32)
        for _ in range(50):
            t0 = time.perf_counter()
            srv.predict(x1)
            lat.append(time.perf_counter() - t0)
        lat.sort()
        _emit("serving_mlp_single_request_p50_ms",
              round(lat[len(lat) // 2] * 1e3, 3), "ms")
        reqs = [rng.rand(1, 784).astype(np.float32) for _ in range(256)]
        t0 = time.perf_counter()
        with ThreadPoolExecutor(16) as pool:
            futs = list(pool.map(srv.submit, reqs))
        for f in futs:
            f.result()
        _emit("serving_mlp_coalesced_req_per_sec",
              round(len(reqs) / (time.perf_counter() - t0), 1), "req/s")
    finally:
        srv.shutdown()


def _serving_gateway_rows():
    """Gateway section (mxnet_tpu.serving.gateway, ISSUE 15): 2-model
    mixed load with a mid-run zero-drop hot swap and SLO-coupled
    shedding. One model ("hot") is flooded past an unmeetable SLO so
    its lowest deadline class sheds; the other ("steady") runs moderate
    load and is hot-swapped mid-run. THE CONTRACT ROWS:

    - gateway_swap_dropped_requests == 0 — no request is dropped by
      the swap (sheds on the hot model's lowest class are the POLICY
      working, counted separately);
    - gateway_protected_p99_ms <= 250 — the non-overloaded model's p99
      stays pinned while the other model burns and sheds.
    """
    import threading

    import mxnet_tpu as mx
    from mxnet_tpu.serving import ModelGateway, ModelSpec, \
        ServiceUnavailableError, QueueFullError, hot_swap

    rng = np.random.RandomState(0)

    def mlp_params(scale):
        return [mx.nd.array(rng.randn(784, 256).astype(np.float32)
                            * scale),
                mx.nd.zeros((256,)),
                mx.nd.array(rng.randn(256, 10).astype(np.float32)
                            * scale)]

    def fwd(w1, b1, w2, x):
        return mx.nd.dot(mx.nd.relu(mx.nd.dot(x, w1) + b1), w2)

    gw = ModelGateway(max_queue=512, max_delay_ms=2.0,
                      burn_windows=(0.5, 2.0), eval_interval_s=0.1,
                      shed_burn_rate=5.0)
    dropped = []        # hard failures (the contract quantity)
    sheds = []          # policy sheds on the hot model's lowest class
    results = {"hot": 0, "steady": 0}
    stop = threading.Event()
    lock = threading.Lock()
    try:
        gw.register(ModelSpec(
            "hot", fn=fwd, params=mlp_params(0.05), item_shape=(784,),
            max_batch=32, weight=1.0,
            deadline_classes=(("interactive", None), ("best_effort",
                                                      None)),
            slo=(0.99, 0.0005)))     # unmeetable: every request burns
        gw.register(ModelSpec(
            "steady", fn=fwd, params=mlp_params(0.05), item_shape=(784,),
            max_batch=32, weight=1.0))

        def hammer(model, cls, n_rows):
            x = rng.rand(n_rows, 784).astype(np.float32)
            while not stop.is_set():
                try:
                    gw.predict(model, x, deadline_class=cls)
                    with lock:
                        results[model] += 1
                except (ServiceUnavailableError, QueueFullError) as exc:
                    if model == "hot":
                        with lock:
                            sheds.append(exc)
                    else:
                        with lock:
                            dropped.append(exc)
                except Exception as exc:
                    with lock:
                        dropped.append(exc)

        threads = [threading.Thread(target=hammer,
                                    args=("hot", "interactive", 4))
                   for _ in range(2)]
        threads += [threading.Thread(target=hammer,
                                     args=("hot", "best_effort", 4))
                    for _ in range(2)]
        threads += [threading.Thread(target=hammer,
                                     args=("steady", "default", 4))
                    for _ in range(2)]
        for t in threads:
            t.start()
        time.sleep(1.5)              # let the burn monitor see the SLO
        t0 = time.perf_counter()
        gen = hot_swap(gw, "steady", params=mlp_params(0.07))
        swap_ms = (time.perf_counter() - t0) * 1e3
        time.sleep(1.5)
        stop.set()
        for t in threads:
            t.join(30)
        stats = gw.stats()
        shedding_seen = len(sheds) > 0 or \
            stats["hot"]["shed"].get("slo_burn:best_effort", 0) > 0
        # THE CONTRACT ROW: the swap (and the hot model's overload)
        # dropped nothing — every steady request and every non-shed hot
        # request completed.
        _emit("gateway_swap_dropped_requests", len(dropped), "req")
        _emit("gateway_swap_generation", gen, "gen")
        _emit("gateway_swap_total_ms", round(swap_ms, 1), "ms")
        # THE CONTRACT ROW: the healthy model's p99 while the other
        # model burned and shed.
        _emit("gateway_protected_p99_ms",
              round(stats["steady"]["p99_ms"], 2), "ms")
        _emit("gateway_hot_p99_ms", round(stats["hot"]["p99_ms"], 2),
              "ms")
        # registry counter only: the client-observed `sheds` list is
        # the SAME events (submit increments the counter, then raises).
        _emit("gateway_hot_sheds",
              int(stats["hot"]["shed"].get("slo_burn:best_effort", 0)),
              "req")
        _emit("gateway_slo_shedding_engaged", int(shedding_seen), "bool")
        _emit("gateway_steady_req_per_sec", round(results["steady"] / 3.0,
                                                  1), "req/s")
        _emit("gateway_hot_req_per_sec", round(results["hot"] / 3.0, 1),
              "req/s")
    finally:
        stop.set()
        gw.shutdown()


def _continuous_batching_rows():
    """Continuous batching section (mxnet_tpu.serving.continuous,
    ISSUE 19): iteration-level slot scheduling vs a static batch on the
    SAME backend at a geometric sequence-length mix. THE CONTRACT ROWS:

    - continuous_batching_tokens_per_sec_speedup >= 2.0 — the static
      regime steps every batch max(L) times to earn mean(L) tokens per
      slot; per-iteration retire/admit reclaims the difference;
    - decode_steady_state_retraces == 0 — compile count flat across
      the whole run (>= 100 steps of admit/retire churn) after warm().

    Plus an informative p99 TTFT row while the batch is saturated.
    """
    import mxnet_tpu as mx
    from mxnet_tpu.serving import DecodeConfig, DecodeLoop, ModelSpec
    from mxnet_tpu.telemetry import metrics as _tm

    H, B, N, REPS = 1536, 32, 384, 3
    rng = np.random.RandomState(3)
    w = mx.nd.array((rng.rand(H, H).astype(np.float32) - 0.5) * 0.05)

    def step(w_, state, tokens, pos):
        return mx.nd.tanh(mx.nd.dot(state, w_)), tokens + 1

    spec = ModelSpec(
        "bench_decode", params=[w], max_batch=B,
        decode=DecodeConfig(step, state_shape=(H,), page_slots=4,
                            max_tokens=128))
    backend = spec.build_backend()
    backend.warm()
    warm_compiles = backend.compile_count
    # Geometric length mix: many short, a heavy tail of long — the
    # regime static batching wastes (each batch runs max(L) steps over
    # the FULL batch width, mostly on rows that already finished).
    lengths = np.clip(
        np.random.RandomState(7).geometric(1 / 10.0, size=N), 1, 128)
    total_tokens = int(lengths.sum())

    def static_pass():
        # Static baseline: same backend, batch-synchronous — admit B
        # sequences, step until the LONGEST finishes, repeat.
        # Admission (slot-state init) is paid per sequence in both
        # regimes; past that the inline loop has strictly less host
        # overhead than the scheduler, so the comparison is
        # conservative.
        tokens = np.zeros(B, np.int32)
        pos = np.zeros(B, np.int32)
        steps = 0
        t0 = time.perf_counter()
        for i in range(0, N, B):
            batch = lengths[i:i + B]
            n_pages = backend.page_count(len(batch))
            active = np.zeros(B, bool)
            for slot in range(len(batch)):
                tokens[slot] = backend.admit(
                    slot, np.asarray([1], np.int32))
            for s in range(int(batch.max())):
                active[:len(batch)] = s < batch
                backend.step(n_pages, tokens, pos, active)
                steps += 1
        return time.perf_counter() - t0, steps

    steps_fam = _tm.REGISTRY.get("mx_decode_steps_total")

    def continuous_pass():
        steps0 = steps_fam.labels(model="bench_decode").value
        loop = DecodeLoop(spec, backend)
        try:
            t0 = time.perf_counter()
            seqs = [loop.submit([int(n) % 97 + 1], max_tokens=int(n))
                    for n in lengths]
            for s in seqs:
                s.future.result(timeout=300)
            dt = time.perf_counter() - t0
            steps = int(steps_fam.labels(model="bench_decode").value
                        - steps0)
            p99 = loop.stats()["p99_ttft_ms"]
        finally:
            loop.close()
        return dt, steps, p99

    # Paired repetitions, median speedup — the same median-of-windows
    # discipline as the training rows (robust to shared-CPU noise).
    runs = []
    for _ in range(REPS):
        static_s, static_steps = static_pass()
        cont_s, cont_steps, p99_ttft = continuous_pass()
        runs.append((cont_s, static_s, cont_steps, static_steps,
                     p99_ttft))
    cont_s, static_s, cont_steps, static_steps, p99_ttft = sorted(
        runs, key=lambda r: r[1] / r[0])[REPS // 2]
    static_tps = total_tokens / static_s
    cont_tps = total_tokens / cont_s

    _emit("decode_tokens_per_sec_continuous", round(cont_tps, 1),
          "tok/s")
    _emit("decode_tokens_per_sec_static", round(static_tps, 1), "tok/s")
    # THE CONTRACT ROW (>= 2.0).
    _emit("continuous_batching_tokens_per_sec_speedup",
          round(cont_tps / static_tps, 3), "x")
    # THE CONTRACT ROW (== 0): zero retraces across every static sweep
    # AND >= 100 continuous steps of admit/retire churn per rep, all
    # post-warm.
    _emit("decode_steady_state_retraces",
          int(backend.compile_count - warm_compiles), "compiles")
    _emit("decode_churn_steps", cont_steps, "steps")
    _emit("decode_static_steps", static_steps, "steps")
    _emit("decode_warm_compiles", warm_compiles, "compiles")
    # Informative: admission latency while every slot is contended.
    _emit("decode_p99_ttft_ms", round(p99_ttft, 2), "ms")


def _telemetry_rows():
    """Telemetry section (mxnet_tpu.telemetry): instrumentation overhead
    on the step path. The SAME TrainStep loop is timed with telemetry
    fully disabled (set_enabled(False): spans and metric updates reduce
    to a boolean check) and fully enabled (registry histograms + trace
    rings + a StepMonitor fed each step — the production configuration).
    THE CONTRACT ROW: telemetry_step_overhead_pct <= 2%."""
    import mxnet_tpu as mx
    from mxnet_tpu import gluon, telemetry
    from mxnet_tpu.parallel import TrainStep, make_mesh

    mx.random.seed(13)
    rng = np.random.RandomState(13)
    net = gluon.nn.HybridSequential(prefix="bench_tel_")
    net.add(gluon.nn.Dense(1024, activation="relu", in_units=784,
                           prefix="fc1_"))
    net.add(gluon.nn.Dense(1024, activation="relu", in_units=1024,
                           prefix="fc2_"))
    net.add(gluon.nn.Dense(10, in_units=1024, prefix="fc3_"))
    net.initialize(mx.init.Xavier())
    step = TrainStep(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                     optimizer="sgd",
                     optimizer_params={"learning_rate": 0.05,
                                       "momentum": 0.9},
                     mesh=make_mesh())
    x = rng.rand(256, 784).astype(np.float32)
    y = rng.randint(0, 10, 256)
    for _ in range(3):                      # compile + settle
        float(np.asarray(step(x, y)))

    iters = 50
    monitor = telemetry.StepMonitor(warn_interval_s=3600)

    def timed(observe):
        times = []
        for _ in range(iters):
            t0 = time.perf_counter()
            loss = step(x, y)
            float(np.asarray(loss))         # close the step like a real loop
            if observe:
                # The monitor's own cost (EWMA, backlog poll, anomaly
                # path) is part of the configuration under contract, so
                # it lands INSIDE the timed window.
                monitor.observe_step(time.perf_counter() - t0)
            times.append(time.perf_counter() - t0)
        return sorted(times)[len(times) // 2]

    prev = telemetry.set_enabled(False)
    try:
        off_ms = timed(observe=False) * 1e3
        telemetry.set_enabled(True)
        on_ms = timed(observe=True) * 1e3
    finally:
        telemetry.set_enabled(prev)

    _emit("telemetry_step_ms_off", round(off_ms, 3), "ms")
    _emit("telemetry_step_ms_on", round(on_ms, 3), "ms")
    # THE CONTRACT ROW: span recording + registry updates on the step
    # path must cost <= 2% of the step. Negative values are measurement
    # noise (the instrumentation is sub-µs against a ms-scale step).
    _emit("telemetry_step_overhead_pct",
          round((on_ms - off_ms) / off_ms * 100.0, 2), "%")


def _telemetry_dist_rows():
    """Pod-observability section (ISSUE 5): what the cross-process
    machinery costs on the step path. The SAME TrainStep loop is timed
    bare, then with (a) registry aggregation at a fixed every-10-steps
    cadence (snapshot + LocalBus push + rank-0 merge — the full
    per-round work a dist job pays, minus only the TCP hop, which is
    pipelined/ack-deferred on the real transport) and (b) streaming
    trace export ticked every step (ring drain + rotation check;
    commits amortized by the size/age budget). THE CONTRACT ROWS:
    telemetry_aggregation_overhead_pct <= 2%,
    trace_streaming_step_overhead_pct <= 1%."""
    import shutil
    import tempfile

    import mxnet_tpu as mx
    from mxnet_tpu import gluon, telemetry
    from mxnet_tpu.telemetry import aggregate, export
    from mxnet_tpu.parallel import TrainStep, make_mesh

    mx.random.seed(17)
    rng = np.random.RandomState(17)
    net = gluon.nn.HybridSequential(prefix="bench_teld_")
    net.add(gluon.nn.Dense(1024, activation="relu", in_units=784,
                           prefix="fc1_"))
    net.add(gluon.nn.Dense(1024, activation="relu", in_units=1024,
                           prefix="fc2_"))
    net.add(gluon.nn.Dense(10, in_units=1024, prefix="fc3_"))
    net.initialize(mx.init.Xavier())
    step = TrainStep(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                     optimizer="sgd",
                     optimizer_params={"learning_rate": 0.05},
                     mesh=make_mesh())
    x = rng.rand(256, 784).astype(np.float32)
    y = rng.randint(0, 10, 256)
    for _ in range(3):                      # compile + settle
        float(np.asarray(step(x, y)))

    iters = 50

    def timed(per_step):
        times = []
        for i in range(iters):
            t0 = time.perf_counter()
            loss = step(x, y)
            float(np.asarray(loss))
            per_step(i)                     # cost under contract
            times.append(time.perf_counter() - t0)
        return times

    def _mean(ts):
        return sum(ts) / len(ts)

    base = timed(lambda i: None)

    bus = aggregate.LocalBus(num_workers=1)
    agg = aggregate.Aggregator(bus.endpoint(0), interval_s=1e9)
    agg_times = timed(lambda i: agg.step() if i % 10 == 0 else None)

    seg_dir = tempfile.mkdtemp(prefix="bench_trace_seg_")
    writer = export.StreamingTraceWriter(seg_dir)
    stream = timed(lambda i: writer.tick())
    writer.close()
    shutil.rmtree(seg_dir, ignore_errors=True)

    # Aggregation lands on 1 step in 10: the contract is on the MEAN
    # (the amortized per-step cost at the cadence — a median would
    # always pick one of the 9 untouched steps and could never fail).
    # Streaming ticks EVERY step, so its median is the honest center.
    base_mean_ms = _mean(base) * 1e3
    agg_mean_ms = _mean(agg_times) * 1e3
    base_med_ms = sorted(base)[len(base) // 2] * 1e3
    stream_med_ms = sorted(stream)[len(stream) // 2] * 1e3

    _emit("telemetry_dist_step_ms_base", round(base_mean_ms, 3), "ms")
    _emit("telemetry_dist_step_ms_aggregated",
          round(agg_mean_ms, 3), "ms")
    _emit("telemetry_dist_step_ms_streaming",
          round(stream_med_ms, 3), "ms")
    # THE CONTRACT ROWS (negatives are measurement noise: both hooks
    # are µs-scale against a ms-scale step).
    _emit("telemetry_aggregation_overhead_pct",
          round((agg_mean_ms - base_mean_ms) / base_mean_ms * 100.0, 2),
          "%")
    _emit("trace_streaming_step_overhead_pct",
          round((stream_med_ms - base_med_ms) / base_med_ms * 100.0, 2),
          "%")


def _xtrace_rows():
    """Causal-tracing section (ISSUE 18): what cross-process trace
    propagation costs on the trainer step path. The SAME
    ``gluon.Trainer`` loop (fused kvstore step: root context per step,
    context-carrying reduce tasks, per-key spans) is timed with head
    sampling OFF (``MXNET_TRACE_SAMPLE=0``: contexts still mint and
    propagate — the designed cheap path — but stamp nothing) and ON
    (rate 1.0 + trace-id exemplars: every span stamps
    trace_id/parent_span_id, the production forensics configuration).
    THE CONTRACT ROW: trace_propagation_step_overhead_pct <= 1%."""
    import time as _t

    import mxnet_tpu as mx
    from mxnet_tpu import gluon, nd
    from mxnet_tpu import kvstore as kvs
    from mxnet_tpu.telemetry import xtrace

    rng = np.random.RandomState(7)
    params = []
    for k in range(300):
        p = gluon.Parameter("xt_bench_%d" % k, shape=(1024,))
        p.initialize(init=mx.init.Constant(0.0))
        p.set_data(nd.array(rng.randn(1024).astype(np.float32)))
        params.append(p)
    trainer = gluon.Trainer(
        params, "sgd", {"learning_rate": 0.05, "momentum": 0.9},
        kvstore=kvs.KVStoreLocal(device_mode=True),
        update_on_kvstore=False)
    for p in params:
        p.grad()[:] = rng.randn(1024).astype(np.float32)
    trainer.step(1)                         # warmup: compile + init
    params[-1].data().asnumpy()

    iters = 30

    def timed():
        times = []
        for _ in range(iters):
            t0 = _t.perf_counter()
            trainer.step(1)
            params[-1].data().asnumpy()
            times.append(_t.perf_counter() - t0)
        return sorted(times)[len(times) // 2] * 1e3

    prev_rate = xtrace.set_sample_rate(0.0)
    try:
        off_ms = timed()
        xtrace.set_sample_rate(1.0)
        xtrace.install_exemplars(True)
        on_ms = timed()
    finally:
        xtrace.install_exemplars(False)
        xtrace.set_sample_rate(prev_rate)

    _emit("xtrace_step_ms_unsampled", round(off_ms, 3), "ms")
    _emit("xtrace_step_ms_sampled", round(on_ms, 3), "ms")
    # THE CONTRACT ROW: stamping every span with its trace context and
    # recording trace-id exemplars must cost <= 1% of the step path.
    # Negative values are measurement noise (the stamp is a dict
    # setdefault against a ms-scale step).
    _emit("trace_propagation_step_overhead_pct",
          round((on_ms - off_ms) / off_ms * 100.0, 2), "%")


def _diagnostics_rows():
    """Diagnostics section (ISSUE 7): what failure forensics costs when
    nothing is failing. THE CONTRACT ROWS:
    numeric_guard_step_overhead_pct <= 2 (an every-step NumericGuard
    loss check — the isfinite read piggybacks on the loss readback a
    real loop already pays) and watchdog_idle_overhead_pct <= 1 (a
    running HangWatchdog: TrainStep's begin/end heartbeats plus the
    4 Hz scan thread amortized over the step).

    Measurement discipline: an A/A interleaved-min experiment on this
    shared-core box shows a ±9% noise floor on the ms-scale step —
    loop-level A/B timing cannot resolve a 1-2% bound, it can only
    flap. The contract rows therefore measure the HOOKS directly
    (thousands of calls against a settled loss / armed lanes — they
    are µs-scale, trivially resolvable) and express the exact per-step
    addition as a percentage of the interleaved median step time; the
    wall-clock A/B rows stay as informative context. A
    flight-recorder capture is also timed (informative): the one-off
    cost of producing a bundle at the moment of failure."""
    import shutil
    import tempfile

    import mxnet_tpu as mx
    from mxnet_tpu import gluon, telemetry
    from mxnet_tpu.parallel import TrainStep, make_mesh

    mx.random.seed(23)
    rng = np.random.RandomState(23)
    net = gluon.nn.HybridSequential(prefix="bench_diag_")
    net.add(gluon.nn.Dense(1024, activation="relu", in_units=784,
                           prefix="fc1_"))
    net.add(gluon.nn.Dense(1024, activation="relu", in_units=1024,
                           prefix="fc2_"))
    net.add(gluon.nn.Dense(10, in_units=1024, prefix="fc3_"))
    net.initialize(mx.init.Xavier())
    step = TrainStep(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                     optimizer="sgd",
                     optimizer_params={"learning_rate": 0.05},
                     mesh=make_mesh())
    x = rng.rand(256, 784).astype(np.float32)
    y = rng.randint(0, 10, 256)
    for _ in range(3):                      # compile + settle
        float(np.asarray(step(x, y)))

    def one(per_step, i):
        t0 = time.perf_counter()
        loss = step(x, y)
        float(np.asarray(loss))             # close the step like a real loop
        per_step(i, loss)                   # cost under contract
        return time.perf_counter() - t0

    from mxnet_tpu.telemetry import watchdog as _wdmod

    noop = lambda i, loss: None             # noqa: E731

    # Informative wall rows: interleaved (alternating pair order, so
    # neither config owns a slot a periodic background load could
    # systematically tax), each config's median. Expect these to agree
    # within this box's noise floor — the contract rows below are the
    # resolvable measurement.
    guard = telemetry.NumericGuard(every=1)
    check = lambda i, loss: guard.check_loss(loss, step=i)  # noqa: E731
    watchdog = telemetry.HangWatchdog(min_deadline_s=30.0,
                                      poll_s=0.25).start()
    base_t, guard_t = [], []
    try:
        for i in range(30):
            for which in ((0, 1) if i % 2 == 0 else (1, 0)):
                if which == 0:
                    base_t.append(one(noop, i))
                else:
                    guard_t.append(one(check, i))
    finally:
        watchdog.close()
    base_ms = sorted(base_t)[len(base_t) // 2] * 1e3
    guard_ms = sorted(guard_t)[len(guard_t) // 2] * 1e3
    _emit("diagnostics_step_ms_base", round(base_ms, 3), "ms")
    _emit("diagnostics_step_ms_guarded_watchdogged",
          round(guard_ms, 3), "ms")

    # CONTRACT: numeric guard. Per step (every=1 cadence) the guard
    # adds exactly one check_loss call; measure it directly against a
    # settled loss (the real loop checks a loss it reads anyway).
    loss = step(x, y)
    float(np.asarray(loss))
    reps = 2000
    t0 = time.perf_counter()
    for i in range(reps):
        guard.check_loss(loss, step=i)
    check_ms = (time.perf_counter() - t0) / reps * 1e3
    _emit("numeric_guard_check_ms", round(check_ms, 5), "ms")
    _emit("numeric_guard_step_overhead_pct",
          round(check_ms / base_ms * 100.0, 3), "%")

    # CONTRACT: idle watchdog. Per step the lanes add one begin+end
    # pair; the 4 Hz scan thread adds scan cost amortized over the
    # steps that fit in a poll interval.
    t0 = time.perf_counter()
    for _ in range(reps):
        _wdmod.begin("step")
        _wdmod.end("step")
    hb_ms = (time.perf_counter() - t0) / reps * 1e3
    scanner = telemetry.HangWatchdog(min_deadline_s=30.0, poll_s=0.25)
    t0 = time.perf_counter()
    for _ in range(reps):
        scanner.check()
    scan_ms = (time.perf_counter() - t0) / reps * 1e3
    scan_per_step_ms = scan_ms * (base_ms / 1e3) / scanner.poll_s
    wd_step_ms = hb_ms + scan_per_step_ms
    _emit("watchdog_heartbeat_ms", round(hb_ms, 5), "ms")
    _emit("watchdog_scan_ms", round(scan_ms, 5), "ms")
    _emit("watchdog_idle_overhead_pct",
          round(wd_step_ms / base_ms * 100.0, 3), "%")

    # Bundle capture cost (off the hot path — paid once per rate-limited
    # anomaly, at the moment of failure).
    diag_dir = tempfile.mkdtemp(prefix="bench_diag_")
    try:
        recorder = telemetry.FlightRecorder(diag_dir, rank=0)
        t0 = time.perf_counter()
        path = recorder.capture("bench", "diagnostics bench capture")
        capture_ms = (time.perf_counter() - t0) * 1e3
        size_kb = os.path.getsize(path) / 1e3 if path else 0.0
        _emit("diag_bundle_capture_ms", round(capture_ms, 3), "ms")
        _emit("diag_bundle_size_kb", round(size_kb, 1), "KB")
    finally:
        shutil.rmtree(diag_dir, ignore_errors=True)


def _healthplane_rows():
    """Health-plane section (ISSUE 8): what operating the pod from
    outside costs the step path. THE CONTRACT ROW:
    push_export_step_overhead_pct <= 1 — a PushExporter snapshotting
    the whole registry and handing it to the transport every 10 steps
    (the gateway hop itself is network time off the critical path; an
    in-memory transport isolates the render+buffer cost the LOOP pays).

    Measurement discipline (the diagnostics-section rule): this box's
    ms-scale step has a ±9% A/B noise floor — a 1% bound is resolved by
    measuring the HOOK directly (hundreds of push() calls against the
    live registry) and expressing the amortized per-step cost at the
    every-10-steps cadence as a percentage of the median step; the
    wall-clock A/B row stays as informative context. Informative:
    health_endpoint_probe_ms — wall time of one GET /healthz against a
    live MetricsServer with the HealthPlane mounted (an orchestrator's
    liveness probe; served off-thread, so this is probe latency, not
    step cost)."""
    import urllib.request

    import mxnet_tpu as mx
    from mxnet_tpu import gluon, telemetry
    from mxnet_tpu.telemetry import export
    from mxnet_tpu.parallel import TrainStep, make_mesh

    mx.random.seed(29)
    rng = np.random.RandomState(29)
    net = gluon.nn.HybridSequential(prefix="bench_hp_")
    net.add(gluon.nn.Dense(1024, activation="relu", in_units=784,
                           prefix="fc1_"))
    net.add(gluon.nn.Dense(1024, activation="relu", in_units=1024,
                           prefix="fc2_"))
    net.add(gluon.nn.Dense(10, in_units=1024, prefix="fc3_"))
    net.initialize(mx.init.Xavier())
    step = TrainStep(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                     optimizer="sgd",
                     optimizer_params={"learning_rate": 0.05},
                     mesh=make_mesh())
    x = rng.rand(256, 784).astype(np.float32)
    y = rng.randint(0, 10, 256)
    for _ in range(3):                      # compile + settle
        float(np.asarray(step(x, y)))

    iters = 50

    def timed(per_step):
        times = []
        for i in range(iters):
            t0 = time.perf_counter()
            loss = step(x, y)
            float(np.asarray(loss))
            per_step(i)                     # cost under contract
            times.append(time.perf_counter() - t0)
        return times

    def _mean(ts):
        return sum(ts) / len(ts)

    base = timed(lambda i: None)

    sunk = []
    exporter = export.PushExporter(
        "http://bench.invalid:9091", interval_s=1e9,
        transport=lambda url, body: sunk.append(len(body)))
    pushed = timed(lambda i: exporter.push() if i % 10 == 0 else None)

    base_mean_ms = _mean(base) * 1e3
    base_med_ms = sorted(base)[len(base) // 2] * 1e3
    push_mean_ms = _mean(pushed) * 1e3
    _emit("healthplane_step_ms_base", round(base_mean_ms, 3), "ms")
    _emit("healthplane_step_ms_push_exported",
          round(push_mean_ms, 3), "ms")

    # THE CONTRACT ROW: direct hook measurement — render + bounded
    # buffer + in-memory transport per push, amortized over the
    # every-10-steps cadence against the median step.
    reps = 200
    t0 = time.perf_counter()
    for _ in range(reps):
        exporter.push()
    push_ms = (time.perf_counter() - t0) / reps * 1e3
    _emit("push_export_snapshot_ms", round(push_ms, 4), "ms")
    _emit("push_export_step_overhead_pct",
          round(push_ms / 10.0 / base_med_ms * 100.0, 3), "%")

    # Probe latency against a real endpoint (informative).
    plane = telemetry.healthplane.HealthPlane()
    server = telemetry.start_http_server(0, health=plane)
    try:
        url = "http://%s:%d/healthz" % server.server_address
        urllib.request.urlopen(url, timeout=10).read()   # warm
        probes = []
        for _ in range(20):
            t0 = time.perf_counter()
            urllib.request.urlopen(url, timeout=10).read()
            probes.append(time.perf_counter() - t0)
        _emit("health_endpoint_probe_ms",
              round(sorted(probes)[len(probes) // 2] * 1e3, 3), "ms")
    finally:
        server.close()


def _profiling_rows():
    """Profiling section (ISSUE 12): what always-on continuous
    profiling costs the step path, plus the attribution plane's
    phase/FLOPs rows. THE CONTRACT ROW:
    continuous_profiler_step_overhead_pct <= 1 — the sampler at its
    default rate (MXNET_PROFILE_HZ) against the step path.

    Measurement discipline (the diagnostics/healthplane-section rule):
    this box's ms-scale step has a ±9% A/B noise floor, so the 1% bound
    is resolved by measuring the HOOK directly — hundreds of
    ``sample()`` calls against the live thread set — and expressing
    per-sample cost × default Hz as a percentage of wall time (the
    sampler's steady-state duty cycle; its window folding is part of
    the sampled call). The sampler-on vs sampler-off wall A/B stays as
    informative context. Also informative: attribution-derived phase
    shares + bound cause over an attributed run (device spans on, so
    each step is host-synchronous there — that bracket is attribution's
    documented price, not the profiler's), and achieved GFLOP/s from
    ``cost_analysis()`` flops at the train_step compile seam."""
    import shutil
    import tempfile

    import mxnet_tpu as mx
    from mxnet_tpu import compile as cc, gluon, telemetry
    from mxnet_tpu.telemetry import attribution as tattr
    from mxnet_tpu.parallel import TrainStep, make_mesh

    mx.random.seed(31)
    rng = np.random.RandomState(31)
    # The compile cache routes TrainStep through maybe_cached_jit's
    # CachedFunction, whose seam records cost_analysis() flops — the
    # achieved-FLOPs row's input.
    cache_dir = tempfile.mkdtemp(prefix="bench_cc_prof_")
    cc.configure(cache_dir)
    try:
        net = gluon.nn.HybridSequential(prefix="bench_prof_")
        net.add(gluon.nn.Dense(1024, activation="relu", in_units=784,
                               prefix="fc1_"))
        net.add(gluon.nn.Dense(1024, activation="relu", in_units=1024,
                               prefix="fc2_"))
        net.add(gluon.nn.Dense(10, in_units=1024, prefix="fc3_"))
        net.initialize(mx.init.Xavier())
        step = TrainStep(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                         optimizer="sgd",
                         optimizer_params={"learning_rate": 0.05},
                         mesh=make_mesh())
        x = rng.rand(256, 784).astype(np.float32)
        y = rng.randint(0, 10, 256)
        for _ in range(3):                  # compile + settle
            float(np.asarray(step(x, y)))

        iters = 50

        def timed():
            times = []
            for _ in range(iters):
                t0 = time.perf_counter()
                loss = step(x, y)
                float(np.asarray(loss))
                times.append(time.perf_counter() - t0)
            return times

        base = timed()
        profiler = telemetry.ContinuousProfiler().start()
        profiled = timed()
        base_med_ms = sorted(base)[len(base) // 2] * 1e3
        prof_med_ms = sorted(profiled)[len(profiled) // 2] * 1e3
        _emit("profiling_step_ms_base", round(base_med_ms, 3), "ms")
        _emit("profiling_step_ms_sampled", round(prof_med_ms, 3), "ms")
        _emit("continuous_profiler_step_overhead_ab_pct",
              round((prof_med_ms - base_med_ms) / base_med_ms * 100.0,
                    3), "%")

        # THE CONTRACT ROW: direct hook measurement — per-sample
        # capture+fold cost x the default sampling rate = the sampler's
        # steady-state share of wall time.
        reps = 300
        t0 = time.perf_counter()
        for _ in range(reps):
            profiler.sample()
        per_sample_s = (time.perf_counter() - t0) / reps
        profiler.close()
        _emit("continuous_profiler_sample_ms",
              round(per_sample_s * 1e3, 4), "ms")
        _emit("continuous_profiler_step_overhead_pct",
              round(per_sample_s * profiler.hz * 100.0, 3), "%")

        # Attribution (informative): phase shares + bound cause over an
        # attributed window, and achieved FLOP/s from the executable's
        # cost analysis.
        attr = telemetry.StepAttribution(interval_s=0.0)
        try:
            attr.update()                   # drain the span backlog
            attr_steps = 20
            for _ in range(attr_steps):
                float(np.asarray(step(x, y)))
            attr.update()
            shares = attr.last_shares or {}
            for phase in tattr.PHASES:
                _emit("step_phase_share[%s]" % phase,
                      round(shares.get(phase, 0.0), 4), "share")
            _emit("step_bound_cause", attr.bound_cause or "unknown",
                  "cause")
            cost = tattr.executable_costs().get("train_step")
            device_s = (attr.last_window or {}).get("device_compute",
                                                    0.0)
            if cost and cost.get("flops") and device_s > 0:
                _emit("train_step_executable_gflop",
                      round(cost["flops"] / 1e9, 4), "GFLOP")
                _emit("train_step_achieved_gflops",
                      round(cost["flops"] * attr_steps / device_s
                            / 1e9, 2), "GFLOP/s")
        finally:
            attr.close()
    finally:
        cc.reset()
        shutil.rmtree(cache_dir, ignore_errors=True)


def _goodput_rows():
    """Goodput section (ISSUE 20): does the ledger's taxonomy actually
    close over wall-clock, and what does keeping it cost the step
    path. THE CONTRACT ROWS: goodput_closure_pct <= 2 (booked seconds
    may overcount wall-clock — the same second claimed by two sources —
    by at most the default tolerance, over a real attributed TrainStep
    run) and goodput_accounting_step_overhead_pct <= 1 (ledger
    bookkeeping on the step path at the default commit cadence).

    Measurement discipline (the diagnostics-section rule): the ms-scale
    step's ±9% A/B noise floor cannot resolve a 1% bound, so the
    overhead row measures the HOOKS directly — thousands of off-cadence
    ``tick()`` calls (a step-watermark write and a clock compare) plus
    timed full ``commit()`` folds amortized over the default 30 s
    cadence — and expresses the sum as a percentage of the median step.
    Informative rows: the run's goodput fraction and each category's
    share of wall-clock."""
    import shutil
    import tempfile

    import mxnet_tpu as mx
    from mxnet_tpu import gluon, telemetry
    from mxnet_tpu.telemetry import goodput as tgp
    from mxnet_tpu.parallel import TrainStep, make_mesh

    mx.random.seed(37)
    rng = np.random.RandomState(37)
    net = gluon.nn.HybridSequential(prefix="bench_gp_")
    net.add(gluon.nn.Dense(1024, activation="relu", in_units=784,
                           prefix="fc1_"))
    net.add(gluon.nn.Dense(1024, activation="relu", in_units=1024,
                           prefix="fc2_"))
    net.add(gluon.nn.Dense(10, in_units=1024, prefix="fc3_"))
    net.initialize(mx.init.Xavier())
    step = TrainStep(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                     optimizer="sgd",
                     optimizer_params={"learning_rate": 0.05},
                     mesh=make_mesh())
    x = rng.rand(256, 784).astype(np.float32)
    y = rng.randint(0, 10, 256)
    for _ in range(3):                      # compile + settle
        float(np.asarray(step(x, y)))

    ldir = tempfile.mkdtemp(prefix="bench_goodput_")
    attr = telemetry.StepAttribution(interval_s=0.0)
    try:
        attr.update()                       # drain the span backlog so
        # the ledger's cursors start at "now", not at whatever earlier
        # bench sections left in the phase counters.
        ledger = tgp.GoodputLedger(directory=ldir, rank=0,
                                   interval_s=0.0, attribution=attr)
        iters = 40
        times = []
        for i in range(iters):
            t0 = time.perf_counter()
            loss = step(x, y)
            float(np.asarray(loss))
            times.append(time.perf_counter() - t0)
            ledger.tick(step=i)
        snap = ledger.snapshot(serving=False)
        med_step_s = sorted(times)[len(times) // 2]

        # THE CONTRACT ROW (<= 2): closure — overcounted seconds as a
        # percentage of this run's wall-clock. Idle is derived, so the
        # only way to miss closure is double-booking.
        _emit("goodput_closure_pct", round(snap["closure_pct"], 3), "%")
        _emit("goodput_fraction", round(snap["goodput_ratio"], 4),
              "share")
        wall = snap["wall_s"] or 1.0
        for cat in tgp.CATEGORIES:
            _emit("goodput_share[%s]" % cat,
                  round(snap["categories"].get(cat, 0.0) / wall, 4),
                  "share")

        # THE CONTRACT ROW (<= 1): direct hook measurement. Off-cadence
        # tick cost x 1 call/step, plus a full fold+commit amortized
        # over the default commit interval.
        ledger.interval_s = 3600.0          # ticks below never commit
        reps = 5000
        t0 = time.perf_counter()
        for r in range(reps):
            ledger.tick(step=iters + r)
        per_tick_s = (time.perf_counter() - t0) / reps
        commits = 5
        t0 = time.perf_counter()
        for _ in range(commits):
            ledger.commit()
        per_commit_s = (time.perf_counter() - t0) / commits
        from mxnet_tpu import env as _env

        default_interval = float(_env.get("MXNET_GOODPUT_INTERVAL_S"))
        amortized_s = per_tick_s + per_commit_s * (
            med_step_s / max(default_interval, 1e-9))
        _emit("goodput_tick_us", round(per_tick_s * 1e6, 3), "us")
        _emit("goodput_commit_ms", round(per_commit_s * 1e3, 3), "ms")
        _emit("goodput_accounting_step_overhead_pct",
              round(amortized_s / med_step_s * 100.0, 3), "%")
        ledger.close(commit=False)
    finally:
        attr.close()
        shutil.rmtree(ldir, ignore_errors=True)


def _compile_accounting_rows():
    """Compile-accounting rows (the ROADMAP direction-2 acceptance
    baseline): per-site executable-cache-fill count and total seconds
    accumulated by mx_compile_seconds{site} over THIS bench run. Two
    runs' outputs diff with `bench.py --compare A.json B.json` — a
    persistent compile cache is accepted when the second run's counts
    drop to ~0."""
    from mxnet_tpu.telemetry import memstats

    for site, rec in sorted(memstats.compile_stats().items()):
        _emit("compile_count[%s]" % site, rec["count"], "compiles")
        _emit("compile_seconds[%s]" % site, round(rec["total_s"], 3),
              "s")


def _compile_cache_child(cache_dir):
    """One simulated process start with the persistent compile cache at
    ``cache_dir`` (run twice by `_compile_cache_rows`: cold then warm).
    Exercises all three cached compile sites the way a real restart
    does — serving bucket-ladder warmup + first predict, fused-update
    first step, TrainStep first step — and prints ONE JSON line:
    time-to-first-batch per surface plus the per-site compile counts
    this process actually paid (mx_compile_seconds is process-local, so
    in a fresh child it IS this start's bill)."""
    t_start = time.perf_counter()
    import numpy as np

    import mxnet_tpu as mx
    from mxnet_tpu import gluon, nd
    from mxnet_tpu.gluon import nn
    from mxnet_tpu.gluon import loss as gloss
    from mxnet_tpu.parallel import TrainStep
    from mxnet_tpu.serving import InferenceServer
    from mxnet_tpu.telemetry import memstats

    assert os.environ.get("MXNET_COMPILE_CACHE") == cache_dir
    rng = np.random.RandomState(0)

    # Serving: a bucket ladder over a small MLP (the cached_op site).
    w1 = rng.rand(64, 128).astype(np.float32)
    b1 = rng.rand(128).astype(np.float32)
    w2 = rng.rand(128, 10).astype(np.float32)

    def fwd(w1_, b1_, w2_, x):
        return nd.dot(nd.relu(nd.dot(x, w1_) + b1_), w2_)

    server = InferenceServer(fwd, (w1, b1, w2), item_shape=(64,),
                             max_batch=8)
    server.predict(rng.rand(3, 64).astype(np.float32))
    ttfb_serving = time.perf_counter() - t_start
    server.shutdown()

    # Fused update: one Trainer step (the fused_apply site). Stable
    # prefix => stable param names => restart-stable executables.
    net = nn.HybridSequential(prefix="ccbench_")
    with net.name_scope():
        for _ in range(3):
            net.add(nn.Dense(128, activation="relu"))
        net.add(nn.Dense(10))
    net.initialize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1})
    data = nd.array(rng.rand(8, 64).astype(np.float32))
    from mxnet_tpu import autograd

    with autograd.record():
        loss = net(data).sum()
    loss.backward()
    trainer.step(8)

    # Whole-step executable (the train_step site).
    net2 = nn.Dense(10, in_units=32, prefix="ccbench_step_")
    net2.initialize()
    step = TrainStep(net2, gloss.L2Loss(), optimizer="sgd",
                     optimizer_params={"learning_rate": 0.1})
    loss = step(rng.rand(8, 32).astype(np.float32),
                rng.rand(8, 10).astype(np.float32))
    float(np.asarray(loss))             # force completion
    ttfb_train = time.perf_counter() - t_start

    counts = {site: rec["count"]
              for site, rec in memstats.compile_stats().items()}
    print(json.dumps({
        "ttfb_serving_s": round(ttfb_serving, 3),
        "ttfb_train_s": round(ttfb_train, 3),
        "compile_counts": counts,
    }), flush=True)
    return 0


def _compile_cache_rows():
    """Compile-cache section (mxnet_tpu.compile, ISSUE 11): cold-vs-warm
    restart measured honestly — two FRESH child processes sharing one
    cache directory, each paying real imports, warmup and first batch.

    THE CONTRACT ROW: warm_restart_compile_count == 0 — the second
    start must load every executable (serving bucket ladder, fused
    apply chunk, whole-step TrainStep) from the cache and compile
    nothing at the cached sites. warm_restart_ttfb_seconds is the
    payoff row (informative: wall time to first train batch of the
    warm start, vs cold)."""
    import shutil
    import subprocess
    import tempfile

    cache_dir = tempfile.mkdtemp(prefix="mx_cc_bench_")
    env = dict(os.environ, MXNET_COMPILE_CACHE=cache_dir)

    def run_child():
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__),
             "--compile-cache-child", cache_dir],
            env=env, capture_output=True, text=True, timeout=600)
        if proc.returncode != 0:
            raise RuntimeError("compile-cache child failed:\n%s"
                               % proc.stderr[-2000:])
        for line in reversed(proc.stdout.splitlines()):
            line = line.strip()
            if line.startswith("{"):
                return json.loads(line)
        raise RuntimeError("compile-cache child printed no JSON")

    try:
        cold = run_child()
        warm = run_child()
    finally:
        shutil.rmtree(cache_dir, ignore_errors=True)

    sites = ("cached_op", "fused_apply", "train_step")
    for site in sites:
        _emit("compile_cache_cold_count[%s]" % site,
              cold["compile_counts"].get(site, 0), "compiles")
        _emit("compile_cache_warm_count[%s]" % site,
              warm["compile_counts"].get(site, 0), "compiles")
    _emit("cold_start_ttfb_seconds", cold["ttfb_train_s"], "s")
    _emit("cold_start_serving_ttfb_seconds", cold["ttfb_serving_s"], "s")
    # THE CONTRACT ROW: a warm restart compiles NOTHING at the cached
    # sites — every executable deserializes from the persistent cache.
    _emit("warm_restart_compile_count",
          sum(warm["compile_counts"].get(site, 0) for site in sites),
          "compiles")
    _emit("warm_restart_ttfb_seconds", warm["ttfb_train_s"], "s")
    _emit("warm_restart_serving_ttfb_seconds", warm["ttfb_serving_s"],
          "s")


def _load_rows(path):
    """Parse one bench output (JSON row per line; non-JSON lines — e.g.
    stderr interleave — are skipped) into {metric: row}."""
    rows = {}
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if isinstance(rec, dict) and "metric" in rec:
                rows[rec["metric"]] = rec
    return rows


def compare(a_path, b_path):
    """`bench.py --compare A.json B.json`: emit per-site compile
    count/seconds DELTAS (B - A) from the two runs' compile-accounting
    rows. This is the acceptance measurement for recompile-elimination
    work: a persistent compile cache must drive every
    compile_count_delta row to -count (second run compiles nothing).
    Returns 0 when both files had accounting rows."""
    import re as _re

    a, b = _load_rows(a_path), _load_rows(b_path)
    # Perf-contract deltas first: the step-hot-path rows two runs are
    # most often compared on (overlap efficiency, fused speedup).
    for metric, unit in (("fused_overlap_efficiency", "share"),
                         ("trainer_fused_update_speedup", "x"),
                         ("gateway_swap_dropped_requests", "req"),
                         ("gateway_protected_p99_ms", "ms"),
                         ("continuous_batching_tokens_per_sec_speedup",
                          "x"),
                         ("decode_steady_state_retraces", "compiles"),
                         ("goodput_closure_pct", "%"),
                         ("goodput_accounting_step_overhead_pct", "%"),
                         ("goodput_fraction", "share")):
        if metric in a or metric in b:
            va = float(a.get(metric, {}).get("value", 0) or 0)
            vb = float(b.get(metric, {}).get("value", 0) or 0)
            print(json.dumps({"metric": metric + "_delta",
                              "value": round(vb - va, 4), "unit": unit,
                              "a": va, "b": vb}), flush=True)
    row_re = _re.compile(r"^compile_(count|seconds)\[(.+)\]$")
    sites = {}
    for metric in list(a) + list(b):
        m = row_re.match(metric)
        if m:
            sites.setdefault(m.group(2), set()).add(m.group(1))
    if not sites:
        print(json.dumps({"metric": "compile_compare_error", "value": 0,
                          "unit": "",
                          "detail": "no compile_count[site]/"
                                    "compile_seconds[site] rows in "
                                    "either input"}), flush=True)
        return 1
    total_count = total_s = 0.0
    for site in sorted(sites):
        for kind, unit in (("count", "compiles"), ("seconds", "s")):
            metric = "compile_%s[%s]" % (kind, site)
            va = float(a.get(metric, {}).get("value", 0) or 0)
            vb = float(b.get(metric, {}).get("value", 0) or 0)
            delta = vb - va
            if kind == "count":
                total_count += delta
            else:
                total_s += delta
            print(json.dumps({
                "metric": "compile_%s_delta[%s]" % (kind, site),
                "value": round(delta, 3), "unit": unit,
                "a": va, "b": vb}), flush=True)
    print(json.dumps({"metric": "compile_count_delta_total",
                      "value": round(total_count, 3),
                      "unit": "compiles"}), flush=True)
    print(json.dumps({"metric": "compile_seconds_delta_total",
                      "value": round(total_s, 3), "unit": "s"}),
          flush=True)
    return 0


def _data_pipeline_rows():
    """Data pipeline section (mxnet_tpu.data, ISSUE 6): per-batch decode
    cost, prefetch overlap, and the step-path input-stall fraction
    derived from the existing step/data_put trace spans.

    THE CONTRACT ROW: data_prefetch_hidden_decode_pct >= 90 — when the
    training step takes at least as long as a batch decodes, the decode
    pool + double-buffered prefetcher must hide >= 90% of the decode
    time (the consumer's wait per batch is <= 10% of the serial decode
    cost)."""
    import tempfile

    import mxnet_tpu as mx
    from mxnet_tpu import data, gluon, recordio, telemetry
    from mxnet_tpu.parallel import TrainStep, make_mesh
    from mxnet_tpu.telemetry import trace

    mx.random.seed(29)
    rng = np.random.RandomState(29)
    batch = 64        # big enough that fixed per-batch handoff cost is
    shape = (3, 48, 48)  # noise against the ~75ms decode it must hide

    with tempfile.TemporaryDirectory() as td:
        rec = os.path.join(td, "ds.rec")
        idx = os.path.join(td, "ds.idx")
        w = recordio.MXIndexedRecordIO(idx, rec, "w")
        for i in range(256):
            img = (rng.rand(56, 56, 3) * 255).astype(np.uint8)
            w.write_idx(i, recordio.pack_img(
                recordio.IRHeader(0, float(i % 4), i, 0), img,
                img_fmt=".jpg"))
        w.close()

        def make_pipe(prefetch):
            return data.DataPipeline(
                data.RecordDataset([rec]),
                data.ImageRecordDecoder(shape, rand_crop=True,
                                        rand_mirror=True),
                batch_size=batch, shuffle=True, seed=29, num_shards=1,
                shard_index=0, decode_threads=4, prefetch=prefetch,
                place=False)

        # Serial decode cost per batch (median): no prefetch thread, the
        # consumer pays the full pool-fed decode latency inline.
        with make_pipe(prefetch=0) as pipe:
            n = pipe.batches_per_epoch
            for _ in range(n):                  # warm page cache + pool
                next(pipe)
            costs = []
            for _ in range(2 * n):
                t0 = time.perf_counter()
                next(pipe)
                costs.append(time.perf_counter() - t0)
            decode_ms = sorted(costs)[len(costs) // 2] * 1e3

        # Prefetched: the consumer "trains" for >= the decode cost per
        # batch; its residual blocking wait (median) is what prefetch
        # failed to hide.
        step_s = decode_ms / 1e3 * 1.5
        with make_pipe(prefetch=2) as pipe:
            next(pipe)                          # spin the stages up
            time.sleep(step_s)
            waits = []
            for _ in range(2 * pipe.batches_per_epoch):
                t0 = time.perf_counter()
                next(pipe)
                waits.append(time.perf_counter() - t0)
                time.sleep(step_s)              # the simulated step
            wait_ms = sorted(waits)[len(waits) // 2] * 1e3

        hidden_pct = (1.0 - wait_ms / decode_ms) * 100.0
        _emit("data_decode_ms_per_batch", round(decode_ms, 3), "ms")
        _emit("data_prefetch_wait_ms_per_batch", round(wait_ms, 3), "ms")
        # THE CONTRACT ROW (>= 90).
        _emit("data_prefetch_hidden_decode_pct", round(hidden_pct, 2), "%")

        # Input-stall fraction of a REAL step loop, from the spans the
        # subsystems already emit (train_step::step / train_step::
        # data_put / data::wait) — the pod-observability view of "is
        # the input pipeline the ceiling?".
        net = gluon.nn.HybridSequential(prefix="bench_data_")
        net.add(gluon.nn.Flatten())
        net.add(gluon.nn.Dense(64, activation="relu",
                               in_units=int(np.prod(shape)),
                               prefix="fc1_"))
        net.add(gluon.nn.Dense(4, in_units=64, prefix="fc2_"))
        net.initialize(mx.init.Xavier())
        step = TrainStep(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                         optimizer="sgd",
                         optimizer_params={"learning_rate": 0.05},
                         mesh=make_mesh())
        prev = telemetry.set_enabled(True)
        try:
            with make_pipe(prefetch=2) as pipe:
                b = next(pipe)                  # compile outside the trace
                float(np.asarray(step(b.data[0], b.label[0])))
                trace.clear()
                for _ in range(2 * pipe.batches_per_epoch):
                    b = next(pipe)
                    float(np.asarray(step(b.data[0], b.label[0])))
                stall = data.stall_fraction()
        finally:
            telemetry.set_enabled(prev)
        _emit("data_input_stall_fraction", round(stall, 4), "fraction")


def _trainer_rows():
    """Trainer section (mxnet_tpu.fused_update): imperative update cost,
    per-param loop vs fused multi-tensor apply, at 10/100/1000
    parameters. The timed window is `trainer.step` with gradients
    already in place — exactly the O(num_params) host cost the fused
    path collapses to O(1) dispatches. THE CONTRACT ROW:
    trainer_fused_update_speedup >= 2x at 1000 params.

    CPU-backend honesty (the checkpoint-section discipline): on a
    shared-core CPU "device" the loop's many small executables and the
    fused path's one large executable contend for the same cores, so
    the measured ratio UNDERSTATES the win on a real accelerator, where
    per-launch host latency (µs-to-ms through the device tunnel)
    dominates and the fused path pays it once instead of N times.
    Each row ends with a host readback of one parameter so async
    dispatch can't leak work past the timer."""
    import mxnet_tpu as mx
    from mxnet_tpu import gluon, nd

    def build(n, fused):
        rng = np.random.RandomState(17)
        params = []
        for k in range(n):
            p = gluon.Parameter("bench_fused_%d_%s_%d"
                                % (n, fused, k), shape=(64,))
            p.initialize(init=mx.init.Constant(0.0))
            p.set_data(nd.array(rng.randn(64).astype(np.float32)))
            params.append(p)
        trainer = gluon.Trainer(params, "sgd",
                                {"learning_rate": 0.05, "momentum": 0.9},
                                fused=fused)
        for p in params:
            p.grad()[:] = rng.randn(64).astype(np.float32)
        return params, trainer

    def paired_ms(n, iters):
        """INTERLEAVED loop/fused timing: the two paths alternate
        step-by-step through the same contention regime, then each
        reports its best-of-N (the test_perf_evidence discipline) — a
        background burst on this shared-core box hits both paths
        instead of silently taxing whichever ran second."""
        lp, ltr = build(n, False)
        fp, ftr = build(n, True)
        for _ in range(3):                  # compile + settle
            ltr.step(1)
            ftr.step(1)
        lp[0].data().asnumpy()
        fp[0].data().asnumpy()
        lt, ft = [], []
        for _ in range(iters):
            t0 = time.perf_counter()
            ltr.step(1)
            lp[-1].data().wait_to_read()
            lt.append(time.perf_counter() - t0)
            t0 = time.perf_counter()
            ftr.step(1)
            fp[-1].data().wait_to_read()
            ft.append(time.perf_counter() - t0)
        return min(lt) * 1e3, min(ft) * 1e3

    speedup_1000 = None
    for n, iters in ((10, 30), (100, 20), (1000, 16)):
        loop_ms, fused_ms = paired_ms(n, iters)
        _emit("trainer_step_ms_loop_p%d" % n, round(loop_ms, 3), "ms")
        _emit("trainer_step_ms_fused_p%d" % n, round(fused_ms, 3), "ms")
        if n == 1000:
            speedup_1000 = loop_ms / fused_ms
    # THE CONTRACT ROW: at 1000 params the coalesced apply must beat the
    # per-param loop by >= 2x — the enforced floor; the target since the
    # overlap work (ISSUE 13) is >= 3x, which this box typically
    # measures (the loop pays 1000 dispatches, the fused path pays 1).
    _emit("trainer_fused_update_speedup", round(speedup_1000, 2), "x")


def _trainer_overlap_rows():
    """Comm/compute overlap section (ISSUE 13): the fused step's
    pipelined reduce->apply (bucket i applies while bucket i+1 is
    still reducing). THE CONTRACT ROW: fused_overlap_efficiency >= 0.30
    — at the default-shaped workload at least 30% of total reduce time
    must be hidden behind the apply stream.

    CPU-backend honesty (the trainer-section discipline): this box has
    no DCN, so the transport is a latency-injecting local store (a
    sleep per push/pull leg standing in for the worker->server
    round-trip), and the compute that hides it is the HOST side of the
    apply stream (unflatten + fused dispatch + per-param commit). On a
    real pod the same pipeline additionally hides transport behind
    device compute, so this measurement *understates* the win. The
    efficiency is computed from the runtime's own accounting
    (mx_trainer_reduce_{seconds,hidden_seconds}_total deltas), i.e. the
    number an operator would scrape — and the serial (depth=0) row on
    the identical workload pins the no-overlap baseline near 0."""
    import time as _t

    import mxnet_tpu as mx
    from mxnet_tpu import gluon, nd
    from mxnet_tpu import kvstore as kvs
    from mxnet_tpu.telemetry import metrics as tm

    lat = 0.0012                  # one simulated DCN round-trip (s)

    class LatencyStore(kvs.KVStoreLocal):
        """Local store + synthetic wire latency per push/pull leg."""

        @property
        def type(self):
            # "dist" in the name makes the Trainer treat this like a
            # real multi-process store (kvstore engaged on 1 context).
            return "dist_bench_latency"

        def push(self, key, value, priority=0):
            _t.sleep(lat / 2)
            super().push(key, value, priority)

        def pull(self, key, out=None, priority=0, ignore_sparse=True):
            _t.sleep(lat / 2)
            super().pull(key, out=out, priority=priority,
                         ignore_sparse=ignore_sparse)

    saved = {k: os.environ.get(k) for k in
             ("MXNET_FUSED_OVERLAP_DEPTH", "MXNET_FUSED_BUCKET_MB")}

    def run(depth, steps=6, n=800, size=1024, clip=None):
        os.environ["MXNET_FUSED_OVERLAP_DEPTH"] = str(depth)
        os.environ["MXNET_FUSED_BUCKET_MB"] = "1"   # ~4 buckets
        rng = np.random.RandomState(5)
        params = []
        for k in range(n):
            p = gluon.Parameter("ov_bench_%d_%d" % (depth, k),
                                shape=(size,))
            p.initialize(init=mx.init.Constant(0.0))
            p.set_data(nd.array(rng.randn(size).astype(np.float32)))
            params.append(p)
        trainer = gluon.Trainer(
            params, "sgd", {"learning_rate": 0.05, "momentum": 0.9},
            kvstore=LatencyStore(device_mode=True),
            update_on_kvstore=False, global_norm_clip=clip)
        for p in params:
            p.grad()[:] = rng.randn(size).astype(np.float32)
        red = tm.REGISTRY.counter("mx_trainer_reduce_seconds_total", "")
        hid = tm.REGISTRY.counter(
            "mx_trainer_reduce_hidden_seconds_total", "")
        trainer.step(1)                     # warmup: compile + init
        params[-1].data().asnumpy()
        r0, h0 = red.value, hid.value
        t0 = _t.perf_counter()
        for _ in range(steps):
            trainer.step(1)
        params[-1].data().asnumpy()
        wall = (_t.perf_counter() - t0) / steps * 1e3
        r, h = red.value - r0, hid.value - h0
        return wall, r, h

    try:
        wall_s, red_s, hid_s = run(0)
        # The serial-ACCOUNTING row must exercise the pipelined step's
        # own hidden-time arithmetic, not the legacy path (which never
        # touches the counters): a no-op global-norm clip routes
        # depth=0 through _step_pipelined, where every reduce second
        # is inline main-thread wait. A broken accounting that
        # reported hidden time serially WOULD trip this row.
        _, red_s2, hid_s2 = run(0, clip=1e12)
        eff_serial = hid_s2 / red_s2 if red_s2 > 0 else 0.0
        wall_o, red_o, hid_o = run(4)
        eff = hid_o / red_o if red_o > 0 else 0.0
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    _emit("trainer_overlap_step_ms_serial", round(wall_s, 3), "ms")
    _emit("trainer_overlap_step_ms_depth4", round(wall_o, 3), "ms")
    _emit("fused_overlap_efficiency_serial", round(eff_serial, 4), "share")
    # THE CONTRACT ROW: >= 0.30 of reduce time hidden behind applies.
    _emit("fused_overlap_efficiency", round(eff, 4), "share")


def _checkpoint_rows():
    """Checkpoint section (mxnet_tpu.checkpoint): per-step wall time
    with no checkpointing, with the reference-style blocking sync save
    every step, and with the async CheckpointManager save every step.
    The async row is the subsystem's contract: snapshot-to-host at the
    step boundary, serialize+commit on a background thread — overhead
    must stay under 10% of the no-checkpoint step time."""
    import shutil
    import tempfile

    import mxnet_tpu as mx
    from mxnet_tpu import gluon
    from mxnet_tpu.checkpoint import CheckpointManager
    from mxnet_tpu.parallel import TrainStep, make_mesh

    mx.random.seed(11)
    rng = np.random.RandomState(11)
    net = gluon.nn.HybridSequential(prefix="bench_ckpt_")
    net.add(gluon.nn.Dense(1024, activation="relu", in_units=784,
                           prefix="fc1_"))
    net.add(gluon.nn.Dense(1024, activation="relu", in_units=1024,
                           prefix="fc2_"))
    net.add(gluon.nn.Dense(10, in_units=1024, prefix="fc3_"))
    net.initialize(mx.init.Xavier())
    step = TrainStep(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                     optimizer="sgd",
                     optimizer_params={"learning_rate": 0.05,
                                       "momentum": 0.9},
                     mesh=make_mesh())
    x = rng.rand(256, 784).astype(np.float32)
    y = rng.randint(0, 10, 256)
    for _ in range(3):                      # compile + settle
        float(np.asarray(step(x, y)))

    # Median over a window long enough that the handful of steps a
    # background commit overlaps (CPU bench: writer and "device" share
    # cores) stay in the minority; on a real TPU the overlap vanishes.
    iters = 40

    def timed(save_fn):
        times = []
        for i in range(iters):
            t0 = time.perf_counter()
            loss = step(x, y)
            save_fn(i)
            float(np.asarray(loss))         # close the step like a real loop
            times.append(time.perf_counter() - t0)
        return sorted(times)[len(times) // 2]

    base_ms = timed(lambda i: None) * 1e3

    d_sync = tempfile.mkdtemp(prefix="bench_ckpt_sync_")
    d_async = tempfile.mkdtemp(prefix="bench_ckpt_async_")
    d_async5 = tempfile.mkdtemp(prefix="bench_ckpt_async5_")
    d_async10 = tempfile.mkdtemp(prefix="bench_ckpt_async10_")
    try:
        # Reference-style blocking save EVERY step (the old
        # save_checkpoint behavior, worst case).
        m_sync = CheckpointManager(d_sync, keep_last=2)
        sync_ms = timed(lambda i: m_sync.save(
            i, step.state_dict(), sync=True)) * 1e3
        m_sync.close()

        # Async every step: stress row — the writer thread never drains
        # between saves, so on a CPU "device" it contends for cores.
        # save_path_costs captures the SYNCHRONOUS portion each save
        # adds to the step (snapshot device_get + enqueue) — the
        # contract quantity: everything else runs off the step path.
        m_async = CheckpointManager(d_async, keep_last=2)
        save_path_costs = []

        def _async_save(i):
            t0 = time.perf_counter()
            m_async.save(i, step.state_dict())
            save_path_costs.append(time.perf_counter() - t0)

        async_ms = timed(_async_save) * 1e3
        save_path_ms = sorted(save_path_costs)[len(save_path_costs) // 2] \
            * 1e3
        t0 = time.perf_counter()
        m_async.wait()                      # drain for the commit-rate row
        drain_s = time.perf_counter() - t0
        total_mb = m_async.total_bytes / 1e6
        commit_s = m_async.total_save_seconds
        m_async.close()

        # Cadence rows measured against ONE paired baseline taken
        # immediately before them (the every-1 sections above include
        # sync-save IO and writer drain, so the opening base_ms is
        # minutes stale by now and machine drift would masquerade as
        # checkpoint cost).
        base10_ms = timed(lambda i: None) * 1e3
        m5 = CheckpointManager(d_async5, keep_last=2)
        async5_ms = timed(lambda i: m5.save(i, step.state_dict())
                          if i % 5 == 0 else None) * 1e3
        m5.close()      # drain before the next timed section
        m10 = CheckpointManager(d_async10, keep_last=2)
        async10_ms = timed(lambda i: m10.save(i, step.state_dict())
                           if i % 10 == 0 else None) * 1e3
        m10.close()
    finally:
        shutil.rmtree(d_sync, ignore_errors=True)
        shutil.rmtree(d_async, ignore_errors=True)
        shutil.rmtree(d_async5, ignore_errors=True)
        shutil.rmtree(d_async10, ignore_errors=True)

    _emit("checkpoint_step_ms_none", round(base_ms, 3), "ms")
    _emit("checkpoint_step_ms_sync_every1", round(sync_ms, 3), "ms")
    _emit("checkpoint_step_ms_async_every1", round(async_ms, 3), "ms")
    _emit("checkpoint_step_ms_async_every5", round(async5_ms, 3), "ms")
    _emit("checkpoint_step_ms_none_paired", round(base10_ms, 3), "ms")
    _emit("checkpoint_step_ms_async_every10", round(async10_ms, 3), "ms")
    _emit("checkpoint_sync_overhead_pct_every1",
          round((sync_ms - base_ms) / base_ms * 100.0, 1), "%")
    _emit("checkpoint_async_overhead_pct_every1",
          round((async_ms - base_ms) / base_ms * 100.0, 1), "%")
    _emit("checkpoint_async_overhead_pct_every5",
          round((async5_ms - base10_ms) / base10_ms * 100.0, 1), "%")
    _emit("checkpoint_async_overhead_pct_every10",
          round((async10_ms - base10_ms) / base10_ms * 100.0, 1), "%")
    # THE CONTRACT ROW: what an async save synchronously adds to the
    # step path (host snapshot + enqueue), as % of the step — even at
    # every-step cadence this must stay <10%. The wall-clock rows above
    # additionally include background-writer CPU contention, a
    # shared-core bench artifact (the writer runs nice+10 and on a real
    # accelerator overlaps device compute instead of stealing it).
    _emit("checkpoint_async_step_path_ms", round(save_path_ms, 3), "ms")
    _emit("checkpoint_async_step_path_overhead_pct",
          round(save_path_ms / base_ms * 100.0, 1), "%")
    if commit_s > 0:
        _emit("checkpoint_commit_mb_per_s", round(total_mb / commit_s, 1),
              "MB/s")
    _emit("checkpoint_async_drain_ms", round(drain_s * 1e3, 3), "ms")


def _acquire_device(timeout_s=120):
    """Bounded backend acquisition. `jax.devices()` can hang forever
    when the TPU tunnel is down (observed in rounds 3-4); probing from
    a daemon thread bounds the wait so a dead chip yields a diagnosable
    JSON error row instead of an rc=1 traceback."""
    import threading

    result = {}

    def probe():
        import jax

        try:
            result["devices"] = jax.devices()
        except Exception as exc:  # backend raised instead of hanging
            result["error"] = repr(exc)

    t = threading.Thread(target=probe, daemon=True)
    t.start()
    t.join(timeout_s)
    if "devices" in result:
        return result["devices"][0]
    detail = result.get(
        "error", "jax.devices() still blocked after %ds" % timeout_s)
    print(json.dumps({"metric": "bench_unavailable", "value": 0,
                      "unit": "img/s", "vs_baseline": 0.0,
                      "error": "tpu-unavailable", "detail": detail}),
          flush=True)
    # The probe thread may be wedged inside a C call; only _exit is safe.
    os._exit(0)


def main():
    import argparse
    import sys
    import traceback

    parser = argparse.ArgumentParser(
        description="mxnet_tpu benchmark (JSON row per line); "
                    "--compare diffs two runs' compile accounting.")
    parser.add_argument("--compare", nargs=2,
                        metavar=("A.json", "B.json"),
                        help="emit per-site compile count/seconds "
                             "deltas (B - A) from two bench outputs "
                             "and exit (no device needed)")
    parser.add_argument("--compile-cache-child", metavar="CACHE_DIR",
                        help="internal: run one simulated process start "
                             "against CACHE_DIR and print its TTFB + "
                             "compile counts (the compile_cache "
                             "section's cold/warm worker)")
    args = parser.parse_args()
    if args.compare:
        return compare(args.compare[0], args.compare[1])
    if args.compile_cache_child:
        return _compile_cache_child(args.compile_cache_child)

    dev = _acquire_device()
    # Non-headline rows never take down the headline: a failed variant
    # logs to stderr and the run continues.
    extra_rows = [
        ("resnet50_v1_infer_img_per_sec_b32_fp32",
         lambda: _infer_rate(32, None, dev), 1076.81, FWD_GFLOP_PER_IMG),
        ("resnet50_v1_infer_img_per_sec_b32_bf16",
         lambda: _infer_rate(32, "bfloat16", dev), 2085.51,
         FWD_GFLOP_PER_IMG),
        ("resnet50_v1_train_img_per_sec_b32_bf16",
         lambda: _train_rate(32, "bfloat16", dev), 298.51,
         TRAIN_GFLOP_PER_IMG),
        ("resnet50_v1_train_img_per_sec_b128_bf16",
         lambda: _train_rate(128, "bfloat16", dev), 363.69,
         TRAIN_GFLOP_PER_IMG),
        ("resnet50_v1_train_img_per_sec_b128_fp32",
         lambda: _train_rate(128, None, dev), 363.69, TRAIN_GFLOP_PER_IMG),
    ]
    for metric, rate_fn, baseline, gflop in extra_rows:
        try:
            _row(metric, rate_fn(), baseline, gflop)
        except Exception:
            print("bench row %s failed:" % metric, file=sys.stderr)
            traceback.print_exc()
    try:
        _serving_rows()
    except Exception:
        print("bench serving section failed:", file=sys.stderr)
        traceback.print_exc()
    try:
        _serving_gateway_rows()
    except Exception:
        print("bench serving_gateway section failed:", file=sys.stderr)
        traceback.print_exc()
    try:
        _continuous_batching_rows()
    except Exception:
        print("bench continuous_batching section failed:",
              file=sys.stderr)
        traceback.print_exc()
    try:
        _telemetry_rows()
    except Exception:
        print("bench telemetry section failed:", file=sys.stderr)
        traceback.print_exc()
    try:
        _telemetry_dist_rows()
    except Exception:
        print("bench telemetry_dist section failed:", file=sys.stderr)
        traceback.print_exc()
    try:
        _xtrace_rows()
    except Exception:
        print("bench xtrace section failed:", file=sys.stderr)
        traceback.print_exc()
    try:
        _diagnostics_rows()
    except Exception:
        print("bench diagnostics section failed:", file=sys.stderr)
        traceback.print_exc()
    try:
        _healthplane_rows()
    except Exception:
        print("bench healthplane section failed:", file=sys.stderr)
        traceback.print_exc()
    try:
        _profiling_rows()
    except Exception:
        print("bench profiling section failed:", file=sys.stderr)
        traceback.print_exc()
    try:
        _goodput_rows()
    except Exception:
        print("bench goodput section failed:", file=sys.stderr)
        traceback.print_exc()
    try:
        _data_pipeline_rows()
    except Exception:
        print("bench data_pipeline section failed:", file=sys.stderr)
        traceback.print_exc()
    try:
        _trainer_rows()
    except Exception:
        print("bench trainer section failed:", file=sys.stderr)
        traceback.print_exc()
    try:
        _trainer_overlap_rows()
    except Exception:
        print("bench trainer_overlap section failed:", file=sys.stderr)
        traceback.print_exc()
    try:
        _checkpoint_rows()
    except Exception:
        print("bench checkpoint section failed:", file=sys.stderr)
        traceback.print_exc()
    try:
        _compile_cache_rows()
    except Exception:
        print("bench compile_cache section failed:", file=sys.stderr)
        traceback.print_exc()
    # Measure the headline BEFORE the compile accounting so its fresh
    # TrainStep compile (the largest single compile of the run) is in
    # the accounting; its row still prints LAST (driver parses the
    # final JSON line; BENCH_r01/r02 continuity).
    train32 = _train_rate(32, None, dev)
    try:
        # After every section: the accounting covers the whole run.
        _compile_accounting_rows()
    except Exception:
        print("bench compile accounting failed:", file=sys.stderr)
        traceback.print_exc()
    _row("resnet50_v1_train_img_per_sec_b32", train32, 298.51,
         TRAIN_GFLOP_PER_IMG)


if __name__ == "__main__":
    sys.exit(main() or 0)
