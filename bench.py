"""Benchmark: ResNet-50 v1 ImageNet-shape training throughput, single
chip — the reference's headline number (docs/faq/perf.md:214: 298.51
img/s, batch 32, fp32, 1x V100; BASELINE.md).

Whole training step (fwd + softmax CE + bwd + SGD-momentum update)
compiled as one XLA executable via mxnet_tpu.parallel.TrainStep.
Prints ONE JSON line.
"""
from __future__ import annotations

import json
import time

import numpy as np

BASELINE_IMG_S = 298.51  # docs/faq/perf.md:214 (b=32 fp32 V100)
BATCH = 32
WARMUP = 3
WINDOWS = 5   # median-of-windows is robust to shared-chip contention
ITERS = 10    # steps per window


def main():
    import jax
    import mxnet_tpu as mx
    from mxnet_tpu import gluon
    from mxnet_tpu.gluon.model_zoo import vision
    from mxnet_tpu.parallel import TrainStep, make_mesh

    net = vision.resnet50_v1(classes=1000)
    net.initialize()
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    step = TrainStep(net, loss_fn, optimizer="sgd",
                     optimizer_params={"learning_rate": 0.1, "momentum": 0.9,
                                       "wd": 1e-4},
                     mesh=make_mesh({"dp": 1}, devices=jax.devices()[:1]))

    rng = np.random.RandomState(0)
    x = rng.rand(BATCH, 3, 224, 224).astype(np.float32)
    y = rng.randint(0, 1000, BATCH).astype(np.float32)

    for _ in range(WARMUP):
        loss = step(x, y)
    jax.block_until_ready(loss)

    rates = []
    for _ in range(WINDOWS):
        t0 = time.perf_counter()
        for _ in range(ITERS):
            loss = step(x, y)
        jax.block_until_ready(loss)
        rates.append(BATCH * ITERS / (time.perf_counter() - t0))
    img_s = sorted(rates)[len(rates) // 2]
    print(json.dumps({
        "metric": "resnet50_v1_train_img_per_sec_b32",
        "value": round(img_s, 2),
        "unit": "img/s",
        "vs_baseline": round(img_s / BASELINE_IMG_S, 4),
    }))


if __name__ == "__main__":
    main()
