#!/usr/bin/env python
"""Sparse end-to-end benchmark: linear model with row_sparse weights.

Reference: benchmark/python/sparse/sparse_end2end.py — a wide linear
classifier over sparse features where only the rows touched by a batch
move (row_sparse gradient + lazy optimizer update + row_sparse_pull of
just the needed rows from the kvstore).

Prints one JSON line per configuration: samples/s for the sparse path
and for the equivalent dense path, so the sparse win is a number.

    python benchmark/sparse_end2end.py --features 100000 --nnz 32
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def run_epoch(mx, net, trainer, loss_fn, batches, autograd):
    t0 = time.monotonic()
    n = 0
    for tokens, y in batches:
        with autograd.record():
            loss = loss_fn(net(tokens), y).sum()
        loss.backward()
        trainer.step(tokens.shape[0])
        n += tokens.shape[0]
    # Drain BOTH the forward chain and the last step's async weight
    # updates before stopping the clock.
    loss.asnumpy()
    next(iter(net.collect_params().values())).data().asnumpy()
    return n / (time.monotonic() - t0)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--features", type=int, default=100000,
                    help="feature-space width (embedding rows)")
    ap.add_argument("--nnz", type=int, default=32,
                    help="active features per sample")
    ap.add_argument("--batch-size", type=int, default=128)
    ap.add_argument("--batches", type=int, default=20)
    ap.add_argument("--dim", type=int, default=16)
    args = ap.parse_args()

    import mxnet_tpu as mx

    mx.util.pin_platform(os.environ.get("MXNET_DEVICE", "cpu"))
    from mxnet_tpu import autograd, gluon

    rng = np.random.RandomState(0)
    batches = []
    for _ in range(args.batches):
        tokens = rng.randint(0, args.features,
                             (args.batch_size, args.nnz))
        y = (rng.rand(args.batch_size) > 0.5).astype(np.float32)
        batches.append((mx.nd.array(tokens.astype(np.float32)),
                        mx.nd.array(y)))

    class LinearOverFeatures(gluon.HybridBlock):
        def __init__(self, sparse, **kw):
            super().__init__(**kw)
            with self.name_scope():
                self.embed = gluon.nn.Embedding(
                    args.features, args.dim, sparse_grad=sparse)
                self.out = gluon.nn.Dense(1)

        def hybrid_forward(self, F, tokens):
            return self.out(self.embed(tokens).sum(axis=1))

    loss_fn = gluon.loss.SigmoidBinaryCrossEntropyLoss()
    for sparse in (True, False):
        mx.random.seed(1)
        net = LinearOverFeatures(sparse)
        net.initialize(mx.init.Normal(0.01))
        trainer = gluon.Trainer(net.collect_params(), "sgd",
                                {"learning_rate": 0.1})
        run_epoch(mx, net, trainer, loss_fn, batches[:2], autograd)  # warm
        rate = run_epoch(mx, net, trainer, loss_fn, batches, autograd)
        print(json.dumps({
            "metric": "sparse_end2end_samples_per_s",
            "grad_stype": "row_sparse" if sparse else "dense",
            "value": round(rate, 1), "unit": "samples/s",
            "features": args.features, "nnz": args.nnz}))

    # The blessed path: the whole step fused + buffer-donated
    # (TrainStep). XLA turns the embedding grad into a fused
    # scatter-add applied in place — no whole-table copies at all.
    import jax

    from mxnet_tpu.parallel import TrainStep, make_mesh

    mx.random.seed(1)
    net = LinearOverFeatures(False)
    net.initialize(mx.init.Normal(0.01))
    step = TrainStep(net, lambda p, l: loss_fn(p, l),
                     optimizer="sgd",
                     optimizer_params={"learning_rate": 0.1},
                     mesh=make_mesh({"dp": 1},
                                    devices=[jax.devices()[0]]))
    tok_np = [np.asarray(t.asnumpy()) for t, _ in batches]
    y_np = [np.asarray(y.asnumpy()) for _, y in batches]
    float(jax.device_get(step(tok_np[0], y_np[0])))   # warm/compile
    t0 = time.monotonic()
    n = 0
    for t, y in zip(tok_np, y_np):
        loss = step(t, y)
        n += t.shape[0]
    float(jax.device_get(loss))
    rate = n / (time.monotonic() - t0)
    print(json.dumps({
        "metric": "sparse_end2end_samples_per_s",
        "grad_stype": "trainstep_fused",
        "value": round(rate, 1), "unit": "samples/s",
        "features": args.features, "nnz": args.nnz}))


if __name__ == "__main__":
    main()
