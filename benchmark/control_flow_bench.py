#!/usr/bin/env python
"""Control-flow benchmark: `foreach` (one compiled scan) vs an unrolled
per-step RNN.

Reference: benchmark/python/control_flow — the case for the `_foreach`
op (control_flow.cc:476): a fused sequence loop compiles once and runs
as ONE executable (`lax.scan` under XLA here), while the unrolled cell
dispatches T per-step op chains. On TPU the gap is the per-launch
overhead times sequence length.

    python benchmark/control_flow_bench.py --seq-len 128
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--seq-len", type=int, default=128)
    ap.add_argument("--batch-size", type=int, default=16)
    ap.add_argument("--hidden", type=int, default=64)
    ap.add_argument("--iters", type=int, default=10)
    args = ap.parse_args()

    import mxnet_tpu as mx

    mx.util.pin_platform(os.environ.get("MXNET_DEVICE", "cpu"))

    T, B, H = args.seq_len, args.batch_size, args.hidden
    rng = np.random.RandomState(0)
    x = mx.nd.array(rng.randn(T, B, H).astype(np.float32) * 0.1)
    h0 = mx.nd.zeros((B, H))
    w = mx.nd.array((rng.randn(H, H) * 0.05).astype(np.float32))

    def step_fn(inp, state):
        nh = mx.nd.tanh(mx.nd.dot(inp, w) + state[0])
        return nh, [nh]

    def foreach_run():
        out, state = mx.nd.contrib.foreach(step_fn, x, [h0])
        return state[0]

    def unrolled_run():
        h = h0
        for t in range(T):
            h = mx.nd.tanh(mx.nd.dot(x[t], w) + h)
        return h

    for name, fn in (("foreach_scan", foreach_run),
                     ("unrolled", unrolled_run)):
        fn().asnumpy()             # warm: trace + compile
        t0 = time.monotonic()
        for _ in range(args.iters):
            out = fn()
        out.asnumpy()
        dt = time.monotonic() - t0
        print(json.dumps({
            "metric": "control_flow_steps_per_s", "mode": name,
            "value": round(args.iters * T / dt, 1), "unit": "steps/s",
            "seq_len": T, "ms_per_sequence": round(dt / args.iters * 1e3,
                                                   2)}))


if __name__ == "__main__":
    main()
