#!/usr/bin/env python
"""Bucketed LSTM word-language-model driver — the reference's LSTM PTB
tracked config (BASELINE.md; reference example/rnn/bucketing/
lstm_bucketing.py): tokenize text, BucketSentenceIter over sentence
buckets, Embedding + stacked fused LSTM + softmax via sym_gen, trained
with BucketingModule.fit and a Perplexity metric.

TPU rebuild: each bucket length is ONE cached XLA executable (the
bucketing-as-executable-cache design, README); the fused LSTM is a
`lax.scan` op. With ``--synthetic`` (or no data file) the driver builds
a Markov-chain corpus so zero-egress environments exercise the exact
training path the reference measures on sherlockholmes/PTB data.
"""
from __future__ import annotations

import argparse
import logging
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import mxnet_tpu as mx


def tokenize_text(fname, vocab=None, invalid_label=-1, start_label=0):
    """(reference lstm_bucketing.py:tokenize_text)."""
    lines = open(fname).readlines()
    lines = [[w for w in line.split(" ") if w] for line in lines]
    sentences, vocab = mx.rnn.encode_sentences(
        lines, vocab=vocab, invalid_label=invalid_label,
        start_label=start_label)
    return sentences, vocab


def synthetic_corpus(args, rng):
    """Markov-chain sentences: structure for the LM to learn. Token id
    0 is RESERVED for padding (invalid_label) — real tokens are
    1..V-1, mirroring the real-data path's start_label=1."""
    V = args.vocab_size
    trans = rng.dirichlet(np.ones(V - 1) * 0.08, size=V - 1)
    sents = []
    for _ in range(args.num_sentences):
        n = rng.choice(args.buckets)
        w = rng.randint(1, V)
        out = [w]
        for _ in range(n - 1):
            w = 1 + int(rng.choice(V - 1, p=trans[w - 1]))
            out.append(w)
        sents.append(out)
    return sents


def sym_gen_factory(args, vocab_size):
    def sym_gen(seq_len):
        data = mx.sym.Variable("data")
        label = mx.sym.Variable("softmax_label")
        embed = mx.sym.Embedding(data, input_dim=vocab_size,
                                 output_dim=args.num_embed, name="embed")
        stack = mx.rnn.SequentialRNNCell()
        for i in range(args.num_layers):
            stack.add(mx.rnn.FusedRNNCell(args.num_hidden, num_layers=1,
                                          mode="lstm",
                                          prefix="lstm_l%d_" % i))
        outputs, _ = stack.unroll(seq_len, inputs=embed, layout="NTC",
                                  merge_outputs=True)
        pred = mx.sym.reshape(outputs, shape=(-1, args.num_hidden))
        pred = mx.sym.FullyConnected(pred, num_hidden=vocab_size,
                                     name="pred")
        label_flat = mx.sym.reshape(label, shape=(-1,))
        out = mx.sym.SoftmaxOutput(pred, label_flat, name="softmax")
        return out, ("data",), ("softmax_label",)

    return sym_gen


def main():
    parser = argparse.ArgumentParser(
        description="Train a bucketed LSTM LM (reference lstm_bucketing)",
        formatter_class=argparse.ArgumentDefaultsHelpFormatter)
    parser.add_argument("--num-layers", type=int, default=2)
    parser.add_argument("--num-hidden", type=int, default=200)
    parser.add_argument("--num-embed", type=int, default=200)
    parser.add_argument("--kv-store", default="local")
    parser.add_argument("--num-epochs", type=int, default=5)
    parser.add_argument("--lr", type=float, default=0.01)
    parser.add_argument("--optimizer", default="adam")
    parser.add_argument("--mom", type=float, default=0.0)
    parser.add_argument("--wd", type=float, default=1e-5)
    parser.add_argument("--batch-size", type=int, default=32)
    parser.add_argument("--disp-batches", type=int, default=50)
    parser.add_argument("--buckets", default="10,20,30,40",
                        help="comma-separated bucket lengths")
    parser.add_argument("--train-data", default=None,
                        help="tokenized text file (one sentence/line)")
    parser.add_argument("--synthetic", action="store_true")
    parser.add_argument("--vocab-size", type=int, default=200,
                        help="synthetic corpus vocabulary")
    parser.add_argument("--num-sentences", type=int, default=2000)
    parser.add_argument("--device", default=os.environ.get(
        "MXNET_DEVICE", "auto"), choices=["auto", "cpu", "tpu"])
    args = parser.parse_args()
    mx.util.pin_platform(args.device)
    logging.basicConfig(level=logging.INFO)
    args.buckets = [int(b) for b in args.buckets.split(",")]

    if args.train_data and os.path.isfile(args.train_data):
        sents, vocab = tokenize_text(args.train_data, start_label=1,
                                     invalid_label=0)
        vocab_size = len(vocab) + 1
    else:
        rng = np.random.RandomState(0)
        sents = synthetic_corpus(args, rng)
        vocab_size = args.vocab_size
    # BucketSentenceIter produces next-token labels internally (input
    # shifted one step; padding slots get invalid_label=0).
    it = mx.rnn.BucketSentenceIter(sents, batch_size=args.batch_size,
                                   buckets=args.buckets, invalid_label=0)

    kv = mx.kv.create(args.kv_store)
    mod = mx.mod.BucketingModule(
        sym_gen_factory(args, vocab_size),
        default_bucket_key=max(args.buckets),
        context=mx.tpu(0) if args.device != "cpu" and mx.num_tpus()
        else mx.cpu())
    mod.fit(it, num_epoch=args.num_epochs,
            eval_metric=mx.metric.Perplexity(ignore_label=0),
            kvstore=kv, optimizer=args.optimizer,
            optimizer_params={"learning_rate": args.lr,
                              "wd": args.wd} if args.optimizer != "sgd"
            else {"learning_rate": args.lr, "momentum": args.mom,
                  "wd": args.wd},
            initializer=mx.init.Xavier(),
            batch_end_callback=mx.callback.Speedometer(
                args.batch_size, args.disp_batches))
    it.reset()
    ppl = mod.score(it, mx.metric.Perplexity(ignore_label=0))[0][1]
    logging.info("final train perplexity: %.2f", ppl)
    print("final-perplexity %.4f" % ppl)
    if hasattr(kv, "close"):
        kv.close()
    return ppl


if __name__ == "__main__":
    main()
