#!/usr/bin/env python
"""The blessed TPU training path, end to end: RecordIO → sharded
streaming pipeline → one fused SPMD executable per step → checkpoint.

This is the driver that shows how this framework actually trains fast
on TPUs — unlike the reference-parity drivers (Module.fit / Trainer),
every piece here is the TPU-first design:

1. `im2rec`-style RecordIO dataset (synthetic images packed on the fly),
2. `mx.data.DataPipeline`: per-rank deterministic sharding, a
   `--preprocess-threads` parallel decode pool, and double-buffered
   async device prefetch — batch N+1 decodes and DMAs while the step
   runs on batch N (the framework form of the old hand-rolled
   preprocess_threads + PrefetchingIter assembly),
3. `parallel.TrainStep`: forward + loss + backward + optimizer update
   compiled into ONE XLA executable over a `Mesh`, bf16 compute with
   fp32 master weights, buffer donation (in-place updates),
4. bitwise `save_checkpoint`/`load_checkpoint`; the pipeline's own
   `state_dict()` makes resume bit-exact *including data order*.

On a pod: launch one process per host with `tools/launch.py -s 0 ...`
and add `parallel.dist.initialize()` — the same script spans hosts
(each rank's pipeline produces its own equal-size shard of every
epoch, so ranks never diverge in step count).

    python examples/train_resnet_trainstep.py --steps 30
"""
from __future__ import annotations

import argparse
import logging
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import gluon, recordio
from mxnet_tpu.parallel import TrainStep, make_mesh, dist


def pack_dataset(path_prefix, n, size, classes, rng):
    """Synthetic labeled JPEGs into an indexed RecordIO pair (what
    tools/im2rec.py produces from an image tree)."""
    rec, idx = path_prefix + ".rec", path_prefix + ".idx"
    w = recordio.MXIndexedRecordIO(idx, rec, "w")
    for i in range(n):
        lab = i % classes
        img = (rng.rand(size, size, 3) * 60).astype(np.uint8)
        # class-dependent blob so the task is learnable
        c = 12 + 8 * lab
        img[c:c + 10, c:c + 10] += 150
        w.write_idx(i, recordio.pack_img(
            recordio.IRHeader(0, float(lab), i, 0), img, img_fmt=".jpg"))
    w.close()
    return rec, idx


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=40)
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--image-size", type=int, default=56)
    ap.add_argument("--classes", type=int, default=4)
    ap.add_argument("--samples", type=int, default=256)
    ap.add_argument("--lr", type=float, default=0.05)
    ap.add_argument("--dtype", default="bfloat16",
                    help="compute dtype inside the step (masters fp32)")
    ap.add_argument("--preprocess-threads", type=int, default=4)
    ap.add_argument("--seed", type=int, default=12)
    args = ap.parse_args()

    logging.basicConfig(level=logging.INFO, format="%(message)s",
                        stream=sys.stdout, force=True)
    mx.util.pin_platform(os.environ.get("MXNET_DEVICE", "cpu"))
    # Multi-host: forms a process group when launched with DMLC_* env
    # (tools/launch.py -s 0); single-process runs fall straight through.
    dist.initialize()
    mx.random.seed(args.seed)
    rng = np.random.RandomState(args.seed)

    import jax

    with tempfile.TemporaryDirectory() as td:
        rec, idx = pack_dataset(os.path.join(td, "ds"), args.samples,
                                args.image_size, args.classes, rng)
        # Per-rank pipeline: each process decodes only its equal-size
        # shard of every epoch (num_shards/shard_index default from
        # dist), so the global batch assembles with no local_slice math.
        if args.batch_size % dist.num_processes():
            raise SystemExit(
                "--batch-size %d must divide evenly over %d processes"
                % (args.batch_size, dist.num_processes()))
        per_rank = args.batch_size // dist.num_processes()
        it = mx.data.DataPipeline(
            mx.data.RecordDataset([rec], [idx]),
            mx.data.ImageRecordDecoder((3, 48, 48), rand_crop=True,
                                       rand_mirror=True,
                                       mean=np.array([30.0, 30.0, 30.0])),
            batch_size=per_rank, shuffle=True, seed=args.seed,
            decode_threads=args.preprocess_threads, prefetch=2,
            # Multi-host: hand TrainStep host batches — it assembles the
            # global array itself (make_array_from_process_local_data);
            # a local device_put here would just add a wasted H2D plus a
            # blocking D2H pull-back on the step path.
            place=dist.num_processes() == 1)

        from mxnet_tpu.gluon.model_zoo import vision

        net = vision.resnet18_v1(classes=args.classes, thumbnail=True)
        net.initialize(mx.init.Xavier())
        step = TrainStep(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                         optimizer="sgd",
                         optimizer_params={"learning_rate": args.lr,
                                           "momentum": 0.9, "wd": 1e-4},
                         mesh=make_mesh(), dtype=args.dtype)

        losses = []
        seen = 0
        t0 = None
        for s in range(args.steps):
            batch = next(it)        # epochs advance inside the pipeline
            loss = step(batch.data[0], batch.label[0])
            losses.append(float(np.asarray(jax.device_get(loss))))
            if s == 0:
                t0 = time.monotonic()   # exclude compile from rate
            else:
                seen += batch.data[0].shape[0]
            if s % 10 == 0 or s == args.steps - 1:
                logging.info("step %d  loss %.4f", s, losses[-1])
        rate = seen / (time.monotonic() - t0)
        ckpt = step.save_checkpoint(os.path.join(td, "final.params"))
        # The pipeline cursor would ride a CheckpointManager save as
        # {"step": step.state_dict(), "data": it.state_dict()} — resume
        # then replays the exact remaining sample sequence.
        data_state = it.state_dict()
        it.close()
        logging.info("img/s (post-compile) %.1f   checkpoint %s  "
                     "input-stall %.0f%%  data epoch %d  loss %.4f -> %.4f",
                     rate, os.path.basename(ckpt),
                     100.0 * mx.data.stall_fraction(),
                     data_state["epoch"],
                     np.mean(losses[:5]), np.mean(losses[-5:]))
        if not np.mean(losses[-5:]) < np.mean(losses[:5]):
            raise SystemExit("fused step did not reduce loss")


if __name__ == "__main__":
    main()
