#!/usr/bin/env python
"""The blessed TPU training path, end to end: RecordIO → parallel
decode → one fused SPMD executable per step → checkpoint.

This is the driver that shows how this framework actually trains fast
on TPUs — unlike the reference-parity drivers (Module.fit / Trainer),
every piece here is the TPU-first design:

1. `im2rec`-style RecordIO dataset (synthetic images packed on the fly),
2. `ImageRecordIter` with a `preprocess_threads` decode team behind a
   background prefetcher,
3. `parallel.TrainStep`: forward + loss + backward + optimizer update
   compiled into ONE XLA executable over a `Mesh`, bf16 compute with
   fp32 master weights, buffer donation (in-place updates),
4. bitwise `save_checkpoint`/`load_checkpoint`.

On a pod: launch one process per host with `tools/launch.py -s 0 ...`
and add `parallel.dist.initialize()` — the same script spans hosts
(each worker feeds its `dist.local_slice` of the global batch).

    python examples/train_resnet_trainstep.py --steps 30
"""
from __future__ import annotations

import argparse
import logging
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import gluon, recordio
from mxnet_tpu.parallel import TrainStep, make_mesh, dist


def pack_dataset(path_prefix, n, size, classes, rng):
    """Synthetic labeled JPEGs into an indexed RecordIO pair (what
    tools/im2rec.py produces from an image tree)."""
    rec, idx = path_prefix + ".rec", path_prefix + ".idx"
    w = recordio.MXIndexedRecordIO(idx, rec, "w")
    for i in range(n):
        lab = i % classes
        img = (rng.rand(size, size, 3) * 60).astype(np.uint8)
        # class-dependent blob so the task is learnable
        c = 12 + 8 * lab
        img[c:c + 10, c:c + 10] += 150
        w.write_idx(i, recordio.pack_img(
            recordio.IRHeader(0, float(lab), i, 0), img, img_fmt=".jpg"))
    w.close()
    return rec, idx


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=40)
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--image-size", type=int, default=56)
    ap.add_argument("--classes", type=int, default=4)
    ap.add_argument("--samples", type=int, default=256)
    ap.add_argument("--lr", type=float, default=0.05)
    ap.add_argument("--dtype", default="bfloat16",
                    help="compute dtype inside the step (masters fp32)")
    ap.add_argument("--preprocess-threads", type=int, default=4)
    ap.add_argument("--seed", type=int, default=12)
    args = ap.parse_args()

    logging.basicConfig(level=logging.INFO, format="%(message)s",
                        stream=sys.stdout, force=True)
    mx.util.pin_platform(os.environ.get("MXNET_DEVICE", "cpu"))
    # Multi-host: forms a process group when launched with DMLC_* env
    # (tools/launch.py -s 0); single-process runs fall straight through.
    dist.initialize()
    mx.random.seed(args.seed)
    rng = np.random.RandomState(args.seed)

    import jax

    with tempfile.TemporaryDirectory() as td:
        rec, idx = pack_dataset(os.path.join(td, "ds"), args.samples,
                                args.image_size, args.classes, rng)
        it = mx.io.ImageRecordIter(
            path_imgrec=rec, path_imgidx=idx,
            data_shape=(3, 48, 48), batch_size=args.batch_size,
            shuffle=True, rand_crop=True, rand_mirror=True,
            mean_r=30.0, mean_g=30.0, mean_b=30.0,
            preprocess_threads=args.preprocess_threads)

        from mxnet_tpu.gluon.model_zoo import vision

        net = vision.resnet18_v1(classes=args.classes, thumbnail=True)
        net.initialize(mx.init.Xavier())
        step = TrainStep(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                         optimizer="sgd",
                         optimizer_params={"learning_rate": args.lr,
                                           "momentum": 0.9, "wd": 1e-4},
                         mesh=make_mesh(), dtype=args.dtype)

        losses = []
        seen = 0
        t0 = None
        for s in range(args.steps):
            try:
                batch = next(it)
            except StopIteration:
                it.reset()
                batch = next(it)
            lo, hi = dist.local_slice(batch.data[0].shape[0])
            x = batch.data[0].asnumpy()[lo:hi]
            y = batch.label[0].asnumpy()[lo:hi]
            loss = step(x, y)
            losses.append(float(np.asarray(jax.device_get(loss))))
            if s == 0:
                t0 = time.monotonic()   # exclude compile from rate
            else:
                seen += batch.data[0].shape[0]
            if s % 10 == 0 or s == args.steps - 1:
                logging.info("step %d  loss %.4f", s, losses[-1])
        rate = seen / (time.monotonic() - t0)
        ckpt = step.save_checkpoint(os.path.join(td, "final.params"))
        logging.info("img/s (post-compile) %.1f   checkpoint %s  "
                     "loss %.4f -> %.4f", rate, os.path.basename(ckpt),
                     np.mean(losses[:5]), np.mean(losses[-5:]))
        if not np.mean(losses[-5:]) < np.mean(losses[:5]):
            raise SystemExit("fused step did not reduce loss")


if __name__ == "__main__":
    main()
