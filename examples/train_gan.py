#!/usr/bin/env python
"""DCGAN-style adversarial training with two optimizers.

Reference: example/gan/CGAN_mnist_R (and the classic gan examples) —
the two-network/two-Trainer adversarial loop is the API surface this
driver exercises: generator and discriminator each own a Trainer, the
discriminator trains on real+fake batches, the generator trains through
the discriminator's frozen graph.

Synthetic by default (zero-egress): "real" samples are 1×8×8 blob
images. CI-sized run:

    python examples/train_gan.py --epochs 2 --batches 8
"""
from __future__ import annotations

import argparse
import logging
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon


def build_generator(latent):
    net = gluon.nn.HybridSequential(prefix="gen_")
    with net.name_scope():
        net.add(gluon.nn.Dense(64, activation="relu", in_units=latent),
                gluon.nn.Dense(64, activation="relu", in_units=64),
                gluon.nn.Dense(64, in_units=64),
                gluon.nn.HybridLambda(lambda F, x: F.tanh(x)))
    return net


def build_discriminator():
    net = gluon.nn.HybridSequential(prefix="disc_")
    with net.name_scope():
        net.add(gluon.nn.Dense(64, in_units=64),
                gluon.nn.LeakyReLU(0.2),
                gluon.nn.Dense(32, in_units=64),
                gluon.nn.LeakyReLU(0.2),
                gluon.nn.Dense(1, in_units=32))
    return net


def real_batch(rng, batch_size):
    """Blobby 8x8 images: a bright gaussian bump at a random position."""
    yy, xx = np.mgrid[0:8, 0:8]
    cy = rng.uniform(2, 6, size=(batch_size, 1, 1))
    cx = rng.uniform(2, 6, size=(batch_size, 1, 1))
    img = np.exp(-((yy - cy) ** 2 + (xx - cx) ** 2) / 3.0)
    return (img * 2 - 1).reshape(batch_size, 64).astype(np.float32)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=3)
    ap.add_argument("--batches", type=int, default=16,
                    help="batches per epoch")
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--latent", type=int, default=16)
    ap.add_argument("--lr", type=float, default=2e-3)
    ap.add_argument("--seed", type=int, default=42)
    args = ap.parse_args()

    logging.basicConfig(level=logging.INFO, format="%(message)s",
                        stream=sys.stdout, force=True)
    mx.util.pin_platform(os.environ.get("MXNET_DEVICE", "cpu"))
    mx.random.seed(args.seed)
    rng = np.random.RandomState(args.seed)

    gen = build_generator(args.latent)
    disc = build_discriminator()
    gen.initialize(mx.init.Normal(0.05))
    disc.initialize(mx.init.Normal(0.05))
    gen.hybridize()
    disc.hybridize()

    g_tr = gluon.Trainer(gen.collect_params(), "adam",
                         {"learning_rate": args.lr, "beta1": 0.5})
    d_tr = gluon.Trainer(disc.collect_params(), "adam",
                         {"learning_rate": args.lr, "beta1": 0.5})
    loss_fn = gluon.loss.SigmoidBinaryCrossEntropyLoss()
    bs = args.batch_size
    ones = mx.nd.ones((bs,))
    zeros = mx.nd.zeros((bs,))

    d_losses = [float("nan")]
    for epoch in range(args.epochs):
        d_losses, g_losses = [], []
        for _ in range(args.batches):
            real = mx.nd.array(real_batch(rng, bs))
            z = mx.nd.array(rng.randn(bs, args.latent).astype(np.float32))

            # -- discriminator: real -> 1, fake -> 0 (fake detached by
            #    recording only disc's forward on generated data)
            fake = gen(z)
            with autograd.record():
                d_loss = (loss_fn(disc(real), ones)
                          + loss_fn(disc(fake), zeros)).sum()
            d_loss.backward()
            d_tr.step(bs)

            # -- generator: fool the discriminator (grads flow through
            #    disc's graph into gen's params; disc is not stepped)
            z = mx.nd.array(rng.randn(bs, args.latent).astype(np.float32))
            with autograd.record():
                g_loss = loss_fn(disc(gen(z)), ones).sum()
            g_loss.backward()
            g_tr.step(bs)

            d_losses.append(float(d_loss.asnumpy()) / bs)
            g_losses.append(float(g_loss.asnumpy()) / bs)
        logging.info("epoch %d  d_loss %.4f  g_loss %.4f", epoch,
                     np.mean(d_losses), np.mean(g_losses))

    # Sanity: the generator's output distribution moved toward the
    # data's global statistics (blobs have mean ≈ -0.55).
    z = mx.nd.array(rng.randn(256, args.latent).astype(np.float32))
    fake_mean = float(gen(z).asnumpy().mean())
    real_mean = float(real_batch(rng, 256).mean())
    logging.info("fake mean %.3f vs real mean %.3f", fake_mean, real_mean)
    if not np.isfinite(np.mean(d_losses)) or not np.isfinite(fake_mean):
        raise SystemExit("GAN training produced non-finite values")


if __name__ == "__main__":
    main()
