#!/usr/bin/env python
"""Convolutional autoencoder: unsupervised reconstruction.

Reference: example/autoencoder (+ deep-embedded-clustering's
pretraining stage) — encode to a small bottleneck, decode back with
transposed convolutions, train on reconstruction L2. The API surface
this driver exercises: `Conv2DTranspose` upsampling, encoder/decoder
composition, and the bottleneck as a representation (nearest neighbors
in code space share blob geometry).

Synthetic data: two-blob images whose blob positions define similarity.

    python examples/train_autoencoder.py --epochs 4
"""
from __future__ import annotations

import argparse
import logging
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon

SIZE = 16


class ConvAE(gluon.HybridBlock):
    def __init__(self, code=8, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.enc = gluon.nn.HybridSequential()
            self.enc.add(
                gluon.nn.Conv2D(8, 3, strides=2, padding=1,
                                activation="relu"),     # 16 -> 8
                gluon.nn.Conv2D(16, 3, strides=2, padding=1,
                                activation="relu"),     # 8 -> 4
                gluon.nn.Flatten(),
                gluon.nn.Dense(code))
            self.dec_fc = gluon.nn.Dense(16 * 4 * 4, activation="relu")
            self.dec = gluon.nn.HybridSequential()
            self.dec.add(
                gluon.nn.Conv2DTranspose(8, 4, strides=2, padding=1,
                                         activation="relu"),  # 4 -> 8
                gluon.nn.Conv2DTranspose(1, 4, strides=2, padding=1))
                                                              # 8 -> 16

    def encode(self, x):
        return self.enc(x)

    def hybrid_forward(self, F, x):
        z = self.enc(x)
        h = self.dec_fc(z).reshape((-1, 16, 4, 4))
        return self.dec(h)


def make_data(rng, n):
    imgs = np.zeros((n, 1, SIZE, SIZE), np.float32)
    pos = rng.randint(2, SIZE - 4, (n, 2))
    for i, (y, x) in enumerate(pos):
        imgs[i, 0, y:y + 3, x:x + 3] = 1.0
        imgs[i, 0, (y + 7) % (SIZE - 3), (x + 5) % (SIZE - 3)] = 0.8
    return imgs + rng.rand(n, 1, SIZE, SIZE).astype(np.float32) * 0.05


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=6)
    ap.add_argument("--train", type=int, default=1024)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--lr", type=float, default=2e-3)
    ap.add_argument("--seed", type=int, default=4)
    args = ap.parse_args()

    logging.basicConfig(level=logging.INFO, format="%(message)s",
                        stream=sys.stdout, force=True)
    mx.util.pin_platform(os.environ.get("MXNET_DEVICE", "cpu"))
    mx.random.seed(args.seed)
    rng = np.random.RandomState(args.seed)

    X = make_data(rng, args.train)
    net = ConvAE()
    net.initialize(mx.init.Xavier())
    net.hybridize()
    tr = gluon.Trainer(net.collect_params(), "adam",
                       {"learning_rate": args.lr})
    l2 = gluon.loss.L2Loss()
    bs = args.batch_size

    first = last = None
    for epoch in range(args.epochs):
        perm = rng.permutation(args.train)
        tot = 0.0
        n_seen = 0
        for off in range(0, args.train - bs + 1, bs):
            xb = mx.nd.array(X[perm[off:off + bs]])
            with autograd.record():
                loss = l2(net(xb), xb).sum()
            loss.backward()
            tr.step(bs)
            tot += float(loss.asnumpy())
            n_seen += bs
        cur = tot / n_seen
        if first is None:
            first = cur
        last = cur
        logging.info("epoch %d  recon_loss %.5f", epoch, cur)

    # Bottleneck-as-representation check: nearest neighbor in code
    # space should share blob geometry — its pixel distance must beat
    # the average random pair by a clear margin.
    Xv = make_data(rng, 128)
    codes = net.encode(mx.nd.array(Xv)).asnumpy()
    d2 = ((codes[:, None] - codes[None]) ** 2).sum(-1)
    np.fill_diagonal(d2, np.inf)
    nn = d2.argmin(1)
    flat = Xv.reshape(128, -1)
    nn_pix = np.linalg.norm(flat - flat[nn], axis=1).mean()
    rand_pix = np.linalg.norm(flat - flat[rng.permutation(128)],
                              axis=1).mean()
    logging.info("recon %.5f -> %.5f   nn-pix %.3f vs random %.3f",
                 first, last, nn_pix, rand_pix)
    if not (np.isfinite(last) and last < first * 0.75):
        raise SystemExit("autoencoder reconstruction did not improve")
    if not nn_pix < rand_pix * 0.9:
        raise SystemExit("bottleneck codes carry no structure "
                         "(%.3f vs %.3f)" % (nn_pix, rand_pix))


if __name__ == "__main__":
    main()
