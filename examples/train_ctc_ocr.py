#!/usr/bin/env python
"""CTC sequence recognition: read digit strings off synthetic "captcha"
strips without per-frame alignment.

Reference: example/ctc + example/captcha (LSTM + warp-CTC OCR) — the
API surface this driver exercises: `gluon.loss.CTCLoss` (the warp-ctc
derived ctc_loss op) over unaligned (image-strip, label-string) pairs,
with a conv column-encoder + BiLSTM-free recurrent head, and greedy
CTC decoding (collapse repeats, drop blanks) for evaluation.

Synthetic data: each sample is a 12×48 strip containing 2-3 glyphs
(blocky 5×7 patterns, 4 classes) at random horizontal positions; the
label is the glyph string. At CI size the model is typically still in
CTC's early all-blank phase (loss dropping, decodes empty) — escaping
it takes more steps than a 1-core CI budget allows; the success
criterion is the loss trajectory. Run:

    python examples/train_ctc_ocr.py --steps 40
"""
from __future__ import annotations

import argparse
import logging
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon

H, W = 12, 48          # strip size
MAXLEN = 3             # max digits per strip
VOC = 4                # digit classes; CTC blank is class VOC
MINLEN = 2

# 5x7 blocky digit glyphs (rows of 5 bits per digit).
_GLYPHS = [
    0x1F11111F, 0x04040404, 0x1F101F01, 0x1F101F10, 0x11111F10,
    0x1F011F10, 0x1F011F11, 0x1F101010, 0x1F111F11, 0x1F111F10,
]


def _glyph(d):
    bits = _GLYPHS[d]
    g = np.zeros((7, 5), np.float32)
    for r in range(7):
        row = (bits >> (5 * (r % 6))) & 0x1F
        for c in range(5):
            g[r, c] = (row >> (4 - c)) & 1
    return g


GLYPHS = [_glyph(d) for d in range(10)]


def make_strip(rng):
    n = rng.randint(MINLEN, MAXLEN + 1)
    digits = rng.randint(0, VOC, n)
    img = rng.rand(H, W).astype(np.float32) * 0.15
    xs = np.sort(rng.choice(np.arange(2, W - 7, 6), n, replace=False))
    for d, x in zip(digits, xs):
        y = rng.randint(1, H - 8)
        img[y:y + 7, x:x + 5] += GLYPHS[d] * 0.8
    label = np.full(MAXLEN, -1, np.float32)
    label[:n] = digits
    return img, label


class OCRNet(gluon.HybridBlock):
    """Column encoder: conv over the strip, then per-column features
    feed a GRU whose per-step outputs are CTC frame activations."""

    def __init__(self, hidden=48, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.conv = gluon.nn.Conv2D(12, 3, padding=1,
                                        activation="relu")
            self.pool = gluon.nn.MaxPool2D((2, 2))   # (H/2, W/2)
            self.gru = gluon.rnn.GRU(hidden, layout="NTC")
            self.head = gluon.nn.Dense(VOC + 1, flatten=False)

    def hybrid_forward(self, F, x):
        f = self.pool(self.conv(x))                  # (N, C, H/2, W/2)
        f = f.transpose((0, 3, 1, 2))                # (N, T=W/2, C, H/2)
        f = f.reshape((0, 0, -1))                    # (N, T, C*H/2)
        return self.head(self.gru(f))                # (N, T, VOC+1)


def greedy_decode(frames):
    """Collapse repeats then drop blanks (standard CTC best path)."""
    best = frames.argmax(axis=-1)
    out = []
    for row in best:
        prev = -1
        s = []
        for t in row:
            if t != prev and t != VOC:
                s.append(int(t))
            prev = t
        out.append(s)
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=150)
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--lr", type=float, default=1e-3)
    ap.add_argument("--seed", type=int, default=1)
    args = ap.parse_args()

    logging.basicConfig(level=logging.INFO, format="%(message)s",
                        stream=sys.stdout, force=True)
    mx.util.pin_platform(os.environ.get("MXNET_DEVICE", "cpu"))
    mx.random.seed(args.seed)
    rng = np.random.RandomState(args.seed)

    net = OCRNet()
    net.initialize(mx.init.Xavier())
    net.hybridize()
    tr = gluon.Trainer(net.collect_params(), "adam",
                       {"learning_rate": args.lr,
                        "clip_gradient": 5.0})
    # layout NTC matches the head's (N, T, C) output; blank = last class
    ctc = gluon.loss.CTCLoss(layout="NTC", label_layout="NT")
    bs = args.batch_size

    def batch():
        imgs, labels = zip(*(make_strip(rng) for _ in range(bs)))
        return (mx.nd.array(np.stack(imgs)[:, None]),
                mx.nd.array(np.stack(labels)))

    first = last = None
    for step in range(args.steps):
        x, y = batch()
        with autograd.record():
            loss = ctc(net(x), y).sum()
        loss.backward()
        tr.step(bs)
        cur = float(loss.asnumpy()) / bs
        if first is None:
            first = cur
        last = cur
        if step % 25 == 0 or step == args.steps - 1:
            logging.info("step %d  ctc_loss %.3f", step, cur)

    # Greedy-decode exact-sequence match on fresh strips (expected 0.00
    # at CI size — see module docstring on the all-blank phase).
    x, y = batch()
    with autograd.pause():
        decoded = greedy_decode(net(x).asnumpy())
    truth = [[int(v) for v in row if v >= 0] for row in y.asnumpy()]
    exact = np.mean([d == t for d, t in zip(decoded, truth)])
    logging.info("ctc loss %.3f -> %.3f   exact-sequence %.2f", first,
                 last, exact)
    logging.info("sample: truth=%s decoded=%s", truth[0], decoded[0])
    if not (np.isfinite(last) and last < first * 0.9):
        raise SystemExit("CTC training did not reduce loss")


if __name__ == "__main__":
    main()
