#!/usr/bin/env python
"""Fully-convolutional segmentation: per-pixel classification with a
learned upsampling head.

Reference: example/fcn-xs (FCN-8s on VOC) — the API surface this driver
exercises: an FCN encoder, `Conv2DTranspose` upsampling back to input
resolution, per-pixel SoftmaxCrossEntropy (label image, not label
scalar), and mean-IoU evaluation.

Synthetic scenes: background plus two shape classes (filled square,
filled disc); the label image marks each pixel 0/1/2.

    python examples/train_fcn_segmentation.py --epochs 4
"""
from __future__ import annotations

import argparse
import logging
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon

SIZE = 24
NCLS = 3


class MiniFCN(gluon.HybridBlock):
    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.enc = gluon.nn.HybridSequential()
            self.enc.add(
                gluon.nn.Conv2D(12, 3, padding=1, activation="relu"),
                gluon.nn.MaxPool2D(2),                      # 24 -> 12
                gluon.nn.Conv2D(24, 3, padding=1, activation="relu"),
                gluon.nn.MaxPool2D(2),                      # 12 -> 6
                gluon.nn.Conv2D(24, 3, padding=1, activation="relu"))
            self.up = gluon.nn.HybridSequential()
            self.up.add(
                gluon.nn.Conv2DTranspose(16, 4, strides=2, padding=1,
                                         activation="relu"),  # 6 -> 12
                gluon.nn.Conv2DTranspose(NCLS, 4, strides=2,
                                         padding=1))          # 12 -> 24

    def hybrid_forward(self, F, x):
        return self.up(self.enc(x))        # (N, NCLS, H, W)


def make_scene(rng):
    img = rng.rand(3, SIZE, SIZE).astype(np.float32) * 0.2
    lab = np.zeros((SIZE, SIZE), np.float32)
    # one square (class 1)
    s = rng.randint(5, 9)
    y, x = rng.randint(0, SIZE - s, 2)
    img[0, y:y + s, x:x + s] += 0.7
    lab[y:y + s, x:x + s] = 1
    # one disc (class 2)
    cy, cx = rng.randint(6, SIZE - 6, 2)
    yy, xx = np.mgrid[0:SIZE, 0:SIZE]
    disc = (yy - cy) ** 2 + (xx - cx) ** 2 <= rng.randint(9, 20)
    img[1][disc] += 0.7
    lab[disc] = 2
    return img, lab


def mean_iou(pred, lab):
    ious = []
    for c in range(NCLS):
        inter = ((pred == c) & (lab == c)).sum()
        union = ((pred == c) | (lab == c)).sum()
        if union:
            ious.append(inter / union)
    return float(np.mean(ious))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=6)
    ap.add_argument("--train", type=int, default=512)
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--lr", type=float, default=3e-3)
    ap.add_argument("--seed", type=int, default=6)
    args = ap.parse_args()

    logging.basicConfig(level=logging.INFO, format="%(message)s",
                        stream=sys.stdout, force=True)
    mx.util.pin_platform(os.environ.get("MXNET_DEVICE", "cpu"))
    mx.random.seed(args.seed)
    rng = np.random.RandomState(args.seed)

    data = [make_scene(rng) for _ in range(args.train + 128)]
    X = np.stack([d[0] for d in data[:args.train]])
    Y = np.stack([d[1] for d in data[:args.train]])
    Xv = np.stack([d[0] for d in data[args.train:]])
    Yv = np.stack([d[1] for d in data[args.train:]])

    net = MiniFCN()
    net.initialize(mx.init.Xavier())
    net.hybridize()
    tr = gluon.Trainer(net.collect_params(), "adam",
                       {"learning_rate": args.lr})
    # axis=1: per-pixel class scores in channel dim, label is an image
    ce = gluon.loss.SoftmaxCrossEntropyLoss(axis=1, sparse_label=True)
    bs = min(args.batch_size, args.train)

    miou = 0.0
    for epoch in range(args.epochs):
        perm = rng.permutation(args.train)
        tot = 0.0
        n_seen = 0
        for off in range(0, args.train - bs + 1, bs):
            sel = perm[off:off + bs]
            with autograd.record():
                loss = ce(net(mx.nd.array(X[sel])),
                          mx.nd.array(Y[sel])).sum()
            loss.backward()
            tr.step(bs)
            tot += float(loss.asnumpy())
            n_seen += bs
        with autograd.pause(train_mode=False):
            pred = net(mx.nd.array(Xv)).asnumpy().argmax(1)
        miou = mean_iou(pred, Yv)
        logging.info("epoch %d  loss %.4f  mean-IoU %.3f", epoch,
                     tot / n_seen, miou)

    if miou < 0.5:
        raise SystemExit("segmentation mean-IoU too low: %.3f" % miou)


if __name__ == "__main__":
    main()
