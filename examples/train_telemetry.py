#!/usr/bin/env python
"""Observability end to end: a TrainStep loop wired into
`mxnet_tpu.telemetry` — unified metrics, chrome-trace spans, and the
step-health monitor (README "Observability").

What this driver shows:

1. `callback.TelemetryCallback` — the Speedometer-shaped batch-end
   callback that feeds `mx_train_batch_seconds` / `mx_train_samples_total`
   and a `telemetry.StepMonitor`,
2. `StepMonitor` — slow-step EWMA outliers, recompile detection via
   `CachedOp.on_trace`, checkpoint-writer backlog (all warn rate-limited
   through mxnet_tpu.log and count into `mx_anomalies_total`),
3. async `checkpoint.CheckpointManager` saves whose `checkpoint::*`
   counters land in the SAME registry,
4. **streaming span export** — a `StreamingTraceWriter` drains the
   trace rings incrementally into atomically committed
   `trace.rank0.*.jsonl` segments (a kill mid-run keeps everything
   committed so far), and `tools/trace_merge.py` stitches them into the
   final chrome_trace.json loadable in Perfetto (chrome://tracing),
5. **pod-style aggregation** — an `Aggregator` over a `LocalBus`
   endpoint shows the fleet view: the final scrape carries every series
   labeled by rank (here rank 0) plus the staleness gauges,
6. **SLO burn rate** — a `BurnRateMonitor` over `mx_train_step_seconds`
   emits `mx_slo_burn_rate{slo,window}` gauges,
7. **flamegraph** — `profiler.dumps(format="top")` self-time table and
   a collapsed-stack file for flamegraph.pl / speedscope,
8. `telemetry.render_prometheus()` — and, with `--metrics-port`, a live
   stdlib `/metrics` endpoint to curl while it trains.

    python examples/train_telemetry.py --num-batches 40
    python examples/train_telemetry.py --metrics-port 9090
"""
from __future__ import annotations

import argparse
import logging
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import callback, gluon, model, telemetry
from mxnet_tpu.checkpoint import CheckpointManager
from mxnet_tpu.parallel import TrainStep, make_mesh
from mxnet_tpu.telemetry import trace


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--num-batches", type=int, default=40)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--ckpt-every", type=int, default=10)
    ap.add_argument("--metrics-port", type=int, default=0,
                    help="serve /metrics on this port (0 = off)")
    ap.add_argument("--out-dir", default=None,
                    help="where chrome_trace.json + checkpoints land "
                         "(default: a temp dir)")
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO)

    out_dir = args.out_dir or tempfile.mkdtemp(prefix="telemetry_demo_")
    os.makedirs(out_dir, exist_ok=True)

    server = None
    if args.metrics_port:
        server = telemetry.start_http_server(args.metrics_port)
        print("metrics: http://%s:%d/metrics" % server.server_address[:2])

    # -- model + fused step ---------------------------------------------------
    mx.random.seed(42)
    rng = np.random.RandomState(42)
    net = gluon.nn.HybridSequential(prefix="tele_")
    net.add(gluon.nn.Dense(256, activation="relu", in_units=784,
                           prefix="fc1_"))
    net.add(gluon.nn.Dense(10, in_units=256, prefix="fc2_"))
    net.initialize(mx.init.Xavier())
    step = TrainStep(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                     optimizer="sgd",
                     optimizer_params={"learning_rate": 0.05,
                                       "momentum": 0.9},
                     mesh=make_mesh())

    # -- telemetry wiring -----------------------------------------------------
    monitor = telemetry.StepMonitor(slow_factor=3.0, warmup_steps=3)
    # Streaming export: spans hit disk incrementally (age budget keeps
    # an observer at most 5s behind), committed atomically per segment.
    writer = telemetry.StreamingTraceWriter(
        os.path.join(out_dir, "trace_segments"), max_segment_age_s=5.0)
    # Pod-style aggregation, single-process edition: a LocalBus stands
    # in for the kvstore channel; the fleet scrape labels every series
    # with its rank. On a real dist job pass the KVStoreDist instead.
    bus = telemetry.aggregate.LocalBus(num_workers=1)
    aggregator = telemetry.Aggregator(bus.endpoint(0), interval_s=2.0,
                                      monitor=monitor)
    # SLO: 95% of steps under 2s — generous on purpose; the burn-rate
    # gauges still show the machinery live.
    burn = telemetry.BurnRateMonitor(eval_interval_s=1.0)
    burn.add_latency_slo("train_step", 0.95, 2.0, "mx_train_step_seconds")
    cb = callback.TelemetryCallback(args.batch_size, frequent=10,
                                    monitor=monitor, trace_writer=writer,
                                    aggregator=aggregator, slo=burn)
    manager = CheckpointManager(os.path.join(out_dir, "ckpt"),
                                keep_last=2)
    monitor.watch_checkpoint(manager)

    x = rng.rand(args.batch_size, 784).astype(np.float32)
    y = rng.randint(0, 10, args.batch_size)
    loss = None
    for i in range(args.num_batches):
        loss = step(x, y)
        if args.ckpt_every and (i + 1) % args.ckpt_every == 0:
            manager.save(i + 1, step.state_dict())     # async commit
        cb(model.BatchEndParam(epoch=0, nbatch=i, eval_metric=None,
                               locals=None))
    final_loss = float(np.asarray(loss))
    manager.close()
    burn.evaluate()
    aggregator.close()          # final push: fleet view is current
    writer.close()              # final segment commit

    # -- merge + report -------------------------------------------------------
    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "tools"))
    import trace_merge

    trace_path = os.path.join(out_dir, "chrome_trace.json")
    merged = trace_merge.merge([os.path.join(out_dir, "trace_segments")],
                               out=trace_path)
    flame_path = telemetry.flamegraph.dump_collapsed(
        os.path.join(out_dir, "flame.collapsed"), merged)

    text = aggregator.render_prometheus()       # the fleet view
    interesting = [l for l in text.splitlines()
                   if l.startswith(("mx_train_steps_total",
                                    "mx_train_samples_total",
                                    "mx_train_step_seconds_count",
                                    "mx_cachedop_compiles_total",
                                    "mx_anomalies_total",
                                    "mx_slo_burn_rate",
                                    "mx_rank_stale"))
                   or 'name="checkpoint::' in l]
    print("\n".join(interesting))
    print("step-health: %s" % monitor.snapshot())
    print(mx.profiler.dumps(format="top"))
    print("chrome trace: %s (load in Perfetto / chrome://tracing); "
          "%d streamed segments; collapsed stacks: %s"
          % (trace_path, len(writer.committed), flame_path))
    print("final loss %.4f" % final_loss)

    steps_total = telemetry.REGISTRY.get("mx_train_steps_total").value
    ok = (steps_total >= args.num_batches
          and os.path.getsize(trace_path) > 0
          and len(writer.committed) >= 1
          and 'rank="0"' in text
          and "mx_train_step_seconds_count" in text)
    if server is not None:
        server.close()
    print("telemetry demo %s" % ("ok" if ok else "FAILED"))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
