#!/usr/bin/env python
"""Observability end to end: a TrainStep loop wired into
`mxnet_tpu.telemetry` — unified metrics, chrome-trace spans, and the
step-health monitor (README "Observability").

What this driver shows:

1. `callback.TelemetryCallback` — the Speedometer-shaped batch-end
   callback that feeds `mx_train_batch_seconds` / `mx_train_samples_total`
   and a `telemetry.StepMonitor`,
2. `StepMonitor` — slow-step EWMA outliers, recompile detection via
   `CachedOp.on_trace`, checkpoint-writer backlog (all warn rate-limited
   through mxnet_tpu.log and count into `mx_anomalies_total`),
3. async `checkpoint.CheckpointManager` saves whose `checkpoint::*`
   counters land in the SAME registry,
4. `telemetry.trace.dump()` — a chrome_trace.json loadable in Perfetto
   (chrome://tracing), spans from the train-step, serving and
   checkpoint seams on their own thread tracks,
5. `telemetry.render_prometheus()` — and, with `--metrics-port`, a live
   stdlib `/metrics` endpoint to curl while it trains.

    python examples/train_telemetry.py --num-batches 40
    python examples/train_telemetry.py --metrics-port 9090
"""
from __future__ import annotations

import argparse
import logging
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import callback, gluon, model, telemetry
from mxnet_tpu.checkpoint import CheckpointManager
from mxnet_tpu.parallel import TrainStep, make_mesh
from mxnet_tpu.telemetry import trace


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--num-batches", type=int, default=40)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--ckpt-every", type=int, default=10)
    ap.add_argument("--metrics-port", type=int, default=0,
                    help="serve /metrics on this port (0 = off)")
    ap.add_argument("--out-dir", default=None,
                    help="where chrome_trace.json + checkpoints land "
                         "(default: a temp dir)")
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO)

    out_dir = args.out_dir or tempfile.mkdtemp(prefix="telemetry_demo_")
    os.makedirs(out_dir, exist_ok=True)

    server = None
    if args.metrics_port:
        server = telemetry.start_http_server(args.metrics_port)
        print("metrics: http://%s:%d/metrics" % server.server_address[:2])

    # -- model + fused step ---------------------------------------------------
    mx.random.seed(42)
    rng = np.random.RandomState(42)
    net = gluon.nn.HybridSequential(prefix="tele_")
    net.add(gluon.nn.Dense(256, activation="relu", in_units=784,
                           prefix="fc1_"))
    net.add(gluon.nn.Dense(10, in_units=256, prefix="fc2_"))
    net.initialize(mx.init.Xavier())
    step = TrainStep(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                     optimizer="sgd",
                     optimizer_params={"learning_rate": 0.05,
                                       "momentum": 0.9},
                     mesh=make_mesh())

    # -- telemetry wiring -----------------------------------------------------
    monitor = telemetry.StepMonitor(slow_factor=3.0, warmup_steps=3)
    cb = callback.TelemetryCallback(args.batch_size, frequent=10,
                                    monitor=monitor)
    manager = CheckpointManager(os.path.join(out_dir, "ckpt"),
                                keep_last=2)
    monitor.watch_checkpoint(manager)

    x = rng.rand(args.batch_size, 784).astype(np.float32)
    y = rng.randint(0, 10, args.batch_size)
    loss = None
    for i in range(args.num_batches):
        loss = step(x, y)
        if args.ckpt_every and (i + 1) % args.ckpt_every == 0:
            manager.save(i + 1, step.state_dict())     # async commit
        cb(model.BatchEndParam(epoch=0, nbatch=i, eval_metric=None,
                               locals=None))
    final_loss = float(np.asarray(loss))
    manager.close()

    # -- flush + report -------------------------------------------------------
    trace_path = trace.dump(os.path.join(out_dir, "chrome_trace.json"))
    text = telemetry.render_prometheus()
    interesting = [l for l in text.splitlines()
                   if l.startswith(("mx_train_steps_total",
                                    "mx_train_samples_total",
                                    "mx_train_step_seconds_count",
                                    "mx_cachedop_compiles_total",
                                    "mx_anomalies_total"))
                   or 'name="checkpoint::' in l]
    print("\n".join(interesting))
    print("step-health: %s" % monitor.snapshot())
    print("chrome trace: %s (load in Perfetto / chrome://tracing)"
          % trace_path)
    print("final loss %.4f" % final_loss)

    steps_total = telemetry.REGISTRY.get("mx_train_steps_total").value
    ok = (steps_total >= args.num_batches
          and os.path.getsize(trace_path) > 0
          and "mx_train_step_seconds_count" in text)
    if server is not None:
        server.shutdown()
    print("telemetry demo %s" % ("ok" if ok else "FAILED"))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
