#!/usr/bin/env python
"""Preemption-safe training with fault-tolerant async checkpoints.

The robustness core of running training on preemptible TPU fleets
(`mxnet_tpu.checkpoint`): every step is checkpointed *asynchronously*
with atomic commit, a SIGTERM triggers one final synchronous save, and
a restarted process resumes **bit-exact** from the latest committed
step — params, optimizer momentum, step counter and RNG stream all
continue exactly as the uninterrupted run would.

Two modes:

* default (demo): spawns itself as a worker, SIGTERMs it mid-run,
  restarts it to completion, then runs an uninterrupted reference in a
  fresh directory and proves the final parameter digests are identical::

      python examples/train_resume.py --steps 18 --kill-after 6

* ``--worker``: the actual training loop (what a fleet scheduler would
  launch). Restarting it with the same ``--ckpt-dir`` resumes from the
  newest fully committed checkpoint; corrupt or torn checkpoints are
  skipped automatically.
"""
from __future__ import annotations

import argparse
import hashlib
import os
import signal
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def make_batch(step, batch_size=32, in_dim=64, classes=8):
    """Deterministic batch for a given global step — the data pipeline
    position is a pure function of the step counter, so a resumed run
    reads exactly the batches the killed run would have."""
    rng = np.random.RandomState(77_000 + step)
    x = rng.rand(batch_size, in_dim).astype(np.float32)
    y = rng.randint(0, classes, batch_size)
    return x, y


def build_step(args):
    import mxnet_tpu as mx
    from mxnet_tpu import gluon
    from mxnet_tpu.parallel import TrainStep, make_mesh

    mx.random.seed(args.seed)
    np.random.seed(args.seed)
    # Fixed prefixes: checkpoint keys must be stable across restarts.
    net = gluon.nn.HybridSequential(prefix="net_")
    net.add(gluon.nn.Dense(64, activation="relu", in_units=64,
                           prefix="fc1_"))
    net.add(gluon.nn.Dense(8, in_units=64, prefix="fc2_"))
    net.initialize(mx.init.Xavier())
    return TrainStep(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                     optimizer="sgd",
                     optimizer_params={"learning_rate": args.lr,
                                       "momentum": 0.9},
                     mesh=make_mesh())


def state_digest(state_dict):
    """SHA-256 over params + optimizer state + step counter — the
    bit-exactness witness printed by every finished worker."""
    h = hashlib.sha256()
    for section in ("params", "opt"):
        sec = state_dict.get(section, {})
        for name in sorted(sec):
            leaf = sec[name]
            if isinstance(leaf, dict):
                for k in sorted(leaf):
                    h.update(np.ascontiguousarray(leaf[k]).tobytes())
            else:
                h.update(np.ascontiguousarray(leaf).tobytes())
    h.update(str(state_dict.get("num_update", 0)).encode())
    return h.hexdigest()


def worker(args):
    import mxnet_tpu as mx
    from mxnet_tpu.checkpoint import CheckpointManager, PreemptionHook, \
        CheckpointNotFoundError

    mx.util.pin_platform(os.environ.get("MXNET_DEVICE", "cpu"))
    step = build_step(args)
    mgr = CheckpointManager(args.ckpt_dir, keep_last=3)

    start = 0
    try:
        restored_step, state = mgr.restore()
        step.load_state_dict(state)
        start = restored_step
        print("resumed-from %d" % restored_step, flush=True)
    except CheckpointNotFoundError:
        print("fresh-start", flush=True)

    hook = PreemptionHook(mgr, state_fn=step.state_dict,
                          step_fn=lambda: step.num_update).install()
    loss = None
    for s in range(start, args.steps):
        x, y = make_batch(s)
        loss = float(np.asarray(step(x, y)))
        if (s + 1) % args.save_every == 0:
            mgr.save(s + 1, step.state_dict())   # async, off the step path
        print("step %d loss %.6f" % (s, loss), flush=True)
        if args.step_delay:
            time.sleep(args.step_delay)
    mgr.save(args.steps, step.state_dict(), sync=True)
    mgr.close()
    hook.uninstall()
    if loss is not None:
        print("final-loss %.6f" % loss, flush=True)
    else:   # restarted at/after completion: clean no-op resume
        print("already-complete at step %d" % start, flush=True)
    print("final-digest %s" % state_digest(step.state_dict()), flush=True)


def _spawn(args, ckpt_dir):
    cmd = [sys.executable, os.path.abspath(__file__), "--worker",
           "--steps", str(args.steps), "--ckpt-dir", ckpt_dir,
           "--seed", str(args.seed), "--lr", str(args.lr),
           "--save-every", str(args.save_every),
           "--step-delay", str(args.step_delay)]
    return subprocess.Popen(cmd, stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT, text=True, bufsize=1)


def _drain(proc):
    out = []
    for line in proc.stdout:
        line = line.rstrip()
        out.append(line)
        print("  | " + line, flush=True)
    proc.wait()
    return out


def demo(args):
    with tempfile.TemporaryDirectory() as td:
        ckpt = os.path.join(td, "ckpt")

        # Phase 1: train, then kill mid-run once enough steps committed.
        print("phase-1: training (will be SIGTERMed)", flush=True)
        p1 = _spawn(args, ckpt)
        seen = -1
        for line in p1.stdout:
            line = line.rstrip()
            print("  | " + line, flush=True)
            if line.startswith("step "):
                seen = int(line.split()[1])
                if seen + 1 >= args.kill_after:
                    break
        assert seen >= 0, "worker produced no steps"
        p1.send_signal(signal.SIGTERM)
        _drain(p1)
        print("phase-1 exit code %d (expect 143 = clean preempt)"
              % p1.returncode, flush=True)

        # Phase 2: restart with the same dir → resumes and finishes.
        print("phase-2: resuming", flush=True)
        out2 = _drain(_spawn(args, ckpt))
        resumed = [l for l in out2 if l.startswith("resumed-from")]
        digest2 = [l for l in out2 if l.startswith("final-digest")]
        assert resumed, "phase-2 did not resume from a checkpoint"
        assert digest2, "phase-2 did not finish"

        # Reference: same run, never interrupted, fresh directory.
        print("reference: uninterrupted run", flush=True)
        ref = argparse.Namespace(**vars(args))
        ref.ckpt_dir = os.path.join(td, "ref")
        out3 = _drain(_spawn(ref, ref.ckpt_dir))
        digest3 = [l for l in out3 if l.startswith("final-digest")]
        assert digest3, "reference run did not finish"

        bitexact = digest2[0] == digest3[0]
        print("resumed-from-step %s" % resumed[0].split()[1], flush=True)
        print("bitexact %s" % bitexact, flush=True)
        if not bitexact:
            raise SystemExit("kill/resume diverged from uninterrupted run")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=18)
    ap.add_argument("--kill-after", type=int, default=6,
                    help="demo: SIGTERM the worker after this many steps")
    ap.add_argument("--save-every", type=int, default=1)
    ap.add_argument("--seed", type=int, default=7)
    ap.add_argument("--lr", type=float, default=0.2)
    ap.add_argument("--step-delay", type=float, default=0.0,
                    help="artificial per-step pause (keeps the demo's "
                         "kill window wide on fast machines)")
    ap.add_argument("--ckpt-dir", default=None)
    ap.add_argument("--worker", action="store_true")
    args = ap.parse_args()
    if args.worker:
        assert args.ckpt_dir, "--worker requires --ckpt-dir"
        worker(args)
    else:
        demo(args)


if __name__ == "__main__":
    main()
