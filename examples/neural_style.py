#!/usr/bin/env python
"""Neural style transfer: optimize an IMAGE against conv features.

Reference: example/neural-style — Gatys-style transfer: the trainable
object is the input image itself, driven by a content loss (feature
match at a deep layer) and a style loss (Gram-matrix match at several
layers). The API surface this driver exercises: optimizing a
non-parameter NDArray with autograd + an explicit optimizer op,
intermediate-feature extraction from a conv stack, and Gram-matrix
losses.

Zero-egress adaptation: no pretrained VGG weights exist in this image,
so the feature net is a small Xavier-initialized conv stack (random
conv features carry enough structure for the demo — the optimization
machinery is identical). Content/style images are synthetic patterns.

    python examples/neural_style.py --steps 60
"""
from __future__ import annotations

import argparse
import logging
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon

SIZE = 32


def feature_net():
    """Conv stack; features tapped after each stage."""
    stages = []
    for ch in (8, 16, 32):
        s = gluon.nn.HybridSequential()
        s.add(gluon.nn.Conv2D(ch, 3, padding=1, activation="relu"),
              gluon.nn.Conv2D(ch, 3, padding=1, activation="relu"),
              gluon.nn.AvgPool2D(2))
        stages.append(s)
    net = gluon.nn.HybridSequential()
    for s in stages:
        net.add(s)
    net.initialize(mx.init.Xavier(magnitude=2.5))
    return stages


def features(stages, x):
    outs = []
    h = x
    for s in stages:
        h = s(h)
        outs.append(h)
    return outs


def gram(f):
    """(N, C, H, W) -> (N, C, C) normalized Gram matrix."""
    n, c = f.shape[0], f.shape[1]
    flat = f.reshape((n, c, -1))
    return mx.nd.batch_dot(flat, flat.transpose((0, 2, 1))) / \
        float(flat.shape[2])


def content_image(rng):
    """A ring on a gradient background."""
    yy, xx = np.mgrid[0:SIZE, 0:SIZE].astype(np.float32)
    img = np.stack([xx / SIZE, yy / SIZE, (xx + yy) / (2 * SIZE)])
    r = np.sqrt((yy - SIZE / 2) ** 2 + (xx - SIZE / 2) ** 2)
    ring = np.exp(-((r - 9.0) ** 2) / 6.0)
    return (img * 0.5 + ring[None] * 0.5).astype(np.float32)


def style_image(rng):
    """Diagonal stripes."""
    yy, xx = np.mgrid[0:SIZE, 0:SIZE]
    stripes = (np.sin((xx + yy) * 0.8) * 0.5 + 0.5).astype(np.float32)
    return np.stack([stripes, 1 - stripes, stripes * 0.5])


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=120)
    ap.add_argument("--lr", type=float, default=0.2)
    ap.add_argument("--style-weight", type=float, default=1.0)
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args()

    logging.basicConfig(level=logging.INFO, format="%(message)s",
                        stream=sys.stdout, force=True)
    mx.util.pin_platform(os.environ.get("MXNET_DEVICE", "cpu"))
    mx.random.seed(args.seed)
    rng = np.random.RandomState(args.seed)

    stages = feature_net()
    content = mx.nd.array(content_image(rng)[None])
    style = mx.nd.array(style_image(rng)[None])

    with autograd.pause():
        raw = features(stages, content)
        # Per-stage normalization: random relu stacks attenuate ~20x
        # per stage; dividing by the content features' std puts every
        # stage's loss at O(1) (the reference relies on trained VGG
        # magnitudes instead).
        scales = [float(f.asnumpy().std()) + 1e-8 for f in raw]

    def norm_features(x):
        return [f / sc for f, sc in zip(features(stages, x), scales)]

    with autograd.pause():
        content_feat = norm_features(content)[-1]
        style_grams = [gram(f) for f in norm_features(style)]

    # The canvas IS the trainable variable (reference neural-style's
    # Executor backward to the data grad). Start from noise so both
    # losses are live.
    canvas = mx.nd.array(rng.rand(1, 3, SIZE, SIZE).astype(np.float32))
    canvas.attach_grad()
    mom = mx.nd.zeros(canvas.shape)

    first = last = None
    for step in range(args.steps):
        with autograd.record():
            feats = norm_features(canvas)
            c_loss = ((feats[-1] - content_feat) ** 2).mean()
            s_loss = sum(((gram(f) - g) ** 2).mean()
                         for f, g in zip(feats, style_grams))
            loss = c_loss + args.style_weight * s_loss
        loss.backward()
        mx.nd.sgd_mom_update(canvas, canvas.grad, mom, lr=args.lr,
                             momentum=0.9, out=(canvas, mom))
        canvas._set_data(canvas._data.clip(0.0, 1.0))
        cur = float(loss.asnumpy())
        if first is None:
            first = cur
        last = cur
        if step % 20 == 0 or step == args.steps - 1:
            logging.info("step %d  loss %.5f (content %.5f style %.5f)",
                         step, cur, float(c_loss.asnumpy()),
                         float(s_loss.asnumpy()))

    logging.info("total loss %.5f -> %.5f", first, last)
    if not (np.isfinite(last) and last < first * 0.7):
        raise SystemExit("style optimization did not converge")


if __name__ == "__main__":
    main()
