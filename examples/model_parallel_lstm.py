#!/usr/bin/env python
"""Model-parallel LSTM: layer groups placed on different devices.

Reference: example/model-parallel + docs/faq/model_parallel_lstm.md —
the reference splits a deep LSTM LM by layer across GPUs with
``group2ctx`` (symbol attrs `__ctx_group__` → AssignContext placement +
_CrossDeviceCopy at group boundaries, graph_executor.cc:907). Same API
here: AttrScope stamps the groups, `bind(group2ctx=...)` places each
layer's ops and parameters on its device, activations hop devices at
the boundary.

On a dev box the "devices" are virtual CPU devices; on a pod slice the
same script places layer groups on distinct chips. (The blessed
large-model path is sharded TrainStep — this driver covers the
reference's explicit-placement API.)

    python examples/model_parallel_lstm.py --steps 12
"""
from __future__ import annotations

import argparse
import logging
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import mxnet_tpu as mx


def build_lm(seq_len, vocab, embed, hidden, layers):
    """Unrolled multi-layer LSTM LM with each layer in its own ctx
    group (reference model_parallel_lstm.md's per-layer split)."""
    data = mx.sym.Variable("data")
    label = mx.sym.Variable("softmax_label")
    with mx.AttrScope(ctx_group="embed"):
        x = mx.sym.Embedding(data, input_dim=vocab, output_dim=embed,
                             name="embed")
    for layer in range(layers):
        with mx.AttrScope(ctx_group="layer%d" % layer):
            cell = mx.rnn.LSTMCell(hidden, prefix="lstm%d_" % layer)
            x, _ = cell.unroll(seq_len, x, layout="NTC",
                               merge_outputs=True)
    with mx.AttrScope(ctx_group="head"):
        pred = mx.sym.FullyConnected(
            mx.sym.reshape(x, shape=(-1, hidden)), num_hidden=vocab,
            name="pred")
        out = mx.sym.SoftmaxOutput(
            pred, mx.sym.reshape(label, shape=(-1,)), name="softmax")
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=60)
    ap.add_argument("--layers", type=int, default=2)
    ap.add_argument("--seq-len", type=int, default=4)
    ap.add_argument("--vocab", type=int, default=20)
    ap.add_argument("--hidden", type=int, default=16)
    ap.add_argument("--batch-size", type=int, default=8)
    ap.add_argument("--lr", type=float, default=0.3)
    ap.add_argument("--seed", type=int, default=11)
    args = ap.parse_args()

    logging.basicConfig(level=logging.INFO, format="%(message)s",
                        stream=sys.stdout, force=True)
    mx.util.pin_platform(os.environ.get("MXNET_DEVICE", "cpu"))
    mx.random.seed(args.seed)
    rng = np.random.RandomState(args.seed)

    sym = build_lm(args.seq_len, args.vocab, 16, args.hidden, args.layers)

    # One device per layer group, cycling over what the host has —
    # accelerator chips when present, virtual CPU devices otherwise.
    n_acc = mx.context.num_tpus()
    if n_acc > 1:
        dev_type, avail = "tpu", n_acc
    else:
        import jax

        dev_type, avail = "cpu", max(len(jax.devices()), 1)
    groups = ["embed"] + ["layer%d" % i for i in range(args.layers)] \
        + ["head"]
    group2ctx = {g: mx.Context(dev_type, i % avail)
                 for i, g in enumerate(groups)}
    logging.info("placement: %s", {g: str(c) for g, c in group2ctx.items()})

    arg_shapes, _, _ = sym.infer_shape(
        data=(args.batch_size, args.seq_len),
        softmax_label=(args.batch_size, args.seq_len))
    init = mx.init.Xavier()
    args_map, grads_map, moms_map = {}, {}, {}
    for name, shape in zip(sym.list_arguments(), arg_shapes):
        if name in ("data", "softmax_label"):
            args_map[name] = mx.nd.zeros(shape)
            continue
        arr = mx.nd.zeros(shape)
        init(mx.init.InitDesc(name), arr)
        args_map[name] = arr
        grads_map[name] = mx.nd.zeros(shape)
        moms_map[name] = mx.nd.zeros(shape)
    exe = sym.bind(mx.cpu(), args_map, args_grad=grads_map,
                   group2ctx=group2ctx)

    def sample_seqs():
        """Repeat-with-noise sequences (next token = current, 10%
        noise): a learnable language, unlike uniform noise."""
        start = rng.randint(1, args.vocab, (args.batch_size, 1))
        cols = [start]
        for _ in range(args.seq_len):
            noise = rng.rand(args.batch_size, 1) < 0.1
            nxt = np.where(noise, rng.randint(1, args.vocab,
                                              (args.batch_size, 1)),
                           cols[-1])
            cols.append(nxt)
        return np.concatenate(cols, axis=1)

    history = []
    for step in range(args.steps):
        seqs = sample_seqs()
        args_map["data"][:] = mx.nd.array(seqs[:, :-1].astype(np.float32))
        args_map["softmax_label"][:] = mx.nd.array(
            seqs[:, 1:].astype(np.float32))
        out = exe.forward(is_train=True)[0]
        exe.backward()
        for name, grad in grads_map.items():
            # SoftmaxOutput grads sum over batch*seq_len rows; momentum
            # + clipping keep the raw-SGD LM stable.
            mx.nd.sgd_mom_update(
                args_map[name], grad, moms_map[name],
                lr=args.lr / (args.batch_size * args.seq_len),
                momentum=0.9, clip_gradient=5.0,
                out=(args_map[name], moms_map[name]))
        p = out.asnumpy().reshape(args.batch_size, args.seq_len,
                                  args.vocab)
        idx = seqs[:, 1:].astype(int)
        nll = -np.log(np.maximum(
            np.take_along_axis(p, idx[..., None], axis=2), 1e-9)).mean()
        history.append(nll)
        if step % 5 == 0 or step == args.steps - 1:
            logging.info("step %d  nll %.4f  (ppl %.1f)", step, nll,
                         np.exp(nll))

    k = max(3, args.steps // 6)
    first = float(np.mean(history[:k]))
    last = float(np.mean(history[-k:]))
    logging.info("nll %.4f -> %.4f (first/last %d-step means)", first,
                 last, k)
    if not (np.isfinite(last) and last < first):
        raise SystemExit("model-parallel LSTM did not learn")


if __name__ == "__main__":
    main()
