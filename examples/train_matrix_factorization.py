#!/usr/bin/env python
"""Matrix factorization recommender: sparse embeddings + lazy optimizer.

Reference: example/recommenders (demo1-MF) — predict ratings as
<user_vec, item_vec> + biases, trained on (user, item, rating) triples.
The API surface this driver exercises: ``sparse_grad`` Embeddings
(row_sparse gradients touch only the rows in the batch) with the lazy
SGD/Adam update path (only touched rows get state updates — the
reference's lazy_update sparse optimizer contract, optimizer_op.cc).

Synthetic by default: a random low-rank ground-truth rating matrix with
noise. CI-sized run:

    python examples/train_matrix_factorization.py --epochs 3
"""
from __future__ import annotations

import argparse
import logging
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon


class MFNet(gluon.HybridBlock):
    """Biased matrix factorization (demo1-MF's model)."""

    def __init__(self, num_users, num_items, rank, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.user = gluon.nn.Embedding(num_users, rank,
                                           sparse_grad=True)
            self.item = gluon.nn.Embedding(num_items, rank,
                                           sparse_grad=True)
            self.user_b = gluon.nn.Embedding(num_users, 1,
                                             sparse_grad=True)
            self.item_b = gluon.nn.Embedding(num_items, 1,
                                             sparse_grad=True)

    def hybrid_forward(self, F, users, items):
        p = self.user(users)
        q = self.item(items)
        return ((p * q).sum(axis=1)
                + self.user_b(users).reshape((-1,))
                + self.item_b(items).reshape((-1,)))


def synthetic_ratings(rng, num_users, num_items, rank, n):
    u_true = rng.randn(num_users, rank) * 0.7
    i_true = rng.randn(num_items, rank) * 0.7
    users = rng.randint(0, num_users, n)
    items = rng.randint(0, num_items, n)
    ratings = (u_true[users] * i_true[items]).sum(1) + 3.0 \
        + 0.1 * rng.randn(n)
    return (users.astype(np.float32), items.astype(np.float32),
            ratings.astype(np.float32))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--num-users", type=int, default=200)
    ap.add_argument("--num-items", type=int, default=150)
    ap.add_argument("--rank", type=int, default=8)
    ap.add_argument("--samples", type=int, default=4096)
    ap.add_argument("--batch-size", type=int, default=256)
    ap.add_argument("--epochs", type=int, default=5)
    ap.add_argument("--lr", type=float, default=0.08)
    ap.add_argument("--optimizer", default="adam",
                    help="adam/sgd — both take the lazy sparse path")
    ap.add_argument("--seed", type=int, default=7)
    args = ap.parse_args()

    logging.basicConfig(level=logging.INFO, format="%(message)s",
                        stream=sys.stdout, force=True)
    mx.util.pin_platform(os.environ.get("MXNET_DEVICE", "cpu"))
    mx.random.seed(args.seed)
    rng = np.random.RandomState(args.seed)

    users, items, ratings = synthetic_ratings(
        rng, args.num_users, args.num_items, args.rank, args.samples)
    n_train = int(args.samples * 0.9)

    net = MFNet(args.num_users, args.num_items, args.rank)
    net.initialize(mx.init.Normal(0.1))
    trainer = gluon.Trainer(net.collect_params(), args.optimizer,
                            {"learning_rate": args.lr})
    loss_fn = gluon.loss.L2Loss()
    bs = args.batch_size

    def rmse(lo, hi):
        pred = net(mx.nd.array(users[lo:hi]),
                   mx.nd.array(items[lo:hi])).asnumpy()
        return float(np.sqrt(np.mean((pred - ratings[lo:hi]) ** 2)))

    first = last = None
    for epoch in range(args.epochs):
        perm = rng.permutation(n_train)
        total = 0.0
        for off in range(0, n_train - bs + 1, bs):
            sel = perm[off:off + bs]
            u = mx.nd.array(users[sel])
            i = mx.nd.array(items[sel])
            r = mx.nd.array(ratings[sel])
            with autograd.record():
                loss = loss_fn(net(u, i), r).sum()
            loss.backward()
            # row_sparse grads: only this batch's embedding rows move
            trainer.step(bs)
            total += float(loss.asnumpy())
        val = rmse(n_train, args.samples)
        if first is None:
            first = val
        last = val
        logging.info("epoch %d  train_loss %.4f  val_rmse %.4f", epoch,
                     total / n_train, val)

    logging.info("val RMSE %.4f -> %.4f", first, last)
    if not (last < first):
        raise SystemExit("matrix factorization failed to improve RMSE")


if __name__ == "__main__":
    main()
