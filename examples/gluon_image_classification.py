#!/usr/bin/env python
"""Gluon imperative/hybrid image-classification driver.

Reference: example/gluon/image_classification.py — the canonical Gluon
training loop: model_zoo network, DataLoader batches, Trainer with
sgd momentum, autograd.record/backward per batch, accuracy metric, with
``--mode hybrid`` flipping the same code to compiled execution.

TPU rebuild: ``--mode hybrid`` makes the whole forward one cached XLA
executable via ``net.hybridize()`` (the CachedOp seam); imperative mode
runs per-op dispatch. With no dataset on disk (zero egress) the driver
builds a synthetic CIFAR-shaped set whose classes are separable color
patterns, so both modes train end-to-end anywhere.
"""
from __future__ import annotations

import argparse
import logging
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon


def synthetic_cifar(n, num_classes, rng, size=32):
    """Class = which third of the image carries the bright band."""
    X = (rng.rand(n, 3, size, size) * 0.3).astype(np.float32)
    y = rng.randint(0, num_classes, n)
    band = size // num_classes
    if band < 1:
        raise ValueError(
            "num_classes=%d exceeds image size %d: the class-identifying "
            "band would be empty (unlearnable noise)" % (num_classes, size))
    for i in range(n):
        c = y[i]
        X[i, c % 3, c * band:(c + 1) * band, :] += 1.0
    return X, y.astype(np.float32)


def main():
    parser = argparse.ArgumentParser(
        description="Gluon image classification "
        "(reference example/gluon/image_classification.py)",
        formatter_class=argparse.ArgumentDefaultsHelpFormatter)
    parser.add_argument("--model", default="resnet18_v1")
    parser.add_argument("--num-classes", type=int, default=4)
    parser.add_argument("--num-examples", type=int, default=512)
    parser.add_argument("--batch-size", type=int, default=32)
    parser.add_argument("--epochs", type=int, default=5)
    parser.add_argument("--lr", type=float, default=0.05)
    parser.add_argument("--momentum", type=float, default=0.9)
    parser.add_argument("--wd", type=float, default=1e-4)
    parser.add_argument("--mode", default="hybrid",
                        choices=["imperative", "hybrid"])
    parser.add_argument("--num-workers", "-j", type=int, default=0)
    parser.add_argument("--seed", type=int, default=123)
    parser.add_argument("--device", default=os.environ.get(
        "MXNET_DEVICE", "auto"), choices=["auto", "cpu", "tpu"])
    args = parser.parse_args()
    mx.util.pin_platform(args.device)
    logging.basicConfig(level=logging.INFO)
    mx.random.seed(args.seed)
    rng = np.random.RandomState(args.seed)

    from mxnet_tpu.gluon.model_zoo import vision

    net = getattr(vision, args.model)(classes=args.num_classes)
    net.initialize(mx.init.Xavier(magnitude=2.0))
    if args.mode == "hybrid":
        net.hybridize()

    X, y = synthetic_cifar(args.num_examples, args.num_classes, rng)
    cut = int(len(X) * 0.9)
    train_ds = gluon.data.ArrayDataset(mx.nd.array(X[:cut]),
                                       mx.nd.array(y[:cut]))
    val_ds = gluon.data.ArrayDataset(mx.nd.array(X[cut:]),
                                     mx.nd.array(y[cut:]))
    train_dl = gluon.data.DataLoader(train_ds, args.batch_size,
                                     shuffle=True, last_batch="discard",
                                     num_workers=args.num_workers)
    val_dl = gluon.data.DataLoader(val_ds, args.batch_size)

    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": args.lr,
                             "momentum": args.momentum, "wd": args.wd})
    ce = gluon.loss.SoftmaxCrossEntropyLoss()
    metric = mx.metric.Accuracy()

    for epoch in range(args.epochs):
        metric.reset()
        t0 = time.perf_counter()
        seen = 0
        for xb, yb in train_dl:
            with autograd.record():
                out = net(xb)
                loss = ce(out, yb)
            loss.backward()
            trainer.step(xb.shape[0])
            metric.update([yb], [out])
            seen += xb.shape[0]
        name, acc = metric.get()
        logging.info("epoch %d: train-%s %.4f (%.1f img/s)", epoch, name,
                     acc, seen / (time.perf_counter() - t0))

    metric.reset()
    for xb, yb in val_dl:
        metric.update([yb], [net(xb)])
    _, vacc = metric.get()
    logging.info("validation accuracy: %.4f", vacc)
    print("final-accuracy %.4f" % vacc)
    return vacc


if __name__ == "__main__":
    main()
