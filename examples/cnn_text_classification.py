#!/usr/bin/env python
"""CNN text classification (Kim 2014 style): multi-width Conv1D filters
over token embeddings with max-over-time pooling.

Reference: example/cnn_text_classification — the API surface this
driver exercises: `Conv1D` with several kernel widths over an embedded
token sequence (NCW layout), global max pooling per filter bank,
concatenation, dropout, and a softmax head.

Synthetic language: class 0 sentences contain at least one of the
"positive" bigram patterns, class 1 at least one "negative" bigram —
exactly the local-pattern structure the windowed filters exist to
detect.

    python examples/cnn_text_classification.py --epochs 4
"""
from __future__ import annotations

import argparse
import logging
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon

SEQ = 16
VOCAB = 40
POS_BIGRAMS = [(3, 7), (11, 5), (20, 21)]
NEG_BIGRAMS = [(4, 9), (15, 2), (22, 30)]


class KimCNN(gluon.HybridBlock):
    def __init__(self, widths=(2, 3, 4), filters=16, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.embed = gluon.nn.Embedding(VOCAB, 24)
            self.convs = gluon.nn.HybridSequential()
            for w in widths:
                self.convs.add(gluon.nn.Conv1D(filters, w,
                                               activation="relu"))
            self.drop = gluon.nn.Dropout(0.3)
            self.out = gluon.nn.Dense(2)

    def hybrid_forward(self, F, tokens):
        e = self.embed(tokens).transpose((0, 2, 1))   # (N, emb, T) NCW
        pooled = [c(e).max(axis=2) for c in self.convs]
        return self.out(self.drop(F.concat(*pooled, dim=1)))


def make_data(rng, n):
    toks = rng.randint(0, VOCAB, (n, SEQ))
    labels = rng.randint(0, 2, n)
    for i, lab in enumerate(labels):
        a, b = (POS_BIGRAMS if lab == 0 else NEG_BIGRAMS)[rng.randint(3)]
        pos = rng.randint(0, SEQ - 1)
        toks[i, pos], toks[i, pos + 1] = a, b
    return toks.astype(np.float32), labels.astype(np.float32)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=5)
    ap.add_argument("--train", type=int, default=2048)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--lr", type=float, default=2e-3)
    ap.add_argument("--seed", type=int, default=8)
    args = ap.parse_args()

    logging.basicConfig(level=logging.INFO, format="%(message)s",
                        stream=sys.stdout, force=True)
    mx.util.pin_platform(os.environ.get("MXNET_DEVICE", "cpu"))
    mx.random.seed(args.seed)
    rng = np.random.RandomState(args.seed)

    X, Y = make_data(rng, args.train)
    Xv, Yv = make_data(rng, 512)

    net = KimCNN()
    net.initialize(mx.init.Xavier())
    net.hybridize()
    tr = gluon.Trainer(net.collect_params(), "adam",
                       {"learning_rate": args.lr})
    ce = gluon.loss.SoftmaxCrossEntropyLoss()
    bs = min(args.batch_size, args.train)

    acc = 0.0
    for epoch in range(args.epochs):
        perm = rng.permutation(args.train)
        tot = 0.0
        n_seen = 0
        for off in range(0, args.train - bs + 1, bs):
            sel = perm[off:off + bs]
            with autograd.record():
                loss = ce(net(mx.nd.array(X[sel])),
                          mx.nd.array(Y[sel])).sum()
            loss.backward()
            tr.step(bs)
            tot += float(loss.asnumpy())
            n_seen += bs
        with autograd.pause(train_mode=False):
            acc = float((net(mx.nd.array(Xv)).asnumpy().argmax(1)
                         == Yv).mean())
        logging.info("epoch %d  loss %.4f  val-acc %.3f", epoch,
                     tot / n_seen, acc)

    if acc < 0.85:
        raise SystemExit("text CNN failed to find the bigram patterns "
                         "(val-acc %.3f)" % acc)


if __name__ == "__main__":
    main()
