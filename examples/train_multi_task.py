#!/usr/bin/env python
"""Multi-task learning: one trunk, two heads, two losses.

Reference: example/multi-task (MNIST digit + odd/even heads sharing a
trunk). The API surface this driver exercises: a shared HybridBlock
trunk feeding two task heads, joint backward over a weighted sum of a
classification and a regression loss, per-task metrics.

Synthetic task: each image contains one bright 3×3 blob; task A
classifies which quadrant holds it (4 classes), task B regresses its
x-position.

    python examples/train_multi_task.py --epochs 4
"""
from __future__ import annotations

import argparse
import logging
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon


class MultiTaskNet(gluon.HybridBlock):
    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.trunk = gluon.nn.HybridSequential()
            self.trunk.add(gluon.nn.Conv2D(8, 3, padding=1,
                                           activation="relu"),
                           gluon.nn.MaxPool2D(2),
                           gluon.nn.Flatten(),
                           gluon.nn.Dense(32, activation="relu"))
            self.cls_head = gluon.nn.Dense(4)
            self.reg_head = gluon.nn.Dense(1)

    def hybrid_forward(self, F, x):
        z = self.trunk(x)
        return self.cls_head(z), self.reg_head(z)


def make_data(rng, n):
    imgs = rng.rand(n, 1, 12, 12).astype(np.float32) * 0.2
    quad = np.zeros(n, np.float32)
    xpos = np.zeros(n, np.float32)
    for i in range(n):
        y = rng.randint(0, 10)
        x = rng.randint(0, 10)
        imgs[i, 0, y:y + 3, x:x + 3] = 1.0
        quad[i] = (1 if x >= 5 else 0) + (2 if y >= 5 else 0)
        xpos[i] = x / 9.0
    return imgs, quad, xpos


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=5)
    ap.add_argument("--train", type=int, default=1024)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--lr", type=float, default=2e-3)
    ap.add_argument("--task-weight", type=float, default=0.5,
                    help="weight of the regression loss")
    ap.add_argument("--seed", type=int, default=2)
    args = ap.parse_args()

    logging.basicConfig(level=logging.INFO, format="%(message)s",
                        stream=sys.stdout, force=True)
    mx.util.pin_platform(os.environ.get("MXNET_DEVICE", "cpu"))
    mx.random.seed(args.seed)
    rng = np.random.RandomState(args.seed)

    X, Yc, Yr = make_data(rng, args.train)
    Xv, Ycv, Yrv = make_data(rng, 256)

    net = MultiTaskNet()
    net.initialize(mx.init.Xavier())
    net.hybridize()
    tr = gluon.Trainer(net.collect_params(), "adam",
                       {"learning_rate": args.lr})
    ce = gluon.loss.SoftmaxCrossEntropyLoss()
    l2 = gluon.loss.L2Loss()
    bs = args.batch_size
    acc, mae = 0.0, float("inf")

    for epoch in range(args.epochs):
        perm = rng.permutation(args.train)
        tot = 0.0
        for off in range(0, args.train - bs + 1, bs):
            sel = perm[off:off + bs]
            with autograd.record():
                logits, reg = net(mx.nd.array(X[sel]))
                loss = (ce(logits, mx.nd.array(Yc[sel])).sum()
                        + args.task_weight
                        * l2(reg, mx.nd.array(Yr[sel][:, None])).sum())
            loss.backward()
            tr.step(bs)
            tot += float(loss.asnumpy())
        logits, reg = net(mx.nd.array(Xv))
        acc = float((logits.asnumpy().argmax(1) == Ycv).mean())
        mae = float(np.abs(reg.asnumpy()[:, 0] - Yrv).mean())
        n_seen = (args.train // bs) * bs
        logging.info("epoch %d  loss %.4f  quad-acc %.3f  xpos-mae %.4f",
                     epoch, tot / n_seen, acc, mae)

    if acc < 0.8 or mae > 0.15:
        raise SystemExit("multi-task heads failed to learn "
                         "(acc %.3f, mae %.4f)" % (acc, mae))


if __name__ == "__main__":
    main()
