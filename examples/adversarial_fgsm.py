#!/usr/bin/env python
"""FGSM adversarial examples: gradients with respect to the INPUT.

Reference: example/adversary (fast-sign-gradient notebook) — train a
small classifier, then perturb test inputs by
``eps * sign(dLoss/dInput)`` and watch accuracy collapse. The API
surface this driver exercises is input-gradient plumbing:
``x.attach_grad()`` + ``loss.backward()`` filling a non-parameter
leaf's ``.grad``.

Synthetic two-class "images" (blob position decides the class).

    python examples/adversarial_fgsm.py --epochs 3
"""
from __future__ import annotations

import argparse
import logging
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon


def make_data(rng, n):
    """Class 0: bright blob in the left half; class 1: right half."""
    imgs = rng.rand(n, 1, 12, 12).astype(np.float32) * 0.3
    labels = rng.randint(0, 2, n)
    for i, lab in enumerate(labels):
        col = rng.randint(0, 4) if lab == 0 else rng.randint(8, 12) - 2
        row = rng.randint(0, 10)
        imgs[i, 0, row:row + 3, col:col + 3] += 0.7
    return imgs, labels.astype(np.float32)


def accuracy(net, X, Y):
    pred = net(mx.nd.array(X)).asnumpy().argmax(1)
    return float((pred == Y).mean())


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=4)
    ap.add_argument("--train", type=int, default=512)
    ap.add_argument("--test", type=int, default=256)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--eps", type=float, default=0.25)
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args()

    logging.basicConfig(level=logging.INFO, format="%(message)s",
                        stream=sys.stdout, force=True)
    mx.util.pin_platform(os.environ.get("MXNET_DEVICE", "cpu"))
    mx.random.seed(args.seed)
    rng = np.random.RandomState(args.seed)

    Xtr, Ytr = make_data(rng, args.train)
    Xte, Yte = make_data(rng, args.test)

    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Conv2D(8, 3, padding=1, activation="relu"),
            gluon.nn.MaxPool2D(2),
            gluon.nn.Flatten(),
            gluon.nn.Dense(2))
    net.initialize(mx.init.Xavier())
    tr = gluon.Trainer(net.collect_params(), "adam",
                       {"learning_rate": 5e-3})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    bs = args.batch_size

    for epoch in range(args.epochs):
        perm = rng.permutation(args.train)
        total = 0.0
        for off in range(0, args.train - bs + 1, bs):
            sel = perm[off:off + bs]
            with autograd.record():
                loss = loss_fn(net(mx.nd.array(Xtr[sel])),
                               mx.nd.array(Ytr[sel])).sum()
            loss.backward()
            tr.step(bs)
            total += float(loss.asnumpy())
        logging.info("epoch %d  loss %.4f", epoch, total / args.train)

    clean_acc = accuracy(net, Xte, Yte)

    # FGSM: one gradient step ON THE INPUT, in the loss-ascending
    # direction (reference adversary notebook's fast sign method).
    x = mx.nd.array(Xte)
    x.attach_grad()
    with autograd.record():
        loss = loss_fn(net(x), mx.nd.array(Yte)).sum()
    loss.backward()
    x_adv = (x + args.eps * mx.nd.sign(x.grad)).clip(0.0, 1.0)
    adv_acc = accuracy(net, x_adv.asnumpy(), Yte)

    logging.info("clean accuracy %.3f  adversarial accuracy %.3f "
                 "(eps=%.2f)", clean_acc, adv_acc, args.eps)
    if clean_acc < 0.85:
        raise SystemExit("classifier failed to train (%.3f)" % clean_acc)
    if adv_acc > clean_acc - 0.1:
        raise SystemExit("FGSM perturbation had no effect "
                         "(%.3f vs %.3f)" % (adv_acc, clean_acc))


if __name__ == "__main__":
    main()
