#!/usr/bin/env python
"""Train an ImageNet-class CNN — the reference's headline driver.

Reference: example/image-classification/train_imagenet.py + common/fit.py
(perf.md's training numbers are measured through this script with
--benchmark 1, which feeds synthetic data so the result is compute-bound).

TPU rebuild: the hot path is `mxnet_tpu.parallel.TrainStep` — forward +
loss + backward + SGD fused into ONE XLA executable (the reference's
bulked GraphExecutor + kvstore update, as a single compiled program).
``--benchmark 1`` reproduces the reference protocol (synthetic data,
img/s printed per batch window); bench.py imports `build_train_step` /
`benchmark_rate` from here, so the recorded benchmark IS this driver.
Without --benchmark, feeds ImageRecordIter batches from --data-train
(.rec) through the same step.
"""
from __future__ import annotations

import argparse
import logging
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def build_net(network, num_classes):
    from mxnet_tpu.gluon.model_zoo import vision

    factory = {
        "resnet18": vision.resnet18_v1, "resnet34": vision.resnet34_v1,
        "resnet50": vision.resnet50_v1, "resnet101": vision.resnet101_v1,
        "alexnet": vision.alexnet, "vgg16": vision.vgg16,
        "inception-v3": vision.inception_v3,
        "mobilenet": vision.mobilenet1_0,
    }[network]
    net = factory(classes=num_classes)
    net.initialize()
    return net


def build_train_step(network="resnet50", num_classes=1000, dtype=None,
                     device=None, lr=0.1, momentum=0.9, wd=1e-4):
    """The compiled training step bench.py measures."""
    import jax

    from mxnet_tpu import gluon
    from mxnet_tpu.parallel import TrainStep, make_mesh

    net = build_net(network, num_classes)
    device = device if device is not None else jax.devices()[0]
    return TrainStep(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                     optimizer="sgd",
                     optimizer_params={"learning_rate": lr,
                                       "momentum": momentum, "wd": wd},
                     mesh=make_mesh({"dp": 1}, devices=[device]),
                     dtype=dtype)


def benchmark_rate(network="resnet50", batch=32, dtype=None, device=None,
                   image_shape=(3, 224, 224), iters=10, windows=5,
                   warmup=3, num_classes=1000, lr=0.1, momentum=0.9,
                   wd=1e-4):
    """img/s, median over windows; each window closed by a host readback
    (see bench.py measurement discipline)."""
    import jax
    import jax.numpy as jnp

    step = build_train_step(network, num_classes, dtype, device,
                            lr=lr, momentum=momentum, wd=wd)
    rng = np.random.RandomState(0)
    x = rng.rand(batch, *image_shape).astype(np.float32)
    y = rng.randint(0, num_classes, batch).astype(np.float32)
    step(x, y)                                   # materialize + compile
    x = jax.device_put(jnp.asarray(x), step._data_sharding)
    y = jax.device_put(jnp.asarray(y), step._data_sharding)
    for _ in range(warmup):
        loss = step(x, y)
    if warmup:
        float(loss)                              # drain the warmup chain
    rates = []
    for _ in range(windows):
        t0 = time.perf_counter()
        for _ in range(iters):
            loss = step(x, y)
        float(loss)                              # completion proof
        rates.append(batch * iters / (time.perf_counter() - t0))
    return sorted(rates)[len(rates) // 2]


def main():
    parser = argparse.ArgumentParser(description="train imagenet",
        formatter_class=argparse.ArgumentDefaultsHelpFormatter)
    parser.add_argument("--network", default="resnet50")
    parser.add_argument("--device", default=os.environ.get(
        "MXNET_DEVICE", "auto"), choices=["auto", "cpu", "tpu"],
        help="'cpu' pins the cpu backend in-process")
    parser.add_argument("--num-classes", type=int, default=1000)
    parser.add_argument("--batch-size", type=int, default=32)
    parser.add_argument("--image-shape", default="3,224,224")
    parser.add_argument("--num-epochs", type=int, default=1)
    parser.add_argument("--lr", type=float, default=0.1)
    parser.add_argument("--mom", type=float, default=0.9)
    parser.add_argument("--wd", type=float, default=1e-4)
    parser.add_argument("--dtype", default="float32",
                        choices=["float32", "bfloat16"])
    parser.add_argument("--kv-store", default="device")
    parser.add_argument("--benchmark", type=int, default=0,
                        help="1: synthetic data, print img/s (the "
                        "reference's measurement mode)")
    parser.add_argument("--max-batches", type=int, default=0,
                        help="stop an epoch early (0 = full epoch)")
    parser.add_argument("--data-train", default=None,
                        help=".rec file for real training data")
    args = parser.parse_args()
    from mxnet_tpu.util import pin_platform

    pin_platform(args.device)
    logging.basicConfig(level=logging.INFO)
    shape = tuple(int(v) for v in args.image_shape.split(","))
    dtype = None if args.dtype == "float32" else args.dtype

    if args.benchmark:
        rate = benchmark_rate(args.network, args.batch_size, dtype,
                              image_shape=shape,
                              num_classes=args.num_classes, lr=args.lr,
                              momentum=args.mom, wd=args.wd)
        print("benchmark: %s b%d %s: %.2f img/s"
              % (args.network, args.batch_size, args.dtype, rate))
        return rate

    import mxnet_tpu as mx

    step = build_train_step(args.network, args.num_classes, dtype,
                            lr=args.lr, momentum=args.mom, wd=args.wd)
    if args.data_train:
        idx_path = os.path.splitext(args.data_train)[0] + ".idx"
        if not os.path.exists(idx_path):
            logging.warning("no %s: shuffle is a no-op without the index "
                            "(regenerate with tools/im2rec.py)", idx_path)
        it = mx.io.ImageRecordIter(
            path_imgrec=args.data_train,
            path_imgidx=idx_path if os.path.exists(idx_path) else None,
            batch_size=args.batch_size, data_shape=shape, shuffle=True)
    else:
        raise SystemExit("provide --data-train <file.rec> or --benchmark 1")
    loss = None
    for epoch in range(args.num_epochs):
        it.reset()
        t0 = time.perf_counter()
        n = 0
        for i, batch in enumerate(it):
            loss = step(batch.data[0], batch.label[0])
            n += args.batch_size
            if args.max_batches and i + 1 >= args.max_batches:
                break
        if loss is None:
            raise SystemExit("no batches in %s (batch size %d too large?)"
                             % (args.data_train, args.batch_size))
        logging.info("epoch %d: loss %.4f, %.1f img/s", epoch,
                     float(loss), n / (time.perf_counter() - t0))
    step.sync_to_net()
    return float(loss)


if __name__ == "__main__":
    main()
