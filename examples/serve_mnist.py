#!/usr/bin/env python
"""Serve an MNIST MLP with the shape-bucketed batching inference server.

The deployment lifecycle end to end (reference: Module
``bind(for_training=False)`` + save/load_checkpoint, c_predict_api):
train (or random-init) an MLP, ``save_checkpoint`` it, load the artifact
into ``serving.InferenceServer`` — which precompiles one frozen eval
executable per batch bucket at warmup — then fire concurrent
single-image requests from a thread pool. The dynamic batcher coalesces
them into bucket-sized device calls; the driver prints throughput,
per-bucket occupancy, and p50/p99 latency, plus a deadline-shedding
demonstration.

With an existing artifact: ``serve_mnist.py --checkpoint prefix --epoch N``.
Without one, a synthetic-MNIST checkpoint is created inline.
"""
from __future__ import annotations

import argparse
import logging
import os
import sys
import tempfile
import time
from concurrent.futures import ThreadPoolExecutor

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import serving


def build_mlp():
    """(reference train_mnist.py:get_mlp, narrowed for serving demo)."""
    data = mx.sym.var("data")
    net = mx.sym.FullyConnected(data, num_hidden=128, name="fc1")
    net = mx.sym.Activation(net, act_type="relu", name="relu1")
    net = mx.sym.FullyConnected(net, num_hidden=10, name="fc2")
    return mx.sym.SoftmaxOutput(net, name="softmax")


def synthetic_digits(n, seed=42):
    """MNIST-shaped synthetic digits (train_mnist.py:synthetic_iters):
    class = row-band position, flattened to 784."""
    rng = np.random.RandomState(seed)
    X = (rng.rand(n, 28, 28) * 0.25).astype(np.float32)
    y = rng.randint(0, 10, n)
    for i in range(n):
        r = y[i] * 2 + 4
        X[i, r:r + 3, 6:22] += 1.0
    return X.reshape(n, 784), y


def make_checkpoint(args, Xtr, ytr, prefix):
    """Produce the serving artifact: fit (or just init) + save_checkpoint."""
    mod = mx.mod.Module(build_mlp(), label_names=["softmax_label"])
    if args.train_epochs > 0:
        train = mx.io.NDArrayIter(Xtr, ytr.astype(np.float32),
                                  batch_size=args.batch_size, shuffle=True,
                                  label_name="softmax_label")
        mod.fit(train, num_epoch=args.train_epochs, optimizer="sgd",
                optimizer_params={"learning_rate": args.lr},
                initializer=mx.init.Xavier(magnitude=2.0))
    else:
        mod.bind(data_shapes=[("data", (args.batch_size, 784))],
                 label_shapes=[("softmax_label", (args.batch_size,))])
        mod.init_params(mx.init.Xavier(magnitude=2.0))
    mod.save_checkpoint(prefix, args.train_epochs)
    return args.train_epochs


def main():
    parser = argparse.ArgumentParser(description="serve mnist")
    parser.add_argument("--device", default=os.environ.get(
        "MXNET_DEVICE", "auto"), choices=["auto", "cpu", "tpu"])
    parser.add_argument("--checkpoint", default=None,
                        help="existing save_checkpoint prefix to serve")
    parser.add_argument("--epoch", type=int, default=0)
    parser.add_argument("--train-epochs", type=int, default=2,
                        help="0 = random-init checkpoint (lifecycle only)")
    parser.add_argument("--num-examples", type=int, default=1500)
    parser.add_argument("--batch-size", type=int, default=64)
    parser.add_argument("--lr", type=float, default=0.5)
    parser.add_argument("--requests", type=int, default=256,
                        help="concurrent single-image requests to fire")
    parser.add_argument("--concurrency", type=int, default=16)
    parser.add_argument("--max-batch", type=int, default=32)
    parser.add_argument("--max-delay-ms", type=float, default=5.0)
    parser.add_argument("--max-queue", type=int, default=512)
    args = parser.parse_args()
    mx.util.pin_platform(args.device)
    logging.basicConfig(level=logging.INFO)

    X, y = synthetic_digits(args.num_examples)
    cut = int(len(X) * 0.9)
    Xte, yte = X[cut:], y[cut:]

    tmp = None
    if args.checkpoint:
        prefix, epoch = args.checkpoint, args.epoch
    else:
        tmp = tempfile.TemporaryDirectory()
        prefix = os.path.join(tmp.name, "mnist_mlp")
        epoch = make_checkpoint(args, X[:cut], y[:cut], prefix)

    t0 = time.perf_counter()
    srv = serving.InferenceServer.from_checkpoint(
        prefix, epoch, item_shape=(784,), max_batch=args.max_batch,
        max_delay_ms=args.max_delay_ms, max_queue=args.max_queue)
    print("warmup: buckets %s -> %d executables in %.2f s"
          % (list(srv.policy.buckets), srv.compile_count,
             time.perf_counter() - t0))

    # concurrent load: each request is ONE image; the batcher coalesces.
    reqs = [Xte[i % len(Xte)][None, :] for i in range(args.requests)]
    t0 = time.perf_counter()
    with ThreadPoolExecutor(args.concurrency) as pool:
        futs = list(pool.map(srv.submit, reqs))
    preds = [int(np.argmax(f.result().asnumpy())) for f in futs]
    dt = time.perf_counter() - t0
    acc = float(np.mean([p == yte[i % len(Xte)]
                         for i, p in enumerate(preds)]))

    # deadline shedding demo: a paused server expires a 1 ms request.
    srv.pause()
    doomed = srv.submit(Xte[:1], timeout_ms=1)
    time.sleep(0.02)
    srv.resume()
    try:
        doomed.result(timeout=5)
    except serving.DeadlineExceededError:
        pass

    stats = srv.stats()
    for bucket, st in sorted(stats["buckets"].items()):
        print("bucket %-3d: %3d batches, %4d requests, occupancy %.2f, "
              "p50 %.2f ms, p99 %.2f ms"
              % (bucket, st["batches"], st["requests"],
                 st["mean_occupancy"], st["p50_ms"], st["p99_ms"]))
    print("shed:", stats["shed"])
    p99 = max(st["p99_ms"] for st in stats["buckets"].values())
    srv.shutdown()
    if tmp is not None:
        tmp.cleanup()
    print("served-accuracy %.4f" % acc)
    print("serving-throughput %.1f req/s  p99-ms %.2f"
          % (args.requests / dt, p99))


if __name__ == "__main__":
    main()
