#!/usr/bin/env python
"""Train an MLP or LeNet on MNIST with the Module API.

Reference: example/image-classification/train_mnist.py (+ common/fit.py)
— the canonical symbolic training driver: build symbol, create kvstore,
Module.fit with metric/speedometer callbacks. Runs distributed with
``tools/launch.py -n N python examples/train_mnist.py --kv-store
dist_sync`` exactly like the reference.

With ``--synthetic`` the driver generates an MNIST-shaped synthetic
classification set (zero-egress environments have no dataset downloads).
"""
from __future__ import annotations

import argparse
import logging
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import mxnet_tpu as mx


def get_mlp():
    """(reference train_mnist.py:get_mlp)."""
    data = mx.sym.var("data")
    net = mx.sym.FullyConnected(data, num_hidden=128, name="fc1")
    net = mx.sym.Activation(net, act_type="relu", name="relu1")
    net = mx.sym.FullyConnected(net, num_hidden=64, name="fc2")
    net = mx.sym.Activation(net, act_type="relu", name="relu2")
    net = mx.sym.FullyConnected(net, num_hidden=10, name="fc3")
    return mx.sym.SoftmaxOutput(net, name="softmax")


def get_lenet():
    """(reference train_mnist.py:get_lenet)."""
    data = mx.sym.var("data")
    c1 = mx.sym.Convolution(data, kernel=(5, 5), num_filter=20)
    a1 = mx.sym.Activation(c1, act_type="tanh")
    p1 = mx.sym.Pooling(a1, pool_type="max", kernel=(2, 2), stride=(2, 2))
    c2 = mx.sym.Convolution(p1, kernel=(5, 5), num_filter=50)
    a2 = mx.sym.Activation(c2, act_type="tanh")
    p2 = mx.sym.Pooling(a2, pool_type="max", kernel=(2, 2), stride=(2, 2))
    f1 = mx.sym.FullyConnected(p2, num_hidden=500)
    a3 = mx.sym.Activation(f1, act_type="tanh")
    f2 = mx.sym.FullyConnected(a3, num_hidden=10)
    return mx.sym.SoftmaxOutput(f2, name="softmax")


def synthetic_iters(args, flat, rank=0, num_workers=1):
    """MNIST-shaped synthetic digits: class = argmax row-band energy.
    Sharded across dist workers (reference drivers pass num_parts/
    part_index so each worker sees its own slice)."""
    rng = np.random.RandomState(42)
    n = args.num_examples
    X = (rng.rand(n, 1, 28, 28) * 0.25).astype(np.float32)
    y = rng.randint(0, 10, n)
    for i in range(n):
        r = y[i] * 2 + 4
        X[i, 0, r:r + 3, 6:22] += 1.0
    if flat:
        X = X.reshape(n, 784)
    cut = int(n * 0.9)
    Xt, yt = X[:cut], y[:cut].astype(np.float32)
    if num_workers > 1:
        part = len(Xt) // num_workers
        Xt = Xt[rank * part:(rank + 1) * part]
        yt = yt[rank * part:(rank + 1) * part]
    train = mx.io.NDArrayIter(Xt, yt,
                              batch_size=args.batch_size, shuffle=True,
                              label_name="softmax_label")
    val = mx.io.NDArrayIter(X[cut:], y[cut:].astype(np.float32),
                            batch_size=args.batch_size,
                            label_name="softmax_label")
    return train, val


def mnist_iters(args, flat, rank=0, num_workers=1):
    prefix = args.data_dir
    train = mx.io.MNISTIter(
        image=os.path.join(prefix, "train-images-idx3-ubyte"),
        label=os.path.join(prefix, "train-labels-idx1-ubyte"),
        batch_size=args.batch_size, shuffle=True, flat=flat,
        num_parts=num_workers, part_index=rank)
    val = mx.io.MNISTIter(
        image=os.path.join(prefix, "t10k-images-idx3-ubyte"),
        label=os.path.join(prefix, "t10k-labels-idx1-ubyte"),
        batch_size=args.batch_size, shuffle=False, flat=flat)
    return train, val


def main():
    parser = argparse.ArgumentParser(description="train mnist")
    parser.add_argument("--network", default="mlp",
                        choices=["mlp", "lenet"])
    parser.add_argument("--device", default=os.environ.get(
        "MXNET_DEVICE", "auto"), choices=["auto", "cpu", "tpu"],
        help="'cpu' pins the cpu backend in-process (reliable even "
        "where the TPU plugin overrides JAX_PLATFORMS)")
    parser.add_argument("--batch-size", type=int, default=64)
    parser.add_argument("--lr", type=float, default=0.05)
    parser.add_argument("--num-epochs", type=int, default=5)
    parser.add_argument("--kv-store", default="local")
    parser.add_argument("--optimizer", default="sgd")
    parser.add_argument("--gpus", default=None,
                        help="e.g. '0' — maps to TPU chips")
    parser.add_argument("--synthetic", action="store_true")
    parser.add_argument("--num-examples", type=int, default=5000)
    parser.add_argument("--data-dir", default="data")
    parser.add_argument("--disp-batches", type=int, default=50)
    parser.add_argument("--model-prefix", default=None)
    args = parser.parse_args()
    mx.util.pin_platform(args.device)

    logging.basicConfig(level=logging.INFO)
    flat = args.network == "mlp"
    net = get_mlp() if args.network == "mlp" else get_lenet()

    kv = mx.kv.create(args.kv_store)
    have_mnist = os.path.exists(os.path.join(
        args.data_dir, "train-images-idx3-ubyte"))
    rank = getattr(kv, "rank", 0)
    num_workers = getattr(kv, "num_workers", 1)
    if args.synthetic or not have_mnist:
        train, val = synthetic_iters(args, flat, rank, num_workers)
    else:
        train, val = mnist_iters(args, flat, rank, num_workers)

    if args.device == "cpu":
        ctx = mx.cpu()
    elif args.device == "tpu":
        ctx = mx.tpu(0)            # raises if no chip is reachable
    elif args.gpus:
        ctx = [mx.gpu(int(i)) for i in args.gpus.split(",")]
    else:
        ctx = mx.tpu(0) if mx.num_tpus() else mx.cpu()

    mod = mx.mod.Module(net, context=ctx, label_names=["softmax_label"])
    checkpoint = (mx.callback.do_checkpoint(args.model_prefix)
                  if args.model_prefix else None)
    mod.fit(train, eval_data=val, num_epoch=args.num_epochs,
            optimizer=args.optimizer,
            optimizer_params={"learning_rate": args.lr},
            initializer=mx.init.Xavier(magnitude=2.0),
            kvstore=kv, eval_metric="acc",
            batch_end_callback=mx.callback.Speedometer(
                args.batch_size, args.disp_batches),
            epoch_end_callback=checkpoint)
    val.reset()
    acc = mod.score(val, mx.metric.Accuracy())[0][1]
    logging.info("final validation accuracy: %.4f", acc)
    if getattr(kv, "rank", 0) == 0:
        print("final-accuracy %.4f" % acc)
    if hasattr(kv, "close"):
        kv.close()
    return acc


if __name__ == "__main__":
    main()
