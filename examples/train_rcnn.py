#!/usr/bin/env python
"""Mini Faster-RCNN driver: RPN training + Proposal + ROIPooling head.

Reference: example/rcnn (train_end2end.py) — this CI-sized driver wires
the detection op family end to end on synthetic data:

1. a small conv backbone over the image,
2. an RPN head trained with (a) objectness cross-entropy against
   anchor labels and (b) smooth-L1 on bbox regression targets,
3. the non-differentiable `Proposal` op turning RPN outputs into ROIs,
4. `ROIPooling` + a classifier head trained on the proposals' overlap
   with ground truth.

Synthetic scenes: one bright square object per image; the GT box is
where the square is. CI-sized run:

    python examples/train_rcnn.py --steps 20
"""
from __future__ import annotations

import argparse
import logging
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon

IMG = 32          # image size
FEAT = 8          # backbone stride 4 -> 8x8 feature map
STRIDE = IMG // FEAT
ANCHOR = 12.0     # single square anchor per cell


def synthetic_scene(rng):
    """One bright 10-14px square on a noisy background; returns
    (image CHW, gt box [x1, y1, x2, y2])."""
    img = rng.rand(3, IMG, IMG).astype(np.float32) * 0.2
    size = rng.randint(10, 15)
    x1 = rng.randint(0, IMG - size)
    y1 = rng.randint(0, IMG - size)
    img[:, y1:y1 + size, x1:x1 + size] += 0.8
    return img, np.array([x1, y1, x1 + size, y1 + size], np.float32)


def anchor_grid():
    """(FEAT*FEAT, 4) anchor boxes, one centered per feature cell."""
    cy, cx = np.mgrid[0:FEAT, 0:FEAT].astype(np.float32)
    cx = (cx.ravel() + 0.5) * STRIDE
    cy = (cy.ravel() + 0.5) * STRIDE
    half = ANCHOR / 2
    return np.stack([cx - half, cy - half, cx + half, cy + half], 1)


def iou(anchors, box):
    ix1 = np.maximum(anchors[:, 0], box[0])
    iy1 = np.maximum(anchors[:, 1], box[1])
    ix2 = np.minimum(anchors[:, 2], box[2])
    iy2 = np.minimum(anchors[:, 3], box[3])
    inter = np.maximum(ix2 - ix1, 0) * np.maximum(iy2 - iy1, 0)
    a_area = (anchors[:, 2] - anchors[:, 0]) * (anchors[:, 3] - anchors[:, 1])
    b_area = (box[2] - box[0]) * (box[3] - box[1])
    return inter / (a_area + b_area - inter + 1e-9)


def rpn_targets(anchors, gt):
    """Objectness labels (IoU>0.5 -> 1, <0.2 -> 0, else ignore=-1) and
    bbox-regression targets for positives (the reference's anchor
    assignment, rcnn/rpn style)."""
    ious = iou(anchors, gt)
    labels = np.full(len(anchors), -1.0, np.float32)
    labels[ious < 0.2] = 0.0
    labels[ious > 0.5] = 1.0
    if labels.max() < 1.0:      # guarantee one positive
        labels[np.argmax(ious)] = 1.0
    aw = anchors[:, 2] - anchors[:, 0]
    ah = anchors[:, 3] - anchors[:, 1]
    acx = anchors[:, 0] + aw / 2
    acy = anchors[:, 1] + ah / 2
    gw, gh = gt[2] - gt[0], gt[3] - gt[1]
    gcx, gcy = gt[0] + gw / 2, gt[1] + gh / 2
    t = np.stack([(gcx - acx) / aw, (gcy - acy) / ah,
                  np.log(gw / aw), np.log(gh / ah)], 1).astype(np.float32)
    return labels, t


class RCNN(gluon.HybridBlock):
    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.c1 = gluon.nn.Conv2D(16, 3, strides=2, padding=1)
            self.c2 = gluon.nn.Conv2D(32, 3, strides=2, padding=1)
            self.rpn_conv = gluon.nn.Conv2D(32, 3, padding=1)
            self.rpn_cls = gluon.nn.Conv2D(2, 1)    # bg/fg per anchor
            self.rpn_bbox = gluon.nn.Conv2D(4, 1)

    def hybrid_forward(self, F, x):
        feat = F.relu(self.c2(F.relu(self.c1(x))))
        rpn = F.relu(self.rpn_conv(feat))
        return feat, self.rpn_cls(rpn), self.rpn_bbox(rpn)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=30)
    ap.add_argument("--batch-size", type=int, default=4)
    ap.add_argument("--lr", type=float, default=0.01)
    ap.add_argument("--seed", type=int, default=3)
    args = ap.parse_args()

    logging.basicConfig(level=logging.INFO, format="%(message)s",
                        stream=sys.stdout, force=True)
    mx.util.pin_platform(os.environ.get("MXNET_DEVICE", "cpu"))
    mx.random.seed(args.seed)
    rng = np.random.RandomState(args.seed)

    net = RCNN()
    head = gluon.nn.HybridSequential()   # ROI classifier: object vs bg
    head.add(gluon.nn.Dense(32, activation="relu"), gluon.nn.Dense(2))
    net.initialize(mx.init.Xavier())
    head.initialize(mx.init.Xavier())
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": args.lr, "momentum": 0.9})
    tr_head = gluon.Trainer(head.collect_params(), "sgd",
                            {"learning_rate": args.lr, "momentum": 0.9})
    ce = gluon.loss.SoftmaxCrossEntropyLoss()
    anchors = anchor_grid()
    bs = args.batch_size

    first = last = None
    for step in range(args.steps):
        imgs, labels, targets, gts = [], [], [], []
        for _ in range(bs):
            img, gt = synthetic_scene(rng)
            lab, tgt = rpn_targets(anchors, gt)
            imgs.append(img)
            labels.append(lab)
            targets.append(tgt)
            gts.append(gt)
        x = mx.nd.array(np.stack(imgs))
        lab = mx.nd.array(np.stack(labels))          # (B, A)
        tgt = mx.nd.array(np.stack(targets))         # (B, A, 4)

        with autograd.record():
            _, cls, bbox = net(x)
            # (B, 2, H, W) -> (B, A, 2); (B, 4, H, W) -> (B, A, 4)
            cls_r = cls.reshape((bs, 2, -1)).transpose((0, 2, 1))
            bbox_r = bbox.reshape((bs, 4, -1)).transpose((0, 2, 1))
            cls_loss = ce(cls_r.reshape((-1, 2)), lab.reshape((-1,)),
                          (lab.reshape((-1, 1)) >= 0))
            pos = (lab == 1.0).reshape((bs, -1, 1))
            box_loss = mx.nd.smooth_l1((bbox_r - tgt) * pos,
                                       scalar=3.0).sum()
            loss = cls_loss.sum() + box_loss
        loss.backward()
        tr.step(bs)

        # Proposal op (non-differentiable) -> ROIs -> pooled head.
        feat, cls, bbox = net(x)
        prob = mx.nd.softmax(cls.reshape((bs, 2, -1)), axis=1) \
            .reshape(cls.shape)
        im_info = mx.nd.array(np.tile([IMG, IMG, 1.0], (bs, 1)))
        rois = mx.nd._contrib_Proposal(
            prob, bbox, im_info, feature_stride=STRIDE,
            scales=(ANCHOR / STRIDE,), ratios=(1.0,),
            rpn_pre_nms_top_n=32, rpn_post_nms_top_n=8,
            threshold=0.7, rpn_min_size=4)
        pooled = mx.nd.ROIPooling(feat, rois, pooled_size=(4, 4),
                                  spatial_scale=1.0 / STRIDE)
        # label each ROI by IoU with its image's GT box
        roi_np = rois.asnumpy()
        roi_lab = np.zeros(len(roi_np), np.float32)
        for i, r in enumerate(roi_np):
            b = int(r[0])
            roi_lab[i] = 1.0 if iou(r[None, 1:], gts[b])[0] > 0.5 else 0.0
        with autograd.record():
            head_loss = ce(head(pooled), mx.nd.array(roi_lab)).sum()
        head_loss.backward()
        tr_head.step(len(roi_np))

        cur = float(loss.asnumpy()) / bs
        if first is None:
            first = cur
        last = cur
        if step % 10 == 0 or step == args.steps - 1:
            logging.info("step %d  rpn_loss %.4f  head_loss %.4f  "
                         "rois %d", step, cur,
                         float(head_loss.asnumpy()) / len(roi_np),
                         len(roi_np))

    logging.info("rpn loss %.4f -> %.4f", first, last)
    if not (np.isfinite(last) and last < first):
        raise SystemExit("rcnn RPN loss did not improve")


if __name__ == "__main__":
    main()
