#!/usr/bin/env python
"""Bidirectional LSTM learns to sort short digit sequences.

Reference: example/bi-lstm-sort — the classic seq2seq-lite task: feed N
unsorted tokens, read out the same tokens sorted, one output per input
position. The API surface this driver exercises:
`mx.rnn.BidirectionalCell` over two LSTMCells unrolled symbolically,
per-position softmax heads, trained with the Module API.

    python examples/bi_lstm_sort.py --steps 200
"""
from __future__ import annotations

import argparse
import logging
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import mxnet_tpu as mx

SEQ = 5


def build(vocab, hidden):
    data = mx.sym.Variable("data")
    label = mx.sym.Variable("softmax_label")
    embed = mx.sym.Embedding(data, input_dim=vocab, output_dim=16,
                             name="embed")
    bi = mx.rnn.BidirectionalCell(
        mx.rnn.LSTMCell(hidden, prefix="fwd_"),
        mx.rnn.LSTMCell(hidden, prefix="bwd_"))
    out, _ = bi.unroll(SEQ, embed, layout="NTC", merge_outputs=True)
    pred = mx.sym.FullyConnected(mx.sym.reshape(out, shape=(-1, 2 * hidden)),
                                 num_hidden=vocab, name="pred")
    return mx.sym.SoftmaxOutput(pred, mx.sym.reshape(label, shape=(-1,)),
                                name="softmax")


class SortIter(mx.io.DataIter):
    """Endless (unsorted sequence -> sorted sequence) batches."""

    def __init__(self, batch_size, vocab, batches_per_epoch, seed):
        super().__init__(batch_size)
        self.vocab = vocab
        self.rng = np.random.RandomState(seed)
        self.batches_per_epoch = batches_per_epoch
        self._i = 0
        self.provide_data = [mx.io.DataDesc("data",
                                            (batch_size, SEQ))]
        self.provide_label = [mx.io.DataDesc("softmax_label",
                                             (batch_size, SEQ))]

    def reset(self):
        self._i = 0

    def next(self):
        if self._i >= self.batches_per_epoch:
            raise StopIteration
        self._i += 1
        x = self.rng.randint(0, self.vocab, (self.batch_size, SEQ))
        y = np.sort(x, axis=1)
        return mx.io.DataBatch(
            data=[mx.nd.array(x.astype(np.float32))],
            label=[mx.nd.array(y.astype(np.float32))],
            provide_data=self.provide_data,
            provide_label=self.provide_label)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=300,
                    help="total training batches")
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--vocab", type=int, default=10)
    ap.add_argument("--hidden", type=int, default=32)
    ap.add_argument("--lr", type=float, default=0.01)
    ap.add_argument("--seed", type=int, default=5)
    args = ap.parse_args()

    logging.basicConfig(level=logging.INFO, format="%(message)s",
                        stream=sys.stdout, force=True)
    mx.util.pin_platform(os.environ.get("MXNET_DEVICE", "cpu"))
    mx.random.seed(args.seed)

    sym = build(args.vocab, args.hidden)
    per_epoch = 20
    epochs = max(args.steps // per_epoch, 1)
    train = SortIter(args.batch_size, args.vocab, per_epoch, args.seed)
    val = SortIter(args.batch_size, args.vocab, 4, args.seed + 1)

    mod = mx.mod.Module(sym, data_names=("data",),
                        label_names=("softmax_label",))
    mod.fit(train, eval_data=val,
            optimizer="adam",
            optimizer_params={"learning_rate": args.lr},
            initializer=mx.init.Xavier(),
            num_epoch=epochs,
            eval_metric=mx.metric.Perplexity(ignore_label=None))

    # Position-wise accuracy on fresh sequences.
    val.reset()
    batch = next(val)
    mod.forward(batch, is_train=False)
    pred = mod.get_outputs()[0].asnumpy().reshape(
        args.batch_size, SEQ, args.vocab).argmax(-1)
    truth = batch.label[0].asnumpy().astype(int)
    acc = float((pred == truth).mean())
    logging.info("sorted-position accuracy %.3f", acc)
    logging.info("sample: in=%s out=%s truth=%s",
                 batch.data[0].asnumpy()[0].astype(int).tolist(),
                 pred[0].tolist(), truth[0].tolist())
    if acc < 0.5:
        raise SystemExit("bi-lstm sort accuracy too low: %.3f" % acc)


if __name__ == "__main__":
    main()
